#!/usr/bin/env python
"""Headline benchmark: distributed 3D C2C forward FFT, reference taxonomy.

Prints JSON result lines on stdout and always exits 0 — the round driver
records the LAST parseable line into ``BENCH_r{N}.json``. The measured
metric is the flagship problem (512^3, cf. ``/root/reference/README.md:
44-58``) timed on the available TPU device(s): GFlops/s = 5 N log2 N / t
(``fftSpeed3d_c2c.cpp:128``) versus the reference's heFFTe baseline
(324.4 GFlops/s at 512^3 on 4 GPUs, ``README.md:65-77``).

Budget discipline (the round-2 failure was rc=124: the driver's timeout
fired before any attempt finished): the schedule is *insurance-first*.

  Phase A (insurance): 256^3, ONE executor, no extras, hard 240 s cap.
    Its JSON line is printed the moment it exists — from then on the
    driver always has a parseable TPU number no matter when it kills us.
  Phase B (upgrade): 512^3, full executor tournament + donated-execution
    timing + t0..t3 stage breakdown, in whatever budget remains. Each
    improvement supersedes the previous line (last line wins).

The overall deadline defaults to 540 s (DFFT_BENCH_DEADLINE overrides —
the hardware campaign scripts raise it). Attempts run in worker
subprocesses because a wedged PJRT tunnel client cannot be cancelled
in-process; a worker that printed its result and then hung in extras
still counts (the line is recovered from partial stdout). A last-resort
CPU-backend measurement (clearly labelled, vs_baseline=0) keeps the
contract when the TPU transport is down entirely.

Executor selection mirrors the reference keeping several backends side by
side and picking one (``setFFTPlans``, ``fft_mpi_3d_api.cpp:318-429``):
every candidate in DFFT_BENCH_EXECUTORS is planned, verified by roundtrip,
and timed; the fastest correct one is reported. A candidate that fails to
compile or verify is skipped, never fatal.

TPU note: TPUs have no complex128 (C128 unsupported), so the on-chip bench
runs complex64; double-precision correctness at the 1e-11 tier is validated
by the CPU-backend test suite (tests/test_fft3d.py).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HEFFTE_BASELINE_GFLOPS = 324.4  # README.md:65-77, 512^3 / 4 ranks / rocfft
ERR_GATE = 1e-3  # complex64 tier; double tier is gated in the test suite


def _flagship_n() -> int:
    """The swept flagship extent (phase B / fallback lines): 512 unless a
    campaign overrides DFFT_BENCH_SHAPE. The fallback result lines derive
    their metric NAME from this too, so a non-512 campaign that dies
    before measuring never mislabels a run record as a 512 row."""
    try:
        return int(os.environ.get("DFFT_BENCH_SHAPE", "512"))
    except ValueError:
        return 512


# --------------------------------------------------------------- worker

class _precision_env:
    """Candidate names may carry precision-tier suffixes — ``matmul:high``
    (== ``matmul:f32``) plans the matmul executor at the 3-pass tier,
    ``matmul:high:gauss`` additionally in the 3-real-matmul dense complex
    product (the measurable accuracy/speed knobs of
    ``ops/dft_matmul.py::mm_precision``/``complex_mode``; the reference
    likewise records faster-but-less-accurate backend rows side by side,
    ``csv/batch_rocResult1D.csv``). These used to be applied by mutating
    ``DFFT_MM_*`` around planning — a process-global trace-time race with
    any concurrent planning (warm pools, tournaments). The tiers are now
    PLAN-SCOPED: the label goes straight into the planner / stage
    builders, which bake the tier into that plan's own trace
    (``ops/executors.py`` tier grammar), so this shim only validates the
    menu label (keeping the old ValueError contract for bad suffixes) and
    yields it through unchanged — no env mutation. The roundtrip gate
    still applies, so a tier that breaks the c64 accuracy bar is dropped,
    never reported."""

    def __init__(self, executor: str):
        if ":" in executor:
            from distributedfft_tpu.ops.executors import split_executor

            split_executor(executor)  # raises on unknown/conflicting
        self.label = executor        # suffixes (message names 'suffix')

    def __enter__(self):
        return self.label

    def __exit__(self, *exc):
        return False


def bench_executor(shape, mesh, dtype, executor: str):
    """Plan, verify (roundtrip), and time one executor. Returns
    (seconds, max_err, plan) or raises. Plans are returned so the caller
    can reuse them (stage breakdown, donation rebuild) without paying a
    second compile through the tunnel."""
    with _precision_env(executor) as base:
        return _bench_executor_inner(shape, mesh, dtype, base)


def _bench_executor_inner(shape, mesh, dtype, executor):
    import functools

    import jax
    import jax.numpy as jnp

    import distributedfft_tpu as dfft
    from distributedfft_tpu.utils.timing import (
        max_rel_err, sync, time_fn_amortized,
    )

    plan = dfft.plan_dft_c2c_3d(
        shape, mesh, direction=dfft.FORWARD, dtype=dtype, donate=False,
        executor=executor,
    )
    iplan = dfft.plan_dft_c2c_3d(
        shape, mesh, direction=dfft.BACKWARD, dtype=dtype, donate=False,
        executor=executor,
    )

    # Deterministic on-device init (host->device of 1 GiB through the
    # tunnel is avoided; the reference also inits on device,
    # fftSpeed3d_c2c.cpp:61-72).
    mk_kw = {}
    if plan.in_sharding is not None:
        mk_kw["out_shardings"] = plan.in_sharding

    @functools.partial(jax.jit, **mk_kw)
    def make_input():
        k1, k2 = jax.random.split(jax.random.PRNGKey(4242))
        re = jax.random.normal(k1, shape, jnp.float32)
        im = jax.random.normal(k2, shape, jnp.float32)
        return (re + 1j * im).astype(dtype)

    x = make_input()
    sync(x)

    # Roundtrip error check (the reference's inline validation,
    # fftSpeed3d_c2c.cpp:85-91).
    max_err = max_rel_err(iplan(plan(x)), x)
    if not max_err < ERR_GATE:
        raise AssertionError(f"roundtrip error {max_err} exceeds {ERR_GATE}")

    seconds, _ = time_fn_amortized(lambda: plan(x), iters=10, repeats=3)
    return seconds, max_err, plan


def bench_donated(shape, mesh, dtype, executor: str):
    """Time donated execution: the plan consumes its input buffer (the
    reference's bufferDev ping-pong, fft_mpi_3d_api.cpp:66-81). A C2C
    transform is shape-preserving, so single-device executions chain
    x <- plan(x); a distributed plan's output LAYOUT differs from its
    input (X-slabs -> Y-slabs), so there the chain alternates donated
    forward/backward plans — layouts line up, the two directions cost
    the same, and per-transform time is the pair time halved. Cost is
    data-independent, so chaining does not perturb the timing."""
    import distributedfft_tpu as dfft
    from distributedfft_tpu.utils.timing import sync
    import math as _math
    import time as _time

    with _precision_env(executor) as base:
        plan = dfft.plan_dft_c2c_3d(
            shape, mesh, direction=dfft.FORWARD, dtype=dtype, donate=True,
            executor=base,
        )
        pair = (plan.in_sharding is not None
                and plan.in_sharding != plan.out_sharding)
        if pair:
            iplan = dfft.plan_dft_c2c_3d(
                shape, mesh, direction=dfft.BACKWARD, dtype=dtype,
                donate=True, executor=base,
            )
            step = lambda v: iplan.fn(plan.fn(v))  # noqa: E731
            per_step = 2
        else:
            step, per_step = plan.fn, 1
        x = dfft.alloc_local(plan)
        # Compile + warm INSIDE the precision scope: jit traces lazily and
        # mm_precision() is read at trace time, so the first call must run
        # while the candidate's tier is in effect.
        x = step(x)  # consumes the zeros buffer
        sync(x)
    best = _math.inf
    iters = 10
    for _ in range(3):
        t0 = _time.perf_counter()
        for _ in range(iters):
            x = step(x)
        sync(x)
        best = min(best, (_time.perf_counter() - t0) / (iters * per_step))
    return best


# Public per-chip peak specs for achieved-vs-peak (MFU/roofline)
# reporting: device_kind substring -> (bf16 peak TFlop/s, HBM GB/s).
_TPU_SPECS = {
    "v5 lite": (197.0, 819.0), "v5e": (197.0, 819.0),
    "v5p": (459.0, 2765.0), "v5": (459.0, 2765.0),
    "v4": (275.0, 1228.0),
    "v6 lite": (918.0, 1640.0), "v6e": (918.0, 1640.0),
}


def _roofline(shape, seconds, n_dev):
    """Memory-roofline context for the flagship metric: a 3D FFT streams
    the array once per axis (3 passes, read + write each) — the minimum
    HBM traffic of any staged implementation. pct_of_roofline says how
    close the measured time is to that bound on this chip, which is what
    makes a sub-baseline number interpretable as chip-limited vs
    code-limited (round-4 verdict item 1). Model, not measurement: real
    XLA fusion can beat 3 passes (fused chains) or trail it (internal
    transposes); the exchange traffic of multi-chip plans rides ICI and
    is not in this bound."""
    import math

    import jax

    kind = jax.devices()[0].device_kind
    kl = kind.lower()
    spec = next((v for k, v in _TPU_SPECS.items() if k in kl), None)
    if spec is None:
        return {"device_kind": kind}
    peak_tf, hbm_gbps = spec
    bytes_per_dev = 8 * math.prod(shape) / n_dev  # complex64
    min_seconds = 3 * 2 * bytes_per_dev / (hbm_gbps * 1e9)
    return {
        "device_kind": kind,
        "roofline": {
            "model": "3-pass HBM stream (min traffic of a staged 3D FFT)",
            "hbm_gbps_per_chip": hbm_gbps,
            "bf16_peak_tflops_per_chip": peak_tf,
            "min_seconds": round(min_seconds, 6),
            "roofline_gflops": round(
                5 * math.prod(shape) * math.log2(math.prod(shape))
                / min_seconds / 1e9, 1),
            "pct_of_roofline": round(100.0 * min_seconds / seconds, 1),
        },
    }


def _plan_cost_block(plan) -> dict:
    """The explain layer's compiled cost/memory block for the telemetry
    line: peak-HBM and AOT compile-seconds gauges (plus flops / bytes
    accessed), all-null when the plan cannot be analyzed — a CPU
    fallback or an exotic executor must degrade to nulls, never crash
    the measurement that is already in hand."""
    null = {"peak_hbm_bytes": None, "compile_seconds": None,
            "flops": None, "bytes_accessed": None, "temp_bytes": None}
    try:
        from distributedfft_tpu.explain import compiled_summary

        res = compiled_summary(plan)
        if res is None:
            return null
        return {k: res.get(k) for k in null}
    except Exception:  # noqa: BLE001 — telemetry, not contract
        return null


def _plan_wire_kw(plan) -> dict:
    """The wire/transport/precision stamps of one plan's result line:
    the resolved ``wire_dtype`` (DFFT_WIRE_DTYPE lands in the plan's
    options at plan time), the exchange transport, and the plan-scoped
    matmul precision tier (``PlanOptions.mm_precision`` — the executor
    label's ``:bf16``/``:f32`` suffix) — _emit drops the defaults so
    exact/alltoall/full-precision rows keep the old schema."""
    opts = getattr(plan, "options", None)
    ex = getattr(plan, "executor", None) or ""
    return {
        "wire_dtype": getattr(opts, "wire_dtype", None),
        "transport": getattr(opts, "algorithm", None),
        "precision": getattr(opts, "mm_precision", None),
        # Pallas fusion tier (executor label ":fuse" — stage-pair
        # mega-kernels): stamped so fused runs form their own baseline
        # group; unfused rows keep the old schema (None is dropped).
        "fusion": True if ":fuse" in ex else None,
    }


def _emit(shape_n, seconds, max_err, executor, n_dev, decomposition,
          all_times, donated=False, stages=None, overlap=None, tuned=None,
          cost=None, batch=None, wire_dtype=None, transport=None,
          precision=None, fusion=None, op=None, degraded=False,
          concurrent=None, scheduler=None, waves_per_s=None,
          occupancy=None):
    import jax

    from distributedfft_tpu.utils.metrics import metrics_snapshot
    from distributedfft_tpu.utils.timing import gflops

    shape = (shape_n,) * 3
    b = batch if batch and batch > 1 else 1
    cc = concurrent if concurrent and concurrent > 1 else 1
    total = b * cc  # one concurrent dispatch computes cc x b transforms
    # One batched execution computes b transforms; GFlops and the
    # throughput stamp both count all of them. A fused spectral-operator
    # run (op) computes forward + inverse per solve — 2x the transform
    # flops — and stamps solves/s instead of transforms/s.
    gf = gflops(shape, seconds) * total * (2 if op else 1)
    metric = (f"spectral_{op}_{shape_n}_gflops" if op
              else f"fft3d_c2c_{shape_n}_forward_gflops")
    out = {
        "metric": metric,
        "value": round(gf, 1),
        "unit": "GFlops/s",
        "vs_baseline": round(gf / HEFFTE_BASELINE_GFLOPS, 3),
        "seconds": round(seconds, 6),
        "max_roundtrip_err": max_err,
        "dtype": "complex64",
        "backend": jax.default_backend(),
        "devices": n_dev,
        "decomposition": decomposition,
        "executor": executor,
        "donated": donated,
        "all": {e: round(t, 6) for e, t in all_times.items()},
    }
    if op:
        # Fused spectral-operator run (DFFT_BENCH_OP): solves/s is the
        # workload's unit of throughput (one solve = FFT -> pointwise ->
        # iFFT). The run-record store lifts *_per_s into rates (gated
        # larger-is-better) and keys "op" into the baseline config
        # group, so operator runs never share baselines with bare
        # transforms. Transform rows keep the old schema exactly.
        out["op"] = op
        out["solves_per_s"] = round(total / seconds, 3)
    else:
        # Throughput as a first-class metric (transforms per second, not
        # just GFlop/s): the serving tier's gated number. Unbatched runs
        # stamp 1/seconds, batched runs B/seconds; the run-record store
        # lifts it into rates and compare --gate treats *_per_s as
        # larger-is-better.
        out["transforms_per_s"] = round(total / seconds, 3)
    if cc > 1:
        # Concurrent-schedule run (DFFT_BENCH_CONCURRENT / speed3d
        # -concurrent): N independent transforms merged into ONE
        # interleaved program (stagegraph.schedule_concurrent — one
        # transform's t2 wire hides under another's FFT compute). The
        # run-record store keys "concurrent" into the baseline config
        # group and gates concurrent_transforms_per_s as a rate;
        # sequential rows keep the old schema.
        out["concurrent"] = cc
        out["concurrent_transforms_per_s"] = round(total / seconds, 3)
    if scheduler is not None:
        # Wave-scheduler serving run (DFFT_BENCH_SERVE / bench.py
        # --serve-streaming): requests driven through a CoalescingQueue
        # in streaming (persistent drain loop) or discrete flush mode.
        # The run-record store keys "scheduler" into the baseline config
        # group — a streaming run must never share baselines with flush
        # runs — and lifts waves_per_s into rates; the occupancy block
        # (docs/OBSERVABILITY.md "Wave scheduler occupancy") makes the
        # line self-describing about device idle between waves.
        out["scheduler"] = scheduler
        if waves_per_s is not None:
            out["waves_per_s"] = round(waves_per_s, 3)
        if occupancy is not None:
            out["occupancy"] = occupancy
    if b > 1:
        # Batched multi-request run (DFFT_BENCH_BATCH): part of the
        # baseline group — a B=8 coalesced run must never be judged
        # against single-transform baselines; default rows keep the old
        # schema.
        out["batch"] = b
    if overlap not in (None, 1):
        # Pipelined t2/t3 overlap (DFFT_OVERLAP / PlanOptions.overlap_
        # chunks). Stamped into the line so the run-record store keys
        # overlapped and monolithic runs into different baselines; default
        # rows keep the old schema.
        out["overlap"] = overlap
    if tuned is not None:
        # Measured-autotuner run (DFFT_BENCH_TUNE): the winner tuple
        # "decomposition/transport/executor/ovK". The run-record store
        # keys it into the baseline group, so tuned and untuned runs
        # never share a compare baseline; untuned rows keep the old
        # schema.
        out["tuned"] = tuned
    if wire_dtype is not None:
        # On-wire compressed run (DFFT_WIRE_DTYPE resolved at plan time):
        # part of the baseline group — a compressed run ships a fraction
        # of the t2 bytes (bf16 half, int8 ~quarter) and must never be
        # judged against exact-wire baselines, or codecs against each
        # other. Exact rows keep the old schema.
        out["wire_dtype"] = wire_dtype
    if precision is not None:
        # Reduced/explicit matmul precision tier (PlanOptions.mm_
        # precision — a plan-scoped MXU accuracy choice, the executor
        # label's :bf16/:f32 suffix): part of the baseline group — a
        # one-pass bf16 run must never be judged against f32-exact
        # baselines or vice versa. Untier'd rows keep the old schema.
        out["precision"] = precision
    if fusion:
        # Pallas fusion tier run (executor ``pallas:fuse`` — adjacent
        # stage pairs collapsed into shape-specialized mega-kernels):
        # keyed into the baseline config group so a fused run's wall
        # time is never judged against unfused baselines or vice versa.
        # Unfused rows keep the old schema.
        out["fusion"] = True
    if degraded:
        # Degraded-mode fallback run (docs/ROBUSTNESS.md): the matmul-
        # DFT executor stood in for a faulted default. The run-record
        # store keys "degraded" into the baseline group, so a degraded
        # run can never poison the fast baselines (nor be gated against
        # them); healthy rows keep the old schema.
        out["degraded"] = True
    if transport not in (None, "alltoall"):
        # Non-default exchange transport (alltoallv/ppermute/
        # hierarchical): a different collective program — keyed into the
        # baseline group like wire_dtype. Default alltoall rows keep the
        # old schema.
        out["transport"] = transport
    try:
        # Calibrated-hardware-profile stamp: when a measured profile
        # (report calibrate) drives the model/divergence constants, the
        # run must form its own baseline group — divergence flags and
        # model ratios mean something different against measured
        # constants. Default/table-profile rows keep the old schema so
        # existing baselines keep accumulating.
        from distributedfft_tpu.explain import device_profile

        if device_profile().get("source") == "calibrated":
            out["profile"] = "calibrated"
    except Exception:  # noqa: BLE001 — telemetry, not contract
        pass
    if jax.default_backend() == "tpu":
        out.update(_roofline(shape, seconds, n_dev))
    if stages:
        out["stages"] = stages
    # Structured telemetry block: the worker-process metrics registry
    # (plan builds/cache, compile seconds, executes, exchange bytes) so
    # every BENCH json line is self-describing without string-grepping.
    # The cost sub-block is the explain layer's compiled view (peak-HBM
    # / AOT compile seconds); the run-record store baselines it so
    # compare --gate catches footprint regressions, not just wall time.
    out["telemetry"] = {
        "metrics": metrics_snapshot(),
        "cost": cost if cost is not None else {
            "peak_hbm_bytes": None, "compile_seconds": None,
            "flops": None, "bytes_accessed": None, "temp_bytes": None},
    }
    try:
        # Live-monitor health verdict (single-sample: lifetime counters
        # play the window). The run-record store lifts it next to qos
        # and regressed_metrics gates on firing alerts, so a bench run
        # that burned SLOs or stalled its queue trips compare --gate
        # even when wall time looks fine.
        from distributedfft_tpu.monitor import health_snapshot

        out["health"] = health_snapshot()
    except Exception:  # noqa: BLE001 — telemetry, not contract
        pass
    try:
        # Numerics plane (docs/OBSERVABILITY.md "Numerics plane"):
        # shadow-audit drift verdicts + non-finite sentinel counters.
        # Stamped only when the plane saw something (DFFT_SHADOW_RATE
        # armed or a sentinel fired) — regressed_metrics folds drifting
        # buckets into the gate, so a codec that got fast by getting
        # wrong cannot pass compare --gate.
        from distributedfft_tpu.numerics import numerics_snapshot

        nsnap = numerics_snapshot()
        if nsnap is not None:
            out["numerics"] = nsnap
    except Exception:  # noqa: BLE001 — telemetry, not contract
        pass
    # Process identity (docs/OBSERVABILITY.md "Fleet view"): which
    # host/process produced this line — the key that lets the fleet
    # aggregator and the run-record store attribute a regression to a
    # member. Multi-process runs also stamp the process shape; the
    # run-record store keys "procs" into the baseline group so single-
    # and multi-process runs never share a compare baseline.
    import socket as _socket

    out["host"] = _socket.gethostname()
    out["pid"] = os.getpid()
    try:
        if jax.process_count() > 1:
            out["procs"] = jax.process_count()
            out["process_index"] = jax.process_index()
    except Exception:  # noqa: BLE001 — telemetry, not contract
        pass
    print(json.dumps(out), flush=True)
    return out


def _worker_tuned(shape_n, shape, mesh, dtype, n_dev, mode: str) -> None:
    """The tune-mode measurement (``DFFT_BENCH_TUNE``): plan through the
    measured autotuner (the multi-axis tournament of
    ``distributedfft_tpu/tuner.py``, or its persisted wisdom) instead of
    the manual executor menu, verify by roundtrip, and stamp the winner
    tuple into the result line so the run-record store keys tuned and
    untuned runs into different baselines."""
    import functools

    import jax
    import jax.numpy as jnp

    import distributedfft_tpu as dfft
    from distributedfft_tpu.tuner import tuned_label
    from distributedfft_tpu.utils.timing import (
        max_rel_err, sync, time_fn_amortized,
    )

    plan = dfft.plan_dft_c2c_3d(
        shape, mesh, direction=dfft.FORWARD, dtype=dtype, tune=mode)
    iplan = dfft.plan_dft_c2c_3d(
        shape, mesh, direction=dfft.BACKWARD, dtype=dtype, tune=mode)
    label = tuned_label(plan)
    print(f"tuned plan: {label}", file=sys.stderr)

    mk_kw = {}
    if plan.in_sharding is not None:
        mk_kw["out_shardings"] = plan.in_sharding

    @functools.partial(jax.jit, **mk_kw)
    def make_input():
        k1, k2 = jax.random.split(jax.random.PRNGKey(4242))
        re = jax.random.normal(k1, shape, jnp.float32)
        im = jax.random.normal(k2, shape, jnp.float32)
        return (re + 1j * im).astype(dtype)

    x = make_input()
    sync(x)
    max_err = max_rel_err(iplan(plan(x)), x)
    if not max_err < ERR_GATE:
        raise AssertionError(f"roundtrip error {max_err} exceeds {ERR_GATE}")
    seconds, _ = time_fn_amortized(lambda: plan(x), iters=10, repeats=3)
    _emit(shape_n, seconds, max_err, plan.executor, n_dev,
          plan.decomposition, {label: round(seconds, 6)},
          overlap=getattr(plan.options, "overlap_chunks", None),
          tuned=label, cost=_plan_cost_block(plan),
          **_plan_wire_kw(plan))


def _worker_batched(shape_n, shape, mesh, dtype, n_dev, b: int) -> None:
    """The batched-serving measurement (``DFFT_BENCH_BATCH=B``): one
    batch=B plan computing B independent transforms per execution — the
    throughput row (transforms/s) of the serving tier. Verified by
    batched roundtrip; the result line stamps ``batch`` so the
    run-record store keys batched and single-transform runs into
    different baselines."""
    import functools

    import jax
    import jax.numpy as jnp

    import distributedfft_tpu as dfft
    from distributedfft_tpu.utils.timing import (
        max_rel_err, sync, time_fn_amortized,
    )

    executor = os.environ.get("DFFT_BENCH_EXECUTORS", "xla").split(",")[0]
    with _precision_env(executor.strip()) as base:
        plan = dfft.plan_dft_c2c_3d(
            shape, mesh, direction=dfft.FORWARD, dtype=dtype,
            executor=base, batch=b)
        iplan = dfft.plan_dft_c2c_3d(
            shape, mesh, direction=dfft.BACKWARD, dtype=dtype,
            executor=base, batch=b)

        mk_kw = {}
        if plan.in_sharding is not None:
            mk_kw["out_shardings"] = plan.in_sharding

        @functools.partial(jax.jit, **mk_kw)
        def make_input():
            k1, k2 = jax.random.split(jax.random.PRNGKey(4242))
            re = jax.random.normal(k1, plan.in_shape, jnp.float32)
            im = jax.random.normal(k2, plan.in_shape, jnp.float32)
            return (re + 1j * im).astype(dtype)

        x = make_input()
        sync(x)
        max_err = max_rel_err(iplan(plan(x)), x)
        if not max_err < ERR_GATE:
            raise AssertionError(
                f"roundtrip error {max_err} exceeds {ERR_GATE}")
        seconds, _ = time_fn_amortized(lambda: plan(x), iters=10, repeats=3)
    # Per-transform seconds follow from the batched execution; _emit
    # derives GFlops and transforms_per_s from (seconds, batch).
    _emit(shape_n, seconds, max_err, executor, n_dev, plan.decomposition,
          {f"{executor}+b{b}": round(seconds, 6)},
          overlap=getattr(plan.options, "overlap_chunks", None),
          batch=b, cost=_plan_cost_block(plan), **_plan_wire_kw(plan))


def _worker_op(shape_n, shape, mesh, dtype, n_dev, opname: str,
               b: int | None) -> None:
    """The spectral-operator measurement (``DFFT_BENCH_OP=poisson|grad|
    gauss``, composable with ``DFFT_BENCH_BATCH=B``): one fused
    FFT -> pointwise -> iFFT plan per solve. Verified against the
    unfused composition (forward plan, full-grid multiplier, inverse
    plan); the result line stamps ``op`` + ``solves_per_s`` so the
    run-record store gates operator throughput in its own baseline
    group."""
    import functools

    import jax
    import jax.numpy as jnp

    import distributedfft_tpu as dfft
    from distributedfft_tpu import operators
    from distributedfft_tpu.utils.timing import (
        max_rel_err, sync, time_fn_amortized,
    )

    op = operators.named_op(opname)
    executor = os.environ.get("DFFT_BENCH_EXECUTORS", "xla").split(",")[0]
    with _precision_env(executor.strip()) as base:
        plan = operators.plan_spectral_op(
            shape, mesh, op=op, dtype=dtype, executor=base, batch=b)

        mk_kw = {}
        if plan.in_sharding is not None:
            mk_kw["out_shardings"] = plan.in_sharding

        @functools.partial(jax.jit, **mk_kw)
        def make_input():
            k1, k2 = jax.random.split(jax.random.PRNGKey(4242))
            re = jax.random.normal(k1, plan.in_shape, jnp.float32)
            im = jax.random.normal(k2, plan.in_shape, jnp.float32)
            return (re + 1j * im).astype(dtype)

        x = make_input()
        sync(x)
        # Verify fused == unfused composition (the operator-tier analog
        # of the transform roundtrip gate): forward transform, multiply
        # by the full-grid multiplier in natural layout, inverse.
        fwd = dfft.plan_dft_c2c_3d(shape, mesh, direction=dfft.FORWARD,
                                   dtype=dtype, executor=base)
        bwd = dfft.plan_dft_c2c_3d(shape, mesh, direction=dfft.BACKWARD,
                                   dtype=dtype, executor=base)
        m = operators.multiplier_grid(op, shape, dtype)
        probe = x if b is None else x[0]
        max_err = max_rel_err(plan(x) if b is None else plan(x)[0],
                              bwd(m * fwd(probe)))
        if not max_err < ERR_GATE:
            raise AssertionError(
                f"fused-vs-unfused {opname} error {max_err} exceeds "
                f"{ERR_GATE}")
        seconds, _ = time_fn_amortized(lambda: plan(x), iters=10,
                                       repeats=3)
    _emit(shape_n, seconds, max_err, executor, n_dev, plan.decomposition,
          {f"{executor}+op{opname}": round(seconds, 6)},
          overlap=getattr(plan.options, "overlap_chunks", None),
          batch=b, op=opname, cost=_plan_cost_block(plan),
          **_plan_wire_kw(plan))


def _worker_concurrent(shape_n, shape, mesh, dtype, n_dev, cc: int,
                       b: int | None) -> None:
    """The concurrent-schedule measurement (``DFFT_BENCH_CONCURRENT=N``,
    composable with ``DFFT_BENCH_BATCH=B``): N independent transforms
    merged into ONE interleaved device program
    (``stagegraph.schedule_concurrent`` — transform A's t2 collectives
    issue while transform B's t0/t3 FFTs run). Verified bit-identical
    against sequential per-plan execution; the result line stamps
    ``concurrent`` + ``concurrent_transforms_per_s`` so the run-record
    store gates concurrent throughput in its own baseline group."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    import distributedfft_tpu as dfft
    from distributedfft_tpu.stagegraph import schedule_concurrent
    from distributedfft_tpu.utils.timing import (
        max_rel_err, sync, time_fn_amortized,
    )

    executor = os.environ.get("DFFT_BENCH_EXECUTORS", "xla").split(",")[0]
    with _precision_env(executor.strip()) as base:
        plan = dfft.plan_dft_c2c_3d(shape, mesh, direction=dfft.FORWARD,
                                    dtype=dtype, executor=base, batch=b)
        if plan.graph is None:
            raise RuntimeError(
                "DFFT_BENCH_CONCURRENT needs a stage-graph (slab/pencil) "
                "plan; single-device plans cannot be co-scheduled")
        cp = schedule_concurrent([plan] * cc)

        mk_kw = {}
        if plan.in_sharding is not None:
            mk_kw["out_shardings"] = plan.in_sharding

        @functools.partial(jax.jit, **mk_kw, static_argnums=0)
        def make_input(seed: int):
            k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
            re = jax.random.normal(k1, plan.in_shape, jnp.float32)
            im = jax.random.normal(k2, plan.in_shape, jnp.float32)
            return (re + 1j * im).astype(dtype)

        xs = [make_input(4242 + i) for i in range(cc)]
        sync(xs)
        # Bit-parity gate: the interleaved schedule must produce exactly
        # the sequential plans' outputs (the schedule moves issue order,
        # never math).
        ys = cp(*xs)
        seq = [plan(x) for x in xs]
        max_err = max(float(max_rel_err(a, r)) for a, r in zip(ys, seq))
        if not all(bool(jnp.all(a == r)) for a, r in zip(ys, seq)):
            raise AssertionError(
                "concurrent schedule diverged from sequential execution")
        seconds, _ = time_fn_amortized(lambda: cp(*xs), iters=10,
                                       repeats=3)
    _emit(shape_n, seconds, float(max_err), executor, n_dev,
          plan.decomposition, {f"{executor}+cc{cc}": round(seconds, 6)},
          overlap=getattr(plan.options, "overlap_chunks", None),
          batch=b, concurrent=cc, cost=_plan_cost_block(plan),
          **_plan_wire_kw(plan))


def _worker_serving(shape_n, shape, mesh, dtype, n_dev, b: int | None,
                    mode: str) -> None:
    """The wave-scheduler serving measurement (``DFFT_BENCH_SERVE=
    stream|flush``, or ``bench.py --serve-streaming``): N submits driven
    through a :class:`..serving.CoalescingQueue` — ``stream`` through
    the persistent drain loop (``serve()``/``stop()``), ``flush``
    through the discrete path — with waves/s and the scheduler-occupancy
    snapshot as the numbers under test. The result line stamps
    ``scheduler`` so the run-record store keys streaming and flush runs
    into different baselines, and ``waves_per_s`` lifts into the gated
    rates."""
    import functools

    import jax
    import jax.numpy as jnp

    import distributedfft_tpu as dfft
    from distributedfft_tpu import serving as _serving
    from distributedfft_tpu.utils.timing import max_rel_err, sync

    b = b or 4
    raw_sub = os.environ.get("DFFT_BENCH_SERVE_SUBMITS", "").strip()
    n_sub = int(raw_sub) if raw_sub else 4 * b
    executor = os.environ.get("DFFT_BENCH_EXECUTORS", "xla").split(",")[0]
    with _precision_env(executor.strip()) as base:
        plan = dfft.plan_dft_c2c_3d(shape, mesh, direction=dfft.FORWARD,
                                    dtype=dtype, executor=base)
        iplan = dfft.plan_dft_c2c_3d(shape, mesh, direction=dfft.BACKWARD,
                                     dtype=dtype, executor=base)

        mk_kw = {}
        if plan.in_sharding is not None:
            mk_kw["out_shardings"] = plan.in_sharding

        @functools.partial(jax.jit, **mk_kw, static_argnums=0)
        def make_input(seed: int):
            k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
            re = jax.random.normal(k1, shape, jnp.float32)
            im = jax.random.normal(k2, shape, jnp.float32)
            return (re + 1j * im).astype(dtype)

        xs = [make_input(4242 + i) for i in range(n_sub)]
        sync(xs)

        def run_once() -> tuple[float, dict, list]:
            q = _serving.CoalescingQueue(
                mesh, kind="c2c", max_batch=b, executor=base,
                concurrent_groups=2, streaming=(mode == "stream"))
            if q._wave_stats is None:
                # Flush mode without a live monitor: arm the occupancy
                # recorder explicitly — the snapshot IS the measurement.
                q._wave_stats = _serving._WaveStats(q.kind)
            t0 = time.perf_counter()
            handles = [q.submit(x) for x in xs]
            if mode == "stream":
                q.stop(drain=True)
            else:
                q.flush()
            outs = [h.result() for h in handles]
            sync(outs)
            seconds = time.perf_counter() - t0
            snap = q._wave_stats.snapshot()
            q.close()
            return seconds, snap, outs

        run_once()  # warm: compiles land in the cache, stats discarded
        total_s, snap, outs = run_once()
        max_err = float(max_rel_err(iplan(outs[0]), xs[0]))
        if not max_err < ERR_GATE:
            raise AssertionError(
                f"roundtrip error {max_err} exceeds {ERR_GATE}")
    occupancy = {k: snap.get(k) for k in (
        "width_mean", "idle_fraction", "idle_s", "busy_s",
        "wave_duration_p50_s", "preemptions", "bumped_transforms")}
    waves = snap.get("waves") or 0
    _emit(shape_n, total_s / max(1, n_sub), max_err, base, n_dev,
          plan.decomposition,
          {f"{base}+serve-{mode}": round(total_s, 6)},
          overlap=getattr(plan.options, "overlap_chunks", None),
          cost=_plan_cost_block(plan),
          scheduler="streaming" if mode == "stream" else "flush",
          waves_per_s=(waves / total_s if total_s > 0 else 0.0),
          occupancy=occupancy, **_plan_wire_kw(plan))


def _worker(shape_n: int) -> None:
    """Measure and print result JSON lines (runs in a subprocess). A line
    is printed after EVERY improvement — the first candidate's number is
    on stdout before the second candidate compiles, so a later hang can
    never cost the measurement (the orchestrator recovers the last line
    from partial stdout on timeout)."""
    import traceback

    import jax

    from distributedfft_tpu.utils.cache import enable_compile_cache

    enable_compile_cache()
    import jax.numpy as jnp

    import distributedfft_tpu as dfft
    from distributedfft_tpu.utils.timing import time_staged

    dfft.enable_metrics()  # the _emit telemetry block reads the registry

    fast = os.environ.get("DFFT_BENCH_FAST", "0") == "1"
    shape = (shape_n,) * 3
    devs = jax.devices()  # orchestrator enforces the timeout around this
    n_dev = len(devs)
    mesh = dfft.make_mesh(n_dev) if n_dev > 1 else None
    dtype = jnp.complex64  # TPU: no C128

    # Tune mode: the measured autotuner replaces the manual executor
    # menu ("1" = measure; "wisdom" consults the store only).
    tune_mode = os.environ.get("DFFT_BENCH_TUNE", "").strip()
    if tune_mode == "1":
        tune_mode = "measure"
    if tune_mode in ("wisdom", "measure"):
        return _worker_tuned(shape_n, shape, mesh, dtype, n_dev, tune_mode)

    # Batched serving mode: one batch=B plan per execution (throughput
    # measurement; transforms_per_s is the number under test).
    batch_env = os.environ.get("DFFT_BENCH_BATCH", "").strip()
    batch_b = (int(batch_env) if batch_env and batch_env not in ("0", "1")
               else None)

    # Spectral-operator mode: one fused FFT -> pointwise -> iFFT plan
    # per solve (solves_per_s is the number under test; composes with
    # DFFT_BENCH_BATCH for batched operator fusion).
    op_env = os.environ.get("DFFT_BENCH_OP", "").strip().lower()
    if op_env:
        return _worker_op(shape_n, shape, mesh, dtype, n_dev, op_env,
                          batch_b)

    # Serving-scheduler mode: requests through a CoalescingQueue in
    # streaming or discrete-flush mode (waves_per_s + occupancy are the
    # numbers under test; composes with DFFT_BENCH_BATCH for the
    # coalescing width).
    serve_env = os.environ.get("DFFT_BENCH_SERVE", "").strip().lower()
    if serve_env in ("stream", "streaming", "flush"):
        return _worker_serving(
            shape_n, shape, mesh, dtype, n_dev, batch_b,
            "stream" if serve_env.startswith("stream") else "flush")

    # Concurrent-schedule mode: N independent transforms as ONE
    # interleaved program (concurrent_transforms_per_s is the number
    # under test; composes with DFFT_BENCH_BATCH).
    cc_env = os.environ.get("DFFT_BENCH_CONCURRENT", "").strip()
    cc_n = int(cc_env) if cc_env and cc_env not in ("0", "1") else None
    if cc_n is not None:
        return _worker_concurrent(shape_n, shape, mesh, dtype, n_dev,
                                  cc_n, batch_b)
    if batch_b is not None:
        return _worker_batched(shape_n, shape, mesh, dtype, n_dev,
                               batch_b)

    # Upgrade-phase menu: xla first (a line exists after one compile),
    # then the dense HIGH-precision MXU path (kept only if it passes the
    # roundtrip gate), the layout/tier variants, and the fused Pallas
    # tiers LAST — the round-5 campaign saw pallas compiles at 512^3
    # wedge the remote compile service for 20+ minutes
    # (hw_campaign_r05.log), and a candidate that hangs must never
    # starve the ones behind it in the menu.
    # matmul:high runs right after the xla insurance candidate: on TPU it
    # is the dense one-contraction-per-axis path (ops/dft_matmul.py
    # direct_max), the highest-expected-value candidate of the menu — a
    # short tunnel window must measure it before the also-rans.
    default_execs = ("xla" if fast
                     else "xla,matmul:high,matmul:high:gauss,"
                          "xla_minor,matmul,pallas,pallas:high")
    candidates = [
        e.strip()
        for e in os.environ.get(
            "DFFT_BENCH_EXECUTORS", default_execs
        ).split(",")
        if e.strip()
    ]
    if jax.default_backend() == "cpu":
        # Pallas runs in the (Python-level) interpreter on CPU — timing
        # it at bench sizes is meaningless and can eat the whole budget.
        candidates = [c for c in candidates
                      if not c.startswith("pallas")] or ["xla"]
    results = {}   # name -> (seconds, max_err, plan)
    best = None
    for ex in candidates:
        try:
            results[ex] = bench_executor(shape, mesh, dtype, ex)
        except Exception:  # noqa: BLE001 — a failed candidate is skipped
            print(f"executor {ex!r} failed:", file=sys.stderr)
            traceback.print_exc(limit=3, file=sys.stderr)
            continue
        new_best = min(results, key=lambda e: results[e][0])
        if new_best != best:
            best = new_best
            _emit(shape_n, results[best][0], results[best][1], best, n_dev,
                  results[best][2].decomposition,
                  {e: r[0] for e, r in results.items()},
                  overlap=getattr(results[best][2].options,
                                  "overlap_chunks", None),
                  **_plan_wire_kw(results[best][2]))

    if not results:
        # Degraded-mode last resort (docs/ROBUSTNESS.md): when every
        # menu candidate failed, try the matmul-DFT executor — it shares
        # no code with the XLA fft thunk, so the long-standing fft-thunk
        # fault class cannot take it down with the rest. A success is
        # emitted with degraded=true (its own baseline group) and the
        # extras (donation, stage breakdown) are skipped: this is an
        # insurance line, not a campaign number.
        fb = os.environ.get("DFFT_FALLBACK_EXECUTOR", "matmul").strip()
        if fb and fb not in ("0", "none") and fb not in candidates:
            try:
                seconds, max_err, plan = bench_executor(
                    shape, mesh, dtype, fb)
            except Exception:  # noqa: BLE001 — the last resort failed too
                traceback.print_exc(limit=3, file=sys.stderr)
            else:
                print(f"degraded: every candidate failed; {fb} fallback "
                      f"succeeded", file=sys.stderr)
                _emit(shape_n, seconds, max_err, fb, n_dev,
                      plan.decomposition, {fb: round(seconds, 6)},
                      overlap=getattr(plan.options, "overlap_chunks", None),
                      cost=_plan_cost_block(plan), degraded=True,
                      **_plan_wire_kw(plan))
                return
        raise SystemExit("no benchmark executor succeeded")
    seconds, max_err, plan = results[best]
    all_times = {e: r[0] for e, r in results.items()}
    overlap = getattr(plan.options, "overlap_chunks", None)
    if fast:
        return

    # Winner's compiled cost/memory block (explain layer) — once, after
    # the tournament, so the insurance path never pays the AOT analysis.
    cost = _plan_cost_block(plan)
    _emit(shape_n, seconds, max_err, best, n_dev, plan.decomposition,
          all_times, overlap=overlap, cost=cost, **_plan_wire_kw(plan))

    # Donated execution of the winner — halves HBM traffic headroom and is
    # how the big-grid campaign runs (bufferDev ping-pong discipline).
    donated = False
    try:
        dsec = bench_donated(shape, mesh, dtype, best)
        all_times[best + "+donate"] = dsec
        if dsec < seconds:
            seconds, donated = dsec, True
        _emit(shape_n, seconds, max_err, best, n_dev, plan.decomposition,
              all_times, donated=donated, overlap=overlap, cost=cost,
              **_plan_wire_kw(plan))
    except Exception:  # noqa: BLE001 — donation is a best-effort extra
        traceback.print_exc(limit=3, file=sys.stderr)

    # Per-stage t0..t3 breakdown (fft_mpi_3d_api.cpp:184-201); the
    # reference prints it even single-rank (t1/t2 zero without an
    # exchange). The whole block runs inside the winner's precision scope:
    # the stage jits trace during time_staged, and a suffixed winner
    # ('pallas:high') must build/trace its stages at that tier under its
    # base executor name.
    stages = None
    try:
        with _precision_env(best) as base:
            stage_fns = None
            if mesh is not None and plan.decomposition == "slab":
                from distributedfft_tpu.parallel.slab import (
                    build_slab_stages,
                )

                stage_fns, _ = build_slab_stages(
                    mesh, shape, axis_name=mesh.axis_names[0], executor=base,
                    forward=True, overlap_chunks=overlap or 1,
                )
            elif mesh is None:
                from distributedfft_tpu.parallel.staged import (
                    build_single_stages,
                )

                stage_fns = build_single_stages(shape, executor=base)
            if stage_fns is not None:
                x = dfft.alloc_local(plan, fill=None)
                st, _ = time_staged(stage_fns, x, iters=3)
                stages = {k: round(v, 6) for k, v in st.times.items()}
    except Exception:  # noqa: BLE001 — breakdown is best-effort extra
        traceback.print_exc(limit=3, file=sys.stderr)

    if stages:
        _emit(shape_n, seconds, max_err, best, n_dev, plan.decomposition,
              all_times, donated=donated, stages=stages, overlap=overlap,
              cost=cost, **_plan_wire_kw(plan))


# ----------------------------------------------------------- orchestrator

def _parse_json_line(text: str) -> dict | None:
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "metric" in obj:
                return obj
    return None


def _run_attempt(shape_n: int, timeout: float, extra_env: dict | None = None):
    """Run one worker subprocess. Returns (result_dict|None, note)."""
    env = dict(os.environ)
    if extra_env:
        env.update(extra_env)
    try:
        proc = subprocess.run(
            [sys.executable, "-u", os.path.abspath(__file__),
             "--worker", str(shape_n)],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired as e:
        # Keep the child's partial output — a worker that printed a result
        # line and then wedged in a later candidate/extra still counts.
        partial = ""
        texts = {}
        for name, stream in (("stderr", e.stderr), ("stdout", e.stdout)):
            if stream:
                text = stream if isinstance(stream, str) else stream.decode(
                    "utf-8", "replace")
                texts[name] = text
                sys.stderr.write(text[-2000:])
                partial = partial or "; ".join(
                    text.strip().splitlines()[-2:])[-300:]
        result = _parse_json_line(texts.get("stdout", ""))
        if result is not None:
            sys.stderr.write(
                "\nworker timed out after printing a result; "
                "recovered the measurement from partial stdout\n")
            return result, "ok (recovered from timed-out worker)"
        note = f"attempt timed out after {int(timeout)}s"
        return None, f"{note}: {partial}" if partial else note
    except OSError as e:
        return None, f"spawn failed: {e}"
    sys.stderr.write(proc.stderr[-2000:])
    result = _parse_json_line(proc.stdout)
    if result is not None:
        return result, "ok"
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    note = "; ".join(tail[-3:])[-500:] if tail else f"rc={proc.returncode}"
    return None, f"rc={proc.returncode}: {note}"


def _last_recorded_tpu_line() -> dict | None:
    """Newest committed ``backend: "tpu"`` bench line from an earlier
    campaign window (any ``benchmarks/results/*bench*.json`` — the wide
    filter means pruning campaign files can't silently drop provenance
    so long as ANY bench artifact with a TPU line survives), for
    labeling a transport-down CPU insurance line with the hardware
    evidence that does exist. Returns None when no such line is on
    disk. Never raises — this is best-effort metadata."""
    here = os.path.dirname(os.path.abspath(__file__))
    # Keyed (mtime, name): the name breaks fresh-checkout mtime ties
    # deterministically (campaign2 sorts after campaign).
    newest: tuple[tuple[float, str], dict] | None = None
    rdir = os.path.join(here, "benchmarks", "results")
    try:
        names = os.listdir(rdir)
    except OSError:
        return None
    for name in names:
        if not ("bench" in name and name.endswith(".json")):
            continue
        path = os.path.join(rdir, name)
        try:
            mtime = os.path.getmtime(path)
            with open(path) as f:
                text = f.read()
        except OSError:
            continue  # one unreadable file must not discard the rest
        for line in reversed(text.strip().splitlines()):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and obj.get("backend") == "tpu":
                if newest is None or (mtime, name) > newest[0]:
                    newest = ((mtime, name), {
                        "note": "recorded in an earlier tunnel window,"
                                " NOT measured by this run",
                        "source": f"benchmarks/results/{name}",
                        **{k: obj[k] for k in (
                            "metric", "value", "unit", "seconds",
                            "executor", "device_kind") if k in obj},
                    })
                break
    return None if newest is None else newest[1]


def _append_history(result: dict) -> None:
    """Append this run's record to the benchmark history store
    (``benchmarks/results/history.jsonl``; DFFT_BENCH_HISTORY overrides,
    empty/0 disables). The regress module is loaded from its file
    directly — importing the package ``__init__`` pulls in jax, and the
    orchestrator must stay importable-anything-free so a sick TPU
    transport can never hang the append. Best-effort: never raises."""
    try:
        import importlib.util

        here = os.path.dirname(os.path.abspath(__file__))
        spec = importlib.util.spec_from_file_location(
            "_dfft_regress",
            os.path.join(here, "distributedfft_tpu", "regress.py"))
        regress = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(regress)
        path = regress.default_history_path()
        if path is None:
            return
        rec = regress.normalize_bench_line(
            result, source="bench.py", commit=regress.git_commit())
        if rec is not None:
            regress.append_records([rec], path)
            print(f"history: run record appended to {path}",
                  file=sys.stderr)
    except Exception:  # noqa: BLE001 — history is telemetry, not contract
        import traceback

        traceback.print_exc(limit=3, file=sys.stderr)


def main() -> None:
    """Print result lines (contract: last line wins) and append the
    final measurement to the benchmark history store."""
    try:
        result = _orchestrate()
    except Exception as e:  # noqa: BLE001 — the contract is JSON + rc 0
        result = {
            "metric": f"fft3d_c2c_{_flagship_n()}_forward_gflops",
            "value": 0.0,
            "unit": "GFlops/s",
            "vs_baseline": 0.0,
            "telemetry": {
                "status": {
                    "tpu_available": False,
                    "fallback_backend": None,
                    "failures": [f"orchestrator: {type(e).__name__}: {e}"],
                    "last_recorded_tpu": None,
                }
            },
        }
        print(json.dumps(result), flush=True)
    if result is not None:
        _append_history(result)


def _orchestrate() -> dict | None:
    """Run the insurance/upgrade/fallback schedule; every result line is
    printed as it exists, and the FINAL one (the driver's last-line-wins
    contract) is returned for the history store."""
    deadline = time.time() + float(os.environ.get("DFFT_BENCH_DEADLINE", 540))
    errors: list[str] = []
    have_line = False
    final: dict | None = None

    def _guard_cpu(res: dict) -> dict:
        # A CPU-backend number is never comparable to the GPU baseline;
        # only the explicit fallback path should produce one, but if the
        # ambient default backend is CPU (e.g. a CI environment), phase
        # A/B lines must not claim a vs_baseline either.
        if res.get("backend") == "cpu":
            res["vs_baseline"] = 0.0
        return res

    # Phase A — insurance: smallest credible TPU number, fastest possible
    # path (one executor, no extras), printed the moment it exists.
    # Retried on a loop until the deadline (minus the CPU-fallback
    # reserve): the axon tunnel is *intermittent*, so a window that opens
    # mid-run must still turn into a TPU line — stopping after two tries
    # (the r1-r3 behaviour) forfeits every later window. Timed-out
    # attempts re-try immediately (the timeout itself is the pacing, and
    # a slow-but-alive tunnel leaves its completed compiles in the
    # persistent cache so the retry mostly just measures); fast failures
    # back off so an instantly-erroring backend can't busy-spin the
    # whole deadline.
    fallback_reserve = 75.0  # keeps the CPU last-resort reachable
    min_attempt_window = 100.0  # smallest remaining that fits one 90s try
    attempt = 0
    backoff = 15.0
    while True:
        remaining = deadline - time.time()
        if remaining < min_attempt_window:
            break
        if attempt > 0 and remaining < min_attempt_window + fallback_reserve:
            # Every attempt so far failed (dead-tunnel evidence): stop
            # while the CPU last-resort still fits, so the driver gets a
            # labelled measurement rather than the bare zero line.
            break
        # Reserve fallback time when there's room; on a fresh short
        # deadline, prefer spending it on a real TPU try (90s floor) over
        # guaranteeing the CPU line — a TPU number is the whole point.
        insurance_cap = min(
            240.0, max(90.0, remaining - fallback_reserve - 30))
        started = time.time()
        result, note = _run_attempt(
            256, insurance_cap, extra_env={"DFFT_BENCH_FAST": "1"})
        if result is not None:
            final = _guard_cpu(result)
            print(json.dumps(final), flush=True)
            have_line = True
            break
        errors.append(f"tpu@256-insurance[{attempt}]: {note}")
        attempt += 1
        if time.time() - started < insurance_cap * 0.5:
            # Fast failure: back off, but never sleep away the last
            # viable attempt window (after the first failure the loop
            # also demands the fallback reserve, so preserve both).
            time.sleep(min(backoff, max(
                0.0, deadline - time.time()
                - min_attempt_window - fallback_reserve)))
            backoff = min(backoff * 2, 120.0)

    # Phase B — upgrade in place: the flagship 512^3 with the full
    # tournament, donation, and stage breakdown. Its line supersedes the
    # insurance line (the driver parses the last line). Only reachable
    # with an insurance line in hand (the loop above spends the rest of
    # the deadline otherwise), so the tunnel is known-alive here.
    remaining = deadline - time.time()
    if have_line and remaining > 150:
        flagship = _flagship_n()
        result, note = _run_attempt(flagship, remaining - 30)
        if result is not None:
            final = _guard_cpu(result)
            print(json.dumps(final), flush=True)
            return final
        errors.append(f"tpu@{flagship}: {note}")
    if have_line:
        return final

    # Last resort: a clearly-labelled CPU-backend measurement so the driver
    # records a parseable line even with the TPU transport down (measured
    # ~15 s on this box; 45 s floor leaves margin).
    remaining = deadline - time.time()
    if remaining > 45:
        result, note = _run_attempt(
            256, min(600.0, remaining - 15),
            # Clearing PALLAS_AXON_POOL_IPS skips the axon PJRT
            # registration in sitecustomize entirely — with it set, even a
            # JAX_PLATFORMS=cpu process attempts (and can hang in) axon
            # backend init through the sick tunnel.
            extra_env={"JAX_PLATFORMS": "cpu",
                       "PALLAS_AXON_POOL_IPS": "",
                       "DFFT_BENCH_FAST": "1",
                       "DFFT_BENCH_EXECUTORS": "xla"},
        )
        if result is not None:
            result["vs_baseline"] = 0.0  # CPU number; not comparable
            # Structured status block: attempt-by-attempt failure list,
            # fallback marker, and the newest committed TPU line — NOT
            # this run's measurement, attached so a transport-down
            # insurance line stays interpretable. (The run-record store
            # reads tpu_available to flag this line as a fallback,
            # excluded from TPU baselines.)
            tel = result.setdefault("telemetry", {})
            tel["status"] = {
                "tpu_available": False,
                "fallback_backend": "cpu",
                "failures": errors or ["no attempt fit the deadline"],
                "last_recorded_tpu": _last_recorded_tpu_line(),
            }
            print(json.dumps(result), flush=True)
            return result
        errors.append(f"cpu-fallback: {note}")

    final = {
        "metric": f"fft3d_c2c_{_flagship_n()}_forward_gflops",
        "value": 0.0,
        "unit": "GFlops/s",
        "vs_baseline": 0.0,
        "telemetry": {
            "status": {
                "tpu_available": False,
                "fallback_backend": None,
                "failures": errors,
                "last_recorded_tpu": None,
            }
        },
    }
    print(json.dumps(final), flush=True)
    return final


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        _worker(int(sys.argv[2]))
    elif len(sys.argv) > 1 and sys.argv[1] in ("--serve-streaming",
                                               "--serve-flush"):
        # Direct serving-scheduler measurement (no orchestrator): drive
        # a CoalescingQueue in streaming or discrete-flush mode at the
        # given extent (default 128 — the wave scheduler, not the FFT,
        # is under test) and print the one result line.
        os.environ["DFFT_BENCH_SERVE"] = (
            "stream" if sys.argv[1] == "--serve-streaming" else "flush")
        _worker(int(sys.argv[2]) if len(sys.argv) > 2 else 128)
    else:
        main()  # catches internally; the contract is JSON + rc 0
        sys.exit(0)
