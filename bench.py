#!/usr/bin/env python
"""Headline benchmark: distributed 3D C2C forward FFT, reference taxonomy.

Prints exactly ONE JSON line on stdout and always exits 0 — the contract the
round driver records into ``BENCH_r{N}.json``. The measured metric is the
flagship problem (512^3, cf. ``/root/reference/README.md:44-58``) timed on
the available TPU device(s): GFlops/s = 5 N log2 N / t
(``fftSpeed3d_c2c.cpp:128``) versus the reference's heFFTe baseline
(324.4 GFlops/s at 512^3 on 4 GPUs, ``README.md:65-77``).

Robustness (the round-1 failure mode was an axon TPU tunnel whose backend
init hangs indefinitely, producing rc=1 and zero perf evidence): this file
is an *orchestrator* that runs the actual measurement in worker
subprocesses, because a wedged PJRT client cannot be cancelled in-process.

  - bounded retries with backoff around backend init/measurement;
  - a hard timeout per attempt and an overall deadline;
  - problem-size fallback 512^3 -> 256^3 on repeated failure/OOM;
  - a last-resort CPU-backend measurement (clearly labelled) so the driver
    still gets a parseable line when the TPU transport is down;
  - on truly unrecoverable failure, a JSON line with an "error" field —
    never a bare traceback, never a nonzero exit.

Executor selection mirrors the reference keeping several backends side by
side and picking one (``setFFTPlans``, ``fft_mpi_3d_api.cpp:318-429``): every
candidate in DFFT_BENCH_EXECUTORS (default "xla,pallas,matmul") is planned,
verified by roundtrip, and timed; the fastest correct one is reported. A
candidate that fails to compile or verify is skipped, never fatal.

TPU note: TPUs have no complex128 (C128 unsupported), so the on-chip bench
runs complex64; double-precision correctness at the 1e-11 tier is validated
by the CPU-backend test suite (tests/test_fft3d.py).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HEFFTE_BASELINE_GFLOPS = 324.4  # README.md:65-77, 512^3 / 4 ranks / rocfft
ERR_GATE = 1e-3  # complex64 tier; double tier is gated in the test suite


# --------------------------------------------------------------- worker

def bench_executor(shape, mesh, dtype, executor: str):
    """Plan, verify (roundtrip), and time one executor. Returns
    (seconds, max_err, decomposition) or raises."""
    import functools

    import jax
    import jax.numpy as jnp

    import distributedfft_tpu as dfft
    from distributedfft_tpu.utils.timing import (
        max_rel_err, sync, time_fn_amortized,
    )

    plan = dfft.plan_dft_c2c_3d(
        shape, mesh, direction=dfft.FORWARD, dtype=dtype, donate=False,
        executor=executor,
    )
    iplan = dfft.plan_dft_c2c_3d(
        shape, mesh, direction=dfft.BACKWARD, dtype=dtype, donate=False,
        executor=executor,
    )

    # Deterministic on-device init (host->device of 1 GiB through the
    # tunnel is avoided; the reference also inits on device,
    # fftSpeed3d_c2c.cpp:61-72).
    mk_kw = {}
    if plan.in_sharding is not None:
        mk_kw["out_shardings"] = plan.in_sharding

    @functools.partial(jax.jit, **mk_kw)
    def make_input():
        k1, k2 = jax.random.split(jax.random.PRNGKey(4242))
        re = jax.random.normal(k1, shape, jnp.float32)
        im = jax.random.normal(k2, shape, jnp.float32)
        return (re + 1j * im).astype(dtype)

    x = make_input()
    sync(x)

    # Roundtrip error check (the reference's inline validation,
    # fftSpeed3d_c2c.cpp:85-91).
    max_err = max_rel_err(iplan(plan(x)), x)
    if not max_err < ERR_GATE:
        raise AssertionError(f"roundtrip error {max_err} exceeds {ERR_GATE}")

    seconds, _ = time_fn_amortized(lambda: plan(x), iters=10, repeats=3)
    return seconds, max_err, plan.decomposition


def _worker(shape_n: int) -> None:
    """Measure and print the result JSON line (runs in a subprocess)."""
    import traceback

    import jax

    from distributedfft_tpu.utils.cache import enable_compile_cache

    enable_compile_cache()
    import jax.numpy as jnp

    import distributedfft_tpu as dfft
    from distributedfft_tpu.utils.timing import gflops, time_staged

    shape = (shape_n,) * 3
    devs = jax.devices()  # orchestrator enforces the timeout around this
    n_dev = len(devs)
    mesh = dfft.make_mesh(n_dev) if n_dev > 1 else None
    dtype = jnp.complex64  # TPU: no C128

    candidates = [
        e.strip()
        for e in os.environ.get(
            "DFFT_BENCH_EXECUTORS", "xla,pallas,matmul"
        ).split(",")
        if e.strip()
    ]
    results = {}
    for ex in candidates:
        try:
            results[ex] = bench_executor(shape, mesh, dtype, ex)
        except Exception:  # noqa: BLE001 — a failed candidate is skipped
            print(f"executor {ex!r} failed:", file=sys.stderr)
            traceback.print_exc(limit=3, file=sys.stderr)

    if not results:
        raise SystemExit("no benchmark executor succeeded")
    best = min(results, key=lambda e: results[e][0])
    seconds, max_err, decomposition = results[best]

    gf = gflops(shape, seconds)
    out = {
        "metric": f"fft3d_c2c_{shape_n}_forward_gflops",
        "value": round(gf, 1),
        "unit": "GFlops/s",
        "vs_baseline": round(gf / HEFFTE_BASELINE_GFLOPS, 3),
        "seconds": round(seconds, 6),
        "max_roundtrip_err": max_err,
        "dtype": "complex64",
        "backend": jax.default_backend(),
        "devices": n_dev,
        "decomposition": decomposition,
        "executor": best,
        "all": {e: round(r[0], 6) for e, r in results.items()},
    }
    # The measurement is in hand: print it BEFORE the best-effort staged
    # extras, which compile fresh programs and can wedge on a sick tunnel
    # (a hang there must not cost the number; the orchestrator recovers
    # the last parseable line from partial stdout on timeout).
    print(json.dumps(out), flush=True)

    # Per-stage t0..t3 breakdown (fft_mpi_3d_api.cpp:184-201); the
    # reference prints it even single-rank (t1/t2 zero without an
    # exchange).
    stages = None
    try:
        stage_fns = None
        if mesh is not None and decomposition == "slab":
            from distributedfft_tpu.parallel.slab import build_slab_stages

            stage_fns, _ = build_slab_stages(
                mesh, shape, axis_name=mesh.axis_names[0], executor=best,
                forward=True,
            )
        elif mesh is None:
            from distributedfft_tpu.parallel.staged import (
                build_single_stages,
            )

            stage_fns = build_single_stages(shape, executor=best)
        if stage_fns is not None:
            plan = dfft.plan_dft_c2c_3d(
                shape, mesh, direction=dfft.FORWARD, dtype=dtype,
                executor=best,
            )
            x = dfft.alloc_local(plan, fill=None)
            st, _ = time_staged(stage_fns, x, iters=3)
            stages = {k: round(v, 6) for k, v in st.times.items()}
    except Exception:  # noqa: BLE001 — breakdown is best-effort extra
        traceback.print_exc(limit=3, file=sys.stderr)

    if stages:
        # Enriched line supersedes the base one (the orchestrator parses
        # the LAST line carrying "metric").
        out["stages"] = stages
        print(json.dumps(out), flush=True)


# ----------------------------------------------------------- orchestrator

def _parse_json_line(text: str) -> dict | None:
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "metric" in obj:
                return obj
    return None


def _run_attempt(shape_n: int, timeout: float, extra_env: dict | None = None):
    """Run one worker subprocess. Returns (result_dict|None, note)."""
    env = dict(os.environ)
    if extra_env:
        env.update(extra_env)
    try:
        proc = subprocess.run(
            [sys.executable, "-u", os.path.abspath(__file__),
             "--worker", str(shape_n)],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired as e:
        # Keep the child's partial output — a worker that printed its
        # result line and then wedged in best-effort extras still counts
        # (the measurement is recovered from partial stdout).
        partial = ""
        texts = {}
        for name, stream in (("stderr", e.stderr), ("stdout", e.stdout)):
            if stream:
                text = stream if isinstance(stream, str) else stream.decode(
                    "utf-8", "replace")
                texts[name] = text
                sys.stderr.write(text[-2000:])
                partial = partial or "; ".join(
                    text.strip().splitlines()[-2:])[-300:]
        result = _parse_json_line(texts.get("stdout", ""))
        if result is not None:
            sys.stderr.write(
                "\nworker timed out after printing its result; "
                "recovered the measurement from partial stdout\n")
            return result, "ok (recovered from timed-out worker)"
        note = f"attempt timed out after {int(timeout)}s"
        return None, f"{note}: {partial}" if partial else note
    except OSError as e:
        return None, f"spawn failed: {e}"
    sys.stderr.write(proc.stderr[-2000:])
    result = _parse_json_line(proc.stdout)
    if result is not None:
        return result, "ok"
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    note = "; ".join(tail[-3:])[-500:] if tail else f"rc={proc.returncode}"
    return None, f"rc={proc.returncode}: {note}"


def main() -> None:
    deadline = time.time() + float(os.environ.get("DFFT_BENCH_DEADLINE", 2100))
    errors: list[str] = []

    # (shape, per-attempt timeout, backoff before the attempt)
    schedule = [(512, 780, 0), (512, 780, 15), (256, 600, 30), (256, 600, 60)]
    for shape_n, timeout, backoff in schedule:
        remaining = deadline - time.time()
        if remaining < 120:
            errors.append("deadline reached before attempt")
            break
        if backoff:
            time.sleep(min(backoff, max(0.0, remaining - 120)))
        timeout = min(timeout, max(120.0, deadline - time.time() - 60))
        result, note = _run_attempt(shape_n, timeout)
        if result is not None:
            print(json.dumps(result), flush=True)
            return
        errors.append(f"tpu@{shape_n}: {note}")

    # Last resort: a clearly-labelled CPU-backend measurement so the driver
    # records a parseable line even with the TPU transport down.
    remaining = deadline - time.time()
    if remaining > 180:
        result, note = _run_attempt(
            256, min(600.0, remaining - 60),
            # Clearing PALLAS_AXON_POOL_IPS skips the axon PJRT
            # registration in sitecustomize entirely — with it set, even a
            # JAX_PLATFORMS=cpu process attempts (and can hang in) axon
            # backend init through the sick tunnel.
            extra_env={"JAX_PLATFORMS": "cpu",
                       "PALLAS_AXON_POOL_IPS": "",
                       "DFFT_BENCH_EXECUTORS": "xla"},
        )
        if result is not None:
            result["error"] = "tpu unavailable: " + " | ".join(errors)[-700:]
            result["vs_baseline"] = 0.0  # CPU number; not comparable
            print(json.dumps(result), flush=True)
            return
        errors.append(f"cpu-fallback: {note}")

    print(
        json.dumps(
            {
                "metric": "fft3d_c2c_512_forward_gflops",
                "value": 0.0,
                "unit": "GFlops/s",
                "vs_baseline": 0.0,
                "error": " | ".join(errors)[-1500:],
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        _worker(int(sys.argv[2]))
    else:
        try:
            main()
        except Exception as e:  # noqa: BLE001 — the contract is JSON + rc 0
            print(
                json.dumps(
                    {
                        "metric": "fft3d_c2c_512_forward_gflops",
                        "value": 0.0,
                        "unit": "GFlops/s",
                        "vs_baseline": 0.0,
                        "error": f"orchestrator: {type(e).__name__}: {e}",
                    }
                ),
                flush=True,
            )
        sys.exit(0)
