#!/usr/bin/env python
"""Headline benchmark: distributed 3D C2C forward FFT, reference taxonomy.

Runs the flagship problem (512^3, cf. ``/root/reference/README.md:44-58``) on
the available TPU device(s) and prints ONE JSON line with the headline
GFlops/s (5 N log2 N / t, ``fftSpeed3d_c2c.cpp:128``) versus the reference's
heFFTe baseline (324.4 GFlops/s at 512^3 on 4 GPUs, ``README.md:65-77``).

Executor selection mirrors the reference keeping several backends side by
side and picking one (``setFFTPlans``, ``fft_mpi_3d_api.cpp:318-429``): every
candidate in DFFT_BENCH_EXECUTORS (default "xla,pallas") is planned, verified
by roundtrip, and timed; the fastest correct one is reported. A candidate
that fails to compile or verify is skipped, never fatal.

TPU note: TPUs have no complex128 (C128 unsupported), so the on-chip bench
runs complex64; double-precision correctness at the 1e-11 tier is validated
by the CPU-backend test suite (tests/test_fft3d.py).
"""

import functools
import json
import os
import sys
import traceback

import jax
import jax.numpy as jnp

import distributedfft_tpu as dfft
from distributedfft_tpu.utils.timing import gflops, max_rel_err, sync, time_fn_amortized

HEFFTE_BASELINE_GFLOPS = 324.4  # README.md:65-77, 512^3 / 4 ranks / rocfft
ERR_GATE = 1e-3  # complex64 tier; double tier is gated in the test suite


def bench_executor(shape, mesh, dtype, executor: str):
    """Plan, verify (roundtrip), and time one executor. Returns
    (seconds, max_err, decomposition) or raises."""
    plan = dfft.plan_dft_c2c_3d(
        shape, mesh, direction=dfft.FORWARD, dtype=dtype, donate=False,
        executor=executor,
    )
    iplan = dfft.plan_dft_c2c_3d(
        shape, mesh, direction=dfft.BACKWARD, dtype=dtype, donate=False,
        executor=executor,
    )

    # Deterministic on-device init (host->device of 1 GiB through the tunnel
    # is avoided; the reference also inits on device, fftSpeed3d_c2c.cpp:61-72).
    mk_kw = {}
    if plan.in_sharding is not None:
        mk_kw["out_shardings"] = plan.in_sharding  # generate each shard in place

    @functools.partial(jax.jit, **mk_kw)
    def make_input():
        k1, k2 = jax.random.split(jax.random.PRNGKey(4242))
        re = jax.random.normal(k1, shape, jnp.float32)
        im = jax.random.normal(k2, shape, jnp.float32)
        return (re + 1j * im).astype(dtype)

    x = make_input()
    sync(x)

    # Roundtrip error check (the reference's inline validation,
    # fftSpeed3d_c2c.cpp:85-91).
    max_err = max_rel_err(iplan(plan(x)), x)
    if not max_err < ERR_GATE:
        raise AssertionError(f"roundtrip error {max_err} exceeds {ERR_GATE}")

    seconds, _ = time_fn_amortized(lambda: plan(x), iters=10, repeats=3)
    return seconds, max_err, plan.decomposition


def main() -> None:
    shape = (512, 512, 512)
    n_dev = len(jax.devices())
    mesh = dfft.make_mesh(n_dev) if n_dev > 1 else None
    dtype = jnp.complex64  # TPU: no C128

    candidates = [
        e.strip()
        for e in os.environ.get("DFFT_BENCH_EXECUTORS", "xla,pallas").split(",")
        if e.strip()
    ]
    results = {}
    for ex in candidates:
        try:
            results[ex] = bench_executor(shape, mesh, dtype, ex)
        except Exception:  # noqa: BLE001 — a failed candidate is skipped
            print(f"executor {ex!r} failed:", file=sys.stderr)
            traceback.print_exc(limit=3)

    if not results:
        raise SystemExit("no benchmark executor succeeded")
    best = min(results, key=lambda e: results[e][0])
    seconds, max_err, decomposition = results[best]
    gf = gflops(shape, seconds)

    print(
        json.dumps(
            {
                "metric": "fft3d_c2c_512_forward_gflops",
                "value": round(gf, 1),
                "unit": "GFlops/s",
                "vs_baseline": round(gf / HEFFTE_BASELINE_GFLOPS, 3),
                "seconds": round(seconds, 6),
                "max_roundtrip_err": max_err,
                "dtype": "complex64",
                "devices": n_dev,
                "decomposition": decomposition,
                "executor": best,
                "all": {e: round(r[0], 6) for e, r in results.items()},
            }
        )
    )


if __name__ == "__main__":
    main()
