#!/usr/bin/env python
"""Batched single-device FFT sweep — the batchTest harness analog.

Reproduces the reference's single-GPU benchmark methodology
(``templateFFT/batchTest/``): batched 1D transforms at a fixed total element
count with the length swept over powers of a radix (``runTest1D_opt.sh``
sweeps powers of 2/3/5/7 up to 48,828,125), and 2D transforms over a shrinking
grid (``runTest2D_opt.sh``: 2048 -> 128). Timing via forced-completion wall
clock (the hipEvent analog, ``Test_1D.cpp:123-137``), GFlops =
5 N log2 N · batch / t (``:139``), FFT->iFFT roundtrip max error
(``:169-176``), CSV rows (``:186-190``) mirroring ``templateFFT/csv/*.csv``.

Examples::

    python benchmarks/batch_bench.py 1d -radix 2 -total $((1<<24))
    python benchmarks/batch_bench.py 1d -radix 5 -executor matmul
    python benchmarks/batch_bench.py 2d -sizes 512 256 128
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def parse_args(argv):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("mode", choices=["1d", "2d"])
    p.add_argument("-radix", type=int, default=2,
                   help="1d: sweep powers of this radix (>= 2)")
    p.add_argument("-total", type=int, default=1 << 22,
                   help="1d: total elements per run (batch = total // n); "
                        "reference uses 64*32*2^15 (Test_1D.cpp:210)")
    p.add_argument("-max-n", type=int, default=None, help="1d: largest length")
    p.add_argument("-sizes", type=int, nargs="+", default=[512, 256, 128],
                   help="2d: square grid edges to sweep")
    p.add_argument("-batch", type=int, default=None, help="2d: batch override")
    p.add_argument("-executor", default="xla")
    p.add_argument("-precision", choices=["double", "single"], default="single")
    p.add_argument("-iters", type=int, default=5)
    p.add_argument("-csv", default=None, help="CSV output path "
                   "(default benchmarks/csv/batch_result{1D,2D}.csv)")
    p.add_argument("-cpu", action="store_true")
    return p.parse_args(argv)


def run_one(plan, iplan, x, iters):
    from distributedfft_tpu.utils.timing import max_rel_err, time_fn_amortized

    err = max_rel_err(iplan(plan(x)), x)
    seconds, _ = time_fn_amortized(lambda: plan(x), iters=iters, repeats=2)
    return seconds, err


def main(argv=None) -> None:
    args = parse_args(argv if argv is not None else sys.argv[1:])
    if args.radix < 2:
        raise SystemExit("-radix must be >= 2")
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if args.precision == "double":
        jax.config.update("jax_enable_x64", True)

    import distributedfft_tpu as dfft
    from distributedfft_tpu.utils.trace import CsvRecorder
    from distributedfft_tpu.utils.timing import sync

    dtype = jnp.complex128 if args.precision == "double" else jnp.complex64
    header = ("n0", "n1", "batch", "seconds", "gflops", "max_err")
    csv_path = args.csv or (
        f"benchmarks/csv/batch_result{args.mode.upper()}.csv"
    )
    rec = CsvRecorder(csv_path, header)

    def make(shape_full):
        @jax.jit
        def mk():
            k1, k2 = jax.random.split(jax.random.PRNGKey(4242))
            rdt = jnp.float64 if dtype == jnp.complex128 else jnp.float32
            return (jax.random.normal(k1, shape_full, rdt)
                    + 1j * jax.random.normal(k2, shape_full, rdt)).astype(dtype)

        x = mk()
        sync(x)
        return x

    if args.mode == "1d":
        n = args.radix
        max_n = args.max_n or args.total
        while n <= max_n:
            batch = max(1, args.total // n)
            plan = dfft.plan_dft_c2c_1d(
                n, batch=batch, executor=args.executor, dtype=dtype)
            iplan = dfft.plan_dft_c2c_1d(
                n, batch=batch, executor=args.executor, dtype=dtype,
                direction=dfft.BACKWARD)
            x = make((batch, n))
            seconds, err = run_one(plan, iplan, x, args.iters)
            gf = plan.flops() / seconds / 1e9
            print(f"1D n={n:>10} batch={batch:>8} t={seconds:.6f}s "
                  f"{gf:8.1f} GFlops/s err={err:.3e}")
            rec.record(n, 1, batch, f"{seconds:.6f}", f"{gf:.1f}", f"{err:.3e}")
            n *= args.radix
    else:
        for edge in args.sizes:
            shape = (edge, edge)
            batch = args.batch or max(1, args.total // (edge * edge))
            plan = dfft.plan_dft_c2c_2d(
                shape, batch=batch, executor=args.executor, dtype=dtype)
            iplan = dfft.plan_dft_c2c_2d(
                shape, batch=batch, executor=args.executor, dtype=dtype,
                direction=dfft.BACKWARD)
            x = make((batch,) + shape)
            seconds, err = run_one(plan, iplan, x, args.iters)
            gf = plan.flops() / seconds / 1e9
            print(f"2D {edge}x{edge} batch={batch:>6} t={seconds:.6f}s "
                  f"{gf:8.1f} GFlops/s err={err:.3e}")
            rec.record(edge, edge, batch, f"{seconds:.6f}", f"{gf:.1f}",
                       f"{err:.3e}")

    print(f"results appended to {csv_path}")


if __name__ == "__main__":
    main()
