#!/usr/bin/env bash
# Probe the tunnel; when LIVE, run (resume) hw_campaign2.sh. Repeat until
# the campaign completes or the deadline passes. One log line per probe.
set -u
cd "$(dirname "$0")/.."
LOG=benchmarks/results/campaign2_loop.log
DEADLINE=$(( $(date +%s) + ${1:-36000} ))
log() { echo "[$(date '+%F %T')] $*" | tee -a "$LOG"; }
log "loop start (deadline in ${1:-36000}s)"
n=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  n=$((n+1))
  if timeout 75 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    log "probe[$n] LIVE -> campaign2"
    bash benchmarks/hw_campaign2.sh >> benchmarks/results/hw_campaign2_r05.log 2>&1
    rc=$?
    log "campaign2 rc=$rc"
    # Belt: hardware rows must survive a builder-session crash — commit
    # the benchmark artifacts the moment a campaign pass ends. Pathspec
    # commit: a concurrent session's staged files (outside these two
    # dirs) must never be swept into the artifact commit.
    git add benchmarks/csv benchmarks/results >/dev/null 2>&1
    git diff --cached --quiet -- benchmarks/csv benchmarks/results 2>/dev/null || \
      git commit -q -m "Hardware-window artifacts (auto-committed by campaign2_loop)" \
        -- benchmarks/csv benchmarks/results
    if [ $rc -eq 0 ]; then log "campaign2 COMPLETE"; exit 0; fi
    sleep 60
  else
    log "probe[$n] down"
    sleep 120
  fi
done
log "deadline reached"
exit 3
