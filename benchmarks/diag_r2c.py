#!/usr/bin/env python
"""On-chip r2c/c2r bisection — run on the real TPU when it is free.

The round-5 campaign's first hardware rows showed the r2c tier failing
its roundtrip gate ON TPU ONLY (speed3d_tpu1.csv: xla 3.4e-01 at 256^3,
every executor 3.7e-01..8.3e-01 at 512^3) while the identical configs
pass at 1e-6 on CPU. This driver isolates which primitive is wrong on
the TPU backend:

  1. native jnp.fft.rfft        vs host numpy        (fwd only)
  2. native jnp.fft.irfft       vs host numpy        (inv only)
  3. fft+slice r2c              vs host numpy        (no native rfft)
  4. mirror+ifft c2r            vs host numpy        (no native irfft)
  5. packed half-complex pair (matmul executor)      at n=256 and 512
  6. full 3D plan roundtrips, per executor, 256^3 and 384^3 and 512^3

Each step prints one line and appends to benchmarks/csv/diag_r2c_tpu.csv;
a crash keeps earlier rows (record-as-you-go).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributedfft_tpu.utils.cache import enable_compile_cache

    enable_compile_cache()

    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "csv",
                        f"diag_r2c_{jax.default_backend()}.csv")
    fresh = not os.path.exists(path)
    f = open(path, "a")
    if fresh:
        f.write("step,n,err,status\n")

    def rec(step, n, err, status="ok"):
        f.write(f"{step},{n},{err:.3e},{status}\n")
        f.flush()
        print(f"[diag_r2c] {step} n={n}: {err:.3e} {status}", flush=True)

    def dev_err(got, ref_np):
        # On-device |got - ref| / max|ref| with the ref pushed as its real/
        # imag planes (complex host->device transfers also ride the tunnel
        # fine; complex device->host does not, so never np.asarray(got)).
        ref = jnp.asarray(ref_np.astype(np.asarray(got).dtype
                                        if not jnp.iscomplexobj(got)
                                        else np.complex64))
        e = jnp.max(jnp.abs(got - ref)) / jnp.max(jnp.abs(ref))
        return float(jax.device_get(e))

    rng = np.random.default_rng(5)

    for n in (256, 512):
        x = rng.standard_normal((64, n)).astype(np.float32)
        xd = jnp.asarray(x)
        ref_f = np.fft.rfft(x.astype(np.float64), axis=1)
        ref_full = np.fft.fft(x.astype(np.float64), axis=1)

        # 1. native rfft
        try:
            got = jax.jit(lambda a: jnp.fft.rfft(a, axis=1))(xd)
            rec("native_rfft", n, dev_err(got, ref_f))
        except Exception as e:  # noqa: BLE001
            rec("native_rfft", n, -1.0, f"ERROR {type(e).__name__}")

        # 2. native irfft (host-exact half-spectrum input)
        try:
            y = jnp.asarray(ref_f.astype(np.complex64))
            got = jax.jit(lambda a: jnp.fft.irfft(a, n=n, axis=1))(y)
            rec("native_irfft", n, dev_err(got, x))
        except Exception as e:  # noqa: BLE001
            rec("native_irfft", n, -1.0, f"ERROR {type(e).__name__}")

        # 2b. native complex fft/ifft as control
        try:
            got = jax.jit(lambda a: jnp.fft.fft(a.astype(jnp.complex64),
                                                axis=1))(xd)
            rec("native_cfft", n, dev_err(got, ref_full))
            yc = jnp.asarray(ref_full.astype(np.complex64))
            got = jax.jit(lambda a: jnp.real(jnp.fft.ifft(a, axis=1)))(yc)
            rec("native_cifft", n, dev_err(got, x))
        except Exception as e:  # noqa: BLE001
            rec("native_cfft", n, -1.0, f"ERROR {type(e).__name__}")

        # 3. fft + slice r2c
        try:
            got = jax.jit(
                lambda a: jax.lax.slice_in_dim(
                    jnp.fft.fft(a.astype(jnp.complex64), axis=1),
                    0, n // 2 + 1, axis=1))(xd)
            rec("slice_r2c", n, dev_err(got, ref_f))
        except Exception as e:  # noqa: BLE001
            rec("slice_r2c", n, -1.0, f"ERROR {type(e).__name__}")

        # 4. mirror + ifft c2r
        try:
            from distributedfft_tpu.ops.executors import mirror_c2r

            y = jnp.asarray(ref_f.astype(np.complex64))
            got = jax.jit(lambda a: mirror_c2r(a, n, 1))(y)
            rec("mirror_c2r", n, dev_err(got, x))
        except Exception as e:  # noqa: BLE001
            rec("mirror_c2r", n, -1.0, f"ERROR {type(e).__name__}")

        # 5. packed half-complex pair with the matmul engine
        try:
            from distributedfft_tpu.ops.executors import get_c2r, get_r2c

            got = get_r2c("matmul")(xd, 1)
            rec("packed_r2c_matmul", n, dev_err(got, ref_f))
            y = jnp.asarray(ref_f.astype(np.complex64))
            got = get_c2r("matmul")(y, n, 1)
            rec("packed_c2r_matmul", n, dev_err(got, x))
        except Exception as e:  # noqa: BLE001
            rec("packed_matmul", n, -1.0, f"ERROR {type(e).__name__}")

    # 6. full 3D plan roundtrips
    import distributedfft_tpu as dfft

    for n in (256, 384, 512):
        shape = (n, n, n)
        for ex in ("xla", "matmul"):
            try:
                fwd = dfft.plan_dft_r2c_3d(shape, None, dtype=jnp.complex64,
                                           executor=ex)
                bwd = dfft.plan_dft_c2r_3d(shape, None, dtype=jnp.complex64,
                                           executor=ex)
                key = jax.random.PRNGKey(7)
                x3 = jax.jit(lambda k: jax.random.normal(k, shape,
                                                         jnp.float32))(key)
                back = bwd(fwd(x3))
                e = jnp.max(jnp.abs(back - x3)) / jnp.max(jnp.abs(x3))
                rec(f"plan3d_{ex}", n, float(jax.device_get(e)))
                # fwd-only check against a host reference on a thin slab
                # (full 3D f64 reference is too big to ship through the
                # tunnel; one YZ plane suffices to catch wrongness).
                xs = np.asarray(jax.device_get(jnp.real(x3[:1])))
                ref = np.fft.rfftn(xs.astype(np.float64), axes=(1, 2))
                got = fwd(x3)[:1]
                # compare only the plane transform of axes 1,2 is NOT the
                # 3d transform of plane 0 — skip unless n small; roundtrip
                # already separates exec bugs from measurement bugs.
                del ref, got, xs
            except Exception as e:  # noqa: BLE001
                rec(f"plan3d_{ex}", n, -1.0, f"ERROR {type(e).__name__}")

    f.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
