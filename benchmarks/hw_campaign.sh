#!/usr/bin/env bash
# One-shot hardware evidence campaign: run when a real TPU is attached.
# Each step is independently deadline-bounded (the drivers run their
# measurements in watchdogged subprocesses), so a mid-campaign backend
# death costs only the remaining steps — rows already written survive.
#
#   bash benchmarks/hw_campaign.sh            # full (~20-30 min)
#   bash benchmarks/hw_campaign.sh --short    # flagship-only (~5 min)

set -u
cd "$(dirname "$0")/.."

SHORT=${1:-}
note() { printf '\n=== %s (%s) ===\n' "$1" "$(date +%T)"; }

note "correctness smoke FIRST (real pallas_call, shard_map vma, ragged a2av, dd tier)"
DFFT_SWEEP_TIMEOUT=1200 python benchmarks/hw_smoke.py

note "flagship bench (512^3 c2c, all executors)"
# Tee into the committed results dir: a mid-round campaign line must
# survive to the round-end commit even if nobody is watching.
DFFT_BENCH_DEADLINE=1500 python bench.py \
    | tee benchmarks/results/hw_bench_campaign.json

note "kernel tile sweep @512 (1D + strided)"
DFFT_SWEEP_TIMEOUT=1200 python benchmarks/tune_pallas.py \
    --n 512 --tiles 128 256 512 --strided --plane 512 --tiles2d 1 2 4 \
    --full3d 512

if [ "$SHORT" != "--short" ]; then
  note "baseline sweep (256^3 + 512^3, c2c + r2c, all executors)"
  DFFT_SWEEP_TIMEOUT=2400 python benchmarks/record_baseline.py \
      --sizes 256 512

  note "1024^3 donated-pair attempt (HBM-limit config)"
  DFFT_SWEEP_TIMEOUT=1500 python benchmarks/record_baseline.py \
      --sizes --big 1024 --executors xla,pallas

  note "non-cubic pencil-config shape (single-chip local)"
  DFFT_SWEEP_TIMEOUT=1200 python benchmarks/record_baseline.py \
      --shapes 768x512x384 --sizes

  note "1D batch sweeps (runTest1D_opt.sh parity: radix 2/3/5/7, long-1D to 5^11)"
  for radix in 2 3 5 7; do
    DFFT_SWEEP_TIMEOUT=900 timeout 900 python benchmarks/batch_bench.py 1d \
        -radix $radix -total 48828125 \
        -csv benchmarks/csv/batch_tpu_1d_r${radix}.csv || true
  done

  note "dd (emulated double) tier rows @256^3 and 512^3"
  for n in 256 512; do
    DFFT_SWEEP_TIMEOUT=900 timeout 900 python benchmarks/speed3d.py \
        c2c dd $n $n $n -iters 3 \
        -csv benchmarks/csv/dd_tier_tpu.csv || true
  done
  DFFT_SWEEP_TIMEOUT=900 timeout 900 python benchmarks/speed3d.py \
      c2c dd 256 256 256 -staged -iters 3 \
      -csv benchmarks/csv/dd_tier_tpu.csv || true
  DFFT_SWEEP_TIMEOUT=900 timeout 900 python benchmarks/speed3d.py \
      c2c dd 256 256 256 -bricks -iters 3 \
      -csv benchmarks/csv/dd_tier_tpu.csv || true

  note "dd depth frontier @256^3 (accuracy vs matmul count)"
  for depth in 8,6,2 7,5,2 7,5,1; do
    DFFT_DD_DEPTH=$depth timeout 900 python benchmarks/speed3d.py \
        c2c dd 256 256 256 -iters 3 \
        -csv benchmarks/csv/dd_depth_tpu.csv || true
  done

  note "matmul four-step split frontier @512 (contraction-dim rebalance toward the MXU edge, docs/MFU_ANALYSIS.md)"
  for split in 16x32 8x64 4x128 2x256; do
    DFFT_MM_SPLIT=512=$split DFFT_MM_PRECISION=high timeout 900 \
      python benchmarks/speed3d.py c2c single 512 512 512 \
      -executor matmul -iters 3 \
      -csv benchmarks/csv/mm_split_tpu.csv || true
  done

  note "precision-tier comparison @256^3 (HIGHEST vs HIGH vs DEFAULT)"
  for prec in highest high default; do
    DFFT_MM_PRECISION=$prec DFFT_SWEEP_TIMEOUT=900 \
      python benchmarks/record_baseline.py --sizes 256 \
      --executors matmul,pallas \
      --out benchmarks/csv/precision_${prec}_tpu.csv
  done
fi

note "campaign done — review benchmarks/csv/ and commit"
git status --short benchmarks/
