#!/usr/bin/env bash
# Round-5 revised hardware campaign: wedge-resistant, resumable.
#
# Lessons from the first r5 window (results/hw_campaign_r05.log):
#   * a pallas compile at large shapes can crash the remote compile helper
#     AND wedge the tunnel server — every later step then burns its full
#     timeout producing zero rows. So: correctness + flagship first,
#     pallas-heavy steps last, and a cheap liveness probe between steps
#     aborts the run early (the driver loop re-fires when the tunnel
#     returns, and completed steps are skipped via the state file).
#   * concurrent TPU clients steal HBM (75% prealloc) and poison each
#     other with UNIMPLEMENTED/RESOURCE_EXHAUSTED — never run two steps
#     at once, never probe while a step runs.
#
#   bash benchmarks/hw_campaign2.sh           # resume from state
#   rm benchmarks/results/campaign2_state     # start over

set -u
cd "$(dirname "$0")/.."

STATE=benchmarks/results/campaign2_state
touch "$STATE"

note() { printf '\n=== %s (%s) ===\n' "$1" "$(date +%T)"; }

alive() {
  # Bounded backend-init probe; the tunnel hangs (never errors) when down.
  timeout 75 python -c "import jax; jax.devices()" >/dev/null 2>&1
}

step() {
  # step <name> <timeout_s> <cmd...>: skip if done, run bounded, mark done
  # on rc==0; abort the whole campaign if the tunnel died mid-step.
  local name=$1 tmo=$2; shift 2
  if grep -qx "done:$name" "$STATE"; then
    echo "[skip] $name (already done)"; return 0
  fi
  note "$name"
  DFFT_SWEEP_TIMEOUT=$tmo DFFT_BENCH_DEADLINE=$tmo timeout "$tmo" "$@"
  local rc=$?
  if [ $rc -eq 0 ]; then
    echo "done:$name" >> "$STATE"
  else
    echo "[step $name] rc=$rc"
  fi
  if ! alive; then
    echo "[campaign2] tunnel died after step $name — aborting; rows so far kept"
    exit 9
  fi
}

# -- 1. flagship bench FIRST (512^3 tournament, safe-real mode) — the
#       round's #1 deliverable must land before anything else can eat a
#       short window. WITHOUT the pallas candidates: a 512-sized pallas
#       compile wedged the tunnel in the first r5 window and would starve
#       every later step. The full menu (pallas included) re-runs as the
#       LAST campaign step.
step bench 1500 env \
    DFFT_BENCH_EXECUTORS=xla,matmul:high,matmul:high:gauss,xla_minor,matmul \
    bash -c 'set -o pipefail
             python bench.py | tee benchmarks/results/hw_bench_campaign2.json'

# -- 2. r2c bisection: which real-transform primitive is wrong on TPU
step diag_r2c 1200 python benchmarks/diag_r2c.py

# -- 3. matmul four-step split frontier @512 (the MXU-path 512^3 candidates)
for split in 16x32 8x64 4x128 2x256; do
  step mm_split_$split 700 env DFFT_MM_SPLIT=512=$split DFFT_MM_PRECISION=high \
    python benchmarks/speed3d.py c2c single 512 512 512 \
    -executor matmul -iters 3 -csv benchmarks/csv/mm_split_tpu.csv
done

# -- 3b. Gauss 3-real-matmul complex product vs XLA's native complex
#        decomposition, on the dense 512^3 path (25% fewer MXU matmuls
#        if XLA lowers complex dots as 4 real ones).
step mm_gauss_512 700 env DFFT_MM_COMPLEX=gauss DFFT_MM_PRECISION=high \
    python benchmarks/speed3d.py c2c single 512 512 512 \
    -executor matmul -iters 3 -csv benchmarks/csv/mm_complex_gauss_tpu.csv
step mm_native_512 700 env DFFT_MM_PRECISION=high \
    python benchmarks/speed3d.py c2c single 512 512 512 \
    -executor matmul -iters 3 -csv benchmarks/csv/mm_complex_native_tpu.csv

# -- 4. precision-tier comparison @256^3 (matmul only; pallas deferred)
for prec in highest high default; do
  step precision_$prec 900 env DFFT_MM_PRECISION=$prec \
    python benchmarks/record_baseline.py --sizes 256 \
    --executors matmul --out benchmarks/csv/precision_${prec}_tpu.csv
done

# -- 5. dd (emulated double) tier: cost + accuracy on chip
step dd_256 900 python benchmarks/speed3d.py c2c dd 256 256 256 -iters 3 \
    -csv benchmarks/csv/dd_tier_tpu.csv
step dd_256_staged 900 python benchmarks/speed3d.py c2c dd 256 256 256 \
    -staged -iters 3 -csv benchmarks/csv/dd_tier_tpu.csv
for depth in 8,6,2 7,5,2 7,5,1; do
  step dd_depth_${depth//,/_} 900 env DFFT_DD_DEPTH=$depth \
    python benchmarks/speed3d.py c2c dd 256 256 256 -iters 3 \
    -csv benchmarks/csv/dd_depth_tpu.csv
done
step dd_512 1200 python benchmarks/speed3d.py c2c dd 512 512 512 -iters 3 \
    -csv benchmarks/csv/dd_tier_tpu.csv

# -- 5b2. wire-codec sweep: exact vs bf16 vs block-scaled int8 t2 wire
#         on the flagship shape, -staged so per-stage t2 rows land for
#         every wire mode (CSV algorithm column 'alltoall' vs
#         'alltoall+wbf16' vs 'alltoall+wint8' — the regress store never
#         mixes their baselines). On a single-chip slice there is no t2
#         to compress; the rows still record so the sweep is a no-op
#         there, not a failure.
step wire_exact 900 python benchmarks/speed3d.py c2c single 512 512 512 \
    -wire none -staged -iters 3 -csv benchmarks/csv/wire_sweep_tpu.csv
step wire_bf16 900 python benchmarks/speed3d.py c2c single 512 512 512 \
    -wire bf16 -staged -iters 3 -csv benchmarks/csv/wire_sweep_tpu.csv
step wire_int8 900 python benchmarks/speed3d.py c2c single 512 512 512 \
    -wire int8 -staged -iters 3 -csv benchmarks/csv/wire_sweep_tpu.csv

# -- 5b. big-grid single-chip rows: 768^3 c64 (3.6 GB in+out — the largest
#        cubic c64 grid one 16 GB chip holds; 1024^3 needs r2c or a donated
#        pair and RESOURCE_EXHAUSTED in the first window).
step c2c_768_xla 900 python benchmarks/speed3d.py c2c single 768 768 768 \
    -executor xla -iters 3 -csv benchmarks/csv/speed3d_tpu1.csv
step c2c_768_mm 900 env DFFT_MM_PRECISION=high \
    python benchmarks/speed3d.py c2c single 768 768 768 \
    -executor matmul -iters 3 -csv benchmarks/csv/speed3d_tpu1.csv

# -- 6. clean correctness smoke (ragged a2av, brick orders now 1-dev-capable,
#       dd rows, pallas kernels) — after the timing steps: it compiles pallas.
step hw_smoke 1500 python benchmarks/hw_smoke.py

# -- 7. pallas tile sweep, small tiles first (128+ OOM'd in r2 and r5;
#       512 crashed the compile helper — keep it out).
step tune_small 1200 python benchmarks/tune_pallas.py \
    --n 512 --tiles 8 16 32 64 --plane 512 --tiles2d 1 2
step tune_mid 1200 python benchmarks/tune_pallas.py \
    --n 512 --tiles 128 --strided --full3d 512
# MXU-edge splits: trade four-step flops for a 128-wide stage factor
# (the balanced 16x32 runs ~idle MXU lanes when packing is rejected).
for split in 4x128 2x256 8x64; do
  step tune_split_$split 1200 env DFFT_PALLAS_SPLIT=512=$split \
    python benchmarks/tune_pallas.py --n 512 --tiles 16 32 64
done

# -- 8. 1D batch corpus (manuscript-CSV parity); pow-5 first, each bounded.
step batch_r5 900 python benchmarks/batch_bench.py 1d -radix 5 \
    -total 48828125 -csv benchmarks/csv/batch_tpu_1d_r5.csv
step batch_r2 900 python benchmarks/batch_bench.py 1d -radix 2 \
    -total 48828125 -csv benchmarks/csv/batch_tpu_1d_r2.csv
step batch_r3 900 python benchmarks/batch_bench.py 1d -radix 3 \
    -total 48828125 -csv benchmarks/csv/batch_tpu_1d_r3.csv
step batch_r7 900 python benchmarks/batch_bench.py 1d -radix 7 \
    -total 48828125 -csv benchmarks/csv/batch_tpu_1d_r7.csv
step batch_2d 900 python benchmarks/batch_bench.py 2d \
    -csv benchmarks/csv/batch_tpu_2d.csv

# -- 9. full-menu flagship bench LAST (adds the pallas candidates; if one
#       wedges the tunnel here, every other row is already on disk).
step bench_full 1500 bash -c \
    'set -o pipefail
     python bench.py | tee benchmarks/results/hw_bench_campaign2_full.json'

note "campaign2 complete"
git status --short benchmarks/ | head -20
