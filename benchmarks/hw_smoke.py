#!/usr/bin/env python
"""Hardware-first correctness smoke — run BEFORE timing anything.

Several paths cannot execute on the CPU test backend and therefore run
for the first time ever on a real chip (the round-2 verdict's top risk
list): the real ``pallas_call`` lowering of all three kernel families,
the same kernels under ``shard_map`` (the varying-axes/pvary plumbing the
interpreter mirrors around), the ``lax.ragged_all_to_all`` lowering (XLA
CPU lacks the op; the dense mirror stands in), the packed-kernel Mosaic
probe, and the dd (emulated-f64) engine's bf16 matmul exactness.

This driver smokes each of them with an on-device numeric gate and
appends one CSV row per step to ``benchmarks/csv/hw_smoke_<backend>.csv``
the moment it finishes — a mid-campaign backend death keeps every row
already written (the record-as-you-go discipline of the batchTest CSVs,
``templateFFT/batchTest/Test_1D.cpp:186-190``). Correctness first, then
timing (``tune_pallas.py``) — the same order the reference's scheduler
validates before it benchmarks.

Usage:
  python benchmarks/hw_smoke.py            # full smoke (~2-4 min on chip)
  python benchmarks/hw_smoke.py --quick    # small shapes only
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

C64_GATE = 1e-3   # complex64 tier (bench.py ERR_GATE)
DD_GATE = 1e-11   # the double tier (test_common.h:138)


def _csv_path(backend: str) -> str:
    d = os.environ.get("DFFT_SMOKE_CSV_DIR") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "csv")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"hw_smoke_{backend}.csv")


_FAILED: list[str] = []  # steps whose gate failed (drives the exit code)


def _record(step: str, status: str, value, detail: str = "",
            backend: str | None = None) -> None:
    # backend is passed explicitly by the jax-free parent orchestrator
    # (a wedged PJRT init hangs on import, so the parent must never
    # touch jax); workers let it default to the live backend.
    if backend is None:
        import jax

        backend = jax.default_backend()
    # "rejected" is the pack probe's expected auto-fallback verdict (the
    # production path handles it gracefully) — informational, not a
    # failure; only numeric-gate FAILs and raised ERRORs gate the exit.
    if status in ("FAIL", "ERROR"):
        _FAILED.append(step)
    path = _csv_path(backend)
    fresh = not os.path.exists(path)
    with open(path, "a") as f:
        if fresh:
            f.write("step,backend,status,value,detail\n")
        f.write(f"{step},{backend},{status},{value},{detail}\n")
        f.flush()
    print(f"[hw_smoke] {step}: {status} (value={value}) {detail}", flush=True)


def _maxrel(got, want) -> float:
    """On-device max-rel error, fetched as a real scalar (complex host
    transfers are unimplemented on the axon tunnel)."""
    import jax.numpy as jnp
    import numpy as np

    e = jnp.max(jnp.abs(got - want)) / jnp.max(jnp.abs(want))
    return float(np.asarray(e))


def _rand_c64(key, shape):
    import jax
    import jax.numpy as jnp

    k1, k2 = jax.random.split(key)
    return (jax.random.normal(k1, shape, jnp.float32)
            + 1j * jax.random.normal(k2, shape, jnp.float32)
            ).astype(jnp.complex64)


def step_pallas_1d(n: int, batch: int) -> None:
    import jax
    import jax.numpy as jnp

    from distributedfft_tpu.ops import pallas_fft

    x = _rand_c64(jax.random.PRNGKey(1), (batch, n))
    got = jax.jit(lambda v: pallas_fft.fft_along_axis(v, -1))(x)
    err = _maxrel(got, jnp.fft.fft(x, axis=-1))
    _record(f"pallas_1d_n{n}", "ok" if err < C64_GATE else "FAIL", err)


def step_pallas_2d(n: int, batch: int) -> None:
    import jax
    import jax.numpy as jnp

    from distributedfft_tpu.ops import pallas_fft

    if not pallas_fft.eligible2d(n, n):
        _record(f"pallas_2d_n{n}", "skip", 0, "plane not eligible")
        return
    x = _rand_c64(jax.random.PRNGKey(2), (batch, n, n))
    got = jax.jit(lambda v: pallas_fft.fft2_last(v))(x)
    err = _maxrel(got, jnp.fft.fftn(x, axes=(1, 2)))
    _record(f"pallas_2d_n{n}", "ok" if err < C64_GATE else "FAIL", err)


def step_pallas_strided(n: int, cols: int) -> None:
    import jax
    import jax.numpy as jnp

    from distributedfft_tpu.ops import pallas_fft

    x = _rand_c64(jax.random.PRNGKey(3), (n, cols))
    got = jax.jit(lambda v: pallas_fft.fft_axis0(v))(x)
    err = _maxrel(got, jnp.fft.fft(x, axis=0))
    _record(f"pallas_strided_n{n}", "ok" if err < C64_GATE else "FAIL", err)


def step_pack_probe(n: int) -> None:
    """Does this Mosaic accept the packed kernels' lane-changing
    reshapes? Records the probe verdict for the exact config the fused
    path would use at axis length n (the ADVICE auto-fallback gate)."""
    from distributedfft_tpu.ops.dft_matmul import pack_factor
    from distributedfft_tpu.ops.pallas_fft import (
        _pack_probe_ok, batch_tile, split_for,
    )

    n1, n2 = split_for(n)
    bt = batch_tile(n)
    g1 = pack_factor(n1, bt * n2)
    g2 = pack_factor(n2, bt * n1)
    if (g1, g2) == (1, 1):
        _record(f"pack_probe_n{n}", "skip", 0, "no packing at this config")
        return
    ok = _pack_probe_ok(n1, n2, g1, g2)
    _record(f"pack_probe_n{n}", "ok" if ok else "rejected", int(ok),
            f"n1={n1} n2={n2} g1={g1} g2={g2}")


def step_pallas_shardmap(n: int) -> None:
    """The real pallas_call under shard_map — the vma/pvary path no CPU
    test can reach (the interpreter mirrors it with jnp math)."""
    import jax
    import jax.numpy as jnp

    import distributedfft_tpu as dfft
    from distributedfft_tpu.parallel.slab import build_slab_fft3d

    ndev = len(jax.devices())
    mesh = dfft.make_mesh(min(2, ndev))
    fn, _ = build_slab_fft3d(
        mesh, (n, n, n), axis_name=mesh.axis_names[0], executor="pallas",
        forward=True,
    )
    x = _rand_c64(jax.random.PRNGKey(4), (n, n, n))
    err = _maxrel(fn(x), jnp.fft.fftn(x))
    _record(f"pallas_shardmap_n{n}_ndev{mesh.devices.size}",
            "ok" if err < C64_GATE else "FAIL", err)


def step_ragged_a2av(S: int = 13) -> None:
    """The real lax.ragged_all_to_all lowering (CPU mirrors it through
    the dense path, so any real-backend mesh — even 1 device — is its
    first execution). Pass = bit-identical to the dense exchange."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map as _shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

    import distributedfft_tpu as dfft
    from distributedfft_tpu.parallel import exchange as ex

    ndev = len(jax.devices())
    mesh = dfft.make_mesh(min(2, ndev))
    ax = mesh.axis_names[0]
    p = mesh.devices.size
    c = -(-S // p)

    x = _rand_c64(jax.random.PRNGKey(5), (p * 4, S, 8))

    def ragged(v):
        return ex.ragged_all_to_all_exchange(
            v, ax, split_axis=1, concat_axis=0, p=p)

    def dense(v):
        vp = ex._pad_axis(v, 1, p * c)
        from jax import lax
        return lax.all_to_all(vp, ax, split_axis=1, concat_axis=0,
                              tiled=True)

    sm = lambda f: _shard_map(
        f, mesh=mesh, in_specs=P(ax), out_specs=P(ax))
    got = jax.jit(sm(ragged))(x)
    want = jax.jit(sm(dense))(x)
    diff = float(np.asarray(jnp.max(jnp.abs(got - want))))
    _record(f"ragged_all_to_all_S{S}_p{p}", "ok" if diff == 0.0 else "FAIL",
            diff, f"first real execution of lax.ragged_all_to_all (p={p})")


def step_dd_fwd(n: int = 64) -> None:
    """dd (emulated-f64) forward vs host numpy float64 fftn — the double
    tier measured on the real chip's bf16 MXU."""
    import numpy as np

    from distributedfft_tpu.ops import ddfft

    import jax

    rng = np.random.default_rng(4242)
    shape = (n, n, n)
    x = rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
    hi, lo = ddfft.dd_from_host(x)
    # Under jit XLA schedules the partial-product chain in place; eager
    # execution would materialize every intermediate on device.
    yh, yl = jax.jit(ddfft.fftn_dd)(hi, lo)
    want = np.fft.fftn(x)
    # Fetch re/im separately (complex transfers unimplemented on tunnel).
    import jax.numpy as jnp

    got = (np.asarray(jnp.real(yh), np.float64)
           + np.asarray(jnp.real(yl), np.float64)
           + 1j * (np.asarray(jnp.imag(yh), np.float64)
                   + np.asarray(jnp.imag(yl), np.float64)))
    err = float(np.max(np.abs(got - want)) / np.max(np.abs(want)))
    _record(f"dd_fwd_{n}", "ok" if err < DD_GATE else "FAIL", err,
            "vs numpy f64 fftn")


def step_dd_roundtrip(n: int = 256) -> None:
    """On-device dd roundtrip at the flagship accuracy config (256^3,
    BASELINE.json double-tier target) — no host transfer of the world."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributedfft_tpu.ops import ddfft

    k1, k2 = jax.random.split(jax.random.PRNGKey(7))
    shape = (n, n, n)
    hi = _rand_c64(k1, shape)
    # A representative lo: ~2^-25 below hi (the dd invariant scale).
    lo = (_rand_c64(k2, shape) * jnp.float32(2.0 ** -25))

    t0 = time.perf_counter()
    fwd = jax.jit(lambda a, b: ddfft.fftn_dd(a, b))
    bwd = jax.jit(lambda a, b: ddfft.fftn_dd(a, b, forward=False))
    yh, yl = fwd(hi, lo)
    bh, bl = bwd(yh, yl)
    # dd difference vs input, evaluated on device.
    dh = bh - hi
    dl = bl - lo
    err = jnp.max(jnp.abs(dh + dl)) / jnp.max(jnp.abs(hi))
    err = float(np.asarray(jnp.real(err)))
    dt = time.perf_counter() - t0  # includes compile; separate row times it
    _record(f"dd_roundtrip_{n}", "ok" if err < DD_GATE else "FAIL", err,
            f"first-call {dt:.1f}s")
    # Amortized timing row for the dd forward (the accuracy-tier speed).
    from distributedfft_tpu.utils.timing import gflops, time_fn_amortized

    sec, _ = time_fn_amortized(fwd, hi, lo, iters=5, repeats=2)
    _record(f"dd_fwd_time_{n}", "ok", round(sec, 6),
            f"gflops={gflops(shape, sec):.1f}")


def step_dd_bluestein(n: int = 521) -> None:
    """The dd tier's chirp-z path on the chip: a prime axis through two
    dd four-step FFTs plus dd chirp multiplies — a different composition
    of the same exactness assumptions the dense rows validate."""
    import jax
    import numpy as np

    from distributedfft_tpu.ops import ddfft

    rng = np.random.default_rng(101)
    x = rng.standard_normal((2, n)) + 1j * rng.standard_normal((2, n))
    hi, lo = ddfft.dd_from_host(x)
    yh, yl = jax.jit(lambda a, b: ddfft.fft_axis_dd(a, b, axis=-1))(hi, lo)
    err = ddfft.max_err_vs_f64(yh, yl, np.fft.fft(x, axis=-1))
    _record(f"dd_bluestein_{n}", "ok" if err < DD_GATE else "FAIL", err,
            "prime axis via chirp-z")


def step_matmul_high(n: int = 256) -> None:
    """The matmul:high flagship candidate (MXU four-step at the 3-pass
    bf16 tier): roundtrip gate + amortized forward rate — the row that
    decides whether the HIGH tier carries the 512^3 tournament
    (bench.py's menu; plain matmul already beat xla at 1D n=512 on the
    round-2 hardware rows)."""
    import jax
    import jax.numpy as jnp

    import distributedfft_tpu as dfft
    from distributedfft_tpu.utils.timing import gflops, time_fn_amortized

    saved = os.environ.get("DFFT_MM_PRECISION")
    os.environ["DFFT_MM_PRECISION"] = "high"
    try:
        shape = (n, n, n)
        fwd = dfft.plan_dft_c2c_3d(shape, None, executor="matmul",
                                   dtype=jnp.complex64)
        bwd = dfft.plan_dft_c2c_3d(shape, None, executor="matmul",
                                   dtype=jnp.complex64,
                                   direction=dfft.BACKWARD)
        x = _rand_c64(jax.random.PRNGKey(11), shape)
        back = bwd(fwd(x))
        err = _maxrel(back, x)
        _record(f"matmul_high_roundtrip_{n}",
                "ok" if err < C64_GATE else "FAIL", err)
        sec, _ = time_fn_amortized(fwd.fn, x, iters=5, repeats=2)
        _record(f"matmul_high_fwd_time_{n}", "ok", round(sec, 6),
                f"gflops={gflops(shape, sec):.1f}")
    finally:
        if saved is None:
            os.environ.pop("DFFT_MM_PRECISION", None)
        else:
            os.environ["DFFT_MM_PRECISION"] = saved


def step_dd_slab(shape=(32, 24, 16)) -> None:
    """Distributed dd tier under shard_map on the real backend: the
    barrier-guarded compensated arithmetic and the exchange collectives
    through one compiled program."""
    import jax
    import numpy as np

    import distributedfft_tpu as dfft
    from distributedfft_tpu.ops import ddfft
    from distributedfft_tpu.parallel.ddslab import build_dd_slab_fft3d

    ndev = len(jax.devices())
    mesh = dfft.make_mesh(min(2, ndev))
    rng = np.random.default_rng(31)
    x = rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
    hi, lo = ddfft.dd_from_host(x)
    fwd, _ = build_dd_slab_fft3d(mesh, shape, forward=True)
    yh, yl = fwd(hi, lo)
    err = ddfft.max_err_vs_f64(yh, yl, np.fft.fftn(x))
    _record(f"dd_slab_{'x'.join(map(str, shape))}_ndev{mesh.devices.size}",
            "ok" if err < DD_GATE else "FAIL", err)


def step_brick_orders(shape=(16, 12, 8)) -> None:
    """Per-box storage-order edge (lax.switch over per-device transposes
    inside shard_map) on the real backend: shuffled-order brick plan vs
    the host reference."""
    import jax
    import numpy as np

    import distributedfft_tpu as dfft
    from distributedfft_tpu.geometry import make_slabs, world_box
    from distributedfft_tpu.parallel.bricks import (
        gather_bricks, scatter_bricks,
    )

    ndev = len(jax.devices())
    p = min(2, ndev)
    mesh = dfft.make_mesh(p)
    w = world_box(shape)
    orders = [(2, 1, 0), (1, 2, 0), (0, 2, 1), (2, 0, 1)]
    ins = [b.with_order(orders[i % len(orders)])
           for i, b in enumerate(make_slabs(w, p, axis=0))]
    outs = [b.with_order(orders[(i + 1) % len(orders)])
            for i, b in enumerate(make_slabs(w, p, axis=1))]
    rng = np.random.default_rng(17)
    x = (rng.standard_normal(shape)
         + 1j * rng.standard_normal(shape)).astype(np.complex64)
    plan = dfft.plan_brick_dft_c2c_3d(shape, mesh, ins, outs,
                                      dtype=np.complex64)
    got = gather_bricks(plan(scatter_bricks(x, ins, mesh=mesh)), outs)
    ref = np.fft.fftn(x)
    err = float(np.max(np.abs(got - ref)) / np.max(np.abs(ref)))
    _record(f"brick_orders_p{p}", "ok" if err < C64_GATE else "FAIL", err,
            "box3d::order edge (switch+transpose under shard_map)")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--timeout", type=float, default=float(
        os.environ.get("DFFT_SWEEP_TIMEOUT", 1200)))
    ap.add_argument("--step", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    n = 128 if args.quick else 512
    batch = 256 if args.quick else 4096
    steps = [
        # a2av FIRST: lax.ragged_all_to_all is the one code path with
        # zero executions anywhere off-chip (XLA:CPU lacks the op, the
        # test suite mirrors it densely) — its first real execution must
        # happen before anything else can wedge the backend.
        (step_ragged_a2av, ()),
        (step_brick_orders, ()),
        (step_pallas_1d, (n, batch)),
        (step_pallas_2d, (n, 4 if not args.quick else 2)),
        (step_pallas_strided, (n, batch)),
        (step_pack_probe, (n,)),
        (step_pallas_shardmap, (64,)),
        (step_matmul_high, (128 if args.quick else 256,)),
        (step_dd_fwd, (32 if args.quick else 64,)),
        (step_dd_bluestein, (521,)),
        (step_dd_slab, ()),
        (step_dd_roundtrip, (64 if args.quick else 256,)),
    ]
    if args.step is not None:
        steps = [s for s in steps if s[0].__name__ == args.step]
        if not steps:
            print(f"[hw_smoke] unknown step {args.step!r}",
                  file=sys.stderr)
            return 2

    if not args.worker:
        # One subprocess PER STEP. The first r5 window proved why: the
        # remote-compile-helper crash on step 1 poisoned the shared
        # backend and turned the other eleven in-process steps into
        # UNIMPLEMENTED noise (csv/hw_smoke_tpu.csv, 01:01 rows). A
        # fresh PJRT client per step converts that into one bad row.
        # The parent never imports jax (a wedged init hangs rather than
        # raising); each child is bounded, and a child that wedges gets
        # a TIMEOUT row written by the parent under the last backend
        # name a child reported.
        import re
        import signal
        import subprocess

        deadline = time.time() + args.timeout
        # A single explicit --step gets the whole budget; a full sweep
        # splits it evenly with a 300 s floor per step (first-ever
        # pallas compiles through the tunnel have taken 20+ min — the
        # operator raises --timeout / DFFT_SWEEP_TIMEOUT for those).
        step_cap = max(300.0, args.timeout / max(1, len(steps)))
        passthru, skip = [], False
        for a in sys.argv[1:]:
            if skip or a == "--worker":
                skip = False
                continue
            if a == "--step":  # parent pins its own per-child --step
                skip = True
                continue
            passthru.append(a)
        # Jax-free backend guess for rows written before any child has
        # reported (a child killed mid-init never prints backend=):
        # default "unknown" (its own CSV) — guessing "tpu" on a CPU box
        # whose children all wedge would stamp TIMEOUT rows into the
        # committed TPU-evidence csv/hw_smoke_tpu.csv. The first child
        # that prints backend= upgrades the guess to the real backend.
        backend = ("cpu" if os.environ.get("JAX_PLATFORMS", "").strip()
                   == "cpu" else "unknown")
        worst = 0
        for fn, _ in steps:
            remaining = deadline - time.time()
            if remaining < 30:
                print(f"[hw_smoke] {fn.__name__}: deadline exhausted, "
                      "not started (rows so far kept)", file=sys.stderr)
                worst = max(worst, 2)
                continue
            per = min(step_cap, remaining - 5)
            # Own process group so a timeout kills the whole tree: a
            # surviving orphaned PJRT client would hold the chip's HBM
            # prealloc and poison every later step — the cascade the
            # per-step isolation exists to prevent.
            proc = subprocess.Popen(
                [sys.executable, "-u", os.path.abspath(__file__),
                 "--worker", "--step", fn.__name__, *passthru],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, start_new_session=True,
            )
            timed_out = False
            try:
                out, err = proc.communicate(timeout=per)
            except subprocess.TimeoutExpired:
                timed_out = True
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                proc.kill()  # direct child, in case killpg was denied
                try:
                    # Bounded: a grandchild that escaped the group and
                    # holds the pipes must not wedge the parent whose
                    # job is converting wedges into TIMEOUT rows.
                    out, err = proc.communicate(timeout=30)
                except subprocess.TimeoutExpired:
                    out, err = "", ""
            sys.stdout.write(out)
            sys.stderr.write((err or "")[-2000:])
            sys.stdout.flush()
            m = re.search(r"backend=(\w+)", out)
            if m:
                backend = m.group(1)
            if timed_out:
                _record(fn.__name__, "TIMEOUT", 0,
                        f"worker exceeded {int(per)}s (wedged backend?)",
                        backend=backend)
                worst = max(worst, 2)
            else:
                worst = max(worst, 1 if proc.returncode else 0)
        return worst

    from distributedfft_tpu.utils.cache import enable_compile_cache

    enable_compile_cache()
    import jax

    print(f"[hw_smoke] backend={jax.default_backend()} "
          f"devices={len(jax.devices())}", flush=True)

    for fn, fargs in steps:
        try:
            fn(*fargs)
        except Exception as e:  # noqa: BLE001 — record and continue
            _record(fn.__name__, "ERROR", 0,
                    f"{type(e).__name__}: {str(e)[:120]}".replace(",", ";"))
    if _FAILED:
        print(f"[hw_smoke] FAILED steps: {', '.join(_FAILED)}",
              file=sys.stderr)
    return 1 if _FAILED else 0


if __name__ == "__main__":
    sys.exit(main())
