#!/usr/bin/env python
"""Record the BASELINE.json config sweep to ``benchmarks/csv/``.

The committed-CSV parity artifact: the reference ships its manuscript
benchmark data as CSVs (``templateFFT/csv/batch_result{1D,2D}.csv``,
``README.md:32``); this driver produces the same kind of recorded evidence
for the TPU framework — size/time/GFlops/error rows per (shape, dtype,
executor, decomposition) config, written via
:class:`distributedfft_tpu.utils.trace.CsvRecorder`.

Run on whatever backend is available; every row records the backend and
device count so a CPU smoke row can never masquerade as a TPU result.
Configs that fail (OOM, unsupported dtype, sick transport) record an
``error`` row rather than aborting the sweep — one bad config must not
cost the evidence for the rest.

Usage:
  python benchmarks/record_baseline.py              # full sweep
  python benchmarks/record_baseline.py --quick      # tiny shapes (CI smoke)
  python benchmarks/record_baseline.py --sizes 256 512
"""

from __future__ import annotations

import argparse
import math
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def reexec_with_watchdog(argv: list[str], timeout: float) -> int:
    """Run this script's worker mode in a subprocess with a hard deadline.

    A wedged PJRT backend init (the sick-axon-tunnel failure mode bench.py
    was hardened against in round 1) hangs without raising, so in-process
    try/except can never record the failure; only a subprocess with a
    timeout can. CSV rows are appended incrementally by the worker, so
    everything measured before a hang survives.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-u", os.path.abspath(__file__), "--worker",
             *argv],
            timeout=timeout,
        )
        return proc.returncode
    except subprocess.TimeoutExpired:
        print(f"sweep worker exceeded {int(timeout)}s (wedged backend?); "
              f"killed — rows recorded so far are kept", file=sys.stderr)
        return 2


def run_config(shape, dtype_name, executor, mesh, *, real=False):
    """Plan, verify, and time one config. Returns a result dict; raises on
    failure (caller records the error row)."""
    import functools

    import jax
    import jax.numpy as jnp

    import distributedfft_tpu as dfft
    from distributedfft_tpu.utils.timing import (
        gflops, max_rel_err, sync, time_fn_amortized,
    )

    dtype = jnp.dtype(dtype_name)
    if real:
        # r2c/c2r plans take the complex working dtype; the real side is
        # derived from it.
        cdt = jnp.dtype("complex128" if dtype == jnp.float64 else "complex64")
        plan = dfft.plan_dft_r2c_3d(shape, mesh, dtype=cdt,
                                    executor=executor)
        iplan = dfft.plan_dft_c2r_3d(shape, mesh, dtype=cdt,
                                     executor=executor)
    else:
        plan = dfft.plan_dft_c2c_3d(shape, mesh, dtype=dtype,
                                    executor=executor)
        iplan = dfft.plan_dft_c2c_3d(shape, mesh, direction=dfft.BACKWARD,
                                     dtype=dtype, executor=executor)

    def _make_input_fn(**jit_kw):
        @functools.partial(jax.jit, **jit_kw)
        def make_input():
            k1, k2 = jax.random.split(jax.random.PRNGKey(4242))
            if real:
                return jax.random.normal(k1, shape, plan.in_dtype)
            re = jax.random.normal(k1, shape, jnp.float32)
            im = jax.random.normal(k2, shape, jnp.float32)
            return (re + 1j * im).astype(dtype)

        return make_input

    try:
        # Pin the plan's input sharding when it can be pinned (jit output
        # shardings need evenly-dividing extents; uneven plans pad/crop
        # internally and take unpinned input).
        x = _make_input_fn(out_shardings=plan.in_sharding)() \
            if plan.in_sharding is not None else _make_input_fn()()
    except ValueError:
        x = _make_input_fn()()
    sync(x)
    err = max_rel_err(iplan(plan(x)), x)
    seconds, _ = time_fn_amortized(lambda: plan(x), iters=10, repeats=3)
    return {
        "seconds": seconds,
        "gflops": gflops(shape, seconds, real=real),
        "max_err": err,
        "decomposition": plan.decomposition,
    }


def run_config_big(shape, dtype_name, executor, mesh, iters=5):
    """HBM-limit config: donated forward/backward pair timing.

    At 1024^3 complex64 a non-donated plan needs input+output resident
    (16 GiB) — over a single chip's HBM. Donated plans ping-pong one
    buffer (the reference's bufferDev discipline), but a donated buffer
    cannot be re-executed, so timing chains fwd->bwd pairs and reports
    the per-transform average. The roundtrip error check regenerates the
    deterministic input instead of keeping a copy."""
    import time as _time

    import jax
    import jax.numpy as jnp

    import distributedfft_tpu as dfft
    from distributedfft_tpu.utils.timing import gflops, max_rel_err, sync

    dtype = jnp.dtype(dtype_name)
    plan = dfft.plan_dft_c2c_3d(shape, mesh, dtype=dtype, executor=executor,
                                donate=True)
    iplan = dfft.plan_dft_c2c_3d(shape, mesh, direction=dfft.BACKWARD,
                                 dtype=dtype, executor=executor, donate=True)

    def _expr():
        k1, k2 = jax.random.split(jax.random.PRNGKey(4242))
        re = jax.random.normal(k1, shape, jnp.float32)
        im = jax.random.normal(k2, shape, jnp.float32)
        return (re + 1j * im).astype(dtype)

    def _make_input_fn(**jit_kw):
        return jax.jit(_expr, **jit_kw)

    try:
        # Same pinned-then-unpinned discipline as run_config: jit output
        # shardings need evenly-dividing extents.
        x = _make_input_fn(out_shardings=plan.in_sharding)() \
            if plan.in_sharding is not None else _make_input_fn()()
    except ValueError:
        x = _make_input_fn()()
    sync(x)
    x = iplan(plan(x))  # warm + compile both directions
    # Probe-plane roundtrip check: regenerating the FULL input for
    # comparison would hold two world-size arrays resident — exactly the
    # HBM over-subscription donation exists to avoid. Slicing the
    # regeneration expression lets XLA push the slice through the
    # elementwise PRNG, so only one plane materializes; the full-array
    # error tier is validated by the regular sweep sizes.
    probe = jax.jit(lambda: _expr()[0])
    err = max_rel_err(x[0], probe())
    sync(x)
    t0 = _time.perf_counter()
    for _ in range(iters):
        x = iplan(plan(x))
    sync(x)
    seconds = (_time.perf_counter() - t0) / (2 * iters)
    return {
        "seconds": seconds,
        "gflops": gflops(shape, seconds),
        "max_err": err,
        "decomposition": plan.decomposition,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", type=int, nargs="*", default=None)
    ap.add_argument("--shapes", nargs="*", default=None,
                    help="extra non-cubic shapes, e.g. 1536x1024x768 "
                         "(the BASELINE.json pencil config)")
    ap.add_argument("--quick", action="store_true",
                    help="tiny shapes for CI smoke")
    ap.add_argument("--out", default=None, help="CSV path override")
    ap.add_argument("--executors", default="xla,xla_minor,pallas,matmul")
    ap.add_argument("--big", type=int, nargs="*", default=None,
                    help="HBM-limit cubic sizes timed as donated fwd/bwd "
                         "pairs (e.g. --big 1024)")
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)  # internal: run in-process
    ap.add_argument("--timeout", type=float, default=float(
        os.environ.get("DFFT_SWEEP_TIMEOUT", 2400)))
    args = ap.parse_args()

    if not args.worker:
        argv = [a for a in sys.argv[1:] if a != "--worker"]
        return reexec_with_watchdog(argv, args.timeout)

    import jax

    from distributedfft_tpu import regress
    from distributedfft_tpu.utils.cache import enable_compile_cache
    from distributedfft_tpu.utils.trace import CsvRecorder

    enable_compile_cache()

    backend = jax.default_backend()
    n_dev = len(jax.devices())
    device_kind = jax.devices()[0].device_kind
    history = regress.default_history_path()
    commit = regress.git_commit() if history else None
    here = os.path.dirname(os.path.abspath(__file__))
    out = args.out or os.path.join(
        here, "csv", f"speed3d_{backend}{n_dev}.csv")
    # One stamp per sweep: re-runs append, so every row names the run it
    # came from (stale rows from older code stay distinguishable).
    run = time.strftime("%Y-%m-%dT%H:%M:%S")
    rec = CsvRecorder(out, (
        "run", "nx", "ny", "nz", "kind", "dtype", "decomposition",
        "executor", "backend", "devices", "seconds", "gflops", "max_err",
        "status",
    ))

    # `--sizes` with no values means "no cubic sweeps" (e.g. --shapes only);
    # omitted entirely means the default sweep.
    if args.sizes is not None:
        sizes = args.sizes
    else:
        sizes = [32] if args.quick else [256, 512]
    executors = [e for e in args.executors.split(",") if e]

    import jax.numpy as jnp

    mesh = None
    if n_dev > 1:
        import distributedfft_tpu as dfft

        mesh = dfft.make_mesh(n_dev)
    # TPU has no complex128; double-precision rows only run where supported.
    cdtypes = ["complex64"]
    rdtypes = ["float32"]
    if jax.config.jax_enable_x64 and backend == "cpu":
        cdtypes.append("complex128")
        rdtypes.append("float64")

    shapes = [(n, n, n) for n in sizes]
    for s in args.shapes or []:
        try:
            dims = tuple(int(v) for v in s.lower().split("x"))
        except ValueError:
            ap.error(f"--shapes value {s!r} is not NXxNYxNZ")
        if len(dims) != 3:
            ap.error(f"--shapes value {s!r} needs exactly 3 extents")
        shapes.append(dims)

    def record_ok(shape, kind, dt, ex, r):
        rec.record(run, *shape, kind, dt, r["decomposition"], ex, backend,
                   n_dev, f"{r['seconds']:.6f}", f"{r['gflops']:.1f}",
                   f"{r['max_err']:.3e}", "ok")
        print(f"{shape} {kind} {dt} {ex}: "
              f"{r['gflops']:.1f} GFlops err={r['max_err']:.2e}", flush=True)
        if not history:
            return
        # Append incrementally (a later wedged config keeps the rows so
        # far) — one run record per ok row, grouped for regression
        # tracking by (metric, dtype/devices/executor, device_kind).
        try:
            regress.append_records([regress.make_run_record(
                metric=f"speed3d_{kind}_{'x'.join(str(v) for v in shape)}"
                       "_gflops",
                value=r["gflops"], seconds=r["seconds"],
                config={"dtype": dt, "devices": n_dev, "executor": ex,
                        "decomposition": r["decomposition"]},
                backend=backend, device_kind=device_kind,
                source="record_baseline.py", commit=commit,
                recorded_at=run,
            )], history)
        except Exception as e:  # noqa: BLE001 — history is telemetry
            print(f"history append failed: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)

    def record_error(shape, kind, dt, ex, e):
        msg = f"{type(e).__name__}: {e}".replace(",", ";")
        msg = " ".join(msg.split())[:160]
        rec.record(run, *shape, kind, dt, "-", ex, backend, n_dev,
                   "-", "-", "-", f"error {msg}")
        print(f"{shape} {kind} {dt} {ex}: FAILED {msg}",
              file=sys.stderr, flush=True)

    failures = 0
    for shape in shapes:
        jobs = [(dt, ex, False) for dt in cdtypes for ex in executors]
        jobs += [(dt, ex, True) for dt in rdtypes for ex in executors]
        for dt, ex, real in jobs:
            kind = "r2c" if real else "c2c"
            try:
                record_ok(shape, kind, dt, ex,
                          run_config(shape, dt, ex, mesh, real=real))
            except Exception as e:  # noqa: BLE001 — record and continue
                failures += 1
                record_error(shape, kind, dt, ex, e)
    for n in args.big or []:
        shape = (n, n, n)
        for ex in executors:
            try:
                record_ok(shape, "c2c-pair", "complex64", ex,
                          run_config_big(shape, "complex64", ex, mesh))
            except Exception as e:  # noqa: BLE001 — record and continue
                failures += 1
                record_error(shape, "c2c-pair", "complex64", ex, e)
    print(f"wrote {out}", flush=True)
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
