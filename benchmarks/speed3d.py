#!/usr/bin/env python
"""Distributed 3D FFT speed benchmark — the driver-tier parity tool.

Merges the two reference drivers into one CLI:

- the first-party ``fftSpeed3d_c2c`` main (``3dmpifft_opt/fftSpeed3d_c2c.cpp``:
  positional NX NY NZ + device count, plan/execute/verify/time, t0..t3 stage
  breakdown, GFlops = 5 N log2 N / t, report block ``README.md:44-58``), and
- heFFTe's ``speed3d`` benchmark CLI (``benchmarks/speed3d.h:240-253``:
  ``speed3d_c2c <backend> <precision> <nx> <ny> <nz> -a2a/-p2p_pl/-slabs/
  -pencils/-ingrid ...``).

Examples::

    python benchmarks/speed3d.py c2c single 512 512 512
    python benchmarks/speed3d.py c2c double 256 256 256 -ndev 8 -slabs -staged
    python benchmarks/speed3d.py r2c single 512 512 512 -pencils -grid 2 4
    python benchmarks/speed3d.py c2c single 512 512 512 -p2p_pl -csv out.csv
"""

from __future__ import annotations

import argparse
import functools
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def parse_args(argv):
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    p.add_argument("kind", choices=["c2c", "r2c"])
    p.add_argument("precision", choices=["double", "single", "dd"])
    p.add_argument("nx", type=int)
    p.add_argument("ny", type=int)
    p.add_argument("nz", type=int)
    g = p.add_mutually_exclusive_group()
    g.add_argument("-slabs", action="store_true", help="force slab decomposition")
    g.add_argument("-pencils", action="store_true", help="force pencil decomposition")
    g.add_argument("-bricks", action="store_true",
                   help="arbitrary-brick I/O plan: uneven Z-slabs in, "
                        "X-pencils out, over the overlap-map ring engine "
                        "(c2c only)")
    a = p.add_mutually_exclusive_group()
    a.add_argument("-a2a", action="store_true", help="fused all_to_all exchange (default)")
    a.add_argument("-p2p_pl", action="store_true",
                   help="pipelined ppermute ring exchange (p2p_plined analog)")
    a.add_argument("-a2av", action="store_true",
                   help="masked ragged all-to-all shipping true slices "
                        "(MPI_Alltoallv analog; TPU backend only, the CPU "
                        "test backend mirrors the dense path)")
    p.add_argument("-executor", default="xla", help="local FFT backend (xla|matmul|...)")
    p.add_argument("-mm", default=None, choices=("bf16", "f32", "highest"),
                   metavar="TIER",
                   help="plan-scoped matmul precision tier: composes "
                        "onto -executor as a tiered label "
                        "('matmul:bf16' — one bf16 MXU pass; 'f32' = "
                        "3-pass; 'highest' = f32-exact, the bare "
                        "default), baked into this plan's own trace "
                        "instead of the process-global DFFT_MM_PRECISION "
                        "env. Stamped into the CSV algorithm column "
                        "'<alg>+mmbf16' (mirroring '+wbf16') so "
                        "reduced-precision sweep rows never mix with "
                        "exact baselines. Matmul-family executors only")
    p.add_argument("-fuse", action="store_true",
                   help="request the Pallas fusion tier: composes onto "
                        "-executor as the fused label ('pallas' -> "
                        "'pallas:fuse'), collapsing adjacent stage "
                        "pairs (stage FFT + wire encode, decode + "
                        "stage FFT) into ONE shape-specialized Pallas "
                        "mega-kernel each — the inter-stage HBM "
                        "round-trip elided. Needs -wire (the fusion "
                        "pass gates on a wire codec) and K=1; "
                        "ineligible sites fall back counted, never "
                        "fail. Stamped into the CSV algorithm column "
                        "'<alg>+pfuse' so fused sweep rows never mix "
                        "with unfused baselines. Pallas-family "
                        "executors only")
    p.add_argument("-concurrent", type=int, default=None, metavar="N",
                   help="co-scheduled transform count: N independent "
                        "transforms merged into ONE interleaved device "
                        "program (stagegraph.schedule_concurrent — "
                        "transform A's t2 collectives issue while "
                        "transform B's t0/t3 FFTs run; the DaggerFFT "
                        "stage-DAG scheduling play). Bit-identical to "
                        "sequential execution; GFlops and the printed "
                        "transforms/s count all N. Rows label the CSV "
                        "algorithm column '<alg>+ccN' (mirroring "
                        "'+bB'), so concurrent sweeps never share a "
                        "regress baseline with sequential rows. "
                        "Stage-graph (slab/pencil) chain plans only")
    p.add_argument("-op", default=None,
                   choices=("poisson", "grad", "gauss", "biharm",
                            "helmholtz"),
                   help="run the fused spectral OPERATOR instead of a "
                        "bare transform: one FFT -> pointwise -> iFFT "
                        "program whose multiplier applies in the "
                        "transposed midpoint layout, skipping the "
                        "cancelling transpose pair (half the all-to-alls "
                        "of a natural-layout unfused composition). "
                        "Prints solves/s; CSV algorithm column gains "
                        "'+op<name>' (mirroring '+ovK'/'+wbf16') so "
                        "operator sweeps never share a regress baseline "
                        "with bare transforms. c2c only; verified "
                        "against the unfused composition unless "
                        "-no-verify")
    p.add_argument("-batch", type=int, default=None, metavar="B",
                   help="coalesced multi-request batch: one batch=B plan "
                        "computes B independent transforms per execution "
                        "(one shared exchange per t2 stage — the serving "
                        "tier's throughput play). GFlops and the printed "
                        "transforms/s count all B. Batched rows label "
                        "the CSV algorithm column '<alg>+bB' (mirroring "
                        "-overlap's '+ovK'), so batched and unbatched "
                        "sweeps never share a regress compare baseline")
    p.add_argument("-overlap", default=None, metavar="K",
                   help="pipelined t2/t3 exchange/compute overlap: chunk "
                        "count K or 'auto' (block-bytes heuristic); "
                        "default reads DFFT_OVERLAP, unset = 1 "
                        "(monolithic). Overlapped rows label the CSV "
                        "algorithm column '<alg>+ovK' so sweeps never "
                        "mix with monolithic baselines")
    p.add_argument("-tune", default=None, choices=("off", "wisdom", "measure"),
                   help="measured plan selection (distributedfft_tpu/"
                        "tuner.py): 'measure' runs the pruned multi-axis "
                        "tournament (decomposition x transport x executor "
                        "x overlap K) on a wisdom miss and records the "
                        "winner; 'wisdom' only consults the persistent "
                        "store (DFFT_WISDOM). The winner tuple is printed "
                        "and stamped into the CSV row ('+tuned' algorithm "
                        "suffix), so tuned sweeps never mix with untuned "
                        "baselines")
    p.add_argument("-wire", default=None,
                   choices=("bf16", "int8", "split", "none"),
                   metavar="DTYPE",
                   help="on-wire exchange compression codec: 'bf16' "
                        "casts the t2 payload to (real, imag) bfloat16 "
                        "pairs around each collective (half the wire "
                        "bytes for c64), 'int8' block-scales the "
                        "component planes to int8 with an f32 scale "
                        "sidecar (~quarter the c64 wire bytes), "
                        "'split' ships int16 mantissas with a shared "
                        "power-of-two exponent sidecar (half the wire "
                        "bytes at ~100x better accuracy than bf16), "
                        "'none' pins the exact wire (overriding "
                        "DFFT_WIRE_DTYPE). Stamped into the CSV "
                        "algorithm column '<alg>+wbf16'/'+wint8'/"
                        "'+wsplit' so compressed sweep rows never mix "
                        "with exact baselines")
    p.add_argument("-r2c_axis", type=int, default=2, choices=(0, 1, 2),
                   help="halved axis for r2c/c2r (heFFTe r2c_direction)")
    p.add_argument("-ndev", type=int, default=None, help="device count (default: all)")
    p.add_argument("-grid", type=int, nargs=2, metavar=("R", "C"),
                   help="explicit 2D pencil mesh")
    p.add_argument("-ingrid", type=int, nargs=3, metavar=("PX", "PY", "PZ"),
                   help="input processor grid (heFFTe -ingrid): per-axis "
                        "device factors, at most two > 1")
    p.add_argument("-outgrid", type=int, nargs=3, metavar=("PX", "PY", "PZ"),
                   help="output processor grid (heFFTe -outgrid)")
    p.add_argument("-staged", action="store_true",
                   help="separately-jitted t0..t3 stage timing (slab and "
                        "pencil, c2c and r2c; dd tier: c2c single/slab/"
                        "pencil; not with -bricks/-ingrid/-outgrid/"
                        "-r2c_axis)")
    p.add_argument("-iters", type=int, default=5)
    p.add_argument("-cpu", action="store_true",
                   help="run on (virtual) CPU devices instead of TPU")
    p.add_argument("-csv", default=None, help="append a result row to this CSV")
    p.add_argument("-trace", action="store_true", help="write a dfft trace log")
    p.add_argument("-metrics", action="store_true",
                   help="print the structured metrics snapshot (plan "
                        "builds/cache, compile seconds, executes, exchange "
                        "bytes) as one 'telemetry ...' JSON line")
    p.add_argument("-explain", action="store_true",
                   help="print the plan explain/attribution table "
                        "(predicted vs compiled vs measured per t0..t3 "
                        "stage, MFU/ICI ratios, divergence flags; "
                        "docs/OBSERVABILITY.md) plus one 'explain {...}' "
                        "JSON line; implies -metrics. CSV rows gain a "
                        "t2_model_measured_ratio column (only when "
                        "-explain ran, so default sweeps keep their "
                        "header)")
    p.add_argument("-profile", default=None, metavar="DIR",
                   help="capture an XLA profiler trace of the timed section "
                        "into DIR (view with tensorboard/xprof)")
    p.add_argument("-no-verify", action="store_true",
                   help="skip the roundtrip error check")
    return p.parse_args(argv)


def mesh_prod(mesh, entry) -> int:
    """Product of mesh-axis sizes named by one PartitionSpec entry."""
    names = entry if isinstance(entry, tuple) else (entry,)
    p = 1
    for nm in names:
        p *= mesh.shape[nm]
    return p


def main(argv=None) -> None:
    args = parse_args(argv if argv is not None else sys.argv[1:])

    # -ingrid/-outgrid describe plan LAYOUTS; they are incompatible with
    # the decomposition-forcing flags (which would silently discard them).
    if (args.ingrid or args.outgrid) and (args.bricks or args.grid
                                          or args.slabs or args.pencils):
        raise SystemExit("-ingrid/-outgrid cannot combine with "
                         "-bricks/-grid/-slabs/-pencils")

    def reconcile_ndev(label, want):
        """One device-count reconciliation rule for every grid-ish flag."""
        if args.ndev is not None and args.ndev != want:
            raise SystemExit(
                f"{label} implies {want} devices, contradicting the "
                f"earlier count {args.ndev}")
        args.ndev = want

    for label, g in (("-ingrid", args.ingrid), ("-outgrid", args.outgrid)):
        if g:
            if any(v < 1 for v in g):
                raise SystemExit(f"{label} {g}: grid entries must be >= 1")
            if sum(1 for v in g if v > 1) > 2:
                raise SystemExit(f"{label} {g}: at most two axes may have "
                                 f">1 factors (mesh-expressible layouts)")
            reconcile_ndev(label, math.prod(g))
    if args.ingrid and args.outgrid:
        if sorted(v for v in args.ingrid if v > 1) != sorted(
                v for v in args.outgrid if v > 1):
            raise SystemExit("-ingrid and -outgrid must use the same "
                             "device factors (one mesh)")

    # Reconcile the requested device count before any backend comes up: an
    # explicit -grid fixes it (and must agree with -ndev if both are given).
    if args.grid:
        reconcile_ndev("-grid", args.grid[0] * args.grid[1])
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        if args.ndev and args.ndev > 1:
            jax.config.update("jax_num_cpu_devices", args.ndev)
    if args.precision == "double":
        jax.config.update("jax_enable_x64", True)

    import distributedfft_tpu as dfft
    from distributedfft_tpu.utils import trace as tr
    from distributedfft_tpu.utils.timing import (
        gflops, max_rel_err, result_block, sync, time_fn_amortized, time_staged,
    )

    if args.trace:
        tr.init_tracing("dfft_speed3d")
    dfft.enable_metrics()  # registry feeds the -metrics telemetry line

    shape = (args.nx, args.ny, args.nz)
    dtype = jnp.complex128 if args.precision == "double" else jnp.complex64
    ndev = args.ndev or len(jax.devices())
    algorithm = ("ppermute" if args.p2p_pl
                 else "alltoallv" if args.a2av else "alltoall")
    if args.overlap is not None and args.bricks:
        raise SystemExit("-overlap applies to the chain exchanges; "
                         "brick-edge plans (-bricks) do not take it")
    if args.tune and args.tune != "off":
        if args.bricks or args.precision == "dd":
            raise SystemExit("-tune applies to the c2c/r2c chain planners; "
                             "brick and dd plans do not take it")
        if args.a2av or args.p2p_pl:
            raise SystemExit("-tune searches the transport axis; do not pin "
                             "one with -a2av/-p2p_pl")
        if args.wire is not None:
            raise SystemExit("-tune owns the wire axis (compressed "
                             "candidates enter only under a plan error "
                             "budget); do not pin one with -wire")
        if args.fuse:
            raise SystemExit("-tune owns the fusion axis (fused "
                             "candidates enter the tournament beside "
                             "their wire codecs); do not pin it with "
                             "-fuse")
    if args.explain:
        if args.bricks or args.precision == "dd":
            raise SystemExit("-explain applies to the c2c/r2c chain "
                             "planners; brick and dd plans do not take it")
        args.metrics = True  # the attribution join reads the registry
    if args.wire is not None and (args.bricks or args.precision == "dd"):
        raise SystemExit("-wire applies to the c2c/r2c chain planners; "
                         "brick and dd plans do not take it")
    if args.fuse and (args.bricks or args.precision == "dd"):
        raise SystemExit("-fuse applies to the c2c/r2c chain planners; "
                         "brick and dd plans do not take it")
    if args.batch is not None:
        if args.batch < 1:
            raise SystemExit(f"-batch must be >= 1, got {args.batch}")
        if (args.bricks or args.precision == "dd" or args.ingrid
                or args.outgrid or args.r2c_axis != 2):
            raise SystemExit("-batch applies to the canonical c2c/r2c "
                             "chain planners; brick, dd, layout "
                             "(-ingrid/-outgrid), and r2c_axis!=2 plans "
                             "do not take it")

    if args.r2c_axis != 2 and (args.kind != "r2c"
                               or args.precision == "dd"):
        raise SystemExit("-r2c_axis applies to the c64/c128 r2c path only")
    if args.op is not None:
        if (args.kind != "c2c" or args.precision == "dd" or args.bricks
                or args.ingrid or args.outgrid):
            raise SystemExit("-op runs the fused c2c operator chains; "
                             "r2c, dd, brick, and layout "
                             "(-ingrid/-outgrid) plans do not take it")
        if args.tune and args.tune != "off":
            raise SystemExit("-op with -tune is not wired in this "
                             "driver; use the planner API "
                             "(plan_spectral_op(..., tune=...)) for "
                             "tuned operator plans")

    if args.concurrent is not None:
        if args.concurrent < 1:
            raise SystemExit(f"-concurrent must be >= 1, "
                             f"got {args.concurrent}")
        if (args.bricks or args.precision == "dd" or args.ingrid
                or args.outgrid or args.tune not in (None, "off")):
            raise SystemExit("-concurrent schedules stage-graph chain "
                             "plans; brick, dd, layout (-ingrid/"
                             "-outgrid), and -tune runs do not take it")

    if args.precision == "dd":
        # Emulated-double tier: the CLI meaning of "double precision" on
        # hardware without f64 (see ops/ddfft.py). c2c, single-device or
        # slab mesh.
        return _run_dd(args, shape, ndev)

    in_spec = out_spec = None
    if args.ingrid or args.outgrid:
        from jax.sharding import PartitionSpec as P

        base = args.ingrid or args.outgrid
        factors = [v for v in base if v > 1]
        if not factors:
            # All-ones grids: a single-device plan, no layout to pin
            # (heFFTe accepts this on one rank).
            mesh = None
        else:
            mesh = dfft.make_mesh(tuple(factors) if len(factors) > 1
                                  else factors[0])
            names = list(mesh.axis_names)

            def to_spec(g):
                if g is None:
                    return None
                entries, pool = [], list(names)
                for v in g:
                    if v <= 1:
                        entries.append(None)
                        continue
                    # The factor-multiset checks above guarantee a match.
                    nm = next(n for n in pool if mesh.shape[n] == v)
                    entries.append(nm)
                    pool.remove(nm)
                return P(*entries)

            in_spec, out_spec = to_spec(args.ingrid), to_spec(args.outgrid)
        decomposition = None
    if args.bricks and args.kind != "c2c":
        raise SystemExit("-bricks supports c2c only")
    if args.ingrid or args.outgrid:
        pass  # mesh built above
    elif args.grid:
        mesh = dfft.make_mesh(tuple(args.grid))
        decomposition = None
    elif args.bricks:
        mesh = dfft.make_mesh(ndev) if ndev > 1 else None
        decomposition = None
    elif args.pencils:
        # Same min-surface grid the planner's int-mesh path would choose, so
        # -pencils benchmarks what plan_dft_c2c_3d(shape, ndev) plans.
        from distributedfft_tpu import native

        r, c = native.pencil_grid(shape, ndev)
        mesh = dfft.make_mesh((r, c)) if ndev > 1 else None
        decomposition = None
    elif args.slabs:
        mesh = dfft.make_mesh(ndev) if ndev > 1 else None
        decomposition = None
    else:
        mesh = ndev  # auto decomposition via plan logic
        decomposition = None

    if args.mm is not None:
        # Compose the tier onto the executor label: every downstream
        # consumer (planners, staged builders, brick/op paths) resolves
        # tiered labels through ops.executors.get_executor, so one
        # composition point covers them all. Raises for non-matmul
        # executors (the tier is meaningless there).
        from distributedfft_tpu.ops.executors import tiered_name

        args.executor = tiered_name(args.executor, args.mm)
    if args.fuse:
        # Compose the fusion flag onto the executor label the same way
        # -mm composes the tier: one composition point, resolved by
        # every downstream consumer through the executor-label grammar.
        # Raises for non-Pallas executors (fusion is meaningless there).
        from distributedfft_tpu.ops.executors import fused_name

        args.executor = fused_name(args.executor, True)
    plan_fn = dfft.plan_dft_r2c_3d if args.kind == "r2c" else dfft.plan_dft_c2c_3d
    kw = dict(decomposition=decomposition, executor=args.executor,
              dtype=dtype, algorithm=algorithm)
    # batch=1 normalizes to the unbatched plan; bsz drives input shapes,
    # GFlops scaling, and the CSV '+bB' label only when a real batch runs.
    bsz = args.batch if (args.batch or 0) > 1 else None
    if args.batch is not None:
        kw["batch"] = args.batch
    if args.overlap is not None:
        kw["overlap_chunks"] = args.overlap
    if args.wire is not None:
        kw["wire_dtype"] = args.wire
    if args.tune is not None:
        kw["tune"] = args.tune
    if args.kind == "r2c" and args.r2c_axis != 2:
        kw["r2c_axis"] = args.r2c_axis
    op_spec = None
    if args.op is not None:
        from distributedfft_tpu import operators

        op_spec = operators.named_op(args.op)
        fwd = operators.plan_spectral_op(shape, mesh, op=op_spec, **kw)
        bwd = None  # the operator IS the round trip (one fused program)
    elif args.bricks:
        if mesh is None:
            raise SystemExit("-bricks needs a multi-device mesh")
        from distributedfft_tpu.geometry import (
            ceil_splits, make_pencils, make_slabs, world_box,
        )
        from distributedfft_tpu import native

        w = world_box(shape)
        in_boxes = make_slabs(w, ndev, axis=2, rule=ceil_splits)
        out_boxes = make_pencils(w, native.pencil_grid(shape, ndev), 0)
        fwd = dfft.plan_brick_dft_c2c_3d(
            shape, mesh, in_boxes, out_boxes, direction=dfft.FORWARD,
            executor=args.executor, dtype=dtype, algorithm=algorithm)
        bwd = dfft.plan_brick_dft_c2c_3d(
            shape, mesh, out_boxes, in_boxes, direction=dfft.BACKWARD,
            executor=args.executor, dtype=dtype, algorithm=algorithm)
    else:
        if in_spec is not None or out_spec is not None:
            kw = dict(kw, in_spec=in_spec, out_spec=out_spec)
        fwd = plan_fn(shape, mesh, direction=dfft.FORWARD, **kw)
        # The inverse runs the opposite layout direction.
        bkw = (dict(kw, in_spec=out_spec, out_spec=in_spec)
               if (in_spec is not None or out_spec is not None) else kw)
        bwd = plan_fn(shape, mesh, direction=dfft.BACKWARD, **bkw)
    print(dfft.plan_info(fwd))
    tuned_lbl = None
    if args.tune and args.tune != "off":
        # The tuner resolved decomposition/transport/executor/K: describe
        # (and stage-time, and CSV-stamp) what actually won, not the CLI
        # defaults the search started from.
        from distributedfft_tpu.tuner import tuned_label

        tuned_lbl = tuned_label(fwd)
        algorithm = fwd.options.algorithm
        args.executor = fwd.executor
        print(f"tuned: {tuned_lbl}")
    # Resolved overlap chunk count (env/"auto" -> int at plan time) — the
    # staged builders and the CSV row must describe the same schedule.
    overlap = getattr(fwd.options, "overlap_chunks", None) or 1
    # Resolved wire mode likewise (DFFT_WIRE_DTYPE lands in the plan's
    # options): the staged breakdown must ship the same wire bytes as
    # the timed plan.
    wiredt = getattr(fwd.options, "wire_dtype", None)

    # On-device deterministic init (the reference inits on device too,
    # fftSpeed3d_c2c.cpp:61-72). Sharding hints need divisible extents;
    # uneven plans place the (padded) sharding themselves.
    mk_kw = {}
    if args.bricks:
        pass  # brick stacks always shard evenly (one brick per device)
    elif fwd.in_sharding is not None:
        from distributedfft_tpu.plan_logic import spec_entries

        divides = all(
            e is None or fwd.in_shape[d] % mesh_prod(fwd.mesh, e) == 0
            for d, e in enumerate(spec_entries(
                fwd.mesh, fwd.in_sharding.spec, len(fwd.in_shape)))
        )
        if divides:
            mk_kw["out_shardings"] = fwd.in_sharding

    @functools.partial(jax.jit, **mk_kw)
    def make_input():
        k1, k2 = jax.random.split(jax.random.PRNGKey(4242))
        rdt = jnp.float64 if dtype == jnp.complex128 else jnp.float32
        if args.bricks:
            # On-device brick-stack init: random values, with the per-brick
            # pad regions masked to zero (pads never travel the ring, but
            # the stack-level roundtrip compare needs them zero on input).
            import numpy as np
            from jax import lax as jlax

            stack_shape = fwd.in_shape
            sizes = np.array([b.shape for b in fwd.in_boxes], np.int32)
            re = jax.random.normal(k1, stack_shape, rdt)
            im = jax.random.normal(k2, stack_shape, rdt)
            mask = jnp.ones(stack_shape, bool)
            for d in range(3):
                idx = jlax.broadcasted_iota(jnp.int32, stack_shape, d + 1)
                lim = jnp.asarray(sizes[:, d]).reshape(-1, 1, 1, 1)
                mask &= idx < lim
            z = (re + 1j * im).astype(dtype) * mask
            if fwd.in_sharding is not None:
                z = jlax.with_sharding_constraint(z, fwd.in_sharding)
            return z
        mk_shape = shape if bsz is None else (bsz,) + shape
        re = jax.random.normal(k1, mk_shape, rdt)
        if args.kind == "r2c":
            return re
        im = jax.random.normal(k2, mk_shape, rdt)
        return (re + 1j * im).astype(dtype)

    x = make_input()
    sync(x)

    max_err = float("nan")
    if not args.no_verify:
        if args.op is not None:
            # Fused-vs-unfused gate: forward transform, full-grid
            # multiplier in natural layout, inverse — the reference
            # composition the fused chain must reproduce.
            from distributedfft_tpu import operators as _ops

            tf = dfft.plan_dft_c2c_3d(
                shape, mesh, direction=dfft.FORWARD, dtype=dtype,
                executor=args.executor, algorithm=algorithm)
            tb = dfft.plan_dft_c2c_3d(
                shape, mesh, direction=dfft.BACKWARD, dtype=dtype,
                executor=args.executor, algorithm=algorithm)
            m = _ops.multiplier_grid(op_spec, shape, dtype)
            probe = x if bsz is None else x[0]
            got = fwd(x) if bsz is None else fwd(x)[0]
            max_err = max_rel_err(got, tb(m * tf(probe)))
        else:
            max_err = max_rel_err(bwd(fwd(x)), x)

    stage_times = None
    if args.staged and args.bricks:
        print("note: -staged is not available for brick plans; ignoring",
              file=sys.stderr)
        args.staged = False
    if args.staged and (in_spec is not None or out_spec is not None):
        # The staged builders rebuild the CANONICAL chain; an absorbed
        # user layout re-axes it, so the breakdown would describe a
        # different execution than the timed plan.
        print("note: -staged is not available with -ingrid/-outgrid; "
              "ignoring", file=sys.stderr)
        args.staged = False
    if args.staged and args.kind == "r2c" and args.r2c_axis != 2:
        # Same mismatch: the staged builders run the canonical axis-2
        # chain, while the timed plan runs the transposed view (plus a
        # device transpose per edge).
        print("note: -staged is not available with -r2c_axis != 2; "
              "ignoring", file=sys.stderr)
        args.staged = False
    if args.staged and args.op is not None:
        stages = None
        if (fwd.decomposition == "slab" and fwd.mesh is not None
                and len(fwd.mesh.axis_names) == 1):
            from distributedfft_tpu.parallel.staged import (
                build_slab_op_stages,
            )

            stages, _ = build_slab_op_stages(
                fwd.mesh, shape, fwd.multiplier,
                axis_name=fwd.mesh.axis_names[0], executor=args.executor,
                algorithm=algorithm, overlap_chunks=overlap, batch=bsz,
                wire_dtype=wiredt,
            )
            stage_times, _ = time_staged(stages, x, iters=args.iters)
        else:
            print("note: -staged with -op supports the slab chain only; "
                  "ignoring", file=sys.stderr)
        args.staged = False
    if args.staged:
        stages = None
        if fwd.mesh is None:
            if args.kind == "c2c":
                from distributedfft_tpu.parallel.staged import (
                    build_single_stages,
                )

                stages = build_single_stages(shape, executor=args.executor,
                                             batch=bsz)
            else:
                print("note: single-device -staged supports c2c only; "
                      "ignoring", file=sys.stderr)
        elif fwd.decomposition == "slab" and args.kind == "c2c":
            from distributedfft_tpu.parallel.slab import build_slab_stages

            stages, _ = build_slab_stages(
                fwd.mesh, shape, axis_name=fwd.mesh.axis_names[0],
                executor=args.executor, algorithm=algorithm,
                overlap_chunks=overlap, batch=bsz, wire_dtype=wiredt,
            )
        elif fwd.decomposition == "slab":
            from distributedfft_tpu.parallel.staged import build_slab_rfft_stages

            stages, _ = build_slab_rfft_stages(
                fwd.mesh, shape, axis_name=fwd.mesh.axis_names[0],
                executor=args.executor, algorithm=algorithm,
                overlap_chunks=overlap, batch=bsz, wire_dtype=wiredt,
            )
        elif args.kind == "c2c":
            from distributedfft_tpu.parallel.staged import build_pencil_stages

            stages, _ = build_pencil_stages(
                fwd.mesh, shape, row_axis=fwd.mesh.axis_names[0],
                col_axis=fwd.mesh.axis_names[1], executor=args.executor,
                algorithm=algorithm, overlap_chunks=overlap, batch=bsz,
            )
        else:
            from distributedfft_tpu.parallel.staged import (
                build_pencil_rfft_stages,
            )

            stages, _ = build_pencil_rfft_stages(
                fwd.mesh, shape, row_axis=fwd.mesh.axis_names[0],
                col_axis=fwd.mesh.axis_names[1], executor=args.executor,
                algorithm=algorithm, overlap_chunks=overlap, batch=bsz,
            )
        if stages is not None:
            stage_times, _ = time_staged(stages, x, iters=args.iters)

    import contextlib

    ccn = args.concurrent if (args.concurrent or 0) > 1 else None
    cc_plan = None
    if ccn is not None:
        from distributedfft_tpu.stagegraph import schedule_concurrent

        if fwd.graph is None:
            raise SystemExit("-concurrent needs a stage-graph (slab/"
                             "pencil) chain plan; this plan has none")
        cc_plan = schedule_concurrent([fwd] * ccn)

    prof = jax.profiler.trace(args.profile) if args.profile else contextlib.nullcontext()
    with prof:
        if cc_plan is not None:
            cc_xs = [x] * ccn
            seconds, _ = time_fn_amortized(
                lambda: cc_plan(*cc_xs), iters=args.iters, repeats=2)
        else:
            seconds, _ = time_fn_amortized(lambda: fwd(x),
                                           iters=args.iters, repeats=2)
    is_real = args.kind == "r2c"
    # One batched execution computes bsz transforms (times ccn
    # co-scheduled programs): GFlops and the throughput line count all
    # of them. A fused operator run pays forward + inverse per solve
    # (2x the transform flops).
    gf = (gflops(shape, seconds, real=is_real) * (bsz or 1) * (ccn or 1)
          * (2 if args.op else 1))

    print(result_block(shape, ndev, seconds, max_err, stage_times, real=is_real))
    if args.op is not None:
        print(f"operator: fused {args.op} -> "
              f"{(bsz or 1) / seconds:.2f} solves/s")
    if bsz is not None and args.op is None:
        print(f"batch: {bsz} coalesced transforms -> "
              f"{bsz / seconds:.2f} transforms/s")
    if ccn is not None:
        print(f"concurrent: {ccn} co-scheduled transforms -> "
              f"{(ccn * (bsz or 1)) / seconds:.2f} "
              f"concurrent transforms/s")

    exp_rec = None
    if args.explain:
        import json as _json

        from distributedfft_tpu.explain import format_explain

        try:
            exp_rec = dfft.explain(fwd, iters=max(2, min(args.iters, 5)))
            print(format_explain(exp_rec))
            # The machine-readable twin of the table (the 'telemetry'
            # line pattern) for campaign scripts that archive stdout.
            print("explain " + _json.dumps(exp_rec, sort_keys=True))
        except Exception as e:  # noqa: BLE001 — explain is an extra
            print(f"note: -explain failed: {type(e).__name__}: {e}",
                  file=sys.stderr)

    if args.csv:
        header = ["kind", "precision", "nx", "ny", "nz", "ndev",
                  "decomposition", "algorithm", "executor", "seconds",
                  "gflops", "max_err"]
        if args.explain:
            # Predicted-vs-measured t2 column ONLY on explain runs: the
            # CsvRecorder refuses mismatched headers, so default sweeps
            # keep their schema and explain sweeps get their own file.
            header.append("t2_model_measured_ratio")
        rec = tr.CsvRecorder(args.csv, tuple(header))
        deco = f"bricks-{fwd.decomposition}" if args.bricks else fwd.decomposition
        # Non-default r2c_axis is the variable under study in an
        # r2c_direction sweep: encode it in the kind column (schema
        # unchanged for default rows).
        kind = (f"r2c_axis{args.r2c_axis}"
                if args.kind == "r2c" and args.r2c_axis != 2 else args.kind)
        alg_label = _algorithm_label(
            algorithm, overlap, batch=bsz,
            wire=getattr(fwd.options, "wire_dtype", None), op=args.op,
            mm=getattr(fwd.options, "mm_precision", None),
            fuse=":fuse" in (fwd.executor or ""))
        if ccn is not None:
            # Concurrent rows compile a merged N-transform program —
            # never comparable to sequential rows (same separation rule
            # as '+bB').
            alg_label += f"+cc{ccn}"
        if tuned_lbl is not None:
            # Tuned rows must never be indistinguishable from rows that
            # pinned the same knobs by hand (the tuple can move between
            # re-tunes); same separation rule as '+ovK'.
            alg_label += "+tuned"
        row = [kind, args.precision, *shape, ndev, deco,
               alg_label,
               _executor_label(args.executor),
               f"{seconds:.6f}", f"{gf:.1f}", f"{max_err:.3e}"]
        if args.explain:
            row.append(f"{_t2_ratio(exp_rec)}")
        rec.record(*row)
    _print_telemetry(args)
    if args.trace:
        print(f"trace written to {tr.finalize_tracing()}")


def _print_telemetry(args) -> None:
    """One self-contained ``telemetry {...}`` JSON line (with -metrics):
    the structured counterpart of the human-readable result block, for
    campaign scripts that archive stdout."""
    if not getattr(args, "metrics", False):
        return
    import json

    import distributedfft_tpu as dfft

    print("telemetry " + json.dumps(dfft.metrics_snapshot()))


def _t2_ratio(exp_rec) -> str:
    """Predicted/measured t2 ratio of one explain record ("nan" when
    either side is unavailable — single-device plans have no t2, and a
    failed explain must still leave a well-formed CSV row)."""
    try:
        t2 = exp_rec["stages"]["t2"]
        model_s = t2["model"]["seconds"]
        meas_s = t2["measured"]["seconds"]
        if model_s and meas_s:
            return f"{model_s / meas_s:.4f}"
    except (TypeError, KeyError):
        pass
    return "nan"


def _algorithm_label(algorithm: str, overlap: int | None,
                     batch: int | None = None,
                     wire: str | None = None,
                     op: str | None = None,
                     mm: str | None = None,
                     fuse: bool = False) -> str:
    """Algorithm column label with the overlap chunk count
    (``alltoall+ov4``), coalesced batch size (``alltoall+b8``), on-wire
    compression (``alltoall+wbf16``), fused spectral operator
    (``alltoall+oppoisson``), plan-scoped matmul precision tier
    (``alltoall+mmbf16``), and/or Pallas stage-pair fusion
    (``alltoall+wbf16+pfuse``) appended — overlapped / batched /
    compressed / operator / reduced-precision / fused sweep rows must
    never be indistinguishable from monolithic exact single-transform
    baselines (the regress store keys the label into the baseline
    config group). Default (K=1, unbatched, exact-wire, bare-transform,
    env-default precision, unfused) rows keep the bare name (schema
    unchanged)."""
    label = (f"{algorithm}+ov{overlap}"
             if overlap and overlap != 1 else algorithm)
    if batch and batch > 1:
        label += f"+b{batch}"
    if wire:
        label += f"+w{wire}"
    if op:
        label += f"+op{op}"
    if mm:
        label += f"+mm{mm}"
    if fuse:
        label += "+pfuse"
    return label


# Env knobs appended to the executor label, gated on the executor
# families that actually consult them at trace time: the DFFT_MM_* tiers
# are read by the matmul engine and the Pallas kernels
# (ops/dft_matmul.py::mm_precision/complex_mode), DFFT_DD_DEPTH by the
# dd slicing engine only. A leftover env var from an earlier sweep step
# must not mislabel an 'xla' row as 'xla[gauss]'.
_MM_EXECUTORS = ("matmul", "pallas")
_DD_EXECUTORS = ("dd",)  # the dd tier records executor "dd-mxu"


def _executor_label(executor: str) -> str:
    """Executor column label with the active trace-time knobs of THIS
    executor family appended (e.g. ``matmul[high+gauss+split=4x128]`` —
    ``+``-joined: a comma would split the CSV field) — sweep rows driven
    by env (DFFT_MM_*, DFFT_DD_DEPTH) must be self-describing, not
    distinguishable only by which campaign step appended them. Executors
    that never consult a knob (xla, xla_minor) keep the bare name, and
    default rows keep the old schema."""
    import os

    base = executor.split(":", 1)[0]
    knobs = []
    if base.startswith(_MM_EXECUTORS):
        # A tiered label ('matmul:bf16') pins its own precision/complex
        # mode at trace time — the env knobs are defaults only there, so
        # appending them would mislabel what actually ran.
        try:
            from distributedfft_tpu.ops.executors import split_executor

            _, own_tier, own_cmode = (split_executor(executor)
                                      if ":" in executor
                                      else (base, None, None))
        except ValueError:
            own_tier = own_cmode = None
        prec = os.environ.get("DFFT_MM_PRECISION", "").strip().lower()
        if prec and prec != "highest" and own_tier is None:
            knobs.append(prec)
        if (os.environ.get("DFFT_MM_COMPLEX", "").strip().lower() == "gauss"
                and own_cmode is None):
            knobs.append("gauss")
        split = os.environ.get("DFFT_MM_SPLIT", "").strip()
        if split:  # multi-entry values are comma-separated (512=4x128,...)
            knobs.append(f"split={split.replace(',', ';')}")
        dmax = os.environ.get("DFFT_MM_DIRECT_MAX", "").strip()
        if dmax:
            knobs.append(f"dmax={dmax}")
    if base.startswith(_DD_EXECUTORS):
        depth = os.environ.get("DFFT_DD_DEPTH", "").strip()
        if depth:  # the dd tier's slice-depth knob (campaign-swept)
            knobs.append(f"depth={depth.replace(',', ';')}")
    return f"{executor}[{'+'.join(knobs)}]" if knobs else executor


def _spec_axis_sizes(sharding):
    """Per-array-dim total shard counts of a NamedSharding (1 where
    unsharded) — the divisibility guard for pinned input shardings."""
    entries = (tuple(sharding.spec) + (None,) * 3)[:3]
    return [mesh_prod(sharding.mesh, e) if e else 1 for e in entries]


def _run_dd(args, shape, ndev) -> None:
    """The dd (emulated double precision) benchmark path: roundtrip
    verification and amortized timing of ``plan_dd_dft_c2c_3d`` plans —
    the accuracy-tier rows of the campaign through the standard CLI."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    import distributedfft_tpu as dfft
    from distributedfft_tpu.utils import trace as tr
    from distributedfft_tpu.utils.timing import (
        gflops, result_block, sync, time_fn_amortized,
    )

    if args.kind != "c2c":
        raise SystemExit("-precision dd supports c2c only")
    for flag in ("grid", "ingrid", "outgrid", "a2av", "p2p_pl"):
        if getattr(args, flag, None):
            raise SystemExit(f"-{flag} is not available at the dd tier")
    from distributedfft_tpu.plan_logic import resolve_overlap_chunks

    overlap = (1 if args.bricks or ndev <= 1 else
               resolve_overlap_chunks(args.overlap, shape=shape, ndev=ndev))
    if args.bricks and args.staged:
        print("note: -staged is not available for brick plans; ignoring",
              file=sys.stderr)
        args.staged = False

    brick_in_boxes = None
    if args.bricks:
        if ndev < 2:
            raise SystemExit("-bricks needs a multi-device mesh")
        from distributedfft_tpu import native as _native
        from distributedfft_tpu.geometry import (
            ceil_splits, make_pencils, make_slabs, world_box,
        )

        mesh = dfft.make_mesh(ndev)
        w = world_box(shape)
        brick_in_boxes = make_slabs(w, ndev, axis=2, rule=ceil_splits)
        out_boxes = make_pencils(w, _native.pencil_grid(shape, ndev), 0)
        fwd = dfft.plan_dd_brick_dft_c2c_3d(
            shape, mesh, brick_in_boxes, out_boxes)
        bwd = dfft.plan_dd_brick_dft_c2c_3d(
            shape, mesh, out_boxes, brick_in_boxes,
            direction=dfft.BACKWARD)
    else:
        if args.pencils and ndev > 1:
            # Same min-surface grid the c64 -pencils path benchmarks.
            from distributedfft_tpu import native as _native

            r, c = _native.pencil_grid(shape, ndev)
            mesh = dfft.make_mesh((r, c))
        else:
            mesh = dfft.make_mesh(ndev) if ndev > 1 else None
        fwd = dfft.plan_dd_dft_c2c_3d(shape, mesh, overlap_chunks=overlap)
        bwd = dfft.plan_dd_dft_c2c_3d(shape, mesh, direction=dfft.BACKWARD,
                                      overlap_chunks=overlap)
    print(f"decomposition: {fwd.decomposition}")
    print("precision: dd (double-double over exact-sliced bf16 matmuls)")

    mk_kw = {}
    if brick_in_boxes is not None:
        pass  # brick stacks always shard evenly (one brick per device)
    elif fwd.in_sharding is not None and all(
            shape[d] % s == 0 for d, s in enumerate(
                _spec_axis_sizes(fwd.in_sharding))):
        mk_kw["out_shardings"] = (fwd.in_sharding, fwd.in_sharding)

    @functools.partial(jax.jit, **mk_kw)
    def make_input():
        k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(4242), 4)
        mk_shape = shape
        if brick_in_boxes is not None:
            from distributedfft_tpu.parallel.bricks import stack_pad_for

            mk_shape = (ndev,) + stack_pad_for(brick_in_boxes)
        hi = (jax.random.normal(k1, mk_shape, jnp.float32)
              + 1j * jax.random.normal(k2, mk_shape, jnp.float32)
              ).astype(jnp.complex64)
        # A representative lo ~2^-25 below hi (the dd invariant scale).
        lo = ((jax.random.normal(k3, mk_shape, jnp.float32)
               + 1j * jax.random.normal(k4, mk_shape, jnp.float32)
               ) * jnp.float32(2.0 ** -25)).astype(jnp.complex64)
        if brick_in_boxes is not None:
            # Zero the per-brick pad regions (pads never travel the
            # ring, but the stack-level roundtrip compare needs them
            # zero on input), and pin one brick per device.
            import numpy as _np
            from jax import lax as jlax
            from jax.sharding import (
                NamedSharding as _NS, PartitionSpec as _P,
            )

            sizes = _np.array([b.storage_shape for b in brick_in_boxes],
                              _np.int32)
            mask = jnp.ones(mk_shape, bool)
            for d in range(3):
                idx = jlax.broadcasted_iota(jnp.int32, mk_shape, d + 1)
                lim = jnp.asarray(sizes[:, d]).reshape(-1, 1, 1, 1)
                mask &= idx < lim
            hi, lo = hi * mask, lo * mask
            sh = _NS(mesh, _P(tuple(mesh.axis_names), None, None, None))
            hi = jlax.with_sharding_constraint(hi, sh)
            lo = jlax.with_sharding_constraint(lo, sh)
        return hi, lo

    hi, lo = make_input()
    sync(lo)

    stage_times = None
    if args.staged:
        from distributedfft_tpu.parallel.ddslab import (
            build_dd_pencil_stages, build_dd_single_stages,
            build_dd_slab_stages,
        )
        from distributedfft_tpu.utils.timing import time_staged

        if mesh is None:
            stages = build_dd_single_stages(shape)
        elif len(mesh.axis_names) > 1:
            stages, _ = build_dd_pencil_stages(
                mesh, shape, row_axis=mesh.axis_names[0],
                col_axis=mesh.axis_names[1], overlap_chunks=overlap)
        else:
            stages, _ = build_dd_slab_stages(
                mesh, shape, axis_name=mesh.axis_names[0],
                overlap_chunks=overlap)
        stage_times, _ = time_staged(stages, (hi, lo), iters=args.iters)

    max_err = float("nan")
    if not args.no_verify:
        bh, bl = bwd(*fwd(hi, lo))
        # dd roundtrip error, evaluated on device; fetched real (complex
        # host transfers are unimplemented on the axon tunnel).
        e = jnp.max(jnp.abs((bh - hi) + (bl - lo))) / jnp.max(jnp.abs(hi))
        max_err = float(np.asarray(jnp.real(e)))

    seconds, _ = time_fn_amortized(lambda: fwd(hi, lo), iters=args.iters,
                                   repeats=2)
    gf = gflops(shape, seconds)
    print(result_block(shape, ndev, seconds, max_err, stage_times))

    if args.csv:
        rec = tr.CsvRecorder(args.csv, (
            "kind", "precision", "nx", "ny", "nz", "ndev", "decomposition",
            "algorithm", "executor", "seconds", "gflops", "max_err",
        ))
        rec.record(args.kind, "dd", *shape, ndev, fwd.decomposition,
                   _algorithm_label("alltoall", overlap),
                   _executor_label("dd-mxu"),
                   f"{seconds:.6f}", f"{gf:.1f}", f"{max_err:.3e}")
    _print_telemetry(args)


if __name__ == "__main__":
    main()
