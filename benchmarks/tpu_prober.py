#!/usr/bin/env python
"""Persistent TPU-tunnel prober with auto-campaign trigger.

The axon tunnel that backs `jax.devices()` on this box is intermittent:
when it is down, backend init *hangs* (never errors), so every probe must
run in a killable subprocess.  Rounds 1-3 lost their hardware windows to
exactly this — the r3 verdict's top item is "keep trying all round, and
fire the campaign the moment a probe succeeds".  This script is that:

  * probe loop: one subprocess per attempt (`import jax; jax.devices()`),
    hard timeout, one log line per attempt (timestamped, appended and
    flushed so the log itself is committable evidence of continuous
    attempts, mirroring the one-run report discipline of the reference
    driver, 3dmpifft_opt/fftSpeed3d_c2c.cpp:123-137);
  * on the first successful probe: immediately exec the short hardware
    campaign (smoke -> bench -> tile sweep, benchmarks/hw_campaign.sh
    --short) and exit 0 so the orchestrating session is notified and can
    commit the rows while the window is still open;
  * on deadline without a live probe: exit 3, leaving the log as the
    committed proof of continuous attempts across the round.

Usage:
    python benchmarks/tpu_prober.py [--hours H] [--interval S] [--no-campaign]
"""
from __future__ import annotations

import argparse
import datetime as _dt
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
LOG = REPO / "benchmarks" / "results" / "prober_r05.log"

PROBE_SRC = (
    "import jax; d = jax.devices(); "
    "print('PLATFORM=' + d[0].platform + ' N=' + str(len(d)))"
)


def _log(line: str) -> None:
    stamp = _dt.datetime.now().strftime("%Y-%m-%d %H:%M:%S")
    LOG.parent.mkdir(parents=True, exist_ok=True)
    with LOG.open("a") as f:
        f.write(f"[{stamp}] {line}\n")
    print(f"[{stamp}] {line}", flush=True)


def probe_once(timeout: float) -> tuple[bool, str]:
    """One killable backend-init attempt. True only for a real TPU."""
    env = dict(os.environ)
    # Make sure the probe actually attempts the axon backend (a stray
    # JAX_PLATFORMS=cpu from a test environment would always "succeed").
    env.pop("JAX_PLATFORMS", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", PROBE_SRC],
            capture_output=True, text=True, timeout=timeout, env=env,
        )
    except subprocess.TimeoutExpired:
        return False, f"timeout after {int(timeout)}s (tunnel down: init hang)"
    except OSError as e:
        return False, f"spawn failed: {e}"
    out = (proc.stdout or "").strip().splitlines()
    marker = next((l for l in out if l.startswith("PLATFORM=")), "")
    if proc.returncode == 0 and marker and "cpu" not in marker.lower():
        return True, marker
    tail = "; ".join((proc.stderr or "").strip().splitlines()[-2:])[-300:]
    return False, f"rc={proc.returncode} {marker or tail}"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hours", type=float, default=11.0)
    ap.add_argument("--interval", type=float, default=150.0,
                    help="sleep between failed probes (seconds)")
    ap.add_argument("--probe-timeout", type=float, default=90.0)
    ap.add_argument("--no-campaign", action="store_true",
                    help="log the live probe and exit without running "
                         "hw_campaign.sh (monitoring mode)")
    args = ap.parse_args()

    deadline = time.time() + args.hours * 3600.0
    _log(f"prober start: deadline in {args.hours:.1f}h, "
         f"interval {args.interval:.0f}s, probe timeout "
         f"{args.probe_timeout:.0f}s")
    attempt = 0
    while time.time() < deadline:
        attempt += 1
        t0 = time.time()
        ok, note = probe_once(args.probe_timeout)
        _log(f"probe[{attempt}] {'LIVE' if ok else 'down'}: {note} "
             f"({time.time() - t0:.0f}s)")
        if ok:
            if args.no_campaign:
                return 0
            _log("tunnel LIVE -> launching hw_campaign.sh --short")
            camp_env = dict(os.environ)
            # The campaign must run on the TPU the probe just saw — a
            # stray JAX_PLATFORMS=cpu (stripped for the probe above)
            # would silently benchmark CPU while the log claims LIVE.
            camp_env.pop("JAX_PLATFORMS", None)
            rc = subprocess.call(
                ["bash", str(REPO / "benchmarks" / "hw_campaign.sh"),
                 "--short"],
                cwd=REPO, env=camp_env,
                stdout=(LOG.parent / "campaign_r05.log").open("a"),
                stderr=subprocess.STDOUT,
            )
            _log(f"hw_campaign.sh --short finished rc={rc} "
                 f"(rows in benchmarks/csv; full log in "
                 f"results/campaign_r05.log)")
            return 0 if rc == 0 else 2
        time.sleep(max(0.0, args.interval - (time.time() - t0)))
    _log(f"prober deadline reached after {attempt} attempts; tunnel never "
         f"came up")
    return 3


if __name__ == "__main__":
    sys.exit(main())
