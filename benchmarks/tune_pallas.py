#!/usr/bin/env python
"""Pallas kernel tuning sweep on live hardware.

The plan-time-autotune analog of the reference's scheduler exploring
shared-memory-sized axis splits (``templateFFT.cpp:3941-4100``): sweeps the
batch-tile size of the fused four-step kernel at a given axis length and
times it against the XLA FFT and the un-fused matmul path on the same
[batch, n] problem, then (optionally) the full 3D transform per executor.

Writes rows to ``benchmarks/csv/pallas_tune_<backend>.csv``. Run when a
real chip is attached; on CPU it measures the interpreter (only useful as
a smoke test with --quick).

Usage:
  python benchmarks/tune_pallas.py                 # n=512, batch=512^2
  python benchmarks/tune_pallas.py --n 1024 --tiles 64 128 256
  python benchmarks/tune_pallas.py --full3d 512
"""

from __future__ import annotations

import argparse
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def reexec_with_watchdog_self(argv, timeout: float) -> int:
    """Subprocess-with-deadline wrapper (see record_baseline.py rationale:
    a wedged backend init hangs, it does not raise)."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-u", os.path.abspath(__file__), "--worker",
             *argv],
            timeout=timeout,
        )
        return proc.returncode
    except subprocess.TimeoutExpired:
        print(f"tune worker exceeded {int(timeout)}s (wedged backend?); "
              f"killed — rows recorded so far are kept", file=sys.stderr)
        return 2


def time_fn(f, *args, iters=10):
    """Shared timing methodology (utils.timing.time_fn_amortized) so tune
    numbers stay comparable with every other benchmark in the repo."""
    from distributedfft_tpu.utils.timing import time_fn_amortized

    return time_fn_amortized(f, *args, iters=iters, repeats=3)[0]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=512)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--tiles", type=int, nargs="*",
                    default=[64, 128, 256, 512])
    ap.add_argument("--full3d", type=int, default=None,
                    help="also time full 3D c2c at this cube size per executor")
    ap.add_argument("--strided", action="store_true",
                    help="also sweep the strided axis-0 kernel at --n")
    ap.add_argument("--plane", type=int, default=None,
                    help="also sweep the fused 2D kernel at this plane size")
    ap.add_argument("--plane-batch", type=int, default=None)
    ap.add_argument("--tiles2d", type=int, nargs="*", default=[1, 2, 4])
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)  # internal: run in-process
    ap.add_argument("--timeout", type=float, default=float(
        os.environ.get("DFFT_SWEEP_TIMEOUT", 2400)))
    args = ap.parse_args()

    if not args.worker:
        # A wedged PJRT init on a sick axon tunnel hangs without raising;
        # only a subprocess deadline turns that into a recorded failure.
        argv = [a for a in sys.argv[1:] if a != "--worker"]
        return reexec_with_watchdog_self(argv, args.timeout)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributedfft_tpu.utils.cache import enable_compile_cache

    enable_compile_cache()

    from distributedfft_tpu.ops import pallas_fft
    from distributedfft_tpu.utils.timing import max_rel_err, sync
    from distributedfft_tpu.utils.trace import CsvRecorder

    backend = jax.default_backend()
    here = os.path.dirname(os.path.abspath(__file__))
    rec = CsvRecorder(
        os.path.join(here, "csv", f"pallas_tune_{backend}.csv"),
        ("kind", "n", "batch", "tile", "seconds", "gflops", "max_err",
         "status"),
    )

    n = args.n
    batch = args.batch or (64 if args.quick else n * n)
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    xr = jax.random.normal(k1, (batch, n), jnp.float32)
    xi = jax.random.normal(k2, (batch, n), jnp.float32)
    x = jax.jit(jax.lax.complex)(xr, xi)
    sync(x)
    model = 5.0 * batch * n * math.log2(n)

    xla_fft = jax.jit(lambda a: jnp.fft.fft(a, axis=-1))
    try:
        t = time_fn(xla_fft, x)
        y_ref = xla_fft(x)
        sync(y_ref)
        rec.record("1d-xla", n, batch, "-", f"{t:.6f}",
                   f"{model / t / 1e9:.1f}", "0", "ok")
        print(f"xla fft [{batch},{n}]: {t*1e3:.3f} ms "
              f"({model/t/1e9:.1f} GFlops)", flush=True)
    except Exception as e:  # noqa: BLE001
        y_ref = None
        rec.record("1d-xla", n, batch, "-", "-", "-", "-",
                   f"error {type(e).__name__}")
        print(f"xla fft failed: {e}", file=sys.stderr, flush=True)

    from distributedfft_tpu.ops import dft_matmul

    mm = jax.jit(lambda a: dft_matmul.fft_along_axis(a, -1, forward=True))
    try:
        t = time_fn(mm, x)
        err = max_rel_err(mm(x), y_ref) if y_ref is not None else float("nan")
        rec.record("1d-matmul", n, batch, "-", f"{t:.6f}",
                   f"{model / t / 1e9:.1f}", f"{err:.3e}", "ok")
        print(f"matmul [{batch},{n}]: {t*1e3:.3f} ms "
              f"({model/t/1e9:.1f} GFlops) err={err:.2e}", flush=True)
    except Exception as e:  # noqa: BLE001
        rec.record("1d-matmul", n, batch, "-", "-", "-", "-",
                   f"error {type(e).__name__}")
        print(f"matmul failed: {e}", file=sys.stderr, flush=True)

    for tile in args.tiles:
        os.environ["DFFT_PALLAS_TILE"] = str(tile)
        pallas_fft._fft_tiles.clear_cache()
        try:
            pf = jax.jit(
                lambda a: pallas_fft.fft_along_axis(a, -1, forward=True))
            t = time_fn(pf, x)
            err = (max_rel_err(pf(x), y_ref)
                   if y_ref is not None else float("nan"))
            rec.record("1d-pallas", n, batch, tile, f"{t:.6f}",
                       f"{model / t / 1e9:.1f}", f"{err:.3e}", "ok")
            print(f"pallas tile={tile} [{batch},{n}]: {t*1e3:.3f} ms "
                  f"({model/t/1e9:.1f} GFlops) err={err:.2e}", flush=True)
        except Exception as e:  # noqa: BLE001
            msg = " ".join(str(e).split())[:140]
            rec.record("1d-pallas", n, batch, tile, "-", "-", "-",
                       f"error {msg}")
            print(f"pallas tile={tile} failed: {msg}", file=sys.stderr,
                  flush=True)
    os.environ.pop("DFFT_PALLAS_TILE", None)
    pallas_fft._fft_tiles.clear_cache()

    if args.strided:
        xs = jax.jit(lambda a: jnp.swapaxes(a, 0, 1))(x)  # [n, batch]
        sync(xs)
        xla0 = jax.jit(lambda a: jnp.fft.fft(a, axis=0))
        ys_ref = None
        try:
            t = time_fn(xla0, xs)
            ys_ref = xla0(xs)
            sync(ys_ref)
            rec.record("s-xla", n, batch, "-", f"{t:.6f}",
                       f"{model / t / 1e9:.1f}", "0", "ok")
            print(f"xla fft axis0 [{n},{batch}]: {t*1e3:.3f} ms "
                  f"({model/t/1e9:.1f} GFlops)", flush=True)
        except Exception as e:  # noqa: BLE001
            rec.record("s-xla", n, batch, "-", "-", "-", "-",
                       f"error {type(e).__name__}")
            print(f"xla axis0 failed: {e}", file=sys.stderr, flush=True)
        for tile in args.tiles:
            os.environ["DFFT_PALLAS_TILE_STRIDED"] = str(tile)
            pallas_fft._fft_strided_tiles.clear_cache()
            try:
                pf0 = jax.jit(lambda a: pallas_fft.fft_axis0(a, forward=True))
                t = time_fn(pf0, xs)
                err = (max_rel_err(pf0(xs), ys_ref)
                       if ys_ref is not None else float("nan"))
                rec.record("s-pallas", n, batch, tile, f"{t:.6f}",
                           f"{model / t / 1e9:.1f}", f"{err:.3e}", "ok")
                print(f"pallas strided ct={tile} [{n},{batch}]: "
                      f"{t*1e3:.3f} ms ({model/t/1e9:.1f} GFlops) "
                      f"err={err:.2e}", flush=True)
            except Exception as e:  # noqa: BLE001
                msg = " ".join(str(e).split())[:140]
                rec.record("s-pallas", n, batch, tile, "-", "-", "-",
                           f"error {msg}")
                print(f"pallas strided ct={tile} failed: {msg}",
                      file=sys.stderr, flush=True)
        os.environ.pop("DFFT_PALLAS_TILE_STRIDED", None)
        pallas_fft._fft_strided_tiles.clear_cache()

    if args.plane:
        ny = nz = args.plane
        pb = args.plane_batch or (4 if args.quick else max(1, args.plane))
        xp = jax.jit(jax.lax.complex)(
            jax.random.normal(k1, (pb, ny, nz), jnp.float32),
            jax.random.normal(k2, (pb, ny, nz), jnp.float32))
        sync(xp)
        model2 = 5.0 * pb * ny * nz * math.log2(ny * nz)
        xla2 = jax.jit(lambda a: jnp.fft.fftn(a, axes=(1, 2)))
        y2_ref = None
        try:
            t = time_fn(xla2, xp)
            y2_ref = xla2(xp)
            sync(y2_ref)
            rec.record("2d-xla", ny, pb, "-", f"{t:.6f}",
                       f"{model2 / t / 1e9:.1f}", "0", "ok")
            print(f"xla fft2 [{pb},{ny},{nz}]: {t*1e3:.3f} ms "
                  f"({model2/t/1e9:.1f} GFlops)", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"xla fft2 failed: {e}", file=sys.stderr, flush=True)
        for tile in args.tiles2d:
            os.environ["DFFT_PALLAS_TILE2D"] = str(tile)
            pallas_fft._fft2_tiles.clear_cache()
            try:
                pf2 = jax.jit(lambda a: pallas_fft.fft2_last(a, forward=True))
                t = time_fn(pf2, xp)
                err = (max_rel_err(pf2(xp), y2_ref)
                       if y2_ref is not None else float("nan"))
                rec.record("2d-pallas", ny, pb, tile, f"{t:.6f}",
                           f"{model2 / t / 1e9:.1f}", f"{err:.3e}", "ok")
                print(f"pallas2d tile={tile} [{pb},{ny},{nz}]: "
                      f"{t*1e3:.3f} ms ({model2/t/1e9:.1f} GFlops) "
                      f"err={err:.2e}", flush=True)
            except Exception as e:  # noqa: BLE001
                msg = " ".join(str(e).split())[:140]
                rec.record("2d-pallas", ny, pb, tile, "-", "-", "-",
                           f"error {msg}")
                print(f"pallas2d tile={tile} failed: {msg}", file=sys.stderr,
                      flush=True)
        os.environ.pop("DFFT_PALLAS_TILE2D", None)
        pallas_fft._fft2_tiles.clear_cache()

    if args.full3d:
        import distributedfft_tpu as dfft

        s = args.full3d
        shape = (s, s, s)
        model3 = 5.0 * s**3 * math.log2(s**3)
        for ex in ("xla", "pallas", "matmul"):
            try:
                plan = dfft.plan_dft_c2c_3d(shape, None, dtype=jnp.complex64,
                                            executor=ex)
                x3 = jax.jit(lambda: jax.lax.complex(
                    jax.random.normal(k1, shape, jnp.float32),
                    jax.random.normal(k2, shape, jnp.float32)))()
                sync(x3)
                t = time_fn(plan.fn, x3, iters=5)
                rec.record(f"3d-{ex}", s, 1, "-", f"{t:.6f}",
                           f"{model3 / t / 1e9:.1f}", "-", "ok")
                print(f"3d {ex} {shape}: {t*1e3:.2f} ms "
                      f"({model3/t/1e9:.1f} GFlops)", flush=True)
            except Exception as e:  # noqa: BLE001
                msg = " ".join(str(e).split())[:140]
                rec.record(f"3d-{ex}", s, 1, "-", "-", "-", "-",
                           f"error {msg}")
                print(f"3d {ex} failed: {msg}", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
