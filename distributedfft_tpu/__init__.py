"""distributedfft_tpu — a TPU-native distributed FFT framework.

A from-scratch JAX/XLA re-design with the capability surface of the reference
GPU framework (lueelu/DistributedFFT): large distributed 3D complex-to-complex
FFTs, slab and pencil decompositions over a device mesh, pluggable local FFT
executors, per-stage t0..t3 timing, and a heFFTe-style correctness suite.

Quick start::

    import distributedfft_tpu as dfft

    mesh = dfft.make_mesh(8)                       # 1D slab mesh
    plan = dfft.plan_dft_c2c_3d((512, 512, 512), mesh)
    y = plan(x)                                    # X-slabs in, Y-slabs out

    solve = dfft.solve_poisson((512, 512, 512), mesh)
    u = solve(f)     # fused FFT -> -1/|k|^2 -> iFFT, one program
"""

# Package/module name-collision rule: ``dfft.explain`` is the FUNCTION
# (the api convenience below), ``dfft.explain_mod`` the module. The
# submodule is imported eagerly so its one-time package attribute
# binding happens HERE, before the api import below rebinds ``explain``
# to the function — ``dfft.explain(plan)`` stays callable no matter who
# imports ``distributedfft_tpu.explain`` later (a late submodule import
# would otherwise clobber the function with the module). Module
# contents are reachable two stable ways: ``dfft.explain_mod.<name>``
# or ``from distributedfft_tpu.explain import <name>`` — never via
# ``dfft.explain.<name>`` (that's the function).
from . import explain as explain_mod  # noqa: F401

from .api import (  # noqa: F401
    BACKWARD,
    DDPlan3D,
    FORWARD,
    Plan3D,
    alloc_local,
    clear_plan_cache,
    destroy_plan,
    execute,
    explain,
    plan_brick_dft_c2c_3d,
    plan_brick_dft_c2r_3d,
    plan_brick_dft_r2c_3d,
    plan_dd_brick_dft_c2c_3d,
    plan_dd_brick_dft_c2r_3d,
    plan_dd_brick_dft_r2c_3d,
    plan_dd_dft_c2c_3d,
    plan_dd_dft_c2r_3d,
    plan_dd_dft_r2c_3d,
    plan_dft_c2c_3d,
    plan_dft_c2r_3d,
    plan_dft_r2c_3d,
)
from .ops.ddfft import dd_from_host, dd_to_host  # noqa: F401
from .operators import (  # noqa: F401
    SpectralOp,
    fft_convolve,
    gaussian_filter,
    plan_spectral_op,
    solve_poisson,
    spectral_gradient,
)
from .stagegraph import (  # noqa: F401
    ConcurrentPlan,
    StageGraph,
    schedule_concurrent,
)
from .api import OpPlan3D  # noqa: F401
from .serving import (  # noqa: F401
    CoalescingQueue,
    DeadlineExceeded,
    Handle,
    QueueFull,
    submit,
    warm_pool,
)
# Multi-tenant QoS (docs/SERVING_QOS.md): the module is the API surface
# (dfft.qos.parse_qos / .write_ledger); the policy/tenant types and the
# quota-shed error are lifted for ctor calls and except clauses.
from . import qos  # noqa: F401
from .qos import QosPolicy, QuotaExceeded, Tenant  # noqa: F401
# Deterministic fault injection (docs/ROBUSTNESS.md): the module is the
# API surface (dfft.faults.inject / .injected / .check / .classify);
# the fault error type is lifted for except clauses.
from . import faults  # noqa: F401
from .faults import InjectedFault  # noqa: F401
# Numerics observability plane (docs/OBSERVABILITY.md "Numerics
# plane"): the module is the API surface (dfft.numerics
# .numerics_snapshot / .realized_error); the quarantine error a
# poisoned request's handle carries is lifted for except clauses.
from . import numerics  # noqa: F401
from .numerics import NonFiniteResult  # noqa: F401
from .geometry import Box3, world_box  # noqa: F401
from .local import (  # noqa: F401
    LocalPlan,
    plan_dft_c2c,
    plan_dft_c2c_1d,
    plan_dft_c2c_2d,
)
from .ops.executors import Scale, available_executors  # noqa: F401
from .parallel.fft1d import (  # noqa: F401
    DistPlan1D,
    build_dist_fft1d,
    choose_split_1d,
    plan_dft_c2c_1d_dist,
)
from .parallel.mesh import make_mesh  # noqa: F401
from .parallel.multihost import (  # noqa: F401
    fft_mesh_for,
    init_multihost,
    make_hybrid_mesh,
)
from .parallel.reshape import make_reshape3d, reshape3d  # noqa: F401
from .plan_logic import (  # noqa: F401
    LogicPlan,
    PlanOptions,
    choose_decomposition,
    negotiate_device_count,
    default_options,
    logic_plan3d,
)
from .utils.metrics import (  # noqa: F401
    enable_metrics,
    metrics_enabled,
    metrics_reset,
    metrics_snapshot,
)
from .utils.trace import (  # noqa: F401
    add_trace,
    finalize_tracing,
    init_tracing,
    plan_info,
    tracing_enabled,
)

__version__ = "0.1.0"
