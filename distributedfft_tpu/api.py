"""Plan/execute API — the framework's public surface.

Mirrors the reference's FFTW-MPI-style C API
(``3dmpifft_opt/include/fft_mpi_3d_api.h:68-74``):

    fft_mpi_init                  -> :func:`distributedfft_tpu.parallel.make_mesh`
    fft_mpi_plan_dft_c2c_3d       -> :func:`plan_dft_c2c_3d`
    fft_mpi_execute_dft_3d_c2c    -> :func:`execute` / ``Plan3D.__call__``
    fft_mpi_alloc_local_memory    -> :func:`alloc_local`
    fft_mpi_destroy_plan          -> :func:`destroy_plan` (a no-op: buffers
                                     are GC'd, plans are immutable)

plus the heFFTe-style r2c pair (``heffte_fft3d_r2c.h``):
:func:`plan_dft_r2c_3d` / :func:`plan_dft_c2r_3d`.

A plan captures everything the reference resolves at plan time — geometry,
exchange tables, compiled kernels (``setFFTPlans``,
``fft_mpi_3d_api.cpp:318-429``; hipRTC compilation,
``templateFFT.cpp:5621-5712``) — as jit-compiled XLA executables; execution
only replays them, exactly as ``launchFFTKernel`` only replays precomputed
launches (``templateFFT.cpp:6212-6260``). Decomposition/mesh/algorithm
decisions live in :mod:`.plan_logic` (the ``plan_operations`` analog).

Transform convention is numpy's: forward unnormalized, inverse scaled by
1/N. heFFTe-style ``Scale`` options are applied on top (see
:class:`distributedfft_tpu.ops.Scale`).
"""

from __future__ import annotations

import functools
import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, Sequence, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .utils import metrics as _metrics
from .utils.trace import add_trace

from . import faults as _faults
from . import geometry as geo
from .geometry import Box3, world_box
from .ops.executors import (
    Scale, apply_scale, get_c2r, get_executor, get_r2c, scale_factor,
)
from .plan_logic import (
    DEFAULT_OPTIONS,
    LogicPlan,
    PlanOptions,
    io_boxes,
    logic_plan3d,
    resolve_fuse,
    resolve_overlap_chunks,
    resolve_tune_mode,
    spec_entries as _spec_entries_impl,
)
from .parallel.pencil import PencilSpec, build_pencil_fft3d, build_pencil_rfft3d
from .parallel.slab import (
    SlabSpec,
    batch_pspec,
    build_slab_fft3d,
    build_slab_rfft3d,
    build_slab_stages,
    check_batch,
)

# FFTW sign convention (FFTW_FORWARD = -1, FFTW_BACKWARD = +1); single
# definition lives in .local, re-exported here as the public surface.
from .local import BACKWARD, FORWARD  # noqa: E402


@dataclass
class Plan3D:
    """A compiled distributed 3D FFT plan (one direction).

    The analog of the reference's plan struct
    (``fft_mpi_3d_api.h:11-66``): owns the decomposition geometry, the
    input/output shardings, and the compiled transform.
    """

    shape: tuple[int, int, int]
    direction: int
    dtype: Any
    decomposition: str            # "single" | "slab" | "pencil"
    executor: str
    mesh: Mesh | None
    fn: Callable
    spec: SlabSpec | PencilSpec | None
    in_sharding: NamedSharding | None
    out_sharding: NamedSharding | None
    in_boxes: list[Box3] = field(default_factory=list)
    out_boxes: list[Box3] = field(default_factory=list)
    # r2c/c2r plans transform between different shapes/dtypes; c2c plans leave
    # these as the world shape / complex dtype (set in __post_init__).
    in_shape: tuple[int, int, int] | None = None
    out_shape: tuple[int, int, int] | None = None
    in_dtype: Any = None
    out_dtype: Any = None
    real: bool = False
    # The halved axis of an r2c/c2r plan (heFFTe ``r2c_direction``).
    # Stored explicitly because shape inference is ambiguous when the
    # halved extent is 1 or 2 (N//2+1 == N there).
    r2c_axis: int = 2
    # Leading batch axis of a coalesced multi-request plan: B independent
    # same-shape transforms executed as ONE device program with one
    # shared exchange per t2 stage (in/out shapes carry the [B, ...]
    # prefix; boxes stay per-transform). None = unbatched (batch=1 plans
    # normalize here — byte-identical HLO to an unadorned plan).
    batch: int | None = None
    options: PlanOptions = DEFAULT_OPTIONS
    # The resolved plan skeleton (axis assignment, stage chain, device-count
    # negotiation record) — surfaced by plan_info.
    logic: LogicPlan | None = None
    # Brick-I/O plans: the two overlap-map ring edges (in->chain, chain->out)
    # with their payload/wire accounting (BrickSpec pair); None otherwise.
    brick_edges: tuple | None = None

    def __post_init__(self) -> None:
        if self.in_shape is None:
            self.in_shape = self.shape
        if self.out_shape is None:
            self.out_shape = self.shape
        if self.in_dtype is None:
            self.in_dtype = self.dtype
        if self.out_dtype is None:
            self.out_dtype = self.dtype

    @property
    def forward(self) -> bool:
        return self.direction == FORWARD

    @property
    def graph(self):
        """The declarative :class:`~.stagegraph.StageGraph` this plan's
        chain was compiled from (rides the compiled callable), or None
        for plans below the IR tier (single-device, dd, brick-wrapped,
        user-layout-wrapped chains) — the feature-detection hook of
        :func:`~.stagegraph.schedule_concurrent` and the serving tier's
        multi-group flush."""
        from .stagegraph import graph_of

        return graph_of(self.fn)

    @property
    def world_size(self) -> int:
        return math.prod(self.shape)

    def __call__(self, x, *, scale: Scale = Scale.NONE):
        return execute(self, x, scale=scale)

    def compile(self) -> "Plan3D":
        """Eagerly compile (and warm every cache for) this plan's
        transform, so later executes only replay — the reference's
        plan-time discipline: all hipRTC compilation happens inside
        ``setFFTPlans``/``initializeFFT`` and ``launchFFTKernel`` only
        replays precomputed launches (``templateFFT.cpp:5621-5712,
        6212-6260``). Runs one throwaway zero-filled execution; returns
        ``self`` for chaining."""
        from .utils.timing import sync

        _faults.check("compile", self.executor)
        t0 = time.perf_counter()
        sync(self.fn(alloc_local(self)))
        self._warm = True  # the compile fault point fired (or passed)
        if _metrics._enabled:
            _metrics.observe(
                "compile_seconds", time.perf_counter() - t0,
                decomposition=self.decomposition, executor=self.executor)
        return self

    def flops(self) -> float:
        return geo.fft_flops(self.shape)


@dataclass
class OpPlan3D(Plan3D):
    """A compiled fused spectral-operator plan (:mod:`.operators`):
    FFT -> pointwise multiplier -> iFFT as one program, I/O in the
    chain's canonical input layout on both sides (``in_sharding ==
    out_sharding``). ``op`` is the operator label ("poisson", ...),
    ``op_spec`` the symbolic :class:`~.operators.SpectralOp`, and
    ``multiplier`` the per-shard wavenumber-indexed generator (kept so
    the explain layer can rebuild the staged ``t_mid`` pipeline).
    Execution via ``plan(x)`` / :func:`execute` exactly like a
    transform plan."""

    op: str = ""
    op_spec: Any = None
    multiplier: Any = None


def _default_executor(executor: str) -> str:
    """Resolve the planner's executor default: ``DFFT_EXECUTOR`` (when
    set) replaces the built-in ``"xla"`` default — the documented escape
    hatch for environments whose XLA FFT lowering is broken (the
    XLA:CPU fft-thunk fault: ``DFFT_EXECUTOR=matmul`` routes every
    default-executor plan through the thunk-free MXU matmul engine). An
    explicitly non-default ``executor=`` argument always wins; the knob
    is part of the plan-cache key."""
    if executor != "xla":
        return executor
    env = os.environ.get("DFFT_EXECUTOR", "").strip()
    return env if env and env not in ("0", "none") else executor


def _resolve_options(
    decomposition: str | None,
    executor: str,
    donate: bool,
    algorithm: str,
    options: PlanOptions | None,
    overlap_chunks: int | str | None = None,
    tune: str | None = None,
    wire_dtype: str | None = None,
    max_roundtrip_err: float | None = None,
    mm_precision: str | None = None,
    mm_complex: str | None = None,
    fuse: bool | str | None = None,
) -> PlanOptions:
    if options is not None:
        if (decomposition is not None or executor != "xla" or donate
                or algorithm != "alltoall" or overlap_chunks is not None
                or tune is not None or wire_dtype is not None
                or max_roundtrip_err is not None
                or mm_precision is not None or mm_complex is not None
                or fuse is not None):
            raise ValueError(
                "pass either options= or individual plan keywords, not both"
            )
        return _apply_fuse(_apply_mm_tiers(options))
    return _apply_fuse(_apply_mm_tiers(PlanOptions(
        decomposition=decomposition or "auto",
        algorithm=algorithm,
        executor=_default_executor(executor),
        donate=donate,
        overlap_chunks=overlap_chunks,
        tune=tune,
        wire_dtype=wire_dtype,
        max_roundtrip_err=max_roundtrip_err,
        mm_precision=mm_precision,
        mm_complex=mm_complex,
        fuse=fuse,
    )))


def _apply_mm_tiers(opts: PlanOptions) -> PlanOptions:
    """Normalize a plan's accuracy tier into its canonical executor
    label: ``mm_precision``/``mm_complex`` compose into the executor
    name (``matmul`` + ``bf16`` -> ``matmul:bf16``), and a label that
    already carries suffixes back-fills the option fields — after this,
    ``opts.executor`` and ``opts.mm_*`` are two views of one choice (the
    label is what the plan cache, wisdom store, and benchmark stamps
    key; the fields are what drivers read). ``mm_precision=None`` with a
    bare executor is returned unchanged — byte-identical planning."""
    import dataclasses

    from .ops.executors import (
        MM_EXECUTOR_BASES, fused_name, split_executor, split_fuse,
        tiered_name,
    )

    ex = opts.executor
    if opts.mm_precision is None and opts.mm_complex is None:
        if ":" not in ex:
            return opts
        base, tier, cmode = split_executor(ex)  # validates the label
        _, want_fuse = split_fuse(ex)  # the orthogonal fusion flag
        return dataclasses.replace(
            opts, mm_precision=tier, mm_complex=cmode,
            # Canonical spelling ("matmul:high" -> "matmul:f32", the
            # ":fuse" flag last): one label per tier across cache keys,
            # wisdom, and stamps.
            executor=fused_name(tiered_name(base, tier, cmode),
                                want_fuse or None))
    if not ex.split(":", 1)[0].startswith(MM_EXECUTOR_BASES):
        if resolve_tune_mode(opts.tune) != "off":
            # Tuned planning: the tier choice pins the TUNER's precision
            # axis (every matmul-family candidate carries it) — the base
            # executor here is just the search's starting point, not
            # what runs.
            return opts
        raise ValueError(
            f"mm_precision/mm_complex scope the matmul-family executors "
            f"{MM_EXECUTOR_BASES}; executor={ex!r} never consults them "
            f"(use tune='measure'/'wisdom' to search the tiered "
            f"candidate axis instead)")
    name = tiered_name(ex, opts.mm_precision, opts.mm_complex)
    base, tier, cmode = (split_executor(name) if ":" in name
                         else (name, None, None))
    return dataclasses.replace(opts, executor=name, mm_precision=tier,
                               mm_complex=cmode)


def _apply_fuse(opts: PlanOptions) -> PlanOptions:
    """Normalize the Pallas fusion flag into the canonical executor
    label — the ``_apply_mm_tiers`` convention: after this,
    ``opts.executor``'s ``:fuse`` flag and ``opts.fuse`` are two views
    of one choice (the label is what the plan cache, wisdom store, and
    benchmark stamps key; whether fusion actually *activates* is then
    the stage-graph gate, :func:`..stagegraph.plan_fusion`).

    An explicit ``fuse=True`` on an executor family without a fusion
    tier is a loud error (the ``mm_precision`` discipline); the
    ``DFFT_FUSE`` env default is a preference and is ignored there —
    a global ``DFFT_FUSE=1`` must not break ``xla`` plans."""
    import dataclasses

    from .ops.executors import FUSE_BASES, fused_name, split_fuse

    ex = opts.executor
    if not isinstance(ex, str):
        return opts
    pinned = split_fuse(ex)[1] if ":" in ex else False
    if opts.fuse is False and pinned:
        raise ValueError(
            f"executor {ex!r} already pins the fuse flag; fuse=False "
            f"conflicts (drop one of the two spellings)")
    want = resolve_fuse(opts.fuse)
    if want and not pinned:
        if ex.split(":", 1)[0] in FUSE_BASES:
            ex = fused_name(ex, True)
            pinned = True
        elif opts.fuse is not None:
            raise ValueError(
                f"fuse=True scopes the Pallas-family executors "
                f"{FUSE_BASES}; executor={ex!r} has no fusion tier "
                f"(the DFFT_FUSE env default is ignored there)")
    if ex == opts.executor and bool(opts.fuse) == pinned:
        return opts
    return dataclasses.replace(opts, executor=ex, fuse=pinned)


def _thunk_guard_executor(opts: PlanOptions, lp: LogicPlan,
                          forward: bool) -> str:
    """The XLA:CPU fft-thunk retirement path at the planner level
    (:func:`..ops.executors.thunk_guard_substitute` is the shared
    predicate — the staged pipeline builders apply the same rule): with
    ``DFFT_THUNK_GUARD`` armed, the known-poisoned class (inverse pencil
    chains with uneven ceil-padded shards on the CPU backend) routes
    through the substitute executor; everything else (and every plan
    when the knob is unset — the default) keeps its executor untouched,
    HLO-identical. Part of the plan-cache key."""
    from .ops.executors import thunk_guard_substitute

    if lp.mesh is None:
        return opts.executor
    # Uneven = some chain stage ceil-pads (shards of unequal shape); the
    # even pencil chains run the thunk cleanly. The slab class is the
    # MINOR-AXIS starved chain only: input slabs on axis 2 with
    # zero-extent shards (extent < parts) — merely-starved chains on the
    # major axes run the thunk fine, and substituting there would break
    # the executor-sensitive bitwise-parity contracts for no protection.
    uneven = any(len({b.shape for b in boxes}) > 1
                 for _axes, boxes in lp.stages)
    starved = bool(
        lp.decomposition == "slab" and lp.slab_axes
        and lp.slab_axes[0] == 2
        and any(0 in b.shape for b in lp.stages[0][1]))
    return thunk_guard_substitute(
        opts.executor, decomposition=lp.decomposition, forward=forward,
        uneven=uneven, starved=starved)


def _guarded(opts: PlanOptions, lp: LogicPlan, forward: bool):
    """Apply :func:`_thunk_guard_executor`; on a substitution, rewrite
    both option views (the planner's and the logic skeleton's) so every
    consumer — builders, metrics labels, bench stamps — describes the
    executor that actually runs."""
    import dataclasses

    gex = _thunk_guard_executor(opts, lp, forward)
    if gex == opts.executor:
        return opts, lp
    opts = dataclasses.replace(opts, executor=gex)
    lp = dataclasses.replace(
        lp, options=dataclasses.replace(lp.options, executor=gex))
    return opts, lp


def _check_direction(shape, direction) -> tuple[tuple[int, int, int], bool]:
    shape = tuple(int(s) for s in shape)
    if len(shape) != 3:
        raise ValueError("3D plans require a 3D shape")
    if direction not in (FORWARD, BACKWARD):
        raise ValueError("direction must be FORWARD (-1) or BACKWARD (+1)")
    return shape, direction == FORWARD


def _default_cdtype(dtype):
    if dtype is None:
        return jnp.dtype(
            jnp.complex128 if jax.config.jax_enable_x64 else jnp.complex64
        )
    return jnp.dtype(dtype)


def _norm_batch(batch) -> int | None:
    """Planner ``batch`` argument -> None (unbatched) or an int >= 2.

    ``batch=1`` IS the unbatched plan: same chain, same plan-cache entry
    family, byte-identical HLO to an unadorned call (the acceptance
    pin) — the serving tier executes singleton groups through the plain
    plan instead of a [1, ...] program."""
    batch = check_batch(batch)
    return None if batch == 1 else batch


def _slab_axis_name(mesh: Mesh):
    """The slab chain's mesh-axis spec: the single 1D axis name, or the
    (dcn, ici) tuple of a hierarchical plan's hybrid mesh (the combined
    axis in row-major linearization)."""
    names = mesh.axis_names
    return names[0] if len(names) == 1 else tuple(names)


def _shardings(lp: LogicPlan, spec, batch: int | None = None):
    """Input/output NamedShardings of the built chain — taken from the
    builder's own spec object (direction-true), so they reflect generalized
    axis assignments. ``batch`` prepends the replicated leading batch
    entry of a batched chain."""
    if lp.mesh is None or spec is None:
        return None, None
    if hasattr(spec, "in_pspec"):  # SlabSpec
        return (NamedSharding(lp.mesh, batch_pspec(spec.in_pspec, batch)),
                NamedSharding(lp.mesh, batch_pspec(spec.out_pspec, batch)))
    return (NamedSharding(lp.mesh, batch_pspec(spec.in_spec, batch)),
            NamedSharding(lp.mesh, batch_pspec(spec.out_spec, batch)))


def _boxes(lp: LogicPlan, world_in: Box3, world_out: Box3):
    """Per-device input/output boxes of this plan's own orientation; r2c
    plans pass a shrunk complex-side world. Delegates to
    :func:`.plan_logic.io_boxes` (one source of truth with ``lp.stages``)."""
    return io_boxes(lp, world_in, world_out)


def _spec_entries(mesh: Mesh, spec: P, ndim: int) -> tuple:
    """Validate a user PartitionSpec (rank, axis names) and return it padded
    to ``ndim`` entries (shared with the planner's layout classifier)."""
    return _spec_entries_impl(mesh, spec, ndim)


def _layout_boxes(mesh: Mesh, spec: P, world: Box3) -> list[Box3]:
    """Per-device boxes of a mesh-expressible layout, ordered to match
    ``mesh.devices.flat`` (the same device order as the canonical
    ``io_boxes``) — the ``ioboxes`` view of a PartitionSpec, derived from
    the sharding's own index map so box metadata can never diverge from
    what XLA actually places on each device."""
    _spec_entries(mesh, spec, 3)
    shape = tuple(h - lo for lo, h in zip(world.low, world.high))
    index_map = NamedSharding(mesh, spec).devices_indices_map(shape)
    boxes = []
    for dev in mesh.devices.flat:
        idxs = index_map[dev]
        low = tuple(world.low[d] + (ix.start or 0) for d, ix in enumerate(idxs))
        high = tuple(
            world.low[d] + (ix.stop if ix.stop is not None else shape[d])
            for d, ix in enumerate(idxs)
        )
        boxes.append(Box3(low, high))
    return boxes


def _spec_divides(mesh: Mesh, spec: P, shape) -> bool:
    """True when every sharded dim of ``shape`` divides by its mesh-axis
    product (the equal-shard requirement of jit-level shardings)."""
    for d, entry in enumerate(_spec_entries(mesh, spec, len(shape))):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        k = math.prod(mesh.shape[nm] for nm in names)
        if shape[d] % k:
            return False
    return True


def _wrap_user_layout(
    fn: Callable,
    mesh: Mesh,
    canonical_in: NamedSharding,
    canonical_out: NamedSharding,
    in_spec: P | None,
    out_spec: P | None,
    donate: bool,
    in_shape,
    out_shape,
) -> tuple[Callable, NamedSharding, NamedSharding]:
    """Compose user brick layouts around a canonical-layout transform — the
    heFFTe brick-in/brick-out capability (arbitrary ``box3d`` per rank,
    ``heffte_fft3d.h:105-115``) restricted to mesh-expressible bricks: the
    reshard into the canonical layout is the plan's first (and last)
    reshape, exactly how heFFTe's planner prepends/appends reshapes for
    non-pencil input (``heffte_plan_logic.cpp:162-245``). XLA emits the
    collectives for both reshards and fuses them into the program.

    User bricks require evenly-divisible extents (TPU equal-shard rule);
    uneven *canonical* layouts are fine — the inner plan pads/crops itself,
    so sharding hints are simply omitted where they would not divide.
    """
    for label, spec, shp in (("in_spec", in_spec, in_shape),
                             ("out_spec", out_spec, out_shape)):
        if spec is not None and not _spec_divides(mesh, spec, shp):
            raise ValueError(
                f"{label}={spec} does not evenly divide extents {tuple(shp)} "
                f"over the mesh; brick layouts need divisible shards"
            )
    user_in = NamedSharding(mesh, in_spec) if in_spec is not None else canonical_in
    user_out = NamedSharding(mesh, out_spec) if out_spec is not None else canonical_out

    # User specs were just validated; only the canonical fallbacks (uneven
    # extents the inner plan pads/crops itself) can fail to divide here.
    canon_in_fits = _spec_divides(mesh, canonical_in.spec, in_shape)
    jit_kw: dict = {"donate_argnums": 0} if donate else {}
    if in_spec is not None or canon_in_fits:
        jit_kw["in_shardings"] = user_in
    out_fits = out_spec is not None or _spec_divides(
        mesh, canonical_out.spec, out_shape
    )
    if out_fits:
        jit_kw["out_shardings"] = user_out

    @functools.partial(jax.jit, **jit_kw)
    def wrapped(x):
        if canon_in_fits:
            x = jax.lax.with_sharding_constraint(x, canonical_in)
        y = fn(x)
        if out_fits:
            y = jax.lax.with_sharding_constraint(y, user_out)
        return y

    return wrapped, user_in, user_out


def plan_dft_c2c_3d(
    shape: Sequence[int],
    mesh: Mesh | int | None = None,
    *,
    direction: int = FORWARD,
    decomposition: str | None = None,
    executor: str = "xla",
    dtype: Any = None,
    donate: bool = False,
    algorithm: str = "alltoall",
    overlap_chunks: int | str | None = None,
    tune: str | None = None,
    wire_dtype: str | None = None,
    max_roundtrip_err: float | None = None,
    mm_precision: str | None = None,
    mm_complex: str | None = None,
    fuse: bool | str | None = None,
    options: PlanOptions | None = None,
    in_spec: P | None = None,
    out_spec: P | None = None,
    batch: int | None = None,
) -> Plan3D:
    """Create a distributed 3D complex-to-complex FFT plan.

    ``mesh`` may be a :class:`jax.sharding.Mesh` (1D -> slab, 2D -> pencil),
    an int (decomposition chosen by :func:`~.plan_logic.choose_decomposition`
    and the mesh built to fit), or None (single device). ``direction`` uses
    the FFTW sign convention (-1 forward).

    cf. ``fft_mpi_plan_dft_c2c_3d`` (``fft_mpi_3d_api.cpp:41``), which also
    fixes direction at plan time and builds one plan per direction.

    ``in_spec`` / ``out_spec`` accept any mesh-expressible brick layout for
    the plan's input/output. Slab/pencil-shaped layouts are *absorbed* into
    the stage chain itself (heFFTe's reshape minimization,
    ``heffte_plan_logic.cpp:162-245,265-408``); other layouts get an edge
    reshard (:func:`_wrap_user_layout`). With both None the canonical chain
    runs (X-slabs <-> Y-slabs, z-pencils <-> x-pencils). NOTE: when only
    ``in_spec`` is given, the output layout follows the re-axed chain's
    natural endpoint, which may differ from canonical — read
    ``plan.out_sharding`` (pass ``out_spec`` to pin a specific layout).

    ``donate=True`` makes execution consume its input buffer (the analog of
    the reference's bufferDev ping-pong, halving HBM footprint for big
    grids) at the cost of repeat-execution on the same array; the default
    keeps FFTW-style repeatable-execute semantics.

    ``overlap_chunks`` enables the pipelined exchange/compute overlap
    (int K, ``"auto"``, or None -> ``DFFT_OVERLAP`` env; see
    :class:`~.plan_logic.PlanOptions`). K=1 is today's monolithic chain.

    ``tune`` selects measured planning (:mod:`.tuner`): ``"measure"``
    runs the pruned multi-axis tournament (decomposition x transport x
    executor x overlap K) on a wisdom miss and records the winner;
    ``"wisdom"`` only consults the persistent store and falls back to
    these static heuristics on a miss; default ``"off"`` (or the
    ``DFFT_TUNE`` env var) plans exactly as before.

    ``batch=B`` coalesces B independent same-shape transforms into ONE
    device program: I/O is ``[B, N0, N1, N2]`` (``plan.in_shape``), the
    chain runs batched FFT stages, and every exchange is one shared
    collective with the batch riding as a bystander dim — B transforms
    pay one collective latency, the whole throughput play of the serving
    tier (:mod:`.serving`). ``batch=1``/``None`` is the unbatched plan
    (byte-identical HLO). Batched plans are plan-cache- and wisdom-keyed
    by B; ``in_spec``/``out_spec`` layouts take the unbatched path only.

    ``wire_dtype`` compresses the t2 exchange payload on the wire with
    a registered codec (``"bf16"``: component pairs, half the c64 wire
    bytes; ``"int8"``: block-scaled component planes with a tiny f32
    scale sidecar, ~quarter the c64 wire bytes — each at a bounded,
    measured precision cost; ``None`` defers to ``DFFT_WIRE_DTYPE``,
    unset = exact wire, byte-identical HLO). ``algorithm="hierarchical"``
    runs the two-leg
    ICI/DCN transport over a hybrid 2D (dcn x ici) mesh
    (:func:`~.parallel.exchange.hierarchical_all_to_all`).
    ``max_roundtrip_err`` declares the plan's error budget — the gate
    under which the tuner may pick (or replay) compressed and
    reduced-precision candidates (the errors compose; one budget
    governs the sum).

    ``mm_precision="bf16"|"f32"|"highest"`` scopes the matmul-family
    executors' MXU contraction tier to THIS plan (the executor label
    becomes ``matmul:bf16`` etc. — a distinct, plan-cache-keyed
    executor; two tiers coexist in one process). ``None`` defers to the
    ``DFFT_MM_PRECISION`` env default at trace time, byte-identical to
    today. ``mm_complex="gauss"`` likewise scopes the 3-real-matmul
    complex product (env default ``DFFT_MM_COMPLEX``).

    ``fuse=True`` requests the Pallas fusion tier (executor label
    ``pallas:fuse`` — the same choice spelled as a kwarg): adjacent
    stage/codec pairs around each compressed exchange collapse into one
    shape-specialized mega-kernel when the stage-graph gate passes
    (``wire_dtype`` set, ``overlap_chunks=1``); ineligible graphs and
    shapes fall back to the unfused chain, counted and explain-visible,
    never an error. ``None`` defers to ``DFFT_FUSE`` (unset = off,
    byte-identical HLO). See docs/TUNING.md, "Pallas fusion tier".
    """
    shape, forward = _check_direction(shape, direction)
    batch = _norm_batch(batch)
    if batch is not None and (in_spec is not None or out_spec is not None):
        raise ValueError("batched plans take the canonical chain layouts; "
                         "in_spec/out_spec require batch=None (or 1)")
    opts = _resolve_options(decomposition, executor, donate, algorithm,
                            options, overlap_chunks, tune, wire_dtype,
                            max_roundtrip_err, mm_precision, mm_complex,
                            fuse)
    if resolve_tune_mode(opts.tune) != "off":
        from . import tuner

        return tuner.tuned_plan(
            "c2c", shape, mesh, opts,
            dict(direction=direction, dtype=dtype, in_spec=in_spec,
                 out_spec=out_spec, batch=batch))
    if opts.executor == "auto":
        return _auto_plan(
            functools.partial(plan_dft_c2c_3d, shape, mesh), opts,
            direction=direction, dtype=dtype, in_spec=in_spec,
            out_spec=out_spec, batch=batch,
        )
    dtype = _default_cdtype(dtype)
    lp = logic_plan3d(
        shape, mesh, opts, forward=forward, in_spec=in_spec,
        out_spec=out_spec, batch=batch,
    )
    opts, lp = _guarded(opts, lp, forward)
    world = world_box(shape)
    if (in_spec is not None or out_spec is not None) and lp.mesh is None:
        raise ValueError("in_spec/out_spec require a mesh")

    if lp.decomposition == "single":
        ex = get_executor(opts.executor)
        fft_axes = (0, 1, 2) if batch is None else (1, 2, 3)
        fn = jax.jit(lambda x: ex(x, fft_axes, forward))
        spec = None
    elif lp.decomposition == "slab":
        fn, spec = build_slab_fft3d(
            lp.mesh, shape, axis_name=_slab_axis_name(lp.mesh),
            executor=opts.executor, forward=forward, donate=opts.donate,
            algorithm=opts.algorithm,
            in_axis=lp.slab_axes[0], out_axis=lp.slab_axes[1],
            overlap_chunks=lp.options.overlap_chunks, batch=batch,
            wire_dtype=lp.options.wire_dtype,
        )
    else:
        row, col = lp.mesh.axis_names[:2]
        fn, spec = build_pencil_fft3d(
            lp.mesh, shape, row_axis=row, col_axis=col,
            executor=opts.executor, forward=forward, donate=opts.donate,
            algorithm=opts.algorithm,
            perm=lp.pencil_perm, order=lp.pencil_order,
            overlap_chunks=lp.options.overlap_chunks, batch=batch,
            wire_dtype=lp.options.wire_dtype,
        )

    in_sh, out_sh = _shardings(lp, spec, batch)
    in_boxes, out_boxes = _boxes(lp, world, world)
    # Edge reshards only for layouts the chain could not absorb — absorbed
    # layouts ARE the chain's own endpoints (heFFTe's reshape minimization,
    # heffte_plan_logic.cpp:162-245,265-408).
    wrap_in = in_spec if (in_spec is not None and not lp.in_absorbed) else None
    wrap_out = out_spec if (out_spec is not None and not lp.out_absorbed) else None
    if wrap_in is not None or wrap_out is not None:
        fn, in_sh, out_sh = _wrap_user_layout(
            fn, lp.mesh, in_sh, out_sh, wrap_in, wrap_out, opts.donate,
            shape, shape,
        )
    # Absorbed layouts ARE the chain endpoints, so the chain's own (ceil-
    # split, possibly uneven) boxes already describe them; _layout_boxes is
    # only for wrapped layouts (validated divisible by _wrap_user_layout).
    if wrap_in is not None:
        in_boxes = _layout_boxes(lp.mesh, in_spec, world)
    if wrap_out is not None:
        out_boxes = _layout_boxes(lp.mesh, out_spec, world)
    io_shape = shape if batch is None else (batch,) + shape
    return Plan3D(
        shape=shape, direction=direction, dtype=dtype,
        decomposition=lp.decomposition, executor=opts.executor, mesh=lp.mesh,
        fn=fn, spec=spec, in_sharding=in_sh, out_sharding=out_sh,
        in_boxes=in_boxes, out_boxes=out_boxes,
        in_shape=io_shape, out_shape=io_shape, batch=batch,
        options=lp.options, logic=lp,
    )


#: Executor candidates tried by ``executor="auto"`` (override with the
#: DFFT_AUTO_EXECUTORS env var, comma-separated).
_AUTO_CANDIDATES = ("xla", "xla_minor", "pallas", "matmul")


def _autotune(make_plan: Callable[[str], Plan3D]) -> Plan3D:
    """Plan every candidate executor, time one execution of each, keep the
    fastest — the reference's plan-and-pick discipline (``setFFTPlans``
    builds hipfft, rocfft, AND templateFFT plans side by side and selects
    one, ``fft_mpi_3d_api.cpp:318-429``). Candidates that fail to compile
    or execute are skipped, never fatal.

    Timing uses a zero-filled input (FFT cost is data-independent) and
    pays one compile per candidate at plan time — the same cost profile
    as the reference's plan-time hipRTC compilation of every backend.
    The tournament itself (multi-host candidate-set agreement, lockstep
    timing, winner decided from the allgathered time matrix so a
    candidate that failed timing on any process can never win) is
    :func:`.tuner.measured_select` — the same engine behind the
    multi-axis ``tune="measure"`` tournament; timing budget via
    ``DFFT_TUNE_ITERS`` (:func:`.tuner.tune_budget`).
    """
    import os

    from .tuner import measured_select, tune_budget
    from .utils.timing import time_fn_amortized

    names = [e.strip() for e in os.environ.get(
        "DFFT_AUTO_EXECUTORS", ",".join(_AUTO_CANDIDATES)).split(",")
        if e.strip() and e.strip() != "auto"]  # 'auto' itself would recurse
    iters, repeats = tune_budget()

    def measure(plan: Plan3D) -> float:
        x = alloc_local(plan)
        t, _ = time_fn_amortized(plan.fn, x, iters=iters, repeats=repeats)
        return t

    best, plans, _ = measured_select(
        names, make_plan, measure, what="auto executor candidate")
    return plans[best]


def _auto_plan(plan_fn: Callable, opts: PlanOptions, **kw) -> Plan3D:
    """Shared ``executor="auto"`` dispatch for every plan family: run the
    tournament donation-free (a donated buffer cannot be re-executed for
    timing), then rebuild the winner with the caller's donation flag."""
    import dataclasses

    def mk(ex: str, don: bool) -> Plan3D:
        o = dataclasses.replace(opts, executor=ex, donate=don)
        return plan_fn(options=o, **kw)

    best = _autotune(lambda ex: mk(ex, False))
    return mk(best.executor, opts.donate) if opts.donate else best


def _even_fallback_spec(mesh: Mesh, pref: P, shape) -> P:
    """``pref`` if it divides ``shape`` evenly over the mesh, else the first
    mesh-expressible layout (using every mesh axis) that does."""
    import itertools

    if _spec_divides(mesh, pref, shape):
        return pref
    names = list(mesh.axis_names)
    cands = []
    if len(names) == 1:
        for d in range(3):
            e: list = [None, None, None]
            e[d] = names[0]
            cands.append(P(*e))
    else:
        for da, db in itertools.permutations(range(3), 2):
            e = [None, None, None]
            e[da], e[db] = names[0], names[1]
            cands.append(P(*e))
        for d in range(3):  # both axes merged onto one dim
            e = [None, None, None]
            e[d] = tuple(names)
            cands.append(P(*e))
    for c in cands:
        if _spec_divides(mesh, c, shape):
            return c
    raise ValueError(
        f"no mesh-expressible layout of {tuple(shape)} divides evenly over "
        f"mesh axes {dict(mesh.shape)}; brick plans need at least one even "
        f"intermediate layout"
    )


def plan_brick_dft_c2c_3d(
    shape: Sequence[int],
    mesh: Mesh | int | None,
    in_boxes: Sequence[Box3],
    out_boxes: Sequence[Box3],
    *,
    direction: int = FORWARD,
    decomposition: str | None = None,
    executor: str = "xla",
    dtype: Any = None,
    donate: bool = False,
    algorithm: str = "alltoall",
    options: PlanOptions | None = None,
) -> Plan3D:
    """3D C2C plan with *arbitrary* per-device input/output boxes.

    The full heFFTe brick capability (``fft3d(inbox, outbox, comm)``,
    ``heffte_fft3d.h:105-115``): ``in_boxes``/``out_boxes`` are any
    non-overlapping decompositions of the world — uneven, non-grid,
    axis-swapped — one ``Box3`` per device in ``mesh.devices.flat`` order.
    The plan brackets the canonical stage chain with the overlap-map ring
    reshapes of :mod:`.parallel.bricks` (the ``reshape3d_alltoallv``
    analog, ``src/heffte_reshape3d.cpp:375``).

    I/O travels as *brick stacks*: ``[P, *pad]`` arrays sharded one brick
    per device (see :func:`~.parallel.bricks.scatter_bricks` /
    ``gather_bricks``); ``plan.in_shape``/``plan.out_shape`` give the stack
    shapes. Boxes may declare per-rank storage axis orders
    (``Box3.order`` — heFFTe ``box3d::order``/``use_reorder``,
    ``heffte_geometry.h:67-92``): each brick then travels in its declared
    order and the plan's order edge canonicalizes/restores it on device.
    The canonical chain endpoints must divide the world evenly over
    the mesh (pick a mesh whose axis sizes divide the extents); the user
    boxes themselves carry no such restriction.
    """
    shape, _ = _check_direction(shape, direction)
    dtype = _default_cdtype(dtype)
    inner = plan_dft_c2c_3d(
        shape, mesh, direction=direction, decomposition=decomposition,
        executor=executor, dtype=dtype, donate=donate, algorithm=algorithm,
        options=options,
    )
    return _wrap_brick_io(inner, in_boxes, out_boxes)


def plan_brick_dft_r2c_3d(
    shape: Sequence[int],
    mesh: Mesh | int | None,
    in_boxes: Sequence[Box3],
    out_boxes: Sequence[Box3],
    *,
    direction: int = FORWARD,
    r2c_axis: int = 2,
    decomposition: str | None = None,
    executor: str = "xla",
    dtype: Any = None,
    donate: bool = False,
    algorithm: str = "alltoall",
    options: PlanOptions | None = None,
) -> Plan3D:
    """Real<->complex 3D plan with arbitrary per-device boxes — the brick
    tier of heFFTe's ``fft3d_r2c`` (``heffte_fft3d_r2c.h``; r2c box shrink
    ``box3d::r2c``, ``heffte_geometry.h:94``).

    Forward: ``in_boxes`` partition the real-space world ``shape``,
    ``out_boxes`` the world shrunk to ``N//2+1`` along ``r2c_axis``
    (heFFTe ``r2c_direction``, default 2); backward swaps the roles.
    See :func:`plan_brick_dft_c2c_3d` for the stack I/O convention."""
    shape, _ = _check_direction(shape, direction)
    inner = plan_dft_r2c_3d(
        shape, mesh, direction=direction, r2c_axis=r2c_axis,
        decomposition=decomposition, executor=executor, dtype=dtype,
        donate=donate, algorithm=algorithm, options=options,
    )
    return _wrap_brick_io(inner, in_boxes, out_boxes)


def plan_brick_dft_c2r_3d(shape, mesh, in_boxes, out_boxes, **kw) -> Plan3D:
    """Convenience alias: the inverse of :func:`plan_brick_dft_r2c_3d`."""
    kw.setdefault("direction", BACKWARD)
    return plan_brick_dft_r2c_3d(shape, mesh, in_boxes, out_boxes, **kw)


def _build_brick_edges(m, in_boxes, out_boxes, in_world, out_world,
                       in_spec, out_spec, algorithm: str):
    """Shared edge construction for every brick planner (c64 and dd):
    validate world coverage, target the nearest *even* mesh layout, and
    build the (edge_in, edge_out) stack<->canonical callables plus their
    BrickSpec accounting pair.

    The ring lands an even mesh layout; when the chain endpoint itself
    is uneven (ceil-split), the chain's own sharding constraints move
    data the rest of the way (one extra XLA reshard — the same
    prepend/append reshape heFFTe's planner emits for non-matching
    layouts, heffte_plan_logic.cpp:162-245). ``algorithm="alltoallv"``
    selects the exact-count ragged transport for the brick edges (wire
    == payload); other PlanOptions algorithms keep the padded ppermute
    ring. Per-box storage orders (heFFTe box3d::order / use_reorder)
    are honored: the caller's bricks arrive/leave in their declared
    axis order; the order edge canonicalizes before the ring and
    permutes back after."""
    from .parallel.bricks import (
        plan_bricks_to_spec, plan_spec_to_bricks, reorder_stack,
    )

    _check_brick_algorithm(algorithm)
    _check_world_coverage(in_boxes, out_boxes, in_world, out_world)
    in_target = _even_fallback_spec(m, in_spec, in_world)
    out_target = _even_fallback_spec(m, out_spec, out_world)
    brick_alg = "a2av" if algorithm == "alltoallv" else "ring"
    to_canon, in_bspec = plan_bricks_to_spec(m, in_boxes, in_target,
                                             algorithm=brick_alg)
    from_canon, out_bspec = plan_spec_to_bricks(m, out_target, out_boxes,
                                                algorithm=brick_alg)
    in_reorder = reorder_stack(m, in_boxes, to_canonical=True)
    out_reorder = reorder_stack(m, out_boxes, to_canonical=False)

    def edge_in(stack):
        if in_reorder is not None:
            stack = in_reorder(stack)
        return to_canon(stack)

    def edge_out(y):
        y = from_canon(y)
        return out_reorder(y) if out_reorder is not None else y

    return (edge_in, edge_out, (in_bspec, out_bspec),
            (in_reorder, to_canon, from_canon, out_reorder))


def _check_brick_algorithm(algorithm: str) -> None:
    if algorithm not in ("alltoall", "alltoallv", "ppermute"):
        raise ValueError(
            f"unknown algorithm {algorithm!r} for a brick plan; "
            f"expected alltoall|alltoallv|ppermute")


def _check_world_coverage(in_boxes, out_boxes, in_world, out_world):
    """Both box lists must tile their side's world (shared by the
    distributed and single-device brick edge builders)."""
    from .geometry import find_world

    for label, boxes, want in (("in_boxes", in_boxes, in_world),
                               ("out_boxes", out_boxes, out_world)):
        got = find_world(boxes).shape
        if got != tuple(want):
            raise ValueError(
                f"{label} cover a {got} world; this plan's side is "
                f"{tuple(want)}")


def _single_brick_edges(in_boxes, out_boxes, in_world, out_world):
    """Degenerate (1-device) brick edges: the world is ONE brick per side,
    possibly order-permuted — heFFTe brick plans run fine on a single rank
    (``fft3d(inbox, outbox, comm)`` with a self communicator). No
    collectives; the edge is crop + storage-order permutation only."""
    from .parallel.bricks import _inv_perm

    for label, boxes in (("in_boxes", in_boxes), ("out_boxes", out_boxes)):
        if len(boxes) != 1:
            raise ValueError(
                f"single-device brick plans take exactly one box per side; "
                f"{label} has {len(boxes)}")
    _check_world_coverage(in_boxes, out_boxes, in_world, out_world)
    bi, bo = in_boxes[0], out_boxes[0]

    def edge_in(stack):
        x = stack[0]
        if bi.order != (0, 1, 2):
            x = jnp.transpose(x, _inv_perm(bi.order))
        return x

    def edge_out(y):
        if bo.order != (0, 1, 2):
            y = jnp.transpose(y, bo.order)
        return y[None]

    return edge_in, edge_out


def _wrap_brick_io_single(
    inner: Plan3D, in_boxes: Sequence[Box3], out_boxes: Sequence[Box3]
) -> Plan3D:
    """Single-device tier of :func:`_wrap_brick_io` (inner plan has no
    mesh): same ``[1, *pad]`` stack I/O convention as the distributed
    tier, so callers are decomposition-agnostic."""
    from .parallel.bricks import stack_pad_for
    from .stagegraph import BrickEdgeGraph, compile_brick_io

    edge_in, edge_out = _single_brick_edges(
        in_boxes, out_boxes, inner.in_shape, inner.out_shape)
    fn = compile_brick_io(
        BrickEdgeGraph(edge_in=(None, edge_in), edge_out=(edge_out, None),
                       donate=inner.options.donate,
                       meta={"decomposition": inner.decomposition}),
        inner.fn)

    return Plan3D(
        shape=inner.shape, direction=inner.direction, dtype=inner.dtype,
        decomposition=inner.decomposition, executor=inner.executor,
        mesh=None, fn=fn, spec=inner.spec, in_sharding=None,
        out_sharding=None,
        in_boxes=list(in_boxes), out_boxes=list(out_boxes),
        in_shape=(1,) + stack_pad_for(in_boxes),
        out_shape=(1,) + stack_pad_for(out_boxes),
        in_dtype=inner.in_dtype, out_dtype=inner.out_dtype,
        real=inner.real, r2c_axis=inner.r2c_axis,
        options=inner.options, logic=inner.logic,
    )


def _wrap_brick_io(
    inner: Plan3D, in_boxes: Sequence[Box3], out_boxes: Sequence[Box3]
) -> Plan3D:
    """Bracket a canonical-chain plan with the overlap-map ring reshapes
    (shared by the c2c and r2c brick planners). The wrapper program is
    declared as a :class:`..stagegraph.BrickEdgeGraph` and compiled by
    :func:`..stagegraph.compile_brick_io` — the PR 18 migration of the
    named IR remainder (byte-identical HLO, pinned)."""
    from .parallel.bricks import stack_pad_for
    from .stagegraph import BrickEdgeGraph, compile_brick_io

    if inner.mesh is None or inner.in_sharding is None:
        return _wrap_brick_io_single(inner, in_boxes, out_boxes)
    m = inner.mesh
    _, _, edges, pieces = _build_brick_edges(
        m, in_boxes, out_boxes, inner.in_shape, inner.out_shape,
        inner.in_sharding.spec, inner.out_sharding.spec,
        inner.options.algorithm)
    in_reorder, to_canon, from_canon, out_reorder = pieces
    fn = compile_brick_io(
        BrickEdgeGraph(edge_in=(in_reorder, to_canon),
                       edge_out=(from_canon, out_reorder),
                       donate=inner.options.donate, specs=edges,
                       meta={"decomposition": inner.decomposition,
                             "algorithm": inner.options.algorithm}),
        inner.fn)

    p = len(in_boxes)
    names = tuple(m.axis_names)
    stack_sh = NamedSharding(m, P(names, None, None, None))
    return Plan3D(
        shape=inner.shape, direction=inner.direction, dtype=inner.dtype,
        decomposition=inner.decomposition, executor=inner.executor, mesh=m,
        fn=fn, spec=inner.spec, in_sharding=stack_sh, out_sharding=stack_sh,
        in_boxes=list(in_boxes), out_boxes=list(out_boxes),
        in_shape=(p,) + stack_pad_for(in_boxes),
        out_shape=(p,) + stack_pad_for(out_boxes),
        in_dtype=inner.in_dtype, out_dtype=inner.out_dtype,
        real=inner.real, r2c_axis=inner.r2c_axis,
        options=inner.options, logic=inner.logic,
        brick_edges=edges,
    )


def plan_dft_r2c_3d(
    shape: Sequence[int],
    mesh: Mesh | int | None = None,
    *,
    direction: int = FORWARD,
    decomposition: str | None = None,
    executor: str = "xla",
    dtype: Any = None,
    donate: bool = False,
    algorithm: str = "alltoall",
    overlap_chunks: int | str | None = None,
    tune: str | None = None,
    wire_dtype: str | None = None,
    max_roundtrip_err: float | None = None,
    mm_precision: str | None = None,
    mm_complex: str | None = None,
    fuse: bool | str | None = None,
    options: PlanOptions | None = None,
    in_spec: P | None = None,
    out_spec: P | None = None,
    r2c_axis: int = 2,
    batch: int | None = None,
) -> Plan3D:
    """Create a distributed real-to-complex (forward) / complex-to-real
    (backward) 3D FFT plan — heFFTe ``fft3d_r2c`` parity
    (``heffte_fft3d_r2c.h``; r2c box shrink ``heffte_geometry.h:94``).

    ``shape`` is the *real-space* world shape. The complex side is shrunk
    along ``r2c_axis`` (default 2) to ``N//2+1`` — heFFTe's
    ``r2c_direction`` ctor argument (``heffte_fft3d_r2c.h:71-84``).
    Forward input is real; backward output is real with numpy 1/N
    scaling. Non-default ``r2c_axis`` runs the canonical chain on a
    transposed view (one extra device transpose per edge; the chain's
    collectives are unchanged). ``donate`` is accepted for API symmetry
    but is a no-op on r2c/c2r plans: real and half-spectrum buffers
    differ in dtype and size, so XLA can never alias them.

    ``batch=B`` coalesces B same-shape transforms into one device program
    with one shared exchange per batch (the :func:`plan_dft_c2c_3d`
    convention); canonical ``r2c_axis=2`` chains only.

    ``mm_precision`` / ``mm_complex`` scope the matmul-family executor's
    accuracy tier to this plan (the :func:`plan_dft_c2c_3d` convention).
    """
    batch = _norm_batch(batch)
    if r2c_axis != 2:
        if batch is not None:
            raise ValueError(
                "batched r2c plans run the canonical r2c_axis=2 chain; "
                "transpose the batch's world instead of passing r2c_axis")
        return _r2c_axis_wrapped(
            shape, mesh, r2c_axis, direction=direction,
            decomposition=decomposition, executor=executor, dtype=dtype,
            donate=donate, algorithm=algorithm,
            overlap_chunks=overlap_chunks, tune=tune,
            wire_dtype=wire_dtype, max_roundtrip_err=max_roundtrip_err,
            mm_precision=mm_precision, mm_complex=mm_complex, fuse=fuse,
            options=options, in_spec=in_spec, out_spec=out_spec,
        )
    if batch is not None and (in_spec is not None or out_spec is not None):
        raise ValueError("batched plans take the canonical chain layouts; "
                         "in_spec/out_spec require batch=None (or 1)")
    shape, forward = _check_direction(shape, direction)
    opts = _resolve_options(decomposition, executor, donate, algorithm,
                            options, overlap_chunks, tune, wire_dtype,
                            max_roundtrip_err, mm_precision, mm_complex,
                            fuse)
    if resolve_tune_mode(opts.tune) != "off":
        from . import tuner

        return tuner.tuned_plan(
            "r2c", shape, mesh, opts,
            dict(direction=direction, dtype=dtype, in_spec=in_spec,
                 out_spec=out_spec, batch=batch))
    if opts.donate:
        # r2c/c2r buffers can never alias (real world vs complex
        # half-spectrum differ in dtype and size), so donation would
        # only emit unusable-donation warnings per execute and skew the
        # plan_info memory estimate: accepted for API symmetry,
        # documented no-op (same policy as the dd tier).
        import dataclasses

        opts = dataclasses.replace(opts, donate=False)
    if opts.executor == "auto":
        return _auto_plan(
            functools.partial(plan_dft_r2c_3d, shape, mesh), opts,
            direction=direction, dtype=dtype, in_spec=in_spec,
            out_spec=out_spec, batch=batch,
        )
    if opts.algorithm == "hierarchical":
        raise ValueError(
            "hierarchical transport supports the c2c chains; r2c/c2r "
            "plans run the flat transports")
    dtype = _default_cdtype(dtype)
    if not jnp.issubdtype(dtype, jnp.complexfloating):
        raise ValueError(
            f"r2c plans take the complex working dtype, got {dtype}; the real "
            "side is derived from it"
        )
    rdtype = jnp.float64 if dtype == jnp.complex128 else jnp.float32
    n0, n1, n2 = shape
    cshape = (n0, n1, n2 // 2 + 1)
    # r2c chains keep the canonical axis assignment (the real axis must be
    # axis 2, device-local on the real side); user layouts go through edge
    # reshards below rather than chain re-axing.
    lp = logic_plan3d(shape, mesh, opts, forward=forward, batch=batch)
    opts, lp = _guarded(opts, lp, forward)
    world, cworld = world_box(shape), world_box(cshape)
    bo = 0 if batch is None else 1

    if lp.decomposition == "single":
        ex = get_executor(opts.executor)
        r2c, c2r = get_r2c(opts.executor), get_c2r(opts.executor)
        if forward:
            fn = jax.jit(lambda x: ex(r2c(x, 2 + bo), (bo, 1 + bo), True))
        else:
            fn = jax.jit(
                lambda y: c2r(ex(y, (bo, 1 + bo), False), n2, 2 + bo))
        spec = None
    elif lp.decomposition == "slab":
        fn, spec = build_slab_rfft3d(
            lp.mesh, shape, axis_name=lp.mesh.axis_names[0],
            executor=opts.executor, forward=forward, donate=opts.donate,
            algorithm=opts.algorithm,
            overlap_chunks=lp.options.overlap_chunks, batch=batch,
            wire_dtype=lp.options.wire_dtype,
        )
    else:
        row, col = lp.mesh.axis_names[:2]
        fn, spec = build_pencil_rfft3d(
            lp.mesh, shape, row_axis=row, col_axis=col,
            executor=opts.executor, forward=forward, donate=opts.donate,
            algorithm=opts.algorithm,
            overlap_chunks=lp.options.overlap_chunks, batch=batch,
            wire_dtype=lp.options.wire_dtype,
        )

    if (in_spec is not None or out_spec is not None) and lp.mesh is None:
        raise ValueError("in_spec/out_spec require a mesh")
    in_sh, out_sh = _shardings(lp, spec, batch)
    in_world = world if forward else cworld
    out_world = cworld if forward else world
    in_boxes, out_boxes = _boxes(lp, in_world, out_world)
    if in_spec is not None or out_spec is not None:
        fn, in_sh, out_sh = _wrap_user_layout(
            fn, lp.mesh, in_sh, out_sh, in_spec, out_spec, opts.donate,
            shape if forward else cshape, cshape if forward else shape,
        )
        if in_spec is not None:
            in_boxes = _layout_boxes(lp.mesh, in_spec, in_world)
        if out_spec is not None:
            out_boxes = _layout_boxes(lp.mesh, out_spec, out_world)
    bpfx = () if batch is None else (batch,)
    return Plan3D(
        shape=shape, direction=direction, dtype=dtype,
        decomposition=lp.decomposition, executor=opts.executor, mesh=lp.mesh,
        fn=fn, spec=spec, in_sharding=in_sh, out_sharding=out_sh,
        in_boxes=in_boxes, out_boxes=out_boxes,
        in_shape=bpfx + (shape if forward else cshape),
        out_shape=bpfx + (cshape if forward else shape),
        in_dtype=rdtype if forward else dtype,
        out_dtype=dtype if forward else rdtype,
        real=True, batch=batch, options=lp.options, logic=lp,
    )


def plan_dft_c2r_3d(shape, mesh=None, **kw) -> Plan3D:
    """Convenience alias: the inverse of :func:`plan_dft_r2c_3d` (complex
    half-spectrum in, real out; heFFTe ``fft3d_r2c::backward``)."""
    kw.setdefault("direction", BACKWARD)
    return plan_dft_r2c_3d(shape, mesh, **kw)


def _swap_perm(axis: int) -> list[int]:
    """The self-inverse permutation swapping ``axis`` with 2 (one perm
    serves both directions of every transposed-view wrapper)."""
    perm = [0, 1, 2]
    perm[axis], perm[2] = perm[2], perm[axis]
    return perm


def _permute_spec3(s, perm):
    """Permute a (possibly short) 3-dim PartitionSpec by ``perm``."""
    if s is None:
        return None
    ent = tuple(s) + (None,) * (3 - len(tuple(s)))
    return P(*(ent[p] for p in perm))


def _permute_sharding3(sh, perm):
    return (None if sh is None
            else NamedSharding(sh.mesh, _permute_spec3(sh.spec, perm)))


def _chain_convention_note(e: Exception, axis: int) -> ValueError:
    return ValueError(
        f"{e} [note: r2c_axis={axis} plans run on a transposed view — "
        f"specs and extents in this message are in the chain "
        f"convention (axes {axis} and 2 swapped)]")


def _r2c_axis_wrapped(shape, mesh, axis: int, *, direction, decomposition,
                      executor, dtype, donate, algorithm, options, in_spec,
                      out_spec, overlap_chunks=None, tune=None,
                      wire_dtype=None, max_roundtrip_err=None,
                      mm_precision=None, mm_complex=None,
                      fuse=None) -> Plan3D:
    """r2c/c2r with the halved axis != 2 (heFFTe ``r2c_direction`` 0/1):
    the canonical chain (real axis = 2) runs on a transposed view.
    Caller-facing metadata — shapes, shardings, boxes — is permuted back
    to the caller's axis convention; ``spec``/``logic`` keep the inner
    chain's (transposed) convention, which ``plan_info`` labels. The
    swap permutation is its own inverse, so one ``perm`` serves both
    directions."""
    if axis not in (0, 1):
        raise ValueError(f"r2c_axis must be 0, 1, or 2; got {axis}")
    shape, forward = _check_direction(shape, direction)
    perm = _swap_perm(axis)
    pshape = tuple(shape[p] for p in perm)

    try:
        inner = plan_dft_r2c_3d(
            pshape, mesh, direction=direction, decomposition=decomposition,
            executor=executor, dtype=dtype, donate=donate,
            algorithm=algorithm, overlap_chunks=overlap_chunks, tune=tune,
            wire_dtype=wire_dtype, max_roundtrip_err=max_roundtrip_err,
            mm_precision=mm_precision, mm_complex=mm_complex, fuse=fuse,
            options=options,
            in_spec=_permute_spec3(in_spec, perm),
            out_spec=_permute_spec3(out_spec, perm),
        )
    except ValueError as e:
        raise _chain_convention_note(e, axis) from e

    inner_fn = inner.fn
    fn = jax.jit(
        lambda x: jnp.transpose(inner_fn(jnp.transpose(x, perm)), perm),
        donate_argnums=(0,) if inner.options.donate else (),
    )

    def permute_shape(s):
        return tuple(s[p] for p in perm)

    def permute_boxes(boxes):
        return [Box3(tuple(b.low[p] for p in perm),
                     tuple(b.high[p] for p in perm)) for b in boxes]

    return Plan3D(
        shape=shape, direction=direction, dtype=inner.dtype,
        decomposition=inner.decomposition, executor=inner.executor,
        mesh=inner.mesh, fn=fn, spec=inner.spec,
        in_sharding=_permute_sharding3(inner.in_sharding, perm),
        out_sharding=_permute_sharding3(inner.out_sharding, perm),
        in_boxes=permute_boxes(inner.in_boxes),
        out_boxes=permute_boxes(inner.out_boxes),
        in_shape=permute_shape(inner.in_shape),
        out_shape=permute_shape(inner.out_shape),
        in_dtype=inner.in_dtype, out_dtype=inner.out_dtype,
        real=True, r2c_axis=axis, options=inner.options, logic=inner.logic,
    )


@dataclass
class DDPlan3D:
    """A compiled 3D FFT plan at the emulated-f64 (double-double) tier.

    Same plan-owns-everything discipline as :class:`Plan3D`, but I/O is a
    (hi, lo) two-float pair — complex64 for c2c, float32 on the real side
    of r2c/c2r plans — carrying ~49 significand bits (the reference's
    f64 accuracy gate territory, ``test_common.h:138``; see
    :mod:`distributedfft_tpu.ops.ddfft`). Host conversion via
    ``dd_from_host`` / ``dd_to_host``.
    """

    shape: tuple[int, int, int]
    direction: int
    decomposition: str            # "single" | "slab" | "pencil"
    mesh: Mesh | None
    fn: Callable
    in_sharding: NamedSharding | None
    out_sharding: NamedSharding | None
    # Leading batch axis (both dd components carry it); None = unbatched.
    batch: int | None = None

    @property
    def forward(self) -> bool:
        return self.direction == FORWARD

    def __call__(self, hi, lo, *, scale: Scale = Scale.NONE):
        if _metrics._enabled:
            _metrics.inc("executes", kind="dd",
                         decomposition=self.decomposition, executor="dd")
        with add_trace(f"execute_dd_{self.decomposition}"):
            yh, yl = self.fn(hi, lo)
            if scale != Scale.NONE:
                yh, yl = _jitted_dd_scale()(
                    yh, yl, scale_factor(scale, math.prod(self.shape)))
        return yh, yl


@functools.lru_cache(maxsize=1)
def _jitted_dd_scale():
    """One compiled dd-scalar product per (shapes, scale) — scaled calls
    replay a fused kernel instead of eagerly dispatching the compensated
    chain (the plan-owns-everything discipline)."""
    from .ops import ddfft

    return jax.jit(ddfft.dd_scale, static_argnums=2)


def plan_dd_dft_c2c_3d(
    shape: Sequence[int],
    mesh: Mesh | int | None = None,
    *,
    direction: int = FORWARD,
    donate: bool = False,
    overlap_chunks: int | str | None = None,
    batch: int | None = None,
) -> DDPlan3D:
    """Create a 3D C2C FFT plan at the emulated double-precision tier.

    Single device (``mesh=None``) runs the dd engine whole-cube; a 1D
    mesh runs the dd slab pipeline, a 2D mesh the dd pencil pipeline
    (both dd components through the same collectives,
    :mod:`..parallel.ddslab`). The accuracy analog of the reference's
    f64 ``fft_mpi_plan_dft_c2c_3d`` on hardware without f64 (measured
    ~1e-13 forward / <1e-11 roundtrip). ``overlap_chunks`` pipelines
    each exchange under the downstream dd FFT exactly like the c64 tier
    (int K, ``"auto"``, or None -> ``DFFT_OVERLAP``). ``batch=B``
    coalesces B transforms into one device program with one shared pair
    of collectives per exchange (the :func:`plan_dft_c2c_3d` convention;
    both dd components carry the leading batch axis)."""
    from .ops import ddfft
    from .parallel.slab import batch_pspec as _bp

    shape, forward = _check_direction(shape, direction)
    batch = _norm_batch(batch)
    bo = 0 if batch is None else 1
    dn = (0, 1) if donate else ()
    if mesh is None:
        fn = jax.jit(
            functools.partial(ddfft.fftn_dd, axes=(bo, 1 + bo, 2 + bo),
                              forward=forward), donate_argnums=dn)
        return DDPlan3D(shape=shape, direction=direction,
                        decomposition="single", mesh=None, fn=fn,
                        in_sharding=None, out_sharding=None, batch=batch)
    if isinstance(mesh, int):
        from .parallel.mesh import make_mesh

        mesh = make_mesh(mesh)
    overlap = resolve_overlap_chunks(
        overlap_chunks, shape=shape, ndev=math.prod(mesh.devices.shape),
        itemsize=8 * (batch or 1))
    if len(mesh.axis_names) == 1:
        from .parallel.ddslab import build_dd_slab_fft3d

        fn, spec = build_dd_slab_fft3d(mesh, shape, forward=forward,
                                       axis_name=mesh.axis_names[0],
                                       donate=donate,
                                       overlap_chunks=overlap, batch=batch)
        return DDPlan3D(
            shape=shape, direction=direction, decomposition="slab",
            mesh=mesh, fn=fn,
            in_sharding=NamedSharding(mesh, _bp(spec.in_pspec, batch)),
            out_sharding=NamedSharding(mesh, _bp(spec.out_pspec, batch)),
            batch=batch,
        )
    if len(mesh.axis_names) == 2:
        from .parallel.ddslab import build_dd_pencil_fft3d

        row, col = mesh.axis_names[:2]
        fn, spec = build_dd_pencil_fft3d(
            mesh, shape, row_axis=row, col_axis=col, forward=forward,
            donate=donate, overlap_chunks=overlap, batch=batch)
        return DDPlan3D(
            shape=shape, direction=direction, decomposition="pencil",
            mesh=mesh, fn=fn,
            in_sharding=NamedSharding(mesh, _bp(spec.in_spec, batch)),
            out_sharding=NamedSharding(mesh, _bp(spec.out_spec, batch)),
            batch=batch,
        )
    raise ValueError("dd plans support single-device, 1D, or 2D meshes")


def plan_dd_brick_dft_c2c_3d(
    shape: Sequence[int],
    mesh: Mesh | int | None,
    in_boxes: Sequence[Box3],
    out_boxes: Sequence[Box3],
    *,
    direction: int = FORWARD,
    algorithm: str = "alltoall",
    donate: bool = False,
) -> DDPlan3D:
    """Arbitrary per-device brick I/O at the emulated-double tier —
    heFFTe's double-precision arbitrary-box capability
    (``heffte_fft3d.h:105-115`` at the f64 gate) on f32/bf16 hardware.

    Both dd components travel the same overlap-map transports as the
    c64 brick tier (each component is a complex64 stack), bracketing
    the distributed dd chain; ``Box3.order`` storage orders are honored
    on both sides. I/O is a pair of ``[P, *pad]`` stacks (use
    ``scatter_bricks`` on the host hi/lo parts from ``dd_from_host``).
    ``algorithm="alltoallv"`` selects the exact-count ragged transport
    for the brick edges."""
    shape, _ = _check_direction(shape, direction)
    inner = plan_dd_dft_c2c_3d(shape, mesh, direction=direction)
    return _dd_brick_wrap(inner, shape, shape, in_boxes, out_boxes,
                          algorithm, donate)


def plan_dd_brick_dft_r2c_3d(
    shape: Sequence[int],
    mesh: Mesh | int | None,
    in_boxes: Sequence[Box3],
    out_boxes: Sequence[Box3],
    *,
    direction: int = FORWARD,
    algorithm: str = "alltoall",
    donate: bool = False,
) -> DDPlan3D:
    """Real<->complex brick plan at the emulated double tier — heFFTe's
    ``fft3d_r2c`` arbitrary-box double capability. Forward: ``in_boxes``
    partition the real-space world ``shape`` (float32 dd stacks),
    ``out_boxes`` the axis-2-halved complex world; backward swaps the
    roles. Canonical ``r2c_axis=2`` only at this tier. ``donate`` is a
    documented no-op, as on every r2c plan: the real float32 and
    half-spectrum complex64 stacks can never alias."""
    del donate  # r2c buffers never alias (same contract as the non-brick
    #             dd r2c planner); donating would only warn per execute.
    shape, forward = _check_direction(shape, direction)
    half = tuple(shape[:2]) + (shape[2] // 2 + 1,)
    inner = plan_dd_dft_r2c_3d(shape, mesh, direction=direction)
    in_world, out_world = (shape, half) if forward else (half, shape)
    return _dd_brick_wrap(inner, in_world, out_world, in_boxes, out_boxes,
                          algorithm, donate=False)


def plan_dd_brick_dft_c2r_3d(shape, mesh, in_boxes, out_boxes,
                             **kw) -> DDPlan3D:
    """Convenience alias: the inverse of
    :func:`plan_dd_brick_dft_r2c_3d`."""
    kw.setdefault("direction", BACKWARD)
    return plan_dd_brick_dft_r2c_3d(shape, mesh, in_boxes, out_boxes, **kw)


def _dd_brick_wrap(inner: DDPlan3D, in_world, out_world, in_boxes,
                   out_boxes, algorithm: str, donate: bool) -> DDPlan3D:
    """Bracket a distributed dd plan with the brick edges (shared by the
    dd c2c and r2c brick planners; the dd analog of
    :func:`_wrap_brick_io`, sharing its edge construction)."""
    if inner.mesh is None or inner.in_sharding is None:
        _check_brick_algorithm(algorithm)
        edge_in, edge_out = _single_brick_edges(
            in_boxes, out_boxes, in_world, out_world)
        inner_fn1 = inner.fn

        @functools.partial(
            jax.jit, donate_argnums=(0, 1) if donate else ())
        def fn1(hi, lo):
            yh, yl = inner_fn1(edge_in(hi), edge_in(lo))
            return edge_out(yh), edge_out(yl)

        return DDPlan3D(
            shape=inner.shape, direction=inner.direction,
            decomposition=f"bricks-{inner.decomposition}", mesh=None,
            fn=fn1, in_sharding=None, out_sharding=None,
        )
    m = inner.mesh
    edge_in, edge_out, _, _ = _build_brick_edges(
        m, in_boxes, out_boxes, in_world, out_world,
        inner.in_sharding.spec, inner.out_sharding.spec, algorithm)
    inner_fn = inner.fn

    @functools.partial(
        jax.jit, donate_argnums=(0, 1) if donate else ())
    def fn(hi, lo):
        yh, yl = inner_fn(edge_in(hi), edge_in(lo))
        return edge_out(yh), edge_out(yl)

    names = tuple(m.axis_names)
    stack_sh = NamedSharding(m, P(names, None, None, None))
    return DDPlan3D(
        shape=inner.shape, direction=inner.direction,
        decomposition=f"bricks-{inner.decomposition}", mesh=m, fn=fn,
        in_sharding=stack_sh, out_sharding=stack_sh,
    )


def plan_dd_dft_r2c_3d(
    shape: Sequence[int],
    mesh: Mesh | int | None = None,
    *,
    direction: int = FORWARD,
    r2c_axis: int = 2,
    donate: bool = False,
    overlap_chunks: int | str | None = None,
    batch: int | None = None,
) -> DDPlan3D:
    """Real<->complex 3D plan at the emulated double tier — heFFTe's
    ``fft3d_r2c`` double gate on f32/bf16 hardware. ``shape`` is the
    real-space world; forward takes real float32 dd pairs and returns
    half-spectrum complex dd pairs (``r2c_axis`` — default 2, heFFTe's
    ``r2c_direction`` — shrunk to ``N//2+1``), backward inverts with
    numpy 1/N scaling. Single-device, 1D slab mesh, or 2D pencil mesh
    (the latter via ``build_dd_pencil_rfft3d``). Non-default
    ``r2c_axis`` runs the canonical chain on a transposed view of both
    dd components (the same discipline as :func:`plan_dft_r2c_3d`).
    ``donate`` is accepted for API symmetry but is a no-op here: real
    and half-spectrum buffers differ in dtype and size, so XLA can
    never alias them. ``batch=B`` coalesces B same-shape transforms
    into one program with one shared pair of collectives per exchange
    (the :func:`plan_dd_dft_c2c_3d` convention — both dd components
    carry the leading batch axis); canonical ``r2c_axis=2`` only."""
    from .ops import ddfft
    from .parallel.slab import batch_pspec as _bp

    batch = _norm_batch(batch)
    if r2c_axis != 2:
        if batch is not None:
            raise ValueError(
                "batched dd r2c plans run the canonical r2c_axis=2 chain; "
                "transpose the batch's world instead of passing r2c_axis")
        return _dd_r2c_axis_wrapped(shape, mesh, r2c_axis,
                                    direction=direction,
                                    overlap_chunks=overlap_chunks)
    shape, forward = _check_direction(shape, direction)
    # r2c/c2r buffers can never alias (f32 real world vs complex64
    # half-spectrum differ in dtype and size on every decomposition), so
    # donation would only emit unusable-donation warnings per execute:
    # accepted for API symmetry, documented no-op.
    del donate
    bo = 0 if batch is None else 1
    if mesh is None:
        if batch is None:
            if forward:
                fn = jax.jit(ddfft.rfftn_dd)
            else:
                fn = jax.jit(functools.partial(ddfft.irfftn_dd,
                                               n2=shape[2]))
        else:
            # Batched single-device tier: rfftn_dd/irfftn_dd transform
            # every leading axis, so the batched program spells the
            # spatial axes explicitly (same stage order — batch=1 and an
            # unadorned plan stay byte-identical via _norm_batch).
            h = shape[2] // 2 + 1

            def _rfft_b(hi, lo):
                from jax import lax as _lax

                chi = _lax.complex(hi, jnp.zeros_like(hi))
                clo = _lax.complex(lo, jnp.zeros_like(lo))
                chi, clo = ddfft.fft_axis_dd(chi, clo, 2 + bo)
                chi, clo = chi[..., :h], clo[..., :h]
                for ax in (bo, 1 + bo):
                    chi, clo = ddfft.fft_axis_dd(chi, clo, ax)
                return chi, clo

            def _irfft_b(hi, lo):
                for ax in (bo, 1 + bo):
                    hi, lo = ddfft.fft_axis_dd(hi, lo, ax, forward=False)
                hi, lo = ddfft.fft_axis_dd(
                    ddfft.mirror_half_spectrum(hi, shape[2], axis=2 + bo),
                    ddfft.mirror_half_spectrum(lo, shape[2], axis=2 + bo),
                    2 + bo, forward=False)
                return jnp.real(hi), jnp.real(lo)

            fn = jax.jit(_rfft_b if forward else _irfft_b)
        return DDPlan3D(shape=shape, direction=direction,
                        decomposition="single", mesh=None, fn=fn,
                        in_sharding=None, out_sharding=None, batch=batch)
    if isinstance(mesh, int):
        from .parallel.mesh import make_mesh

        mesh = make_mesh(mesh)
    overlap = resolve_overlap_chunks(
        overlap_chunks, shape=shape, ndev=math.prod(mesh.devices.shape),
        itemsize=8 * (batch or 1))
    if len(mesh.axis_names) == 1:
        from .parallel.ddslab import build_dd_slab_rfft3d

        fn, spec = build_dd_slab_rfft3d(mesh, shape, forward=forward,
                                        axis_name=mesh.axis_names[0],
                                        overlap_chunks=overlap,
                                        batch=batch)
        return DDPlan3D(
            shape=shape, direction=direction, decomposition="slab",
            mesh=mesh, fn=fn,
            in_sharding=NamedSharding(mesh, _bp(spec.in_pspec, batch)),
            out_sharding=NamedSharding(mesh, _bp(spec.out_pspec, batch)),
            batch=batch,
        )
    if len(mesh.axis_names) == 2:
        from .parallel.ddslab import build_dd_pencil_rfft3d

        row, col = mesh.axis_names[:2]
        fn, spec = build_dd_pencil_rfft3d(
            mesh, shape, row_axis=row, col_axis=col, forward=forward,
            overlap_chunks=overlap, batch=batch)
        return DDPlan3D(
            shape=shape, direction=direction, decomposition="pencil",
            mesh=mesh, fn=fn,
            in_sharding=NamedSharding(mesh, _bp(spec.in_spec, batch)),
            out_sharding=NamedSharding(mesh, _bp(spec.out_spec, batch)),
            batch=batch,
        )
    raise ValueError("dd r2c plans support single-device, 1D, or 2D meshes")


def plan_dd_dft_c2r_3d(shape, mesh=None, **kw) -> DDPlan3D:
    """Convenience alias: the inverse of :func:`plan_dd_dft_r2c_3d`."""
    kw.setdefault("direction", BACKWARD)
    return plan_dd_dft_r2c_3d(shape, mesh, **kw)


def _dd_r2c_axis_wrapped(shape, mesh, axis: int, *, direction,
                         overlap_chunks=None) -> DDPlan3D:
    """dd r2c/c2r with the halved axis != 2: the canonical chain runs on
    a transposed view of BOTH dd components; shapes and shardings are
    permuted back to the caller's convention (the
    :func:`_r2c_axis_wrapped` discipline at the dd tier)."""
    if axis not in (0, 1):
        raise ValueError(f"r2c_axis must be 0, 1, or 2; got {axis}")
    shape, _ = _check_direction(shape, direction)
    perm = _swap_perm(axis)
    pshape = tuple(shape[p] for p in perm)
    try:
        inner = plan_dd_dft_r2c_3d(pshape, mesh, direction=direction,
                                   overlap_chunks=overlap_chunks)
    except ValueError as e:
        raise _chain_convention_note(e, axis) from e

    inner_fn = inner.fn

    def fn(hi, lo):
        yh, yl = inner_fn(jnp.transpose(hi, perm), jnp.transpose(lo, perm))
        return jnp.transpose(yh, perm), jnp.transpose(yl, perm)

    return DDPlan3D(
        shape=shape, direction=direction, decomposition=inner.decomposition,
        mesh=inner.mesh, fn=jax.jit(fn),
        in_sharding=_permute_sharding3(inner.in_sharding, perm),
        out_sharding=_permute_sharding3(inner.out_sharding, perm),
    )


# ---------------------------------------------------------------- plan cache
# Plans are immutable (the reference's plan-owns-everything discipline) and
# expensive to build, so the public planners memoize on their full argument
# set. The key also carries every trace-time env knob that changes what a
# plan would compile to (DFFT_MM_*, DFFT_PALLAS_*, ...) plus the x64 flag —
# two calls that could compile different programs never share an entry.
# DFFT_PLAN_CACHE=0 disables; unhashable arguments bypass silently.

_PLAN_CACHE: dict = {}
_PLAN_CACHE_MAX = 128  # plans hold compiled executables; bound the HBM/host
_PLAN_ENV_KNOBS = (
    "DFFT_AUTO_EXECUTORS", "DFFT_MM_PRECISION", "DFFT_MM_COMPLEX",
    "DFFT_MM_SPLIT", "DFFT_MM_DIRECT_MAX", "DFFT_DD_DEPTH",
    "DFFT_PALLAS_PACK", "DFFT_PALLAS_SPLIT", "DFFT_PALLAS_TILE",
    "DFFT_PALLAS_TILE2D", "DFFT_PALLAS_TILE_STRIDED", "DFFT_XLA_REAL",
    "DFFT_FORCE_REAL_LOWERING", "DFFT_OVERLAP",
    # Executor routing: the default-executor escape hatch and the
    # XLA:CPU fft-thunk guard both change which executor a default
    # planner call builds with.
    "DFFT_EXECUTOR", "DFFT_THUNK_GUARD",
    # Tuned planning: mode, wisdom store, budget, and survivor cap all
    # change what a tuned planner call would build/measure — as do the
    # calibrated-profile path and its correction opt-out (they move the
    # pruning model's ranking).
    "DFFT_TUNE", "DFFT_WISDOM", "DFFT_TUNE_ITERS", "DFFT_TUNE_MAX",
    "DFFT_HW_PROFILE", "DFFT_TUNE_CORRECTION",
    # On-wire exchange compression: the default of PlanOptions.wire_dtype
    # resolves from the env at plan time, so two calls under different
    # wire modes compile different collective programs.
    "DFFT_WIRE_DTYPE",
    # Pallas fusion tier: the default of PlanOptions.fuse resolves from
    # the env at plan time (fused chains compile a different program —
    # codec moved out of the transport into the stage kernels).
    "DFFT_FUSE",
)


def clear_plan_cache() -> None:
    """Drop every memoized plan (tuning sweeps that mutate env knobs
    outside ``_PLAN_ENV_KNOBS``, tests)."""
    _PLAN_CACHE.clear()


def _plan_cache_key(kind: str, shape, mesh, kw: dict):
    """Hashable cache key, or None when caching is off / impossible."""
    if os.environ.get("DFFT_PLAN_CACHE", "1") == "0":
        return None
    key = (
        kind, shape, mesh, tuple(sorted(kw.items())),
        bool(jax.config.jax_enable_x64),
        tuple(os.environ.get(v, "") for v in _PLAN_ENV_KNOBS),
    )
    try:
        hash(key)
    except TypeError:
        return None
    return key


def _timed_build(kind: str, build: Callable, shape, mesh, kw: dict):
    # Fault-injection point "plan": a cache miss is about to construct a
    # plan (docs/ROBUSTNESS.md; cache hits replay an already-built plan
    # and are not a build). The label lets match= target one executor.
    _faults.check("plan", str(kw.get("executor") or ""))
    t0 = time.perf_counter()
    plan = build(shape, mesh, **kw)
    if _metrics._enabled:
        _metrics.observe(
            "plan_build_seconds", time.perf_counter() - t0, kind=kind)
        _metrics.inc(
            "plan_builds", kind=kind, decomposition=plan.decomposition,
            executor=getattr(plan, "executor", "dd"))
    return plan


def _plan_cached(kind: str, build: Callable) -> Callable:
    """Memoizing wrapper applied to each public planner below."""

    @functools.wraps(build)
    def wrapper(shape, mesh=None, **kw):
        shape = tuple(int(s) for s in shape)
        key = _plan_cache_key(kind, shape, mesh, kw)
        if key is None:
            return _timed_build(kind, build, shape, mesh, kw)
        plan = _PLAN_CACHE.get(key)
        if plan is not None:
            if _metrics._enabled:
                _metrics.inc("plan_cache_hits", kind=kind)
            return plan
        if _metrics._enabled:
            _metrics.inc("plan_cache_misses", kind=kind)
        plan = _PLAN_CACHE[key] = _timed_build(kind, build, shape, mesh, kw)
        while len(_PLAN_CACHE) > _PLAN_CACHE_MAX:
            _PLAN_CACHE.pop(next(iter(_PLAN_CACHE)))
        return plan

    return wrapper


plan_dft_c2c_3d = _plan_cached("c2c", plan_dft_c2c_3d)
plan_dft_r2c_3d = _plan_cached("r2c", plan_dft_r2c_3d)
plan_dd_dft_c2c_3d = _plan_cached("dd_c2c", plan_dd_dft_c2c_3d)
plan_dd_dft_r2c_3d = _plan_cached("dd_r2c", plan_dd_dft_r2c_3d)


def _plan_exchange_bytes(plan: Plan3D) -> tuple[int, int]:
    """(true, wire) bytes one execution of ``plan`` moves between
    devices: chain exchanges per ``plan_logic.exchange_payloads`` under
    the plan's own algorithm, plus any brick-edge ring/a2av traffic.
    Computed once and cached on the plan object, so the per-execute
    metrics hook is a dict lookup."""
    cached = getattr(plan, "_exchange_bytes", None)
    if cached is not None:
        return cached
    import numpy as np

    true_b = wire_b = 0
    lp = plan.logic
    if lp is not None and lp.mesh is not None:
        from .parallel.exchange import WIRE_BYTE_KEYS
        from .plan_logic import exchange_payloads

        shape_eff = plan.out_shape if (plan.real and plan.forward) else (
            plan.in_shape if plan.real else plan.shape)
        if plan.batch is not None and len(shape_eff) == 4:
            # exchange_payloads takes the per-transform 3D shape; the
            # B-fold scaling rides on lp.batch inside it.
            shape_eff = shape_eff[1:]
        itemsize = np.dtype(plan.dtype).itemsize
        wire_key = WIRE_BYTE_KEYS[plan.options.algorithm]
        for e in exchange_payloads(lp, shape_eff, itemsize):
            true_b += e["true_bytes"]
            # wire_factor scales for on-wire compression (bf16 pairs
            # halve c64 wire bytes, int8 block-scaled pairs quarter
            # them, sidecar included); 1.0 on the exact wire.
            wire_b += int(e[wire_key] * e.get("wire_factor", 1.0))
    if plan.brick_edges is not None:
        itemsize = np.dtype(plan.dtype).itemsize
        for bs in plan.brick_edges:
            true_b += bs.payload_elems * itemsize
            wire_b += bs.wire_elems * itemsize
    plan._exchange_bytes = (true_b, wire_b)
    return true_b, wire_b


def execute(plan: Plan3D, x, *, scale: Scale = Scale.NONE):
    """Run a plan (``fft_mpi_execute_dft_3d_c2c``,
    ``fft_mpi_3d_api.cpp:181``). Accepts any array-like of the plan's global
    input shape; device placement follows the plan's input sharding.

    Telemetry: with tracing on, the whole call is the ``execute_*`` span
    and the chain's t0..t3 stage spans nest inside it (recorded when the
    plan's jit first traces; device-side they ride the profiler
    annotations). With metrics on, bumps the ``executes`` counter and the
    exchange true/wire byte counters. Both disabled (the default) cost
    one flag check each — no events, no allocations.
    """
    x = jnp.asarray(x, dtype=plan.in_dtype)
    if x.shape != plan.in_shape:
        raise ValueError(f"plan input shape is {plan.in_shape}, got {x.shape}")
    opname = getattr(plan, "op", "")
    if opname:
        kind = f"op_{opname}"  # fused spectral-operator execution
    elif plan.real:
        kind = "r2c" if plan.forward else "c2r"
    else:
        kind = "c2c"
    if _metrics._enabled:
        _metrics.inc("executes", kind=kind,
                     decomposition=plan.decomposition, executor=plan.executor)
        true_b, wire_b = _plan_exchange_bytes(plan)
        if true_b or wire_b:
            _metrics.inc("exchange_true_bytes", float(true_b))
            _metrics.inc("exchange_wire_bytes", float(wire_b))
    with add_trace(f"execute_{kind}_{plan.decomposition}"):
        # Fault-injection points (docs/ROBUSTNESS.md): "compile" fires
        # on a plan's FIRST execution (JAX compiles at first call),
        # "exchange" emulates a t2-exchange fault host-side for plans
        # that own one (a fault inside the compiled collective cannot
        # raise from XLA), "execute" on every dispatch. All three are
        # env-dict lookups when nothing is armed, and none touch the
        # traced program — the HLO is byte-identical either way.
        if not getattr(plan, "_warm", False):
            _faults.check("compile", plan.executor)
        if plan.mesh is not None:
            _faults.check("exchange", plan.options.algorithm)
        _faults.check("execute", plan.executor)
        y = plan.fn(x)
        plan._warm = True
        if scale != Scale.NONE:
            y = apply_scale(y, scale, plan.world_size)
    return y


def alloc_local(plan: Plan3D, fill=None):
    """Allocate a global array laid out per the plan's input sharding
    (``fft_mpi_alloc_local_memory``, ``fft_mpi_3d_api.h:73``).

    Uneven extents cannot be placed by ``device_put`` (equal-shard rule);
    there the array is returned unplaced and the plan's own pad/crop
    chain shards it on first execute — previously this raised, which
    silently failed every measured-tournament candidate (and
    ``executor="auto"``) on uneven shapes."""
    if fill is None:
        arr = jnp.zeros(plan.in_shape, plan.in_dtype)
    else:
        arr = jnp.asarray(fill, dtype=plan.in_dtype)
    if plan.in_sharding is not None and _spec_divides(
            plan.in_sharding.mesh, plan.in_sharding.spec, arr.shape):
        arr = jax.device_put(arr, plan.in_sharding)
    return arr


def explain(plan: Plan3D, **kw) -> dict:
    """Plan attribution record: the model/compiled/measured join per
    t0..t3 stage, with per-stage MFU, ICI utilization, whole-program
    cost/memory, and divergence flags (:mod:`.explain`). ``iters``
    controls the measured warm passes; ``measure=False`` skips every
    execution; ``device_timing=True`` attributes stages from the
    ``jax.profiler`` device timeline (host-bracket fallback);
    ``allgather=True`` merges per-host stage medians (collective).
    Render with :func:`.explain.format_explain`, or use the
    ``report explain`` subcommand / ``speed3d -explain`` drivers."""
    from .explain import explain as _explain_impl

    return _explain_impl(plan, **kw)


def destroy_plan(plan: Plan3D) -> None:
    """Parity shim for ``fft_mpi_destroy_plan`` — plans hold no manually
    managed device memory; XLA buffers are garbage collected."""
    del plan
