"""Calibrated hardware profiles — measured per-chip constants.

Every model surface in this repo (the tuner's pruning model, the
explain layer's divergence gate, the roofline rows) runs on hardware
constants, and until now those were *datasheet* numbers — the
``DEVICE_SPECS`` table for known TPU kinds, cross-platform ranking
magnitudes for everything else (``explain.device_profile()`` reports
``source: "table"`` or ``"default"``). AccFFT (arXiv 1506.07933) and
the HPX collectives benchmark (arXiv 2504.03657) both calibrate their
communication models against measured link bandwidth before attributing
anything; a divergence flag computed against a datasheet constant says
as much about the constant as about the code.

This module closes that gap with short microbenchmarks:

- **HBM bandwidth** — a jitted device-to-device copy of a block large
  enough to stream (read + write per pass), timed amortized.
- **Matmul peak** — one square matmul sized to saturate the MXU (or the
  host's GEMM on CPU), ``2 n^3`` flops over the amortized time.
- **ICI link bandwidth** — a ``ppermute`` ring shift of per-device
  blocks across the mesh (every device ships its block one hop — the
  per-link number the exchange model wants), multi-device only.
- **Launch overhead** — a trivial jitted op round-tripped through
  :func:`..utils.timing.sync`: the fixed per-collective cost floor.

The resulting profile persists as JSON next to the tuner's wisdom store
(``<compile cache dir>/hwprofile.json``; ``DFFT_HW_PROFILE`` overrides,
empty/``0`` disables) — same lifecycle: derived, hardware-keyed, safe
to delete. ``explain.device_profile()`` consumes a matching profile and
reports ``source: "calibrated"``; ``tuner.model_cost`` applies the
profile's per-transport ``model_correction`` factors (the persisted
``tune_model_measured_ratio`` feedback loop) when ranking candidates.

CLI: ``python -m distributedfft_tpu.report calibrate`` (see
docs/OBSERVABILITY.md "Calibration").
"""

from __future__ import annotations

import json
import math
import os
import time

__all__ = [
    "PROFILE_SCHEMA",
    "default_profile_path",
    "load_profile",
    "matching_profile",
    "write_profile",
    "update_model_correction",
    "model_correction",
    "calibrate",
    "format_profile",
]

PROFILE_SCHEMA = 1

#: Per-device block the bandwidth/peak microbenchmarks stream —
#: large enough to leave caches on any current chip, small enough to
#: fit the CPU test backend comfortably.
_HBM_BYTES = 64 * 1024 * 1024
_MM_N = 1024
_WIRE_BYTES = 8 * 1024 * 1024


def default_profile_path() -> str | None:
    """The hardware-profile path: ``DFFT_HW_PROFILE`` when set (empty or
    ``0`` disables the profile entirely -> None), else
    ``hwprofile.json`` under the persistent compile-cache directory —
    the same home (and lifecycle) as the tuner's wisdom store."""
    env = os.environ.get("DFFT_HW_PROFILE")
    if env is not None:
        env = env.strip()
        return None if env in ("", "0") else env
    from .utils.cache import compile_cache_dir

    return os.path.join(compile_cache_dir(), "hwprofile.json")


# Loaded-profile cache keyed (path, mtime) so the per-candidate
# model_cost calls of a pruning pass do not re-read the file.
_cache: tuple[str, float, dict | None] | None = None


def load_profile(path: str | None = None) -> dict | None:
    """The stored profile document, or None (disabled store, missing or
    malformed file — never a raise). Cached by file mtime."""
    global _cache
    if path is None:
        path = default_profile_path()
    if path is None:
        return None
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    if _cache is not None and _cache[0] == path and _cache[1] == mtime:
        return _cache[2]
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        doc = None
    if not isinstance(doc, dict):
        doc = None
    _cache = (path, mtime, doc)
    return doc


def _current_identity() -> tuple[str, str]:
    """(device_kind, platform) of the running backend; best-effort."""
    kind, platform = "unknown", "unknown"
    try:
        import jax

        platform = jax.default_backend()
        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — identity must work backendless
        pass
    return kind, platform


def matching_profile(path: str | None = None) -> dict | None:
    """The stored profile, but only when it was calibrated on THIS
    hardware (device_kind and platform both match) — a v5e profile must
    never price a v4's exchanges, and a TPU profile never the CPU test
    backend's."""
    prof = load_profile(path)
    if prof is None:
        return None
    kind, platform = _current_identity()
    if prof.get("device_kind") != kind or prof.get("platform") != platform:
        return None
    return prof


def write_profile(profile: dict, path: str | None = None) -> str | None:
    """Write (replace) the profile document; returns the path, or None
    when the store is disabled. Atomic rename so a concurrently reading
    ``model_cost`` never sees a half-written file."""
    global _cache
    if path is None:
        path = default_profile_path()
    if path is None:
        return None
    from .utils.atomicio import replace_file

    replace_file(path,
                 json.dumps(profile, sort_keys=True, indent=1) + "\n")
    _cache = None
    return path


def update_model_correction(
    ratios: dict[str, float], path: str | None = None,
) -> dict | None:
    """Merge measured/model ratios per transport into the profile's
    ``model_correction`` block — the persisted
    ``tune_model_measured_ratio`` feedback the tuner's pruning reads
    back. A profile that does not exist yet gets a correction-only stub
    (no bandwidth fields, so ``device_profile()`` keeps reporting its
    uncalibrated source); an existing calibrated profile keeps every
    measured field. New ratios are blended 50/50 with stored ones so a
    single noisy tournament cannot swing the ranking."""
    ratios = {str(k): float(v) for k, v in ratios.items()
              if isinstance(v, (int, float)) and math.isfinite(v) and v > 0}
    if not ratios:
        return None
    if path is None:
        path = default_profile_path()
    if path is None:
        return None
    kind, platform = _current_identity()
    prof = load_profile(path)
    if (prof is None or prof.get("device_kind") != kind
            or prof.get("platform") != platform):
        prof = {"schema": PROFILE_SCHEMA, "device_kind": kind,
                "platform": platform}
    corr = dict(prof.get("model_correction") or {})
    for alg, r in ratios.items():
        old = corr.get(alg)
        corr[alg] = (0.5 * (float(old) + r)
                     if isinstance(old, (int, float)) and old > 0 else r)
    prof["model_correction"] = corr
    prof["correction_updated_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    write_profile(prof, path)
    return prof


def model_correction(algorithm: str, path: str | None = None) -> float:
    """The pruning model's per-transport correction factor (measured
    seconds / modeled seconds, persisted by the tuner's divergence
    audit) for ``algorithm`` on this hardware; 1.0 when no profile, no
    matching hardware, or no stored ratio. Clamped to [0.1, 10] — a
    correction beyond one order of magnitude means the profile is
    garbage, not that the model is."""
    prof = matching_profile(path)
    if prof is None:
        return 1.0
    corr = prof.get("model_correction")
    if not isinstance(corr, dict):
        return 1.0
    r = corr.get(str(algorithm))
    if not isinstance(r, (int, float)) or not math.isfinite(r) or r <= 0:
        return 1.0
    return min(10.0, max(0.1, float(r)))


# -------------------------------------------------------- microbenchmarks

def _measure_hbm_gbps(iters: int) -> float | None:
    """Streamed device copy: one pass reads and writes the block once,
    so bytes-per-pass = 2x the block."""
    import jax
    import jax.numpy as jnp

    from .utils.timing import time_fn_amortized

    n = _HBM_BYTES // 4
    x = jnp.zeros((n,), jnp.float32)

    @jax.jit
    def stream(v):
        return v + 1.0

    t, _ = time_fn_amortized(stream, x, iters=iters, repeats=2)
    return (2.0 * _HBM_BYTES / t) / 1e9 if t > 0 else None


def _measure_peak_tflops(iters: int) -> float | None:
    """One square matmul, ``2 n^3`` flops. bf16 inputs on TPU (the MXU's
    native feed), f32 elsewhere."""
    import jax
    import jax.numpy as jnp

    dt = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    return _mm_tflops(iters, dt, jax.lax.Precision.DEFAULT)


def _mm_tflops(iters: int, dtype, precision) -> float | None:
    """Matmul TFlop/s at one (input dtype, lax precision) point — the
    shared microbenchmark behind the per-tier fields."""
    import jax
    import jax.numpy as jnp

    from .utils.timing import time_fn_amortized

    a = jnp.ones((_MM_N, _MM_N), dtype)

    @jax.jit
    def mm(v):
        return jnp.dot(v, v, precision=precision)

    t, _ = time_fn_amortized(mm, a, iters=iters, repeats=2)
    return (2.0 * _MM_N ** 3 / t) / 1e12 if t > 0 else None


def _measure_mm_tier_tflops(iters: int) -> tuple[float | None, float | None]:
    """Per-precision-tier matmul rates ``(mm_bf16_tflops,
    mm_f32_tflops)`` — the two measured points the tuner's
    precision-tier cost model prices candidates with
    (:func:`..tuner.mm_tier_tflops`; the exact tier derives as half the
    f32 rate — 6 passes vs 3). bf16 inputs at DEFAULT precision = the
    one-pass MXU feed of the ``matmul:bf16`` executor tier; f32 inputs
    at HIGHEST = the multi-pass f32-exact contraction of the bare
    executor's contractions."""
    import jax
    import jax.numpy as jnp

    bf16 = _mm_tflops(iters, jnp.bfloat16, jax.lax.Precision.DEFAULT)
    f32 = _mm_tflops(iters, jnp.float32, jax.lax.Precision.HIGHEST)
    return bf16, f32


def _measure_axis_gbps(iters: int, mesh, axis_name: str) -> float | None:
    """Per-link bandwidth along ONE mesh axis: a one-hop ``ppermute``
    ring shift on that axis — every device ships its whole block to its
    axis-neighbor, so per-device wire bytes = block bytes and seconds
    are one link's serialization time. None when the axis has a single
    member (nothing to measure)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .utils.timing import time_fn_amortized

    parts = int(mesh.shape[axis_name])
    if parts < 2:
        return None
    n = _WIRE_BYTES // 4
    spec = P(axis_name, None)
    x = jax.device_put(jnp.zeros((parts, n), jnp.float32),
                       NamedSharding(mesh, spec))

    @jax.jit
    def shift(v):
        def body(blk):
            perm = [(i, (i + 1) % parts) for i in range(parts)]
            return jax.lax.ppermute(blk, axis_name, perm)

        return shard_map(body, mesh=mesh, in_specs=spec,
                         out_specs=spec)(v)

    t, _ = time_fn_amortized(shift, x, iters=iters, repeats=2)
    return (_WIRE_BYTES / t) / 1e9 if t > 0 else None


def _measure_wire_gbps(iters: int) -> float | None:
    """The flat (whole-mesh) per-link figure: one ring over every
    device. None on a single device."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 2:
        return None
    return _measure_axis_gbps(iters, Mesh(devs, ("d",)), "d")


def _measure_leg_gbps(iters: int) -> tuple[float | None, float | None]:
    """Per-leg ``(ici_gbps, dcn_gbps)`` for the hierarchical two-leg
    exchange model. Multi-process (a real DCN boundary exists): each
    figure is a ring shift along its own axis of the hybrid
    (dcn x ici) mesh — the intra-slice ICI links and the inter-slice
    DCN links measured separately. Single-process: every link is ICI,
    so ``ici_gbps`` is the flat figure and the DCN entry is null (the
    model then falls back to its DCN ranking constant)."""
    import jax

    if jax.process_count() < 2:
        return _measure_wire_gbps(iters), None
    from .parallel.multihost import make_hybrid_mesh

    mesh = make_hybrid_mesh()
    ici = _measure_axis_gbps(iters, mesh, mesh.axis_names[1])
    dcn = _measure_axis_gbps(iters, mesh, mesh.axis_names[0])
    return ici, dcn


def _measure_fuse_speedup(iters: int) -> float | None:
    """Fused-vs-unfused stage-pair throughput: the measured speedup of
    ONE ``pallas:fuse`` mega-kernel (stage FFT + wire encode in a single
    launch, intermediate kept in VMEM) over the unfused chain (Pallas
    FFT to HBM, then the codec's encode pass re-reading it) on a
    representative stage block. ``> 1`` means the fusion tier's HBM
    round-trip saving is real on this chip — the number the pruning
    model's ``(1 + wire_factor)/2`` stage discount claims. TPU only:
    off-TPU the Pallas kernels run interpreted and the ratio would
    measure the Python interpreter, so the field stays null (consumers
    treat null as "model discount unverified")."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return None
    from .ops import pallas_fft, pallas_fuse
    from .parallel.exchange import wire_codec
    from .utils.timing import time_fn_amortized

    rows, n, tiles = 256, 512, 8
    if pallas_fuse.kernel_ineligible(
            (rows, n), 1, 1, tiles, jnp.complex64, "split") is not None:
        return None
    x = jnp.ones((rows, n), jnp.complex64)
    codec = wire_codec("split")

    @jax.jit
    def unfused(v):
        y = pallas_fft.fft_along_axis(v, axis=1, forward=True)
        return codec.encode(y, tile_axis=1, tiles=tiles)

    @jax.jit
    def fused(v):
        return pallas_fuse.fused_fft_encode(
            v, fft_axis=1, forward=True, tile_axis=1, tiles=tiles,
            wire_dtype="split")

    tu, _ = time_fn_amortized(unfused, x, iters=iters, repeats=2)
    tf, _ = time_fn_amortized(fused, x, iters=iters, repeats=2)
    return tu / tf if tu > 0 and tf > 0 else None


def _measure_launch_seconds(iters: int) -> float | None:
    """Fixed per-dispatch cost: a trivial jitted op, synced per call —
    the launch + host round-trip floor the exchange model charges per
    collective step."""
    import jax
    import jax.numpy as jnp

    from .utils.timing import sync

    @jax.jit
    def tiny(v):
        return v + 1.0

    x = jnp.zeros((8,), jnp.float32)
    sync(tiny(x))  # compile
    best = math.inf
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        sync(tiny(x))
        best = min(best, time.perf_counter() - t0)
    return best if math.isfinite(best) else None


def calibrate(iters: int = 10, *, wire: bool = True) -> dict:
    """Run the microbenchmarks and return a profile document (nothing is
    written — pair with :func:`write_profile`). Fields a benchmark
    cannot produce (single-device wire, a failed measurement) are None;
    the consumers fall back per-field. Never raises past a working
    backend: each microbenchmark failure nulls its field."""
    import jax

    kind, platform = _current_identity()
    prof: dict = {
        "schema": PROFILE_SCHEMA,
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "device_kind": kind,
        "platform": platform,
        "ndev": len(jax.devices()),
        "jax": jax.__version__,
    }
    for field, fn in (
        ("hbm_gbps", lambda: _measure_hbm_gbps(iters)),
        ("peak_tflops", lambda: _measure_peak_tflops(iters)),
        ("wire_gbps", (lambda: _measure_wire_gbps(iters)) if wire
         else (lambda: None)),
        ("launch_seconds", lambda: _measure_launch_seconds(iters)),
        # Fused stage-pair tier: measured mega-kernel vs unfused-chain
        # speedup (null off-TPU — the tier only compiles natively there).
        ("fuse_speedup", lambda: _measure_fuse_speedup(iters)),
    ):
        try:
            prof[field] = fn()
        except Exception:  # noqa: BLE001 — one sick benchmark nulls its
            prof[field] = None  # field, never the whole calibration
    # Per-precision-tier matmul rates: the measured bf16 vs f32(-exact)
    # MXU throughput the precision-tier cost model prices the
    # matmul:bf16 / matmul:f32 / bare executor candidates with.
    try:
        bf16, f32 = _measure_mm_tier_tflops(iters)
    except Exception:  # noqa: BLE001
        bf16 = f32 = None
    prof["mm_bf16_tflops"] = bf16
    prof["mm_f32_tflops"] = f32
    # Per-leg link bandwidths for the hierarchical two-leg exchange
    # model: multi-process jobs measure the intra-slice ICI axis and the
    # inter-slice DCN axis separately (each leg priced on its own
    # fabric); single-process, every link is ICI — the flat figure
    # stands in and the DCN entry stays null (consumers fall back to
    # the ranking constant).
    try:
        if not wire:
            ici = dcn = None
        elif jax.process_count() < 2:
            ici, dcn = prof.get("wire_gbps"), None
        else:
            ici, dcn = _measure_leg_gbps(iters)
    except Exception:  # noqa: BLE001
        ici = dcn = None
    prof["ici_gbps"] = ici
    prof["dcn_gbps"] = dcn
    # Carry forward corrections an earlier tournament already persisted
    # for this hardware — calibration refreshes constants, it must not
    # amnesia the feedback loop.
    prev = matching_profile()
    if prev is not None and isinstance(prev.get("model_correction"), dict):
        prof["model_correction"] = prev["model_correction"]
    return prof


def format_profile(prof: dict) -> str:
    """One-line-per-field human rendering of a profile document."""
    def num(v, unit):
        return "-" if v is None else f"{v:.6g} {unit}"

    lines = [
        f"device: {prof.get('device_kind')} ({prof.get('platform')}, "
        f"{prof.get('ndev', '?')} device(s))",
        f"hbm bandwidth:  {num(prof.get('hbm_gbps'), 'GB/s')}",
        f"wire bandwidth: {num(prof.get('wire_gbps'), 'GB/s')}"
        + ("" if prof.get("wire_gbps") is not None
           else "  (single device: not measurable)"),
        f"matmul peak:    {num(prof.get('peak_tflops'), 'TFlop/s')}",
        f"matmul bf16:    {num(prof.get('mm_bf16_tflops'), 'TFlop/s')}",
        f"matmul f32:     {num(prof.get('mm_f32_tflops'), 'TFlop/s')}",
        f"launch floor:   {num(prof.get('launch_seconds'), 's')}",
        f"fuse speedup:   {num(prof.get('fuse_speedup'), 'x')}"
        + ("" if prof.get("fuse_speedup") is not None
           else "  (TPU only: fused stage-pair tier unmeasured)"),
        f"ici leg:        {num(prof.get('ici_gbps'), 'GB/s')}",
        f"dcn leg:        {num(prof.get('dcn_gbps'), 'GB/s')}"
        + ("" if prof.get("dcn_gbps") is not None
           else "  (single process: no DCN boundary)"),
    ]
    corr = prof.get("model_correction")
    if isinstance(corr, dict) and corr:
        pairs = ", ".join(f"{k}={v:.3g}x" for k, v in sorted(corr.items()))
        lines.append(f"model correction: {pairs}")
    if prof.get("recorded_at"):
        lines.append(f"calibrated at: {prof['recorded_at']}")
    return "\n".join(lines)
