"""Transform-time C API bridge — the ``heffte_c`` surface for C callers.

heFFTe exposes its C++ transforms to C (and through it, Fortran) via
opaque plan handles and typed execute calls (``heffte_c.h:52-179``,
``src/heffte_c.cpp``). This framework's runtime is Python/JAX, so the
bridge runs the other way around: :func:`install_c_api` registers ctypes
trampolines into ``libdfft_native.so``'s function-pointer table, after
which any C/C++/Fortran code living in a Python-hosted process can call
the plain C ABI

.. code-block:: c

    long long dfft_plan_c2c_3d(long long nx, ny, nz, int direction);
    int       dfft_execute_c2c(long long plan, const float* in, float* out);
    void      dfft_destroy_plan_c(long long plan);

with interleaved complex64 buffers (C-order, full world per call). The
native side's ``dfft_c_selftest`` drives the complete plan → execute →
destroy lifecycle from compiled C — the proof the ABI carries a real
transform, not a Python detour (``tests/test_capi.py``).

Single-process scope: the C caller sees the whole world array; plans may
still be distributed over a local mesh (the bridge scatters/gathers
through the plan's shardings). Multi-host C drivers are out of scope —
the multi-host tier speaks Python (``parallel/multihost.py``).
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from . import native as _native

__all__ = ["install_c_api", "c_api_installed", "c_selftest"]

_lock = threading.Lock()
_installed = False
# The CFUNCTYPE objects must outlive every C call: ctypes callbacks are
# freed with their Python wrapper, and a dangling pointer in the native
# table would crash the next C caller.
_keepalive: list = []
_plans: dict[int, tuple] = {}
_next_id = 0

_PLAN_FN = ctypes.CFUNCTYPE(
    ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
    ctypes.c_longlong, ctypes.c_int)
_EXEC_FN = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_longlong, ctypes.POINTER(ctypes.c_float),
    ctypes.POINTER(ctypes.c_float))
_DESTROY_FN = ctypes.CFUNCTYPE(None, ctypes.c_longlong)


def install_c_api(mesh=None) -> bool:
    """Install the transform bridge into ``libdfft_native.so``.

    ``mesh`` (a Mesh, device count, or None for single-device) is the
    mesh every C-created plan runs on. Returns False when the native
    library is unavailable (no toolchain); True once C callers can use
    the ABI. Idempotent; a second call re-points the plan mesh. The
    native callback slots are atomics, so a reinstall can never be
    observed torn — but reinstalling while a C thread is inside
    ``dfft_execute_*`` may still run the *old* bridge once more; callers
    switching meshes must quiesce C-side executes first."""
    global _installed
    lib = _native._load()
    if lib is None:
        return False

    from . import api as _api

    @_PLAN_FN
    def _plan(nx, ny, nz, direction):
        global _next_id
        if min(nx, ny, nz) < 1 or direction not in (-1, 1):
            return -1  # C-side argument validation: no zero-extent plans
        try:
            p = _api.plan_dft_c2c_3d(
                (int(nx), int(ny), int(nz)), mesh, direction=int(direction),
                dtype=np.complex64)
        except Exception:
            return -1
        with _lock:
            pid = _next_id
            _next_id += 1
            _plans[pid] = (p, (int(nx), int(ny), int(nz)))
        return pid

    @_EXEC_FN
    def _exec(pid, in_ptr, out_ptr):
        with _lock:
            entry = _plans.get(int(pid))
        if entry is None:
            return 2
        plan, shape = entry
        n = shape[0] * shape[1] * shape[2]
        try:
            buf = np.ctypeslib.as_array(in_ptr, shape=(2 * n,))
            x = buf.view(np.complex64).reshape(shape)
            y = np.asarray(plan(x), dtype=np.complex64)
            out = np.ctypeslib.as_array(out_ptr, shape=(2 * n,))
            out.view(np.complex64).reshape(shape)[...] = y
        except Exception:
            return 3
        return 0

    @_DESTROY_FN
    def _destroy(pid):
        with _lock:
            _plans.pop(int(pid), None)

    lib.dfft_c_api_install.argtypes = [_PLAN_FN, _EXEC_FN, _DESTROY_FN]
    with _lock:
        # Append (never replace) under the lock: a reinstall must not
        # drop the trampolines an in-flight C call may still be using.
        _keepalive.extend([_plan, _exec, _destroy])
        lib.dfft_c_api_install(_plan, _exec, _destroy)
        _installed = True
    return True


def c_api_installed() -> bool:
    lib = _native._load()
    if lib is None or not _installed:
        return False
    lib.dfft_c_api_ready.restype = ctypes.c_int
    return bool(lib.dfft_c_api_ready())


def c_selftest(shape=(8, 6, 5)) -> float:
    """Run the native side's C-driven roundtrip (plan + execute + destroy
    all issued from compiled C). Returns the relative max error
    (negative = failure; see ``dfft_c_selftest``)."""
    lib = _native._load()
    if lib is None:
        return -1.0
    lib.dfft_c_selftest.restype = ctypes.c_double
    lib.dfft_c_selftest.argtypes = [ctypes.c_longlong] * 3
    return float(lib.dfft_c_selftest(*map(int, shape)))
