"""Transform-time C API bridge — the ``heffte_c`` surface for C callers.

heFFTe exposes its C++ transforms to C (and through it, Fortran) via
opaque plan handles and typed execute calls (``heffte_c.h:52-179``,
``src/heffte_c.cpp``). This framework's runtime is Python/JAX, so the
bridge runs the other way around: :func:`install_c_api` registers ctypes
trampolines into ``libdfft_native.so``'s function-pointer table, after
which any C/C++/Fortran code living in a Python-hosted process can call
the plain C ABI

.. code-block:: c

    long long dfft_plan_c2c_3d(long long nx, ny, nz, int direction);
    int       dfft_execute_c2c(long long plan, const float* in, float* out);
    void      dfft_destroy_plan_c(long long plan);

with interleaved complex64 buffers (C-order, full world per call). The
native side's ``dfft_c_selftest`` drives the complete plan → execute →
destroy lifecycle from compiled C — the proof the ABI carries a real
transform, not a Python detour (``tests/test_capi.py``).

The *typed* surface (heFFTe's full C type matrix, ``heffte_c.h:63,
141-179``) extends this through a second callback pair
(``dfft_c_api_install_typed``):

.. code-block:: c

    long long dfft_plan_r2c_3d(nx, ny, nz, direction, r2c_axis);
    int       dfft_execute_r2c / dfft_execute_c2r(plan, float*, float*);
    long long dfft_plan_z2z_3d(nx, ny, nz, direction);       /* double */
    int       dfft_execute_z2z(plan, double*, double*);
    long long dfft_plan_d2z_3d(nx, ny, nz, direction, axis); /* double r2c */
    int       dfft_execute_d2z / dfft_execute_z2d(plan, double*, double*);
    int       dfft_upload(plan, const void*);   /* plan-resident buffers */
    int       dfft_execute_resident(plan);
    int       dfft_download(plan, void*);

Double buffers are plain C doubles; the bridge splits them into (hi, lo)
float32 dd pairs (:mod:`.ops.ddfft`) and recombines on output — the
framework's f64 tier on f32/bf16 hardware. The resident-buffer ops keep
input/output on device between calls, so a C driver can repeat-execute
(the reference's warm + timed loop, ``fftSpeed3d_c2c.cpp:94-98``)
without a host round-trip per call.

Single-process scope: the C caller sees the whole world array; plans may
still be distributed over a local mesh (the bridge scatters/gathers
through the plan's shardings). Multi-host C drivers are out of scope —
the multi-host tier speaks Python (``parallel/multihost.py``).
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from . import native as _native

__all__ = ["install_c_api", "c_api_installed", "c_selftest",
           "c_selftest_r2c", "c_selftest_z2z", "c_selftest_resident"]

_lock = threading.Lock()
_installed = False
# The CFUNCTYPE objects must outlive every C call: ctypes callbacks are
# freed with their Python wrapper, and a dangling pointer in the native
# table would crash the next C caller.
_keepalive: list = []
# pid -> _Entry; shared by the v1 (c2c) and typed surfaces, so one
# destroy entry point serves every plan kind.
_plans: dict[int, "_Entry"] = {}
_next_id = 0

_PLAN_FN = ctypes.CFUNCTYPE(
    ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
    ctypes.c_longlong, ctypes.c_int)
_EXEC_FN = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_longlong, ctypes.POINTER(ctypes.c_float),
    ctypes.POINTER(ctypes.c_float))
_DESTROY_FN = ctypes.CFUNCTYPE(None, ctypes.c_longlong)
# Typed surface: plan2(kind, nx, ny, nz, direction, axis) and
# exec2(plan, op, in, out) — see the native dispatch table
# (dfft_c_api_install_typed) for the kind/op codes.
_PLAN2_FN = ctypes.CFUNCTYPE(
    ctypes.c_longlong, ctypes.c_int, ctypes.c_longlong, ctypes.c_longlong,
    ctypes.c_longlong, ctypes.c_int, ctypes.c_int)
_EXEC2_FN = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_longlong, ctypes.c_int, ctypes.c_void_p,
    ctypes.c_void_p)

_KIND_C2C_F, _KIND_R2C_F, _KIND_C2C_D, _KIND_R2C_D = 0, 1, 2, 3
_OP_EXEC, _OP_UPLOAD, _OP_RUN, _OP_DOWNLOAD = 0, 1, 2, 3


class _Entry:
    """Registry record for one C-created plan: the compiled plan, its
    host-buffer geometry, and (when used) the resident device buffers."""

    __slots__ = ("plan", "kind", "in_shape", "out_shape", "in_np",
                 "out_np", "resident_in", "resident_out")

    def __init__(self, plan, kind, in_shape, out_shape, in_np, out_np):
        self.plan = plan
        self.kind = kind
        self.in_shape = in_shape    # host logical shape of the input
        self.out_shape = out_shape  # host logical shape of the output
        self.in_np = in_np          # host numpy dtype of the input
        self.out_np = out_np        # host numpy dtype of the output
        self.resident_in = None
        self.resident_out = None


def install_c_api(mesh=None) -> bool:
    """Install the transform bridge into ``libdfft_native.so``.

    ``mesh`` (a Mesh, device count, or None for single-device) is the
    mesh every C-created plan runs on. Returns False when the native
    library is unavailable (no toolchain); True once C callers can use
    the ABI. Idempotent; a second call re-points the plan mesh. The
    native callback slots are atomics, so a reinstall can never be
    observed torn — but reinstalling while a C thread is inside
    ``dfft_execute_*`` may still run the *old* bridge once more; callers
    switching meshes must quiesce C-side executes first."""
    global _installed
    lib = _native._load()
    if lib is None:
        return False

    from . import api as _api

    def _half(shape, axis):
        s = list(shape)
        s[axis] = s[axis] // 2 + 1
        return tuple(s)

    def _make_entry(kind, shape, direction, axis):
        """Build the plan + host-geometry record for one C plan request."""
        fwd = direction == _api.FORWARD
        if kind == _KIND_C2C_F:
            p = _api.plan_dft_c2c_3d(shape, mesh, direction=direction,
                                     dtype=np.complex64)
            return _Entry(p, kind, shape, shape, np.complex64, np.complex64)
        if kind == _KIND_R2C_F:
            h = _half(shape, axis)
            if fwd:
                p = _api.plan_dft_r2c_3d(shape, mesh, r2c_axis=axis,
                                         dtype=np.complex64)
                return _Entry(p, kind, shape, h, np.float32, np.complex64)
            p = _api.plan_dft_c2r_3d(shape, mesh, r2c_axis=axis,
                                     dtype=np.complex64)
            return _Entry(p, kind, h, shape, np.complex64, np.float32)
        if kind == _KIND_C2C_D:
            p = _api.plan_dd_dft_c2c_3d(shape, mesh, direction=direction)
            return _Entry(p, kind, shape, shape, np.complex128,
                          np.complex128)
        if kind == _KIND_R2C_D:
            h = _half(shape, axis)
            if fwd:
                p = _api.plan_dd_dft_r2c_3d(shape, mesh, r2c_axis=axis)
                return _Entry(p, kind, shape, h, np.float64, np.complex128)
            p = _api.plan_dd_dft_c2r_3d(shape, mesh, r2c_axis=axis)
            return _Entry(p, kind, h, shape, np.complex128, np.float64)
        return None

    def _register(kind, nx, ny, nz, direction, axis):
        global _next_id
        if (min(nx, ny, nz) < 1 or direction not in (-1, 1)
                or axis not in (0, 1, 2) or not 0 <= kind <= 3):
            return -1  # C-side argument validation: no zero-extent plans
        try:
            entry = _make_entry(kind, (int(nx), int(ny), int(nz)),
                                int(direction), int(axis))
        except Exception:
            return -1
        if entry is None:
            return -1
        with _lock:
            pid = _next_id
            _next_id += 1
            _plans[pid] = entry
        return pid

    def _host_view(ptr, shape, np_dtype):
        """Reinterpret a C buffer pointer as the numpy array the entry's
        side declares (interleaved re/im floats or doubles for complex)."""
        n = int(np.prod(shape))
        if np.issubdtype(np_dtype, np.complexfloating):
            base = (ctypes.c_float if np_dtype == np.complex64
                    else ctypes.c_double)
            buf = np.ctypeslib.as_array(
                ctypes.cast(ptr, ctypes.POINTER(base)), shape=(2 * n,))
            return buf.view(np_dtype).reshape(shape)
        base = ctypes.c_float if np_dtype == np.float32 else ctypes.c_double
        return np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(base)), shape=(n,)).reshape(shape)

    def _to_device(entry, x_np):
        """Host array -> the plan's device-side input value. The plan's
        input sharding is a placement hint only: when it cannot apply
        (e.g. an r2c half-spectrum extent that does not divide a pencil
        mesh axis), the value is placed unsharded and the plan's own
        sharding constraints reshard on first use."""
        import jax

        from .ops import ddfft as _dd

        sh = getattr(entry.plan, "in_sharding", None)

        def put(a):
            if sh is not None:
                try:
                    return jax.device_put(a, sh)
                except ValueError:
                    pass
            return jax.device_put(a)

        if entry.kind in (_KIND_C2C_D, _KIND_R2C_D):
            hi, lo = _dd.dd_from_host(x_np)
            return (put(hi), put(lo))
        return put(x_np)

    def _run(entry, dev_in):
        if entry.kind in (_KIND_C2C_D, _KIND_R2C_D):
            return entry.plan(*dev_in)
        return entry.plan(dev_in)

    def _to_host(entry, dev_out):
        from .ops import ddfft as _dd

        if entry.kind in (_KIND_C2C_D, _KIND_R2C_D):
            return _dd.dd_to_host(*dev_out).astype(entry.out_np, copy=False)
        return np.asarray(dev_out, dtype=entry.out_np)

    @_PLAN_FN
    def _plan(nx, ny, nz, direction):
        return _register(_KIND_C2C_F, nx, ny, nz, direction, 2)

    @_EXEC_FN
    def _exec(pid, in_ptr, out_ptr):
        return _exec2(pid, _OP_EXEC,
                      ctypes.cast(in_ptr, ctypes.c_void_p),
                      ctypes.cast(out_ptr, ctypes.c_void_p))

    @_PLAN2_FN
    def _plan2(kind, nx, ny, nz, direction, axis):
        return _register(kind, nx, ny, nz, direction, axis)

    @_EXEC2_FN
    def _exec2(pid, op, in_ptr, out_ptr):
        with _lock:
            entry = _plans.get(int(pid))
        if entry is None:
            return 2
        try:
            if op == _OP_EXEC:
                x = _host_view(in_ptr, entry.in_shape, entry.in_np)
                y = _to_host(entry, _run(entry, _to_device(entry, x)))
                _host_view(out_ptr, entry.out_shape, entry.out_np)[...] = y
            elif op == _OP_UPLOAD:
                x = _host_view(in_ptr, entry.in_shape, entry.in_np)
                entry.resident_in = _to_device(entry, np.array(x))
                # A new upload invalidates any previous run's output —
                # downloading before the next execute must be an error
                # (code 5), never stale data with rc 0.
                entry.resident_out = None
            elif op == _OP_RUN:
                if entry.resident_in is None:
                    return 4
                from .utils.timing import sync

                entry.resident_out = _run(entry, entry.resident_in)
                sync(entry.resident_out)
            elif op == _OP_DOWNLOAD:
                if entry.resident_out is None:
                    return 5
                y = _to_host(entry, entry.resident_out)
                _host_view(out_ptr, entry.out_shape, entry.out_np)[...] = y
            else:
                return 6
        except Exception:
            return 3
        return 0

    @_DESTROY_FN
    def _destroy(pid):
        with _lock:
            _plans.pop(int(pid), None)

    lib.dfft_c_api_install.argtypes = [_PLAN_FN, _EXEC_FN, _DESTROY_FN]
    lib.dfft_c_api_install_typed.argtypes = [_PLAN2_FN, _EXEC2_FN]
    with _lock:
        # Append (never replace) under the lock: a reinstall must not
        # drop the trampolines an in-flight C call may still be using.
        _keepalive.extend([_plan, _exec, _destroy, _plan2, _exec2])
        lib.dfft_c_api_install(_plan, _exec, _destroy)
        lib.dfft_c_api_install_typed(_plan2, _exec2)
        _installed = True
    return True


def c_api_installed() -> bool:
    lib = _native._load()
    if lib is None or not _installed:
        return False
    lib.dfft_c_api_ready.restype = ctypes.c_int
    return bool(lib.dfft_c_api_ready())


def c_selftest(shape=(8, 6, 5)) -> float:
    """Run the native side's C-driven roundtrip (plan + execute + destroy
    all issued from compiled C). Returns the relative max error
    (negative = failure; see ``dfft_c_selftest``)."""
    lib = _native._load()
    if lib is None:
        return -1.0
    lib.dfft_c_selftest.restype = ctypes.c_double
    lib.dfft_c_selftest.argtypes = [ctypes.c_longlong] * 3
    return float(lib.dfft_c_selftest(*map(int, shape)))


def c_selftest_r2c(shape=(8, 6, 5), r2c_axis: int = 2) -> float:
    """C-driven r2c/c2r roundtrip through the typed ABI
    (``dfft_c_selftest_r2c``); negative = failure."""
    lib = _native._load()
    if lib is None:
        return -1.0
    lib.dfft_c_selftest_r2c.restype = ctypes.c_double
    lib.dfft_c_selftest_r2c.argtypes = [ctypes.c_longlong] * 3 + [
        ctypes.c_int]
    return float(lib.dfft_c_selftest_r2c(*map(int, shape), int(r2c_axis)))


def c_selftest_z2z(shape=(8, 6, 5)) -> float:
    """C-driven DOUBLE roundtrip (dd tier) through the typed ABI
    (``dfft_c_selftest_z2z``); the 1e-11 double gate from compiled C."""
    lib = _native._load()
    if lib is None:
        return -1.0
    lib.dfft_c_selftest_z2z.restype = ctypes.c_double
    lib.dfft_c_selftest_z2z.argtypes = [ctypes.c_longlong] * 3
    return float(lib.dfft_c_selftest_z2z(*map(int, shape)))


def c_selftest_resident(shape=(8, 6, 5), repeats: int = 3) -> float:
    """C-driven plan-resident lifecycle: upload once, execute
    ``repeats`` times device-side, download once, inverse, roundtrip
    error (``dfft_c_selftest_resident``); negative = failure."""
    lib = _native._load()
    if lib is None:
        return -1.0
    lib.dfft_c_selftest_resident.restype = ctypes.c_double
    lib.dfft_c_selftest_resident.argtypes = [ctypes.c_longlong] * 3 + [
        ctypes.c_int]
    return float(lib.dfft_c_selftest_resident(*map(int, shape),
                                              int(repeats)))
