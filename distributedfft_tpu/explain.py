"""Plan explain & attribution — predicted vs compiled vs measured.

The reference's only self-description is a flat t0..t3 wall-clock table
printed per execute (``fft_mpi_3d_api.cpp:184-201``); nothing in it can
say *why* a configuration is fast or slow. This module closes that gap
by joining, per ``t0..t3`` stage, the three views the repo already
produces but never correlates:

- **model** — the analytic prediction the tuner prunes with
  (:func:`..plan_logic.model_stage_seconds`: 3-pass HBM roofline stage
  times, wire bytes under the plan's transport via ``WIRE_BYTE_KEYS``,
  the overlap-K exposure crossover);
- **compiled** — what XLA actually built: per-stage
  ``compiled.cost_analysis()`` FLOPs / bytes accessed and
  ``memory_analysis()`` argument/output/temp HBM, plus AOT compile
  seconds (the separately-jitted staged pipelines give this per stage;
  the fused plan gives the whole-program view);
- **measured** — warm per-stage wall-clock samples (the PR 1 trace-span
  quantities, captured with the sync bracketing of the timing harness);
  with ``device_timing=True`` / ``DFFT_DEVICE_TIMING=1`` the samples
  come from the ``jax.profiler`` DEVICE timeline instead (per-chunk
  ``t2[k]``/``t3[k]`` rows under overlap-K; clean host-bracket fallback
  wherever device lanes don't exist — the CPU backend always), and
  ``allgather=True`` merges every host process's stage medians into
  min/median/max straggler rows (docs/OBSERVABILITY.md
  "Flight recorder").

plus per-stage MFU and ICI-utilization ratios, and **divergence flags**
wherever the model's prediction falls outside the measured samples'
median + MAD noise band (the PR 2 gate) — the audit loop AccFFT and the
Collective-Optimized-FFTs work close with per-stage communication
models, and the direct feedback signal for the tuner's prune quality.

Surfaces: ``dfft.explain(plan)`` (this module's :func:`explain`),
``python -m distributedfft_tpu.report explain`` (live plans or history
records), ``benchmarks/speed3d.py -explain``, and the
:func:`compiled_summary` cost/memory block that ``bench.py`` stamps
into run records so ``regress.py`` can baseline peak-HBM and
compile-time, not just wall time. See docs/OBSERVABILITY.md
"Explain & attribution".
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Sequence

import numpy as np

from . import regress
from .utils import metrics as _metrics
from .utils.timing import sync
from .utils.trace import OP_STAGE_KEYS, STAGE_KEYS, stage_key

__all__ = [
    "EXPLAIN_SCHEMA",
    "DEVICE_SPECS",
    "device_profile",
    "explain",
    "compiled_summary",
    "model_stage_estimates",
    "stage_divergence",
    "parse_device_trace",
    "device_stage_samples",
    "across_hosts_stages",
    "format_explain",
    "explain_from_record",
]

EXPLAIN_SCHEMA = 1

#: Public per-chip specs for attribution ratios: device_kind substring ->
#: (peak bf16 TFlop/s, HBM GB/s, per-link ICI GB/s estimate). The ICI
#: numbers are usable-bandwidth estimates of one link (the same magnitude
#: the tuner's ranking model assumes), not datasheet aggregates.
DEVICE_SPECS = {
    "v5 lite": (197.0, 819.0, 45.0),
    "v5e": (197.0, 819.0, 45.0),
    "v5p": (459.0, 2765.0, 90.0),
    "v5": (459.0, 2765.0, 90.0),
    "v4": (275.0, 1228.0, 45.0),
    "v6 lite": (918.0, 1640.0, 90.0),
    "v6e": (918.0, 1640.0, 90.0),
}

#: Divergence gate defaults — the PR 2 compare-engine noise model.
DEFAULT_MADS = regress.DEFAULT_MADS
DEFAULT_MIN_REL = regress.DEFAULT_MIN_REL
DEFAULT_MIN_SAMPLES = regress.DEFAULT_MIN_SAMPLES

_MB = 1.0 / (1024 * 1024)


def device_profile() -> dict:
    """The hardware constants the model side of the join runs on.

    A **calibrated** profile measured on this machine (``python -m
    distributedfft_tpu.report calibrate``; :mod:`.calibrate`) wins when
    its device_kind/platform match the running backend — divergence
    flags are then computed against measured, not datasheet, constants
    and ``source`` reports ``"calibrated"`` (with ``calibrated_at``).
    Otherwise known TPU kinds come from :data:`DEVICE_SPECS`
    (``source: "table"``); anything else (the CPU test backend included)
    falls back to the tuner's cross-platform ranking constants
    (``source: "default"``) — still useful for *ordering* stages, but
    divergence flags on a default profile say as much about the
    constants as about the code, and the record carries the source so
    readers can tell."""
    from .calibrate import matching_profile
    from .tuner import (
        MODEL_DCN_GBPS, MODEL_HBM_GBPS, MODEL_LAUNCH_SECONDS,
        MODEL_WIRE_GBPS,
    )

    kind, backend = "unknown", "unknown"
    try:
        import jax

        backend = jax.default_backend()
        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — profile must work backendless
        pass
    spec = next((v for k, v in DEVICE_SPECS.items() if k in kind.lower()),
                None)
    if spec is None:
        peak_tf, hbm, wire, source = (
            197.0, MODEL_HBM_GBPS, MODEL_WIRE_GBPS, "default")
    else:
        peak_tf, hbm, wire = spec
        source = "table"
    launch = MODEL_LAUNCH_SECONDS
    out = {
        "device_kind": kind,
        "backend": backend,
        "peak_tflops": peak_tf,
        "hbm_gbps": hbm,
        "wire_gbps": wire,
        # DCN (inter-slice) leg bandwidth for the hierarchical/hybrid
        # exchange model; the ranking default until a multi-process
        # calibration measures the real figure (single-process
        # calibrations store a null DCN entry).
        "dcn_gbps": MODEL_DCN_GBPS,
        "launch_seconds": launch,
        "source": source,
    }
    cal = matching_profile()
    if cal is not None and isinstance(cal.get("hbm_gbps"), (int, float)):
        # Per-field override: a single-device calibration cannot measure
        # wire bandwidth, so the table/default value stands in for the
        # fields the microbenchmarks could not produce.
        for field in ("hbm_gbps", "wire_gbps", "dcn_gbps", "peak_tflops",
                      "launch_seconds", "mm_bf16_tflops",
                      "mm_f32_tflops"):
            v = cal.get(field)
            if isinstance(v, (int, float)) and v > 0:
                out[field] = float(v)
        # Per-leg ICI figure: a hybrid-mesh calibration measures the
        # intra-slice axis on its own (calibrate._measure_leg_gbps); the
        # exchange model prices ICI legs with wire_gbps, so the leg
        # number wins over the flat whole-mesh ring figure.
        ici = cal.get("ici_gbps")
        if isinstance(ici, (int, float)) and ici > 0:
            out["wire_gbps"] = float(ici)
        out["source"] = "calibrated"
        if cal.get("recorded_at"):
            out["calibrated_at"] = cal["recorded_at"]
    return out


# ---------------------------------------------------------------- model

def _model_shape_itemsize(plan) -> tuple[tuple[int, int, int], int]:
    """The complex-side shape and itemsize the exchange/roofline model
    runs on — the same effective-shape rule the per-execute byte
    counters use (``api._plan_exchange_bytes``)."""
    shape = plan.out_shape if (plan.real and plan.forward) else (
        plan.in_shape if plan.real else plan.shape)
    if getattr(plan, "batch", None) is not None and len(shape) == 4:
        # The model takes the per-transform 3D shape; the B-fold scaling
        # rides on the plan's LogicPlan.batch inside model_stage_seconds.
        shape = shape[1:]
    return tuple(shape), int(np.dtype(plan.dtype).itemsize)


def model_stage_estimates(plan, hw: dict | None = None) -> dict:
    """Per-stage analytic predictions of one execution of ``plan``,
    keyed exactly ``t0..t3`` (:func:`..plan_logic.model_stage_seconds`
    on the plan's own logic skeleton and hardware profile). When a
    calibrated profile stores a ``model_correction`` ratio for the
    plan's transport, the exchange prediction is scaled by it — the
    divergence gate then judges the model *after* its own persisted
    feedback."""
    from .calibrate import model_correction
    from .plan_logic import fused_model_stages, model_stage_seconds
    from .tuner import mm_tier_tflops

    hw = hw or device_profile()
    lp = plan.logic
    if lp is None:
        raise ValueError("plan carries no logic skeleton to model")
    shape, itemsize = _model_shape_itemsize(plan)
    oc = plan.options.overlap_chunks
    return model_stage_seconds(
        lp, shape, itemsize,
        hbm_gbps=hw["hbm_gbps"], wire_gbps=hw["wire_gbps"],
        launch_seconds=hw["launch_seconds"],
        dcn_gbps=hw.get("dcn_gbps"),
        algorithm=plan.options.algorithm,
        overlap_chunks=oc if isinstance(oc, int) else 1,
        exchange_correction=model_correction(plan.options.algorithm),
        # Measured realized-overlap feedback: the monitor's overlap
        # attribution persists measured/model hide ratios under this
        # key (1.0 until a measurement lands).
        hide_correction=model_correction("leg_hide"),
        # Matmul-family plans price their FFT stages at the executor
        # tier's MXU rate (calibrated mm_*_tflops fields win inside
        # mm_tier_tflops); None for every other executor keeps the pure
        # HBM roofline byte-identical.
        mm_tflops=mm_tier_tflops(plan.executor),
        # Fused stage pairs keep the intermediate in VMEM: the stages
        # the fusion pass actually collapses for this plan shape are
        # priced without their inter-stage c64 HBM stream (empty tuple
        # for every unfused plan keeps the roofline byte-identical).
        fused=fused_model_stages(lp, shape, itemsize),
    )


# ------------------------------------------------------------- compiled

def _cost_dict(compiled) -> dict:
    """Flatten ``compiled.cost_analysis()`` (a dict, or the older
    one-element list of dicts) to {flops, bytes_accessed}; absent keys
    -> None, never a raise."""
    out = {"flops": None, "bytes_accessed": None}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            if "flops" in ca:
                out["flops"] = float(ca["flops"])
            if "bytes accessed" in ca:
                out["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:  # noqa: BLE001 — analysis is best-effort
        pass
    return out


def _memory_dict(compiled) -> dict:
    """``compiled.memory_analysis()`` as plain numbers. ``peak_hbm
    _bytes`` is the argument+output+temp sum — the live-buffer
    footprint one execution holds at once (the ``getMaxDataCount``
    sizing role), an estimate: XLA's true high-water mark can be lower
    when buffers alias."""
    out = {"argument_bytes": None, "output_bytes": None,
           "temp_bytes": None, "generated_code_bytes": None,
           "peak_hbm_bytes": None}
    try:
        ma = compiled.memory_analysis()
        arg = int(ma.argument_size_in_bytes)
        outb = int(ma.output_size_in_bytes)
        tmp = int(ma.temp_size_in_bytes)
        out.update(
            argument_bytes=arg, output_bytes=outb, temp_bytes=tmp,
            generated_code_bytes=int(ma.generated_code_size_in_bytes),
            peak_hbm_bytes=arg + outb + tmp,
        )
    except Exception:  # noqa: BLE001
        pass
    return out


def _compile_analysis(jitted, arg) -> dict | None:
    """AOT-lower and compile one jitted callable at ``arg``'s aval and
    return its cost/memory/compile-seconds view, or None when the
    callable cannot be lowered (not a jit, tracing failure, ...)."""
    lower = getattr(jitted, "lower", None)
    if lower is None:
        return None
    try:
        t0 = time.perf_counter()
        compiled = lower(arg).compile()
        dt = time.perf_counter() - t0
    except Exception:  # noqa: BLE001 — explain must survive any plan
        return None
    out = {"available": True, "compile_seconds": dt}
    out.update(_cost_dict(compiled))
    out.update(_memory_dict(compiled))
    return out


_UNAVAILABLE = {"available": False}


def compiled_summary(plan, x=None) -> dict | None:
    """Whole-program compiled cost/memory block of ``plan`` — the
    record-schema extension ``bench.py`` stamps into result lines and
    ``regress.py`` baselines (peak-HBM / compile-seconds gates).

    Returns ``{flops, bytes_accessed, peak_hbm_bytes, argument_bytes,
    output_bytes, temp_bytes, compile_seconds}`` or None when the plan
    cannot be AOT-analyzed (never raises). Cached on the plan object;
    with metrics enabled the peak-HBM gauge and AOT compile-seconds
    histogram are recorded once per plan."""
    cached = getattr(plan, "_compiled_summary", None)
    if cached is not None:
        return cached or None  # False sentinel = known-unavailable
    from .api import alloc_local

    try:
        if x is None:
            x = alloc_local(plan)
    except Exception:  # noqa: BLE001
        plan._compiled_summary = False
        return None
    res = _compile_analysis(plan.fn, x)
    if res is None:
        plan._compiled_summary = False
        return None
    res.pop("available", None)
    plan._compiled_summary = res
    if _metrics._enabled:
        if res.get("peak_hbm_bytes") is not None:
            _metrics.set_gauge(
                "plan_peak_hbm_bytes", res["peak_hbm_bytes"],
                decomposition=plan.decomposition, executor=plan.executor)
        _metrics.observe(
            "aot_compile_seconds", res["compile_seconds"],
            decomposition=plan.decomposition, executor=plan.executor)
    return res


# --------------------------------------------------------------- staged

def _canonical_chain(plan) -> bool:
    """True when the plan runs the canonical stage chain the staged
    builders rebuild — re-axed (absorbed-layout) chains and transposed
    r2c views execute a different program than the breakdown would
    describe (the same refusal rule as ``speed3d -staged``)."""
    lp = plan.logic
    if lp is None or plan.brick_edges is not None:
        return False
    if getattr(plan, "r2c_axis", 2) != 2:
        return False
    if lp.decomposition == "slab":
        want = (0, 1) if plan.forward else (1, 0)
        return lp.slab_axes in (None, want)
    if lp.decomposition == "pencil":
        if plan.real:  # the rfft staged builders are canonical-only
            want_perm = (0, 1, 2) if plan.forward else (1, 2, 0)
            want_order = "col_first" if plan.forward else "row_first"
            return (lp.pencil_perm in (None, want_perm)
                    and lp.pencil_order in (None, want_order))
    return True


def _staged_for(plan):
    """The separately-jitted t0..t3 pipeline matching ``plan`` (the
    builders bench.py / speed3d -staged use), or None when no staged
    equivalent exists for this plan family. A fused spectral-operator
    plan measures through its OWN staged chain (t0 | t2 | t_mid | t2 |
    t3 — slab, flat transports; other op geometries have no staged
    twin and report model/compiled views only): the transform stage
    builders describe a different program than the fused solve."""
    if getattr(plan, "op", None):
        lp = plan.logic
        if (lp is None or lp.decomposition != "slab" or plan.mesh is None
                or len(plan.mesh.axis_names) != 1
                or plan.options.algorithm == "hierarchical"
                or getattr(plan, "multiplier", None) is None):
            return None
        from .parallel.staged import build_slab_op_stages

        oc = plan.options.overlap_chunks
        try:
            return build_slab_op_stages(
                plan.mesh, plan.shape, plan.multiplier,
                axis_name=plan.mesh.axis_names[0],
                executor=plan.executor,
                algorithm=plan.options.algorithm,
                overlap_chunks=oc if isinstance(oc, int) else 1,
                batch=getattr(plan, "batch", None),
                wire_dtype=getattr(plan.options, "wire_dtype", None))[0]
        except Exception:  # noqa: BLE001 — no staged view is a soft miss
            return None
    if not _canonical_chain(plan):
        return None
    lp = plan.logic
    oc = plan.options.overlap_chunks
    overlap = oc if isinstance(oc, int) else 1
    kw = dict(executor=plan.executor, forward=plan.forward,
              batch=getattr(plan, "batch", None))
    try:
        if lp.decomposition == "single" or plan.mesh is None:
            if plan.real:
                return None
            from .parallel.staged import build_single_stages

            return build_single_stages(plan.shape, **kw)
        kw.update(algorithm=plan.options.algorithm, overlap_chunks=overlap,
                  wire_dtype=getattr(plan.options, "wire_dtype", None))
        if lp.decomposition == "slab":
            if plan.real:
                from .parallel.staged import build_slab_rfft_stages

                return build_slab_rfft_stages(
                    plan.mesh, plan.shape,
                    axis_name=plan.mesh.axis_names[0], **kw)[0]
            from .parallel.slab import build_slab_stages

            # Hierarchical slab plans run over the combined (dcn, ici)
            # axis pair; the staged builder splits their t2 into per-leg
            # t2a/t2b stages.
            names = plan.mesh.axis_names
            axis = names[0] if len(names) == 1 else tuple(names)
            return build_slab_stages(
                plan.mesh, plan.shape, axis_name=axis, **kw)[0]
        row, col = plan.mesh.axis_names[:2]
        if plan.real:
            from .parallel.staged import build_pencil_rfft_stages

            return build_pencil_rfft_stages(
                plan.mesh, plan.shape, row_axis=row, col_axis=col, **kw)[0]
        from .parallel.staged import build_pencil_stages

        return build_pencil_stages(
            plan.mesh, plan.shape, row_axis=row, col_axis=col,
            perm=lp.pencil_perm, order=lp.pencil_order, **kw)[0]
    except Exception:  # noqa: BLE001 — no staged view is a soft miss
        return None


def _measure_stages(stages, x, iters: int) -> tuple[dict, dict, dict]:
    """Warm per-stage wall-clock samples: one compile/warmup pass, then
    ``iters`` sync-bracketed passes. Returns ``(samples, compiled,
    legs)`` where ``samples`` maps canonical stage key -> [seconds, ...],
    ``compiled`` maps stage key -> per-stage AOT analysis (summed over
    a key's stages — the pencil chain has two t2 jits), and ``legs``
    maps the per-leg exchange sub-keys (``t2a``/``t2b`` — the pencil
    chain's two exchanges, or the hierarchical transport's ICI/DCN legs)
    to their own sample lists so the t2 row can attribute each leg."""
    samples: dict[str, list[float]] = {}
    legs: dict[str, list[float]] = {}
    compiled: dict[str, dict] = {}
    for it in range(iters + 1):
        cur = x
        for name, fn in stages:
            key = stage_key(name) or name
            if it == 0:
                inner = getattr(fn, "__wrapped__", fn)
                res = _compile_analysis(inner, cur)
                if res is not None:
                    agg = compiled.get(key)
                    if agg is None:
                        compiled[key] = res
                    else:
                        for k2, v in res.items():
                            if isinstance(v, (int, float)) and not isinstance(
                                    v, bool):
                                if agg.get(k2) is None:
                                    agg[k2] = v
                                elif v is not None:
                                    agg[k2] += v
            sync(cur)
            t0 = time.perf_counter()
            cur = fn(cur)
            sync(cur)
            dt = time.perf_counter() - t0
            if it > 0:
                samples.setdefault(key, []).append(dt)
                if name[:3] in ("t2a", "t2b"):
                    legs.setdefault(name[:3], []).append(dt)
    # A key emitted by two stages (pencil t2a/t2b) must report the SUM
    # of its per-pass stage times, not interleaved per-stage samples.
    per_pass: dict[str, list[float]] = {}
    counts = {}
    for name, _ in stages:
        key = stage_key(name) or name
        counts[key] = counts.get(key, 0) + 1
    for key, vals in samples.items():
        n = counts.get(key, 1)
        if n <= 1:
            per_pass[key] = vals
        else:
            # Pass j appended this key's n stage times consecutively.
            per_pass[key] = [sum(vals[j * n:(j + 1) * n])
                             for j in range(len(vals) // n)]
    return per_pass, compiled, legs


# -------------------------------------------------------- device timing

def _device_pids(entries: list[dict]) -> set:
    """pids of device-lane processes in one XLA profiler chrome trace:
    the ``process_name`` metadata rows whose name carries a
    ``device:`` tag (``/device:TPU:0``-style). The CPU backend emits
    only ``/host:CPU`` lanes -> empty set -> the caller falls back."""
    pids = set()
    for e in entries:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            nm = str((e.get("args") or {}).get("name", ""))
            if "device:" in nm.lower():
                pids.add(e.get("pid"))
    return pids


def parse_device_trace(doc, iters: int = 1) -> dict | None:
    """Per-stage device-timeline samples out of one XLA profiler trace
    (the ``*.trace.json.gz`` chrome document ``jax.profiler.trace``
    writes). Events are kept when they sit on a device-lane process
    AND their name normalizes to a ``t0..t3`` stage key (the
    ``TraceAnnotation`` names the chain builders emit, per-chunk
    ``t2_...[k]`` variants included) — so the returned seconds are what
    the DEVICE spent inside each stage, not the host's dispatch
    bracket.

    Returns ``{"samples": {key: [seconds, ...]}, "chunks": {raw_name:
    {"count", "seconds"}}, "device_pids": [...]}``. When the per-key
    event count divides ``iters`` (the expected case: each measured
    pass emits the same spans), consecutive event groups become one
    sample per pass; otherwise one aggregate sample (total/iters) is
    returned and the divergence gate's min-sample rule withholds its
    verdict. None when the trace has no device lanes or no stage events
    on them — the caller's signal to fall back to host brackets."""
    raw = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
    if not isinstance(raw, list):
        return None
    entries = [e for e in raw if isinstance(e, dict)]
    pids = _device_pids(entries)
    if not pids:
        return None
    per_key: dict[str, list[tuple[float, float]]] = {}
    chunks: dict[str, dict] = {}
    for e in entries:
        if e.get("ph") != "X" or e.get("pid") not in pids:
            continue
        name = str(e.get("name", ""))
        key = stage_key(name)
        if key is None:
            continue
        try:
            ts, dur = float(e["ts"]), float(e["dur"])
        except (KeyError, TypeError, ValueError):
            continue
        per_key.setdefault(key, []).append((ts, dur / 1e6))
        if "[" in name:
            c = chunks.setdefault(name, {"count": 0, "seconds": 0.0})
            c["count"] += 1
            c["seconds"] += dur / 1e6
    if not per_key:
        return None
    iters = max(1, int(iters))
    samples: dict[str, list[float]] = {}
    for key, evs in per_key.items():
        evs.sort()
        durs = [d for _, d in evs]
        if len(durs) >= iters and len(durs) % iters == 0:
            per = len(durs) // iters
            samples[key] = [sum(durs[i * per:(i + 1) * per])
                            for i in range(iters)]
        else:
            samples[key] = [sum(durs) / iters]
    return {"samples": samples, "chunks": chunks,
            "device_pids": sorted(pids)}


def _load_trace_doc(path: str):
    import gzip

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        import json

        return json.load(f)


def device_stage_samples(
    stages, x, iters: int = 3, logdir: str | None = None,
) -> tuple[dict | None, str | None]:
    """Run ``iters`` pipeline passes under ``jax.profiler.trace`` and
    attribute the stage times from the device timeline.

    Returns ``(parsed, None)`` on success (``parsed`` per
    :func:`parse_device_trace`) or ``(None, reason)`` when the
    environment cannot produce a device attribution — profiler
    unavailable, no trace file written, or no device-lane stage events
    (the CPU backend's case; its "device" time IS the host bracket).
    The capture directory is temporary unless ``logdir`` keeps it."""
    import glob as _glob
    import shutil
    import tempfile

    import jax

    from .utils.timing import sync

    tmp = None
    if logdir is None:
        tmp = tempfile.mkdtemp(prefix="dfft_devtrace_")
        logdir = tmp
    try:
        try:
            # One unprofiled warm pass: stage compiles must not land in
            # (and distort) the captured timeline.
            cur = x
            for _, fn in stages:
                cur = fn(cur)
            sync(cur)
            with jax.profiler.trace(logdir):
                for _ in range(max(1, iters)):
                    cur = x
                    for _, fn in stages:
                        cur = fn(cur)
                    sync(cur)
        except Exception as e:  # noqa: BLE001 — capture is best-effort
            return None, f"profiler capture failed: {type(e).__name__}"
        files = sorted(
            _glob.glob(os.path.join(logdir, "**", "*.trace.json.gz"),
                       recursive=True)
            + _glob.glob(os.path.join(logdir, "**", "*.trace.json"),
                         recursive=True))
        if not files:
            return None, "profiler wrote no trace file"
        for path in reversed(files):  # newest capture first
            try:
                parsed = parse_device_trace(_load_trace_doc(path),
                                            iters=iters)
            except Exception:  # noqa: BLE001 — corrupt capture
                continue
            if parsed is not None:
                return parsed, None
        return None, "no device-lane stage events in trace"
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


# ----------------------------------------------------------- multi-host

def _allgather_rows(vec: np.ndarray) -> np.ndarray:
    """One float row per process -> (nproc, len(vec)) matrix; the
    tuner's indirection so tests can simulate multi-host merges."""
    from .tuner import _allgather_rows as rows

    return rows(vec)


def across_hosts_stages(stage_medians: dict) -> dict:
    """Allgather one process's per-stage measured medians and fold them
    into min/median/max-across-hosts rows — the straggler view: a
    healthy job's t2 rows agree within noise; one slow host stretches
    ``max`` (and ``straggler_ratio``) while the median stays put.
    Single-process runs degenerate to n=1 rows (same schema)."""
    vec = np.array(
        [float(stage_medians.get(k) if stage_medians.get(k) is not None
               else math.nan) for k in STAGE_KEYS], np.float64)
    rows = np.asarray(_allgather_rows(vec), np.float64).reshape(-1, len(vec))
    out: dict[str, Any] = {}
    for i, key in enumerate(STAGE_KEYS):
        col = rows[:, i]
        col = col[np.isfinite(col)]
        if not len(col):
            continue
        med = float(np.median(col))
        out[key] = {
            "min": float(col.min()),
            "median": med,
            "max": float(col.max()),
            "n": int(len(col)),
            "straggler_ratio": (float(col.max() / med) if med else None),
        }
    return {"processes": int(rows.shape[0]), "stages": out}


# ----------------------------------------------------------- divergence

def stage_divergence(
    model_seconds: float,
    samples: Sequence[float],
    *,
    mads: float = DEFAULT_MADS,
    min_rel: float = DEFAULT_MIN_REL,
    min_samples: int = DEFAULT_MIN_SAMPLES,
) -> dict:
    """Does the model's prediction for one stage fall outside the
    measured samples' noise band? Same robust model as the PR 2 compare
    engine: band = median +/- max(``mads`` scaled MADs, ``min_rel`` x
    median). ``diverged`` is None (not a verdict) with fewer than
    ``min_samples`` samples or a zero/absent model prediction — a
    stage the model prices at exactly 0 (slab t1) can never "diverge".
    """
    out = {
        "model_seconds": float(model_seconds),
        "n": len(samples),
        "diverged": None,
    }
    if len(samples) < min_samples or not model_seconds > 0.0:
        return out
    med, mad = regress.robust_stats([float(s) for s in samples])
    band = regress._band(med, mad, mads, min_rel)
    out.update(
        median=med, mad=mad, band=band,
        ratio=(med / model_seconds) if model_seconds else math.inf,
        diverged=abs(med - model_seconds) > band,
    )
    if out["diverged"]:
        out["direction"] = "slower" if med > model_seconds else "faster"
    return out


def _median(samples: Sequence[float]) -> float | None:
    if not samples:
        return None
    med, _ = regress.robust_stats([float(s) for s in samples])
    return med


# -------------------------------------------------- overlap attribution

def _overlap_block(
    plan,
    concurrent,
    model: dict,
    *,
    iters: int,
    mads: float,
    min_rel: float,
    min_samples: int,
) -> dict | None:
    """Measured overlap attribution of the plan's schedule — the
    monitor's dispatch-span join (:func:`..monitor.dispatch_spans` /
    :func:`..monitor.overlap_from_events`) next to the model's hide
    budget, under the same median+MAD divergence gate as the stage
    rows.

    ``concurrent`` (an int cohort size >= 2, or a sequence of plans)
    measures the :func:`..stagegraph.schedule_concurrent` interleave
    across transforms (kind ``"concurrent"``); otherwise an overlap-K
    plan (K > 1) measures its per-chunk leg pipeline (kind
    ``"overlap_k"``); anything else attributes nothing (None). The
    measured/model ratio is persisted into the calibration profile
    (:func:`..monitor.update_overlap_correction`) so the auto-width and
    overlap-K pricing learn from it; plans below the stage-graph tier
    return None — there is no merged program to re-trace."""
    from .monitor import (dispatch_spans, overlap_from_events,
                          update_overlap_correction)

    if concurrent is not None:
        if isinstance(concurrent, bool) or (
                isinstance(concurrent, int) and concurrent < 2):
            raise ValueError(f"concurrent must be an int >= 2 or a "
                             f"sequence of plans, got {concurrent!r}")
        cohort = ((plan,) * concurrent if isinstance(concurrent, int)
                  else tuple(concurrent))
        if len(cohort) < 2:
            raise ValueError("a concurrent cohort needs >= 2 plans")
        kind, join = "concurrent", "concurrent"
    else:
        oc = plan.options.overlap_chunks
        if not (isinstance(oc, int) and oc > 1):
            return None
        cohort, kind, join = (plan,), "overlap_k", "legs"
    if any(getattr(p, "graph", None) is None
           or getattr(p, "logic", None) is None for p in cohort):
        return None

    # Model hide ratio on the same 1 - wall/extents scale the measured
    # join produces: the fraction of the schedule's serial cost the
    # model prices as hidden.
    if kind == "concurrent":
        from .plan_logic import model_concurrent_seconds

        hw = device_profile()
        triples = []
        for p in cohort:
            shape, itemsize = _model_shape_itemsize(p)
            triples.append((p.logic, shape, itemsize))
        mcs = model_concurrent_seconds(
            triples, hbm_gbps=hw["hbm_gbps"], wire_gbps=hw["wire_gbps"],
            launch_seconds=hw["launch_seconds"],
            dcn_gbps=hw.get("dcn_gbps"))
        seq = mcs["sequential_seconds"]
        model_side = {
            "hide_seconds": mcs["hidden_seconds"],
            "hide_ratio": (mcs["hidden_seconds"] / seq
                           if seq > 0 else None),
            "speedup": mcs["speedup"],
        }
    else:
        t2 = model.get("t2") or {}
        raw = t2.get("raw_seconds")
        legs = t2.get("legs") or []
        hide_total = sum(leg.get("hide_seconds") or 0.0 for leg in legs)
        # Hidden-wire over raw-wire (NOT 1 - exposed/raw: chunked launch
        # overhead can push the exposed price above the monolithic raw
        # wire, which would read as a negative hide).
        model_side = {
            "hide_seconds": hide_total,
            "hide_ratio": (min(1.0, hide_total / raw)
                           if isinstance(raw, (int, float)) and raw > 0
                           else None),
        }

    samples: list[float] = []
    groups = None
    for _ in range(max(1, iters)):
        try:
            ov = overlap_from_events(dispatch_spans(cohort))[join]
        except Exception:  # noqa: BLE001 — attribution, not contract
            return None
        if ov is None:
            break
        samples.append(ov["hide_ratio"])
        groups = ov["groups"]
    block: dict[str, Any] = {
        "kind": kind,
        "cohort": len(cohort),
        "groups": groups,
        "measured_hide_ratio": _median(samples),
        "measured_samples": [round(v, 6) for v in samples],
        "model_hide_seconds": model_side.get("hide_seconds"),
        "model_hide_ratio": model_side.get("hide_ratio"),
    }
    if "speedup" in model_side:
        block["model_speedup"] = model_side["speedup"]
    mr = block["model_hide_ratio"]
    block["divergence"] = stage_divergence(
        mr if isinstance(mr, (int, float)) else 0.0, samples,
        mads=mads, min_rel=min_rel, min_samples=min_samples)
    # Feed the realized ratio back into the calibration profile (the
    # "concurrent_hide"/"leg_hide" hide_correction keys); a disarmed
    # profile store (DFFT_HW_PROFILE=0) makes this a no-op.
    try:
        update_overlap_correction(block)
    except Exception:  # noqa: BLE001 — feedback is best-effort
        pass
    return block


# -------------------------------------------------------------- explain

def explain(
    plan,
    *,
    iters: int = 3,
    measure: bool = True,
    device_timing: bool | None = None,
    allgather: bool = False,
    mads: float = DEFAULT_MADS,
    min_rel: float = DEFAULT_MIN_REL,
    min_samples: int = DEFAULT_MIN_SAMPLES,
    concurrent: int | Sequence | None = None,
) -> dict:
    """One structured attribution record for a built plan: the
    model/compiled/measured join per ``t0..t3`` stage, per-stage MFU and
    ICI-utilization, whole-program compiled cost/memory, divergence
    flags under the median+MAD gate, and — for overlap-K and
    concurrent schedules — the measured realized-overlap attribution
    (``record["overlap"]``: the monitor's dispatch-span join next to
    the model's hide budget; see :func:`_overlap_block`).

    ``concurrent`` (an int cohort size >= 2, or a sequence of plans to
    co-schedule with this one) switches the overlap attribution to the
    :func:`..stagegraph.schedule_concurrent` cross-transform interleave
    instead of the plan's own leg pipeline.

    ``measure=False`` skips every execution (model + compiled views
    only — safe on a backend whose dispatch is known-sick); ``iters``
    warm passes feed the measured samples (>= ``min_samples`` for
    divergence verdicts).

    ``device_timing`` (default: env ``DFFT_DEVICE_TIMING``) swaps the
    host sync-bracket samples for a ``jax.profiler``-backed device
    timeline attribution (:func:`device_stage_samples`): the measured
    seconds are then what the device spent inside each stage span,
    per-chunk ``t2[k]``/``t3[k]`` rows included under overlap-K. The
    attempt falls back to host brackets — with the reason in
    ``record["timing"]`` — wherever the environment cannot produce
    device lanes (the CPU test backend always falls back).

    ``allgather=True`` additionally merges every process's measured
    stage medians into min/median/max-across-hosts rows
    (``record["across_hosts"]``; :func:`across_hosts_stages`) so
    stragglers are visible. Collective: in a multi-process job every
    process must make the same call.

    Never raises on analysis gaps: sections the environment cannot
    produce carry ``available: False`` / None values so the record
    shape is stable for the report CLI and the run-record store."""
    from .api import alloc_local

    hw = device_profile()
    model = model_stage_estimates(plan, hw)
    lp = plan.logic
    ndev = 1 if plan.mesh is None else int(plan.mesh.devices.size)
    opname = getattr(plan, "op", None) or None
    # Operator plans carry the t_mid midpoint stage (the fused
    # FFT -> pointwise -> iFFT chain); transforms keep t0..t3 exactly.
    keys = OP_STAGE_KEYS if "t_mid" in model else STAGE_KEYS

    if opname:
        kind = f"op_{opname}"
    else:
        kind = ("r2c" if plan.real and plan.forward
                else "c2r" if plan.real else "c2c")
    oc = plan.options.overlap_chunks
    record: dict[str, Any] = {
        "schema": EXPLAIN_SCHEMA,
        "generated_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "plan": {
            "shape": list(plan.shape),
            "kind": kind,
            "op": opname,
            "forward": plan.forward,
            "decomposition": plan.decomposition,
            "executor": plan.executor,
            "algorithm": plan.options.algorithm,
            "overlap_chunks": oc if isinstance(oc, int) else 1,
            "devices": ndev,
            "mesh": (None if plan.mesh is None
                     else list(plan.mesh.devices.shape)),
            "dtype": str(np.dtype(plan.dtype)),
            "donate": bool(plan.options.donate),
            "wire_dtype": getattr(plan.options, "wire_dtype", None),
            # Plan-scoped matmul accuracy tier (PlanOptions.mm_precision
            # / the executor label's suffix): the per-stage MFU below is
            # computed against THIS tier's matmul rate, so a bf16-tier
            # run's utilization is judged on the bf16 peak.
            "mm_precision": getattr(plan.options, "mm_precision", None),
            "mm_complex": getattr(plan.options, "mm_complex", None),
        },
        "hw": hw,
        "gate": {"mads": mads, "min_rel": min_rel,
                 "min_samples": min_samples},
    }
    # On-wire compression view: the measured round-trip error of one
    # encode/decode cast at this plan's dtype (0.0 on the exact wire) and
    # the wire-byte scale — the numbers the tuner's error-budget filter
    # admits against, surfaced next to the divergence flags so a
    # compressed run's accuracy cost is part of the attribution record.
    wd = getattr(plan.options, "wire_dtype", None)
    try:
        from .parallel.exchange import wire_itemsize, wire_roundtrip_error

        _, itemsize = _model_shape_itemsize(plan)
        record["wire"] = {
            "wire_dtype": wd,
            "compression_err": wire_roundtrip_error(plan.dtype, wd),
            "wire_factor": (wire_itemsize(itemsize, wd) / itemsize
                            if wd else 1.0),
        }
    except Exception:  # noqa: BLE001 — attribution, not contract
        record["wire"] = {"wire_dtype": wd, "compression_err": None,
                          "wire_factor": None}

    x = None
    try:
        x = alloc_local(plan)
    except Exception:  # noqa: BLE001
        pass

    # Whole-program compiled view (also the regress cost block).
    whole = compiled_summary(plan, x) if x is not None else None
    record["compiled"] = dict(whole) if whole else None

    # Per-stage compiled + measured via the staged pipelines.
    if device_timing is None:
        device_timing = os.environ.get(
            "DFFT_DEVICE_TIMING", "") not in ("", "0")
    timing: dict[str, Any] = {"source": "host",
                              "device_requested": bool(device_timing)}
    samples: dict[str, list[float]] = {}
    leg_samples: dict[str, list[float]] = {}
    stage_compiled: dict[str, dict] = {}
    chunk_rows: dict[str, dict] = {}
    staged_available = False
    if measure and x is not None and not plan.options.donate:
        stages = _staged_for(plan)
        if stages is not None:
            try:
                samples, stage_compiled, leg_samples = _measure_stages(
                    stages, x, iters)
                staged_available = True
            except Exception:  # noqa: BLE001 — sick dispatch, keep going
                samples, stage_compiled, leg_samples = {}, {}, {}
            if staged_available and device_timing:
                dev, reason = device_stage_samples(stages, x, iters)
                if dev is not None:
                    samples = {k: v for k, v in dev["samples"].items()
                               if k in keys}
                    chunk_rows = dev["chunks"]
                    timing["source"] = "device"
                    timing["device_pids"] = dev["device_pids"]
                else:
                    timing["fallback_reason"] = reason
    record["staged_available"] = staged_available
    record["timing"] = timing

    peak_flops = hw["peak_tflops"] * 1e12
    try:
        # Matmul-family plans: MFU against the executor TIER's matmul
        # rate (calibrated mm_*_tflops fields win), so predicted-vs-
        # measured utilization is the tier's own — a bf16-tier stage at
        # 30% of the bf16 peak must not read as 90% of the exact peak.
        from .tuner import mm_tier_tflops

        tier_tf = mm_tier_tflops(plan.executor)
        if tier_tf:
            peak_flops = tier_tf * 1e12
            record["plan"]["mm_tflops"] = tier_tf
    except Exception:  # noqa: BLE001 — attribution, not contract
        pass
    wire_bps = hw["wire_gbps"] * 1e9
    stages_out: dict[str, dict] = {}
    diverged: list[str] = []
    for key in keys:
        m = model.get(key) or {}
        s = samples.get(key, [])
        med = _median(s)
        comp = stage_compiled.get(key) or dict(_UNAVAILABLE)
        div = stage_divergence(
            m.get("seconds", 0.0), s, mads=mads, min_rel=min_rel,
            min_samples=min_samples)
        flops = comp.get("flops") or m.get("flops") or 0.0
        entry = {
            "model": m,
            "compiled": comp,
            "measured": {
                "available": bool(s),
                "seconds": med,
                "best_seconds": min(s) if s else None,
                "samples": [round(v, 9) for v in s],
            },
            "divergence": div,
            "mfu": (flops / (med * peak_flops)
                    if med and flops and peak_flops else None),
        }
        if key == "t2":
            wire = m.get("wire_bytes", 0.0)
            entry["ici_utilization"] = (
                wire / (med * wire_bps) if med and wire else None)
            model_legs = m.get("legs")
            if model_legs and len(model_legs) > 1:
                # Per-leg modeled-vs-measured rows: the pencil chain's
                # two exchanges, or the hierarchical transport's ICI and
                # DCN legs — each leg's model prediction joined with its
                # own measured stage samples (t2a/t2b sub-keys).
                entry["legs"] = []
                for leg in model_legs:
                    ls = leg_samples.get(leg.get("stage"), [])
                    entry["legs"].append({
                        **leg,
                        "measured_seconds": _median(ls),
                        "measured_samples": [round(v, 9) for v in ls],
                    })
        if chunk_rows:
            # Per-chunk device attribution (overlap-K): the raw
            # t2_...[k]/t3_...[k] span rows whose key this stage owns.
            mine = {n: c for n, c in chunk_rows.items()
                    if stage_key(n) == key}
            if mine:
                entry["chunks"] = mine
        stages_out[key] = entry
        if div.get("diverged"):
            diverged.append(key)
    record["stages"] = stages_out

    model_total = sum((model.get(k) or {}).get("seconds", 0.0)
                      for k in keys)
    meds = [stages_out[k]["measured"]["seconds"] for k in keys]
    record["totals"] = {
        "model_seconds": model_total,
        "measured_stage_seconds": (sum(v for v in meds if v)
                                   if any(meds) else None),
    }
    record["divergence"] = {"any": bool(diverged), "stages": diverged}
    try:
        record["overlap"] = _overlap_block(
            plan, concurrent, model, iters=iters, mads=mads,
            min_rel=min_rel, min_samples=min_samples)
    except ValueError:
        raise
    except Exception:  # noqa: BLE001 — attribution, not contract
        record["overlap"] = None
    if allgather:
        try:
            record["across_hosts"] = across_hosts_stages(
                {k: stages_out[k]["measured"]["seconds"]
                 for k in STAGE_KEYS})
        except Exception:  # noqa: BLE001 — a single-controller runtime
            record["across_hosts"] = None  # without allgather support
    # Fusion-tier view: what the stage-graph fusion pass decided for
    # this plan (``graph.meta["fusion"]``, stamped at compile time) —
    # the requested/active verdict, the gate reasons when it stayed
    # off, and the per-exchange site routing (sender/receiver kernel
    # vs counted fallback). Captured last: the site records fill in
    # when the plan body traces, which the measurements above force.
    try:
        meta = getattr(plan.graph, "meta", None)
        fu = meta.get("fusion") if isinstance(meta, dict) else None
    except Exception:  # noqa: BLE001 — plans below the graph tier
        fu = None
    record["fusion"] = None if not isinstance(fu, dict) else {
        "requested": bool(fu.get("requested")),
        "active": bool(fu.get("active")),
        "reasons": [str(r) for r in (fu.get("reasons") or ())],
        "sites": {str(k): dict(v)
                  for k, v in (fu.get("sites") or {}).items()},
    }
    return record


# ------------------------------------------------------------ rendering

def _fmt(v, unit: str = "") -> str:
    if v is None:
        return "-"
    if unit == "s":
        return f"{v:.6f}"
    if unit == "MB":
        return f"{v * _MB:.2f}"
    if unit == "%":
        return f"{100.0 * v:.1f}%"
    if isinstance(v, float) and (abs(v) >= 1e5 or (0 < abs(v) < 1e-3)):
        return f"{v:.3e}"
    return str(v)


def format_explain(record: dict) -> str:
    """Human-readable attribution table of one explain record — the
    ``report explain`` / ``speed3d -explain`` rendering."""
    p = record.get("plan") or {}
    hw = record.get("hw") or {}
    shape = "x".join(str(s) for s in p.get("shape") or [])
    lines = [
        f"plan: {shape} {p.get('kind')} "
        + (f"(fused {p['op']} operator)  " if p.get("op")
           else f"{'forward' if p.get('forward', True) else 'backward'}  ")
        + f"{p.get('decomposition')}/{p.get('algorithm')}"
        f"/{p.get('executor')}/ov{p.get('overlap_chunks')}  "
        f"{p.get('devices')} device(s)  [{p.get('dtype')}]",
        f"hw: {hw.get('device_kind')} (hbm {hw.get('hbm_gbps')} GB/s, "
        f"ici {hw.get('wire_gbps')} GB/s, peak {hw.get('peak_tflops')} "
        f"TFlop/s; {hw.get('source')} profile)",
    ]
    wire = record.get("wire") or {}
    if wire.get("wire_dtype"):
        err = wire.get("compression_err")
        wf = wire.get("wire_factor")
        lines.append(
            f"wire: {wire['wire_dtype']} compression"
            + (f" (x{wf:.2f} wire bytes" if wf else " (")
            + (f", round-trip err {err:.2e})" if err is not None else ")"))
    fu = record.get("fusion")
    if isinstance(fu, dict) and fu.get("requested"):
        if fu.get("active"):
            sites = fu.get("sites") or {}
            routes = sorted(
                f"{v.get('sender', '?')}+{v.get('receiver', '?')}"
                for v in sites.values()) if sites else []
            lines.append(
                "fusion: active (stage-pair mega-kernels"
                + (f"; sites {', '.join(routes)}" if routes else "")
                + ")")
        else:
            lines.append(
                "fusion: requested but gated off "
                f"({', '.join(fu.get('reasons') or ['unknown'])})")
    timing = record.get("timing") or {}
    if timing.get("source") == "device":
        lines.append("timing: device timeline (jax.profiler capture)")
    elif timing.get("device_requested"):
        lines.append(
            f"timing: host sync brackets (device capture fell back: "
            f"{timing.get('fallback_reason', 'unavailable')})")
    header = (f"{'stage':<6} {'model(s)':>11} {'measured(s)':>12} "
              f"{'flops':>11} {'peakHBM(MB)':>12} {'MFU':>7} "
              f"{'ICI':>7}  divergence")
    lines.append(header)
    rec_stages = record.get("stages") or {}
    # Operator records carry the t_mid midpoint row between t2 and t3;
    # transform records render exactly t0..t3 as before.
    row_keys = ([k for k in OP_STAGE_KEYS if k in rec_stages]
                or list(STAGE_KEYS))
    for key in row_keys:
        st = rec_stages.get(key) or {}
        m = st.get("model") or {}
        comp = st.get("compiled") or {}
        meas = st.get("measured") or {}
        div = st.get("divergence") or {}
        if div.get("diverged"):
            note = (f"DIVERGED {div.get('ratio', 0.0):.1f}x "
                    f"{div.get('direction', '')}")
        elif div.get("diverged") is False:
            note = "within noise"
        else:
            note = "-"
        lines.append(
            f"{key:<6} {_fmt(m.get('seconds'), 's'):>11} "
            f"{_fmt(meas.get('seconds'), 's'):>12} "
            f"{_fmt(comp.get('flops')):>11} "
            f"{_fmt(comp.get('peak_hbm_bytes'), 'MB'):>12} "
            f"{_fmt(st.get('mfu'), '%'):>7} "
            f"{_fmt(st.get('ici_utilization'), '%'):>7}  {note}")
        for leg in st.get("legs") or []:
            # Per-leg exchange rows (pencil t2a/t2b; hierarchical
            # ICI/DCN): indented under the t2 summary row. A
            # leg-pipelined row is one the K-chunk schedule hides under
            # the other leg's transfer (hierarchical K > 1).
            lines.append(
                f"  {leg.get('stage', '?'):<4} "
                f"{_fmt(leg.get('seconds'), 's'):>11} "
                f"{_fmt(leg.get('measured_seconds'), 's'):>12} "
                f"{'':>11} {'':>12} {'':>7} {'':>7}  "
                f"[{leg.get('link', '?')} axis {leg.get('mesh_axis')}, "
                f"{leg.get('parts')} parts"
                + (", pipelined" if leg.get("leg_pipelined") else "")
                + "]")
    tot = record.get("totals") or {}
    lines.append(
        f"totals: model {_fmt(tot.get('model_seconds'), 's')} s | "
        f"measured stages "
        f"{_fmt(tot.get('measured_stage_seconds'), 's')} s")
    whole = record.get("compiled")
    if whole:
        lines.append(
            f"compiled (whole plan): flops {_fmt(whole.get('flops'))} | "
            f"bytes accessed {_fmt(whole.get('bytes_accessed'), 'MB')} MB"
            f" | peak HBM {_fmt(whole.get('peak_hbm_bytes'), 'MB')} MB "
            f"(arg {_fmt(whole.get('argument_bytes'), 'MB')}"
            f" + out {_fmt(whole.get('output_bytes'), 'MB')}"
            f" + temp {_fmt(whole.get('temp_bytes'), 'MB')})"
            f" | compile {_fmt(whole.get('compile_seconds'), 's')} s")
    else:
        lines.append("compiled (whole plan): unavailable")
    ah = record.get("across_hosts")
    if isinstance(ah, dict) and ah.get("stages"):
        lines.append(f"across {ah.get('processes')} host process(es) "
                     f"(measured seconds, min/median/max):")
        for key in STAGE_KEYS:
            row = ah["stages"].get(key)
            if not row:
                continue
            strag = row.get("straggler_ratio")
            lines.append(
                f"  {key:<4} {_fmt(row['min'], 's')} / "
                f"{_fmt(row['median'], 's')} / {_fmt(row['max'], 's')}"
                + (f"  (straggler {strag:.2f}x)"
                   if strag and strag > 1.2 else ""))
    d = record.get("divergence") or {}
    if d.get("any"):
        lines.append(
            f"divergence: model and measurement disagree beyond the "
            f"noise gate on {', '.join(d['stages'])}"
            + (" (default hw profile: constants, not calibration)"
               if hw.get("source") == "default" else ""))
    return "\n".join(lines)


def explain_from_record(record: dict) -> dict | None:
    """The explain block of a run record (or a bare explain record):
    ``record["explain"]`` when present, else the record itself when it
    IS an explain record (schema + stages). None otherwise."""
    if not isinstance(record, dict):
        return None
    exp = record.get("explain")
    if isinstance(exp, dict) and exp.get("stages"):
        return exp
    if record.get("schema") == EXPLAIN_SCHEMA and isinstance(
            record.get("stages"), dict):
        return record
    return None
