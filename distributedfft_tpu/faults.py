"""Deterministic fault injection — reproducible chaos for the serving tier.

The reference pipeline assumes every stage succeeds
(``fft_mpi_3d_api.cpp:184-201`` threads t0..t3 with no error path); the
serving tier cannot. Testing its recovery machinery (retry, batch
isolation, degraded-mode fallback — :mod:`.serving`) requires faults
that fire *on demand and reproducibly*: count-based and seeded, never
"hope the hardware flakes during CI". This module is that switchboard.

Injection points (where the hosting code calls :func:`check`):

- ``plan``     — plan construction (:func:`..api._timed_build`, i.e.
  every public planner's cache-miss build).
- ``compile``  — executable preparation: the first execution of a plan
  (JAX compiles at first call) and ``Plan3D.compile()``.
- ``execute``  — every ``execute()`` dispatch.
- ``exchange`` — the t2 exchange, emulated host-side at dispatch of any
  plan that owns one (``plan.mesh is not None``) — a fault inside the
  compiled collective cannot raise from XLA, so the hook brackets it.

Spec grammar (env ``DFFT_FAULT_INJECT``; clauses separated by ``;``)::

    clause    = point ":" directive ("," directive)*
    directive = "once"                 fire on the 1st check only
              | "every=N"             fire on every Nth check (N, 2N, ...)
              | "at=N[+N...]"         fire on exactly these check numbers
              | "p=P"                 fire with probability P (seeded)
              | "seed=S"              RNG seed for p (default 0)
              | "times=N"             cap total fires at N
              | "kind=transient"      (default) retryable fault
              | "kind=deterministic"  never-retryable fault
              | "match=SUBSTR"        only fire when the check site's
                                      label contains SUBSTR (e.g. the
                                      plan's executor name)

Examples: ``"execute:every=3"``, ``"plan:once"``,
``"exchange:seed=7,p=0.25"``,
``"execute:at=1+3,kind=deterministic,match=xla"``.

Programmatic API: :func:`inject` arms one point (same knobs as the
grammar), :func:`clear` disarms everything programmatic, and the
``injected(...)`` context manager scopes an injection to a block. The
env spec is re-parsed (with counters reset) whenever the variable's
value changes, so a test fixture can arm/disarm by mutating the env —
the ``chaos`` pytest fixture in ``tests/conftest.py`` does exactly
that, restoring the env even on failure.

Every fired fault bumps the ``fault_injected`` metric (labels: point,
kind) and lands a ``fault_injected[point:kind]`` marker span on the
flight-recorder timeline, then raises :class:`InjectedFault` (its
``transient`` flag drives :func:`classify`, the error taxonomy the
serving tier's retry policy consults).

Disabled-path discipline: with ``DFFT_FAULT_INJECT`` unset and no
programmatic injection, :func:`check` is one env-dict lookup and an
early return — no state, no allocation, and the hosting plans' HLO is
untouched either way (faults raise around compiled code, never inside
it).
"""

from __future__ import annotations

import os
import random
from contextlib import contextmanager

from .utils import metrics as _metrics
from .utils.trace import add_trace, tracing_enabled

__all__ = [
    "POINTS",
    "InjectedFault",
    "check",
    "classify",
    "clear",
    "inject",
    "injected",
    "parse_spec",
    "reset",
]

#: The valid injection points (see the module docstring for where each
#: one's :func:`check` call lives).
POINTS = ("plan", "compile", "execute", "exchange")


class InjectedFault(RuntimeError):
    """A fault raised by :func:`check`. ``point`` names the injection
    point; ``transient`` says whether the retry policy may treat it as
    recoverable (``kind=transient``) or must not (``deterministic``)."""

    def __init__(self, point: str, kind: str, call: int):
        super().__init__(
            f"injected {kind} fault at point {point!r} (check #{call})")
        self.point = point
        self.transient = kind == "transient"


class _FaultPoint:
    """Armed state of one clause: counts checks, decides fires."""

    __slots__ = ("point", "kind", "mode", "n", "at", "p", "times",
                 "match", "_rng", "calls", "fires")

    def __init__(self, point: str, *, once: bool = False,
                 every: int | None = None, at: tuple[int, ...] = (),
                 p: float | None = None, seed: int = 0,
                 times: int | None = None, kind: str = "transient",
                 match: str = ""):
        if point not in POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; expected one of {POINTS}")
        if kind not in ("transient", "deterministic"):
            raise ValueError(
                f"fault kind must be transient|deterministic, got {kind!r}")
        modes = sum((bool(once), every is not None, bool(at),
                     p is not None))
        if modes != 1:
            raise ValueError(
                f"fault point {point!r} needs exactly one of "
                f"once|every=N|at=...|p=P")
        if every is not None and every < 1:
            raise ValueError(f"every={every} must be >= 1")
        if p is not None and not (0.0 <= p <= 1.0):
            raise ValueError(f"p={p} must be in [0, 1]")
        self.point = point
        self.kind = kind
        self.mode = ("once" if once else "every" if every is not None
                     else "at" if at else "p")
        self.n = every
        self.at = frozenset(at)
        self.p = p
        self.times = 1 if once else times
        self.match = match
        self._rng = random.Random(seed)
        self.calls = 0
        self.fires = 0

    def should_fire(self, label: str) -> bool:
        if self.match and self.match not in label:
            return False
        self.calls += 1
        if self.times is not None and self.fires >= self.times:
            return False
        if self.mode == "once":
            fire = self.calls == 1
        elif self.mode == "every":
            fire = self.calls % self.n == 0
        elif self.mode == "at":
            fire = self.calls in self.at
        else:
            fire = self._rng.random() < self.p
        if fire:
            self.fires += 1
        return fire


def parse_spec(raw: str) -> list[_FaultPoint]:
    """Parse one ``DFFT_FAULT_INJECT`` spec string into armed points.
    Raises ``ValueError`` on malformed clauses — a chaos spec that
    silently arms nothing would make every chaos test vacuously pass."""
    points: list[_FaultPoint] = []
    for clause in raw.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if ":" not in clause:
            raise ValueError(
                f"fault clause {clause!r} lacks a ':' (point:directives)")
        point, _, body = clause.partition(":")
        kw: dict = {"point": point.strip()}
        for directive in body.split(","):
            directive = directive.strip()
            if not directive:
                continue
            name, _, value = directive.partition("=")
            name = name.strip()
            value = value.strip()
            try:
                if name == "once" and not value:
                    kw["once"] = True
                elif name == "every":
                    kw["every"] = int(value)
                elif name == "at":
                    kw["at"] = tuple(int(v) for v in value.split("+"))
                elif name == "p":
                    kw["p"] = float(value)
                elif name == "seed":
                    kw["seed"] = int(value)
                elif name == "times":
                    kw["times"] = int(value)
                elif name == "kind":
                    kw["kind"] = value
                elif name == "match":
                    kw["match"] = value
                else:
                    raise ValueError(f"unknown directive {name!r}")
            except ValueError as e:
                raise ValueError(
                    f"fault clause {clause!r}: {e}") from None
        points.append(_FaultPoint(**kw))
    return points


# Armed state: the env layer (re-parsed whenever the variable's VALUE
# changes — counters reset with it, so a test that re-arms the same
# point starts a fresh deterministic sequence) and the programmatic
# layer (inject()/clear()).
_env_raw: str | None = None
_env_points: list[_FaultPoint] = []
_prog_points: list[_FaultPoint] = []


def inject(point: str, *, once: bool = False, every: int | None = None,
           at: tuple[int, ...] = (), p: float | None = None, seed: int = 0,
           times: int | None = None, kind: str = "transient",
           match: str = "") -> _FaultPoint:
    """Arm one injection point programmatically (the ``faults.inject``
    API — same knobs as the env-spec grammar). Returns the armed point;
    disarm with :func:`clear` (everything) or :func:`injected` (scoped)."""
    fp = _FaultPoint(point, once=once, every=every, at=at, p=p, seed=seed,
                     times=times, kind=kind, match=match)
    _prog_points.append(fp)
    return fp


def clear() -> None:
    """Disarm every programmatic injection (the env layer follows the
    env variable; unset it — or use the ``chaos`` fixture — to disarm)."""
    del _prog_points[:]


def reset() -> None:
    """Disarm everything AND force the env layer to re-parse (with fresh
    counters) on the next :func:`check` — test setup/teardown hook."""
    global _env_raw
    clear()
    _env_raw = None
    del _env_points[:]


@contextmanager
def injected(point: str, **kw):
    """Scope one programmatic injection to a block (armed on entry,
    disarmed on exit — even on failure)."""
    fp = inject(point, **kw)
    try:
        yield fp
    finally:
        try:
            _prog_points.remove(fp)
        except ValueError:
            pass  # a reset()/clear() inside the block already removed it


def _fire(fp: _FaultPoint) -> None:
    if _metrics._enabled:
        _metrics.inc("fault_injected", point=fp.point, kind=fp.kind)
    if tracing_enabled():
        # Zero-length marker span: the fault's position on the merged
        # flight-recorder timeline, next to the serve_*/t0..t3 spans.
        with add_trace(f"fault_injected[{fp.point}:{fp.kind}]"):
            pass
    raise InjectedFault(fp.point, fp.kind, fp.calls)


def check(point: str, label: str = "") -> None:
    """The injection hook: called by the hosting code at each point.
    Raises :class:`InjectedFault` when an armed clause decides to fire;
    otherwise returns immediately. ``label`` is site context the
    ``match=`` directive filters on (e.g. the plan's executor name)."""
    global _env_raw
    raw = os.environ.get("DFFT_FAULT_INJECT")
    if raw != _env_raw:
        _env_raw = raw
        _env_points[:] = parse_spec(raw) if raw else []
    if not _env_points and not _prog_points:
        return
    for fp in _env_points:
        if fp.point == point and fp.should_fire(label):
            _fire(fp)
    for fp in _prog_points:
        if fp.point == point and fp.should_fire(label):
            _fire(fp)


# --------------------------------------------------------- classification

#: Substrings of runtime-error messages that mark infrastructure blips
#: (the gRPC/absl status families a sick transport surfaces) — worth one
#: bounded retry, unlike a deterministic compile/shape error.
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED", "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
    "connection reset", "temporarily unavailable",
)


def classify(err: BaseException) -> str:
    """``"transient"`` (a bounded retry may recover it) or
    ``"deterministic"`` (retrying reproduces it — isolate or degrade
    instead). Injected faults carry their own flag; infrastructure blips
    (timeouts, connection errors, gRPC-status-marked runtime errors) are
    transient; everything else — shape errors, compile failures, the
    XLA:CPU fft-thunk fault — is deterministic, because retrying the
    same program on the same input cannot change the outcome."""
    if isinstance(err, InjectedFault):
        return "transient" if err.transient else "deterministic"
    if isinstance(err, (TimeoutError, ConnectionError, InterruptedError)):
        return "transient"
    msg = str(err)
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return "transient"
    return "deterministic"
