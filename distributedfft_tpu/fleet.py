"""Fleet observability plane — multi-process monitor aggregation.

PR 16's live monitor sees exactly one process; the ROADMAP's scale-out
serving item makes the *fleet* the unit that owns an SLO: N front-door
processes share tenant quotas, and a stall or burn on one member is a
fleet incident even when the others look healthy. This module is the
cross-process half of docs/OBSERVABILITY.md "Fleet view & load
generation":

1. **Shared-directory convention** — ``DFFT_MONITOR_DIR=dir`` makes
   every :class:`..monitor.Monitor` armed from the environment stream
   its JSONL series to ``dir/monitor-<host>-<pid>.jsonl``
   (:func:`series_path`; the :func:`..utils.atomicio.append_line`
   discipline keeps each file torn-line-free even if its writer dies
   mid-run). :func:`load_fleet` reads every series in the directory,
   lenient to empty files, foreign files, and torn last lines.

2. **Clock-offset estimation** — every schema-2 sample carries both a
   wall stamp (``ts``) and a monotonic stamp (``mono``). Within one
   host all processes share the monotonic epoch, so the per-stream
   anchor ``median(ts - mono)`` differs between two same-host streams
   exactly by their wall-clock disagreement (an NTP step mid-run, a
   container with a skewed clock). :func:`estimate_offsets` computes
   per-stream offsets relative to the per-host median anchor; streams
   on different hosts get no cross-host correction (monotonic epochs
   are boot times — unrelated across hosts — so skew and boot-age are
   indistinguishable there) and v1 samples without ``mono`` get 0.

3. **Merge** — :func:`merge_streams` re-buckets every stream onto one
   corrected timeline and emits *fleet samples* shaped exactly like
   monitor samples (summed queue depth/stalls/flush progress, summed
   metrics counters, per-tenant ledgers merged with a true quantile
   merge over the exported wait reservoirs), so the PR 16 health engine
   (:func:`..monitor.health_from_samples`) runs on the fleet series
   unchanged. Each fleet sample also carries a ``per_proc`` block — the
   per-process share of submits/sheds/stalls the imbalance checks read.

4. **Fleet health** — :func:`fleet_health` layers cross-stream verdicts
   on top: per-stream health, the merged-series health, plus
   ``fleet_stall`` (a member stalled or went quiet while peers
   progressed), ``straggler_skew`` (one member's wait p99 or burn rate
   diverging from the fleet median), and ``quota_imbalance`` (one
   process carrying nearly all of a shared tenant's traffic). ``report
   fleet --gate`` turns the verdict into a CI exit code; the loadgen
   (:mod:`..loadgen`) drives sustained mixed traffic through it.

Prometheus: :func:`prometheus_from_fleet` renders every stream's newest
sample with ``proc``/``host`` labels plus fleet-level aggregates, one
``# TYPE`` per family across the whole document.

Stdlib-only (no jax): the aggregator runs on an operator's laptop
against a directory rsync'd from the serving pod.
"""

from __future__ import annotations

import os
import statistics

from .monitor import (
    DEFAULT_BURN_THRESHOLD,
    DEFAULT_FAST_WINDOW_S,
    DEFAULT_SLOW_WINDOW_S,
    _delta,
    _prom_rows,
    _render_prom,
    _tenant_counter,
    health_from_samples,
    load_series,
)

__all__ = [
    "FLEET_SCHEMA",
    "series_path",
    "monitor_dir_from_env",
    "load_fleet",
    "estimate_offsets",
    "merge_streams",
    "fleet_health",
    "prometheus_from_fleet",
    "format_fleet",
]

#: Fleet-verdict format version (stamped into every fleet health doc).
FLEET_SCHEMA = 1

#: A member's newest sample may lag the fleet's newest by this many
#: sampling intervals before the member counts as "gone quiet" (its
#: writer wedged or died) for the ``fleet_stall`` verdict.
DEFAULT_LAG_FACTOR = 3.0

#: A member whose wait p99 exceeds ``skew_factor x`` the fleet median
#: (or whose fast-window burn rate does, against burning peers' median)
#: is flagged ``straggler_skew``.
DEFAULT_SKEW_FACTOR = 4.0

#: Ignore wait-skew verdicts below this absolute p99 (seconds) — at
#: micro waits, scheduler noise dwarfs any real divergence.
DEFAULT_MIN_SKEW_S = 1e-3

#: One process carrying more than this share of a shared tenant's
#: windowed submits (with at least ``_IMBALANCE_MIN_SUBMITS`` of them)
#: fires ``quota_imbalance``.
DEFAULT_IMBALANCE_SHARE = 0.9
_IMBALANCE_MIN_SUBMITS = 8.0


# ------------------------------------------------------------ directory


def monitor_dir_from_env() -> str | None:
    """The fleet series directory (``DFFT_MONITOR_DIR``), or None."""
    d = os.environ.get("DFFT_MONITOR_DIR", "").strip()
    return d or None


def series_path(dir_: str, host: str | None = None,
                pid: int | None = None) -> str:
    """This (or the named) process's series file under the shared fleet
    directory: ``monitor-<host>-<pid>.jsonl``."""
    from .monitor import _HOST

    return os.path.join(
        dir_, f"monitor-{host or _HOST}-{pid or os.getpid()}.jsonl")


def _stream_id(samples: list[dict], fallback: str) -> str:
    """Stream identity from the newest sample's stamps (``host:pid``,
    ``#<process_index>`` appended when the writer was a jax process),
    or the filename stem for pre-identity (v1) series."""
    newest = samples[-1]
    host, pid = newest.get("host"), newest.get("pid")
    if not host or pid is None:
        return fallback
    sid = f"{host}:{pid}"
    pi = newest.get("process_index")
    if isinstance(pi, int):
        sid += f"#{pi}"
    return sid


def load_fleet(dir_: str) -> dict[str, list[dict]]:
    """Every per-process monitor series under ``dir_``:
    ``{stream_id: samples (oldest first)}``. Lenient by construction —
    :func:`..monitor.load_series` drops torn/foreign lines, empty or
    unreadable series are skipped (a worker that died before its first
    sample must not sink the fleet view), and non-series files in the
    directory are ignored."""
    streams: dict[str, list[dict]] = {}
    try:
        names = sorted(os.listdir(dir_))
    except OSError:
        return {}
    for name in names:
        if not (name.startswith("monitor-") and name.endswith(".jsonl")):
            continue
        samples = load_series(os.path.join(dir_, name))
        if not samples:
            continue
        sid = _stream_id(samples, name[len("monitor-"):-len(".jsonl")])
        # Two files claiming one identity (a restarted pid): keep both,
        # disambiguated by filename.
        while sid in streams:
            sid += "'"
        streams[sid] = samples
    return streams


# --------------------------------------------------------- clock offsets


def _host_of(samples: list[dict]) -> str:
    return str(samples[-1].get("host") or "")


def estimate_offsets(streams: dict[str, list[dict]]) -> dict[str, float]:
    """Per-stream wall-clock offsets (seconds a stream's wall clock
    runs AHEAD of its host group's median): within each host, the
    anchor ``median(ts - mono)`` is shared-epoch, so anchor deltas are
    wall-clock skew. Corrected time = ``ts - offset``. Streams without
    monotonic stamps (v1 samples) and single-stream hosts get 0; no
    correction is attempted across hosts (monotonic epochs are
    unrelated boot times there)."""
    anchors: dict[str, float] = {}
    for sid, samples in streams.items():
        vals = [s["ts"] - s["mono"] for s in samples
                if isinstance(s.get("ts"), (int, float))
                and isinstance(s.get("mono"), (int, float))]
        if vals:
            anchors[sid] = statistics.median(vals)
    by_host: dict[str, list[str]] = {}
    for sid in anchors:
        by_host.setdefault(_host_of(streams[sid]), []).append(sid)
    offsets = {sid: 0.0 for sid in streams}
    for _, sids in by_host.items():
        if len(sids) < 2:
            continue
        ref = statistics.median(anchors[s] for s in sids)
        for sid in sids:
            offsets[sid] = anchors[sid] - ref
    return offsets


# ---------------------------------------------------------------- merge


def _median_interval(streams: dict[str, list[dict]]) -> float:
    """The fleet's sampling cadence: median inter-sample spacing across
    every stream (floor 1 ms; 1 s when no stream has two samples)."""
    gaps: list[float] = []
    for samples in streams.values():
        ts = [s.get("ts") for s in samples
              if isinstance(s.get("ts"), (int, float))]
        gaps.extend(b - a for a, b in zip(ts, ts[1:]) if b > a)
    if not gaps:
        return 1.0
    return max(1e-3, statistics.median(gaps))


def _merge_counters(snaps: list[dict | None]) -> dict:
    """Sum metrics counters across processes, per (name, label row)."""
    out: dict[str, dict[str, float]] = {}
    for snap in snaps:
        for name, rows in ((snap or {}).get("counters") or {}).items():
            dst = out.setdefault(name, {})
            for lbl, v in rows.items():
                if isinstance(v, (int, float)):
                    dst[lbl] = dst.get(lbl, 0.0) + float(v)
    return {"counters": out}


def _quantile(sorted_vals: list[float], q: float) -> float | None:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def _merge_tenants(docs: list[dict | None]) -> dict | None:
    """Merge per-process SLO ledgers into one fleet ledger: counters
    sum; waits are a true quantile merge — the exported reservoir tails
    are concatenated and the fleet p50/p99 read off the union, never
    averaged from per-process quantiles (quantiles do not average).
    ``slo_ok`` is re-judged from the merged evidence."""
    tenants: dict[str, dict] = {}
    waits: dict[str, list[float]] = {}
    any_doc = False
    for doc in docs:
        for tname, t in ((doc or {}).get("tenants") or {}).items():
            any_doc = True
            row = tenants.setdefault(tname, {
                "class": t.get("class"), "weight": t.get("weight"),
                "rate": t.get("rate"), "submits": 0, "transforms": 0,
                "quota_shed": 0, "deadline_misses": 0,
                "slo_wait_s": None,
            })
            for fld in ("submits", "transforms", "quota_shed",
                        "deadline_misses"):
                v = t.get(fld)
                if isinstance(v, (int, float)):
                    row[fld] += v
            if isinstance(t.get("slo_wait_s"), (int, float)):
                row["slo_wait_s"] = t["slo_wait_s"]
            w = t.get("waits")
            if isinstance(w, list):
                waits.setdefault(tname, []).extend(
                    float(x) for x in w if isinstance(x, (int, float)))
    if not any_doc:
        return None
    for tname, row in tenants.items():
        pool = sorted(waits.get(tname, ()))
        row["wait_p50_s"] = _quantile(pool, 0.50)
        row["wait_p99_s"] = _quantile(pool, 0.99)
        if row["slo_wait_s"] is not None:
            p99 = row["wait_p99_s"]
            row["slo_ok"] = (row["deadline_misses"] == 0
                             and (p99 is None or p99 <= row["slo_wait_s"]))
    return {"schema": 1, "tenants": tenants}


def _merge_waves(docs: list[dict]) -> dict:
    """Sum per-process wave-scheduler occupancy blocks (the queue
    block's ``waves`` snapshot, schema 3) into one fleet block. The
    counters and busy/idle second pools sum; ``idle_fraction`` is
    re-derived from the POOLED seconds (fractions do not average — a
    process that ran one wave must not weigh as much as one that ran a
    thousand), and ``width_mean`` is re-weighted by each member's wave
    count for the same reason."""
    out: dict = {"waves": 0, "preemptions": 0, "bumped_groups": 0,
                 "bumped_transforms": 0, "idle_s": 0.0, "busy_s": 0.0}
    wsum = 0.0
    dur_max = None
    for d in docs:
        for fld in ("waves", "preemptions", "bumped_groups",
                    "bumped_transforms"):
            v = d.get(fld)
            if isinstance(v, (int, float)):
                out[fld] += v
        for fld in ("idle_s", "busy_s"):
            v = d.get(fld)
            if isinstance(v, (int, float)):
                out[fld] += float(v)
        wm, n = d.get("width_mean"), d.get("waves")
        if isinstance(wm, (int, float)) and isinstance(n, (int, float)):
            wsum += wm * n
        dm = d.get("wave_duration_max_s")
        if isinstance(dm, (int, float)):
            dur_max = dm if dur_max is None else max(dur_max, dm)
    total = out["idle_s"] + out["busy_s"]
    out["idle_fraction"] = (out["idle_s"] / total) if total > 0 else None
    out["width_mean"] = (wsum / out["waves"]) if out["waves"] else None
    out["wave_duration_max_s"] = dur_max
    return out


def _proc_share(sample: dict) -> dict:
    """One process's contribution row for a fleet sample's ``per_proc``
    block."""
    qb = sample.get("queue") or {}
    tenants = ((sample.get("qos") or {}).get("tenants") or {})
    return {
        "ts": sample.get("ts"),
        "seq": sample.get("seq"),
        "depth": qb.get("depth", 0),
        "flush_seq": qb.get("flush_seq", 0),
        "stalls_total": qb.get("stalls_total", 0),
        "submits": sum(
            t.get("submits", 0) for t in tenants.values()
            if isinstance(t.get("submits"), (int, float))),
        "quota_shed": sum(
            t.get("quota_shed", 0) for t in tenants.values()
            if isinstance(t.get("quota_shed"), (int, float))),
        "deadline_misses": sum(
            t.get("deadline_misses", 0) for t in tenants.values()
            if isinstance(t.get("deadline_misses"), (int, float))),
    }


def _merge_numerics(blocks: list[dict | None]) -> dict | None:
    """Pool per-process numerics ledgers (monitor schema v4;
    docs/OBSERVABILITY.md "Numerics plane") into one fleet block — the
    wait-reservoir discipline applied to accuracy: counters sum, the
    exported realized-error tails concatenate per (plan, tenant)
    bucket, and the fleet p50/p99/drift verdict is re-ranked over the
    union (never averaged percentiles — quantiles do not average).
    Mixed-schema fleets (a rolling restart with pre-v4 members still
    streaming schema 2/3) treat absent blocks as empty: None when no
    member carries one."""
    from .numerics import DEFAULT_SLACK, judge_bucket

    blocks = [b for b in blocks if isinstance(b, dict)]
    if not blocks:
        return None
    slack = max((b["slack"] for b in blocks
                 if isinstance(b.get("slack"), (int, float))),
                default=DEFAULT_SLACK)
    out: dict = {"schema": 1, "sampled": 0, "audited": 0,
                 "audit_failures": 0, "slack": slack,
                 "nonfinite": {}, "plans": {}}
    pooled: dict[str, dict] = {}
    for b in blocks:
        for fld in ("sampled", "audited", "audit_failures"):
            v = b.get(fld)
            if isinstance(v, (int, float)):
                out[fld] += int(v)
        for k, v in (b.get("nonfinite") or {}).items():
            if isinstance(v, (int, float)):
                out["nonfinite"][k] = out["nonfinite"].get(k, 0) + int(v)
        for key, bucket in (b.get("plans") or {}).items():
            dst = pooled.setdefault(key, {
                "plan": bucket.get("plan"), "tenant": bucket.get("tenant"),
                "n": 0, "admitted_err": 0.0, "floor": 0.0, "errors": []})
            if isinstance(bucket.get("n"), (int, float)):
                dst["n"] += int(bucket["n"])
            for fld in ("admitted_err", "floor"):
                if isinstance(bucket.get(fld), (int, float)):
                    dst[fld] = max(dst[fld], float(bucket[fld]))
            errs = bucket.get("errors")
            if isinstance(errs, list):
                dst["errors"].extend(float(e) for e in errs
                                     if isinstance(e, (int, float)))
    for key, dst in sorted(pooled.items()):
        doc = judge_bucket(dst["errors"], dst["n"], dst["admitted_err"],
                           dst["floor"], slack)
        doc["plan"] = dst["plan"]
        doc["tenant"] = dst["tenant"]
        doc["errors"] = sorted(dst["errors"])[-64:]
        out["plans"][key] = doc
    return out


def merge_streams(
    streams: dict[str, list[dict]],
    *,
    offsets: dict[str, float] | None = None,
    bucket_s: float | None = None,
) -> list[dict]:
    """Merge N per-process series into one fleet sample series (oldest
    first), shaped like monitor samples so
    :func:`..monitor.health_from_samples` consumes it unchanged.

    Streams are clock-corrected (``ts - offset``), bucketed at the
    fleet's sampling cadence, and each stream contributes its newest
    sample at-or-before each bucket (carry-forward — lifetime counters
    are monotone, so a slow sampler's last reading stays correct until
    its next one). Per fleet sample: queue depth/groups/stalls/flush
    progress sum across members, metrics counters sum per label row,
    tenant ledgers merge with counter sums + reservoir quantile merge,
    and ``per_proc`` carries each member's share for the imbalance and
    straggler checks."""
    if not streams:
        return []
    if offsets is None:
        offsets = estimate_offsets(streams)
    width = bucket_s if bucket_s and bucket_s > 0 \
        else _median_interval(streams)

    # Per stream: bucket index -> newest sample in that bucket
    # (corrected time).
    per_stream: dict[str, dict[int, dict]] = {}
    lo, hi = None, None
    for sid, samples in streams.items():
        off = offsets.get(sid, 0.0)
        buckets: dict[int, dict] = {}
        for s in samples:
            ts = s.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            b = int((ts - off) / width)
            buckets[b] = s
            lo = b if lo is None else min(lo, b)
            hi = b if hi is None else max(hi, b)
        if buckets:
            per_stream[sid] = buckets
    if not per_stream:
        return []

    out: list[dict] = []
    last_seen: dict[str, dict] = {}
    for b in range(lo, hi + 1):
        advanced = False
        for sid, buckets in per_stream.items():
            if b in buckets:
                last_seen[sid] = buckets[b]
                advanced = True
        if not advanced or not last_seen:
            continue
        members = dict(last_seen)
        queues = [m.get("queue") for m in members.values()
                  if m.get("queue")]
        kind = next((q.get("kind") for q in queues if q.get("kind")), "")
        fleet_queue = None
        if queues:
            fleet_queue = {
                "kind": kind,
                "depth": sum(q.get("depth", 0) for q in queues),
                "groups": sum(q.get("groups", 0) for q in queues),
                "oldest_pending_age_s": max(
                    (q.get("oldest_pending_age_s", 0.0) for q in queues),
                    default=0.0),
                "flush_seq": sum(q.get("flush_seq", 0) for q in queues),
                "stalls_total": sum(q.get("stalls_total", 0)
                                    for q in queues),
            }
            wave_docs = [q["waves"] for q in queues
                         if isinstance(q.get("waves"), dict)]
            if wave_docs:
                fleet_queue["waves"] = _merge_waves(wave_docs)
                fleet_queue["streaming"] = any(
                    q.get("streaming") for q in queues)
        doc = {
            "schema": 2,
            "fleet": True,
            "ts": (b + 1) * width,
            "seq": b,
            "procs": len(members),
            "metrics": _merge_counters(
                [m.get("metrics") for m in members.values()]),
            "queue": fleet_queue,
            "qos": _merge_tenants([m.get("qos")
                                   for m in members.values()]),
            "per_proc": {sid: _proc_share(m)
                         for sid, m in sorted(members.items())},
        }
        # Schema tolerance (rolling restarts): members may mix monitor
        # schemas 2/3/4 in one directory — blocks a member does not
        # carry (waves, numerics) are treated as empty, and the merged
        # numerics block appears only when at least one member has one.
        nmerged = _merge_numerics([m.get("numerics")
                                   for m in members.values()])
        if nmerged is not None:
            doc["numerics"] = nmerged
        out.append(doc)
    return out


# --------------------------------------------------------- fleet health


def _stream_progressed(samples: list[dict], window_s: float) -> bool:
    """Did this member make serving progress in the window — flushes
    advanced or new submits arrived?"""
    def flush_of(s: dict) -> float:
        return float((s.get("queue") or {}).get("flush_seq") or 0)

    def submits_of(s: dict) -> float:
        tenants = ((s.get("qos") or {}).get("tenants") or {})
        return float(sum(t.get("submits", 0) for t in tenants.values()
                         if isinstance(t.get("submits"), (int, float))))

    return (_delta(samples, window_s, flush_of) > 0
            or _delta(samples, window_s, submits_of) > 0)


def _stream_stall_delta(samples: list[dict], window_s: float) -> float:
    def stalls_of(s: dict) -> float:
        return float((s.get("queue") or {}).get("stalls_total") or 0)

    return _delta(samples, window_s, stalls_of)


def _stream_burn(samples: list[dict], window_s: float) -> float:
    """Windowed bad-submit fraction across every tenant of one
    stream."""
    tenants = ((samples[-1].get("qos") or {}).get("tenants") or {})

    def bad(s: dict) -> float:
        return sum(_tenant_counter(s, t, "deadline_misses")
                   + _tenant_counter(s, t, "quota_shed") for t in tenants)

    def submits(s: dict) -> float:
        return sum(_tenant_counter(s, t, "submits") for t in tenants)

    return (_delta(samples, window_s, bad)
            / max(1.0, _delta(samples, window_s, submits)))


def _stream_wait_p99(samples: list[dict]) -> float | None:
    """The newest sample's worst per-tenant wait p99 (seconds)."""
    tenants = ((samples[-1].get("qos") or {}).get("tenants") or {})
    vals = [t.get("wait_p99_s") for t in tenants.values()
            if isinstance(t.get("wait_p99_s"), (int, float))]
    return max(vals) if vals else None


def fleet_health(
    streams: dict[str, list[dict]],
    *,
    fast_window_s: float = DEFAULT_FAST_WINDOW_S,
    slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
    burn_threshold: float = DEFAULT_BURN_THRESHOLD,
    skew_factor: float = DEFAULT_SKEW_FACTOR,
    min_skew_s: float = DEFAULT_MIN_SKEW_S,
    imbalance_share: float = DEFAULT_IMBALANCE_SHARE,
    lag_factor: float = DEFAULT_LAG_FACTOR,
    offsets: dict[str, float] | None = None,
    bucket_s: float | None = None,
) -> dict:
    """Fleet health verdicts: the PR 16 engine over the merged series,
    per-member verdicts over each stream, and the cross-stream checks
    no single member can see. The combined ``alerts`` list carries a
    ``scope`` per alert (``"fleet"`` for merged-series verdicts,
    ``"cross"`` for the fleet-only ones); ``status`` is ``"alert"``
    when any severity-alert fires anywhere — the ``report fleet
    --gate`` exit verdict.

    Cross-stream verdicts:

    - ``fleet_stall`` (alert) — a member stalled (its watchdog counted
      a stall in the fast window) or went quiet (its newest corrected
      sample lags the fleet's newest by more than ``lag_factor``
      sampling intervals) while at least one peer progressed.
    - ``straggler_skew`` (alert) — a member's worst tenant wait p99
      exceeds ``skew_factor x`` the fleet median (above ``min_skew_s``),
      or its fast-window burn rate exceeds ``burn_threshold`` while the
      fleet median burn stays under half the threshold.
    - ``quota_imbalance`` (warn) — one process carries more than
      ``imbalance_share`` of a shared tenant's windowed submits (the
      shared quota is not being shared).
    """
    if not streams:
        return {"schema": FLEET_SCHEMA, "status": "unknown",
                "procs": {}, "fleet": None, "alerts": [],
                "offsets": {}, "samples": 0}
    if offsets is None:
        offsets = estimate_offsets(streams)
    width = bucket_s if bucket_s and bucket_s > 0 \
        else _median_interval(streams)
    merged = merge_streams(streams, offsets=offsets, bucket_s=width)
    hkw = dict(fast_window_s=fast_window_s, slow_window_s=slow_window_s,
               burn_threshold=burn_threshold)
    fleet_verdict = health_from_samples(merged, **hkw)
    alerts: list[dict] = [dict(a, scope="fleet")
                          for a in fleet_verdict.get("alerts") or []]

    procs: dict[str, dict] = {}
    corrected_newest: dict[str, float] = {}
    for sid, samples in sorted(streams.items()):
        v = health_from_samples(samples, **hkw)
        ts = samples[-1].get("ts")
        corr = (ts - offsets.get(sid, 0.0)
                if isinstance(ts, (int, float)) else None)
        corrected_newest[sid] = corr if corr is not None else 0.0
        procs[sid] = {
            "status": v.get("status"),
            "samples": len(samples),
            "host": _host_of(samples),
            "newest_ts": ts,
            "clock_offset_s": offsets.get(sid, 0.0),
            "depth": ((samples[-1].get("queue") or {}).get("depth")
                      or 0),
            "stalls": _stream_stall_delta(samples, fast_window_s),
            "burn_fast": _stream_burn(samples, fast_window_s),
            "wait_p99_s": _stream_wait_p99(samples),
            "wave_idle_fraction": (
                ((samples[-1].get("queue") or {}).get("waves") or {})
                .get("idle_fraction")),
            "progressed": _stream_progressed(samples, fast_window_s),
            "alerts": v.get("alerts") or [],
        }

    # fleet_stall: stalled-or-quiet member + progressing peer. A member
    # whose series merely ends earlier than its peers' but drained to
    # depth 0 finished cleanly — "quiet" means it went dark with work
    # still queued (or without any recent progress), the dead-writer
    # shape.
    fleet_newest = max(corrected_newest.values(), default=0.0)
    for sid, p in procs.items():
        quiet = (fleet_newest - corrected_newest[sid]
                 > lag_factor * width
                 and (p["depth"] > 0 or not p["progressed"]))
        stalled = p["stalls"] > 0
        if not (stalled or quiet):
            continue
        peers_progress = any(q["progressed"] for osid, q in procs.items()
                             if osid != sid)
        if not peers_progress:
            continue
        how = ("stalled" if stalled else
               f"quiet for {fleet_newest - corrected_newest[sid]:.3g}s")
        alerts.append({
            "name": "fleet_stall", "severity": "alert", "scope": "cross",
            "proc": sid,
            "detail": f"member {sid} {how} while peers progress"})

    # straggler_skew: wait-p99 or burn-rate divergence vs fleet median.
    p99s = {sid: p["wait_p99_s"] for sid, p in procs.items()
            if isinstance(p["wait_p99_s"], (int, float))}
    if len(p99s) >= 2:
        med = statistics.median(p99s.values())
        for sid, v in sorted(p99s.items()):
            if v > max(min_skew_s, skew_factor * med) and med >= 0.0 \
                    and v > min_skew_s:
                alerts.append({
                    "name": "straggler_skew", "severity": "alert",
                    "scope": "cross", "proc": sid,
                    "detail": (f"member {sid} wait p99 {v:.3g}s vs "
                               f"fleet median {med:.3g}s")})
    burns = {sid: p["burn_fast"] for sid, p in procs.items()}
    if len(burns) >= 2:
        med_burn = statistics.median(burns.values())
        for sid, v in sorted(burns.items()):
            if v > burn_threshold and med_burn <= burn_threshold / 2:
                alerts.append({
                    "name": "straggler_skew", "severity": "alert",
                    "scope": "cross", "proc": sid,
                    "detail": (f"member {sid} burns {v:.0%} of submits "
                               f"while the fleet median burns "
                               f"{med_burn:.0%}")})

    # quota_imbalance: windowed per-tenant submit share per process.
    tenant_share: dict[str, dict[str, float]] = {}
    for sid, samples in streams.items():
        tenants = ((samples[-1].get("qos") or {}).get("tenants") or {})
        for tname in tenants:
            d = _delta(samples, fast_window_s,
                       lambda s, _t=tname: _tenant_counter(
                           s, _t, "submits"))
            tenant_share.setdefault(tname, {})[sid] = d
    for tname, shares in sorted(tenant_share.items()):
        if len(shares) < 2:
            continue
        total = sum(shares.values())
        if total < _IMBALANCE_MIN_SUBMITS:
            continue
        top_sid, top = max(shares.items(), key=lambda kv: kv[1])
        if top / total > imbalance_share:
            alerts.append({
                "name": "quota_imbalance", "severity": "warn",
                "scope": "cross", "proc": top_sid, "tenant": tname,
                "detail": (f"{top:g}/{total:g} of tenant {tname!r}'s "
                           f"windowed submits land on {top_sid}")})

    firing = [a for a in alerts if a.get("severity") == "alert"]
    return {
        "schema": FLEET_SCHEMA,
        "status": ("alert" if firing
                   else "warn" if alerts else "ok"),
        "procs": procs,
        "fleet": fleet_verdict,
        "alerts": alerts,
        "offsets": dict(sorted(offsets.items())),
        "samples": sum(len(s) for s in streams.values()),
        "bucket_s": width,
    }


# ----------------------------------------------------------- Prometheus


def prometheus_from_fleet(
    streams: dict[str, list[dict]],
    *,
    offsets: dict[str, float] | None = None,
) -> str:
    """The fleet in Prometheus text exposition format: every stream's
    newest sample rendered with ``proc``/``host`` labels (one ``# TYPE``
    per family across the whole document), plus the fleet aggregates —
    member count, summed queue depth, per-member clock offset — from
    the merged view."""
    if offsets is None:
        offsets = estimate_offsets(streams)
    rows: list[tuple] = []
    for sid, samples in sorted(streams.items()):
        newest = samples[-1]
        extra = {"proc": sid, "host": str(newest.get("host") or "")}
        rows.extend(_prom_rows(newest, extra))
    merged = merge_streams(streams, offsets=offsets)
    rows.append(("dfft_fleet_procs", "gauge",
                 f"dfft_fleet_procs {len(streams):g}"))
    if merged:
        newest = merged[-1]
        qb = newest.get("queue") or {}
        rows.append(("dfft_fleet_queue_depth", "gauge",
                     f"dfft_fleet_queue_depth {qb.get('depth', 0):g}"))
        rows.append(("dfft_fleet_queue_stalls_total", "counter",
                     f"dfft_fleet_queue_stalls_total "
                     f"{qb.get('stalls_total', 0):g}"))
        wv = qb.get("waves") or {}
        if wv:
            rows.append(("dfft_fleet_waves_total", "counter",
                         f"dfft_fleet_waves_total "
                         f"{wv.get('waves', 0):g}"))
            rows.append(("dfft_fleet_wave_preemptions_total", "counter",
                         f"dfft_fleet_wave_preemptions_total "
                         f"{wv.get('preemptions', 0):g}"))
            frac = wv.get("idle_fraction")
            if isinstance(frac, (int, float)):
                rows.append(("dfft_fleet_wave_idle_fraction", "gauge",
                             f"dfft_fleet_wave_idle_fraction "
                             f"{frac:.6f}"))
        for tname, t in sorted(
                ((newest.get("qos") or {}).get("tenants") or {}).items()):
            for fld, pname in (
                    ("submits", "dfft_fleet_tenant_submits_total"),
                    ("deadline_misses",
                     "dfft_fleet_tenant_slo_misses_total")):
                v = t.get(fld)
                if isinstance(v, (int, float)):
                    rows.append((
                        pname, "counter",
                        f'{pname}{{tenant="{tname}"}} {v:g}'))
    for sid in sorted(streams):
        rows.append((
            "dfft_fleet_clock_offset_seconds", "gauge",
            f'dfft_fleet_clock_offset_seconds{{proc="{sid}"}} '
            f"{offsets.get(sid, 0.0):.6f}"))
    return _render_prom(rows)


# ------------------------------------------------------------ rendering


def format_fleet(doc: dict) -> str:
    """Human rendering of a :func:`fleet_health` verdict: the fleet
    status line, one row per member, then the alerts."""
    lines = [f"fleet status: {doc.get('status', 'unknown')}   "
             f"({len(doc.get('procs') or {})} process(es), "
             f"{doc.get('samples', 0)} sample(s))"]
    procs = doc.get("procs") or {}
    if procs:
        wid = max(len("proc"), max(len(s) for s in procs))
        lines.append(f"{'proc':<{wid}}  {'status':<7} {'samples':>7}  "
                     f"{'offset_s':>9}  {'burn':>6}  {'p99_s':>9}  "
                     f"{'stalls':>6}  {'idle':>5}  progressed")
        for sid, p in sorted(procs.items()):
            p99 = p.get("wait_p99_s")
            idle = p.get("wave_idle_fraction")
            lines.append(
                f"{sid:<{wid}}  {str(p.get('status')):<7} "
                f"{p.get('samples', 0):>7d}  "
                f"{p.get('clock_offset_s', 0.0):>9.4f}  "
                f"{p.get('burn_fast', 0.0):>6.0%}  "
                f"{('-' if p99 is None else f'{p99:.6f}'):>9}  "
                f"{p.get('stalls', 0):>6g}  "
                f"{('-' if idle is None else f'{idle:.0%}'):>5}  "
                f"{'yes' if p.get('progressed') else 'no'}")
    alerts = doc.get("alerts") or []
    if not alerts:
        lines.append("no alerts")
    for a in alerts:
        where = f" proc={a['proc']}" if a.get("proc") else ""
        tenant = f" tenant={a['tenant']}" if a.get("tenant") else ""
        lines.append(f"[{a.get('severity', '?'):5s}] "
                     f"({a.get('scope', '?')}) {a.get('name', '?')}"
                     f"{where}{tenant}: {a.get('detail', '')}")
    return "\n".join(lines)
