"""Domain-decomposition geometry for distributed 3D FFTs.

TPU-native re-design of the geometry layer of the reference framework
(lueelu/DistributedFFT). The reference expresses decompositions as inclusive
``box3d`` index boxes with processor-grid search helpers
(``heffte/heffteBenchmark/include/heffte_geometry.h:67`` ``box3d``,
``:303`` ``make_procgrid``, ``:376`` ``split_world``, ``:516`` ``make_pencils``,
``:546`` ``make_slabs``, ``:589`` ``proc_setup_min_surface``) and, in the
first-party engine, as X/Y slab tables with asymmetric last-device counts
(``3dmpifft_opt/include/fft_mpi_3d_api.cpp:274-316``).

Here the same concepts are pure Python over half-open intervals. Uneven
divisions are expressed with *ceil-division padding* rather than per-peer
asymmetric count tables, because TPU collectives (``jax.lax.all_to_all``)
require equal shard sizes — see :func:`ceil_shards`.
"""

from __future__ import annotations

import itertools
import math
from dataclasses import dataclass, field
from typing import Iterable, Sequence


@dataclass(frozen=True, order=True)
class Box3:
    """A half-open axis-aligned index box ``[low, high)`` in 3D.

    Unlike the reference's inclusive-``high`` convention
    (``heffte_geometry.h:67``), ``high`` is exclusive, so ``shape`` is simply
    ``high - low`` and empty boxes are representable with ``low == high``.

    ``order`` is the box's *storage* axis order (heFFTe ``box3d::order``,
    ``heffte_geometry.h:67-92``): the caller's local buffer for this box
    holds the brick transposed by ``order`` in the numpy sense —
    ``stored = canonical.transpose(order)``, i.e. stored dimension ``j``
    runs over world axis ``order[j]`` (slowest dimension first, C order).
    heFFTe lists its order fast-to-slow, so a heFFTe box with order
    ``(f, m, s)`` maps to ``order=(s, m, f)`` here. Like the reference,
    ``order`` does not participate in box equality/comparison
    (``box3d::operator==`` ignores order).
    """

    low: tuple[int, int, int]
    high: tuple[int, int, int]
    order: tuple[int, int, int] = field(default=(0, 1, 2), compare=False)

    def __post_init__(self) -> None:
        if len(self.low) != 3 or len(self.high) != 3:
            raise ValueError("Box3 requires 3D low/high tuples")
        if any(h < l for l, h in zip(self.low, self.high)):
            raise ValueError(f"Box3 high must be >= low, got {self.low}..{self.high}")
        if tuple(sorted(self.order)) != (0, 1, 2):
            raise ValueError(
                f"Box3 order must be a permutation of (0, 1, 2), "
                f"got {self.order!r}")

    @property
    def shape(self) -> tuple[int, int, int]:
        return tuple(h - l for l, h in zip(self.low, self.high))  # type: ignore[return-value]

    @property
    def size(self) -> int:
        s = self.shape
        return s[0] * s[1] * s[2]

    @property
    def empty(self) -> bool:
        return self.size == 0

    @property
    def storage_shape(self) -> tuple[int, int, int]:
        """Shape of the caller's buffer for this box: ``shape`` permuted
        by ``order`` (identity order -> ``shape``)."""
        s = self.shape
        return tuple(s[o] for o in self.order)  # type: ignore[return-value]

    def with_order(self, order: Sequence[int]) -> "Box3":
        """Same box, different declared storage order."""
        return Box3(self.low, self.high, tuple(int(o) for o in order))  # type: ignore[arg-type]

    def contains(self, other: "Box3") -> bool:
        return all(
            sl <= ol and oh <= sh
            for sl, sh, ol, oh in zip(self.low, self.high, other.low, other.high)
        )

    def intersect(self, other: "Box3") -> "Box3":
        low = tuple(max(a, b) for a, b in zip(self.low, other.low))
        high = tuple(max(l, min(a, b)) for l, a, b in zip(low, self.high, other.high))
        return Box3(low, high, self.order)  # type: ignore[arg-type]

    def slices(self) -> tuple[slice, slice, slice]:
        """Numpy-style slices selecting this box out of the world array."""
        return tuple(slice(l, h) for l, h in zip(self.low, self.high))  # type: ignore[return-value]

    def surface(self) -> int:
        """Total surface area (the min-surface processor-grid cost metric,
        cf. ``proc_setup_min_surface``, ``heffte_geometry.h:589``)."""
        a, b, c = self.shape
        return 2 * (a * b + b * c + a * c)

    def r2c(self, axis: int) -> "Box3":
        """Shrink along ``axis`` to the r2c non-redundant half, size n//2+1
        (cf. ``box3d::r2c``, ``heffte_geometry.h:94``)."""
        n = self.high[axis] - self.low[axis]
        high = list(self.high)
        high[axis] = self.low[axis] + n // 2 + 1
        return Box3(self.low, tuple(high), self.order)  # type: ignore[arg-type]


def world_box(shape: Sequence[int]) -> Box3:
    """The full-problem index box for a global grid ``shape``."""
    return Box3((0, 0, 0), tuple(int(s) for s in shape))  # type: ignore[arg-type]


def find_world(boxes: Iterable[Box3]) -> Box3:
    """Bounding box of a set of boxes (cf. ``find_world``,
    ``heffte_geometry.h:196``)."""
    boxes = list(boxes)
    if not boxes:
        raise ValueError("find_world of no boxes")
    low = tuple(min(b.low[i] for b in boxes) for i in range(3))
    high = tuple(max(b.high[i] for b in boxes) for i in range(3))
    return Box3(low, high)  # type: ignore[arg-type]


def world_complete(boxes: Sequence[Box3], world: Box3) -> bool:
    """True iff ``boxes`` tile ``world`` exactly: disjoint and covering
    (cf. ``world_complete``, ``heffte_geometry.h:233``)."""
    total = sum(b.size for b in boxes)
    if total != world.size:
        return False
    for a, b in itertools.combinations([b for b in boxes if not b.empty], 2):
        if not a.intersect(b).empty:
            return False
    return all(world.contains(b) for b in boxes)


def even_splits(n: int, parts: int) -> list[tuple[int, int]]:
    """Split ``[0, n)`` into ``parts`` contiguous chunks differing by at most
    one, matching the reference's balanced splitter (``split_world``,
    ``heffte_geometry.h:376``). Returns (start, stop) pairs."""
    base, rem = divmod(n, parts)
    out = []
    start = 0
    for i in range(parts):
        stop = start + base + (1 if i < rem else 0)
        out.append((start, stop))
        start = stop
    return out


def ceil_splits(n: int, parts: int) -> list[tuple[int, int]]:
    """Split ``[0, n)`` into chunks of ``ceil(n/parts)`` with the remainder on
    the *last* part — the reference engine's slab rule (``ceil`` slabs with the
    short slab on the last device, ``fft_mpi_3d_api.cpp:274-316``). Trailing
    parts may be empty."""
    step = -(-n // parts)
    return [(min(i * step, n), min((i + 1) * step, n)) for i in range(parts)]


def ceil_shards(n: int, parts: int) -> int:
    """Padded per-shard extent for equal-size TPU collectives.

    ``jax.lax.all_to_all`` requires every shard equal, so where the reference
    builds asymmetric per-peer count tables for the last device
    (``fft_mpi_3d_api.cpp:93-133``), the TPU design pads the axis to
    ``parts * ceil(n/parts)`` and crops after the transform.
    """
    return -(-n // parts)


def split_world(world: Box3, grid: Sequence[int], *, rule=even_splits) -> list[Box3]:
    """Tile ``world`` with a ``grid[0] x grid[1] x grid[2]`` processor grid.

    Boxes are emitted with the *first* grid axis slowest, matching row-major
    rank order. ``rule`` selects balanced (heFFTe-style) or ceil (first-party
    engine-style) splitting.
    """
    per_axis = [
        [(world.low[d] + a, world.low[d] + b) for a, b in rule(world.shape[d], grid[d])]
        for d in range(3)
    ]
    out = []
    for (x0, x1), (y0, y1), (z0, z1) in itertools.product(*per_axis):
        out.append(Box3((x0, y0, z0), (x1, y1, z1)))
    return out


def factorizations3(p: int) -> list[tuple[int, int, int]]:
    """All ordered triples (a, b, c) with a*b*c == p."""
    out = []
    for a in range(1, p + 1):
        if p % a:
            continue
        q = p // a
        for b in range(1, q + 1):
            if q % b:
                continue
            out.append((a, b, q // b))
    return out


def factorizations2(p: int) -> list[tuple[int, int]]:
    """All ordered pairs (a, b) with a*b == p."""
    return [(a, p // a) for a in range(1, p + 1) if p % a == 0]


def make_procgrid(p: int) -> tuple[int, int]:
    """Most-square 2D factorization of ``p`` (cf. ``make_procgrid``,
    ``heffte_geometry.h:303``)."""
    best = (1, p)
    for a, b in factorizations2(p):
        if abs(a - b) < abs(best[0] - best[1]):
            best = (a, b)
    return best


def pencil_grid_min_surface(shape: Sequence[int], p: int) -> tuple[int, int]:
    """2D processor grid (rows over axis 0, cols over axis 1) minimizing the
    surface area of the input z-pencil boxes — the pencil-planner analog of
    ``proc_setup_min_surface`` (``heffte_geometry.h:589-626``). Ties prefer
    more rows (the most-square heritage orientation of ``make_procgrid``).

    Kept in float lockstep with the native ``dfft_pencil_grid``
    (``native/dfft_native.cpp``); tests pin the two together.
    """
    n0, n1, n2 = (int(s) for s in shape)
    best = None  # (cost, r, c)
    for r, c in factorizations2(int(p)):
        sx, sy = n0 / r, n1 / c
        cost = sx * sy + sy * n2 + sx * n2
        if best is None or cost < best[0] or (cost == best[0] and r > best[1]):
            best = (cost, r, c)
    return best[1], best[2]


def proc_setup_min_surface(world: Box3, p: int) -> tuple[int, int, int]:
    """3D processor grid minimizing total box surface area — the reference's
    default-grid search (``proc_setup_min_surface``, ``heffte_geometry.h:589``).

    Surface area is a proxy for communication volume; on a TPU mesh it is a
    proxy for all-to-all payload per ICI hop.
    """
    nx, ny, nz = world.shape

    def cost(grid: tuple[int, int, int]) -> float:
        gx, gy, gz = grid
        return (nx / gx) * (ny / gy) + (ny / gy) * (nz / gz) + (nx / gx) * (nz / gz)

    return min(factorizations3(p), key=cost)


def make_slabs(world: Box3, p: int, axis: int = 0, *, rule=even_splits) -> list[Box3]:
    """1D slab decomposition over ``axis`` (cf. ``make_slabs``,
    ``heffte_geometry.h:546``; the first-party engine's only mode, X-slabs,
    ``fft_mpi_3d_api.cpp:274-287``)."""
    grid = [1, 1, 1]
    grid[axis] = p
    return split_world(world, grid, rule=rule)


def make_pencils(
    world: Box3, grid2: Sequence[int], long_axis: int, *, rule=even_splits
) -> list[Box3]:
    """Pencil decomposition: full extent along ``long_axis``, 2D grid over the
    other two axes (cf. ``make_pencils``, ``heffte_geometry.h:516``)."""
    if len(grid2) != 2:
        raise ValueError("grid2 must have two entries")
    grid = [0, 0, 0]
    grid[long_axis] = 1
    others = [d for d in range(3) if d != long_axis]
    grid[others[0]], grid[others[1]] = int(grid2[0]), int(grid2[1])
    return split_world(world, grid, rule=rule)


def is_slab(boxes: Sequence[Box3], world: Box3, axes: tuple[int, int]) -> bool:
    """True if every box spans the world along both ``axes`` (cf. ``is_slab``,
    ``heffte_geometry.h:411``)."""
    return all(
        b.low[a] == world.low[a] and b.high[a] == world.high[a]
        for b in boxes
        for a in axes
    )


def is_pencil(boxes: Sequence[Box3], world: Box3, axis: int) -> bool:
    """True if every box spans the world along ``axis``."""
    return all(
        b.low[axis] == world.low[axis] and b.high[axis] == world.high[axis]
        for b in boxes
    )


def pad_to(n: int, parts: int) -> int:
    """Smallest multiple of ``parts`` that is >= ``n``."""
    return parts * ceil_shards(n, parts)


def fft_flops(shape: Sequence[int]) -> float:
    """The 5 N log2 N flop model used by every reference benchmark
    (``3dmpifft_opt/fftSpeed3d_c2c.cpp:128``, ``benchmarks/speed3d.h:159``)."""
    n = math.prod(shape)
    return 5.0 * n * math.log2(n)
