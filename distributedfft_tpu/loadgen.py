"""Mixed-traffic load generator — the fleet's workload driver.

``python -m distributedfft_tpu.loadgen`` closes the loop the ROADMAP
called out after PR 16: the monitor/health/QoS stack was only ever
measured under unit tests, never under sustained mixed multi-tenant
traffic. This module generates that traffic — deterministically, at
CPU-friendly reduced scale — and then judges the run with the fleet
gate (docs/OBSERVABILITY.md "Fleet view & load generation"):

1. **Schedule** (:func:`build_schedule`) — a pure function of
   ``(seed, rank, knobs)``: open-loop Poisson arrivals at ``--rate``
   per process over ``--duration`` seconds, each event drawing a
   tenant from the weighted ``--mix``, a shape from ``--shapes``, a
   dtype from ``--dtypes``, and a direction from ``--ops``. Same seed,
   same schedule — a regression in the serving tier reproduces under
   the exact byte-identical workload.

2. **Workers** — the parent spawns ``--procs`` subprocesses (``--worker
   --rank i``), each driving its own ``DFFT_QOS`` +
   ``DFFT_MONITOR_DIR``-armed :class:`..serving.CoalescingQueue` on CPU
   (``JAX_PLATFORMS=cpu``). Open-loop discipline: the worker submits on
   schedule regardless of completion and drains with an explicit
   ``flush()`` cadence (``--flush-every``) — arrival rate is the
   independent variable, so backpressure shows up in the monitor series
   (depth, waits, sheds) instead of silently slowing the generator.
   ``--streaming`` swaps the cadence for the persistent wave drain loop
   (``serve()``/``stop()``; docs/SERVING_QOS.md "Streaming scheduler"):
   the loop owns dispatch, each worker's stats line carries its wave
   count/preemptions/idle fraction, and the monitor series record the
   schema-3 ``waves`` occupancy block the fleet gate aggregates.

3. **Fault drill** — ``DFFT_FAULT_INJECT`` in the parent environment is
   forwarded to exactly one worker (``--fault-rank``, default 0) and
   stripped from the rest. When the injected fault kills that worker's
   flush, its dispatcher wedges — it keeps *submitting* but stops
   *draining* (the realistic sick-member shape: traffic still arrives,
   nothing completes). Its pending groups age past the monitor's stall
   watchdog with no flush progress, the member's series records the
   stall, and the fleet gate must go red while the healthy peers stay
   green — the CI fleet smoke asserts exactly this asymmetry.

4. **Verdict** — after the workers join, the parent aggregates the
   ``--dir`` series via :func:`..fleet.fleet_health` and prints the
   fleet report (``--json`` for the machine form); ``--gate`` turns it
   into an exit code (0 ok/warn, 1 alert), mirroring ``report fleet
   --gate``.

The generator needs jax only inside workers (CPU backend); the parent
and the schedule are stdlib-pure so tests can exercise determinism and
parsing without a device runtime.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import tempfile
import time

__all__ = [
    "Event",
    "build_schedule",
    "parse_mix",
    "parse_shapes",
    "DEFAULT_QOS",
    "DEFAULT_MIX",
    "DEFAULT_SHAPES",
    "main",
]

#: Default two-tenant policy: a realtime tenant with a generous wait
#: SLO and 3x the batch tenant's drain share. Deliberately quota-free —
#: the healthy smoke must gate green, so nothing sheds by default.
DEFAULT_QOS = "rt:class=realtime,weight=3,slo=5;bulk:class=batch"

#: Default traffic mix (tenant:weight, matching :data:`DEFAULT_QOS`).
DEFAULT_MIX = "rt:3,bulk:1"

#: Default shape mix — tiny 3D tuples (the queue serves unbatched 3D
#: transforms) so a CPU worker sustains hundreds of arrivals per second
#: without the FFT dominating the run.
DEFAULT_SHAPES = "8x8x8,16x8x4"


# ------------------------------------------------------------- schedule


class Event:
    """One scheduled arrival: ``t`` seconds after worker start."""

    __slots__ = ("t", "tenant", "shape", "dtype", "op")

    def __init__(self, t, tenant, shape, dtype, op):
        self.t = t
        self.tenant = tenant
        self.shape = shape
        self.dtype = dtype
        self.op = op

    def astuple(self) -> tuple:
        return (self.t, self.tenant, self.shape, self.dtype, self.op)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Event{self.astuple()!r}"


def parse_mix(raw: str) -> list[tuple[str | None, float]]:
    """``"rt:3,bulk:1"`` -> ``[("rt", 3.0), ("bulk", 1.0)]``. A bare
    name weighs 1; ``"-"`` is the anonymous (no-tenant) lane; empty
    spec -> one anonymous lane."""
    out: list[tuple[str | None, float]] = []
    for part in (p.strip() for p in raw.split(",")):
        if not part:
            continue
        name, _, w = part.partition(":")
        weight = 1.0
        if w.strip():
            weight = float(w)
            if weight <= 0:
                raise ValueError(
                    f"mix weight must be positive, got {part!r}")
        out.append((None if name.strip() == "-" else name.strip(),
                    weight))
    return out or [(None, 1.0)]


def parse_shapes(raw: str) -> list[tuple[int, ...]]:
    """``"16x16,32x8x2"`` -> ``[(16, 16), (32, 8, 2)]``."""
    out = []
    for part in (p.strip() for p in raw.split(",")):
        if not part:
            continue
        dims = tuple(int(d) for d in part.split("x"))
        if not dims or any(d < 1 for d in dims):
            raise ValueError(f"bad shape {part!r}")
        out.append(dims)
    if not out:
        raise ValueError(f"no shapes in {raw!r}")
    return out


def build_schedule(
    *,
    seed: int,
    rank: int,
    duration_s: float,
    rate_hz: float,
    mix: list[tuple[str | None, float]],
    shapes: list[tuple[int, ...]],
    dtypes: list[str],
    ops: list[str],
) -> list[Event]:
    """The rank's full arrival schedule — a pure function of its
    arguments (the rng seeds on ``seed:rank``, so ranks draw distinct
    but reproducible streams). Open-loop Poisson arrivals: exponential
    inter-arrival gaps at ``rate_hz``, truncated at ``duration_s``."""
    if rate_hz <= 0 or duration_s <= 0:
        return []
    rng = random.Random(f"{seed}:{rank}")
    tenants = [t for t, _ in mix]
    weights = [w for _, w in mix]
    out: list[Event] = []
    t = 0.0
    while True:
        t += rng.expovariate(rate_hz)
        if t >= duration_s:
            return out
        out.append(Event(
            t,
            rng.choices(tenants, weights)[0],
            rng.choice(shapes),
            rng.choice(dtypes),
            rng.choice(ops),
        ))


# --------------------------------------------------------------- worker


def _run_worker(ns: argparse.Namespace) -> int:
    """One load-generating process: drive a monitor-armed queue through
    this rank's schedule, explicit-flush cadence, wedge-on-fault."""
    import numpy as np

    from .local import BACKWARD, FORWARD
    from .serving import CoalescingQueue

    events = build_schedule(
        seed=ns.seed, rank=ns.rank, duration_s=ns.duration,
        rate_hz=ns.rate, mix=parse_mix(ns.mix),
        shapes=parse_shapes(ns.shapes),
        dtypes=[d.strip() for d in ns.dtypes.split(",") if d.strip()],
        ops=[o.strip() for o in ns.ops.split(",") if o.strip()])
    mesh = None
    if ns.mesh > 0:
        # Distributed plans: the wire codec (DFFT_WIRE_DTYPE) only
        # engages on a multi-device mesh, so numerics drift drills
        # need this armed (single-device plans are exact by
        # construction).
        from .parallel.mesh import make_mesh

        mesh = make_mesh(ns.mesh)
    queue = CoalescingQueue(
        mesh,
        max_batch=ns.max_batch,
        max_wait_s=ns.max_wait if ns.max_wait and ns.max_wait > 0
        else None,
        streaming=bool(ns.streaming))
    has_policy = queue.policy is not None

    # One buffer per (shape, dtype) — the generator measures the
    # serving tier, not numpy allocation.
    bufs: dict[tuple, object] = {}

    def buf(shape, dtype):
        key = (shape, dtype)
        if key not in bufs:
            rng = np.random.default_rng(ns.seed + ns.rank)
            x = rng.standard_normal(shape)
            if dtype.startswith("complex"):
                x = x.astype(dtype) + 1j * rng.standard_normal(shape) \
                    .astype(dtype)
            else:
                x = x.astype(dtype)
            bufs[key] = x
        return bufs[key]

    stats = {"rank": ns.rank, "pid": os.getpid(), "submitted": 0,
             "shed": 0, "flushed": 0, "wedged": False,
             "mode": "streaming" if ns.streaming else "flush"}
    # --hot-tail P: seeded heavy-tailed amplitude mixing — a fraction P
    # of submits scale one random octant block of their input by ~1e4
    # (docs/OBSERVABILITY.md "Numerics plane"). Pure data shaping: the
    # schedule, tenancy, and arrival times stay byte-identical to the
    # P=0 run; what changes is the dynamic range the block-scaled wire
    # codecs see — a hot member batched into a cohort poisons the
    # shared per-tile scales, and the shadow audit must catch it.
    hot_rng = random.Random(f"{ns.seed}:{ns.rank}:hot")

    def maybe_hot(x):
        if ns.hot_tail <= 0 or hot_rng.random() >= ns.hot_tail:
            return x
        y = np.array(x, copy=True)
        sl = tuple(
            slice(0, max(1, n // 2)) if hot_rng.random() < 0.5
            else slice(n - max(1, n // 2), n) for n in y.shape)
        y[sl] *= 1e4
        return y

    wedged = False
    start = time.monotonic()
    next_flush = ns.flush_every
    for ev in events:
        now = time.monotonic() - start
        if ev.t > now:
            time.sleep(ev.t - now)
            now = ev.t
        # Streaming mode: the persistent drain loop owns dispatch —
        # the explicit flush cadence stays off (an injected fault
        # fails that wave's handles but never wedges the loop, so the
        # wedge drill below is a flush-mode shape by construction).
        while not ns.streaming and not wedged and now >= next_flush:
            next_flush += ns.flush_every
            try:
                stats["flushed"] += queue.flush(reason="manual")
            except Exception:  # noqa: BLE001 — injected faults land
                # here: the dispatcher wedges (stops draining) while
                # arrivals continue, so the stall is visible to the
                # monitor instead of crashing the generator.
                wedged = True
                stats["wedged"] = True
        try:
            queue.submit(maybe_hot(buf(ev.shape, ev.dtype)),
                         direction=FORWARD if ev.op != "ifft"
                         else BACKWARD,
                         tenant=ev.tenant if has_policy else None)
            stats["submitted"] += 1
        except Exception:  # noqa: BLE001 — quota sheds / admission
            stats["shed"] += 1  # rejects are load-test data, not crashes
    # Let the monitor observe the terminal state: a wedged worker sits
    # on its leftover pending groups (partial batches its dead
    # dispatcher will never drain) until they age past the stall
    # watchdog's grace, so the stall lands in the series before the
    # final sample.
    if wedged:
        time.sleep(ns.linger)
        m = queue._monitor
        if m is not None:
            m.stop()  # final sample; close() would flush (and raise)
    elif ns.streaming:
        # Drain the in-flight waves through the loop, then snapshot the
        # scheduler occupancy into the worker stats line before close()
        # tears the recorder down.
        queue.stop(drain=True)
        ws = queue._wave_stats
        if ws is not None:
            snap = ws.snapshot()
            stats["waves"] = snap.get("waves", 0)
            stats["preemptions"] = snap.get("preemptions", 0)
            stats["idle_fraction"] = snap.get("idle_fraction")
        queue.close()
    else:
        try:
            stats["flushed"] += queue.flush(reason="manual")
        except Exception:  # noqa: BLE001
            stats["wedged"] = True
        queue.close()
    # Numerics-plane summary (when DFFT_SHADOW_RATE armed the plane):
    # how many requests were shadow-audited and the worst bucket's
    # drift ratio — the worker-stats view of what the fleet gate
    # judges.
    from .numerics import numerics_snapshot

    nsnap = numerics_snapshot()
    if nsnap is not None:
        stats["shadow_sampled"] = nsnap.get("sampled", 0)
        stats["drift_ratio"] = max(
            (b.get("drift_ratio", 0.0)
             for b in (nsnap.get("plans") or {}).values()), default=0.0)
    print(json.dumps(stats))
    return 0


# --------------------------------------------------------------- parent


def _spawn(ns: argparse.Namespace, rank: int, dir_: str):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["DFFT_MONITOR_DIR"] = dir_
    env["DFFT_MONITOR"] = f"{ns.interval:g}"
    env["DFFT_METRICS"] = "1"
    if ns.qos:
        env["DFFT_QOS"] = ns.qos
    else:
        env.pop("DFFT_QOS", None)
    # The fault drill hits exactly one member; everyone else must not
    # inherit the injection from the parent environment.
    if rank != ns.fault_rank:
        env.pop("DFFT_FAULT_INJECT", None)
    argv = [sys.executable, "-m", "distributedfft_tpu.loadgen",
            "--worker", "--rank", str(rank)]
    for flag, val in (
            ("--seed", ns.seed), ("--duration", ns.duration),
            ("--rate", ns.rate), ("--mix", ns.mix),
            ("--shapes", ns.shapes), ("--dtypes", ns.dtypes),
            ("--ops", ns.ops), ("--max-batch", ns.max_batch),
            ("--max-wait", ns.max_wait),
            ("--flush-every", ns.flush_every),
            ("--hot-tail", ns.hot_tail),
            ("--mesh", ns.mesh),
            ("--linger", ns.linger)):
        argv.extend([flag, str(val)])
    if ns.streaming:
        argv.append("--streaming")
    return subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.loadgen",
        description="Deterministic open-loop mixed-traffic generator "
                    "+ fleet gate (docs/OBSERVABILITY.md)")
    ap.add_argument("--procs", type=int, default=2,
                    help="worker processes to spawn (default 2)")
    ap.add_argument("--duration", type=float, default=4.0,
                    help="seconds of traffic per worker (default 4)")
    ap.add_argument("--rate", type=float, default=50.0,
                    help="arrivals/s per worker (default 50)")
    ap.add_argument("--seed", type=int, default=0,
                    help="schedule seed (same seed = same traffic)")
    ap.add_argument("--dir", default=None,
                    help="fleet series directory (default: a fresh "
                         "temp dir, printed)")
    ap.add_argument("--qos", default=DEFAULT_QOS,
                    help="DFFT_QOS spec for the workers ('' disables)")
    ap.add_argument("--mix", default=DEFAULT_MIX,
                    help="tenant:weight arrival mix (default "
                         f"{DEFAULT_MIX!r}; '-' = anonymous)")
    ap.add_argument("--shapes", default=DEFAULT_SHAPES,
                    help=f"shape mix (default {DEFAULT_SHAPES!r})")
    ap.add_argument("--dtypes", default="complex64",
                    help="dtype mix (default complex64)")
    ap.add_argument("--ops", default="fft,ifft",
                    help="op mix: fft|ifft (default both)")
    ap.add_argument("--streaming", action="store_true",
                    help="drive the workers through the persistent "
                         "streaming drain loop (serve()/stop(); "
                         "docs/SERVING_QOS.md 'Streaming scheduler') "
                         "instead of the explicit flush cadence")
    ap.add_argument("--max-batch", type=int, default=8,
                    help="queue max_batch (default 8)")
    ap.add_argument("--max-wait", type=float, default=0.0,
                    help="queue max_wait_s; 0 = explicit-flush only "
                         "(default)")
    ap.add_argument("--interval", type=float, default=0.25,
                    help="monitor sampling interval seconds "
                         "(default 0.25)")
    ap.add_argument("--flush-every", type=float, default=0.05,
                    help="worker flush cadence seconds (default 0.05)")
    ap.add_argument("--mesh", type=int, default=0, metavar="N",
                    help="worker mesh size: 0 (default) = meshless "
                         "single-device plans (exact, no wire); N >= 1 "
                         "= make_mesh(N) distributed plans so the wire "
                         "codec engages (numerics drills)")
    ap.add_argument("--hot-tail", type=float, default=0.0, metavar="P",
                    help="fraction of submits that scale a random "
                         "block of the input by ~1e4 (seeded "
                         "heavy-tailed amplitude mixing; stresses "
                         "shared-exponent wire codecs for numerics "
                         "drift drills — docs/OBSERVABILITY.md "
                         "'Numerics plane')")
    ap.add_argument("--linger", type=float, default=4.5,
                    help="wedged-worker linger after the schedule ends "
                         "so its leftover pending groups age past the "
                         "monitor's stall grace (4x1s by default) and "
                         "the watchdog fires before the final sample "
                         "(default 4.5)")
    ap.add_argument("--fault-rank", type=int, default=0,
                    help="the one rank that inherits DFFT_FAULT_INJECT "
                         "from the parent env (default 0)")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 when the fleet verdict is 'alert'")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable fleet verdict")
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--rank", type=int, default=0,
                    help=argparse.SUPPRESS)
    ns = ap.parse_args(argv)

    if ns.worker:
        return _run_worker(ns)

    from .fleet import fleet_health, format_fleet, load_fleet

    dir_ = ns.dir or tempfile.mkdtemp(prefix="dfft-fleet-")
    os.makedirs(dir_, exist_ok=True)
    procs = [_spawn(ns, r, dir_) for r in range(max(1, ns.procs))]
    worker_stats = []
    deadline = time.monotonic() + ns.duration + ns.linger + 60.0
    for p in procs:
        try:
            out, _ = p.communicate(
                timeout=max(5.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        for line in (out or "").splitlines():
            try:
                worker_stats.append(json.loads(line))
            except ValueError:
                pass

    doc = fleet_health(load_fleet(dir_))
    doc["dir"] = dir_
    doc["workers"] = worker_stats
    if ns.json:
        print(json.dumps(doc, indent=2, sort_keys=True, default=str))
    else:
        print(f"series dir: {dir_}")
        for w in worker_stats:
            print(f"worker rank={w.get('rank')} pid={w.get('pid')}: "
                  f"{w.get('submitted', 0)} submitted, "
                  f"{w.get('shed', 0)} shed, "
                  f"{w.get('flushed', 0)} flushed"
                  + (f", {w['waves']} waves"
                     f" ({w.get('preemptions', 0)} preempted)"
                     if w.get("waves") is not None else "")
                  + (" [WEDGED]" if w.get("wedged") else ""))
        print(format_fleet(doc))
    if ns.gate:
        return 1 if doc.get("status") == "alert" else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
