"""Batched local (single-device) 1D/2D/3D transforms.

The templateFFT public-surface analog (``templateFFT/src/templateFFT.h``:
``FFTConfiguration`` holds ``size[3]`` + ``numberBatches`` (``:84-132``),
``initializeFFT``/``launchFFTKernel`` (``:340-344``)), as exercised by the
batchTest harness (1D batched and 2D benchmarks,
``templateFFT/batchTest/Test_1D.cpp:29``, ``Test_2D.cpp``).

A :class:`LocalPlan` is the compiled, batched transform of the trailing
``rank`` axes of a ``[batch, *shape]`` array. On TPU the batch dimension is
exactly what keeps the MXU/VPU busy — the analog of templateFFT filling the
GPU with one kernel over ``numberBatches`` lines.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from .ops.executors import Scale, apply_scale, get_executor

FORWARD = -1
BACKWARD = +1


@dataclass
class LocalPlan:
    """A compiled batched C2C transform over the trailing axes."""

    shape: tuple[int, ...]
    batch: int
    direction: int
    dtype: Any
    executor: str
    fn: Callable

    @property
    def forward(self) -> bool:
        return self.direction == FORWARD

    @property
    def transform_size(self) -> int:
        return math.prod(self.shape)

    def flops(self) -> float:
        """5 N log2 N per transform times the batch count
        (``Test_1D.cpp:139``)."""
        n = self.transform_size
        return 5.0 * n * math.log2(n) * self.batch

    def __call__(self, x, *, scale: Scale = Scale.NONE):
        x = jnp.asarray(x, dtype=self.dtype)
        expect = (self.batch,) + self.shape
        if x.shape != expect:
            raise ValueError(f"plan input shape is {expect}, got {x.shape}")
        y = self.fn(x)
        if scale != Scale.NONE:
            y = apply_scale(y, scale, self.transform_size)
        return y


def plan_dft_c2c(
    shape: Sequence[int] | int,
    *,
    batch: int = 1,
    direction: int = FORWARD,
    executor: str = "xla",
    dtype: Any = None,
    donate: bool = False,
) -> LocalPlan:
    """Plan a batched local C2C FFT of rank ``len(shape)`` (1, 2, or 3).

    Input/output shape is ``[batch, *shape]``; the transform runs over the
    trailing axes. cf. ``initializeFFT`` + ``FFTConfiguration``
    (``templateFFT.h:84-132,340``).
    """
    if isinstance(shape, int):
        shape = (shape,)
    shape = tuple(int(s) for s in shape)
    if not 1 <= len(shape) <= 3:
        raise ValueError("plan_dft_c2c supports rank 1..3 transforms")
    if direction not in (FORWARD, BACKWARD):
        raise ValueError("direction must be FORWARD (-1) or BACKWARD (+1)")
    if dtype is None:
        dtype = jnp.complex128 if jax.config.jax_enable_x64 else jnp.complex64
    ex = get_executor(executor)
    axes = tuple(range(1, 1 + len(shape)))
    fwd = direction == FORWARD
    fn = jax.jit(
        lambda x: ex(x, axes, fwd), donate_argnums=(0,) if donate else ()
    )
    return LocalPlan(
        shape=shape, batch=int(batch), direction=direction,
        dtype=jnp.dtype(dtype), executor=executor, fn=fn,
    )


def plan_dft_c2c_1d(n: int, **kw) -> LocalPlan:
    """Batched 1D plan (the batchTest 1D harness shape,
    ``Test_1D.cpp:29``)."""
    return plan_dft_c2c((n,), **kw)


def plan_dft_c2c_2d(shape: Sequence[int], **kw) -> LocalPlan:
    """Batched 2D plan (``Test_2D.cpp``)."""
    if len(tuple(shape)) != 2:
        raise ValueError("plan_dft_c2c_2d requires a 2D shape")
    return plan_dft_c2c(shape, **kw)
