"""Live serving monitor — streaming export, health engine, overlap
attribution.

Everything the observability stack had so far is per-run and offline:
telemetry snapshots ride bench result lines, the flight recorder dumps
at ``finalize_tracing``, the QoS ledger is written when someone asks.
This module watches a *running* serving tier (docs/OBSERVABILITY.md
"Live monitoring & health"), in three pillars:

1. **Streaming export** — :class:`Monitor` runs a daemon sampler
   (``Monitor(queue, interval_s=...)``, or ``DFFT_MONITOR=interval[,path]``
   which every :class:`..serving.CoalescingQueue` arms at construction)
   that periodically joins :func:`..utils.metrics.metrics_snapshot`, the
   queue's depth/pending-age, and the QoS policy's
   :meth:`..qos.QosPolicy.slo_report` into one sample document,
   appended as a JSONL time series with the
   :func:`..utils.atomicio.append_line` discipline (line-atomic under
   concurrent writers — N serving processes can share one series).
   :func:`prometheus_from_sample` / :meth:`Monitor.prometheus_text`
   render a sample in Prometheus text exposition format
   (``report live --prom`` serves it), the first brick of the ROADMAP's
   "scale-out serving with shared QoS state".

2. **Health engine** — :func:`health_from_samples` turns a sample
   series into verdicts: windowed per-tenant SLO burn rate over the
   ledger counters (fast/slow windows — lifetime counters are diffed
   across samples, never read as rates), quota-pressure and
   degraded/isolated-failure deltas from the fault counters, and the
   queue-stall watchdog (a pending group older than
   ``stall_factor x max_wait_s`` with no flush progress between samples
   fires ``serving_stalls`` + a retroactive ``serve_stall`` span).
   ``report health [--json|--gate]`` exits 1 on firing alerts;
   bench.py stamps a single-sample verdict into every run record so
   :func:`..regress.regressed_metrics` gates health alongside
   cost/rates.

3. **Measured overlap attribution** — :func:`dispatch_spans` re-traces
   a cohort's merged :func:`..stagegraph.schedule_concurrent` program
   under :func:`..utils.trace.capture_events` (``jax.eval_shape`` — no
   compile, no execution) and :func:`overlap_from_events` joins the
   ``cc<j>:`` / per-chunk ``[k]`` span intervals into realized-overlap
   ratios: ``1 - wall / sum(per-group extents)`` over the dispatch
   timeline, 0 for a back-to-back schedule, approaching ``1 - 1/n`` for
   a perfect n-way interleave. The explain layer stamps the ratio into
   records as ``overlap.measured_hide_ratio`` next to the model's
   ``hide_seconds`` and :func:`update_overlap_correction` persists the
   measured/model ratio (:func:`..calibrate.update_model_correction`
   keys ``"concurrent_hide"``/``"leg_hide"``) so auto-width and overlap-K
   pricing learn from the schedule as actually issued.

Dispatch-time caveat (the docs/OBSERVABILITY.md span contract): the
joined spans are recorded at jit *trace* time, so the ratios measure the
interleave of the schedule as issued — which transforms' compute the
scheduler placed inside which exchange's window — not device-clock
overlap. That is exactly the quantity the model's hide budgets assume;
device-level confirmation still belongs to the XLA profiler.

Disarmed discipline: a queue without ``DFFT_MONITOR`` (and without an
explicit Monitor) takes no hook on any hot path — the sampler reads
queue state from its own thread under the queue lock, and serving
behavior is pinned byte-identical with the monitor off
(``tests/test_monitor.py``).
"""

from __future__ import annotations

import json
import os
import re
import socket
import sys
import threading
import time
from collections import deque

from .utils import metrics as _metrics
from .utils.atomicio import append_line
from .utils.trace import capture_events, record_span

__all__ = [
    "MONITOR_SCHEMA",
    "HEALTH_SCHEMA",
    "Monitor",
    "load_series",
    "health_from_samples",
    "health_snapshot",
    "prometheus_from_sample",
    "dispatch_spans",
    "overlap_from_events",
    "realized_overlap",
    "update_overlap_correction",
]

#: Sample-document format version (stamped into every JSONL sample).
#: v2 added the fleet identity fields (``host``/``process_index``), the
#: monotonic clock stamp (``mono`` — the fleet aggregator's clock-offset
#: anchor), and the per-tenant wait-reservoir tail inside the qos block
#: (:meth:`..qos.QosPolicy.slo_report` ``include_waits``). v3 (PR 18)
#: added the ``waves`` block inside the queue reading — the streaming
#: scheduler's occupancy document (wave count/width, admit-to-dispatch
#: latency per class, inter-wave device-idle fraction, preemption
#: counts; ``CoalescingQueue._wave_stats.snapshot()``), present on
#: streaming or monitor-armed queues. Older samples still load and
#: merge (the added fields are simply absent). v4 (PR 20) added the
#: ``numerics`` block — the numerical-health ledger of the shadow-
#: sampled accuracy audit (:mod:`..numerics`; docs/OBSERVABILITY.md
#: "Numerics plane"): sampled/audited counts, per-(plan-tuple, tenant)
#: realized-error reservoir tails against the admitted budget with the
#: drift verdict, and the non-finite sentinel counters. Present only
#: once the plane is armed (``DFFT_SHADOW_RATE``) or a sentinel fired.
MONITOR_SCHEMA = 4
#: Health-verdict format version (stamped into every health block).
HEALTH_SCHEMA = 1

#: This process's hostname, stamped into every sample — half of the
#: fleet stream identity (``host``/``pid``); the other half of the
#: shared-directory naming convention (``fleet.series_path``).
_HOST = socket.gethostname()

#: Sampling interval when only ``DFFT_MONITOR_DIR`` is set (no
#: ``DFFT_MONITOR`` interval to say otherwise).
DEFAULT_DIR_INTERVAL_S = 1.0


def _process_index() -> int | None:
    """``jax.process_index()`` when jax is already imported and
    initialized; None otherwise. Never imports jax — a metrics-only
    monitor in a jax-free process must stay jax-free."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — uninitialized backend
        return None

#: A pending group is judged stalled past ``stall_factor x max_wait_s``
#: (or ``x stall_grace_s`` on queues without a deadline) with no flush
#: progress between two consecutive samples.
DEFAULT_STALL_FACTOR = 4.0
DEFAULT_STALL_GRACE_S = 1.0
#: SLO burn windows — the classic fast/slow pair: fast catches an
#: active incident, slow catches a smolder the fast window forgives.
DEFAULT_FAST_WINDOW_S = 60.0
DEFAULT_SLOW_WINDOW_S = 600.0
#: Fraction of a tenant's windowed submits that may miss (deadline
#: misses + quota sheds) before ``slo_burn`` fires.
DEFAULT_BURN_THRESHOLD = 0.1


# ------------------------------------------------------------- sampling


class Monitor:
    """Live sampler over one process's serving state.

    ``queue`` (a :class:`..serving.CoalescingQueue`, or None for a
    metrics-only monitor) is sampled under its own lock; ``interval_s``
    arms the daemon sampler thread (None leaves the monitor manual —
    :meth:`sample` / :meth:`prometheus_text` / :meth:`health` still
    work); ``path`` streams every sample as one JSONL line
    (line-atomic, multi-process safe). The queue's :meth:`..serving
    .CoalescingQueue.close` stops an attached monitor's thread.

    ``DFFT_MONITOR=interval[,path]`` arms one per queue at construction
    (:meth:`from_env`); unset, queues carry no monitor and no hook.
    """

    def __init__(
        self,
        queue=None,
        *,
        interval_s: float | None = None,
        path: str | None = None,
        stall_factor: float = DEFAULT_STALL_FACTOR,
        stall_grace_s: float = DEFAULT_STALL_GRACE_S,
        fast_window_s: float = DEFAULT_FAST_WINDOW_S,
        slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
        burn_threshold: float = DEFAULT_BURN_THRESHOLD,
        history: int = 512,
    ):
        if interval_s is not None and (
                isinstance(interval_s, bool)
                or not isinstance(interval_s, (int, float))
                or not interval_s > 0):
            raise ValueError(f"interval_s must be a positive number or "
                             f"None, got {interval_s!r}")
        self.queue = queue
        self.interval_s = None if interval_s is None else float(interval_s)
        self.path = path
        self.stall_factor = float(stall_factor)
        self.stall_grace_s = float(stall_grace_s)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self._samples: deque = deque(maxlen=max(2, int(history)))
        self._seq = 0
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # Stall-watchdog state: flush progress at the previous sample,
        # and the keys already counted this stall episode (one
        # ``serving_stalls`` bump per group per episode, re-armed when
        # a flush makes progress).
        self._last_flush_seq: int | None = None
        self._stalled_keys: set = set()
        self._stall_count = 0

    # ------------------------------------------------------- lifecycle

    @classmethod
    def from_env(cls, queue=None) -> "Monitor | None":
        """A monitor armed from ``DFFT_MONITOR=interval[,path]`` and/or
        the fleet directory convention ``DFFT_MONITOR_DIR=dir`` (one
        JSONL series per process: ``monitor-<host>-<pid>.jsonl``). None
        when both are unset (the zero-overhead default). An explicit
        ``DFFT_MONITOR=0`` disarms even with the directory set; an
        explicit path in ``DFFT_MONITOR`` wins over the derived one;
        the directory alone samples at ``DEFAULT_DIR_INTERVAL_S``."""
        spec = os.environ.get("DFFT_MONITOR", "").strip()
        mdir = os.environ.get("DFFT_MONITOR_DIR", "").strip()
        if spec in ("", "0") and not mdir:
            return None
        if spec == "0":
            return None
        interval, tail = DEFAULT_DIR_INTERVAL_S, ""
        if spec:
            head, _, tail = spec.partition(",")
            try:
                interval = float(head)
            except ValueError:
                raise ValueError(
                    f"DFFT_MONITOR must be 'interval[,path]' (seconds), "
                    f"got {spec!r}") from None
            if interval <= 0:
                return None
        path = tail.strip() or None
        if path is None and mdir:
            from .fleet import series_path

            path = series_path(mdir)
        return cls(queue, interval_s=interval, path=path)

    def start(self) -> "Monitor":
        """Arm the daemon sampler thread (no-op without ``interval_s``,
        idempotent while running)."""
        with self._lock:
            if self.interval_s is None:
                return self
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = threading.Event()
            t = threading.Thread(target=self._run, name="dfft-monitor",
                                 daemon=True)
            self._thread = t
            t.start()
        return self

    def stop(self) -> None:
        """Tear the sampler thread down (idempotent; joins the thread
        so no sample lands after stop returns). Stopping a started
        sampler takes one final sample first, so a run shorter than
        ``interval_s`` still leaves its terminal state in the series."""
        with self._lock:
            t, self._thread = self._thread, None
            self._stop.set()
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        if t is not None:
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass

    def __enter__(self) -> "Monitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        stop = self._stop
        while not stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — the sampler must never
                pass           # take the serving process down

    # -------------------------------------------------------- sampling

    def _watch_queue(self, now: float) -> dict | None:
        """One reading of the attached queue (under its lock): depth,
        pending age, and the stall watchdog's verdict. A stall =
        a pending group older than ``stall_factor x max_wait_s`` (or
        ``x stall_grace_s`` without a deadline) while the queue's flush
        sequence has not advanced since the previous sample — counted
        once per group per episode into ``serving_stalls`` with a
        retroactive ``serve_stall`` span over the un-flushed wait."""
        q = self.queue
        if q is None:
            return None
        with q._lock:
            depth = sum(len(g) for g in q._pending.values())
            fseq = q._flush_seq
            infos = []
            for k, g in q._pending.items():
                if not g:
                    continue
                _, t0 = q._formed.get(k, (0, now))
                oldest = min((r.handle._enqueued for r in g
                              if r.handle._enqueued is not None),
                             default=t0)
                infos.append((k, max(0.0, now - oldest), oldest))
        ref = self.stall_factor * (q.max_wait_s if q.max_wait_s is not None
                                   else self.stall_grace_s)
        stalled = []
        if self._last_flush_seq is not None and fseq != self._last_flush_seq:
            # Progress: the episode ends, every group re-arms.
            self._stalled_keys.clear()
        no_progress = (self._last_flush_seq is not None
                       and fseq == self._last_flush_seq)
        for k, age, oldest in infos:
            if not (no_progress and age > ref):
                continue
            if k in self._stalled_keys:
                continue
            self._stalled_keys.add(k)
            self._stall_count += 1
            _metrics.inc("serving_stalls", kind=q.kind)
            record_span(f"serve_stall[{q.kind}]", oldest, now)
            stalled.append({
                "age_s": age,
                "tenant": k[3] if len(k) > 3 else None,
            })
        self._last_flush_seq = fseq
        self._stalled_keys &= {k for k, _, _ in infos}
        out = {
            "kind": q.kind,
            "depth": depth,
            "groups": len(infos),
            "oldest_pending_age_s": max((a for _, a, _ in infos),
                                        default=0.0),
            "flush_seq": fseq,
            "stalls_total": self._stall_count,
        }
        if stalled:
            out["stalled"] = stalled
        ws = getattr(q, "_wave_stats", None)
        if ws is not None:
            # Scheduler occupancy (schema v3): the wave-level document
            # `report live`/`report fleet` render and the streaming
            # acceptance gate (idle fraction, realtime admit latency)
            # judges.
            out["waves"] = ws.snapshot()
        out["streaming"] = bool(getattr(q, "_streaming", False))
        return out

    def sample(self) -> dict:
        """Take one sample document: metrics snapshot + queue reading
        (stall watchdog included) + QoS ledger. Appends to the
        in-memory ring and — with ``path`` set — to the JSONL series."""
        now = time.perf_counter()
        doc = {
            "schema": MONITOR_SCHEMA,
            "ts": time.time(),
            # The monotonic stamp next to the wall stamp is the fleet
            # aggregator's clock-offset anchor: within one host every
            # process shares the monotonic epoch, so ts - mono deltas
            # across streams ARE wall-clock skew (fleet.estimate_offsets).
            "mono": time.monotonic(),
            "host": _HOST,
            "pid": os.getpid(),
            "process_index": _process_index(),
            "seq": self._seq,
            "metrics": _metrics.metrics_snapshot(),
            "queue": self._watch_queue(now),
        }
        self._seq += 1
        q = self.queue
        pol = getattr(q, "policy", None) if q is not None else None
        # include_waits: the reservoir tail rides in the sample so the
        # fleet aggregator can quantile-merge waits across processes.
        doc["qos"] = (pol.slo_report(include_waits=True)
                      if pol is not None else None)
        # Numerics plane (schema v4): the process-global shadow-audit /
        # non-finite ledger. None (block absent) while the plane is
        # dark — older consumers and disarmed processes are unaffected.
        from .numerics import numerics_snapshot

        nsnap = numerics_snapshot()
        if nsnap is not None:
            doc["numerics"] = nsnap
        self._samples.append(doc)
        if self.path:
            append_line(self.path, json.dumps(doc, sort_keys=True))
        return doc

    @property
    def samples(self) -> list[dict]:
        """The in-memory sample ring, oldest first."""
        return list(self._samples)

    # ------------------------------------------------------------ views

    def prometheus_text(self, sample: dict | None = None) -> str:
        """Prometheus text-exposition rendering of ``sample`` (default:
        a fresh one)."""
        return prometheus_from_sample(sample or self.sample())

    def health(self, samples: list[dict] | None = None) -> dict:
        """Health verdicts over the in-memory series (or ``samples``);
        takes a fresh sample first when the ring is empty."""
        if samples is None:
            if not self._samples:
                self.sample()
            samples = list(self._samples)
        return health_from_samples(
            samples, fast_window_s=self.fast_window_s,
            slow_window_s=self.slow_window_s,
            burn_threshold=self.burn_threshold)


def load_series(path: str) -> list[dict]:
    """Load a monitor JSONL series, lenient to torn/foreign lines (the
    history/wisdom loader discipline) and ordered oldest-first by
    timestamp — concurrent writers interleave whole lines in arbitrary
    order."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                if isinstance(doc, dict) and "ts" in doc:
                    out.append(doc)
    except OSError:
        return []
    out.sort(key=lambda d: d.get("ts") or 0.0)
    return out


# ------------------------------------------------------- health engine


def _counter_sum(snap: dict | None, name: str) -> float:
    """Sum of one metrics counter across every label row of a
    snapshot."""
    rows = ((snap or {}).get("counters") or {}).get(name) or {}
    return float(sum(v for v in rows.values()
                     if isinstance(v, (int, float))))


def _baseline(samples: list[dict], window_s: float) -> dict | None:
    """The newest sample OLDER than the window (the delta baseline).
    None when the series does not reach back that far — then the series
    start is the baseline, or, for a single-sample series, zero (the
    bench single-shot semantics: lifetime totals ARE the window)."""
    end = samples[-1].get("ts") or 0.0
    base = None
    for s in samples:
        if (s.get("ts") or 0.0) < end - window_s:
            base = s
        else:
            break
    if base is None and len(samples) > 1:
        base = samples[0]
    return base


def _delta(samples: list[dict], window_s: float, get) -> float:
    """Windowed counter increase: newest minus the baseline sample
    (0-baselined for a single-sample series). Clamped at 0 so a
    counter reset can never read as negative burn."""
    base = _baseline(samples, window_s)
    return max(0.0, get(samples[-1]) - (get(base) if base else 0.0))


def _tenant_counter(sample: dict, tenant: str, field: str) -> float:
    t = (((sample.get("qos") or {}).get("tenants") or {}).get(tenant)
         or {})
    v = t.get(field)
    return float(v) if isinstance(v, (int, float)) else 0.0


def health_from_samples(
    samples: list[dict],
    *,
    fast_window_s: float = DEFAULT_FAST_WINDOW_S,
    slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
    burn_threshold: float = DEFAULT_BURN_THRESHOLD,
) -> dict:
    """Health verdicts over a monitor sample series (oldest first).

    Alert severities: ``"alert"`` fires the gate (``report health
    --gate`` exits 1; :func:`..regress.regressed_metrics` reports it),
    ``"warn"`` is surfaced but never gates.

    - ``stall`` (alert) — the queue-stall watchdog counted a stalled
      group within the fast window.
    - ``slo_burn`` (alert) — a tenant WITH a declared SLO burned more
      than ``burn_threshold`` of its windowed submits on deadline
      misses + quota sheds (fast window), or the newest ledger already
      judges its lifetime p99/misses out of SLO.
    - ``slo_burn_slow`` (warn) — same burn over the slow window only
      (a smolder the fast window forgives).
    - ``quota_pressure`` (warn) — quota sheds within the fast window.
    - ``degraded`` (warn) — degraded executions or isolated failures
      within the fast window (the PR 10 fault counters).
    - ``accuracy_drift`` (alert) — a shadow-audited plan bucket's
      realized p99 error exceeds its admitted budget x slack
      (docs/OBSERVABILITY.md "Numerics plane").
    - ``nonfinite`` (alert) — non-finite outputs from finite inputs
      within the fast window (quarantined serving damage);
      ``nonfinite_input`` (warn) is the caller-side counterpart.
    """
    if not samples:
        return {"schema": HEALTH_SCHEMA, "status": "unknown",
                "alerts": [], "samples": 0,
                "windows": {"fast_s": fast_window_s,
                            "slow_s": slow_window_s}}
    newest = samples[-1]
    alerts: list[dict] = []

    def stalls_of(s: dict) -> float:
        qb = s.get("queue") or {}
        v = qb.get("stalls_total")
        if isinstance(v, (int, float)):
            return float(v)
        return _counter_sum(s.get("metrics"), "serving_stalls")

    stall_d = _delta(samples, fast_window_s, stalls_of)
    if stall_d > 0:
        alerts.append({
            "name": "stall", "severity": "alert",
            "detail": f"{stall_d:g} stalled group(s) in the fast "
                      f"window with no flush progress"})

    tenants = ((newest.get("qos") or {}).get("tenants") or {})
    for tname, t in sorted(tenants.items()):
        declared = isinstance(t.get("slo_wait_s"), (int, float))

        def bad(s, _t=tname):
            return (_tenant_counter(s, _t, "deadline_misses")
                    + _tenant_counter(s, _t, "quota_shed"))

        def submits(s, _t=tname):
            return _tenant_counter(s, _t, "submits")

        shed_d = _delta(samples, fast_window_s,
                        lambda s, _t=tname: _tenant_counter(
                            s, _t, "quota_shed"))
        if shed_d > 0:
            alerts.append({
                "name": "quota_pressure", "severity": "warn",
                "tenant": tname,
                "detail": f"{shed_d:g} over-quota shed(s) in the fast "
                          f"window"})
        if not declared:
            continue
        bad_fast = _delta(samples, fast_window_s, bad)
        sub_fast = _delta(samples, fast_window_s, submits)
        burn_fast = bad_fast / max(1.0, sub_fast)
        bad_slow = _delta(samples, slow_window_s, bad)
        sub_slow = _delta(samples, slow_window_s, submits)
        burn_slow = bad_slow / max(1.0, sub_slow)
        out_of_slo = t.get("slo_ok") is False
        if (bad_fast > 0 and burn_fast > burn_threshold) or out_of_slo:
            alerts.append({
                "name": "slo_burn", "severity": "alert",
                "tenant": tname,
                "burn_fast": burn_fast, "burn_slow": burn_slow,
                "detail": (f"burn {burn_fast:.0%} of submits in the "
                           f"fast window"
                           + (" and the lifetime ledger is out of SLO"
                              if out_of_slo else ""))})
        elif bad_slow > 0 and burn_slow > burn_threshold:
            alerts.append({
                "name": "slo_burn_slow", "severity": "warn",
                "tenant": tname,
                "burn_fast": burn_fast, "burn_slow": burn_slow,
                "detail": f"burn {burn_slow:.0%} of submits over the "
                          f"slow window"})

    def faults_of(s: dict) -> float:
        snap = s.get("metrics")
        return (_counter_sum(snap, "serving_degraded")
                + _counter_sum(snap, "serving_isolated_failures"))

    fault_d = _delta(samples, fast_window_s, faults_of)
    if fault_d > 0:
        alerts.append({
            "name": "degraded", "severity": "warn",
            "detail": f"{fault_d:g} degraded execution(s)/isolated "
                      f"failure(s) in the fast window"})

    # Numerics plane (schema v4; docs/OBSERVABILITY.md "Numerics
    # plane"): accuracy drift judges the newest ledger (the reservoirs
    # are cumulative — a drifting plan stays drifting until its p99
    # recovers); the non-finite sentinels are windowed counter deltas
    # like every other counter verdict. Output-site non-finites are
    # serving damage (alert); input-site ones are the caller's (warn).
    numerics = newest.get("numerics") or {}
    drifting = [b for b in (numerics.get("plans") or {}).values()
                if b.get("drifting")]
    if drifting:
        worst = max(drifting, key=lambda b: b.get("drift_ratio", 0.0))
        alerts.append({
            "name": "accuracy_drift", "severity": "alert",
            "plan": worst.get("plan"), "tenant": worst.get("tenant"),
            "drift_ratio": worst.get("drift_ratio"),
            "detail": (f"{len(drifting)} plan bucket(s) drifting; "
                       f"worst {worst.get('plan')}: realized p99 "
                       f"{worst.get('realized_p99', 0.0):.3g} is "
                       f"{worst.get('drift_ratio', 0.0):.3g}x the "
                       f"admitted budget "
                       f"{worst.get('admitted_err', 0.0):.3g}")})

    def nonfinite_of(site):
        def get(s):
            nf = (s.get("numerics") or {}).get("nonfinite") or {}
            return float(sum(v for k, v in nf.items()
                             if k.startswith(site + ":")))
        return get

    nf_out_d = _delta(samples, fast_window_s, nonfinite_of("output"))
    if nf_out_d > 0:
        alerts.append({
            "name": "nonfinite", "severity": "alert",
            "detail": f"{nf_out_d:g} non-finite output(s) from finite "
                      f"input(s) in the fast window (quarantined)"})
    nf_in_d = _delta(samples, fast_window_s, nonfinite_of("input"))
    if nf_in_d > 0:
        alerts.append({
            "name": "nonfinite_input", "severity": "warn",
            "detail": f"{nf_in_d:g} non-finite caller input(s) in the "
                      f"fast window (delivered as-is, never retried)"})

    firing = [a for a in alerts if a["severity"] == "alert"]
    fast_n = len(samples) - len(
        samples[:samples.index(_baseline(samples, fast_window_s))]
    ) if _baseline(samples, fast_window_s) in samples else len(samples)
    return {
        "schema": HEALTH_SCHEMA,
        "status": ("alert" if firing
                   else "warn" if alerts else "ok"),
        "alerts": alerts,
        "samples": len(samples),
        "windows": {"fast_s": fast_window_s, "slow_s": slow_window_s,
                    "fast_samples": fast_n},
        "totals": {
            "stalls": stalls_of(newest),
            "deadline_misses": sum(
                _tenant_counter(newest, t, "deadline_misses")
                for t in tenants),
            "quota_shed": sum(
                _tenant_counter(newest, t, "quota_shed")
                for t in tenants),
            "degraded": _counter_sum(newest.get("metrics"),
                                     "serving_degraded"),
            "isolated_failures": _counter_sum(
                newest.get("metrics"), "serving_isolated_failures"),
            "expired": _counter_sum(newest.get("metrics"),
                                    "serving_expired"),
            "shadow_sampled": float(numerics.get("sampled", 0)),
            "shadow_audited": float(numerics.get("audited", 0)),
            "nonfinite": float(sum(
                (numerics.get("nonfinite") or {}).values())),
        },
    }


def health_snapshot(queue=None) -> dict:
    """Single-shot health verdict from the process's current state (one
    fresh sample; lifetime totals play the window) — the block bench.py
    stamps into every run record."""
    m = Monitor(queue)
    return health_from_samples([m.sample()])


# -------------------------------------------------- Prometheus rendering

# Metrics-snapshot label strings are "k=v,k2=v2" with stringified
# values; values may themselves contain commas ("(64, 64, 64)" shapes),
# so split only at commas that start a new key.
_LABEL_SPLIT = re.compile(r",(?=[A-Za-z_][A-Za-z0-9_]*=)")


def _esc(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _plabels(label_str: str, extra: dict | None = None) -> str:
    pairs = []
    if label_str:
        for part in _LABEL_SPLIT.split(label_str):
            k, _, v = part.partition("=")
            pairs.append((k, v))
    for k, v in (extra or {}).items():
        pairs.append((k, v))
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_esc(v)}"' for k, v in pairs) + "}"


def _prom_rows(sample: dict, extra: dict | None = None) -> list[tuple]:
    """One monitor sample as ``(family, type, line)`` Prometheus rows.
    ``extra`` labels (the fleet renderer's ``proc``/``host``) are
    appended to every row's label set. :func:`_render_prom` joins rows
    into the text exposition format, emitting each family's ``# TYPE``
    exactly once — the property that lets the fleet view concatenate N
    processes' rows into one valid scrape document."""
    rows: list[tuple] = []
    extra = extra or {}

    def lab(label_str: str, more: dict | None = None) -> str:
        merged = dict(more or {})
        merged.update(extra)
        return _plabels(label_str, merged)

    snap = sample.get("metrics") or {}
    for name, fam in sorted((snap.get("counters") or {}).items()):
        pname = f"dfft_{name}_total"
        for lbl, v in sorted(fam.items()):
            rows.append((pname, "counter", f"{pname}{lab(lbl)} {v:g}"))
    for name, fam in sorted((snap.get("gauges") or {}).items()):
        pname = f"dfft_{name}"
        for lbl, v in sorted(fam.items()):
            rows.append((pname, "gauge", f"{pname}{lab(lbl)} {v:g}"))
    for name, fam in sorted((snap.get("histograms") or {}).items()):
        pname = f"dfft_{name}"
        for lbl, h in sorted(fam.items()):
            rows.append((pname, "summary",
                         f"{pname}_count{lab(lbl)} {h.get('count', 0):g}"))
            rows.append((pname, "summary",
                         f"{pname}_sum{lab(lbl)} {h.get('total', 0.0):g}"))
            for q, fld in (("0.5", "p50"), ("0.99", "p99")):
                if fld in h:
                    rows.append((pname, "summary",
                                 f"{pname}{lab(lbl, {'quantile': q})} "
                                 f"{h[fld]:g}"))

    qb = sample.get("queue") or None
    if qb:
        kind = {"kind": qb.get("kind", "")}
        for pname, ptype, fld, dflt in (
                ("dfft_queue_depth", "gauge", "depth", 0),
                ("dfft_queue_pending_groups", "gauge", "groups", 0),
                ("dfft_queue_oldest_pending_age_seconds", "gauge",
                 "oldest_pending_age_s", 0.0),
                ("dfft_queue_stalls_total", "counter",
                 "stalls_total", 0)):
            rows.append((pname, ptype,
                         f"{pname}{lab('', kind)} {qb.get(fld, dflt):g}"))

    waves = (qb or {}).get("waves")
    if waves:
        kind = {"kind": (qb or {}).get("kind", "")}
        for pname, ptype, fld in (
                ("dfft_waves_total", "counter", "waves"),
                ("dfft_wave_preemptions_total", "counter", "preemptions"),
                ("dfft_wave_bumped_transforms_total", "counter",
                 "bumped_transforms"),
                ("dfft_wave_idle_seconds_total", "counter", "idle_s"),
                ("dfft_wave_busy_seconds_total", "counter", "busy_s"),
                ("dfft_wave_idle_fraction", "gauge", "idle_fraction"),
                ("dfft_wave_width_mean", "gauge", "width_mean"),
                ("dfft_wave_duration_seconds_max", "gauge",
                 "wave_duration_max_s")):
            v = waves.get(fld)
            if isinstance(v, (int, float)):
                rows.append((pname, ptype,
                             f"{pname}{lab('', kind)} {v:g}"))
        for klass, a in sorted((waves.get("admit_wait") or {}).items()):
            for q, fld in (("0.5", "p50_s"), ("0.99", "p99_s")):
                v = a.get(fld)
                if isinstance(v, (int, float)):
                    rows.append((
                        "dfft_wave_admit_seconds", "summary",
                        f"dfft_wave_admit_seconds"
                        f"{lab('', {'class': klass, 'quantile': q})}"
                        f" {v:g}"))

    tenants = ((sample.get("qos") or {}).get("tenants") or {})
    if tenants:
        fams = (("submits", "dfft_tenant_submits_total", "counter"),
                ("transforms", "dfft_tenant_transforms_total", "counter"),
                ("quota_shed", "dfft_tenant_quota_shed_total", "counter"),
                ("deadline_misses", "dfft_tenant_slo_misses_total",
                 "counter"))
        for fld, pname, ptype in fams:
            for tname, t in sorted(tenants.items()):
                v = t.get(fld)
                if isinstance(v, (int, float)):
                    rows.append((pname, ptype,
                                 f"{pname}{lab('', {'tenant': tname})} "
                                 f"{v:g}"))
        for tname, t in sorted(tenants.items()):
            for q, fld in (("0.5", "wait_p50_s"), ("0.99", "wait_p99_s")):
                v = t.get(fld)
                if isinstance(v, (int, float)):
                    rows.append((
                        "dfft_tenant_wait_seconds", "summary",
                        f"dfft_tenant_wait_seconds"
                        f"{lab('', {'tenant': tname, 'quantile': q})}"
                        f" {v:g}"))
        for tname, t in sorted(tenants.items()):
            if "slo_ok" in t:
                rows.append((
                    "dfft_tenant_slo_ok", "gauge",
                    f"dfft_tenant_slo_ok{lab('', {'tenant': tname})} "
                    f"{1 if t['slo_ok'] else 0}"))

    numerics = sample.get("numerics") or None
    if numerics:
        for pname, fld in (
                ("dfft_numerics_shadow_sampled_total", "sampled"),
                ("dfft_numerics_shadow_audited_total", "audited"),
                ("dfft_numerics_audit_failures_total",
                 "audit_failures")):
            v = numerics.get(fld)
            if isinstance(v, (int, float)):
                rows.append((pname, "counter",
                             f"{pname}{lab('')} {v:g}"))
        for sk, v in sorted((numerics.get("nonfinite") or {}).items()):
            site, _, nfkind = sk.partition(":")
            rows.append((
                "dfft_numerics_nonfinite_total", "counter",
                f"dfft_numerics_nonfinite_total"
                f"{lab('', {'site': site, 'kind': nfkind})} {v:g}"))
        for _, b in sorted((numerics.get("plans") or {}).items()):
            pl = {"plan": b.get("plan", ""),
                  "tenant": b.get("tenant") or ""}
            for pname, fld in (
                    ("dfft_numerics_admitted_err", "admitted_err"),
                    ("dfft_numerics_drift_ratio", "drift_ratio")):
                v = b.get(fld)
                if isinstance(v, (int, float)):
                    rows.append((pname, "gauge",
                                 f"{pname}{lab('', pl)} {v:g}"))
            for q, fld in (("0.5", "realized_p50"),
                           ("0.99", "realized_p99")):
                v = b.get(fld)
                if isinstance(v, (int, float)):
                    rows.append((
                        "dfft_numerics_realized_err", "summary",
                        f"dfft_numerics_realized_err"
                        f"{lab('', dict(pl, quantile=q))} {v:g}"))

    ts_line = f"dfft_monitor_sample_timestamp_seconds{lab('')}" \
        if extra else "dfft_monitor_sample_timestamp_seconds"
    rows.append(("dfft_monitor_sample_timestamp_seconds", "gauge",
                 f"{ts_line} {sample.get('ts', 0.0):.6f}"))
    return rows


def _render_prom(rows: list[tuple]) -> str:
    """Join ``(family, type, line)`` rows into the Prometheus text
    exposition format. Each family's ``# TYPE`` header is emitted once,
    at the family's first appearance; later rows of the same family
    (another process's, in the fleet view) group under it."""
    by_family: dict[str, tuple[str, list[str]]] = {}
    order: list[str] = []
    for family, ptype, line in rows:
        if family not in by_family:
            by_family[family] = (ptype, [])
            order.append(family)
        by_family[family][1].append(line)
    lines: list[str] = []
    for family in order:
        ptype, fam_lines = by_family[family]
        lines.append(f"# TYPE {family} {ptype}")
        lines.extend(fam_lines)
    return "\n".join(lines) + "\n"


def prometheus_from_sample(sample: dict) -> str:
    """One monitor sample in Prometheus text exposition format. Series
    are prefixed ``dfft_``; counters get ``_total``, histograms emit
    ``_count``/``_sum`` plus ``quantile`` rows where the registry keeps
    a reservoir; the queue/QoS blocks surface depth, pending age, stall
    count, and per-tenant SLO standing for scraping. The fleet view
    (:func:`..fleet.prometheus_from_fleet`) renders the same rows once
    per process with ``proc``/``host`` labels."""
    return _render_prom(_prom_rows(sample))


# ------------------------------------------- measured overlap attribution

_CC_PREFIX = re.compile(r"^cc(\d+):")
_CHUNK_SUFFIX = re.compile(r"\[(\d+)\]$")


def dispatch_spans(plans) -> list[tuple[str, float, float]]:
    """The dispatch-order flight-recorder spans of the merged schedule
    of ``plans`` (1+ stage-graph plans), captured from a FRESH program
    trace: ``jax.eval_shape`` on an uncached
    :func:`..stagegraph._build_concurrent` program under
    :func:`..utils.trace.capture_events` — abstract evaluation runs the
    staged Python (so every ``cc<j>:`` wave span and per-chunk ``[k]``
    exchange span fires) without compiling or executing anything.
    Raises ``ValueError`` for plans below the stage-graph tier."""
    import jax

    from .stagegraph import _build_concurrent

    plans = tuple(plans)
    cp = _build_concurrent(plans)
    sds = [jax.ShapeDtypeStruct(p.in_shape, p.in_dtype) for p in plans]
    with capture_events() as buf:
        jax.eval_shape(cp.fn, *sds)
    return list(buf)


def realized_overlap(events, group_of) -> dict | None:
    """Realized-overlap join over a dispatch span timeline: group every
    span by ``group_of(name)`` (None = ignore), then

        ``hide_ratio = 1 - wall / sum(per-group extents)``

    where each group's extent runs first-start to last-stop and ``wall``
    is the whole cohort's. Groups dispatched back-to-back give 0; a
    perfect n-way interleave (every group's extent spanning the whole
    schedule) approaches ``1 - 1/n``. None without >= 2 groups."""
    groups: dict = {}
    for name, start, stop in events:
        g = group_of(name)
        if g is None:
            continue
        cur = groups.get(g)
        if cur is None:
            groups[g] = [start, stop]
        else:
            cur[0] = min(cur[0], start)
            cur[1] = max(cur[1], stop)
    if len(groups) < 2:
        return None
    extents = sum(hi - lo for lo, hi in groups.values())
    wall = (max(hi for _, hi in groups.values())
            - min(lo for lo, _ in groups.values()))
    if extents <= 0.0:
        return None
    return {
        "groups": len(groups),
        "wall_seconds": wall,
        "extent_seconds": extents,
        "hide_ratio": max(0.0, 1.0 - wall / extents),
    }


def overlap_from_events(events) -> dict:
    """Both overlap joins of one captured dispatch timeline:

    - ``"concurrent"`` — groups = ``cc<j>:`` transform prefixes (the
      :func:`..stagegraph.schedule_concurrent` interleave across
      transforms); None for a single-transform program.
    - ``"legs"`` — groups = per-chunk ``[k]`` span suffixes (the
      leg-pipelined / overlap-K interleave across chunks of one
      exchange); None at K <= 1.
    """
    def cc_of(name: str):
        m = _CC_PREFIX.match(name)
        return int(m.group(1)) if m else None

    def chunk_of(name: str):
        m = _CHUNK_SUFFIX.search(_CC_PREFIX.sub("", name))
        return int(m.group(1)) if m else None

    return {
        "concurrent": realized_overlap(events, cc_of),
        "legs": realized_overlap(events, chunk_of),
    }


def update_overlap_correction(
    overlap: dict | None, path: str | None = None,
) -> dict | None:
    """Persist an explain record's measured/model overlap ratio into the
    calibration profile (:func:`..calibrate.update_model_correction`)
    under ``"concurrent_hide"`` / ``"leg_hide"`` — the keys
    :func:`..plan_logic.model_stage_seconds`'s ``hide_correction``
    reads back for auto-width and overlap-K pricing. No-op (returns
    None) without a measured ratio, a positive model ratio, or an
    armed profile store."""
    if not isinstance(overlap, dict):
        return None
    measured = overlap.get("measured_hide_ratio")
    model = overlap.get("model_hide_ratio")
    kind = overlap.get("kind")
    key = {"concurrent": "concurrent_hide",
           "overlap_k": "leg_hide"}.get(kind)
    if (key is None
            or not isinstance(measured, (int, float))
            or not isinstance(model, (int, float)) or model <= 0.0):
        return None
    from .calibrate import update_model_correction

    return update_model_correction({key: measured / model}, path)
