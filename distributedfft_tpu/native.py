"""ctypes bindings for the native runtime core (``native/dfft_native.cpp``).

The reference's runtime around the device kernels is C++ (plan scheduler
``templateFFT.cpp:3941-4100``, exchange tables ``fft_mpi_3d_api.cpp:84-133``,
trace log ``heffte_trace.h``); this framework keeps the same split: JAX/XLA/
Pallas own device compute, while plan-time scheduling, geometry search,
exchange bookkeeping, and trace recording have a native C++ implementation.

The library is built on demand with the in-tree Makefile (g++ only, no
external deps). Every entry point has a pure-Python fallback so the package
works without a toolchain; ``tests/test_native.py`` asserts the two agree.
"""

from __future__ import annotations

import ctypes
import math
import os
import subprocess
import threading

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdfft_native.so")

_lib = None
_tried = False
_lock = threading.Lock()


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "dfft_native.cpp")
    if not os.path.exists(src):
        return False
    if os.path.exists(_SO_PATH) and os.path.getmtime(_SO_PATH) >= os.path.getmtime(src):
        return True
    try:
        subprocess.run(
            ["make", "-s", "libdfft_native.so"],
            cwd=_NATIVE_DIR, check=True, capture_output=True, timeout=120,
        )
        return os.path.exists(_SO_PATH)
    except (OSError, subprocess.SubprocessError):
        return False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        ll = ctypes.c_longlong
        lp = ctypes.POINTER(ll)
        lib.dfft_abi_version.restype = ctypes.c_int
        lib.dfft_schedule_axis.restype = ctypes.c_int
        lib.dfft_schedule_axis.argtypes = [ll, ll, ctypes.c_int, lp]
        lib.dfft_procgrid2.argtypes = [ll, lp, lp]
        lib.dfft_min_surface_grid.argtypes = [ll, ll, ll, ll, lp]
        lib.dfft_pencil_grid.argtypes = [ll, ll, ll, ll, lp]
        lib.dfft_balanced_split.restype = ctypes.c_int
        lib.dfft_balanced_split.argtypes = [ll, ll, lp]
        lib.dfft_exchange_table.argtypes = [ll] * 5 + [lp] * 4
        lib.dfft_trace_begin.restype = ll
        lib.dfft_trace_begin.argtypes = [ctypes.c_char_p]
        lib.dfft_trace_end.argtypes = [ll]
        lib.dfft_trace_count.restype = ll
        lib.dfft_trace_dump.restype = ctypes.c_int
        lib.dfft_trace_dump.argtypes = [ctypes.c_char_p, ll, ll]
        if lib.dfft_abi_version() != 3:
            return None
        _lib = lib
        return _lib


def is_available() -> bool:
    return _load() is not None


# ------------------------------------------------------------- scheduler

def schedule_axis(
    n: int, max_factor: int = 256, max_passes: int = 4
) -> list[int] | None:
    """Split ``n`` into <= ``max_passes`` balanced factors each <=
    ``max_factor`` (descending), or None when impossible (large prime ->
    Bluestein; or too many passes). The FFTScheduler decision
    (``templateFFT.cpp:3941-4100``) with VMEM/MXU bounds in place of shared
    memory."""
    lib = _load()
    if lib is not None:
        out = (ctypes.c_longlong * max_passes)()
        r = lib.dfft_schedule_axis(n, max_factor, max_passes, out)
        return [int(v) for v in out[:r]] if r > 0 else None
    return _schedule_axis_py(n, max_factor, max_passes)


def _prime_factors(n: int) -> list[int]:
    out, p = [], 2
    while p * p <= n:
        while n % p == 0:
            out.append(p)
            n //= p
        p += 1
    if n > 1:
        out.append(n)
    return out


def _schedule_axis_py(n: int, max_factor: int, max_passes: int) -> list[int] | None:
    """Pure-Python mirror of ``dfft_schedule_axis`` (kept in lockstep —
    see tests/test_native.py)."""
    if n < 1 or max_factor < 2 or max_passes < 1:
        return None
    if n == 1:
        return [1]
    primes = _prime_factors(n)
    if max(primes) > max_factor:
        return None
    for passes in range(1, max_passes + 1):
        bins = [1] * passes
        ok = True
        for p in sorted(primes, reverse=True):
            fits = [b for b in range(passes) if bins[b] * p <= max_factor]
            if not fits:
                ok = False
                break
            bins[max(fits, key=lambda b: bins[b])] *= p
        if not ok:
            continue
        for _ in range(64):
            bins.sort(reverse=True)
            if bins[-1] == 1 and len(bins) > 1:
                bins.pop()
                continue
            moved = False
            for p in sorted(_prime_factors(bins[0])):
                big, small = bins[0] // p, bins[-1] * p
                if small <= max_factor and max(big, small) < bins[0]:
                    bins[0], bins[-1] = big, small
                    moved = True
                    break
            if not moved:
                break
        return sorted(bins, reverse=True)
    return None


# -------------------------------------------------------------- geometry

def procgrid2(p: int) -> tuple[int, int]:
    lib = _load()
    if lib is not None:
        a, b = ctypes.c_longlong(), ctypes.c_longlong()
        lib.dfft_procgrid2(p, ctypes.byref(a), ctypes.byref(b))
        return int(a.value), int(b.value)
    from .geometry import make_procgrid

    return make_procgrid(p)


def min_surface_grid(shape, p: int) -> tuple[int, int, int]:
    lib = _load()
    if lib is not None:
        out = (ctypes.c_longlong * 3)()
        lib.dfft_min_surface_grid(shape[0], shape[1], shape[2], p, out)
        return int(out[0]), int(out[1]), int(out[2])
    from .geometry import proc_setup_min_surface, world_box

    return proc_setup_min_surface(world_box(tuple(shape)), p)


def pencil_grid(shape, p: int) -> tuple[int, int]:
    """Min-surface 2D pencil grid (rows over axis 0, cols over axis 1) — the
    planner's default grid for pencil decompositions (the
    ``proc_setup_min_surface`` role, ``heffte_geometry.h:589-626``)."""
    lib = _load()
    if lib is not None:
        out = (ctypes.c_longlong * 2)()
        lib.dfft_pencil_grid(shape[0], shape[1], shape[2], p, out)
        return int(out[0]), int(out[1])
    from .geometry import pencil_grid_min_surface

    return pencil_grid_min_surface(shape, p)


def balanced_split(n: int, max_factor: int) -> tuple[int, int] | None:
    """Balanced divisor pair (n1, n2), n1 <= n2 <= max_factor, n1 maximal —
    the per-axis split rule of the matmul/Pallas executors (the FFTScheduler
    decision, ``templateFFT.cpp:3941-4100``). None when impossible."""
    lib = _load()
    if lib is not None:
        out = (ctypes.c_longlong * 2)()
        r = lib.dfft_balanced_split(n, max_factor, out)
        return (int(out[0]), int(out[1])) if r == 0 else None
    return _balanced_split_py(n, max_factor)


def _balanced_split_py(n: int, max_factor: int) -> tuple[int, int] | None:
    """Pure-Python mirror of ``dfft_balanced_split`` (kept in lockstep)."""
    for d in range(math.isqrt(n), 1, -1):
        if n % d == 0:
            n1, n2 = d, n // d
            return (n1, n2) if n2 <= max_factor else None
    return None


# -------------------------------------------------------- exchange tables

def exchange_table(n0: int, n1: int, n2: int, p: int, rank: int):
    """Per-peer (send_counts, send_offsets, recv_counts, recv_offsets) for
    the uneven X-slab -> Y-slab redistribution (``fft_mpi_3d_api.cpp:84-133``
    TransInfo semantics; element counts, not bytes)."""
    lib = _load()
    if lib is not None:
        arrs = [(ctypes.c_longlong * p)() for _ in range(4)]
        lib.dfft_exchange_table(n0, n1, n2, p, rank, *arrs)
        return tuple([int(v) for v in a] for a in arrs)
    return _exchange_table_py(n0, n1, n2, p, rank)


def _exchange_table_py(n0: int, n1: int, n2: int, p: int, rank: int):
    c0, c1 = -(-n0 // p), -(-n1 // p)
    owned = lambda n, c, r: max(0, min(n, (r + 1) * c) - min(n, r * c))
    my_rows, my_cols = owned(n0, c0, rank), owned(n1, c1, rank)
    sc = [my_rows * owned(n1, c1, j) * n2 for j in range(p)]
    rc = [owned(n0, c0, j) * my_cols * n2 for j in range(p)]
    off = lambda cs: [sum(cs[:j]) for j in range(p)]
    return sc, off(sc), rc, off(rc)


# ----------------------------------------------------------------- trace

class NativeTrace:
    """Native trace recorder handle; no-ops when the library is missing so
    callers can use it unconditionally."""

    def __init__(self) -> None:
        self._lib = _load()

    @property
    def available(self) -> bool:
        return self._lib is not None

    def init(self) -> None:
        if self._lib is not None:
            self._lib.dfft_trace_init()

    def begin(self, name: str) -> int:
        if self._lib is None:
            return -1
        return int(self._lib.dfft_trace_begin(name.encode()))

    def end(self, event_id: int) -> None:
        if self._lib is not None:
            self._lib.dfft_trace_end(event_id)

    def count(self) -> int:
        return 0 if self._lib is None else int(self._lib.dfft_trace_count())

    def dump(self, path: str, process: int = 0, nprocs: int = 1) -> bool:
        if self._lib is None:
            return False
        return self._lib.dfft_trace_dump(path.encode(), process, nprocs) == 0
