"""Runtime numerical-health plane — shadow audits + non-finite sentinels.

The platform trades accuracy for speed in three independent places —
wire codecs (bf16/int8/split), matmul precision tiers, and the Pallas
fusion tier — and every error figure the tuner's ONE-budget admission
rule consumes (:func:`..parallel.exchange.wire_roundtrip_error`,
:func:`..ops.executors.executor_roundtrip_error`) is a *plan-time
estimate on a seeded Gaussian input*. Nothing in the PR 16/17 monitor →
fleet → health pipeline observes the error actually *realized* on live
traffic, where block-scaled quantization degrades sharply on
heavy-tailed dynamic ranges (a single hot request poisons the shared
per-tile pow2 scales of every cohort member batched with it — see
``tests/test_a2r_numerics.py``'s adversarial-range parity test) and a
non-finite value silently propagates through a coalesced batch. This
module is the numerical axis of that pipeline (docs/OBSERVABILITY.md
"Numerics plane"):

1. **Shadow-sampled accuracy audit.** ``DFFT_SHADOW_RATE=p[,seed]``
   arms a deterministic seeded sampler on every
   :class:`..serving.CoalescingQueue`; a fraction ``p`` of requests
   are, after their primary (possibly batched/compressed/fused)
   execution resolves, re-executed through a memoized *exact reference
   plan* (same geometry, exact wire, exact executor tier, fusion off).
   The realized relative error lands in a per-(plan-tuple, tenant)
   Algorithm-R reservoir in this module's process-global ledger,
   alongside the plan's *admitted* budget (the seeded wire + executor
   roundtrip figures), producing a live drift verdict: realized p99 vs
   admitted budget x a slack factor. Unset ⇒ the plane is dark and the
   serving path is byte-identical (pinned).

2. **Non-finite sentinels.** Cheap ``isfinite`` reductions at the
   serving output boundary — with the *input* checked first, so a
   caller's NaN is distinguished from codec/executor damage — stamp
   ``numerics_nonfinite{site,kind}`` counters. A non-finite output for
   a finite input raises :class:`NonFiniteResult` (classified
   deterministic by ``faults.classify``), routing the group into the
   existing retry → exact-rebuild → bisect chain so the poisoned
   request fails alone while its cohort completes bit-correct. A
   non-finite input is the caller's: reported, delivered, never
   retried.

3. **Surfacing.** :func:`numerics_snapshot` is the schema-4 monitor
   block (:meth:`..monitor.Monitor.sample`), pooled cross-process by
   :func:`..fleet.merge_streams` (rank over concatenated tails, never
   averaged percentiles), judged by ``health_from_samples``
   (``accuracy_drift`` / ``nonfinite`` alerts) and ``report numerics
   [--gate]``.

Import stays jax-free (the monitor/report/fleet consumers are
stdlib-pure); jax is pulled in lazily by the array helpers only.
"""

from __future__ import annotations

import os
import random
import threading

from .utils import metrics as _metrics

__all__ = [
    "NonFiniteResult",
    "NumericsPlane",
    "Reservoir",
    "DEFAULT_SLACK",
    "MIN_DRIFT_SAMPLES",
    "parse_shadow_rate",
    "realized_error",
    "nonfinite_kind",
    "record_audit",
    "record_audit_failure",
    "record_nonfinite",
    "drift_floor",
    "judge_bucket",
    "numerics_snapshot",
    "reset_numerics",
    "NUMERICS_SCHEMA",
]

#: Version stamp of the ``numerics`` block inside monitor samples.
NUMERICS_SCHEMA = 1

#: Drift slack: realized p99 may exceed the admitted budget by this
#: factor before ``accuracy_drift`` fires. Headroom for the honest gap
#: between the admitted figure (max-relative on a seeded Gaussian) and
#: the realized metric (L2-relative on live data) — ~2-4x apart for a
#: well-behaved codec, orders of magnitude apart under block-scale
#: contamination (the failure mode the audit exists to catch).
DEFAULT_SLACK = 8.0

#: A bucket needs this many audits before its drift verdict can fire —
#: one unlucky draw is not drift.
MIN_DRIFT_SAMPLES = 5

#: Reservoir capacity per (plan-tuple, tenant) bucket, and the exported
#: tail length (the monitor-sample / fleet-merge payload cap — same
#: discipline as the QoS wait reservoirs).
_RESERVOIR_CAP = 256
_TAIL_EXPORT = 64


class NonFiniteResult(ArithmeticError):
    """A serving execution produced NaN/Inf from a finite input.

    Raised by the armed numerics plane at the output boundary *before
    any handle resolves*, so the fault chain (retry → exact-rebuild →
    bisect; docs/ROBUSTNESS.md) owns the failure: a poisoned request
    fails alone with this error on its handle while finite cohort
    members complete bit-correct. ``faults.classify`` sees it as
    deterministic (retrying the same math reproduces the same Inf).
    """

    def __init__(self, message: str, *, site: str = "output",
                 kind: str = "inf"):
        super().__init__(message)
        self.site = site
        self.kind = kind


def parse_shadow_rate(raw: str | None) -> tuple[float, int] | None:
    """``DFFT_SHADOW_RATE=p[,seed]`` -> ``(p, seed)``; unset/empty ->
    None (plane dark). ``p`` clamps to [0, 1]; rate 0 still arms the
    non-finite sentinels (audits just never sample). A malformed value
    raises — a typo silently disabling the audit is not acceptable."""
    if raw is None:
        return None
    raw = raw.strip()
    if not raw:
        return None
    head, _, tail = raw.partition(",")
    try:
        p = float(head)
        seed = int(tail) if tail.strip() else 0
    except ValueError:
        raise ValueError(
            f"DFFT_SHADOW_RATE must be 'p[,seed]' (e.g. '0.1' or "
            f"'0.25,7'), got {raw!r}") from None
    return (min(max(p, 0.0), 1.0), seed)


class NumericsPlane:
    """Per-queue arm of the plane: the deterministic shadow sampler.

    One seeded PRNG consumed once per request in dispatch order — same
    seed, same traffic, same picks (the loadgen reproducibility
    contract). The ledger itself is process-global (module state), so
    every armed queue in a process feeds one monitor block.
    """

    def __init__(self, rate: float, seed: int = 0):
        self.rate = float(rate)
        self.seed = int(seed)
        self._rng = random.Random(f"shadow:{seed}")
        self._lock = threading.Lock()
        global _ARMED
        _ARMED = True

    @classmethod
    def from_env(cls) -> "NumericsPlane | None":
        parsed = parse_shadow_rate(os.environ.get("DFFT_SHADOW_RATE"))
        if parsed is None:
            return None
        return cls(*parsed)

    def pick(self) -> bool:
        """Deterministically decide whether the next request is
        shadow-audited."""
        if self.rate <= 0.0:
            return False
        with self._lock:
            return self._rng.random() < self.rate


# ------------------------------------------------------------- metrics


def realized_error(y, yref) -> float:
    """Realized relative error of ``y`` against the exact reference:
    ``||y - yref||_2 / ||yref||_2`` (L2-relative — one scalar that
    weights every element, so a cohort member whose wire tiles were
    zeroed by a co-batched outlier reads O(1), not the misleadingly
    tiny figure a max-normalized metric would give). Zero reference →
    absolute L2 of ``y``."""
    import numpy as np

    a = np.asarray(y, dtype=np.complex128).ravel()
    b = np.asarray(yref, dtype=np.complex128).ravel()
    denom = float(np.linalg.norm(b))
    num = float(np.linalg.norm(a - b))
    if not np.isfinite(num):
        return float("inf")
    return num / denom if denom > 0.0 else num


def nonfinite_kind(x) -> str | None:
    """``"nan"`` / ``"inf"`` when ``x`` contains a non-finite value,
    None when clean (or non-inexact). Two scalar device reductions —
    the arrays stay put."""
    import jax.numpy as jnp

    dt = getattr(x, "dtype", None)
    if dt is None:
        return None
    if not (jnp.issubdtype(dt, jnp.floating)
            or jnp.issubdtype(dt, jnp.complexfloating)):
        return None
    if bool(jnp.all(jnp.isfinite(x))):
        return None
    return "nan" if bool(jnp.any(jnp.isnan(x))) else "inf"


def drift_floor(dtype) -> float:
    """Noise floor under the drift judgment: 100 machine epsilons of
    the dtype's real component. Exact plans admit a budget of 0.0; an
    fp rounding wiggle above zero must not read as infinite drift."""
    import numpy as np

    try:
        real = np.finfo(np.dtype(dtype)).eps
    except ValueError:
        return 1e-12
    return 100.0 * float(real)


# ------------------------------------------------------------ reservoir


class Reservoir:
    """Algorithm-R reservoir of realized errors (seeded, bounded).

    The PR 16 wait-reservoir discipline applied to accuracy: keep a
    uniform sample of up to ``cap`` observations, export a bounded tail
    for cross-process pooling (fleet ranks concatenated tails, never
    averages percentiles)."""

    __slots__ = ("cap", "n", "values", "_rng")

    def __init__(self, cap: int = _RESERVOIR_CAP, seed: int = 0):
        self.cap = cap
        self.n = 0
        self.values: list[float] = []
        self._rng = random.Random(f"reservoir:{seed}")

    def add(self, x: float) -> None:
        self.n += 1
        if len(self.values) < self.cap:
            self.values.append(float(x))
            return
        j = self._rng.randrange(self.n)
        if j < self.cap:
            self.values[j] = float(x)

    def quantile(self, q: float) -> float:
        return _quantile(sorted(self.values), q)

    def tail(self, k: int = _TAIL_EXPORT) -> list[float]:
        """The ``k`` largest held values (the informative end of an
        error distribution) — the exported pooling payload."""
        return sorted(self.values)[-k:]


def _quantile(ordered: list[float], q: float) -> float:
    """Nearest-rank quantile of an ascending list (the fleet/qos
    convention); 0.0 on empty."""
    if not ordered:
        return 0.0
    i = min(len(ordered) - 1, max(0, int(q * len(ordered))))
    return float(ordered[i])


def judge_bucket(errors: list[float], n: int, admitted: float,
                 floor: float, slack: float = DEFAULT_SLACK) -> dict:
    """The drift verdict shared by the live ledger, the fleet merge,
    and the report renderer: realized p99 (nearest-rank over
    ``errors``) against ``max(admitted, floor) * slack``; fires only
    with ``n >= MIN_DRIFT_SAMPLES``."""
    ordered = sorted(float(e) for e in errors)
    budget = max(float(admitted), float(floor))
    p99 = _quantile(ordered, 0.99)
    ratio = (p99 / budget) if budget > 0.0 else 0.0
    return {
        "n": int(n),
        "admitted_err": float(admitted),
        "floor": float(floor),
        "realized_p50": _quantile(ordered, 0.50),
        "realized_p99": p99,
        "drift_ratio": ratio,
        "drifting": bool(n >= MIN_DRIFT_SAMPLES and ratio > slack),
    }


# --------------------------------------------------------------- ledger


class _Ledger:
    """Process-global accuracy/non-finite ledger (the monitor block's
    backing store — like the metrics registry, one per process)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            self.sampled = 0
            self.audited = 0
            self.audit_failures = 0
            self.nonfinite: dict[str, int] = {}
            # bucket key "<plan>@<tenant|->" -> dict with reservoir
            self.plans: dict[str, dict] = {}

    def record_sampled(self) -> None:
        with self._lock:
            self.sampled += 1
        _metrics.inc("numerics_shadow_sampled")

    def record_audit(self, plan_label: str, tenant: str | None,
                     realized: float, admitted: float,
                     floor: float) -> None:
        key = f"{plan_label}@{tenant or '-'}"
        with self._lock:
            self.audited += 1
            b = self.plans.get(key)
            if b is None:
                b = {"plan": plan_label, "tenant": tenant,
                     "admitted_err": float(admitted),
                     "floor": float(floor),
                     "reservoir": Reservoir(seed=len(self.plans))}
                self.plans[key] = b
            b["admitted_err"] = float(admitted)
            b["floor"] = float(floor)
            b["reservoir"].add(realized)
        _metrics.inc("numerics_shadow_audits")

    def record_audit_failure(self) -> None:
        with self._lock:
            self.audit_failures += 1

    def record_nonfinite(self, site: str, kind: str) -> None:
        key = f"{site}:{kind}"
        with self._lock:
            self.nonfinite[key] = self.nonfinite.get(key, 0) + 1
        _metrics.inc("numerics_nonfinite", site=site, kind=kind)

    def snapshot(self, slack: float = DEFAULT_SLACK) -> dict | None:
        """The monitor-sample ``numerics`` block; None while the plane
        has never been armed AND nothing was recorded (disarmed
        processes keep emitting schema-4 samples without the block)."""
        with self._lock:
            active = (_ARMED or self.sampled or self.audited
                      or self.audit_failures or self.nonfinite
                      or self.plans)
            if not active:
                return None
            out = {
                "schema": NUMERICS_SCHEMA,
                "sampled": self.sampled,
                "audited": self.audited,
                "audit_failures": self.audit_failures,
                "slack": slack,
                "nonfinite": dict(self.nonfinite),
                "plans": {},
            }
            for key, b in sorted(self.plans.items()):
                res: Reservoir = b["reservoir"]
                doc = judge_bucket(res.values, res.n, b["admitted_err"],
                                   b["floor"], slack)
                doc["plan"] = b["plan"]
                doc["tenant"] = b["tenant"]
                # The pooled-merge payload: the reservoir's upper tail.
                doc["errors"] = res.tail()
                out["plans"][key] = doc
            return out


_LEDGER = _Ledger()
#: Flips True the first time any NumericsPlane is constructed in this
#: process — from then on samples carry the block even when it is all
#: zeros (a healthy armed run must be distinguishable from a dark one).
_ARMED = False


def record_audit(plan_label: str, tenant: str | None, realized: float,
                 admitted: float, floor: float) -> None:
    _LEDGER.record_audit(plan_label, tenant, realized, admitted, floor)


def record_audit_failure() -> None:
    _LEDGER.record_audit_failure()


def record_nonfinite(site: str, kind: str) -> None:
    _LEDGER.record_nonfinite(site, kind)


def record_sampled() -> None:
    _LEDGER.record_sampled()


def numerics_snapshot(slack: float = DEFAULT_SLACK) -> dict | None:
    """The process-global ``numerics`` block (monitor schema 4), or
    None when the plane has never been armed and nothing recorded."""
    return _LEDGER.snapshot(slack)


def reset_numerics() -> None:
    """Clear the ledger (tests; the armed flag stays — arming is a
    process-lifetime property)."""
    _LEDGER.reset()
