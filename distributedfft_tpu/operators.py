"""Spectral-operator subsystem — fused FFT -> pointwise -> iFFT plans.

The transform layer below only *transforms*; the workloads users
actually run are operators: Poisson solves, spectral derivatives,
Gaussian filtering, large-kernel convolution (AccFFT's operator tier,
arXiv 1506.07933 — and "Large-Scale DFT on TPUs", arXiv 2002.03260,
keeps the pointwise stage on-device between the transform halves for
the same reason). This module plans those operators as ONE jitted
program: a forward chain that stops in the *transposed* midpoint
layout, a symbolically-specified wavenumber-indexed multiplier
generated per shard (and per overlap chunk) right there, and an
inverse chain that retraces the exchanges back to the input layout.

Why fuse at the transposed midpoint: the multiplier is diagonal
(pointwise) in wavenumber space, so it does not care which layout the
spectrum lives in. A natural-layout unfused composition — forward
transform, reshard the spectrum back to the caller's input layout,
multiply, reshard again for the inverse — pays a cancelling pair of
global transposes around the multiply. The fused chain applies the
multiplier where the forward half already is and skips that pair
entirely: the classic pruned-spectral-solver trick, compiling exactly
HALF the all-to-all collectives of the natural-layout pair (pinned in
``tests/test_a2h_operators.py``) and roughly halving t2 wire bytes per
solve.

Everything composes with the existing chain axes: ``batch=B`` rides
every collective as a bystander dim (B solves, one collective latency),
``overlap_chunks=K`` pipelines both exchange legs with the multiplier
generated per chunk through the midpoint bounds hook,
``wire_dtype="bf16"`` compresses each leg's wire (the multiplier
applies on the DECODED payload), and ``algorithm="hierarchical"`` runs
each leg as the two-leg ICI/DCN transport on a hybrid mesh. Operator
plans are plan-cache memoized, get their own wisdom kind
(``op:<name>`` — transform winners never cross-replay), and carry a
``t_mid`` stage through the model (:func:`..plan_logic
.model_stage_seconds`), the flight recorder (``t_mid``/
``t_mid_pointwise`` spans), and ``dfft.explain``.

Wavenumber convention: the unit torus — ``k_d = 2*pi*f_d`` with
``f_d`` the signed integer frequency of axis ``d`` (numpy ``fftfreq``
indexing, times ``n``). Scale the operator parameters for other box
lengths (e.g. a physical Poisson solve on ``[0, L)^3`` divides the
result by ``(2*pi/L)^-2`` — equivalently pre-scale ``f``).

See ``docs/OPERATORS.md`` for the operator menu and the fusion model.
"""

from __future__ import annotations

import hashlib
import math
from dataclasses import dataclass, field, replace as _dc_replace
from typing import Any, Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from . import api as _api
from .api import FORWARD, OpPlan3D
from .geometry import world_box
from .ops.executors import get_executor
from .parallel.pencil import build_pencil_spectral_op
from .parallel.slab import apply_multiplier, build_slab_spectral_op
from .plan_logic import logic_plan3d, resolve_tune_mode, stage_layouts
from .utils import metrics as _metrics
from .utils.trace import add_trace

__all__ = [
    "SpectralOp",
    "poisson",
    "biharmonic",
    "helmholtz",
    "gradient",
    "gaussian",
    "convolve",
    "custom",
    "chain",
    "named_op",
    "OP_NAMES",
    "multiplier_grid",
    "plan_spectral_op",
    "solve_poisson",
    "spectral_gradient",
    "gaussian_filter",
    "fft_convolve",
]


@dataclass(frozen=True)
class SpectralOp:
    """Symbolic pointwise spectral multiplier — the operator a fused
    plan applies at its transposed midpoint.

    ``kind`` names the operator family; ``params`` is the hashable
    parameter tuple (the plan-cache and wisdom identity — two ops that
    could generate different multipliers must never compare equal);
    ``payload`` carries non-hashable data (a convolution kernel, a
    custom multiplier callable) excluded from equality — its identity
    lives in ``params`` (a content digest for kernels, the callable id
    for custom ops). Build instances through the constructors below."""

    kind: str
    params: tuple = ()
    payload: Any = field(default=None, compare=False, repr=False)

    @property
    def name(self) -> str:
        """Short label for metric/CSV stamping (``poisson``,
        ``gradient0``, ...)."""
        if self.kind == "gradient":
            return f"gradient{self.params[0]}"
        if self.kind == "helmholtz":
            return f"helmholtz{self.params[0]:g}"
        if self.kind == "chain":
            return "chain(" + "+".join(o.name for o in self.payload) + ")"
        return self.kind


def poisson() -> SpectralOp:
    """Poisson solve ``laplacian(u) = f`` on the unit torus: multiplier
    ``-1/|k|^2`` with the zero mode nulled (the solution is mean-free —
    the k=0 compatibility convention every spectral solver uses)."""
    return SpectralOp("poisson")


def biharmonic() -> SpectralOp:
    """Biharmonic solve ``laplacian(laplacian(u)) = f`` on the unit
    torus: multiplier ``1/|k|^4`` with the zero mode nulled (the symbol
    of the squared Laplacian is ``|k|^4``; the solution is mean-free).
    Exactly the composition of two Poisson solves —
    ``biharmonic == chain([poisson, poisson])`` multiplier-for-
    multiplier (the parity pin of ``tests/test_a2h_operators.py``) —
    but priced and fused as ONE t_mid multiply."""
    return SpectralOp("biharmonic")


def helmholtz(shift: float) -> SpectralOp:
    """Helmholtz solve ``(shift - laplacian) u = f`` on the unit torus:
    multiplier ``1/(shift + |k|^2)``. ``shift > 0`` is the screened
    (modified) Helmholtz operator — well-posed at every mode, identity
    parity ``(shift + |k|^2) * multiplier == 1``. ``shift == 0``
    degenerates to the negative Poisson solve (zero mode nulled, the
    mean-free convention)."""
    s = float(shift)
    if not s >= 0.0:
        raise ValueError(f"helmholtz shift must be >= 0, got {shift!r}")
    return SpectralOp("helmholtz", (s,))


def chain(ops: Sequence["SpectralOp"]) -> SpectralOp:
    """Operator chaining: compose N diagonal multipliers into ONE
    fused plan — one forward transform, the *product* of the
    multipliers at the single t_mid midpoint, one inverse transform
    per set. Because every op is pointwise-diagonal in wavenumber
    space, composition is just multiplication — the chained plan
    compiles exactly the collective count of a single-op fused plan
    (pinned), where running the ops as separate plans would pay the
    full exchange round trip per op.

    Identity lives in the member ops' identities (kind + params in
    order — chains over different kernels/callables never collide)."""
    ops = tuple(ops)
    if not ops:
        raise ValueError("chain() takes at least one SpectralOp")
    for o in ops:
        if not isinstance(o, SpectralOp):
            raise TypeError(
                f"chain() composes SpectralOp instances, got {o!r}")
    if len(ops) == 1:
        return ops[0]
    return SpectralOp("chain", tuple((o.kind, o.params) for o in ops),
                      payload=ops)


def gradient(axis: int = 0) -> SpectralOp:
    """Spectral derivative along ``axis``: multiplier ``i*k_axis``."""
    if axis not in (0, 1, 2):
        raise ValueError(f"gradient axis must be 0, 1, or 2; got {axis}")
    return SpectralOp("gradient", (int(axis),))


def gaussian(sigma: float = 1.0) -> SpectralOp:
    """Gaussian low-pass filter: multiplier ``exp(-|k|^2 sigma^2 / 2)``
    (sigma in unit-torus length units)."""
    if not sigma > 0:
        raise ValueError(f"gaussian sigma must be > 0, got {sigma}")
    return SpectralOp("gaussian", (float(sigma),))


def convolve(kernel) -> SpectralOp:
    """Circular convolution with ``kernel`` (a world-shaped array):
    multiplier ``FFT(kernel)``, precomputed at plan time (numpy on
    host) and gathered per shard. The kernel spectrum is replicated per
    device — suited to kernels that fit device memory; the *data* stays
    fully distributed. Identity: ``convolve(delta at 0) == roundtrip``.

    The op's cache/wisdom identity is the kernel's content digest, so
    two plans over different kernels never share a compiled program."""
    arr = np.asarray(kernel)
    digest = hashlib.sha256(arr.tobytes()).hexdigest()[:16]
    return SpectralOp("convolve", (digest, arr.shape), payload=arr)


def custom(name: str, fn: Callable) -> SpectralOp:
    """A caller-supplied multiplier generator: ``fn(i0, i1, i2)`` takes
    broadcastable int32 GLOBAL index grids of the three spatial axes
    (already offset for the executing shard/chunk) and returns the
    pointwise factor (real or complex, broadcastable). Plan-cache
    identity is ``(name, id(fn))`` — stable within a process."""
    if not callable(fn):
        raise TypeError("custom() takes a callable multiplier generator")
    return SpectralOp("custom", (str(name), id(fn)), payload=fn)


#: Driver-tier operator menu (``speed3d -op``, ``DFFT_BENCH_OP``).
OP_NAMES = ("poisson", "grad", "gauss", "biharm", "helmholtz")


def named_op(name: str, **kw) -> SpectralOp:
    """The driver-tier operator spelled by name: ``poisson``,
    ``grad``/``gradient`` (axis via ``axis=``, default 0), ``gauss``/
    ``gaussian`` (``sigma=``, default 1.0), ``biharm``/``biharmonic``,
    ``helmholtz`` (``shift=``, default 1.0)."""
    n = name.strip().lower()
    if n == "poisson":
        return poisson()
    if n in ("grad", "gradient"):
        return gradient(kw.pop("axis", 0))
    if n in ("gauss", "gaussian"):
        return gaussian(kw.pop("sigma", 1.0))
    if n in ("biharm", "biharmonic"):
        return biharmonic()
    if n == "helmholtz":
        return helmholtz(kw.pop("shift", 1.0))
    raise ValueError(
        f"unknown operator {name!r}; expected one of {OP_NAMES}")


# ------------------------------------------------------- multiplier gen

def _multiplier_fn(op: SpectralOp, shape, cdtype) -> Callable:
    """The per-shard multiplier generator of one op at one world shape:
    ``fn(i0, i1, i2)`` over broadcastable int32 global index grids.
    Wavenumbers are computed at the chain's real component precision
    (f64 under a c128 plan) so the accuracy tier is not silently
    degraded by f32 constants."""
    shape = tuple(int(s) for s in shape)
    rdt = (jnp.float64 if np.dtype(cdtype) == np.complex128
           else jnp.float32)
    two_pi = 2.0 * math.pi

    def k_of(i, n):
        # Signed integer frequency (numpy fftfreq * n), then angular.
        f = jnp.where(i < (n + 1) // 2, i, i - n).astype(rdt)
        return f * rdt(two_pi)

    if op.kind == "poisson":

        def mult(i0, i1, i2):
            k0, k1, k2 = (k_of(i0, shape[0]), k_of(i1, shape[1]),
                          k_of(i2, shape[2]))
            ksq = k0 * k0 + k1 * k1 + k2 * k2
            nz = ksq > 0
            return jnp.where(nz, -1.0 / jnp.where(nz, ksq, 1.0), 0.0)

        return mult
    if op.kind == "biharmonic":

        def mult(i0, i1, i2):
            k0, k1, k2 = (k_of(i0, shape[0]), k_of(i1, shape[1]),
                          k_of(i2, shape[2]))
            ksq = k0 * k0 + k1 * k1 + k2 * k2
            nz = ksq > 0
            return jnp.where(
                nz, 1.0 / jnp.where(nz, ksq * ksq, 1.0), 0.0)

        return mult
    if op.kind == "helmholtz":
        shift = op.params[0]

        def mult(i0, i1, i2):
            k0, k1, k2 = (k_of(i0, shape[0]), k_of(i1, shape[1]),
                          k_of(i2, shape[2]))
            ksq = shift + k0 * k0 + k1 * k1 + k2 * k2
            if shift > 0:
                return 1.0 / ksq
            nz = ksq > 0  # shift==0: the mean-free Poisson convention
            return jnp.where(nz, 1.0 / jnp.where(nz, ksq, 1.0), 0.0)

        return mult
    if op.kind == "chain":
        # Diagonal ops compose by multiplication: ONE t_mid multiply
        # carries the whole set (one forward, one inverse per set).
        fns = [_multiplier_fn(o, shape, cdtype) for o in op.payload]

        def mult(i0, i1, i2):
            m = fns[0](i0, i1, i2)
            for f in fns[1:]:
                m = m * f(i0, i1, i2)
            return m

        return mult
    if op.kind == "gradient":
        axis = op.params[0]

        def mult(i0, i1, i2):
            k = k_of((i0, i1, i2)[axis], shape[axis])
            return (1j * k).astype(np.dtype(cdtype))

        return mult
    if op.kind == "gaussian":
        sigma = op.params[0]

        def mult(i0, i1, i2):
            k0, k1, k2 = (k_of(i0, shape[0]), k_of(i1, shape[1]),
                          k_of(i2, shape[2]))
            ksq = k0 * k0 + k1 * k1 + k2 * k2
            return jnp.exp(rdt(-0.5 * sigma * sigma) * ksq)

        return mult
    if op.kind == "convolve":
        kernel = np.asarray(op.payload)
        if kernel.shape != shape:
            raise ValueError(
                f"convolve kernel shape {kernel.shape} != world {shape}")
        # Host-side FFT at plan time (numpy — never the backend's fft
        # thunk), replicated per device; the chain gathers its shard's
        # slice through the global index grids.
        khat = jnp.asarray(np.fft.fftn(kernel).astype(np.dtype(cdtype)))

        def mult(i0, i1, i2):
            return khat[i0, i1, i2]

        return mult
    if op.kind == "custom":
        return op.payload
    raise ValueError(f"unknown SpectralOp kind {op.kind!r}")


def _full_grids(shape) -> tuple:
    n0, n1, n2 = (int(s) for s in shape)
    return (jnp.arange(n0, dtype=jnp.int32)[:, None, None],
            jnp.arange(n1, dtype=jnp.int32)[None, :, None],
            jnp.arange(n2, dtype=jnp.int32)[None, None, :])


def multiplier_grid(op: SpectralOp, shape, dtype=None):
    """The op's full world-shaped multiplier array — the reference the
    unfused composition (and the parity tests, and the bench verify
    gate) multiplies the natural-layout spectrum by."""
    cdtype = _api._default_cdtype(dtype)
    return _multiplier_fn(op, shape, cdtype)(*_full_grids(shape))


# ------------------------------------------------------------- planner

def plan_spectral_op(
    shape: Sequence[int],
    mesh=None,
    *,
    op: SpectralOp,
    decomposition: str | None = None,
    executor: str = "xla",
    dtype: Any = None,
    donate: bool = False,
    algorithm: str = "alltoall",
    overlap_chunks: int | str | None = None,
    tune: str | None = None,
    wire_dtype: str | None = None,
    max_roundtrip_err: float | None = None,
    fuse: bool | str | None = None,
    options=None,
    batch: int | None = None,
) -> OpPlan3D:
    """Plan one fused spectral operator: FFT -> pointwise ``op`` ->
    iFFT as ONE jitted program, I/O in the chain's canonical input
    layout on BOTH sides (in == out sharding; a unit multiplier is the
    identity, forward unnormalized x inverse 1/N).

    The chain runs the canonical forward decomposition, stops at the
    transposed midpoint (slab: Y-slab layout after the t2 exchange;
    pencil: the x-pencil layout after both exchanges), applies the
    wavenumber-diagonal multiplier there (the ``t_mid`` stage — indices
    are generated per shard and per overlap chunk, so the multiplier
    never materializes globally), and retraces the exchanges back —
    skipping the cancelling transpose pair a natural-layout unfused
    composition pays (half its all-to-alls; see the module docstring).

    All :func:`..api.plan_dft_c2c_3d` knobs compose: ``batch=B``
    coalesces B solves into one program, ``overlap_chunks`` pipelines
    both exchange legs, ``wire_dtype`` compresses each leg's wire,
    ``algorithm="hierarchical"`` takes the two-leg transport on a
    hybrid mesh, and ``tune="wisdom"|"measure"`` runs the measured
    planner under the operator's own wisdom kind (``op:<name>`` —
    transform winners never cross-replay; see ``docs/TUNING.md``).
    """
    shape, _ = _api._check_direction(shape, FORWARD)
    if isinstance(op, (list, tuple)):
        # Operator chaining: a sequence composes its diagonal
        # multipliers at ONE t_mid — one forward, one inverse per SET
        # (collective count pinned equal to a single-op fused plan).
        op = chain(op)
    if not isinstance(op, SpectralOp):
        raise TypeError(
            f"op must be a SpectralOp (poisson(), gradient(), ...) or "
            f"a sequence of them (operator chaining); got {op!r}")
    batch = _api._norm_batch(batch)
    opts = _api._resolve_options(
        decomposition, executor, donate, algorithm, options,
        overlap_chunks, tune, wire_dtype, max_roundtrip_err, fuse=fuse)
    if resolve_tune_mode(opts.tune) != "off":
        return _tuned_op_plan(shape, mesh, op, opts,
                              dict(dtype=dtype, batch=batch))
    if opts.executor == "auto":
        import functools

        return _api._auto_plan(
            functools.partial(plan_spectral_op, shape, mesh), opts,
            op=op, dtype=dtype, batch=batch)
    cdtype = _api._default_cdtype(dtype)
    lp = logic_plan3d(shape, mesh, opts, forward=True, batch=batch)
    lp = _dc_replace(lp, op=op.name)
    mult = _multiplier_fn(op, shape, cdtype)
    bo = 0 if batch is None else 1

    if lp.decomposition == "single":
        ex = get_executor(opts.executor)
        fft_axes = tuple(a + bo for a in range(3))
        grids = _full_grids(shape)

        def _single(x):
            y = ex(x, fft_axes, True)
            with add_trace("t_mid_pointwise"):
                y = apply_multiplier(y, mult(*grids))
            return ex(y, fft_axes, False)

        fn = jax.jit(_single, donate_argnums=(0,) if opts.donate else ())
        spec = None
    elif lp.decomposition == "slab":
        fn, spec = build_slab_spectral_op(
            lp.mesh, shape, mult,
            axis_name=_api._slab_axis_name(lp.mesh),
            executor=opts.executor, donate=opts.donate,
            algorithm=opts.algorithm,
            overlap_chunks=lp.options.overlap_chunks, batch=batch,
            wire_dtype=lp.options.wire_dtype)
    else:
        row, col = lp.mesh.axis_names[:2]
        fn, spec = build_pencil_spectral_op(
            lp.mesh, shape, mult, row_axis=row, col_axis=col,
            executor=opts.executor, donate=opts.donate,
            algorithm=opts.algorithm,
            overlap_chunks=lp.options.overlap_chunks, batch=batch,
            wire_dtype=lp.options.wire_dtype)

    # I/O sharding and boxes are the chain's INPUT side on both ends —
    # the operator's whole point is that the caller's layout round trip
    # disappears.
    if spec is None or lp.mesh is None:
        in_sh = None
    else:
        from jax.sharding import NamedSharding

        from .parallel.slab import batch_pspec

        pspec = (spec.in_pspec if hasattr(spec, "in_pspec")
                 else spec.in_spec)
        in_sh = NamedSharding(lp.mesh, batch_pspec(pspec, batch))
    boxes = list(stage_layouts(
        lp.decomposition, lp.mesh, world_box(shape),
        slab_axes=lp.slab_axes, pencil_perm=lp.pencil_perm,
        pencil_order=lp.pencil_order)[0][1])
    io_shape = shape if batch is None else (batch,) + shape
    return OpPlan3D(
        shape=shape, direction=FORWARD, dtype=cdtype,
        decomposition=lp.decomposition, executor=opts.executor,
        mesh=lp.mesh, fn=fn, spec=spec,
        in_sharding=in_sh, out_sharding=in_sh,
        in_boxes=boxes, out_boxes=list(boxes),
        in_shape=io_shape, out_shape=io_shape, batch=batch,
        options=lp.options, logic=lp,
        op=op.name, op_spec=op, multiplier=mult,
    )


plan_spectral_op = _api._plan_cached("op", plan_spectral_op)


def solve_poisson(shape, mesh=None, **kw) -> OpPlan3D:
    """Fused Poisson solver plan: ``plan(f)`` returns the mean-free u
    with ``laplacian(u) = f - mean(f)`` on the unit torus (multiplier
    ``-1/|k|^2``, zero mode nulled)."""
    return plan_spectral_op(shape, mesh, op=poisson(), **kw)


def spectral_gradient(shape, mesh=None, *, axis: int = 0,
                      **kw) -> OpPlan3D:
    """Fused spectral-derivative plan along ``axis`` (multiplier
    ``i*k_axis``)."""
    return plan_spectral_op(shape, mesh, op=gradient(axis), **kw)


def gaussian_filter(shape, mesh=None, *, sigma: float = 1.0,
                    **kw) -> OpPlan3D:
    """Fused Gaussian filter plan (multiplier
    ``exp(-|k|^2 sigma^2 / 2)``)."""
    return plan_spectral_op(shape, mesh, op=gaussian(sigma), **kw)


def fft_convolve(shape, mesh=None, *, kernel, **kw) -> OpPlan3D:
    """Fused circular-convolution plan with a world-shaped ``kernel``
    (multiplier ``FFT(kernel)``, precomputed host-side at plan time)."""
    return plan_spectral_op(shape, mesh, op=convolve(kernel), **kw)


# ------------------------------------------------------- tuned planning

def _build_op_candidate(shape, mesh, op, base, plan_kw, cand, *,
                        donate: bool) -> OpPlan3D:
    opts = _dc_replace(
        base, tune="off", decomposition=cand.decomposition,
        algorithm=cand.algorithm, executor=cand.executor,
        overlap_chunks=int(cand.overlap_chunks), donate=donate,
        wire_dtype=cand.wire_dtype or "none")
    return plan_spectral_op(shape, mesh, op=op, options=opts, **plan_kw)


def _tuned_op_plan(shape, mesh, op: SpectralOp, options, plan_kw: dict):
    """The tuned tier of :func:`plan_spectral_op` — the transform
    tuner's wisdom/measure flow under the operator's OWN wisdom kind
    (``op:<name>``): a winner measured for a fused Poisson chain (two
    exchange legs, midpoint compute between them) moves the
    transport/overlap crossovers, so transform winners and operator
    winners must never cross-replay."""
    from . import tuner
    from .parallel.multihost import is_hybrid_mesh

    mode = resolve_tune_mode(options.tune)
    base = _dc_replace(options, tune="off", donate=False)
    heuristic = _dc_replace(options, tune="off")
    ndev, mesh_dims = tuner._mesh_context(mesh)
    if ndev <= 1:
        return plan_spectral_op(shape, mesh, op=op, options=heuristic,
                                **plan_kw)
    dtype = _api._default_cdtype(plan_kw.get("dtype"))
    batch = plan_kw.get("batch")
    err_budget = options.max_roundtrip_err
    kind = f"op:{op.name}"
    key = tuner.wisdom_key(
        kind=kind, shape=shape, dtype=dtype, direction=FORWARD,
        ndev=ndev, mesh_dims=mesh_dims, batch=batch,
        err_budget=err_budget)
    path = tuner.default_wisdom_path()

    entry = tuner.lookup_wisdom(key, path) if path is not None else None
    if entry is not None:
        _metrics.inc("tune_wisdom_hits", kind=kind)
        wd = entry["winner"].get("wire_dtype")
        if wd is not None:
            rec_err = entry.get("compression_err")
            if rec_err is None:
                from .parallel.exchange import wire_roundtrip_error

                rec_err = wire_roundtrip_error(dtype, wd)
            if err_budget is None or rec_err > err_budget:
                wd = None
        cand = tuner.Candidate(
            decomposition=str(entry["winner"]["decomposition"]),
            algorithm=str(entry["winner"]["algorithm"]),
            executor=str(entry["winner"]["executor"]),
            overlap_chunks=int(entry["winner"]["overlap_chunks"]),
            wire_dtype=wd)
        return _build_op_candidate(shape, mesh, op, base, plan_kw, cand,
                                   donate=options.donate)
    _metrics.inc("tune_wisdom_misses", kind=kind)
    if mode == "wisdom":
        return plan_spectral_op(shape, mesh, op=op, options=heuristic,
                                **plan_kw)

    itemsize = np.dtype(dtype).itemsize
    wire_dtypes: tuple = (None,)
    if err_budget is not None:
        wire_dtypes = (None, "bf16")
    cands = tuner.prune_candidates(
        tuner.enumerate_candidates(
            shape, ndev, mesh_dims=mesh_dims, itemsize=itemsize,
            batch=batch, hybrid=is_hybrid_mesh(mesh),
            wire_dtypes=wire_dtypes),
        shape, mesh, itemsize=itemsize, batch=batch,
        max_err=err_budget, dtype=dtype)
    _metrics.set_gauge("tune_candidates", len(cands), kind=kind,
                       stage="pruned")
    by_label = {c.label: c for c in cands}
    _metrics.inc("tune_tournaments", kind=kind)
    iters, repeats = tuner.tune_budget()

    def build(label: str):
        return _build_op_candidate(shape, mesh, op, base, plan_kw,
                                   by_label[label], donate=False)

    def measure(plan) -> float:
        from .utils.timing import time_fn_amortized

        x = _api.alloc_local(plan)
        t, _ = time_fn_amortized(plan.fn, x, iters=iters,
                                 repeats=repeats)
        return t

    winner, built, times = tuner.measured_select(
        list(by_label), build, measure, what=f"{kind} tune candidate")
    tuner._log_model_divergence(by_label, times, winner, shape, mesh,
                                itemsize=itemsize, batch=batch)
    tuner.record_wisdom(key, by_label[winner], times[winner], path=path,
                        times=times)
    if options.donate:
        return _build_op_candidate(shape, mesh, op, base, plan_kw,
                                   by_label[winner], donate=True)
    return built[winner]
