from .executors import (  # noqa: F401
    Scale,
    get_executor,
    register_executor,
    available_executors,
    scale_factor,
    apply_scale,
)
