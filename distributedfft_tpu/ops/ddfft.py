"""Emulated double-precision DFT — the 1e-11 accuracy tier on a TPU.

The reference's accuracy bar is double precision at 1e-11 (heFFTe's test
gate, ``heffte/heffteBenchmark/test/test_common.h:138``; observed ~4e-15,
``/root/reference/README.md:56``). TPUs have no f64 MXU and no complex128
at all, so that tier cannot be reached by dtype choice — it has to be
*constructed*. This module does it with two ingredients:

1. **Double-double (dd) storage**: a value is an unevaluated sum
   ``hi + lo`` of two float32s (~49 significand bits), the classic
   two-float representation. Host conversion is exact: ``hi = f32(x)``,
   ``lo = f32(x - hi)``.

2. **Exact-sliced matmuls (Ozaki-style splitting) on the MXU**: the DFT
   contraction ``C = A @ W`` is decomposed into partial matmuls of
   *slices* with <=8 significand bits each. An 8-bit slice is exactly
   representable in bfloat16, the product of two slices (<=16 bits) is
   exact in the MXU's float32 accumulator, and a K<=512-term sum of such
   products (<=25 bits... kept under 2^24 by the slice budget) rounds to
   at most 1 ulp — so every partial matmul runs at FULL bf16 MXU rate
   while being exact. The partials (ordered large to small) are then
   recombined with compensated two-float adds on the VPU. Net effect:
   f64-class accuracy from bf16 hardware, the same "matrix engine as FFT
   engine" thesis as the rest of this framework (``ops/dft_matmul.py``)
   extended to the reference's double-precision tier. The reference's
   own half-precision matrix-FFT experiment (``FFT_matrix_2d_kernel.cpp``
   WMMA) walks the opposite direction — precision traded *down* for
   matrix-unit speed; here slicing buys the precision back.

Slicing scheme (per row, after exact power-of-two row normalization):

- ``hi`` is extracted into ``_SLICES_HI`` = 8 slices at grids
  ``2^(1-7(s+1))`` relative to the row max — 7 value bits per slice
  (+1 carry bit from round-to-nearest, still bf16-exact). Eight slices
  reach 2^-56: elements far below the row max keep their full f32
  significand.
- ``lo`` (<= ulp(hi)/2, i.e. ~2^-24 below the row max) is normalized by
  its own row max and extracted into ``_SLICES_LO`` = 4 slices.
- The DFT matrix ``W`` (host float64, |entries| <= 1) is pre-sliced into
  7 slices of 7 bits.
- Partial products are kept when their combined grid can still touch the
  2^-52 target: hi-slice i x W-slice j for i+j <= 6 (28 matmuls),
  lo-slice i x W-slice j for i+j <= 2 (6 matmuls). A complex x complex
  contraction is 4 real contractions.

Scope: dense-matrix DFT for axis lengths n <= ``DD_DENSE_MAX`` (=512),
extended by a dd four-step (two dense stages with an exact-dd twiddle,
:func:`_dd_cmul` built on barrier-guarded Dekker two-products) to every
length with a factor pair whose BOTH factors are <= 512 — all smooth
lengths through 512^2 = 262,144, covering the BASELINE.json accuracy
configs including 1024^3 and 2048^3 axes — and by a dd Bluestein
(:func:`_dd_bluestein_last`: chirp-z over a padded power of two, built
entirely from the same dd machinery) to lengths with prime factors
above 512, up to prime axes ~131072 (measured ~7e-14 at n=521/1031).

Dynamic-range note: two-float storage needs the lo component to live
~25-50 bits below hi, and TPU/host float units flush SUBNORMAL inputs
to zero (DAZ), so lo is only reliable while it stays normal: the tier
holds for magnitudes in roughly [1e-25, 3e38] (measured: 1e-25 at
3.8e-14; degradation begins near 1e-28 as per-element lo values cross
2^-126 and flush). Below that, accuracy degrades gracefully toward
plain f32 — inherent to the representation on flush-to-zero hardware,
not to the transform. Rescale data toward O(1) for tiny-magnitude
worlds (standard practice; an exact power-of-two scale is free).

Verification: tests/test_ddfft.py holds the slices bf16-exact, checks the
3D transform against numpy's float64 ``fftn`` at the 1e-11 tier on CPU,
and the hardware campaign measures the same error on the real chip.
"""

from __future__ import annotations

import functools
import math

import jax.numpy as jnp
import numpy as np
from jax import lax

# Largest axis length the dense dd-DFT covers. K=512 keeps the exact-sum
# budget: products of two 8-bit slices (16 bits) summed over K=512 terms
# stay within 16+9=25 bits... the slice extraction's round-to-nearest
# keeps magnitudes <= 129/256 of the 8-bit ceiling, so the worst sum is
# 512 * 129^2 * grid^2 < 2^24 * grid^2 — exact in the f32 accumulator.
DD_DENSE_MAX = 512

_SLICES_HI = 8
_SLICES_LO = 4
_W_SLICES = 7
_B = 7  # slice width in bits
_CUT_HI = 6  # keep hi-slice i x W-slice j when i + j <= _CUT_HI
_CUT_LO = 2  # lo starts ~2^-24 down; i + j <= 2 reaches 2^-24-7*4 ~ 2^-52


def _dd_depth() -> tuple[int, int, int]:
    """(hi slices, hi pair cut, lo pair cut) — the engine's accuracy/
    speed frontier, env-tunable for the hardware campaign
    (``DFFT_DD_DEPTH=s,ch,cl``). Measured on the 1D engine: default
    8,6,2 ~5e-14; 7,5,2 ~9e-13; 7,5,1 ~6e-12 (still inside the 1e-11
    tier at ~30% fewer matmuls); 6,4,1 ~9e-11 (outside). Read at trace
    time: set before planning; tuning sweeps must clear the jit caches
    like the tile sweeps do."""
    import os

    env = os.environ.get("DFFT_DD_DEPTH")
    if not env:
        return _SLICES_HI, _CUT_HI, _CUT_LO
    s, ch, cl = (int(v) for v in env.split(","))
    return s, ch, cl


# ------------------------------------------------------------ dd helpers

def dd_from_host(x) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Two-float split of a host float64/complex128 array into (hi, lo)
    float32/complex64 device arrays. The split is not exact: the f64
    residual ``x - f64(hi)`` can need up to 29 significand bits, so
    ``lo`` itself rounds — the pair carries ~49 significand bits
    (relative residual ~2^-49; see the module docstring and
    ``test_dd_host_roundtrip_exact``'s 1e-13 bound)."""
    x = np.asarray(x)
    if np.iscomplexobj(x):
        hi = x.astype(np.complex64)
        lo = (x - hi.astype(np.complex128)).astype(np.complex64)
    else:
        hi = x.astype(np.float32)
        lo = (x - hi.astype(np.float64)).astype(np.float32)
    return jnp.asarray(hi), jnp.asarray(lo)


def dd_to_host(hi, lo) -> np.ndarray:
    """(hi, lo) device pair -> host float64/complex128 (exact sum)."""
    h = np.asarray(hi)
    wide = np.complex128 if np.iscomplexobj(h) else np.float64
    return h.astype(wide) + np.asarray(lo).astype(wide)


def _two_sum(a, b):
    """Knuth two-sum: s + err == a + b exactly (f32 IEEE adds).

    The sum is wrapped in an optimization barrier: under jit, XLA's
    algebraic simplifier folds patterns like ``(a + b) - a -> b``, which
    collapses the error term to zero and silently degrades the whole
    engine to bf16 accuracy (caught by the jitted smoke run; eager
    per-op dispatch never exposed it). The barrier makes ``s`` opaque so
    every downstream difference is computed as written."""
    s = lax.optimization_barrier(a + b)
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def _split(a):
    """Dekker split of f32 into 12+12 significand-bit halves whose
    pairwise products are exact. The scaled value is barrier-wrapped for
    the same reason as :func:`_two_sum`."""
    c = lax.optimization_barrier(jnp.float32(4097.0) * a)  # 2^12 + 1
    big = c - (c - a)
    return big, a - big


def _two_prod(a, b):
    """Dekker two-product: p + err == a * b exactly (no FMA needed)."""
    p = a * b
    ah, al = _split(a)
    bh, bl = _split(b)
    err = ((lax.optimization_barrier(ah * bh) - p) + ah * bl + al * bh) \
        + al * bl
    return p, err


def _dd_mul(ah, al, bh, bl):
    """Real dd x dd multiply: (ah+al)(bh+bl) to ~2^-48 relative."""
    p, e = _two_prod(ah, bh)
    e = e + (ah * bl + al * bh)
    return _two_sum(p, e)


def _dd_add(ah, al, bh, bl):
    """Real dd + dd add (Knuth-compensated)."""
    s, e = _two_sum(ah, bh)
    return _two_sum(s, e + al + bl)


def _dd_cmul(xh, xl, th, tl):
    """Complex dd multiply by a complex dd constant: four real dd
    products recombined with compensated adds (the dd twiddle apply of
    the four-step; cf. the reference's inter-pass twiddle LUTs,
    ``templateFFT.cpp:5144-5153``)."""
    ar, ai = jnp.real(xh), jnp.imag(xh)
    br, bi = jnp.real(xl), jnp.imag(xl)
    cr, ci = jnp.real(th), jnp.imag(th)
    dr, di = jnp.real(tl), jnp.imag(tl)
    rr_h, rr_l = _dd_mul(ar, br, cr, dr)   # Re*Re
    ii_h, ii_l = _dd_mul(ai, bi, ci, di)   # Im*Im
    ri_h, ri_l = _dd_mul(ar, br, ci, di)   # Re*Im
    ir_h, ir_l = _dd_mul(ai, bi, cr, dr)   # Im*Re
    re_h, re_l = _dd_add(rr_h, rr_l, -ii_h, -ii_l)
    im_h, im_l = _dd_add(ri_h, ri_l, ir_h, ir_l)
    return lax.complex(re_h, im_h), lax.complex(re_l, im_l)


# Partial-product diagonals at or past this order key are summed in
# plain f32 before entering the compensated chain: their magnitude is
# <= ~2^-28 of the row max, so the plain sum's rounding (~25 adds x
# eps x 2^-28 ~ 2^-49) sits below the tier while costing 1 VPU op per
# term instead of the two-sum chain's ~8 — the accumulation is roughly
# half the engine's non-MXU work.
_PLAIN_SUM_KEY = 4


def _dd_accumulate_quad(parts):
    """Compensated accumulation of the Cr and Ci chains together from
    (order_key, thunk) parts, where each thunk yields the four quadrant
    terms of one stacked slice-product (see :func:`_quad_term`): two
    terms for the Cr chain and two for the Ci chain, consumed in key
    order exactly as the per-contraction chains did. Driving both
    chains from one pass keeps at most ONE stacked product live at a
    time outside jit — at campaign sizes materializing the ~34 products
    up front peaks at multiple GB. Terms are consumed largest-magnitude
    first; deep diagonals (key >= ``_PLAIN_SUM_KEY``) fold into one
    plain-f32 term per chain. Error ~2^-48 relative per chain."""
    big = [t for k, t in parts if k < _PLAIN_SUM_KEY]
    small = [t for k, t in parts if k >= _PLAIN_SUM_KEY]
    if not big:  # degenerate depth settings: everything is "small"
        big, small = small[:1], small[1:]
    cr_a, cr_b, ci_a, ci_b = big[0]()
    cr_hi, cr_lo = _two_sum(cr_a, cr_b)
    ci_hi, ci_lo = _two_sum(ci_a, ci_b)
    for t in big[1:]:
        cr_a, cr_b, ci_a, ci_b = t()
        cr_hi, e = _two_sum(cr_hi, cr_a)
        cr_lo = cr_lo + e
        cr_hi, e = _two_sum(cr_hi, cr_b)
        cr_lo = cr_lo + e
        ci_hi, e = _two_sum(ci_hi, ci_a)
        ci_lo = ci_lo + e
        ci_hi, e = _two_sum(ci_hi, ci_b)
        ci_lo = ci_lo + e
    if small:
        cr_a, cr_b, ci_a, ci_b = small[0]()
        cr_t = cr_a + cr_b
        ci_t = ci_a + ci_b
        for t in small[1:]:
            cr_a, cr_b, ci_a, ci_b = t()
            cr_t = cr_t + cr_a + cr_b
            ci_t = ci_t + ci_a + ci_b
        cr_hi, e = _two_sum(cr_hi, cr_t)
        cr_lo = cr_lo + e
        ci_hi, e = _two_sum(ci_hi, ci_t)
        ci_lo = ci_lo + e
    return _two_sum(cr_hi, cr_lo), _two_sum(ci_hi, ci_lo)


# ------------------------------------------------------- slicing engine

def _extract_slices(x: jnp.ndarray, n_slices: int) -> list[jnp.ndarray]:
    """Sequential slice extraction of a row-normalized f32 array
    (|x| < 2): slice s holds x rounded to grid 2^(1-_B*(s+1)) minus the
    previous slices. Each slice is an integer multiple of its grid with
    magnitude <= 2^(_B+1) * grid — exactly representable in bfloat16.
    The splitter constant trick (r + S) - S rounds r to ulp(S); both
    operations and the residual subtraction are exact in f32."""
    slices = []
    r = x
    for s in range(n_slices):
        grid = 2.0 ** (1 - _B * (s + 1))
        big = jnp.float32(1.5 * (2 ** 23) * grid)
        # The barrier stops XLA folding (r + big) - big back to r under
        # jit (see _two_sum) — without it every slice silently becomes
        # the full value and the scheme degrades to plain bf16.
        top = lax.optimization_barrier(r + big) - big
        slices.append(top)
        r = r - top
    return slices


def _row_exponent(x: jnp.ndarray) -> jnp.ndarray:
    """Per-row max exponent e with 2^-e an exact, finite f32 scale:
    clamped to [-126, 127] so neither 2^-e nor 2^e overflows (at e = 128,
    row max near f32-max, the scaled row tops out just under 2 — inside
    :func:`_extract_slices`' domain)."""
    mu = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    _, e = jnp.frexp(jnp.where(mu == 0, 1.0, mu))
    return jnp.clip(e, -126, 127)


@functools.lru_cache(maxsize=None)
def _w_slices_np(n: int, forward: bool, normalize: bool):
    """Host-exact slices of the n x n DFT matrix (f64), 7 bits each, as
    float32 arrays (cast to bf16 at use).

    ``normalize`` folds only the NON-power-of-two residue of the 1/n
    inverse scale into the matrix — ``w * 2^floor(log2 n) / n``, entries
    staying O(1) so the fixed slice grids keep their full occupancy (a
    plain ``w/n`` at n=512 zeroes the leading slices and pushes real
    signal past the pair cutoff — measured 2e-11, outside the tier). The
    remaining exact power of two is returned as ``k`` for the caller to
    apply with ``ldexp`` (exact), giving a normalized inverse that stays
    inside 1e-11 at every supported n."""
    sign = -2j if forward else 2j
    jk = np.outer(np.arange(n), np.arange(n))
    w = np.exp(sign * np.pi * (jk % n) / n)
    k = 0
    if normalize:
        k = int(math.floor(math.log2(n)))
        w = w * (2.0 ** k / n)
    outs = []
    for part in (w.real, w.imag):
        r = part.copy()
        sl = []
        for s in range(_W_SLICES):
            grid = 2.0 ** (-_B * (s + 1) + 1)
            top = np.round(r / grid) * grid
            sl.append(top.astype(np.float32))
            r = r - top
        outs.append(sl)
    return tuple(outs[0]), tuple(outs[1]), k


def _stacked_dot(xs, ws):
    """One bf16 MXU product of a row-stacked operand slice against a
    column-stacked W slice: [2R, n] @ [n, 2n] -> f32 [2R, 2n]. Rows are
    the re operand over the im operand; columns are Wr beside Wi — four
    independent real contractions in ONE matmul (rows and columns never
    mix under contraction, so every partial stays sliced-exact). This
    quarters the dot count of the old per-contraction layout (136 -> 34
    per axis) and feeds the MXU 4x-larger tiles."""
    return lax.dot_general(
        xs.astype(jnp.bfloat16), ws.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        precision=lax.Precision.DEFAULT,
        preferred_element_type=jnp.float32,
    )


def _quad_term(xs, ws, fr, fi, r, n):
    """The four chain terms of one stacked slice-product: Cr gets
    (+Ar@Wr * fr, -Ai@Wi * fi), Ci gets (+Ar@Wi * fr, +Ai@Wr * fi).
    The scales are exact powers of two (negation included), applied in
    the NORMALIZED domain: each term carries only 2^(e_operand -
    common_e) <= 1 relative to the contraction's common row exponent,
    and the caller applies 2^common_e once after accumulation. Scaling
    each term by its full 2^e instead underflows the far diagonals for
    small-magnitude rows (measured: 7e-9 error at |x| ~ 1e-30, where
    terms near 2^-100 * 2^-49 flush to zero) — relative factors keep
    every term that matters above the f32 floor."""
    d = _stacked_dot(xs, ws)
    return (d[:r, :n] * fr, d[r:, n:] * (-fi),
            d[:r, n:] * fr, d[r:, :n] * fi)


def _operand_slices(a_hi, a_lo):
    """Row-normalize and slice one real operand once (shared between the
    two contractions that consume it). Returns the slices plus the row
    exponents (the scales are reapplied once, post-accumulation)."""
    e_hi = _row_exponent(a_hi)
    e_lo = _row_exponent(a_lo)
    hi_n = a_hi * jnp.ldexp(jnp.float32(1.0), -e_hi)
    lo_n = a_lo * jnp.ldexp(jnp.float32(1.0), -e_lo)
    return (_extract_slices(hi_n, _dd_depth()[0]), e_hi,
            _extract_slices(lo_n, _SLICES_LO), e_lo)


def _dd_dft_last(re_hi, re_lo, im_hi, im_lo, n: int, forward: bool,
                 normalize: bool):
    """dd complex DFT along the last axis: the four real contractions
    Cr = Ar@Wr - Ai@Wi, Ci = Ar@Wi + Ai@Wr run as ONE stacked matmul
    per kept slice pair ([re;im] rows x [Wr|Wi] columns — see
    :func:`_stacked_dot`), recombined with compensated adds in the
    normalized domain, row scales (and the inverse's exact power-of-two
    remainder) applied once at the end."""
    wr_sl, wi_sl, k = _w_slices_np(n, forward, normalize)
    w_st = [jnp.asarray(np.concatenate((r, i), axis=1))
            for r, i in zip(wr_sl, wi_sl)]
    re_slices = _operand_slices(re_hi, re_lo)
    im_slices = _operand_slices(im_hi, im_lo)
    # One common row exponent for everything feeding an output (re and
    # im operands both feed Cr and Ci): relative factors stay <= 1, and
    # the full scale is applied exactly once after accumulation —
    # combined with the inverse's power-of-two remainder k.
    common_e = jnp.maximum(re_slices[1], im_slices[1])

    lead = re_hi.shape[:-1]
    r = math.prod(lead) if lead else 1

    def flat(a):
        return a.reshape(r, n)

    def fcol(e):  # [R, 1] exact power-of-two scale column
        return jnp.ldexp(jnp.float32(1.0), e - common_e).reshape(r, 1)

    hi_st = [jnp.concatenate((flat(a), flat(b)), axis=0)
             for a, b in zip(re_slices[0], im_slices[0])]
    lo_st = [jnp.concatenate((flat(a), flat(b)), axis=0)
             for a, b in zip(re_slices[2], im_slices[2])]
    fr_hi, fi_hi = fcol(re_slices[1]), fcol(im_slices[1])
    fr_lo, fi_lo = fcol(re_slices[3]), fcol(im_slices[3])
    _, cut_hi, cut_lo = _dd_depth()

    parts = []  # (order_key, thunk -> 4 quadrant terms)
    for i, xs in enumerate(hi_st):
        for j, ws in enumerate(w_st):
            if i + j <= cut_hi:
                parts.append((i + j, functools.partial(
                    _quad_term, xs, ws, fr_hi, fi_hi, r, n)))
    for i, xs in enumerate(lo_st):
        for j, ws in enumerate(w_st):
            if i + j <= cut_lo:
                # lo sits ~24 bits below hi: order after the hi diagonals.
                parts.append((i + j + 24 // _B, functools.partial(
                    _quad_term, xs, ws, fr_lo, fi_lo, r, n)))
    parts.sort(key=lambda kv: kv[0])
    (cr_hi, cr_lo), (ci_hi, ci_lo) = _dd_accumulate_quad(parts)
    back = jnp.ldexp(jnp.float32(1.0), common_e - k)
    out_shape = lead + (n,)
    return tuple(v.reshape(out_shape) * s for v, s in (
        (cr_hi, back), (cr_lo, back), (ci_hi, back), (ci_lo, back)))


# ----------------------------------------------------- four-step (n > 512)

def _dd_split(n: int) -> tuple[int, int] | None:
    """Balanced factor pair with both factors dense-coverable — the same
    native-scheduler split decision every other engine here uses
    (``dfft_balanced_split``)."""
    from .. import native

    return native.balanced_split(n, DD_DENSE_MAX)


@functools.lru_cache(maxsize=None)
def _dd_twiddle_np(n: int, n1: int, n2: int, forward: bool):
    """Inter-stage twiddle table (``dft_matmul._twiddle_np`` — one
    twiddle convention in the repo) as an exact host-split dd pair
    (complex64 hi + lo), shaped [n1, n2]."""
    from .dft_matmul import _twiddle_np

    t = _twiddle_np(n, n1, n2, forward)
    th = t.astype(np.complex64)
    tl = (t - th.astype(np.complex128)).astype(np.complex64)
    return th, tl


def _dd_four_step_last(hi, lo, n: int, forward: bool):
    """dd DFT of the last axis via the four-step split n = n1*n2: two
    dense dd stages with an exact-dd twiddle between them (the same
    recursion as ``dft_matmul._fft_last``, at the dd tier). The inverse
    normalization composes from the stages' own 1/n1 and 1/n2.

    The twiddle path's Dekker splits compute ``4097 * a``, which
    overflows f32 above ~8e34 — and the unnormalized stage-1 output
    grows to n1 x the input. The DFT is linear, so the whole pass runs
    on an exactly 2^-e down-scaled copy and the scale is restored once
    at the end. The exponent comes from a static bound on the INPUT —
    |stage-1 out| <= n1 * max|in|, so e = exp(max|in|) + ceil(log2 n1)
    — rather than a max over the stage-1 output: the input reduction has
    no dependency on stage 1, so XLA can overlap it with the stage-1
    matmuls instead of serializing a full-array reduction between the
    stages (the plan-time-resolution discipline of the reference's
    launch parameters, ``templateFFT.cpp:6212-6260``)."""
    n1, n2 = _dd_split(n)
    shp = hi.shape
    # Overflow bound off the critical path: computed on the input,
    # before stage 1. The extra log2(n1) headroom (vs the old measured
    # stage-1 max) costs <= 9 bits of down-scale; scaled lo components
    # sit ~2^-60 at worst — far above the f32 subnormal floor.
    mu = jnp.max(jnp.abs(jnp.real(hi))) + jnp.max(jnp.abs(jnp.imag(hi)))
    _, e = jnp.frexp(jnp.where(mu == 0, 1.0, mu))
    # 126 (not 127): 2^-127 is subnormal and flushes to zero — a 127
    # clip silently zeroes the whole transform for huge-but-finite
    # inputs (the bound reaches 127 at ~2^(126 - log2 n1) already).
    e = jnp.clip(e + int(math.ceil(math.log2(n1))), -126, 126)
    down = jnp.ldexp(jnp.float32(1.0), -e)
    hi = hi.reshape(shp[:-1] + (n1, n2))
    lo = lo.reshape(shp[:-1] + (n1, n2))
    # DFT_n1 over j1 (axis -2) -> [..., k1, j2].
    hi, lo = fft_axis_dd(hi, lo, axis=-2, forward=forward)
    hi, lo = hi * down, lo * down
    th, tl = _dd_twiddle_np(n, n1, n2, forward)
    hi, lo = _dd_cmul(hi, lo, jnp.asarray(th), jnp.asarray(tl))
    # DFT_n2 over j2 (last axis) -> [..., k1, k2].
    hi, lo = fft_axis_dd(hi, lo, axis=-1, forward=forward)
    up = jnp.ldexp(jnp.float32(1.0), e)
    hi, lo = hi * up, lo * up
    # Output flat index k = k2*n1 + k1.
    hi = jnp.swapaxes(hi, -1, -2).reshape(shp)
    lo = jnp.swapaxes(lo, -1, -2).reshape(shp)
    return hi, lo


# ------------------------------------------------- Bluestein (large primes)

# Largest padded length the dd Bluestein accepts: 2^18 = 512*512 is the
# largest power of two the dd four-step covers, bounding prime axes at
# ~131072 (the same chirp-z fallback role as dft_matmul's Bluestein,
# itself the over-radix-13 answer the reference lacks).
_DD_BLUESTEIN_MAX_M = DD_DENSE_MAX * DD_DENSE_MAX


def _dd_bluestein_m(n: int) -> int | None:
    m = 1
    while m < 2 * n - 1:
        m *= 2
    return m if m <= _DD_BLUESTEIN_MAX_M else None


@functools.lru_cache(maxsize=None)
def _dd_bluestein_np(n: int, m: int, forward: bool):
    """Host-exact Bluestein tables as dd pairs: the chirp and kernel
    spectrum come from ``dft_matmul._bluestein_tables`` (ONE chirp
    convention in the repo, like :func:`_dd_twiddle_np` reuses its
    twiddle), with the inverse's 1/n folded into the output chirp. The
    kernel spectrum is host-f64 ``np.fft.fft`` output (error ~1e-16,
    below the dd pair's ~3.5e-15 storage grid), so no on-device kernel
    transform is needed."""
    from .dft_matmul import _bluestein_tables

    w, big = _bluestein_tables(n, m, forward)
    wout = w if forward else w / n  # inverse: numpy 1/n convention

    def dd(z):
        zh = z.astype(np.complex64)
        return zh, (z - zh.astype(np.complex128)).astype(np.complex64)

    return dd(w), dd(wout), dd(big)


def _dd_bluestein_last(hi, lo, n: int, forward: bool):
    """dd DFT of a last axis whose length has a prime factor above
    ``DD_DENSE_MAX``: the chirp-z identity X_k = w_k * (x.w (*) conj-
    chirp)_k realized as two dd four-step FFTs of the padded power-of-two
    length m >= 2n-1 with dd chirp multiplies between (every piece is the
    existing machinery: :func:`_dd_cmul`, :func:`fft_axis_dd`). The same
    static input down-scale as the four-step keeps the Dekker splits
    clear of the f32 ceiling (|FFT_m| <= m * max|x|, |B| ~ sqrt(m))."""
    m = _dd_bluestein_m(n)
    (wh, wl), (oh, ol), (bh, bl) = (
        (jnp.asarray(a), jnp.asarray(b_)) for a, b_ in
        _dd_bluestein_np(n, m, forward))
    mu = jnp.max(jnp.abs(jnp.real(hi))) + jnp.max(jnp.abs(jnp.imag(hi)))
    _, e = jnp.frexp(jnp.where(mu == 0, 1.0, mu))
    # 126 (not 127): 2^-127 is subnormal and flushes to zero — a 127
    # clip silently zeroes the whole transform for ~2^126-max inputs.
    e = jnp.clip(e, -126, 126)
    down = jnp.ldexp(jnp.float32(1.0), -e)
    ah, al = _dd_cmul(hi * down, lo * down, wh, wl)
    pad = [(0, 0)] * (ah.ndim - 1) + [(0, m - n)]
    fh, fl = fft_axis_dd(jnp.pad(ah, pad), jnp.pad(al, pad), axis=-1)
    gh, gl = _dd_cmul(fh, fl, bh, bl)
    ch, cl = fft_axis_dd(gh, gl, axis=-1, forward=False)
    yh, yl = _dd_cmul(ch[..., :n], cl[..., :n], oh, ol)
    up = jnp.ldexp(jnp.float32(1.0), e)
    return yh * up, yl * up


# ------------------------------------------------------------ public API

def fft_axis_dd(hi: jnp.ndarray, lo: jnp.ndarray, axis: int,
                forward: bool = True) -> tuple[jnp.ndarray, jnp.ndarray]:
    """dd complex DFT along ``axis`` of a (hi, lo) complex64 pair.
    Forward unnormalized; inverse applies the exact 1/n (numpy
    convention, like every executor in this framework). Lengths above
    ``DD_DENSE_MAX`` take the dd four-step when n has a factor pair with
    BOTH factors <= 512 (all smooth lengths through 512^2 = 262,144);
    lengths with a prime factor above 512 take the dd Bluestein
    (chirp-z over a padded power of two, itself a dd four-step) up to
    prime axes ~131072."""
    n = hi.shape[axis]
    four_step = n > DD_DENSE_MAX
    bluestein = four_step and _dd_split(n) is None
    if bluestein and _dd_bluestein_m(n) is None:
        raise ValueError(
            f"dd executor: no n1*n2 split of {n} with both factors "
            f"<= {DD_DENSE_MAX}, and the Bluestein pad 2^ceil(log2(2n-1)) "
            f"exceeds {_DD_BLUESTEIN_MAX_M} — prime axes above "
            f"{_DD_BLUESTEIN_MAX_M // 2} are out of dd scope"
        )
    moved = axis not in (-1, hi.ndim - 1)
    if moved:
        hi = jnp.moveaxis(hi, axis, -1)
        lo = jnp.moveaxis(lo, axis, -1)
    if bluestein:
        out_hi, out_lo = _dd_bluestein_last(hi, lo, n, forward)
    elif four_step:
        out_hi, out_lo = _dd_four_step_last(hi, lo, n, forward)
    else:
        cr_hi, cr_lo, ci_hi, ci_lo = _dd_dft_last(
            jnp.real(hi), jnp.real(lo), jnp.imag(hi), jnp.imag(lo),
            n, forward, normalize=not forward,
        )
        out_hi = lax.complex(cr_hi, ci_hi)
        out_lo = lax.complex(cr_lo, ci_lo)
    if moved:
        out_hi = jnp.moveaxis(out_hi, -1, axis)
        out_lo = jnp.moveaxis(out_lo, -1, axis)
    return out_hi, out_lo


def fftn_dd(hi: jnp.ndarray, lo: jnp.ndarray, axes=None,
            forward: bool = True) -> tuple[jnp.ndarray, jnp.ndarray]:
    """dd complex N-D DFT over ``axes`` (default: all) of a (hi, lo)
    complex64 pair — the double-precision-tier 3D transform."""
    if axes is None:
        axes = tuple(range(hi.ndim))
    for ax in axes:
        hi, lo = fft_axis_dd(hi, lo, ax, forward=forward)
    return hi, lo


def rfftn_dd(hi: jnp.ndarray, lo: jnp.ndarray,
             ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """dd real-to-complex 3D DFT: real float32 (hi, lo) pairs in,
    half-spectrum complex dd out (last axis shrunk to n2//2+1) — the
    double tier of heFFTe's ``fft3d_r2c`` (``heffte_fft3d_r2c.h``).

    The last axis runs as a full complex dd DFT and keeps the
    non-redundant half — 2x the flops of a packed half-complex r2c, a
    deliberate trade: the dd tier is the *accuracy* surface and the
    packed trick's pack/unpack algebra would need its own dd error
    analysis (the c64 executors keep the fast packed path,
    ``ops/realfft.py``)."""
    n2 = hi.shape[-1]
    chi = lax.complex(hi, jnp.zeros_like(hi))
    clo = lax.complex(lo, jnp.zeros_like(lo))
    chi, clo = fft_axis_dd(chi, clo, axis=-1)
    h = n2 // 2 + 1
    chi, clo = chi[..., :h], clo[..., :h]
    for ax in range(hi.ndim - 1):
        chi, clo = fft_axis_dd(chi, clo, axis=ax)
    return chi, clo


def mirror_half_spectrum(y: jnp.ndarray, n2: int,
                         axis: int = -1) -> jnp.ndarray:
    """Rebuild the full hermitian axis (true extent ``n2``) from its
    non-redundant half (the odd-n discipline of
    ``executors._matmul_c2r``); one home for the index algebra, shared by
    the single-device and distributed dd c2r paths."""
    h = y.shape[axis]
    m = lax.slice_in_dim(y, 1, n2 - h + 1, axis=axis)
    return jnp.concatenate([y, jnp.conj(jnp.flip(m, axis=axis))], axis=axis)


def irfftn_dd(hi: jnp.ndarray, lo: jnp.ndarray, n2: int,
              ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Inverse of :func:`rfftn_dd`: half-spectrum complex dd in, real dd
    out with numpy 1/N scaling (imaginary residue dropped)."""
    for ax in range(hi.ndim - 1):
        hi, lo = fft_axis_dd(hi, lo, axis=ax, forward=False)
    hi, lo = fft_axis_dd(mirror_half_spectrum(hi, n2),
                         mirror_half_spectrum(lo, n2),
                         axis=-1, forward=False)
    return jnp.real(hi), jnp.real(lo)


def dd_scale(hi, lo, s: float) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Multiply a dd pair by a host scalar AT THE TIER: a plain f32
    multiply rounds each component to 2^-24 and collapses the pair to
    single precision. Exact powers of two short-circuit (exact f32
    multiplies); everything else goes through the real dd x dd product
    (~2^-48) on each component — the dd analog of the roc backend's
    ``scale_element`` normalization kernel."""
    if s == 1.0:
        return hi, lo
    m, _ = math.frexp(s)
    if abs(m) == 0.5:  # exact (signed) power of two
        f = jnp.float32(s)
        return hi * f, lo * f
    sh = np.float32(s)
    sl = np.float32(s - float(sh))
    if jnp.issubdtype(jnp.asarray(hi).dtype, jnp.complexfloating):
        rh, rl = _dd_mul(jnp.real(hi), jnp.real(lo),
                         jnp.float32(sh), jnp.float32(sl))
        ih, il = _dd_mul(jnp.imag(hi), jnp.imag(lo),
                         jnp.float32(sh), jnp.float32(sl))
        return lax.complex(rh, ih), lax.complex(rl, il)
    return _dd_mul(hi, lo, jnp.float32(sh), jnp.float32(sl))


def max_err_vs_f64(hi, lo, want: np.ndarray) -> float:
    """max |dd - want| / max |want| against a host float64 reference —
    the roundtrip/accuracy metric of the reference harnesses
    (``fftSpeed3d_c2c.cpp:85-91``) at the double tier."""
    got = dd_to_host(hi, lo)
    return float(np.max(np.abs(got - want)) / np.max(np.abs(want)))
