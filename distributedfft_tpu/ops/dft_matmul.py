"""Mixed-radix DFT by matrix multiplication — the MXU-native FFT executor.

TPU-first replacement for templateFFT's runtime-generated Stockham kernels
(``templateFFT/src/templateFFT.cpp:4699`` ``shaderGenFFT``; scheduler
``:3941-4100``). On a GPU the natural FFT engine is a hand-scheduled
shared-memory butterfly kernel; on a TPU the FLOPs live in the 128x128 MXU, so
the natural engine is the *four-step / Bailey decomposition* expressed as
batched matrix multiplies against small DFT matrices, with trace-time twiddle
LUTs (the reference precomputes its twiddle LUTs on the host in double
precision too, ``templateFFT.cpp:5063-5154``):

    n = n1 * n2, view x as A[j1, j2] (j = j1*n2 + j2)
    B[k1, j2] = DFT_n1 over j1         (matmul against the n1 x n1 DFT matrix)
    B       *= w_n^{k1 * j2}           (twiddle LUT, computed at trace time)
    C[k1, k2] = DFT_n2 over j2         (recurse)
    X[k2*n1 + k1] = C[k1, k2]          (transpose + reshape)

Factors at or below :data:`DIRECT_MAX` are computed as a single dense matmul;
everything is jit-traceable with static shapes, so XLA tiles the matmuls onto
the MXU. Prime lengths above the threshold fall back to the O(n^2) dense
matmul (the reference's radix set is 2..13, ``templateFFT.cpp:3956-3963``, so
composite sizes with small prime factors are the parity target; Bluestein is a
possible extension).

Like every executor in this framework the transform is unnormalized in the
forward direction and scales by 1/n on the inverse (numpy convention).
"""

from __future__ import annotations

import functools
import math

import jax.numpy as jnp
import numpy as np
from jax import lax

# Largest factor handled as a single dense DFT matmul. 128 matches the MXU
# systolic-array edge, so each stage's matmul has a contraction dim that tiles
# cleanly onto the hardware.
DIRECT_MAX = 128


@functools.lru_cache(maxsize=None)
def _dft_matrix_np(n: int, forward: bool) -> np.ndarray:
    """Dense n x n DFT matrix W[j, k] = exp(-+ 2*pi*i*j*k / n), float64
    precision at trace time (cast to the working dtype on use)."""
    sign = -2j if forward else 2j
    jk = np.outer(np.arange(n), np.arange(n))
    return np.exp(sign * np.pi * (jk % n) / n)


@functools.lru_cache(maxsize=None)
def _twiddle_np(n: int, n1: int, n2: int, forward: bool) -> np.ndarray:
    """Inter-stage twiddles w_n^{k1*j2} of shape [n1, n2] (cf. templateFFT's
    four-step LUT generation, ``templateFFT.cpp:5144-5153``)."""
    sign = -2j if forward else 2j
    k1j2 = np.outer(np.arange(n1), np.arange(n2))
    return np.exp(sign * np.pi * (k1j2 % n) / n)


def _best_split(n: int) -> tuple[int, int] | None:
    """Divisor pair (n1, n2), n1 <= n2, with n1 as close to sqrt(n) as
    possible while preferring both factors composite-small. Returns None for
    primes (no nontrivial divisor)."""
    best = None
    for d in range(int(math.isqrt(n)), 1, -1):
        if n % d == 0:
            best = (d, n // d)
            break
    return best


def _direct(x: jnp.ndarray, forward: bool) -> jnp.ndarray:
    """Dense DFT of the last axis: one batched matmul on the MXU."""
    n = x.shape[-1]
    w = jnp.asarray(_dft_matrix_np(n, forward), dtype=x.dtype)
    return jnp.einsum("...j,jk->...k", x, w, precision=lax.Precision.HIGHEST)


def _fft_last(x: jnp.ndarray, forward: bool) -> jnp.ndarray:
    """Unnormalized DFT along the last axis (both directions)."""
    n = x.shape[-1]
    if n == 1:
        return x
    split = None if n <= DIRECT_MAX else _best_split(n)
    if split is None:
        return _direct(x, forward)
    n1, n2 = split
    a = x.reshape(x.shape[:-1] + (n1, n2))
    # DFT_n1 along axis -2: swap to last, recurse, swap back.
    b = jnp.swapaxes(_fft_last(jnp.swapaxes(a, -1, -2), forward), -1, -2)
    tw = jnp.asarray(_twiddle_np(n, n1, n2, forward), dtype=x.dtype)
    b = b * tw
    c = _fft_last(b, forward)  # DFT_n2 along the last axis
    # c is indexed [..., k1, k2]; output index is k2*n1 + k1.
    return jnp.swapaxes(c, -1, -2).reshape(x.shape)


def fft_along_axis(x: jnp.ndarray, axis: int, forward: bool = True) -> jnp.ndarray:
    """C2C FFT along one axis via MXU matmuls. Forward unnormalized, inverse
    scaled by 1/n (numpy convention)."""
    if not jnp.issubdtype(x.dtype, jnp.complexfloating):
        wide = jnp.dtype(x.dtype).itemsize >= 8
        x = x.astype(jnp.complex128 if wide else jnp.complex64)
    n = x.shape[axis]
    moved = axis not in (-1, x.ndim - 1)
    if moved:
        x = jnp.moveaxis(x, axis, -1)
    y = _fft_last(x, forward)
    if not forward:
        y = y * jnp.asarray(1.0 / n, dtype=y.real.dtype)
    if moved:
        y = jnp.moveaxis(y, -1, axis)
    return y
