"""Mixed-radix DFT by matrix multiplication — the MXU-native FFT executor.

TPU-first replacement for templateFFT's runtime-generated Stockham kernels
(``templateFFT/src/templateFFT.cpp:4699`` ``shaderGenFFT``; scheduler
``:3941-4100``). On a GPU the natural FFT engine is a hand-scheduled
shared-memory butterfly kernel; on a TPU the FLOPs live in the 128x128 MXU, so
the natural engine is the *four-step / Bailey decomposition* expressed as
batched matrix multiplies against small DFT matrices, with trace-time twiddle
LUTs (the reference precomputes its twiddle LUTs on the host in double
precision too, ``templateFFT.cpp:5063-5154``):

    n = n1 * n2, view x as A[j1, j2] (j = j1*n2 + j2)
    B[k1, j2] = DFT_n1 over j1         (matmul against the n1 x n1 DFT matrix)
    B       *= w_n^{k1 * j2}           (twiddle LUT, computed at trace time)
    C[k1, k2] = DFT_n2 over j2         (recurse)
    X[k2*n1 + k1] = C[k1, k2]          (transpose + reshape)

Lengths at or below the backend-dependent :func:`direct_max` bound (128 on
CPU, 512 on TPU — the flagship extent in one MXU contraction per axis) are
computed as a single dense matmul; everything is jit-traceable with static
shapes, so XLA tiles the matmuls onto the MXU. Lengths above the bound with
no usable factorization — primes — use the O(n^2) dense matmul up to
max(:func:`direct_max`, :data:`BLUESTEIN_MIN`) (still MXU-friendly); larger
primes switch to Bluestein's chirp-z transform — exceeding the reference's
radix-2..13 coverage (``templateFFT.cpp:3956-3963``), which cannot handle
large primes at all.

Like every executor in this framework the transform is unnormalized in the
forward direction and scales by 1/n on the inverse (numpy convention).
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import math
import os

import jax.numpy as jnp
import numpy as np
from jax import lax

# Largest factor handled as a single dense DFT matmul. 128 matches the MXU
# systolic-array edge, so each stage's matmul has a contraction dim that tiles
# cleanly onto the hardware. On TPU the effective bound is larger — see
# :func:`direct_max`.
DIRECT_MAX = 128


def direct_max() -> int:
    """Trace-time dense-tier bound. The four-step split minimizes flops
    but pays ~6 materialized HBM passes per axis (transposes, packed-row
    regroups, twiddle stages) — on TPU that movement, not arithmetic,
    dominates (docs/MFU_ANALYSIS.md: 99 ms measured vs ~25 ms of MXU
    time at 512^3). A DENSE n-point DFT is ONE dot_general per axis —
    n=512 is a [rows, 512] @ [512, 512] contraction, perfectly
    MXU-shaped with no inter-stage traffic — so the TPU default covers
    the flagship extent: 512. CPU keeps 128 (movement is cheap there;
    the suite's f64 reference runs would pay the O(n^2) flops for
    nothing). ``DFFT_MM_DIRECT_MAX`` overrides for sweeps."""
    env = os.environ.get("DFFT_MM_DIRECT_MAX")
    if env:
        try:
            bound = int(env)
        except ValueError:
            raise ValueError(
                f"DFFT_MM_DIRECT_MAX={env!r} is not an integer") from None
        if bound < 2:
            raise ValueError(
                f"DFFT_MM_DIRECT_MAX={env!r}: bound must be >= 2 (a "
                f"sub-2 bound would silently disable the dense tier)")
        return bound
    import jax

    return 512 if jax.default_backend() == "tpu" else DIRECT_MAX


@functools.lru_cache(maxsize=None)
def _dft_matrix_np(n: int, forward: bool) -> np.ndarray:
    """Dense n x n DFT matrix W[j, k] = exp(-+ 2*pi*i*j*k / n), float64
    precision at trace time (cast to the working dtype on use)."""
    sign = -2j if forward else 2j
    jk = np.outer(np.arange(n), np.arange(n))
    return np.exp(sign * np.pi * (jk % n) / n)


@functools.lru_cache(maxsize=None)
def _twiddle_np(n: int, n1: int, n2: int, forward: bool) -> np.ndarray:
    """Inter-stage twiddles w_n^{k1*j2} of shape [n1, n2] (cf. templateFFT's
    four-step LUT generation, ``templateFFT.cpp:5144-5153``)."""
    sign = -2j if forward else 2j
    k1j2 = np.outer(np.arange(n1), np.arange(n2))
    return np.exp(sign * np.pi * (k1j2 % n) / n)


def _split_override(n: int) -> tuple[int, int] | None:
    """Per-length four-step split override from ``DFFT_MM_SPLIT``
    (e.g. ``"512=4x128,256=2x128"``) — the contraction-dim rebalance
    knob of the campaign's MXU-edge experiments (docs/MFU_ANALYSIS.md):
    the balanced split minimizes flops, but a lopsided split whose large
    factor sits at the 128 MXU edge can trade flops for utilization.
    Read at trace time, like DFFT_MM_PRECISION. Invalid entries raise
    (a silently-ignored typo would invalidate a whole sweep)."""
    spec = os.environ.get("DFFT_MM_SPLIT", "").strip()
    if not spec:
        return None
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            key, val = part.split("=")
            a, b = (int(v) for v in val.split("x"))
        except ValueError:
            raise ValueError(
                f"DFFT_MM_SPLIT entry {part!r} is not N=AxB") from None
        if int(key) <= min(DIRECT_MAX, direct_max()):
            # Lengths at or under the every-backend dense floor (128, or
            # a lowered DFFT_MM_DIRECT_MAX) are always transformed dense
            # — rejecting the key loudly beats an override that silently
            # invalidates a whole sweep. Keys ABOVE the floor are live
            # even when this backend's dense tier could cover them: an
            # explicit split forces the four-step (see _fft_last).
            raise ValueError(
                f"DFFT_MM_SPLIT {part!r}: length {key} is at or under "
                f"the always-dense floor "
                f"({min(DIRECT_MAX, direct_max())}); the split is "
                f"policy-blocked there, set DFFT_MM_DIRECT_MAX lower "
                f"to unblock it")
        if int(key) == n:
            if a * b != n or a < 2 or b < 2:
                raise ValueError(
                    f"DFFT_MM_SPLIT {part!r}: {a}x{b} != {n} or "
                    f"factor < 2")
            return (a, b)
    return None


def _best_split(n: int) -> tuple[int, int] | None:
    """Divisor pair (n1, n2), n1 <= n2, with n1 as close to sqrt(n) as
    possible. Returns None for primes (no nontrivial divisor).

    Delegates to the native runtime core (``dfft_balanced_split``,
    ``native/dfft_native.cpp`` — the per-axis split decision of the
    reference's FFTScheduler, ``templateFFT.cpp:3941-4100``), with its
    Python mirror as the toolchain-less fallback. ``DFFT_MM_SPLIT``
    overrides are consulted by the caller (``_fft_last``), the single
    owner of split precedence."""
    from .. import native

    return native.balanced_split(n, n)


# Plan-scoped precision/complex-mode overrides. The env knobs below are
# read at TRACE time, which made them process-global state: a warm_pool
# preplan and a concurrent tune="measure" tournament in one process would
# share whatever DFFT_MM_PRECISION happened to say when each plan first
# traced. A tiered executor label ("matmul:bf16" — see
# :func:`..executors.get_executor`) instead enters this scope around the
# base executor call, so the setting is baked into that plan's jaxpr at
# its own trace time and two tiers coexist in one process. ContextVars:
# concurrent serving/tuner threads each see only their own scope.
_PRECISION_OVERRIDE: contextvars.ContextVar[str | None] = (
    contextvars.ContextVar("dfft_mm_precision_override", default=None))
_COMPLEX_OVERRIDE: contextvars.ContextVar[str | None] = (
    contextvars.ContextVar("dfft_mm_complex_override", default=None))


@contextlib.contextmanager
def mm_scope(precision: str | None = None, complex_mode: str | None = None):
    """Scope a plan-level precision/complex-mode override over the DFT
    contractions traced inside it. ``precision`` is a lax tier name
    (``"default"|"high"|"highest"``), ``complex_mode``
    ``"native"|"gauss"``; ``None`` leaves that knob on its env default.
    Entered by the tiered-executor wrappers at trace time — the single
    mechanism that makes the ``DFFT_MM_*`` env knobs defaults instead of
    process-global state."""
    tokens = []
    if precision is not None:
        tokens.append((_PRECISION_OVERRIDE,
                       _PRECISION_OVERRIDE.set(precision)))
    if complex_mode is not None:
        tokens.append((_COMPLEX_OVERRIDE,
                       _COMPLEX_OVERRIDE.set(complex_mode)))
    try:
        yield
    finally:
        for var, tok in reversed(tokens):
            var.reset(tok)


def mm_precision() -> "lax.Precision":
    """MXU precision for every DFT contraction (matmul + Pallas engines).

    HIGHEST (f32-exact via multi-pass bf16) by default — the accuracy tier
    the c64 roundtrip gates assume. ``DFFT_MM_PRECISION=default|high|
    highest`` trades passes for throughput (up to ~3x MXU rate at reduced
    accuracy) — a measurable knob for the hardware tuning sweeps, in the
    spirit of the reference's per-backend accuracy/speed trade
    (``csv/batch_rocResult1D.csv`` records rocFFT's faster-but-inaccurate
    rows side by side). Read at trace time: set it before planning — or
    plan-scoped via :func:`mm_scope` (a ``PlanOptions.mm_precision`` /
    tiered executor label overrides the env for its own plan only)."""
    import os

    s = _PRECISION_OVERRIDE.get()
    if s is None:
        s = os.environ.get("DFFT_MM_PRECISION", "highest").strip().lower()
    table = {
        "default": lax.Precision.DEFAULT,
        "high": lax.Precision.HIGH,
        "highest": lax.Precision.HIGHEST,
    }
    try:
        return table[s]
    except KeyError:
        raise ValueError(
            f"DFFT_MM_PRECISION={s!r} is not a precision tier; "
            f"use one of {sorted(table)}"
        ) from None


@functools.lru_cache(maxsize=None)
def _blockdiag_dft_np(n: int, g: int, forward: bool) -> np.ndarray:
    """I_g (x) W_n — ``g`` independent n-point DFTs as ONE (g*n, g*n) matmul."""
    return np.kron(np.eye(g), _dft_matrix_np(n, forward))


def pack_factor(n: int, rows: int) -> int:
    """How many independent n-point DFTs to pack into one matmul.

    A lone n x n DFT matmul with n well under 128 runs the MXU at
    (n/128)^2 utilization — the systolic array pads both the contraction
    and output dims to 128. Packing g = 128/n transforms as a
    block-diagonal (g*n, g*n) matmul multiplies the flops by g but lifts
    utilization by g^2: identical sums (the off-block zeros contribute
    exact +0 terms), ~g-fold faster on hardware. ``rows`` (the flattened
    batch extent) must stay divisible by g; the search walks every g down
    from 128//n so a non-power-of-two cap (e.g. n=10 -> 12) still finds
    the largest divisor of ``rows`` rather than bailing to 1."""
    for g in range(max(1, 128 // n), 1, -1):
        if rows % g == 0:
            return g
    return 1


def complex_mode() -> str:
    """How the dense tier multiplies by the complex DFT matrix.

    ``native`` (default): one complex einsum — XLA owns the
    complex-to-real decomposition (typically 4 real matmuls).
    ``gauss``: explicit 3-real-matmul Gauss/Karatsuba split,
    m1=(xr+xi)@Wr, m2=xr@(Wi-Wr), m3=xi@(Wi+Wr), y=(m1-m3)+i(m1+m2) —
    the combined matrices are trace-time constants, so this trades one
    MXU matmul (~25% of the dense tier's compute) for two fused
    elementwise passes, and pins the bf16 pass count to exactly
    3 x mm_precision() passes instead of XLA's decomposition choice.
    A hardware-sweep knob (campaign-swept at 512^3), like
    DFFT_MM_PRECISION. Read at trace time; a :func:`mm_scope` override
    (the ``:gauss`` executor suffix / ``PlanOptions.mm_complex``) wins
    over the env for its own plan."""
    m = _COMPLEX_OVERRIDE.get()
    if m is None:
        m = os.environ.get("DFFT_MM_COMPLEX", "native").strip().lower()
    if m not in ("native", "gauss"):
        raise ValueError(
            f"DFFT_MM_COMPLEX={m!r} is not a complex-product mode; "
            f"use 'native' or 'gauss'")
    return m


def _gauss_matmul(x: jnp.ndarray, w_np: np.ndarray,
                  pat: str) -> jnp.ndarray:
    """y = einsum(pat, x, W) for complex x and constant complex W via the
    3-real-matmul Gauss split (see :func:`complex_mode`)."""
    rdt = x.real.dtype
    xr, xi = jnp.real(x), jnp.imag(x)
    wr = jnp.asarray(np.real(w_np), dtype=rdt)
    d1 = jnp.asarray(np.imag(w_np) - np.real(w_np), dtype=rdt)
    d2 = jnp.asarray(np.imag(w_np) + np.real(w_np), dtype=rdt)
    p = mm_precision()
    m1 = jnp.einsum(pat, xr + xi, wr, precision=p)
    m2 = jnp.einsum(pat, xr, d1, precision=p)
    m3 = jnp.einsum(pat, xi, d2, precision=p)
    return lax.complex(m1 - m3, m1 + m2)


def _direct(x: jnp.ndarray, forward: bool) -> jnp.ndarray:
    """Dense DFT of the last axis: one batched matmul on the MXU; factors
    under the 128 MXU edge are block-diagonal-packed to full width."""
    n = x.shape[-1]
    rows = math.prod(x.shape[:-1]) if x.ndim > 1 else 1
    g = pack_factor(n, rows)
    if g > 1:
        w_np = _blockdiag_dft_np(n, g, forward)
        x2 = x.reshape(rows // g, g * n)
        if complex_mode() == "gauss":
            y = _gauss_matmul(x2, w_np, "...j,jk->...k")
        else:
            y = jnp.einsum("...j,jk->...k", x2,
                           jnp.asarray(w_np, dtype=x.dtype),
                           precision=mm_precision())
        return y.reshape(x.shape)
    w_np = _dft_matrix_np(n, forward)
    if complex_mode() == "gauss":
        return _gauss_matmul(x, w_np, "...j,jk->...k")
    return jnp.einsum("...j,jk->...k", x,
                      jnp.asarray(w_np, dtype=x.dtype),
                      precision=mm_precision())


# Prime lengths above this use Bluestein's chirp-z algorithm instead of the
# O(n^2) dense matmul. Kept well above DIRECT_MAX: the dense matmul IS the
# fast path on the MXU for moderate n.
BLUESTEIN_MIN = 512


@functools.lru_cache(maxsize=None)
def _bluestein_tables(n: int, m: int, forward: bool):
    """Host-precomputed chirp w[j] = exp(-+ i pi j^2 / n) and the length-m DFT
    of the symmetric chirp kernel b (exact at trace time, like every twiddle
    LUT here). j^2 is reduced mod 2n to keep the argument small."""
    j = np.arange(n)
    sign = -1j if forward else 1j
    w = np.exp(sign * np.pi * ((j * j) % (2 * n)) / n)
    b = np.zeros(m, dtype=np.complex128)
    b[:n] = np.conj(w)
    b[m - n + 1:] = np.conj(w[1:][::-1])
    return w, np.fft.fft(b)


def _bluestein(x: jnp.ndarray, forward: bool) -> jnp.ndarray:
    """Bluestein/chirp-z DFT of an arbitrary (here: large-prime) length as a
    circular convolution at a power-of-two length — the capability templateFFT
    lacks entirely (its radix set stops at 13, ``templateFFT.cpp:3956-3963``;
    the batch harness only sweeps smooth sizes, ``runTest1D_opt.sh``)."""
    n = x.shape[-1]
    m = 1 << (2 * n - 1).bit_length()
    w_np, B_np = _bluestein_tables(n, m, forward)
    w = jnp.asarray(w_np, dtype=x.dtype)
    B = jnp.asarray(B_np, dtype=x.dtype)
    a = x * w
    pad = [(0, 0)] * (x.ndim - 1) + [(0, m - n)]
    A = _fft_last(jnp.pad(a, pad), True)
    c = _fft_last(A * B, False)  # unnormalized inverse
    return c[..., :n] * w * jnp.asarray(1.0 / m, dtype=x.real.dtype)


def _fft_last(x: jnp.ndarray, forward: bool) -> jnp.ndarray:
    """Unnormalized DFT along the last axis (both directions)."""
    n = x.shape[-1]
    if n == 1:
        return x
    # An explicit DFFT_MM_SPLIT for this length forces the four-step
    # (sweep intent wins); otherwise the dense tier takes everything up
    # to the backend's direct_max() in one MXU contraction. This is the
    # ONLY consult site — _best_split is pure balanced-split.
    split = _split_override(n)
    if split is None and n > direct_max():
        split = _best_split(n)
    if split is None:
        # Chirp-z only above BOTH bounds: primes in (direct_max,
        # BLUESTEIN_MIN] take the O(n^2) dense matmul (still MXU-friendly),
        # and a raised DFFT_MM_DIRECT_MAX must mean dense on every axis —
        # not dense on middle axes but Bluestein on the last.
        if n > max(direct_max(), BLUESTEIN_MIN):
            return _bluestein(x, forward)
        return _direct(x, forward)
    n1, n2 = split
    a = x.reshape(x.shape[:-1] + (n1, n2))
    # DFT_n1 along axis -2: swap to last, recurse, swap back.
    b = jnp.swapaxes(_fft_last(jnp.swapaxes(a, -1, -2), forward), -1, -2)
    tw = jnp.asarray(_twiddle_np(n, n1, n2, forward), dtype=x.dtype)
    b = b * tw
    c = _fft_last(b, forward)  # DFT_n2 along the last axis
    # c is indexed [..., k1, k2]; output index is k2*n1 + k1.
    return jnp.swapaxes(c, -1, -2).reshape(x.shape)


def _direct_axis(x: jnp.ndarray, axis: int, forward: bool) -> jnp.ndarray:
    """Dense DFT contracting ``axis`` IN PLACE — one dot_general, no
    moveaxis round trip through HBM (XLA folds the operand/result
    layouts into the contraction). Callers gate on the dense tier and
    on pack_factor == 1 (packed sub-128 factors need the row-regroup
    path)."""
    n = x.shape[axis]
    w_np = _dft_matrix_np(n, forward)
    subs = "abcdefgh"[: x.ndim]
    j = subs[axis]
    out = subs.replace(j, "z")
    pat = f"{subs},{j}z->{out}"
    if complex_mode() == "gauss":
        return _gauss_matmul(x, w_np, pat)
    return jnp.einsum(pat, x, jnp.asarray(w_np, dtype=x.dtype),
                      precision=mm_precision())


def fft_along_axis(x: jnp.ndarray, axis: int, forward: bool = True) -> jnp.ndarray:
    """C2C FFT along one axis via MXU matmuls. Forward unnormalized, inverse
    scaled by 1/n (numpy convention)."""
    if not jnp.issubdtype(x.dtype, jnp.complexfloating):
        wide = jnp.dtype(x.dtype).itemsize >= 8
        x = x.astype(jnp.complex128 if wide else jnp.complex64)
    n = x.shape[axis]
    ax = axis % x.ndim
    if (1 < n <= direct_max() and _split_override(n) is None
            and ax != x.ndim - 1 and x.ndim <= 8
            and pack_factor(n, math.prod(x.shape) // n) == 1):
        # Dense middle/leading-axis transform without the two moveaxis
        # materializations (the flagship 512^3 path on TPU: three such
        # contractions IS the whole transform).
        y = _direct_axis(x, ax, forward)
        if not forward:
            y = y * jnp.asarray(1.0 / n, dtype=y.real.dtype)
        return y
    moved = axis not in (-1, x.ndim - 1)
    if moved:
        x = jnp.moveaxis(x, axis, -1)
    y = _fft_last(x, forward)
    if not forward:
        y = y * jnp.asarray(1.0 / n, dtype=y.real.dtype)
    if moved:
        y = jnp.moveaxis(y, -1, axis)
    return y
