"""Pluggable local FFT executors.

The reference keeps several interchangeable compute backends side by side —
``setFFTPlans`` builds hipfft, rocfft, *and* templateFFT plans and picks one
(``3dmpifft_opt/include/fft_mpi_3d_api.cpp:318-429``); heFFTe abstracts the
same idea as the ``one_dim_backend`` trait over {stock,fftw,mkl,cufft,rocfft,
onemkl} (``heffte/heffteBenchmark/include/heffte_common.h:97-127,275``).

The TPU-native equivalent is a registry of *jit-traceable callables*: each
executor maps ``(x, axes, forward) -> y`` with pure functional semantics, so
any of them can be dropped into the distributed pipeline under ``jit`` /
``shard_map``. Backends:

- ``"xla"``    — ``jnp.fft``; XLA's built-in FFT lowering (default).
- ``"matmul"`` — mixed-radix DFT-by-matrix-multiply on the MXU
  (:mod:`distributedfft_tpu.ops.dft_matmul`), the TPU-idiomatic analog of
  templateFFT's runtime-generated Stockham kernels.
- ``"pallas"`` — the fused four-step Pallas kernel
  (:mod:`distributedfft_tpu.ops.pallas_fft`): whole-axis transform staged
  through VMEM in one kernel, one HBM read/write per axis; falls back to
  ``"matmul"`` for ineligible lengths/dtypes.
"""

from __future__ import annotations

import enum
import functools
import math
from typing import Callable, Sequence

import jax.numpy as jnp

Array = jnp.ndarray
ExecutorFn = Callable[..., Array]  # (x, axes, forward=True) -> y

_REGISTRY: dict[str, ExecutorFn] = {}

# --- precision-tiered executor labels -----------------------------------
#
# "matmul:bf16" / "matmul:f32" / "matmul:highest" (and the ":gauss"
# complex-product mode) are DISTINCT executor names: the suffix scopes the
# MXU contraction precision over the base executor's trace
# (:func:`.dft_matmul.mm_scope`), so the accuracy tier is per-plan state
# — plan-cache keyed, wisdom-recorded, two tiers coexisting in one
# process — instead of the process-global trace-time DFFT_MM_PRECISION /
# DFFT_MM_COMPLEX env read (which stays as the *default* for bare names).

#: Accuracy tiers of the matmul-family executors, in descending-error
#: order: ``bf16`` = one bf16 MXU pass (lax DEFAULT), ``f32`` = the
#: 3-pass bf16 refinement (HIGH), ``highest`` = f32-exact multi-pass
#: (HIGHEST — the bare executor's default tier).
MM_TIERS = ("bf16", "f32", "highest")

#: Tier label -> lax precision name (the :func:`.dft_matmul.mm_precision`
#: table key the scope pins).
TIER_PRECISION = {"bf16": "default", "f32": "high", "highest": "highest"}

#: Accepted lax-name spellings of the tiers (the grammar bench.py's
#: executor menus used before the tiers were plan-scoped:
#: ``matmul:high`` == ``matmul:f32``). Normalized to the canonical MXU
#: names by :func:`split_executor`.
TIER_ALIASES = {"default": "bf16", "high": "f32"}

#: Base executors whose contractions consult the DFFT_MM_* knobs — the
#: only bases a tier suffix is meaningful for (speed3d's
#: ``_executor_label`` gates on the same family).
MM_EXECUTOR_BASES = ("matmul", "pallas")

#: Complex-product modes accepted as a suffix (``native`` is the bare
#: default; only ``gauss`` changes the trace).
MM_COMPLEX_MODES = ("native", "gauss")


#: The stage-fusion flag token: ``pallas:fuse`` asks the stage-graph
#: compiler's fusion pass (``stagegraph.plan_fusion``) to fuse the wire
#: codec's encode/decode into the adjacent stage computes — Pallas
#: mega-kernels where the shapes are eligible, the pure-JAX codec mirror
#: otherwise. Orthogonal to the precision tiers: the flag never changes
#: the local executor callable (``get_executor("pallas:fuse")`` is the
#: plain pallas executor), it is plan-level state the compiler consumes.
FUSE_SUFFIX = "fuse"

#: Bases the fuse flag composes with (the fused kernels are Pallas
#: specializations; other bases have no fused engine to dispatch to).
FUSE_BASES = ("pallas",)


def split_fuse(name: str) -> tuple[str, bool]:
    """Strip the ``:fuse`` flag off an executor label: ``"pallas:fuse"
    -> ("pallas", True)``, ``"pallas:bf16:fuse" -> ("pallas:bf16",
    True)``; unfused labels return ``(name, False)``. Validates the flag
    only rides a :data:`FUSE_BASES` base and appears at most once. Pure
    label algebra — the fusion pass and the planner normalization share
    this one parse."""
    if ":" not in name:
        return name, False
    base, *mods = name.split(":")
    hits = mods.count(FUSE_SUFFIX)
    if hits == 0:
        return name, False
    if hits > 1:
        raise ValueError(f"executor {name!r} repeats the fuse flag")
    if base not in FUSE_BASES:
        raise ValueError(
            f"the :fuse flag applies to {FUSE_BASES} executors, "
            f"got {name!r}")
    rest = [m for m in mods if m != FUSE_SUFFIX]
    return ":".join([base] + rest), True


def fused_name(name: str, fuse: bool | None = None) -> str:
    """Compose/normalize the fuse flag onto a label (the fuse analog of
    :func:`tiered_name`). ``fuse=None`` keeps the label's own flag;
    ``True`` adds it (idempotent; validates the base); ``False`` with a
    label that already pins ``:fuse`` raises — a plan asking for
    ``executor="pallas:fuse", fuse=False`` is a bug, not a precedence
    question. The canonical composed form carries ``:fuse`` last:
    ``pallas:bf16:fuse``."""
    bare, have = split_fuse(name)
    if fuse is None:
        fuse = have
    elif have and not fuse:
        raise ValueError(
            f"executor {name!r} already pins the fuse flag; "
            f"conflicting request fuse=False")
    if not fuse:
        return bare
    if bare.split(":", 1)[0] not in FUSE_BASES:
        raise ValueError(
            f"the fuse tier applies to {FUSE_BASES} executors, "
            f"got {name!r}")
    return bare + f":{FUSE_SUFFIX}"


def split_executor(name: str) -> tuple[str, str | None, str | None]:
    """Parse a (possibly tiered) executor label into
    ``(base, precision_tier, complex_mode)`` — e.g. ``"matmul:bf16:gauss"
    -> ("matmul", "bf16", "gauss")``; bare names return ``(name, None,
    None)``. Lax-name tier spellings normalize to the canonical MXU
    names (``matmul:high -> ("matmul", "f32", None)`` — the bench menu
    grammar). Validates suffix vocabulary and that the base consults the
    precision knobs at all; does NOT require the base to be registered
    (pure label algebra, shared with the tuner's candidate space)."""
    name, _ = split_fuse(name)  # the fuse flag is orthogonal label state
    if ":" not in name:
        return name, None, None
    base, *mods = name.split(":")
    tier: str | None = None
    cmode: str | None = None
    for m in mods:
        if m in MM_TIERS or m in TIER_ALIASES:
            if tier is not None:
                raise ValueError(
                    f"executor {name!r} names two precision tiers")
            tier = TIER_ALIASES.get(m, m)
        elif m in MM_COMPLEX_MODES:
            if cmode is not None:
                raise ValueError(
                    f"executor {name!r} repeats the complex mode")
            cmode = m
        else:
            raise ValueError(
                f"unknown executor suffix {m!r} in {name!r}; tiers: "
                f"{MM_TIERS} (or lax spellings {sorted(TIER_ALIASES)}), "
                f"complex modes: {MM_COMPLEX_MODES}")
    if not base.startswith(MM_EXECUTOR_BASES):
        raise ValueError(
            f"executor {base!r} does not consult the matmul precision "
            f"knobs; tier suffixes apply to {MM_EXECUTOR_BASES}")
    return base, tier, cmode


def tiered_name(base: str, precision: str | None = None,
                complex_mode: str | None = None) -> str:
    """Compose the canonical tiered executor label from a base name and
    plan-level tier choices (``PlanOptions.mm_precision`` /
    ``mm_complex``). Idempotent: a base that already carries a suffix
    merges with the requested one — and conflicts raise (a plan asking
    for ``executor="matmul:bf16", mm_precision="highest"`` is a bug, not
    a precedence question). ``None`` tiers leave the bare name (the env
    defaults keep governing that plan's trace)."""
    base, have_fuse = split_fuse(base)
    b, have_tier, have_cmode = (split_executor(base) if ":" in base
                                else (base, None, None))
    if precision is not None:
        precision = TIER_ALIASES.get(precision, precision)
    for what, have, want in (("precision tier", have_tier, precision),
                             ("complex mode", have_cmode, complex_mode)):
        if have is not None and want is not None and have != want:
            raise ValueError(
                f"executor {base!r} already pins {what} {have!r}; "
                f"conflicting request {want!r}")
    tier = precision if precision is not None else have_tier
    cmode = complex_mode if complex_mode is not None else have_cmode
    if tier is not None and tier not in MM_TIERS:
        raise ValueError(
            f"mm_precision must be one of {MM_TIERS} or None, got {tier!r}")
    if cmode is not None and cmode not in MM_COMPLEX_MODES:
        raise ValueError(
            f"mm_complex must be one of {MM_COMPLEX_MODES} or None, "
            f"got {cmode!r}")
    if cmode == "native":
        cmode = None  # the bare default — not a distinct label
    if tier is None and cmode is None:
        return fused_name(b, have_fuse) if have_fuse else b
    name = b + (f":{tier}" if tier else "") + (f":{cmode}" if cmode else "")
    split_executor(name)  # one validation path for every composed label
    return fused_name(name, have_fuse) if have_fuse else name


#: Executor bases that lower through XLA's FFT ops — the family the
#: fft-thunk guard below may substitute away from.
THUNK_BASES = ("xla", "xla_minor")


def thunk_guard_substitute(executor, *, decomposition: str, forward: bool,
                           uneven: bool, starved: bool = False):
    """The XLA:CPU fft-thunk retirement predicate, shared by the planners
    (``api._thunk_guard_executor``) and the staged pipeline builders:
    with ``DFFT_THUNK_GUARD`` armed (an executor name, normally
    ``matmul``), an XLA-family executor on the CPU backend building one
    of the known-poisoned chain classes —

    - an *inverse pencil chain with uneven (ceil-padded) shards*, whose
      irfft/ifft feeds the fft thunk a non-major layout, or
    - a *starved minor-axis slab chain* (input slabs on the minor axis
      with its extent smaller than the part count — zero-extent shards;
      the caller passes this condition as ``starved``), whose t0 FFT
      over the non-minor axes gets the same non-major layout

    — both tripping the ``fft_thunk.cc:69`` RET_CHECK, an INTERNAL error
    that permanently poisons the process's sharded dispatch stream — is
    replaced by the substitute, which never touches the FFT thunk (every
    matmul stage is a dot_general). Anything outside those classes, any
    non-string executor (the dd tier's callables), and every call with
    the knob unset (the default) returns ``executor`` untouched."""
    import os

    guard = os.environ.get("DFFT_THUNK_GUARD", "").strip()
    if not guard or guard in ("0", "none"):
        return executor
    if not isinstance(executor, str):
        return executor
    if executor.split(":", 1)[0] not in THUNK_BASES:
        return executor
    poisoned = ((decomposition == "pencil" and not forward and uneven)
                or (decomposition == "slab" and starved))
    if not poisoned:
        return executor
    import jax

    if jax.default_backend() != "cpu":
        return executor
    return guard


#: Tiers below the exact default — the ones that cost accuracy and must
#: be admitted against a plan's ``max_roundtrip_err`` budget
#: (``highest`` IS the bare default's tier: exact by the suite's
#: convention, like the exact wire).
REDUCED_TIERS = ("bf16", "f32")

_EXEC_ERR_CACHE: dict = {}


def executor_roundtrip_error(name: str, dtype, n: int = 256, *,
                             sample=None) -> float:
    """Measured relative round-trip error of one forward+inverse DFT
    pass of a *reduced-precision* tiered executor at ``dtype`` (``max
    |ifft(fft(x)) - x| / max |x|`` over a seeded standard-normal block)
    — the precision analog of
    :func:`..parallel.exchange.wire_roundtrip_error`, and the number the
    tuner's error-budget filter admits a ``matmul:bf16`` candidate
    against. Deterministic (fixed seed) and cached per (label, dtype,
    n), so per-candidate pruning never re-measures. 0.0 for bare labels
    and exact tiers (``highest``/``gauss``) — the accuracy baseline the
    budget is declared relative to. Measured on the RUNNING backend: on
    CPU every lax precision collapses to the native f64/f32 kernels (the
    tiers genuinely cost nothing there); on TPU the bf16 tier's MXU
    pass shows its real ~1e-2/1e-3 cost.

    ``sample`` (an ``(8, n)``-reshapeable block) measures on
    caller-supplied data instead of the seeded Gaussian, cached by
    content digest — the wire-side kwarg's precision analog."""
    if ":" not in name:
        return 0.0
    _, tier, _ = split_executor(name)
    if tier not in REDUCED_TIERS:
        return 0.0
    import hashlib

    import numpy as _np

    if sample is not None:
        x = _np.asarray(sample, dtype=_np.dtype(dtype)).reshape(8, -1)
        digest = hashlib.sha256(x.tobytes()).hexdigest()[:16]
        key = (name, str(_np.dtype(dtype)), x.shape[1], digest)
    else:
        x = None
        key = (name, str(_np.dtype(dtype)), int(n))
    hit = _EXEC_ERR_CACHE.get(key)
    if hit is not None:
        return hit
    if x is None:
        rng = _np.random.default_rng(0)
        x = (rng.standard_normal((8, n))
             + 1j * rng.standard_normal((8, n))).astype(_np.dtype(dtype))
    fn = get_executor(name)
    y = _np.asarray(fn(fn(jnp.asarray(x), (1,), True), (1,), False))
    err = float(_np.max(_np.abs(y - x)) / _np.max(_np.abs(x)))
    _EXEC_ERR_CACHE[key] = err
    return err


def _scoped(fn: Callable, tier: str | None, cmode: str | None) -> Callable:
    """Wrap an executor-family callable so its trace runs under the
    tier's :func:`.dft_matmul.mm_scope` — the point where a tiered label
    becomes baked-in jaxpr precision instead of an env read."""
    from . import dft_matmul

    prec = TIER_PRECISION[tier] if tier is not None else None

    @functools.wraps(fn)
    def scoped(*args, **kw):
        with dft_matmul.mm_scope(precision=prec, complex_mode=cmode):
            return fn(*args, **kw)

    return scoped


class Scale(enum.Enum):
    """Result scaling, mirroring heFFTe's ``scale`` enum none/full/symmetric
    (``heffte_fft3d.h:84-91``) and the roc backend's explicit 1/N
    normalization kernel (``3dmpifft_roc/include/kernel_func.cpp``
    ``scale_element``)."""

    NONE = "none"
    FULL = "full"
    SYMMETRIC = "symmetric"


def scale_factor(scale: Scale, world_size: int) -> float:
    if scale == Scale.NONE:
        return 1.0
    if scale == Scale.FULL:
        return 1.0 / world_size
    return 1.0 / math.sqrt(world_size)


def apply_scale(x: Array, scale: Scale, world_size: int) -> Array:
    s = scale_factor(scale, world_size)
    return x if s == 1.0 else x * jnp.asarray(s, dtype=x.real.dtype)


def register_executor(name: str, fn: ExecutorFn) -> None:
    _REGISTRY[name] = fn


def get_executor(name: str) -> ExecutorFn:
    if ":" in name:
        base, tier, cmode = split_executor(name)
        return _scoped(get_executor(base), tier, cmode)
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown executor {name!r}; available: {sorted(_REGISTRY)}"
        ) from None


def available_executors() -> list[str]:
    return sorted(_REGISTRY)


def _xla_executor(x: Array, axes: Sequence[int], forward: bool = True) -> Array:
    """XLA built-in FFT over ``axes`` (complex-to-complex, unnormalized
    forward / 1/N inverse, matching numpy conventions)."""
    axes = tuple(axes)
    if forward:
        return jnp.fft.fftn(x, axes=axes)
    return jnp.fft.ifftn(x, axes=axes)


register_executor("xla", _xla_executor)


def _matmul_executor(x: Array, axes: Sequence[int], forward: bool = True) -> Array:
    from . import dft_matmul

    for ax in tuple(axes):
        x = dft_matmul.fft_along_axis(x, ax, forward=forward)
    return x


register_executor("matmul", _matmul_executor)


def _xla_minor_executor(x: Array, axes: Sequence[int],
                        forward: bool = True) -> Array:
    """XLA FFT with the transformed axis explicitly rotated to the minor
    (lane) dimension first — a layout experiment for the executor
    tournament: TPU vector lanes run over the minor-most dim, and a
    leading-axis FFT otherwise leaves the layout choice to XLA's internal
    fft expansion. Mathematically identical to ``xla``; only the
    transpose placement differs (XLA fuses adjacent transposes, so the
    cost model is decided by the compiler, measured by the tournament —
    the role of the reference's side-by-side backend plans,
    ``fft_mpi_3d_api.cpp:318-429``)."""
    fft = jnp.fft.fft if forward else jnp.fft.ifft
    for ax in tuple(axes):
        if ax == x.ndim - 1 or ax == -1:
            x = fft(x, axis=-1)
        else:
            x = jnp.moveaxis(fft(jnp.moveaxis(x, ax, -1), axis=-1), -1, ax)
    return x


register_executor("xla_minor", _xla_minor_executor)


# --- real <-> complex transforms (the heFFTe r2c/c2r executor surface,
# ``heffte_backend_rocm.h:567`` ``rocfft_executor_r2c``; geometry shrink
# ``box3d::r2c``, ``heffte_geometry.h:94``). Each executor may register its
# own pair; unregistered executors fall back to the XLA implementation.

_R2C_REGISTRY: dict[str, Callable] = {}
_C2R_REGISTRY: dict[str, Callable] = {}


def register_real_executor(name: str, r2c: Callable, c2r: Callable) -> None:
    _R2C_REGISTRY[name] = r2c
    _C2R_REGISTRY[name] = c2r


def slice_r2c(x: Array, axis: int) -> Array:
    """r2c via full complex FFT + slice — no native RFFT HLO. Twice the
    flops of a native rfft but immune to backend RFFT bugs."""
    import jax.lax as lax

    n = x.shape[axis]
    y = jnp.fft.fft(x.astype(_ctype_for(x.dtype)), axis=axis)
    return lax.slice_in_dim(y, 0, n // 2 + 1, axis=axis)


def mirror_c2r(y: Array, n: int, axis: int) -> Array:
    """c2r via Hermitian mirror + full complex inverse FFT — no native
    IRFFT HLO. The index algebra lives in
    :func:`.ddfft.mirror_half_spectrum` (one home, shared with the dd
    tier and the odd-n executor branches); exact for Hermitian input,
    twice the flops of a native irfft."""
    from .ddfft import mirror_half_spectrum

    return jnp.real(jnp.fft.ifft(mirror_half_spectrum(y, n, axis=axis),
                                 axis=axis))


def _ctype_for(rdtype):
    return (jnp.complex128
            if jnp.dtype(rdtype) == jnp.float64 else jnp.complex64)


def _xla_real_mode() -> str:
    """How the xla executor runs real transforms: ``native`` (RFFT/IRFFT
    HLOs) or ``safe`` (fft+slice / mirror+ifft). ``auto`` (default)
    resolves per backend — the round-5 hardware campaign measured the
    native path failing its roundtrip gate on the TPU backend
    (csv/speed3d_tpu1.csv: xla r2c 3.4e-01 at 256^3 vs 3.6e-07 for the
    same config on CPU; benchmarks/diag_r2c.py is the per-primitive
    bisection), so auto = safe on TPU, native elsewhere.
    ``DFFT_XLA_REAL=native|safe`` overrides."""
    import os

    mode = os.environ.get("DFFT_XLA_REAL", "auto")
    if mode in ("native", "safe"):
        return mode
    import jax

    return "safe" if jax.default_backend() == "tpu" else "native"


def _xla_r2c(x: Array, axis: int) -> Array:
    """Real-to-complex DFT along ``axis``: output extent n//2+1,
    unnormalized."""
    if _xla_real_mode() == "safe":
        return slice_r2c(x, axis)
    return jnp.fft.rfft(x, axis=axis)


def _xla_c2r(y: Array, n: int, axis: int) -> Array:
    """Complex-to-real inverse DFT along ``axis`` back to true extent ``n``;
    scaled by 1/n (numpy convention)."""
    if _xla_real_mode() == "safe":
        return mirror_c2r(y, n, axis)
    return jnp.fft.irfft(y, n=n, axis=axis)


register_real_executor("xla", _xla_r2c, _xla_c2r)


def _matmul_r2c(x: Array, axis: int) -> Array:
    from . import dft_matmul
    from .realfft import r2c_via_half_complex

    n = x.shape[axis]
    if n % 2 == 0 and n > 2 and not jnp.issubdtype(
            jnp.dtype(x.dtype), jnp.complexfloating):
        # Half-length packed transform: half the flops of the promote-and-
        # slice path (the native-r2c discipline of rocfft_executor_r2c,
        # heffte_backend_rocm.h:567).
        return r2c_via_half_complex(x, axis, dft_matmul.fft_along_axis)
    y = dft_matmul.fft_along_axis(x, axis, forward=True)
    import jax.lax as lax

    return lax.slice_in_dim(y, 0, n // 2 + 1, axis=axis)


def _matmul_c2r(y: Array, n: int, axis: int) -> Array:
    from . import dft_matmul
    import jax.lax as lax

    from .realfft import c2r_via_half_complex

    if n % 2 == 0 and n > 2:
        return c2r_via_half_complex(y, n, axis, dft_matmul.fft_along_axis)
    # Odd n: rebuild the full hermitian spectrum from the non-redundant
    # half, then a plain complex inverse; imaginary residue is dropped.
    from .ddfft import mirror_half_spectrum

    full = mirror_half_spectrum(y, n, axis=axis)
    x = dft_matmul.fft_along_axis(full, axis, forward=False)
    return jnp.real(x)


register_real_executor("matmul", _matmul_r2c, _matmul_c2r)


def _pallas_executor(x: Array, axes: Sequence[int], forward: bool = True) -> Array:
    from . import pallas_fft

    axes = tuple(axes)
    # Fuse a trailing 2D plane into one kernel launch (the templateFFT
    # 2D-app role for the t0 stage): both axes transform through VMEM with
    # one HBM read/write instead of two of each.
    if (len(axes) >= 2 and jnp.dtype(x.dtype) == jnp.complex64
            and x.size > 0
            and {axes[-2] % x.ndim, axes[-1] % x.ndim}
            == {x.ndim - 2, x.ndim - 1}):
        if pallas_fft.eligible2d(x.shape[-2], x.shape[-1]):
            x = pallas_fft.fft2_last(x, forward=forward)
            axes = axes[:-2]
        else:
            pallas_fft.record_fallback(axes[-1], "plane2d")
    for ax in axes:
        x = pallas_fft.fft_along_axis(x, ax, forward=forward)
    return x


register_executor("pallas", _pallas_executor)


def _pallas_r2c(x: Array, axis: int) -> Array:
    import jax.lax as lax

    from . import pallas_fft
    from .realfft import r2c_via_half_complex

    n = x.shape[axis]
    if n % 2 == 0 and n > 2 and not jnp.issubdtype(
            jnp.dtype(x.dtype), jnp.complexfloating):
        # Half-length packed transform (see _matmul_r2c). f32 input packs
        # to complex64 and runs the fused kernel; f64 packs to complex128,
        # which the kernel's dtype gate routes to the matmul fallback —
        # still the packed half-length work, just not the fused engine.
        return r2c_via_half_complex(x, axis, pallas_fft.fft_along_axis)
    # Odd n: promote real input up front — the kernel's dtype gate only
    # admits complex64, so a float32 operand would silently take the
    # fallback.
    if not jnp.issubdtype(x.dtype, jnp.complexfloating):
        wide = jnp.dtype(x.dtype).itemsize >= 8
        x = x.astype(jnp.complex128 if wide else jnp.complex64)
    y = pallas_fft.fft_along_axis(x, axis, forward=True)
    return lax.slice_in_dim(y, 0, n // 2 + 1, axis=axis)


def _pallas_c2r(y: Array, n: int, axis: int) -> Array:
    import jax.lax as lax

    from . import pallas_fft
    from .realfft import c2r_via_half_complex

    if n % 2 == 0 and n > 2:
        return c2r_via_half_complex(y, n, axis, pallas_fft.fft_along_axis)
    from .ddfft import mirror_half_spectrum

    full = mirror_half_spectrum(y, n, axis=axis)
    return jnp.real(pallas_fft.fft_along_axis(full, axis, forward=False))


register_real_executor("pallas", _pallas_r2c, _pallas_c2r)


def get_r2c(name: str) -> Callable:
    if ":" in name:
        base, tier, cmode = split_executor(name)
        return _scoped(get_r2c(base), tier, cmode)
    return _R2C_REGISTRY.get(name, _xla_r2c)


def get_c2r(name: str) -> Callable:
    if ":" in name:
        base, tier, cmode = split_executor(name)
        return _scoped(get_c2r(base), tier, cmode)
    return _C2R_REGISTRY.get(name, _xla_c2r)
