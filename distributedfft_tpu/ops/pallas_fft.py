"""Fused four-step FFT as a Pallas TPU kernel — the templateFFT analog.

The reference's single-GPU engine is a runtime kernel generator that stages a
whole 1D/2D FFT through shared memory in one launch (``shaderGenFFT``,
``templateFFT/src/templateFFT.cpp:4699``; the scheduler splits an axis into
shared-memory-sized passes, ``:3941-4100``). The TPU-native equivalent is NOT
a butterfly kernel — TPU FLOPs live in the 128x128 MXU, not in a scalar/vector
butterfly network — but the *fusion* idea carries over: this module stages the
entire four-step decomposition of one axis

    n = n1 * n2,  x viewed as A[j1, j2]
    G[j2, k1] = sum_j1 A[j1, j2] * W1[j1, k1]     (MXU matmul, contract j1)
    H[j2, k1] = G * w_n^{j2*k1}                   (VPU twiddle)
    Z[k1, k2] = sum_j2 H[j2, k1] * W2[j2, k2]     (MXU matmul, contract j2)
    X[k1 + n1*k2] = Z[k1, k2]                     (VMEM transpose)

through VMEM in ONE kernel per batch tile: one HBM read and one HBM write per
transform, where the un-fused einsum path (``ops/dft_matmul.py``) materializes
every intermediate stage to HBM (XLA cannot fuse matmul -> matmul). Complex
data travels as separate real/imaginary float32 planes (Mosaic has no complex
dtype); each complex matmul is four real MXU matmuls at HIGHEST precision.

Twiddle/DFT-matrix LUTs are precomputed on the host in float64 and cast to
float32 — the same plan-time LUT discipline as the reference
(``templateFFT.cpp:5063-5154``).

Scope: complex64, composite n with a balanced split n1*n2 (n1, n2 <= 256 —
one kernel covers n up to 65536; longer axes fall back to the recursive
matmul executor). The inverse is the conjugate-matrix kernel with the 1/n
scale applied by the caller (numpy convention, like every executor here).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.compat import (
    pvary, shape_dtype_struct, tpu_compiler_params, typeof_vma,
)
from .dft_matmul import _dft_matrix_np

# Largest per-stage DFT factor the kernel accepts; 256 keeps every LUT and
# matmul comfortably MXU/VMEM-sized and covers n <= 65536 in one kernel.
MAX_FACTOR = 256

# VMEM working-set budget per batch tile (bytes). Hardware-measured (v5e):
# Mosaic's scoped stack holds ~12 [tile, n] float32 planes live (re/im at
# each staged intermediate plus the transpose copies), and the grid
# pipeline double-buffers the in/out tiles on top — ~1.5 MiB of budget per
# 16 n-rows. The budget is sized so the whole footprint stays inside
# _VMEM_LIMIT with headroom (a 512-row tile at n=512 measured 48 MiB of
# scoped stack).
_VMEM_BUDGET = 2 * 1024 * 1024

# Mosaic scoped-VMEM ceiling requested via CompilerParams. The default
# scoped limit (16 MiB on v5e) rejects any usefully-sized tile; the chip
# has 128 MiB of VMEM and granting the kernel most of it is the same
# decision the reference makes sizing shared memory per workgroup
# (templateFFT.cpp:3941-4100 maxSharedMemSize).
_VMEM_LIMIT = 100 * 1024 * 1024


def split_for(n: int) -> tuple[int, int] | None:
    """(n1, n2) factor pair the kernel runs, or None.

    The bounded-split decision comes from the native scheduler
    (``dfft_balanced_split`` with the kernel's MAX_FACTOR bound — the
    VMEM-bounded analog of the reference's shared-memory-bounded axis split,
    ``templateFFT.cpp:3941-4100``). The balanced pair minimizes flops
    (8N(n1+n2)) but runs tiny stage matmuls (16x32 at n=512 — a nearly
    idle 128-lane MXU when the pack probe rejects widening);
    ``DFFT_PALLAS_SPLIT`` (same ``N=AxB,...`` syntax as DFFT_MM_SPLIT)
    overrides per length for the hardware sweeps, trading flops for a
    stage factor at the 128 MXU edge (e.g. 512=4x128). Read at trace
    time, like DFFT_MM_PRECISION: the tile jits capture the split, so
    in-process sweepers must clear their caches (tune_pallas does)."""
    import os

    from .. import native

    spec = os.environ.get("DFFT_PALLAS_SPLIT", "").strip()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            key, val = part.split("=")
            key = int(key)
            a, b = (int(v) for v in val.split("x"))
        except ValueError:
            raise ValueError(
                f"DFFT_PALLAS_SPLIT entry {part!r} is not N=AxB") from None
        if key == n:
            if a * b != n or not (1 < a <= MAX_FACTOR) \
                    or not (1 < b <= MAX_FACTOR):
                raise ValueError(
                    f"DFFT_PALLAS_SPLIT {part!r}: need A*B == {n} with "
                    f"factors in (1, {MAX_FACTOR}]")
            return a, b
    return native.balanced_split(n, MAX_FACTOR)


def eligible(n: int) -> bool:
    """Axis lengths the fused kernel handles (others fall back)."""
    return n >= 64 and split_for(n) is not None


def _tile_rows(env_name: str, bytes_per_row: int, floor: int) -> int:
    """Shared tile-size model: power of two, >= ``floor``, VMEM-budgeted;
    ``env_name`` overrides for hardware tuning sweeps (single source for
    the 1D and 2D kernels so budget changes cannot desynchronize them)."""
    import os

    env = os.environ.get(env_name)
    if env:
        return int(env)
    rows = max(floor, _VMEM_BUDGET // bytes_per_row)
    return 1 << min(10, int(math.log2(rows)))


def batch_tile(n: int) -> int:
    """Batch rows per grid step for the 1D kernel."""
    return _tile_rows("DFFT_PALLAS_TILE", 4 * 4 * n, 8)


def _tables_np(n: int, forward: bool, g1: int = 1, g2: int = 1):
    """(W1, T, W2) float32 LUT triple for n = n1*n2, host-exact float64.

    The split is resolved HERE (so a DFFT_PALLAS_SPLIT change between
    calls is honored) and passed into the cached builder — the cache key
    carries (n1, n2), never a stale environment read."""
    n1, n2 = split_for(n)
    return _tables_np_cached(n, n1, n2, forward, g1, g2)


@functools.lru_cache(maxsize=None)
def _tables_np_cached(n: int, n1: int, n2: int, forward: bool,
                      g1: int = 1, g2: int = 1):
    """W1[j1, k1] is the n1-point DFT matrix, W2[j2, k2] the n2-point one,
    and T[j2, k1] = w_n^{j2*k1} the inter-stage twiddle laid out to match
    the first stage's [j2, k1] output. ``g1``/``g2`` > 1 widen the stage
    matrices to block-diagonal I_g (x) W — ``g`` independent DFTs as one
    MXU-width matmul (identical sums; the off-block zeros are exact), the
    packing that lifts a sub-128 factor's systolic-array utilization from
    (n/128)^2 to ~full (see ``dft_matmul.pack_factor``).
    """
    from .dft_matmul import _blockdiag_dft_np
    w1 = _blockdiag_dft_np(n1, g1, forward)
    w2 = _blockdiag_dft_np(n2, g2, forward)
    sign = -2j if forward else 2j
    jk = np.outer(np.arange(n2), np.arange(n1))
    t = np.exp(sign * np.pi * (jk % n) / n)
    f32 = lambda a: np.ascontiguousarray(a.astype(np.complex64))
    return f32(w1), f32(t), f32(w2)


def _interpret_mode() -> bool:
    """True on the CPU test backend (kernels run in the Pallas
    interpreter; shard_map calls route to the jnp mirrors).
    ``DFFT_FORCE_REAL_LOWERING=1`` (``utils.compat.force_real_lowering``,
    shared with the exchange mirrors) forces the REAL pallas_call path
    regardless of backend — not executable on CPU, but it lets
    ``jax.export``-based lowering tests build the actual Mosaic module
    (including the shard_map/vma path) on a chipless host
    (tests/test_tpu_lowering.py)."""
    from ..utils.compat import force_real_lowering

    if force_real_lowering():
        return False
    return jax.default_backend() == "cpu"


def _vma(x) -> frozenset:
    """Varying-across-mesh-axes set of a traced value (empty outside
    shard_map); pallas_call outputs must declare the same set."""
    return typeof_vma(x)


def _mm(a, b):
    from .dft_matmul import mm_precision

    return lax.dot_general(
        a, b, (((1,), (0,)), ((), ())),
        precision=mm_precision(),
        preferred_element_type=jnp.float32,
    )


def _four_step_pass(a3r, a3i, w1r, w1i, tr, ti, w2r, w2i, g1=1, g2=1):
    """One four-step DFT pass contracting the factor dims of [rows, n1, n2]
    planes (the transform axis pre-split to (n1, n2) by the caller), shared
    by the 1D and fused-2D kernels. With ``g1``/``g2`` > 1 the stage
    matrices arrive block-diagonal (I_g (x) W, see ``_tables_np``) and the
    row dim is regrouped so each matmul contracts a full MXU-width g*n
    lanes instead of a sub-128 factor — the reshapes change the lane dim,
    which Mosaic implements as VMEM relayouts (cheap next to a 98%-idle
    systolic array). Returns [rows, n2, n1] planes — flat (k2, k1) IS the
    transformed axis in natural order (k = k1 + n1*k2)."""
    rows, n1, n2 = a3r.shape
    # A[b, j1, j2] -> [b*j2, j1] so stage 1 contracts j1 on the MXU.
    sr = a3r.transpose(0, 2, 1).reshape(rows * n2 // g1, g1 * n1)
    si = a3i.transpose(0, 2, 1).reshape(rows * n2 // g1, g1 * n1)
    gr = _mm(sr, w1r) - _mm(si, w1i)
    gi = _mm(sr, w1i) + _mm(si, w1r)
    # Twiddle on [b, j2, k1] (T broadcast over the batch).
    gr = gr.reshape(rows, n2, n1)
    gi = gi.reshape(rows, n2, n1)
    hr = gr * tr - gi * ti
    hi = gr * ti + gi * tr
    # Stage 2 contracts j2: [b*k1, j2] @ W2 -> Z[b, k1, k2].
    hr = hr.transpose(0, 2, 1).reshape(rows * n1 // g2, g2 * n2)
    hi = hi.transpose(0, 2, 1).reshape(rows * n1 // g2, g2 * n2)
    zr = _mm(hr, w2r) - _mm(hi, w2i)
    zi = _mm(hr, w2i) + _mm(hi, w2r)
    # Output flat index k = k1 + n1*k2: emit Z^T = [b, k2, k1].
    zr = zr.reshape(rows, n1, n2).transpose(0, 2, 1)
    zi = zi.reshape(rows, n1, n2).transpose(0, 2, 1)
    return zr, zi


@functools.lru_cache(maxsize=None)
def _pack_probe_ok(n1: int, n2: int, g1: int, g2: int) -> bool:
    """Per-config Mosaic compile probe for the packed kernels' lane-changing
    reshapes. The packed stage matmuls regroup rows with reshapes that
    change the lane (last) dimension; interpret-mode tests cannot prove a
    given Mosaic version lowers them — and acceptance can depend on the
    pack widths themselves (a 128-lane-aligned g=8 relayout may lower
    while a 120-lane g=12 one does not) — so on a real backend a one-block
    kernel with the exact (n1, n2, g1, g2) about to be used is compiled
    once per process, and the block-diagonal packing is auto-disabled for
    that config (g1=g2=1 — correct, just slower) if the compiler rejects
    it. ``DFFT_PALLAS_PACK=0/1`` overrides the probe in either direction."""
    from ..utils.compat import force_real_lowering

    chipless_lowering = (jax.default_backend() == "cpu"
                         and force_real_lowering())
    if jax.default_backend() == "cpu" and not chipless_lowering:
        return True  # interpret mode executes the reshapes directly
    try:
        n = n1 * n2
        # Smallest row tile the kernel's regroup reshapes accept: rows*n2
        # divisible by g1 and rows*n1 by g2 (same invariant pack_factor
        # guarantees for the real tile).
        bt = next(r for r in range(8, 8 * g1 * g2 + 9)
                  if (r * n2) % g1 == 0 and (r * n1) % g2 == 0)
        w1, t, w2 = _tables_np(n, True, g1, g2)
        consts = [jnp.asarray(p) for m in (w1, t, w2)
                  for p in (m.real, m.imag)]
        lut_specs = [
            pl.BlockSpec(m.shape, lambda i: (0, 0), memory_space=pltpu.VMEM)
            for m in (w1, w1, t, t, w2, w2)
        ]
        x_spec = pl.BlockSpec((bt, n1, n2), lambda i: (i, 0, 0),
                              memory_space=pltpu.VMEM)
        y_spec = pl.BlockSpec((bt, n2, n1), lambda i: (i, 0, 0),
                              memory_space=pltpu.VMEM)
        call = pl.pallas_call(
            _make_kernel(n1, n2, g1, g2),
            grid=(1,),
            in_specs=lut_specs + [x_spec, x_spec],
            out_specs=(y_spec, y_spec),
            out_shape=(
                jax.ShapeDtypeStruct((bt, n2, n1), jnp.float32),
                jax.ShapeDtypeStruct((bt, n2, n1), jnp.float32),
            ),
            compiler_params=tpu_compiler_params(
                dimension_semantics=("parallel",),
                vmem_limit_bytes=_VMEM_LIMIT,
            ),
        )
        z = jnp.zeros((bt, n1, n2), jnp.float32)
        if chipless_lowering:
            # No chip to compile against: probe the Mosaic front end via
            # the TPU export pipeline instead, so force-real lowering
            # tests exercise the same pack gate the real backend would
            # (target-stage acceptance still differs — the hardware
            # probe owns that).
            from jax import export as _export

            _export.export(jax.jit(lambda a, b: call(*consts, a, b)),
                           platforms=["tpu"])(z, z)
            return True
        jax.jit(lambda a, b: call(*consts, a, b)).lower(z, z).compile()
        return True
    except Exception:  # noqa: BLE001 — any rejection means fall back
        return False


def _packs(n1: int, n2: int, rows: int) -> tuple[int, int]:
    """(g1, g2) block-diagonal pack factors for one four-step pass over
    [rows, n1, n2] tiles. ``DFFT_PALLAS_PACK=0`` force-disables,
    ``=1`` force-enables; unset, a one-time compile probe
    (:func:`_pack_probe_ok`) decides whether this Mosaic version accepts
    the packed kernels' lane-changing reshapes."""
    import os

    from .dft_matmul import pack_factor

    env = os.environ.get("DFFT_PALLAS_PACK")
    if env == "0":
        return 1, 1
    g1 = pack_factor(n1, rows * n2)
    g2 = pack_factor(n2, rows * n1)
    if (g1, g2) == (1, 1):
        return 1, 1
    if env is None and not _pack_probe_ok(n1, n2, g1, g2):
        return 1, 1
    return g1, g2


def _make_kernel(n1: int, n2: int, g1: int, g2: int):
    def kernel(w1r, w1i, tr, ti, w2r, w2i, xr, xi, yr, yi):
        zr, zi = _four_step_pass(
            xr[:], xi[:],
            w1r[:], w1i[:], tr[:], ti[:], w2r[:], w2i[:],
            g1=g1, g2=g2,
        )
        yr[:] = zr
        yi[:] = zi

    return kernel


def _make_kernel2d(ny: int, nz: int, gy: tuple[int, int],
                   gz: tuple[int, int]):
    """Fused 2D kernel: FFT along Z then Y of one plane tile, both passes
    staged through VMEM in ONE launch — the templateFFT 2D-app role (one
    ``FFT_main`` covering the whole YZ plane, ``kernel_512x512x1.h``; the
    t0 stage of the slab pipeline, ``fft_mpi_3d_api.cpp:466-522``). Where
    the per-axis path writes the full array to HBM between axes, this
    kernel transposes in VMEM: one HBM read and one write for the plane.
    ``gy``/``gz`` are the per-axis block-diagonal pack factors (see
    ``_packs``).

    Blocks are 5D ``[bt, y1, y2, z1, z2]`` (both axes pre-split by the
    caller); inter-axis data movement is done by transposes, and the
    packed stage matmuls inside ``_four_step_pass`` regroup rows with
    lane-changing reshapes — both are VMEM relayouts under Mosaic. Output blocks are ``[bt, ky2, ky1, kz2,
    kz1]`` — flat (k2, k1) per axis is that axis's natural transformed
    order, so the caller's view back to ``[batch, ny, nz]`` is free."""
    y1, y2 = split_for(ny)
    z1, z2 = split_for(nz)

    def kernel(wy1r, wy1i, tyr, tyi, wy2r, wy2i,
               wz1r, wz1i, tzr, tzi, wz2r, wz2i, xr, xi, yr, yi):
        bt = xr.shape[0]
        # Pass 1 over Z: rows = bt*y1*y2 (leading merge).
        ar = xr[:].reshape(bt * y1 * y2, z1, z2)
        ai = xi[:].reshape(bt * y1 * y2, z1, z2)
        br, bi = _four_step_pass(ar, ai, wz1r[:], wz1i[:], tzr[:],
                                 tzi[:], wz2r[:], wz2i[:],
                                 g1=gz[0], g2=gz[1])
        # [bt, y1, y2, kz2, kz1] -> [bt, kz2, kz1, y1, y2] (VMEM relayout).
        br = br.reshape(bt, y1, y2, z2, z1).transpose(0, 3, 4, 1, 2)
        bi = bi.reshape(bt, y1, y2, z2, z1).transpose(0, 3, 4, 1, 2)
        # Pass 2 over Y: rows = bt*z2*z1.
        br = br.reshape(bt * z2 * z1, y1, y2)
        bi = bi.reshape(bt * z2 * z1, y1, y2)
        cr, ci = _four_step_pass(br, bi, wy1r[:], wy1i[:], tyr[:],
                                 tyi[:], wy2r[:], wy2i[:],
                                 g1=gy[0], g2=gy[1])
        # [bt, kz2, kz1, ky2, ky1] -> [bt, ky2, ky1, kz2, kz1].
        cr = cr.reshape(bt, z2, z1, y2, y1).transpose(0, 3, 4, 1, 2)
        ci = ci.reshape(bt, z2, z1, y2, y1).transpose(0, 3, 4, 1, 2)
        yr[:] = cr
        yi[:] = ci

    return kernel


@functools.partial(jax.jit, static_argnames=("n", "forward", "interpret"))
def _fft_tiles(xr, xi, *, n: int, forward: bool, interpret: bool):
    """Batched length-n DFT of [batch, n] float32 re/im planes; batch must be
    a multiple of the tile size."""
    n1, n2 = split_for(n)
    batch = xr.shape[0]
    bt = min(batch_tile(n), batch)
    grid = batch // bt
    g1, g2 = _packs(n1, n2, bt)

    w1, t, w2 = _tables_np(n, forward, g1, g2)
    consts = [jnp.asarray(p) for m in (w1, t, w2) for p in (m.real, m.imag)]
    vma = _vma(xr)
    if vma:
        # Under shard_map every kernel operand must carry the data's
        # varying-axes set; the replicated LUTs are marked explicitly.
        consts = [pvary(c, tuple(vma)) for c in consts]

    lut_specs = [
        pl.BlockSpec(m.shape, lambda i: (0, 0), memory_space=pltpu.VMEM)
        for m in (w1, w1, t, t, w2, w2)
    ]
    x_spec = pl.BlockSpec((bt, n1, n2), lambda i: (i, 0, 0),
                          memory_space=pltpu.VMEM)
    y_spec = pl.BlockSpec((bt, n2, n1), lambda i: (i, 0, 0),
                          memory_space=pltpu.VMEM)

    yr, yi = pl.pallas_call(
        _make_kernel(n1, n2, g1, g2),
        grid=(grid,),
        in_specs=lut_specs + [x_spec, x_spec],
        out_specs=(y_spec, y_spec),
        # Under shard_map the operands carry a varying-across-mesh-axes set;
        # the outputs vary the same way (per-device batches are independent).
        out_shape=(
            shape_dtype_struct((batch, n2, n1), jnp.float32,
                               vma=_vma(xr)),
            shape_dtype_struct((batch, n2, n1), jnp.float32,
                               vma=_vma(xr)),
        ),
        cost_estimate=pl.CostEstimate(
            flops=8 * batch * n * (g1 * n1 + g2 * n2),
            bytes_accessed=4 * batch * n * 4,
            transcendentals=0,
        ),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel",),
            vmem_limit_bytes=_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(*consts, xr.reshape(batch, n1, n2), xi.reshape(batch, n1, n2))
    return yr.reshape(batch, n), yi.reshape(batch, n)


def batch_tile_2d(ny: int, nz: int) -> int:
    """Plane-batch rows per grid step for the fused 2D kernel (same budget
    model as :func:`batch_tile` scaled by the full plane size)."""
    return _tile_rows("DFFT_PALLAS_TILE2D", 4 * 4 * ny * nz, 1)


# Largest ny*nz plane (float32 elements) the fused 2D kernel accepts: one
# plane copy must fit the per-tile VMEM budget, since the kernel's working
# set is ~a dozen live plane copies even at bt=1 (the measured stack model
# behind _VMEM_BUDGET). 512x1024 planes pass; 1024^2 and beyond take the
# per-axis path until hardware-proven.
_MAX_PLANE_ELEMS = _VMEM_BUDGET // 4


def eligible2d(ny: int, nz: int) -> bool:
    """Plane shapes the fused 2D kernel handles: single-kernel factors on
    BOTH axes *and* a VMEM-bounded plane footprint; larger planes take the
    per-axis path."""
    return (eligible(ny) and eligible(nz)
            and ny * nz <= _MAX_PLANE_ELEMS)


@functools.partial(jax.jit, static_argnames=("ny", "nz", "forward",
                                             "interpret"))
def _fft2_tiles(xr, xi, *, ny: int, nz: int, forward: bool, interpret: bool):
    """Batched 2D DFT of [batch, ny, nz] float32 re/im planes; batch must
    be a multiple of the tile size. Blocks travel pre-split as
    [bt, y1, y2, z1, z2] (see ``_make_kernel2d``); outputs come back as
    [batch, ky2, ky1, kz2, kz1] = [batch, ny, nz] flat."""
    batch = xr.shape[0]
    bt = min(batch_tile_2d(ny, nz), batch)
    grid = batch // bt
    y1, y2 = split_for(ny)
    z1, z2 = split_for(nz)
    gz = _packs(z1, z2, bt * y1 * y2)
    gy = _packs(y1, y2, bt * z2 * z1)

    tabs = []
    for n, g in ((ny, gy), (nz, gz)):
        w1, t, w2 = _tables_np(n, forward, *g)
        tabs += [m for m in (w1, t, w2)]
    consts = [jnp.asarray(p) for m in tabs for p in (m.real, m.imag)]
    vma = _vma(xr)
    if vma:
        consts = [pvary(c, tuple(vma)) for c in consts]

    lut_specs = [
        pl.BlockSpec(m.shape, lambda i: (0, 0), memory_space=pltpu.VMEM)
        for m in tabs for _ in (0, 1)
    ]
    x_spec = pl.BlockSpec((bt, y1, y2, z1, z2), lambda i: (i, 0, 0, 0, 0),
                          memory_space=pltpu.VMEM)
    y_spec = pl.BlockSpec((bt, y2, y1, z2, z1), lambda i: (i, 0, 0, 0, 0),
                          memory_space=pltpu.VMEM)

    yr, yi = pl.pallas_call(
        _make_kernel2d(ny, nz, gy, gz),
        grid=(grid,),
        in_specs=lut_specs + [x_spec, x_spec],
        out_specs=(y_spec, y_spec),
        out_shape=(
            shape_dtype_struct((batch, y2, y1, z2, z1), jnp.float32,
                               vma=vma),
            shape_dtype_struct((batch, y2, y1, z2, z1), jnp.float32,
                               vma=vma),
        ),
        cost_estimate=pl.CostEstimate(
            flops=8 * batch * ny * nz * (gy[0] * y1 + gy[1] * y2
                                         + gz[0] * z1 + gz[1] * z2),
            bytes_accessed=4 * batch * ny * nz * 4,
            transcendentals=0,
        ),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel",),
            vmem_limit_bytes=_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(*consts,
      xr.reshape(batch, y1, y2, z1, z2),
      xi.reshape(batch, y1, y2, z1, z2))
    return yr.reshape(batch, ny, nz), yi.reshape(batch, ny, nz)


def _make_kernel_strided(n1: int, n2: int, g1: int, g2: int):
    """Strided kernel: four-step DFT over the LEADING axis of [n1, n2, ct]
    tiles (transform axis pre-split, a column chunk trailing) — the
    ``radixStrided`` role of the reference's codegen
    (``templateFFT.cpp:1760``): transform a strided axis without a global
    transpose. The HBM layout never changes; the reorders run on the tile
    in VMEM. ``g1``/``g2`` are block-diagonal pack factors (``_packs``).
    Output tiles are [n2, n1, ct] (flat (k2, k1) = the transformed
    axis in natural order)."""

    def kernel(w1r, w1i, tr, ti, w2r, w2i, xr, xi, yr, yi):
        ct = xr.shape[-1]
        # Stage 1 contracts j1: [j1, j2, c] -> [j2, c, j1] -> [j2*c, j1].
        ar = xr[:].transpose(1, 2, 0).reshape(n2 * ct // g1, g1 * n1)
        ai = xi[:].transpose(1, 2, 0).reshape(n2 * ct // g1, g1 * n1)
        gr = _mm(ar, w1r[:]) - _mm(ai, w1i[:])
        gi = _mm(ar, w1i[:]) + _mm(ai, w1r[:])
        # Twiddle T[j2, k1] broadcast over the column chunk.
        gr = gr.reshape(n2, ct, n1)
        gi = gi.reshape(n2, ct, n1)
        hr = gr * tr[:][:, None, :] - gi * ti[:][:, None, :]
        hi = gr * ti[:][:, None, :] + gi * tr[:][:, None, :]
        # Stage 2 contracts j2: [j2, c, k1] -> [c, k1, j2] -> [c*k1, j2].
        hr = hr.transpose(1, 2, 0).reshape(ct * n1 // g2, g2 * n2)
        hi = hi.transpose(1, 2, 0).reshape(ct * n1 // g2, g2 * n2)
        zr = _mm(hr, w2r[:]) - _mm(hi, w2i[:])
        zi = _mm(hr, w2i[:]) + _mm(hi, w2r[:])
        # [c, k1, k2] -> [k2, k1, c]: leading flat (k2, k1) = output order.
        yr[:] = zr.reshape(ct, n1, n2).transpose(2, 1, 0)
        yi[:] = zi.reshape(ct, n1, n2).transpose(2, 1, 0)

    return kernel


def col_tile(n: int) -> int:
    """Column chunk per grid step for the strided kernel."""
    return _tile_rows("DFFT_PALLAS_TILE_STRIDED", 4 * 4 * n, 8)


@functools.partial(jax.jit, static_argnames=("n", "forward", "interpret"))
def _fft_strided_tiles(xr, xi, *, n: int, forward: bool, interpret: bool):
    """Length-n DFT over the LEADING axis of [n, cols] float32 re/im
    planes; cols must be a multiple of the tile size."""
    n1, n2 = split_for(n)
    cols = xr.shape[1]
    ct = min(col_tile(n), cols)
    grid = cols // ct
    g1, g2 = _packs(n1, n2, ct)

    w1, t, w2 = _tables_np(n, forward, g1, g2)
    consts = [jnp.asarray(p) for m in (w1, t, w2) for p in (m.real, m.imag)]
    vma = _vma(xr)
    if vma:
        consts = [pvary(c, tuple(vma)) for c in consts]

    lut_specs = [
        pl.BlockSpec(m.shape, lambda i: (0, 0), memory_space=pltpu.VMEM)
        for m in (w1, w1, t, t, w2, w2)
    ]
    x_spec = pl.BlockSpec((n1, n2, ct), lambda i: (0, 0, i),
                          memory_space=pltpu.VMEM)
    y_spec = pl.BlockSpec((n2, n1, ct), lambda i: (0, 0, i),
                          memory_space=pltpu.VMEM)

    yr, yi = pl.pallas_call(
        _make_kernel_strided(n1, n2, g1, g2),
        grid=(grid,),
        in_specs=lut_specs + [x_spec, x_spec],
        out_specs=(y_spec, y_spec),
        out_shape=(
            shape_dtype_struct((n2, n1, cols), jnp.float32, vma=vma),
            shape_dtype_struct((n2, n1, cols), jnp.float32, vma=vma),
        ),
        cost_estimate=pl.CostEstimate(
            flops=8 * cols * n * (g1 * n1 + g2 * n2),
            bytes_accessed=4 * cols * n * 4,
            transcendentals=0,
        ),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel",),
            vmem_limit_bytes=_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(*consts, xr.reshape(n1, n2, cols), xi.reshape(n1, n2, cols))
    return yr.reshape(n, cols), yi.reshape(n, cols)


def fft_axis0(x: jnp.ndarray, forward: bool = True,
              normalize: bool = True) -> jnp.ndarray:
    """C2C FFT over axis 0 of ``x`` via the strided kernel — no HBM
    transpose (callers gate on :func:`eligible` of ``x.shape[0]`` and
    complex64). Forward unnormalized, inverse scaled by 1/n
    (``normalize=False`` skips the inverse scale for composing stages)."""
    n = x.shape[0]
    rest = x.shape[1:]
    cols = math.prod(rest) if rest else 1
    x2 = x.reshape(n, cols)

    ct = min(col_tile(n), max(8, cols))
    pad = (-cols) % ct
    if pad:
        x2 = jnp.pad(x2, ((0, 0), (0, pad)))
    interpret = _interpret_mode()
    if interpret and _vma(x2):
        y = _four_step_ref(x2.T, n, forward).T
    else:
        yr, yi = _fft_strided_tiles(jnp.real(x2), jnp.imag(x2), n=n,
                                    forward=forward, interpret=interpret)
        y = lax.complex(yr, yi)
    if pad:
        y = y[:, :cols]
    if not forward and normalize:
        y = y * jnp.float32(1.0 / n)
    return y.reshape((n,) + rest)


def fft2_last(x: jnp.ndarray, forward: bool = True) -> jnp.ndarray:
    """Fused 2D C2C FFT over the LAST TWO axes of ``x`` (complex64, both
    extents kernel-eligible — callers gate on :func:`eligible2d`). Forward
    unnormalized, inverse scaled by 1/(ny*nz)."""
    ny, nz = x.shape[-2:]
    lead = x.shape[:-2]
    batch = math.prod(lead) if lead else 1
    x2 = x.reshape((batch, ny, nz))

    bt = min(batch_tile_2d(ny, nz), max(1, batch))
    pad = (-batch) % bt
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0), (0, 0)))
    interpret = _interpret_mode()
    if interpret and _vma(x2):
        # CPU test backend under shard_map: the interpreter's grid loop
        # cannot carry varying-axes types — per-axis jnp mirror, numerics
        # identical to the kernel.
        y = _four_step_ref(x2.reshape(-1, nz), nz, forward)
        y = y.reshape(x2.shape)
        y = jnp.swapaxes(y, -1, -2)
        y = _four_step_ref(y.reshape(-1, ny), ny, forward)
        y = jnp.swapaxes(y.reshape(x2.shape[0], nz, ny), -1, -2)
    else:
        yr, yi = _fft2_tiles(jnp.real(x2), jnp.imag(x2), ny=ny, nz=nz,
                             forward=forward, interpret=interpret)
        y = lax.complex(yr, yi)
    if pad:
        y = y[:batch]
    if not forward:
        y = y * jnp.float32(1.0 / (ny * nz))
    return y.reshape(lead + (ny, nz))


@functools.lru_cache(maxsize=None)
def outer_split(n: int) -> tuple[int, int] | None:
    """Balanced divisor pair with BOTH factors kernel-eligible — the
    two-level plan for axes beyond one kernel's reach (the multi-upload
    regime of the reference's scheduler, ``templateFFT.cpp:4007-4100``:
    there >1 shared-memory passes, here >1 fused-kernel passes). Capped at
    n < 2^31 so the int32 twiddle phase stays exact; longer axes take the
    recursive matmul path."""
    if n >= 1 << 31:
        return None
    for d in range(int(math.isqrt(n)), 63, -1):
        if n % d == 0 and eligible(d) and eligible(n // d):
            return d, n // d
    return None


def _fft_last_big(x2: jnp.ndarray, n: int, forward: bool) -> jnp.ndarray:
    """Two-level four-step over [batch, n]: each DFT stage is a fused-kernel
    batched transform, the inter-stage twiddle/transposes run at the XLA
    level (exact int32 phase: i*j < n < 2^31)."""
    m1, m2 = outer_split(n)
    batch = x2.shape[0]
    a = x2.reshape(batch, m1, m2)
    # DFT over j1 via the vmapped strided kernel — in-VMEM reorders, no
    # HBM swapaxes round trip (the mirror path under shard_map on CPU
    # takes the explicit transposes instead).
    if _interpret_mode() and _vma(a):
        b = jnp.swapaxes(a, -1, -2).reshape(batch * m2, m1)
        b = _fft_eligible(b, m1, forward)
        b = jnp.swapaxes(b.reshape(batch, m2, m1), -1, -2)  # [batch, k1, j2]
    else:
        # Unnormalized stage: the caller applies the single 1/n at the end.
        b = jax.vmap(
            lambda v: fft_axis0(v, forward=forward, normalize=False))(a)
    i = jnp.arange(m1, dtype=jnp.int32)[:, None]
    j = jnp.arange(m2, dtype=jnp.int32)[None, :]
    phase = (i * j) % jnp.int32(n)
    sign = -2.0 if forward else 2.0
    ang = (sign * np.pi / n) * phase.astype(jnp.float32)
    b = b * lax.complex(jnp.cos(ang), jnp.sin(ang))
    c = _fft_eligible(b.reshape(batch * m1, m2), m2, forward)
    c = c.reshape(batch, m1, m2)
    # Output flat index k = k1 + m1*k2.
    return jnp.swapaxes(c, -1, -2).reshape(batch, n)


def _fft_eligible(x2: jnp.ndarray, n: int, forward: bool) -> jnp.ndarray:
    """Kernel-path transform of [batch, n] complex64 rows (n eligible),
    including the batch pad/crop discipline."""
    batch = x2.shape[0]
    bt = min(batch_tile(n), max(8, batch))
    pad = (-batch) % bt
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    interpret = _interpret_mode()
    if interpret and _vma(x2):
        y = _four_step_ref(x2, n, forward)
    else:
        yr, yi = _fft_tiles(jnp.real(x2), jnp.imag(x2), n=n, forward=forward,
                            interpret=interpret)
        y = lax.complex(yr, yi)
    return y[:batch] if pad else y


def _four_step_ref(x2: jnp.ndarray, n: int, forward: bool) -> jnp.ndarray:
    """jnp mirror of the kernel math (same LUTs, same contraction order and
    precision) for [batch, n] complex input. Used on the CPU test backend
    under shard_map, where the Pallas interpreter's grid loop cannot carry
    varying-axes types; numerics are identical to the kernel."""
    n1, n2 = split_for(n)
    w1, t, w2 = (jnp.asarray(m) for m in _tables_np(n, forward, 1, 1))
    a = x2.reshape(-1, n1, n2)
    from .dft_matmul import mm_precision

    g = jnp.einsum("bij,ik->bjk", a, w1, precision=mm_precision())
    h = g * t
    z = jnp.einsum("bjk,jl->bkl", h, w2, precision=mm_precision())
    return z.transpose(0, 2, 1).reshape(x2.shape)


def record_fallback(axis, reason: str) -> None:
    """Count one Pallas-eligibility fallback into the ``pallas_fallback``
    metrics series (axis + reason labels). Trace-time: the eligibility
    decision is static per compiled plan, so the counter ticks once per
    trace, not per execute — the observable is *which shapes route away
    from the kernel and why* (docs/OBSERVABILITY.md)."""
    from ..utils import metrics as _metrics

    _metrics.inc("pallas_fallback", axis=int(axis), reason=reason)


def fft_along_axis(x: jnp.ndarray, axis: int, forward: bool = True) -> jnp.ndarray:
    """C2C FFT along one axis via the fused Pallas kernel; falls back to the
    recursive MXU-matmul path for ineligible lengths/dtypes (counted in the
    ``pallas_fallback`` metrics series). Forward is unnormalized, inverse
    scaled by 1/n (numpy convention)."""
    from . import dft_matmul

    n = x.shape[axis]
    two_level = False
    if jnp.dtype(x.dtype) != jnp.complex64 or x.size == 0:
        record_fallback(axis, "dtype" if x.size else "empty")
        return dft_matmul.fft_along_axis(x, axis, forward=forward)
    if not eligible(n):
        if outer_split(n) is None:
            record_fallback(axis, "length")
            return dft_matmul.fft_along_axis(x, axis, forward=forward)
        two_level = True

    if axis % x.ndim == 0 and x.ndim > 1 and not two_level:
        # Leading-axis transform: the strided kernel reorders in VMEM,
        # skipping the two HBM moveaxis passes entirely.
        return fft_axis0(x, forward=forward)
    ax = axis % x.ndim
    if 0 < ax < x.ndim - 1 and not two_level:
        # Middle-axis transform: vmap the strided kernel over the leading
        # dims (the batching rule adds a grid dimension) — still no HBM
        # transpose.
        lead = math.prod(x.shape[:ax])
        shp = x.shape
        x3 = x.reshape((lead,) + x.shape[ax:ax + 1]
                       + (math.prod(x.shape[ax + 1:]),))
        y = jax.vmap(lambda v: fft_axis0(v, forward=forward))(x3)
        return y.reshape(shp)

    moved = axis not in (-1, x.ndim - 1)
    if moved:
        x = jnp.moveaxis(x, axis, -1)
    mshape = x.shape
    batch = math.prod(mshape[:-1]) if x.ndim > 1 else 1
    x = x.reshape(batch, n)

    if two_level:
        y = _fft_last_big(x, n, forward)
    else:
        y = _fft_eligible(x, n, forward)
    if not forward:
        y = y * jnp.float32(1.0 / n)
    y = y.reshape(mshape)
    if moved:
        y = jnp.moveaxis(y, -1, axis)
    return y
