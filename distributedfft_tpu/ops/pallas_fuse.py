"""Fused stage+codec Pallas mega-kernels — the Pallas fusion tier's engine.

The reference's core speed trick is runtime kernel generation: one
specialized kernel per shape, staging a whole transform through on-chip
memory (``shaderGenFFT``, ``templateFFT.cpp:4699``). PR 12's
:mod:`.pallas_fft` brought that to single stages; this module fuses the
*stage boundary* the wire codec creates: in the unfused chain a compressed
exchange pays

    FFT kernel  -> write c64 block to HBM
    wire encode -> read c64 block, write wire bytes      (transport side)
    collective  -> wire bytes on the fabric
    wire decode -> read wire bytes, write c64 block      (transport side)
    FFT kernel  -> read c64 block from HBM

and this module's kernels collapse each side to ONE launch: the four-step
FFT (the exact :func:`.pallas_fft._four_step_pass` math) with the codec's
quantize/dequantize done in-register next to the butterfly, so the stage's
exchange-facing HBM stream is the *wire form*, never the intermediate c64
block. The stage-graph fusion pass (:func:`...stagegraph.plan_fusion`)
decides which stage pairs route here.

Kernel scope (everything else takes the pure-JAX mirror, values identical
to the unfused chain by construction):

- single transform axis, tiled on that same axis (the canonical fused
  pairs: every exchange's receiver FFT runs along the concat axis it
  decodes on, and the pencil sender FFT runs along the split axis it
  encodes on);
- complex64, kernel-eligible length (:func:`.pallas_fft.eligible`), tile
  count dividing the axis, and the whole local block VMEM-resident (one
  grid step — the per-(peer-tile, component-plane) amax reduction of the
  quantized codecs is a global reduction over the block, so the block
  must be in VMEM at once; the same `_MAX_PLANE_ELEMS` bound as the
  fused 2D kernel).

On the CPU test backend the mirrors also serve as the interpret-safe
shard_map path (the :func:`.pallas_fft._fft_eligible` discipline); the
kernel bodies themselves are exercised by the interpret-mode CI smoke
(``tests/test_a2q_fusion.py``) outside shard_map.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.compat import pvary, shape_dtype_struct, tpu_compiler_params
from . import pallas_fft
from .pallas_fft import (
    _MAX_PLANE_ELEMS,
    _VMEM_LIMIT,
    _interpret_mode,
    _tables_np,
    _vma,
    eligible,
    split_for,
)

#: Quantized codecs the in-kernel pack supports: name -> (signed levels,
#: mantissa dtype). ``bf16`` is the cast-only codec (no amax reduction).
_Q_CODECS = {"int8": (127.0, jnp.int8), "split": (32767.0, jnp.int16)}

#: Wire codecs with an in-kernel pack/unpack.
FUSABLE_CODECS = ("bf16",) + tuple(_Q_CODECS)


def record_fusion_fallback(site, reason: str) -> None:
    """Count one fusion fallback into the ``fusion_fallback`` metrics
    series (site + reason labels). Trace-time, like
    :func:`.pallas_fft.record_fallback`: the decision is static per
    compiled plan; the observable is which sites route away from the
    fused path and why (docs/OBSERVABILITY.md)."""
    from ..utils import metrics as _metrics

    _metrics.inc("fusion_fallback", site=str(site), reason=str(reason))


def kernel_ineligible(shape, fft_axis: int, tile_axis: int, tiles: int,
                      dtype, wire_dtype: str) -> str | None:
    """Why the fused kernel cannot run this site, or None if it can.
    Pure shape/dtype algebra (no backend query) — shared by the trace
    and by the tests pinning the fallback taxonomy."""
    if wire_dtype not in FUSABLE_CODECS:
        return "codec"
    if jnp.dtype(dtype) != jnp.complex64:
        return "dtype"
    elems = math.prod(int(s) for s in shape)
    if elems == 0:
        return "empty"
    ndim = len(shape)
    fa, ta = fft_axis % ndim, tile_axis % ndim
    if fa != ta:
        return "tile_axis"
    n = int(shape[fa])
    if not eligible(n):
        return "length"
    if tiles < 1 or n % tiles:
        return "uneven_tiles"
    if elems > _MAX_PLANE_ELEMS:
        return "vmem"
    return None


def _pow2_step_block(amax, levels: float):
    """In-kernel power-of-two step (the :func:`...parallel.exchange`
    ``_pow2_step`` math at any level count): exact decode products,
    exact encode/decode idempotence, and sidecars bit-identical to the
    mirror codecs' (``exchange.exact_pow2`` bit-construction — XLA's
    ``exp2`` can be 1 ulp off a true power of two)."""
    safe = jnp.where(amax > 0.0, amax, jnp.float32(levels))
    k = jnp.clip(jnp.ceil(jnp.log2(safe / levels)),
                 -126.0, 127.0).astype(jnp.int32)
    step = lax.bitcast_convert_type((k + 127) << 23, jnp.float32)
    return jnp.where(amax > 0.0, step, jnp.float32(1.0))


def _make_encode_kernel(R: int, n: int, n1: int, n2: int, tiles: int,
                        codec: str, forward: bool):
    """FFT + wire-encode mega-kernel body over one [R, n] block: four-step
    transform of every row, then the codec pack — bf16 cast, or the
    per-(tile segment, component plane) pow2 quantization — all in VMEM.
    Tile segments partition the TRANSFORMED axis (the fused pairs always
    tile the exchange axis they transform)."""
    seg = n // tiles
    inv = None if forward else float(1.0 / n)

    def _transform(xr, xi, w1r, w1i, tr, ti, w2r, w2i):
        zr, zi = pallas_fft._four_step_pass(
            xr.reshape(R, n1, n2), xi.reshape(R, n1, n2),
            w1r, w1i, tr, ti, w2r, w2i)
        yr, yi = zr.reshape(R, n), zi.reshape(R, n)
        if inv is not None:
            yr, yi = yr * inv, yi * inv
        return yr, yi

    if codec == "bf16":
        def kernel(w1r, w1i, tr, ti, w2r, w2i, xr, xi, q):
            yr, yi = _transform(xr[:], xi[:], w1r[:], w1i[:], tr[:],
                                ti[:], w2r[:], w2i[:])
            q[:] = jnp.stack([yr, yi], axis=-1).astype(jnp.bfloat16)

        return kernel

    levels, qdt = _Q_CODECS[codec]

    def kernel(w1r, w1i, tr, ti, w2r, w2i, xr, xi, q, s):
        yr, yi = _transform(xr[:], xi[:], w1r[:], w1i[:], tr[:],
                            ti[:], w2r[:], w2i[:])
        # Per-tile-segment amax over the whole block (tile leading so the
        # reduction runs over one contiguous [R*seg] extent per tile).
        tr_ = yr.reshape(R, tiles, seg).transpose(1, 0, 2)
        ti_ = yi.reshape(R, tiles, seg).transpose(1, 0, 2)
        amr = jnp.max(jnp.abs(tr_.reshape(tiles, R * seg)), axis=1,
                      keepdims=True)
        ami = jnp.max(jnp.abs(ti_.reshape(tiles, R * seg)), axis=1,
                      keepdims=True)
        sr = _pow2_step_block(amr, levels)
        si = _pow2_step_block(ami, levels)
        qr = jnp.clip(jnp.round(tr_ / sr.reshape(tiles, 1, 1)),
                      -levels, levels).astype(qdt)
        qi = jnp.clip(jnp.round(ti_ / si.reshape(tiles, 1, 1)),
                      -levels, levels).astype(qdt)
        q[:] = jnp.stack([qr.transpose(1, 0, 2).reshape(R, n),
                          qi.transpose(1, 0, 2).reshape(R, n)], axis=-1)
        s[:] = jnp.concatenate([sr, si], axis=1)

    return kernel


def _make_decode_kernel(R: int, n: int, n1: int, n2: int, tiles: int,
                        codec: str, forward: bool):
    """Wire-decode + FFT mega-kernel body over one [R, n, 2] wire block:
    the codec unpack (bf16 cast, or mantissa * pow2-step — exact), then
    the four-step transform of every row, all in VMEM."""
    seg = n // tiles
    inv = None if forward else float(1.0 / n)

    def _finish(vr, vi, w1r, w1i, tr, ti, w2r, w2i, yr, yi):
        zr, zi = pallas_fft._four_step_pass(
            vr.reshape(R, n1, n2), vi.reshape(R, n1, n2),
            w1r, w1i, tr, ti, w2r, w2i)
        zr, zi = zr.reshape(R, n), zi.reshape(R, n)
        if inv is not None:
            zr, zi = zr * inv, zi * inv
        yr[:] = zr
        yi[:] = zi

    if codec == "bf16":
        def kernel(w1r, w1i, tr, ti, w2r, w2i, q, yr, yi):
            qv = q[:]
            _finish(qv[..., 0].astype(jnp.float32),
                    qv[..., 1].astype(jnp.float32),
                    w1r[:], w1i[:], tr[:], ti[:], w2r[:], w2i[:], yr, yi)

        return kernel

    def kernel(w1r, w1i, tr, ti, w2r, w2i, q, s, yr, yi):
        qv = q[:]
        sv = s[:]  # [tiles, 2] pow2 steps
        vr = (qv[..., 0].astype(jnp.float32).reshape(R, tiles, seg)
              * sv[:, 0].reshape(1, tiles, 1)).reshape(R, n)
        vi = (qv[..., 1].astype(jnp.float32).reshape(R, tiles, seg)
              * sv[:, 1].reshape(1, tiles, 1)).reshape(R, n)
        _finish(vr, vi, w1r[:], w1i[:], tr[:], ti[:], w2r[:], w2i[:],
                yr, yi)

    return kernel


def _luts(n: int, forward: bool, vma):
    w1, t, w2 = _tables_np(n, forward, 1, 1)
    consts = [jnp.asarray(p) for m in (w1, t, w2)
              for p in (m.real, m.imag)]
    if vma:
        consts = [pvary(c, tuple(vma)) for c in consts]
    specs = [
        pl.BlockSpec(m.shape, lambda i: (0, 0), memory_space=pltpu.VMEM)
        for m in (w1, w1, t, t, w2, w2)
    ]
    return consts, specs


@functools.partial(jax.jit, static_argnames=(
    "n", "forward", "tiles", "codec", "interpret"))
def _encode_tiles(xr, xi, *, n: int, forward: bool, tiles: int,
                  codec: str, interpret: bool):
    """One fused FFT+encode launch over the whole [R, n] block (single
    grid step — the per-tile amax is a block-global reduction)."""
    R = xr.shape[0]
    n1, n2 = split_for(n)
    vma = _vma(xr)
    consts, lut_specs = _luts(n, forward, vma)
    x_spec = pl.BlockSpec((R, n1, n2), lambda i: (0, 0, 0),
                          memory_space=pltpu.VMEM)
    q_spec = pl.BlockSpec((R, n, 2), lambda i: (0, 0, 0),
                          memory_space=pltpu.VMEM)
    if codec == "bf16":
        out_specs = q_spec
        out_shape = shape_dtype_struct((R, n, 2), jnp.bfloat16, vma=vma)
    else:
        _, qdt = _Q_CODECS[codec]
        out_specs = (q_spec,
                     pl.BlockSpec((tiles, 2), lambda i: (0, 0),
                                  memory_space=pltpu.VMEM))
        out_shape = (shape_dtype_struct((R, n, 2), qdt, vma=vma),
                     shape_dtype_struct((tiles, 2), jnp.float32, vma=vma))
    out = pl.pallas_call(
        _make_encode_kernel(R, n, n1, n2, tiles, codec, forward),
        grid=(1,),
        in_specs=lut_specs + [x_spec, x_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        cost_estimate=pl.CostEstimate(
            flops=8 * R * n * (n1 + n2),
            bytes_accessed=2 * R * n * 4 + R * n * 2 * 2,
            transcendentals=0,
        ),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(*consts, xr.reshape(R, n1, n2), xi.reshape(R, n1, n2))
    return out if isinstance(out, tuple) else (out,)


@functools.partial(jax.jit, static_argnames=(
    "n", "forward", "tiles", "codec", "interpret"))
def _decode_tiles(q, s, *, n: int, forward: bool, tiles: int, codec: str,
                  interpret: bool):
    """One fused decode+FFT launch over the whole [R, n, 2] wire block."""
    R = q.shape[0]
    n1, n2 = split_for(n)
    vma = _vma(q)
    consts, lut_specs = _luts(n, forward, vma)
    q_spec = pl.BlockSpec((R, n, 2), lambda i: (0, 0, 0),
                          memory_space=pltpu.VMEM)
    in_specs = lut_specs + [q_spec]
    operands = [q]
    if codec != "bf16":
        in_specs.append(pl.BlockSpec((tiles, 2), lambda i: (0, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(s)
    y_spec = pl.BlockSpec((R, n), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    yr, yi = pl.pallas_call(
        _make_decode_kernel(R, n, n1, n2, tiles, codec, forward),
        grid=(1,),
        in_specs=in_specs,
        out_specs=(y_spec, y_spec),
        out_shape=(
            shape_dtype_struct((R, n), jnp.float32, vma=vma),
            shape_dtype_struct((R, n), jnp.float32, vma=vma),
        ),
        cost_estimate=pl.CostEstimate(
            flops=8 * R * n * (n1 + n2),
            bytes_accessed=2 * R * n * 4 + R * n * 2 * 2,
            transcendentals=0,
        ),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(*consts, *operands)
    return yr, yi


def fused_fft_encode(x: jnp.ndarray, *, fft_axis: int, forward: bool,
                     tile_axis: int, tiles: int, wire_dtype: str,
                     site: str = "fft_encode") -> tuple:
    """Stage FFT + wire encode as ONE kernel launch where eligible.

    Returns exactly what ``wire_codec(wire_dtype).encode(ex(x), ...)``
    returns — the tuple of wire parts, payload first — so the caller
    ships them through the transport unchanged. Ineligible shapes and
    the CPU shard_map interpreter take the pure-JAX mirror (the unfused
    executor + codec — bit-identical to the unfused chain); kernel
    fallbacks are counted in the ``fusion_fallback`` series."""
    from ..parallel.exchange import wire_codec

    codec = wire_codec(wire_dtype)
    reason = kernel_ineligible(x.shape, fft_axis, tile_axis, tiles,
                               x.dtype, wire_dtype)
    interpret = _interpret_mode()
    if reason is not None:
        record_fusion_fallback(site, reason)
    if reason is not None or (interpret and _vma(x)):
        y = pallas_fft.fft_along_axis(x, fft_axis, forward=forward)
        return codec.encode(y, tile_axis=tile_axis, tiles=tiles)
    fa = fft_axis % x.ndim
    xm = jnp.moveaxis(x, fa, -1) if fa != x.ndim - 1 else x
    mshape = xm.shape
    n = mshape[-1]
    R = math.prod(mshape[:-1]) if xm.ndim > 1 else 1
    out = _encode_tiles(
        jnp.real(xm).reshape(R, n).astype(jnp.float32),
        jnp.imag(xm).reshape(R, n).astype(jnp.float32),
        n=n, forward=forward, tiles=tiles, codec=wire_dtype,
        interpret=interpret)
    q = out[0].reshape(mshape + (2,))
    if fa != x.ndim - 1:
        q = jnp.moveaxis(q, -2, fa)
    if wire_dtype == "bf16":
        return (q,)
    bshape = [1] * (x.ndim + 1)
    bshape[fa] = tiles
    bshape[-1] = 2
    return (q, out[1].reshape(bshape))


def fused_decode_fft(parts: tuple, dtype, *, fft_axis: int, forward: bool,
                     tile_axis: int, tiles: int, wire_dtype: str,
                     site: str = "decode_fft") -> jnp.ndarray:
    """Wire decode + stage FFT as ONE kernel launch where eligible —
    the receiver-side twin of :func:`fused_fft_encode`. ``parts`` is the
    post-collective wire tuple; ``tile_axis`` names where the peer tiles
    sit NOW (the concat axis). Same mirror/fallback discipline."""
    from ..parallel.exchange import wire_codec

    codec = wire_codec(wire_dtype)
    payload = parts[0]
    shape = payload.shape[:-1]
    reason = kernel_ineligible(shape, fft_axis, tile_axis, tiles, dtype,
                               wire_dtype)
    interpret = _interpret_mode()
    if reason is not None:
        record_fusion_fallback(site, reason)
    if reason is not None or (interpret and _vma(payload)):
        y = codec.decode(parts, dtype, tile_axis=tile_axis, tiles=tiles)
        return pallas_fft.fft_along_axis(y, fft_axis, forward=forward)
    ndim = len(shape)
    fa = fft_axis % ndim
    qm = jnp.moveaxis(payload, fa, -2) if fa != ndim - 1 else payload
    mshape = qm.shape[:-1]
    n = mshape[-1]
    R = math.prod(mshape[:-1]) if len(mshape) > 1 else 1
    scales = (parts[1].reshape(tiles, 2) if wire_dtype != "bf16"
              else None)
    yr, yi = _decode_tiles(qm.reshape(R, n, 2), scales, n=n,
                           forward=forward, tiles=tiles, codec=wire_dtype,
                           interpret=interpret)
    y = lax.complex(yr, yi).astype(dtype).reshape(mshape)
    if fa != ndim - 1:
        y = jnp.moveaxis(y, -1, fa)
    return y
