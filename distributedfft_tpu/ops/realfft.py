"""Real transforms via half-length complex FFTs — the packed-real trick.

The reference's r2c surface (heFFTe ``rocfft_executor_r2c``,
``heffte_backend_rocm.h:567``; geometry shrink ``box3d::r2c``,
``heffte_geometry.h:94``) leans on the vendor library's native real
transforms, which do half the work of a complex FFT. The matmul/pallas
executors here have no native real path; promoting to complex and slicing
(the round-1 approach) throws that factor of two away.

This module restores it with the classic even-``n`` packing: the real
sequence is viewed as a half-length complex one (even samples -> real
part, odd samples -> imaginary part), transformed with the executor's own
c2c engine, and untangled with one twiddle pass:

    z[m]  = x[2m] + i x[2m+1],           m = 0..h-1,  h = n/2
    Z     = FFT_h(z)
    X[k]  = (Z[k] + Z*[h-k])/2 - (i/2) e^{-2pi i k/n} (Z[k] - Z*[h-k])

for k = 0..h (with Z[h] = Z[0]) — exactly the non-redundant n//2+1
outputs. The inverse packs the hermitian half-spectrum back into a
half-length complex signal and runs the executor's inverse c2c. Twiddles
are host-precomputed in float64 (the plan-time LUT discipline of
``templateFFT.cpp:5063-5154``).

Odd ``n`` falls back to the caller's promote-and-slice path (rare in
practice: r2c worlds are almost always even along the real axis).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import jax.numpy as jnp
from jax import lax

__all__ = ["r2c_via_half_complex", "c2r_via_half_complex"]

# c2c(x, axis, forward) -> y; numpy conventions (inverse scaled by 1/len).
C2CFn = Callable[..., jnp.ndarray]


def _twiddle(n: int, cdtype) -> np.ndarray:
    """e^{-2pi i k / n} for k = 0..n/2, host-exact float64."""
    k = np.arange(n // 2 + 1)
    return np.exp(-2j * np.pi * k / n).astype(cdtype)


def r2c_via_half_complex(x: jnp.ndarray, axis: int, c2c: C2CFn) -> jnp.ndarray:
    """Real-to-complex DFT along ``axis`` (extent n even) using a length-n/2
    complex transform from ``c2c``. Output extent n//2+1, unnormalized."""
    n = x.shape[axis]
    if n % 2:
        raise ValueError(f"half-complex packing needs even extent, got {n}")
    if jnp.issubdtype(jnp.dtype(x.dtype), jnp.complexfloating):
        raise ValueError(
            "half-complex packing takes REAL input; callers route complex "
            "operands through their promote-and-slice fallback"
        )
    h = n // 2
    cdtype = jnp.result_type(x.dtype, jnp.complex64)

    xm = jnp.moveaxis(x, axis, -1)
    pair = xm.reshape(xm.shape[:-1] + (h, 2))
    # lax.complex only accepts f32/f64 planes: low-precision reals
    # (bfloat16/float16) promote through the working dtype's real part.
    rdtype = jnp.finfo(cdtype).dtype
    z = lax.complex(pair[..., 0].astype(rdtype), pair[..., 1].astype(rdtype))
    Z = c2c(z, -1, True)

    Zf = jnp.concatenate([Z, Z[..., :1]], axis=-1)          # Z[h] = Z[0]
    Zr = jnp.conj(jnp.flip(Zf, axis=-1))                    # Z*[h-k]
    w = jnp.asarray(_twiddle(n, cdtype))
    X = 0.5 * (Zf + Zr) - 0.5j * w * (Zf - Zr)
    return jnp.moveaxis(X, -1, axis)


def c2r_via_half_complex(
    y: jnp.ndarray, n: int, axis: int, c2c: C2CFn
) -> jnp.ndarray:
    """Complex-to-real inverse DFT along ``axis`` back to true extent ``n``
    (even) from the n//2+1 hermitian half; scaled by 1/n (numpy
    convention). Uses a length-n/2 inverse complex transform from
    ``c2c``."""
    if n % 2:
        raise ValueError(f"half-complex packing needs even extent, got {n}")
    h = n // 2
    cdtype = jnp.result_type(y.dtype, jnp.complex64)

    ym = jnp.moveaxis(y, axis, -1).astype(cdtype)
    if ym.shape[-1] != h + 1:
        raise ValueError(
            f"expected {h + 1} hermitian coefficients for n={n}, "
            f"got {ym.shape[-1]}"
        )
    yr = jnp.conj(jnp.flip(ym, axis=-1))                    # Y*[h-k]
    # Invert the forward untangle: E = (Y[k]+Y*[h-k])/2 holds FFT(even),
    # O = (Y[k]-Y*[h-k]) * e^{+2pi i k/n} / 2 holds FFT(odd); the packed
    # half-length spectrum is Z = E + iO (k = 0..h-1).
    w = jnp.conj(jnp.asarray(_twiddle(n, cdtype)))
    E = 0.5 * (ym + yr)
    O = 0.5 * (ym - yr) * w
    Z = (E + 1j * O)[..., :h]
    # c2c's inverse 1/h scale recovers the packed samples exactly (the
    # unnormalized-forward / normalized-inverse pair is closed under the
    # packing), matching numpy's irfft(rfft(x)) == x.
    z = c2c(Z, -1, False)
    pair = jnp.stack([jnp.real(z), jnp.imag(z)], axis=-1)
    xm = pair.reshape(pair.shape[:-2] + (n,))
    return jnp.moveaxis(xm, -1, axis)
