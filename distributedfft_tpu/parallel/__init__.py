from .mesh import make_mesh, mesh_devices  # noqa: F401
