"""Arbitrary-box distributed reshape — the overlap-map engine.

heFFTe's reshape engine moves data between *arbitrary* non-overlapping box
decompositions of the same world: each rank intersects its input box with
every output box to build an overlap map, then ships exactly those
intersections (``heffte_reshape3d.h:51-53,60-498``; the MPI_Alltoallv
transport ``src/heffte_reshape3d.cpp:375``; pack/unpack
``heffte_pack3d.h``). :mod:`.reshape` covers the decompositions a
``PartitionSpec`` can name; this module covers the rest — any per-device
``Box3`` list, uneven, non-grid, axis-swapped.

TPU-native design. A brick decomposition is held as a *brick stack*: a
global array ``[P, *pad_shape]`` sharded one brick per device along the
mesh axis, each brick zero-padded to the common ``pad_shape`` (TPU
collectives require uniform block shapes; the pad is the equal-shard
analog of heFFTe's per-rank ragged buffers). The reshape runs under
``shard_map`` as a (P-1)-step ``ppermute`` ring — step ``s`` moves every
``in_box[i] ∩ out_box[(i+s) % P]`` overlap one ring hop — with all slice
geometry precomputed into plan-time tables (the overlap map). Each step's
block extent is the *maximum* overlap over the ring shift, so near-uniform
decompositions ship near-exact payloads; the receiver masks the block down
to the true intersection before merging, so padding never corrupts data.

Every step is a uniform distance-``s`` ring rotation on the ICI, and the
trace-time Python loop lets XLA overlap step ``s``'s transfer with step
``s+1``'s slice/merge work — the same overlap the reference gets from
``MPI_Waitany``-driven pipelining (``src/heffte_reshape3d.cpp:611``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover — jax < 0.7 spelling
    from jax.experimental.shard_map import shard_map as _shard_map

from ..geometry import Box3, find_world, world_complete

__all__ = [
    "BrickSpec",
    "plan_brick_reshape",
    "plan_bricks_to_spec",
    "plan_spec_to_bricks",
    "spec_boxes",
    "scatter_bricks",
    "gather_bricks",
    "pad_shape_for",
    "stack_pad_for",
    "reorder_stack",
]


def pad_shape_for(boxes: Sequence[Box3]) -> tuple[int, int, int]:
    """Common (max-extent) brick shape a stack must be padded to."""
    return tuple(max(b.shape[d] for b in boxes) for d in range(3))


def _check_algorithm(algorithm: str) -> None:
    if algorithm not in ("ring", "a2av"):
        raise ValueError(f"algorithm must be ring|a2av, got {algorithm!r}")


def _validate(boxes: Sequence[Box3], world: Box3, label: str) -> None:
    if not world_complete(boxes, world):
        raise ValueError(
            f"{label} boxes do not partition the world {world}: they must "
            f"be non-overlapping and cover every element exactly once"
        )


@dataclass(frozen=True)
class _Step:
    """One ring shift's overlap map (all numpy, resolved at plan time)."""

    shift: int
    block: tuple[int, int, int]       # max overlap extent this shift
    send_start: np.ndarray            # [P, 3] src-local overlap origin
    true_size: np.ndarray             # [P, 3] overlap extent per sender
    recv_start: np.ndarray            # [P, 3] dst-local overlap origin


@dataclass(frozen=True)
class BrickSpec:
    """Plan-time description of an arbitrary-box reshape.

    ``payload_bytes``/``wire_bytes`` expose the exact-overlap payload vs
    what the padded ring actually ships — the accounting heFFTe keeps in
    its per-pair ``send_size``/``recv_size`` tables.
    """

    in_boxes: tuple[Box3, ...]
    out_boxes: tuple[Box3, ...]
    world: Box3
    in_pad: tuple[int, int, int]
    out_pad: tuple[int, int, int]
    steps: tuple[_Step, ...]
    algorithm: str = "ring"   # "ring" (padded ppermute) | "a2av" (exact)
    # a2av plans skip the ring's step construction entirely; their payload
    # comes straight from the exact tables.
    payload_override: int | None = None
    # Per-device bytes of the a2av RLE index-map operands (None for the
    # ring): O(overlap cross-section), reported by plan_info so campaign
    # configs can see the footprint stays sublinear in brick volume.
    a2av_table_bytes: int | None = None

    @property
    def payload_elems(self) -> int:
        """True overlap elements crossing the wire (exact-table payload,
        self-overlaps excluded — they never leave the device)."""
        if self.payload_override is not None:
            return self.payload_override
        return sum(
            int(np.prod(st.true_size[i]))
            for st in self.steps if st.shift
            for i in range(len(self.in_boxes))
        )

    @property
    def wire_elems(self) -> int:
        """Elements actually shipped: the padded ring sends block * P per
        step; the a2av tier sends exactly the payload (ragged runs)."""
        if self.algorithm == "a2av":
            return self.payload_elems
        p = len(self.in_boxes)
        return sum(
            math.prod(st.block) * p for st in self.steps if st.shift
        )

    @property
    def wire_ratio(self) -> float:
        """wire/payload blowup of the padded ring (1.0 = exact tables).

        :func:`_overlap_steps` mitigates shape skew — a ring step whose
        sender overlap shapes are orthogonal (prod-of-maxes >> max
        volume) is split into shape-similar groups when that wins at
        least a ``_SPLIT_FACTOR`` wire reduction. The mitigation is
        best-effort, not a hard bound: the group cap can force-merge
        dissimilar shapes, and the ring's uniform-block cost itself
        (every shift ships P blocks sized to that group's largest
        overlap) always remains — heFFTe's alltoallv ships exact
        per-pair counts instead (``src/heffte_reshape3d.cpp:375``).
        This accounting makes the actual factor visible per plan
        (``plan_info`` prints it per edge); tests pin it <= P for the
        realistic uneven decompositions."""
        t = self.payload_elems
        return self.wire_elems / t if t else 1.0


# A ring step whose block (elementwise max over sender overlap shapes)
# holds more than this factor times the largest single overlap volume is
# shape-skewed — orthogonal overlap shapes like (a,1,1) vs (1,b,1) inflate
# prod-of-maxes far past any real payload — and gets split into
# shape-similar sender groups. Grouping trades one extra ppermute per
# group for a strictly smaller wire total; the cap bounds the added
# latency on pathological box sets.
_SPLIT_FACTOR = 2.0
_MAX_GROUPS_PER_SHIFT = 4


def _shape_groups(shapes: dict[int, np.ndarray]) -> list[list[int]]:
    """Partition senders into shape-similar groups: greedy best-fit by
    descending overlap volume, opening a new group when joining any
    existing one would inflate that group's block past _SPLIT_FACTOR x
    its largest member volume."""
    order = sorted(shapes, key=lambda i: -int(np.prod(shapes[i])))
    groups: list[dict] = []
    for i in order:
        sh = shapes[i]
        best, best_cost = None, None
        for g in groups:
            nb = np.maximum(g["block"], sh)
            cost = int(np.prod(nb))
            if cost <= _SPLIT_FACTOR * max(g["vol"], int(np.prod(sh))):
                if best is None or cost < best_cost:
                    best, best_cost = g, cost
        if best is None and len(groups) >= _MAX_GROUPS_PER_SHIFT:
            # Cap reached: fall into the group that inflates least.
            for g in groups:
                cost = int(np.prod(np.maximum(g["block"], sh)))
                if best is None or cost < best_cost:
                    best, best_cost = g, cost
        if best is None:
            groups.append({"members": [i], "block": sh.copy(),
                           "vol": int(np.prod(sh))})
        else:
            best["members"].append(i)
            best["block"] = np.maximum(best["block"], sh)
            best["vol"] = max(best["vol"], int(np.prod(sh)))
    return [g["members"] for g in groups]


def _overlap_steps(
    in_boxes: Sequence[Box3], out_boxes: Sequence[Box3]
) -> list[_Step]:
    p = len(in_boxes)
    steps: list[_Step] = []
    for s in range(p):
        send_start = np.zeros((p, 3), np.int32)
        true_size = np.zeros((p, 3), np.int32)
        recv_start = np.zeros((p, 3), np.int32)
        for i in range(p):
            dst = (i + s) % p
            o = in_boxes[i].intersect(out_boxes[dst])
            if o.empty:
                continue
            send_start[i] = np.subtract(o.low, in_boxes[i].low)
            true_size[i] = o.shape
            recv_start[dst] = np.subtract(o.low, out_boxes[dst].low)
        if not true_size.any():
            continue  # no pair exchanges at this shift
        # Shape-skew mitigation: split this shift's senders into
        # shape-similar groups when the joint block is inflated well past
        # the largest true overlap (the per-shift analog of heFFTe's
        # exact alltoallv counts, src/heffte_reshape3d.cpp:375). Each
        # group replays the same shift with the non-members' table rows
        # zeroed — the receiver keys every merge off the tables, so a
        # zero row is a no-op and correctness is untouched.
        active = {i: true_size[i] for i in range(p) if true_size[i].any()}
        joint = tuple(int(true_size[:, d].max()) for d in range(3))
        max_vol = max(int(np.prod(sh)) for sh in active.values())
        groups = [list(active)]
        if math.prod(joint) > _SPLIT_FACTOR * max_vol and len(active) > 1:
            cand = _shape_groups(active)
            if len(cand) > 1:
                # Adopt the split only for a real wire win (>= the same
                # factor that triggered it): each extra group costs a
                # full ppermute step on every device, so near-zero-gain
                # splits are a net slowdown on latency-bound edges.
                split_wire = sum(
                    math.prod(tuple(
                        int(max(true_size[i][d] for i in g))
                        for d in range(3)))
                    for g in cand
                )
                if split_wire * _SPLIT_FACTOR <= math.prod(joint):
                    groups = cand
        for members in groups:
            if len(groups) == 1:
                g_send, g_true, g_recv = send_start, true_size, recv_start
            else:
                g_send = np.zeros((p, 3), np.int32)
                g_true = np.zeros((p, 3), np.int32)
                g_recv = np.zeros((p, 3), np.int32)
                for i in members:
                    dst = (i + s) % p
                    g_send[i] = send_start[i]
                    g_true[i] = true_size[i]
                    g_recv[dst] = recv_start[dst]
            block = tuple(int(g_true[:, d].max()) for d in range(3))
            steps.append(_Step(s, block, g_send, g_true, g_recv))
    return steps


def _resolve_axes(mesh: Mesh, axis_name) -> tuple[tuple[str, ...], int]:
    """Normalize to a tuple of mesh axis names + their linearized size. The
    tuple order must follow ``mesh.axis_names`` so the linearized device id
    (``lax.axis_index(names)``) matches ``mesh.devices.flat`` ordering —
    the order every box list in this package uses."""
    if axis_name is None:
        names = tuple(mesh.axis_names)
    elif isinstance(axis_name, str):
        names = (axis_name,)
    else:
        names = tuple(axis_name)
    p = math.prod(mesh.shape[nm] for nm in names)
    return names, p


def _ring_reshape(
    x: jnp.ndarray,
    axis_names: tuple[str, ...],
    p: int,
    steps: Sequence[_Step],
    in_pad: tuple[int, int, int],
    out_pad: tuple[int, int, int],
    batch: int | None = None,
) -> jnp.ndarray:
    """The overlap-map ppermute ring over one local 3D brick (inside
    shard_map). All geometry comes from the plan-time ``steps`` tables.
    ``batch=B`` runs B independent bricks ``[B, *in_pad]`` through the
    SAME ring — the batch rides every ppermute as a leading bystander
    dim (the PR 6 leading-axis pattern: B transforms, one collective
    latency per step); ``None`` keeps the unbatched trace exactly."""
    i = lax.axis_index(axis_names)

    def _at(idx):
        # Slice starts with the leading batch axis prepended (the zero
        # matches the table dtype — x64 promotes the clamp arithmetic).
        if not batch:
            return tuple(idx)
        return (jnp.zeros((), idx.dtype),) + tuple(idx)

    def _ext(sz):
        return ((batch,) + tuple(sz)) if batch else tuple(sz)

    bo = 1 if batch else 0
    acc = jnp.zeros(_ext(out_pad), x.dtype)
    for st in steps:
        block = st.block
        sstart = jnp.asarray(st.send_start)
        tsize = jnp.asarray(st.true_size)
        rstart = jnp.asarray(st.recv_start)
        # Sender side: a static-extent block containing the overlap.
        # Starts are clamped so the block stays in bounds; the overlap
        # then sits at offset d = start - clamped inside the block
        # (d + true <= block always, since clamped <= pad - block).
        my_st = sstart[i]
        clamp_s = jnp.minimum(
            my_st, jnp.asarray(in_pad, jnp.int32) - jnp.asarray(block))
        blk = lax.dynamic_slice(x, _at(clamp_s), _ext(block))
        if st.shift:
            blk = lax.ppermute(
                blk, axis_names,
                perm=[(j, (j + st.shift) % p) for j in range(p)],
            )
        # Receiver side: the peer's slice geometry comes from the same
        # tables (indexed by src id), not from the wire.
        src = (i - st.shift) % p
        st_src = sstart[src]
        d = st_src - jnp.minimum(
            st_src, jnp.asarray(in_pad, jnp.int32) - jnp.asarray(block))
        true = tsize[src]
        my_r = rstart[i]
        clamp_r = jnp.minimum(
            my_r, jnp.asarray(out_pad, jnp.int32) - jnp.asarray(block))
        d2 = my_r - clamp_r
        # Align the overlap to its destination offset inside the block,
        # mask everything else, and merge read-modify-write. The 3D
        # mask broadcasts over the leading batch axis.
        for ax in range(3):
            blk = jnp.roll(blk, d2[ax] - d[ax], axis=ax + bo)
        mask = jnp.ones(block, bool)
        for ax in range(3):
            idx = lax.broadcasted_iota(jnp.int32, block, ax)
            mask &= (idx >= d2[ax]) & (idx < d2[ax] + true[ax])
        region = lax.dynamic_slice(acc, _at(clamp_r), _ext(block))
        acc = lax.dynamic_update_slice(
            acc, jnp.where(mask, blk, region), _at(clamp_r))
    return acc


# ---------------------------------------------- exact-count (a2av) tier

@dataclass(frozen=True)
class _A2AVTables:
    """Plan-time tables of the exact-count brick transport (all numpy).

    SPMD programs need uniform static shapes, so per-device geometry
    travels as *data*: each device gets its own rows of RUN-LENGTH
    encoded gather/scatter maps plus its offset/size rows for
    ``lax.ragged_all_to_all``. An overlap box decomposes into
    constant-stride z-runs (one per (x, y) cross-section point), so the
    shipped tables are O(volume / nz) — cross-section, not volume — and
    the element index maps are expanded on device by
    :func:`_expand_runs` (a searchsorted over the run ends). Each run r
    is (``*_start[r]``: flat index of its first element;
    ``*_end[r]``: cumulative element count through r). Only the true
    run sizes cross the wire — the heFFTe ``alltoallv`` exact-count
    discipline (``src/heffte_reshape3d.cpp:375``, whose O(P)
    count/offset tables this generalizes to arbitrary boxes)."""

    pack_start: np.ndarray    # [P, Rs] int32: send z-run flat starts
    pack_end: np.ndarray      # [P, Rs] int32: cumulative send elements
    unpack_start: np.ndarray  # [P, Ru] int32: recv z-run flat starts
    unpack_end: np.ndarray    # [P, Ru] int32: cumulative recv elements
    # CPU-emulation gather runs, one per (sender, dest) pair: kept as
    # (sender row, start offset within that row) int32 pairs so indexing
    # the 2D all_gathered buffer never needs a flat index past int32
    # (jnp would silently downcast an int64 table with x64 off).
    gather_row: np.ndarray    # [P, Rg] int32: sender index per run
    gather_off: np.ndarray    # [P, Rg] int32: start within sender's buffer
    gather_end: np.ndarray    # [P, Rg] int32: cumulative elements
    send_off: np.ndarray      # [P, P] int32: run start in sender i's buffer
    sizes: np.ndarray         # [P, P] int64: elements i -> d
    out_off: np.ndarray       # [P, P] int32: landing offset of i's run at d
    send_cap: int
    recv_cap: int

    @property
    def table_bytes_per_device(self) -> int:
        """Bytes of index-map operands each device ships on the ragged
        (hardware) path — the footprint ``plan_info`` reports; sublinear
        in brick volume for grid-run boxes, scaling with the overlap
        cross-sections. The CPU emulation adds its three [Rg] int32
        gather rows (Rg <= P), not counted here."""
        p = self.sizes.shape[0]
        return int(self.pack_start.shape[1] * 8     # start+end int32
                   + self.unpack_start.shape[1] * 8
                   + 4 * p * 4)                     # off/size int32 rows


def _pack_runs(rows: list[list[tuple[int, int]]], dtype=np.int32):
    """[(flat_start, length), ...] per device -> padded (start, end)
    arrays. ``end`` is the cumulative element count (monotone; padding
    repeats the last end so searchsorted never lands on a pad run)."""
    p = len(rows)
    rcap = max(1, max((len(r) for r in rows), default=1))
    start = np.zeros((p, rcap), dtype)
    end = np.zeros((p, rcap), dtype)
    for i, runs in enumerate(rows):
        c = 0
        for r, (s, ln) in enumerate(runs):
            start[i, r] = s
            c += ln
            end[i, r] = c
        end[i, len(runs):] = c
    return start, end


def _a2av_tables(
    in_boxes: Sequence[Box3], out_boxes: Sequence[Box3],
    in_pad: tuple[int, int, int], out_pad: tuple[int, int, int],
) -> _A2AVTables:
    p = len(in_boxes)
    sizes = np.zeros((p, p), np.int64)
    overlaps: dict[tuple[int, int], Box3] = {}
    for i in range(p):
        for d in range(p):
            o = in_boxes[i].intersect(out_boxes[d])
            if o.empty:
                continue
            sizes[i, d] = o.size
            overlaps[(i, d)] = o
    send_tot = sizes.sum(axis=1)
    recv_tot = sizes.sum(axis=0)
    send_cap = int(send_tot.max()) if p else 0
    recv_cap = int(recv_tot.max()) if p else 0
    send_off = np.zeros((p, p), np.int32)
    out_off = np.zeros((p, p), np.int32)
    for i in range(p):
        send_off[i] = np.concatenate(
            ([0], np.cumsum(sizes[i])[:-1])).astype(np.int32)
    for d in range(p):
        out_off[:, d] = np.concatenate(
            ([0], np.cumsum(sizes[:, d])[:-1])).astype(np.int32)

    def z_runs(o: Box3, low_ref, pad) -> list[tuple[int, int]]:
        # C-order z-runs of the overlap box relative to a padded brick:
        # one run per (x, y) point, all of length nz, consecutive in
        # exactly the element order the old per-element maps used.
        nz = o.high[2] - o.low[2]
        xs = np.arange(o.low[0] - low_ref[0], o.high[0] - low_ref[0])
        ys = np.arange(o.low[1] - low_ref[1], o.high[1] - low_ref[1])
        base = (xs[:, None] * (pad[1] * pad[2])
                + ys[None, :] * pad[2]
                + (o.low[2] - low_ref[2])).ravel()
        return [(int(b), nz) for b in base]

    pack_rows: list[list[tuple[int, int]]] = [[] for _ in range(p)]
    unpack_rows: list[list[tuple[int, int]]] = [[] for _ in range(p)]
    gather_rows: list[list[tuple[int, int]]] = [[] for _ in range(p)]
    for i in range(p):
        for d in range(p):
            if (i, d) not in overlaps:
                continue
            o = overlaps[(i, d)]
            pack_rows[i].extend(z_runs(o, in_boxes[i].low, in_pad))
    for d in range(p):
        for i in range(p):
            if (i, d) not in overlaps:
                continue
            o = overlaps[(i, d)]
            unpack_rows[d].extend(z_runs(o, out_boxes[d].low, out_pad))
            # Emulation gather: sender i's run sits contiguous at
            # offset send_off[i, d] in row i of the all_gathered buffer —
            # ONE run per pair, stored as (row, offset) int32.
            gather_rows[d].append(
                ((i, int(send_off[i, d])), int(sizes[i, d])))
    pack_start, pack_end = _pack_runs(pack_rows)
    unpack_start, unpack_end = _pack_runs(unpack_rows)
    grow_rows = [[(r, ln) for (r, _), ln in row] for row in gather_rows]
    goff_rows = [[(off, ln) for (_, off), ln in row] for row in gather_rows]
    gather_row, gather_end = _pack_runs(grow_rows)
    gather_off, _ = _pack_runs(goff_rows)
    return _A2AVTables(pack_start, pack_end, unpack_start, unpack_end,
                       gather_row, gather_off, gather_end,
                       send_off, sizes, out_off, send_cap, recv_cap)


def _a2av_payload(t: _A2AVTables) -> int:
    """Off-device elements the exact transport ships (diagonal self-runs
    never leave the device)."""
    return int(t.sizes.sum() - np.trace(t.sizes))


def _expand_runs(start_row, end_row, cap: int, fill):
    """Expand one device's RLE map rows into a [cap] element-index
    vector: slot s of the buffer belongs to run r = the first run whose
    cumulative end exceeds s, at offset s - end[r-1]. Padding slots
    (s >= total elements) get ``fill`` (0 for harmless gathers, the
    out-of-range sentinel for ``mode='drop'`` scatters). O(cap log R)
    integer work per execute — traded for shipping O(R) instead of
    O(cap) table operands (R = overlap cross-section, not volume)."""
    r, off, valid = _run_slots(end_row, cap)
    idx = (start_row[r] + off).astype(start_row.dtype)
    return jnp.where(valid, idx, fill)


def _run_slots(end_row, cap: int):
    """Shared run-expansion core: for each buffer slot s in [0, cap),
    (run index, offset within that run, validity). Slot s belongs to the
    first run whose cumulative end exceeds s; slots past the final end
    are invalid (padding)."""
    ce = end_row
    cs = jnp.concatenate([jnp.zeros((1,), ce.dtype), ce[:-1]])
    s = jnp.arange(cap, dtype=ce.dtype)
    r = jnp.minimum(jnp.searchsorted(ce, s, side="right"),
                    ce.shape[0] - 1)
    return r, s - cs[r], s < ce[-1]


def _a2av_reshape(
    x: jnp.ndarray,
    pack_rows: tuple[jnp.ndarray, jnp.ndarray],    # [1, Rs] x2 RLE rows
    unpack_rows: tuple[jnp.ndarray, jnp.ndarray],  # [1, Ru] x2 RLE rows
    count_rows: tuple[jnp.ndarray, ...],  # [1, P] x4 off/size rows
    gather_rows,  # [1, Rg] x3 (row, off, end) rows (CPU) | None on TPU
    axis_names: tuple[str, ...],
    t: _A2AVTables,
    out_pad: tuple[int, int, int],
    platform: str,
    batch: int | None = None,
) -> jnp.ndarray:
    """The exact-count reshape of one local brick (inside shard_map).
    Every per-device table arrives as a SHARDED OPERAND (one row per
    device): the RLE index maps (O(cross-section) bytes, expanded to
    element indices on device by :func:`_expand_runs`) and the ragged
    off/size rows (O(P) each), so neither the executable nor the
    operands carry O(P x anything) constants. On backends without the
    ragged op (XLA:CPU, unless force_real_lowering), an all_gather
    emulation with the *same tables* stands in — so the CPU tests
    exercise every run map, and only the collective itself differs on
    hardware. ``platform`` is the mesh devices' platform, resolved at
    plan time (a CPU-device mesh under a non-CPU default backend must
    still take the emulation path). ``batch=B`` reshapes B bricks
    ``[B, *pad]`` through ONE collective — the batch rides the run
    buffers as a trailing dim (the ragged axis must stay leading), so
    the index tables are expanded once and shared by all B."""
    from ..utils.compat import force_real_lowering

    scap = max(t.send_cap, 1)
    rcap = max(t.recv_cap, 1)
    pack_idx = _expand_runs(pack_rows[0][0], pack_rows[1][0], scap, 0)
    if batch:
        # [B, *pad] -> [send_cap, B]: run slots lead, batch trails.
        sendbuf = x.reshape(batch, -1)[:, pack_idx].T
    else:
        sendbuf = x.reshape(-1)[pack_idx]  # [send_cap]

    if platform == "cpu" and not force_real_lowering():
        # Emulation: gather every sender's buffer, then assemble my
        # receive buffer from the same offset tables via a 2D gather
        # ((sender row, column) pairs — never a flat index, so int32
        # suffices at any world size).
        grow, goff, gend = (a[0] for a in gather_rows)
        rr, off, valid = _run_slots(gend, rcap)
        row = jnp.where(valid, grow[rr], 0)
        col = jnp.where(valid, goff[rr] + off, 0)
        ag = lax.all_gather(sendbuf, axis_names)  # [P, send_cap(, B)]
        y = ag[row, col]
    else:
        out = jnp.zeros((rcap, batch) if batch else (rcap,), x.dtype)
        soff, ssz, ooff, rsz = (a[0] for a in count_rows)
        y = lax.ragged_all_to_all(
            sendbuf, out, soff, ssz, ooff, rsz, axis_name=axis_names)
    sentinel = jnp.int32(math.prod(out_pad))
    unpack_idx = _expand_runs(
        unpack_rows[0][0], unpack_rows[1][0], rcap, sentinel)
    accf = jnp.zeros((math.prod(out_pad), batch) if batch
                     else (math.prod(out_pad),), x.dtype)
    # Sentinel indices on padding slots fall out of bounds and drop.
    accf = accf.at[unpack_idx].set(y, mode="drop")
    if batch:
        return accf.T.reshape((batch,) + tuple(out_pad))
    return accf.reshape(out_pad)


def _a2av_mapped(
    mesh: Mesh,
    names: tuple[str, ...],
    p: int,
    tables: _A2AVTables,
    out_pad: tuple[int, int, int],
    data_in_spec: P,
    data_out_spec: P,
    squeeze_in: bool,
    expand_out: bool,
    batch: int | None = None,
) -> Callable:
    """Build ``fn(x)`` for the a2av transport: every per-device table —
    RLE run rows AND the ragged off/size rows — travels as a shard_map
    operand sharded one row per device (the emulation gather rows only
    on CPU meshes, where the ragged op cannot lower). ``batch=B``
    expects the caller's data specs batch-adjusted (leading replicated
    axis); the tables stay unbatched — one run map serves all B."""
    platform = mesh.devices.flat[0].platform
    row = P(names, None)
    sz32 = tables.sizes.astype(np.int32)
    operands = [jnp.asarray(tables.pack_start),
                jnp.asarray(tables.pack_end),
                jnp.asarray(tables.unpack_start),
                jnp.asarray(tables.unpack_end),
                jnp.asarray(tables.send_off), jnp.asarray(sz32),
                jnp.asarray(tables.out_off), jnp.asarray(sz32.T.copy())]
    with_gather = platform == "cpu"
    if with_gather:
        operands += [jnp.asarray(tables.gather_row),
                     jnp.asarray(tables.gather_off),
                     jnp.asarray(tables.gather_end)]

    def _local(x, ps, pe, us, ue, soff, ssz, ooff, rsz, *g):
        if squeeze_in:
            v = x[:, 0] if batch else x[0]
        else:
            v = x
        y = _a2av_reshape(v, (ps, pe), (us, ue), (soff, ssz, ooff, rsz),
                          g or None, names, tables, out_pad, platform,
                          batch=batch)
        if expand_out:
            return y[:, None] if batch else y[None]
        return y

    mapped = _shard_map(
        _local, mesh=mesh,
        in_specs=(data_in_spec,) + (row,) * len(operands),
        out_specs=data_out_spec,
    )
    return lambda x: mapped(x, *operands)


def plan_brick_reshape(
    mesh: Mesh,
    in_boxes: Sequence[Box3],
    out_boxes: Sequence[Box3],
    *,
    axis_name: str | Sequence[str] | None = None,
    jit: bool = True,
    algorithm: str = "ring",
) -> tuple[Callable, BrickSpec]:
    """Compile an arbitrary-box reshape over one or more mesh axes.

    Returns ``(fn, spec)`` where ``fn`` maps an in-brick stack
    ``[P, *spec.in_pad]`` (sharded along ``axis_name``, default all mesh
    axes linearized) to the out-brick stack ``[P, *spec.out_pad]``. The
    analog of constructing a ``reshape3d_alltoallv`` object from the in/out
    box lists (``heffte_reshape3d.h:60-170``): all overlap maps are
    resolved here, execution only replays them.

    ``algorithm`` picks the transport: ``"ring"`` (default) ships padded
    uniform blocks over a ppermute ring (pipelinable, p2p-like);
    ``"a2av"`` ships exactly the true overlap runs via
    ``lax.ragged_all_to_all`` — the heFFTe exact-count ``alltoallv``
    (``src/heffte_reshape3d.cpp:375``; wire == payload, see
    ``BrickSpec.wire_ratio``).
    """
    _check_algorithm(algorithm)
    names, p = _resolve_axes(mesh, axis_name)
    if len(in_boxes) != p or len(out_boxes) != p:
        raise ValueError(
            f"need exactly one in/out box per device on axes "
            f"{names!r} (P={p}); got {len(in_boxes)}/{len(out_boxes)}"
        )
    world = find_world(in_boxes)
    _validate(in_boxes, world, "input")
    _validate(out_boxes, world, "output")

    in_pad = pad_shape_for(in_boxes)
    out_pad = pad_shape_for(out_boxes)
    if algorithm == "a2av":
        tables = _a2av_tables(in_boxes, out_boxes, in_pad, out_pad)
        spec = BrickSpec(tuple(in_boxes), tuple(out_boxes), world, in_pad,
                         out_pad, (), algorithm,
                         payload_override=_a2av_payload(tables),
                         a2av_table_bytes=tables.table_bytes_per_device)
        fn = _a2av_mapped(mesh, names, p, tables, out_pad,
                          P(names), P(names),
                          squeeze_in=True, expand_out=True)
    else:
        steps = _overlap_steps(in_boxes, out_boxes)
        spec = BrickSpec(tuple(in_boxes), tuple(out_boxes), world, in_pad,
                         out_pad, tuple(steps), algorithm)

        def _local(x: jnp.ndarray) -> jnp.ndarray:
            return _ring_reshape(x[0], names, p, steps, in_pad,
                                 out_pad)[None]

        fn = _shard_map(
            _local, mesh=mesh,
            in_specs=P(names), out_specs=P(names),
        )
    if jit:
        fn = jax.jit(fn)
    return fn, spec


# ------------------------------------------------- brick <-> sharded global

def spec_boxes(mesh: Mesh, spec: P, world: Box3) -> list[Box3]:
    """Per-device shard boxes of a PartitionSpec layout, in
    ``mesh.devices.flat`` order (derived from the sharding's own index map,
    so they can never diverge from XLA's placement)."""
    shape = world.shape
    index_map = NamedSharding(mesh, spec).devices_indices_map(shape)
    boxes = []
    for dev in mesh.devices.flat:
        idxs = index_map[dev]
        low = tuple(world.low[d] + (ix.start or 0) for d, ix in enumerate(idxs))
        high = tuple(
            world.low[d] + (ix.stop if ix.stop is not None else shape[d])
            for d, ix in enumerate(idxs)
        )
        boxes.append(Box3(low, high))
    return boxes


def _even_spec_boxes(mesh: Mesh, spec: P, world: Box3, label: str):
    """Shard boxes of ``spec``, validated uniform (even divide) and one per
    device — the requirement for a shard_map-constructed true global."""
    entries = tuple(spec) + (None,) * (3 - len(tuple(spec)))
    for d, entry in enumerate(entries):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        k = math.prod(mesh.shape[nm] for nm in names)
        if world.shape[d] % k:
            raise ValueError(
                f"{label} layout {spec} does not divide {world.shape} into "
                f"uniform shards (dim {d}: {world.shape[d]} % {k} != 0); "
                f"pick a mesh whose axes divide the extents"
            )
    boxes = spec_boxes(mesh, spec, world)
    shapes = {b.shape for b in boxes}
    if len(shapes) != 1:
        raise ValueError(
            f"{label} layout {spec} does not divide {world.shape} into "
            f"uniform shards; pick a mesh whose axes divide the extents"
        )
    if len(set(boxes)) != len(boxes):
        raise ValueError(
            f"{label} layout {spec} leaves some mesh axes unused "
            f"(duplicate shard boxes); bricks need one distinct box per "
            f"device"
        )
    return boxes, boxes[0].shape


def plan_bricks_to_spec(
    mesh: Mesh,
    in_boxes: Sequence[Box3],
    to_spec: P,
    *,
    jit: bool = False,
    algorithm: str = "ring",
    batch: int | None = None,
) -> tuple[Callable, BrickSpec]:
    """Arbitrary in-bricks -> a true global array sharded by ``to_spec``.

    The entry edge of a brick-I/O FFT plan: the overlap reshape lands
    each device's shard of the ``to_spec`` layout, and shard_map's
    out_specs reassemble the true (unpadded) global — which requires
    ``to_spec`` to divide the world evenly. ``algorithm`` as in
    :func:`plan_brick_reshape`.

    ``batch=B`` (the PR 6 leading-axis pattern) maps a batched brick
    stack ``[B, P, *pad]`` to ``[B, *world]``: B independent reshapes
    through the SAME collectives, the batch riding every ppermute /
    ragged exchange as a bystander dim (one collective latency per
    ring step for all B). ``batch=1`` normalizes to the unbatched plan
    — byte-identical HLO, pinned. ``spec`` accounting stays per
    transform (the wire ships ``payload x B``)."""
    _check_algorithm(algorithm)
    from .slab import batch_pspec, check_batch

    batch = check_batch(batch)
    if batch == 1:
        batch = None
    world = find_world(in_boxes)
    _validate(in_boxes, world, "input")
    out_boxes, shard_shape = _even_spec_boxes(mesh, to_spec, world, "target")
    names, p = _resolve_axes(mesh, None)
    if len(in_boxes) != p:
        raise ValueError(f"need {p} input bricks, got {len(in_boxes)}")
    in_pad = pad_shape_for(in_boxes)
    in_spec = batch_pspec(P(names), batch)
    out_spec = batch_pspec(to_spec, batch)
    if algorithm == "a2av":
        tables = _a2av_tables(in_boxes, out_boxes, in_pad, shard_shape)
        spec = BrickSpec(tuple(in_boxes), tuple(out_boxes), world, in_pad,
                         shard_shape, (), algorithm,
                         payload_override=_a2av_payload(tables),
                         a2av_table_bytes=tables.table_bytes_per_device)
        fn = _a2av_mapped(mesh, names, p, tables, shard_shape,
                          in_spec, out_spec,
                          squeeze_in=True, expand_out=False, batch=batch)
    else:
        steps = _overlap_steps(in_boxes, out_boxes)
        spec = BrickSpec(tuple(in_boxes), tuple(out_boxes), world, in_pad,
                         shard_shape, tuple(steps), algorithm)

        def _local(x: jnp.ndarray) -> jnp.ndarray:
            v = x[:, 0] if batch else x[0]
            return _ring_reshape(v, names, p, steps, in_pad, shard_shape,
                                 batch=batch)

        fn = _shard_map(_local, mesh=mesh, in_specs=in_spec,
                        out_specs=out_spec)
    if jit:
        fn = jax.jit(fn)
    return fn, spec


def plan_spec_to_bricks(
    mesh: Mesh,
    from_spec: P,
    out_boxes: Sequence[Box3],
    *,
    jit: bool = False,
    algorithm: str = "ring",
    batch: int | None = None,
) -> tuple[Callable, BrickSpec]:
    """A true global array sharded by ``from_spec`` -> arbitrary out-bricks
    (the exit edge of a brick-I/O FFT plan). ``from_spec`` must divide the
    world evenly. ``algorithm`` as in :func:`plan_brick_reshape`;
    ``batch`` as in :func:`plan_bricks_to_spec` (``[B, *world]`` ->
    ``[B, P, *pad]``; ``batch=1`` = the unbatched plan, byte-identical
    HLO)."""
    _check_algorithm(algorithm)
    from .slab import batch_pspec, check_batch

    batch = check_batch(batch)
    if batch == 1:
        batch = None
    world = find_world(out_boxes)
    _validate(out_boxes, world, "output")
    in_boxes, shard_shape = _even_spec_boxes(mesh, from_spec, world, "source")
    names, p = _resolve_axes(mesh, None)
    if len(out_boxes) != p:
        raise ValueError(f"need {p} output bricks, got {len(out_boxes)}")
    out_pad = pad_shape_for(out_boxes)
    in_spec = batch_pspec(from_spec, batch)
    out_spec = batch_pspec(P(names), batch)
    if algorithm == "a2av":
        tables = _a2av_tables(in_boxes, out_boxes, shard_shape, out_pad)
        spec = BrickSpec(tuple(in_boxes), tuple(out_boxes), world,
                         shard_shape, out_pad, (), algorithm,
                         payload_override=_a2av_payload(tables),
                         a2av_table_bytes=tables.table_bytes_per_device)
        fn = _a2av_mapped(mesh, names, p, tables, out_pad,
                          in_spec, out_spec,
                          squeeze_in=False, expand_out=True, batch=batch)
    else:
        steps = _overlap_steps(in_boxes, out_boxes)
        spec = BrickSpec(tuple(in_boxes), tuple(out_boxes), world,
                         shard_shape, out_pad, tuple(steps), algorithm)

        def _local(x: jnp.ndarray) -> jnp.ndarray:
            y = _ring_reshape(x, names, p, steps, shard_shape,
                              out_pad, batch=batch)
            return y[:, None] if batch else y[None]

        fn = _shard_map(_local, mesh=mesh, in_specs=in_spec,
                        out_specs=out_spec)
    if jit:
        fn = jax.jit(fn)
    return fn, spec


# ------------------------------------------------------- host-side helpers

def scatter_bricks(
    x: np.ndarray, boxes: Sequence[Box3],
    pad: tuple[int, int, int] | None = None,
    mesh: Mesh | None = None, axis_name: str | None = None,
):
    """Host world array -> brick stack [P, *pad] (device-put if mesh given).

    The test/IO-side analog of heFFTe's input gathering; production code
    builds brick stacks directly on device.
    """
    if pad is None:
        pad = stack_pad_for(boxes)
    stack = np.zeros((len(boxes),) + tuple(pad), x.dtype)
    for i, b in enumerate(boxes):
        s = b.storage_shape
        stack[i, : s[0], : s[1], : s[2]] = x[b.slices()].transpose(b.order)
    if mesh is None:
        return stack
    names, _ = _resolve_axes(mesh, axis_name)
    return jax.device_put(
        stack, NamedSharding(mesh, P(names, None, None, None)))


def gather_bricks(stack, boxes: Sequence[Box3]) -> np.ndarray:
    """Brick stack [P, *pad] -> host world array (test/verification side).
    Each brick is read in its box's declared storage ``order``."""
    world = find_world(boxes)
    out = np.zeros(world.shape, np.asarray(stack[0]).dtype)
    arr = np.asarray(stack)
    for i, b in enumerate(boxes):
        s = b.storage_shape
        out[b.slices()] = arr[i, : s[0], : s[1], : s[2]].transpose(
            _inv_perm(b.order))
    return out


# --------------------------------------------------- per-box storage order

def stack_pad_for(boxes: Sequence[Box3]) -> tuple[int, int, int]:
    """Common padded shape of a *user-facing* brick stack: max extents of
    the boxes' storage shapes (``Box3.order`` applied). Identity orders
    collapse to :func:`pad_shape_for`."""
    return tuple(max(b.storage_shape[d] for b in boxes) for d in range(3))


def _inv_perm(order) -> tuple[int, int, int]:
    """Inverse of a 3-axis permutation: transpose(order) then
    transpose(_inv_perm(order)) is the identity."""
    return tuple(sorted(range(3), key=lambda a: order[a]))


def has_orders(boxes: Sequence[Box3]) -> bool:
    return any(tuple(b.order) != (0, 1, 2) for b in boxes)


def _fix_extents(x: jnp.ndarray, pad: tuple[int, int, int]) -> jnp.ndarray:
    """Crop/zero-pad each axis of a 3D block to ``pad`` (true brick data
    lives at the low corner and fits either way)."""
    for a, want in enumerate(pad):
        if x.shape[a] > want:
            x = lax.slice_in_dim(x, 0, want, axis=a)
        elif x.shape[a] < want:
            w = [(0, 0)] * 3
            w[a] = (0, want - x.shape[a])
            x = jnp.pad(x, w)
    return x


def reorder_stack(
    mesh: Mesh,
    boxes: Sequence[Box3],
    *,
    to_canonical: bool,
    axis_name=None,
):
    """Device-side order edge for brick stacks (heFFTe ``transpose_packer``
    / ``plan_options::use_reorder`` role, ``heffte_pack3d.h:116``,
    ``heffte_plan_logic.h:69-89``, applied at the user I/O boundary).

    Returns a shard_map'd function mapping a brick stack between the
    callers' declared storage orders and canonical (x, y, z) axis order:

    * ``to_canonical=True``: ``[P, *stack_pad_for]`` (each brick stored as
      ``canonical.transpose(box.order)``) -> ``[P, *pad_shape_for]``.
    * ``to_canonical=False``: the inverse edge for plan outputs.

    Each device's permutation is static plan data; inside ``shard_map``
    the per-device transpose is selected by ``lax.switch`` on the
    linearized device index (XLA dedups identical branches, so the
    common all-identity-but-one case stays small). Returns ``None`` when
    every order is the identity (no edge needed).
    """
    if not has_orders(boxes):
        return None
    names, p = _resolve_axes(mesh, axis_name)
    if len(boxes) != p:
        raise ValueError(f"need {p} boxes, got {len(boxes)}")
    spad = stack_pad_for(boxes)
    cpad = pad_shape_for(boxes)

    def branch(order):
        inv = _inv_perm(order)

        def run(block):
            if to_canonical:
                return _fix_extents(jnp.transpose(block, inv), cpad)
            return _fix_extents(jnp.transpose(block, order), spad)

        return run

    branches = [branch(tuple(b.order)) for b in boxes]

    def local(x):
        i = lax.axis_index(names)
        return lax.switch(i, branches, x[0])[None]

    return _shard_map(local, mesh=mesh, in_specs=P(names, None, None, None),
                      out_specs=P(names, None, None, None))
