"""Slab- and pencil-decomposed distributed 3D FFT at the emulated-f64
(dd) tier.

The reference's distributed engine is double precision end to end
(``3dmpifft_opt`` computes f64 C2C across GPUs; accuracy gate 1e-11,
``test_common.h:138``). The TPU chips this framework targets have no f64
— the c64 slab pipeline (``parallel/slab.py``) covers the speed tier, and
this module carries the dd (double-double + exact-sliced bf16 matmul,
:mod:`..ops.ddfft`) engine across the mesh so the *accuracy* tier is
distributed too: same t0..t3 taxonomy, with each stage transforming a
(hi, lo) pair and the t2 global transpose moving both components through
the same ``all_to_all`` collectives.

Shapes follow the c64 pipeline's ceil-pad/crop discipline (zero rows are
exact in dd arithmetic, so padding cannot perturb the tier). Axis extents
follow the dd engine's coverage: dense through ``ddfft.DD_DENSE_MAX``,
four-step beyond it for lengths whose factor pairs fit (1024, 2048, ...).
"""

from __future__ import annotations

import functools

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from ..geometry import pad_to  # noqa: F401 — used by the r2c chains
from ..ops import ddfft
from ..utils.trace import add_trace, trace_stages
from .exchange import (
    _crop_axis, _pad_axis, exchange_chunked, exchange_overlapped,
)
from .pencil import PencilSpec, chain_geometry
from .slab import SlabSpec, batch_pspec, check_batch


def _check_dd_extent(n: int, shape) -> None:
    # Every per-axis transform in these pipelines is full-length local,
    # so the coverage rule is exactly fft_axis_dd's: dense, four-step,
    # or Bluestein (prime factors above 512, padded length <= 512^2).
    if (n > ddfft.DD_DENSE_MAX and ddfft._dd_split(n) is None
            and ddfft._dd_bluestein_m(n) is None):
        raise ValueError(
            f"dd pipeline: axis length {n} has no dense-coverable "
            f"four-step split and exceeds the Bluestein pad bound "
            f"(shape {tuple(shape)})"
        )


def build_dd_slab_fft3d(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    axis_name: str = "slab",
    forward: bool = True,
    algorithm: str = "alltoall",
    donate: bool = False,
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[Callable, SlabSpec]:
    """Jitted distributed dd 3D C2C transform over a 1D mesh.

    Returns ``(fn, spec)`` with ``fn(hi, lo) -> (hi, lo)``: complex64
    double-double pairs of the global ``[N0, N1, N2]`` array, input
    sharded along axis 0 forward (axis 1 backward) exactly like the c64
    slab plan. Forward is unnormalized; backward applies the numpy 1/n
    per axis (inside the dd engine, exact power-of-two post-scales).
    ``batch=B`` prepends a leading batch axis to BOTH dd components with
    one shared pair of collectives per batch — the
    :func:`..slab.build_slab_general` convention at the accuracy tier.
    """
    shape = tuple(int(s) for s in shape)
    for n in shape:
        _check_dd_extent(n, shape)
    check_batch(batch)
    bo = 0 if batch is None else 1  # leading-batch axis offset
    p = mesh.shape[axis_name]
    in_axis, out_axis = (0, 1) if forward else (1, 0)
    spec = SlabSpec(shape, p, axis_name, in_axis, out_axis)
    n_in, n_out = shape[in_axis], shape[out_axis]
    n_inp = pad_to(n_in, p)
    local_axes = tuple(a for a in range(3) if a != in_axis)
    platform = mesh.devices.flat[0].platform
    ax_in, ax_out = in_axis + bo, out_axis + bo

    def t3_chunk(pair):
        hi, lo = pair
        hi = _crop_axis(hi, ax_in, n_in)
        lo = _crop_axis(lo, ax_in, n_in)
        # t3: dd transform of the now-local lines.
        return ddfft.fft_axis_dd(hi, lo, ax_in, forward=forward)

    def local_fn(hi, lo):
        # t0: dd transforms of the device-local planes.
        with add_trace("t0_dd_fft_planes"):
            for ax in local_axes:
                hi, lo = ddfft.fft_axis_dd(hi, lo, ax + bo, forward=forward)
        # t1+t2: both dd components ride the same global transpose the
        # c64 pipeline uses (XLA schedules the two collectives back to
        # back on the ICI); overlap_chunks > 1 pipelines each chunk's
        # pair of collectives under the previous chunk's t3.
        return exchange_overlapped(
            (hi, lo), axis_name, split_axis=ax_out, concat_axis=ax_in,
            axis_size=p, algorithm=algorithm, wire_dtype=wire_dtype, platform=platform,
            compute=t3_chunk, overlap_chunks=overlap_chunks,
            chunk_axis=3 - in_axis - out_axis + bo,
            exchange_name=f"t2_exchange_{axis_name}",
            compute_name="t3_dd_fft_lines")

    in_spec = batch_pspec(spec.in_pspec, batch)
    out_spec = batch_pspec(spec.out_pspec, batch)
    mapped = _shard_map(local_fn, mesh=mesh,
                        in_specs=(in_spec, in_spec),
                        out_specs=(out_spec, out_spec))
    in_sh = NamedSharding(mesh, in_spec)

    @functools.partial(
        jax.jit, donate_argnums=(0, 1) if donate else ())
    def fn(hi, lo):
        hi = _pad_axis(hi, ax_in, n_inp)
        lo = _pad_axis(lo, ax_in, n_inp)
        hi = lax.with_sharding_constraint(hi, in_sh)
        lo = lax.with_sharding_constraint(lo, in_sh)
        hi, lo = mapped(hi, lo)
        return (_crop_axis(hi, ax_out, n_out),
                _crop_axis(lo, ax_out, n_out))

    return fn, spec


def build_dd_slab_rfft3d(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    axis_name: str = "slab",
    forward: bool = True,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[Callable, SlabSpec]:
    """Slab-distributed dd r2c (forward) / c2r (backward) — the double
    tier of heFFTe's distributed ``fft3d_r2c``. The real axis (2) is
    device-local, so the r2c shrink happens before any exchange, exactly
    like the c64 pipeline (:func:`..slab.build_slab_rfft3d`); the r2c
    itself is the dd full-transform-and-slice (``ddfft.rfftn_dd``
    rationale). Forward maps real dd X-slab pairs ``[N0, N1, N2]`` to
    complex dd Y-slab pairs ``[N0, N1, N2//2+1]``; backward inverts.
    ``batch=B`` prepends a leading batch axis to BOTH dd components with
    one shared pair of collectives per (chunk, exchange) — the
    :func:`build_dd_slab_fft3d` convention at the real tier."""
    shape = tuple(int(s) for s in shape)
    for n in shape:
        _check_dd_extent(n, shape)
    check_batch(batch)
    bo = 0 if batch is None else 1  # leading-batch axis offset
    p = mesh.shape[axis_name]
    spec = SlabSpec(shape, p, axis_name,
                    in_axis=0 if forward else 1,
                    out_axis=1 if forward else 0)
    n0, n1, n2 = shape
    n0p, n1p = spec.n0p, spec.n1p
    h = n2 // 2 + 1
    platform = mesh.devices.flat[0].platform

    if forward:

        def t3_chunk(pair):
            chi, clo = pair
            chi = _crop_axis(chi, bo, n0)
            clo = _crop_axis(clo, bo, n0)
            return ddfft.fft_axis_dd(chi, clo, bo)         # t3: X lines

        def local_fn(hi, lo):  # real f32 [(B,) n0p/p, N1, N2] per device
            with add_trace("t0_dd_r2c_zy"):
                chi = lax.complex(hi, jnp.zeros_like(hi))
                clo = lax.complex(lo, jnp.zeros_like(lo))
                chi, clo = ddfft.fft_axis_dd(chi, clo, 2 + bo)  # t0a: Z
                chi, clo = chi[..., :h], clo[..., :h]      # r2c shrink
                chi, clo = ddfft.fft_axis_dd(chi, clo, 1 + bo)  # t0b: Y
            return exchange_overlapped(
                (chi, clo), axis_name, split_axis=1 + bo, concat_axis=bo,
                axis_size=p, algorithm=algorithm, wire_dtype=wire_dtype, platform=platform,
                compute=t3_chunk, overlap_chunks=overlap_chunks,
                chunk_axis=2 + bo,
                exchange_name=f"t2_exchange_{axis_name}",
                compute_name="t3_dd_fft_x")

        pre = lambda v: _pad_axis(v, bo, n0p)  # noqa: E731
        post = lambda v: _crop_axis(v, 1 + bo, n1)  # noqa: E731
    else:

        def t0_chunk(pair):
            hi, lo = pair
            hi = _crop_axis(hi, 1 + bo, n1)
            lo = _crop_axis(lo, 1 + bo, n1)
            return ddfft.fft_axis_dd(hi, lo, 1 + bo, forward=False)

        def local_fn(hi, lo):  # complex dd [(B,) N0, n1p/p, h] per device
            with add_trace("t3_dd_ifft_x"):
                hi, lo = ddfft.fft_axis_dd(hi, lo, bo, forward=False)
            # The half-spectrum mirror + inverse Z transform run along the
            # bystander (chunk) axis, so they follow the chunked merge.
            hi, lo = exchange_overlapped(
                (hi, lo), axis_name, split_axis=bo, concat_axis=1 + bo,
                axis_size=p, algorithm=algorithm, wire_dtype=wire_dtype, platform=platform,
                compute=t0_chunk, overlap_chunks=overlap_chunks,
                chunk_axis=2 + bo,
                exchange_name=f"t2_exchange_{axis_name}",
                compute_name="t0_dd_ifft_y")
            hi, lo = ddfft.fft_axis_dd(
                ddfft.mirror_half_spectrum(hi, n2, axis=2 + bo),
                ddfft.mirror_half_spectrum(lo, n2, axis=2 + bo),
                2 + bo, forward=False)
            return jnp.real(hi), jnp.real(lo)

        pre = lambda v: _pad_axis(v, 1 + bo, n1p)  # noqa: E731
        post = lambda v: _crop_axis(v, bo, n0)  # noqa: E731

    in_spec = batch_pspec(spec.in_pspec, batch)
    out_spec = batch_pspec(spec.out_pspec, batch)
    mapped = _shard_map(local_fn, mesh=mesh,
                        in_specs=(in_spec, in_spec),
                        out_specs=(out_spec, out_spec))
    in_sh = NamedSharding(mesh, in_spec)

    @jax.jit
    def fn(hi, lo):
        hi = lax.with_sharding_constraint(pre(hi), in_sh)
        lo = lax.with_sharding_constraint(pre(lo), in_sh)
        hi, lo = mapped(hi, lo)
        return post(hi), post(lo)

    return fn, spec


def build_dd_pencil_rfft3d(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    row_axis: str = "row",
    col_axis: str = "col",
    forward: bool = True,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[Callable, PencilSpec]:
    """Pencil-distributed dd r2c (forward) / c2r (backward) — the last
    cell of the dd decomposition matrix (mirrors the c64
    :func:`..pencil.build_pencil_rfft3d` chain: real Z lines shrink
    before the first exchange; canonical z->x pencils forward).
    ``batch=B`` prepends a leading batch axis to BOTH dd components
    with one shared pair of collectives per (chunk, exchange)."""
    shape = tuple(int(s) for s in shape)
    for n in shape:
        _check_dd_extent(n, shape)
    check_batch(batch)
    bo = 0 if batch is None else 1  # leading-batch axis offset
    rows, cols = mesh.shape[row_axis], mesh.shape[col_axis]
    spec = PencilSpec(
        shape, rows, cols, row_axis, col_axis,
        perm=(0, 1, 2) if forward else (1, 2, 0),
        order="col_first" if forward else "row_first",
    )
    n0, n1, n2 = shape
    n0p, n1pc, n1pr = spec.n0p, spec.n1p_col, spec.n1p_row
    h = n2 // 2 + 1
    n2hp = pad_to(h, cols)
    platform = mesh.devices.flat[0].platform

    if forward:

        def fft_y(pair):
            chi, clo = pair
            chi = _crop_axis(chi, 1 + bo, n1)
            clo = _crop_axis(clo, 1 + bo, n1)
            return ddfft.fft_axis_dd(chi, clo, 1 + bo)  # Y lines

        def fft_x(pair):
            chi, clo = pair
            chi = _crop_axis(chi, bo, n0)
            clo = _crop_axis(clo, bo, n0)
            return ddfft.fft_axis_dd(chi, clo, bo)      # t3: X lines

        def local_fn(hi, lo):  # real f32 [(B,) n0p/rows, n1pc/cols, N2]
            chi = lax.complex(hi, jnp.zeros_like(hi))
            clo = lax.complex(lo, jnp.zeros_like(lo))
            chi, clo = ddfft.fft_axis_dd(chi, clo, 2 + bo)  # t0: Z lines
            chi, clo = chi[..., :h], clo[..., :h]       # r2c shrink
            pair = exchange_overlapped(
                (chi, clo), col_axis, split_axis=2 + bo, concat_axis=1 + bo,
                axis_size=cols, algorithm=algorithm, wire_dtype=wire_dtype, platform=platform,
                compute=fft_y, overlap_chunks=overlap_chunks,
                chunk_axis=bo,
                exchange_name=f"t2a_exchange_{col_axis}",
                compute_name="t1_dd_fft_y")
            return exchange_overlapped(
                pair, row_axis, split_axis=1 + bo, concat_axis=bo,
                axis_size=rows, algorithm=algorithm, wire_dtype=wire_dtype, platform=platform,
                compute=fft_x, overlap_chunks=overlap_chunks,
                chunk_axis=2 + bo,
                exchange_name=f"t2b_exchange_{row_axis}",
                compute_name="t3_dd_fft_x")

        pre = lambda v: _pad_axis(_pad_axis(v, bo, n0p), 1 + bo, n1pc)  # noqa: E731
        post = lambda v: _crop_axis(_crop_axis(v, 1 + bo, n1), 2 + bo, h)  # noqa: E731
    else:

        def ifft_y(pair):
            hi, lo = pair
            hi = _crop_axis(hi, 1 + bo, n1)
            lo = _crop_axis(lo, 1 + bo, n1)
            return ddfft.fft_axis_dd(hi, lo, 1 + bo, forward=False)

        def c2r_z(pair):
            # mirror + inverse Z transform axis 2 (fully local after this
            # exchange); the chunk axis is 0, so per-chunk c2r is exact.
            hi, lo = pair
            hi = _crop_axis(hi, 2 + bo, h)
            lo = _crop_axis(lo, 2 + bo, h)
            return ddfft.fft_axis_dd(
                ddfft.mirror_half_spectrum(hi, n2, axis=2 + bo),
                ddfft.mirror_half_spectrum(lo, n2, axis=2 + bo),
                2 + bo, forward=False)

        def local_fn(hi, lo):  # complex dd [(B,) N0, n1pr/rows, n2hp/cols]
            hi, lo = ddfft.fft_axis_dd(hi, lo, bo, forward=False)
            pair = exchange_overlapped(
                (hi, lo), row_axis, split_axis=bo, concat_axis=1 + bo,
                axis_size=rows, algorithm=algorithm, wire_dtype=wire_dtype, platform=platform,
                compute=ifft_y, overlap_chunks=overlap_chunks,
                chunk_axis=2 + bo,
                exchange_name=f"t2b_exchange_{row_axis}",
                compute_name="t1_dd_ifft_y")
            hi, lo = exchange_overlapped(
                pair, col_axis, split_axis=1 + bo, concat_axis=2 + bo,
                axis_size=cols, algorithm=algorithm, wire_dtype=wire_dtype, platform=platform,
                compute=c2r_z, overlap_chunks=overlap_chunks,
                chunk_axis=bo,
                exchange_name=f"t2a_exchange_{col_axis}",
                compute_name="t0_dd_c2r_z")
            return jnp.real(hi), jnp.real(lo)

        pre = lambda v: _pad_axis(_pad_axis(v, 1 + bo, n1pr), 2 + bo, n2hp)  # noqa: E731
        post = lambda v: _crop_axis(_crop_axis(v, bo, n0), 1 + bo, n1)  # noqa: E731

    in_spec = batch_pspec(spec.in_spec, batch)
    out_spec = batch_pspec(spec.out_spec, batch)
    mapped = _shard_map(local_fn, mesh=mesh,
                        in_specs=(in_spec, in_spec),
                        out_specs=(out_spec, out_spec))
    in_sh = NamedSharding(mesh, in_spec)

    @jax.jit
    def fn(hi, lo):
        hi = lax.with_sharding_constraint(pre(hi), in_sh)
        lo = lax.with_sharding_constraint(pre(lo), in_sh)
        hi, lo = mapped(hi, lo)
        return post(hi), post(lo)

    return fn, spec


def build_dd_pencil_fft3d(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    row_axis: str = "row",
    col_axis: str = "col",
    forward: bool = True,
    algorithm: str = "alltoall",
    donate: bool = False,
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[Callable, PencilSpec]:
    """Jitted distributed dd 3D C2C transform over a 2D (rows x cols)
    mesh — the canonical pencil chain (z-pencils -> x-pencils forward;
    see :mod:`.pencil`) with every stage at the dd tier and both dd
    components through each exchange. ``batch=B`` prepends a leading
    batch axis to both dd components with one shared pair of collectives
    per (chunk, exchange)."""
    shape = tuple(int(s) for s in shape)
    for n in shape:
        _check_dd_extent(n, shape)
    check_batch(batch)
    bo = 0 if batch is None else 1  # leading-batch axis offset
    perm = (0, 1, 2) if forward else (1, 2, 0)
    order = "col_first" if forward else "row_first"
    rows, cols = mesh.shape[row_axis], mesh.shape[col_axis]
    spec = PencilSpec(shape, rows, cols, row_axis, col_axis, perm, order)
    n = spec.shape
    seq, last_fft, in_pads, out_crops = chain_geometry(
        perm, order, rows, cols, row_axis, col_axis, n)
    platform = mesh.devices.flat[0].platform

    fft_names = ("t0_dd_fft", "t1_dd_fft")
    exch_names = (f"t2a_exchange_{seq[0][0]}", f"t2b_exchange_{seq[1][0]}")

    def local_fn(hi, lo):
        with add_trace(fft_names[0]):
            hi, lo = ddfft.fft_axis_dd(hi, lo, seq[0][2] + bo,
                                       forward=forward)
        pair = (hi, lo)
        for i, (mesh_ax, parts, split, concat) in enumerate(seq):
            # Like the c64 pencil chain: each exchange pipelines under
            # the dd FFT of its own concat axis (the next chain stage).
            def post_fft(p_, concat=concat):
                h, l = p_
                h = _crop_axis(h, concat + bo, n[concat])
                l = _crop_axis(l, concat + bo, n[concat])
                return ddfft.fft_axis_dd(h, l, concat + bo, forward=forward)

            pair = exchange_overlapped(
                pair, mesh_ax, split_axis=split + bo, concat_axis=concat + bo,
                axis_size=parts, algorithm=algorithm, wire_dtype=wire_dtype, platform=platform,
                compute=post_fft, overlap_chunks=overlap_chunks,
                chunk_axis=3 - split - concat + bo,
                exchange_name=exch_names[i],
                compute_name=fft_names[1] if i == 0 else "t3_dd_fft")
        return pair

    in_spec = batch_pspec(spec.in_spec, batch)
    out_spec = batch_pspec(spec.out_spec, batch)
    mapped = _shard_map(local_fn, mesh=mesh,
                        in_specs=(in_spec, in_spec),
                        out_specs=(out_spec, out_spec))
    in_sh = NamedSharding(mesh, in_spec)

    @functools.partial(
        jax.jit, donate_argnums=(0, 1) if donate else ())
    def fn(hi, lo):
        for ax, to in in_pads:
            hi = _pad_axis(hi, ax + bo, to)
            lo = _pad_axis(lo, ax + bo, to)
        hi = lax.with_sharding_constraint(hi, in_sh)
        lo = lax.with_sharding_constraint(lo, in_sh)
        hi, lo = mapped(hi, lo)
        for ax, to in out_crops:
            hi = _crop_axis(hi, ax + bo, to)
            lo = _crop_axis(lo, ax + bo, to)
        return hi, lo

    return fn, spec


def _dd_yz_planes(pair, *, forward: bool = True):
    """The shared t0 stage body: dd transforms of the local YZ planes."""
    hi, lo = pair
    for ax in (1, 2):
        hi, lo = ddfft.fft_axis_dd(hi, lo, ax, forward=forward)
    return hi, lo


def build_dd_single_stages(
    shape: tuple[int, int, int],
    *,
    forward: bool = True,
) -> list:
    """Single-device dd staged pipeline — t0 (YZ planes) / t3 (X lines)
    as separate jits over (hi, lo) pairs, the dd-tier analog of
    ``staged.build_single_stages`` (per-stage breakdown of
    ``fft_mpi_3d_api.cpp:184-201`` at the accuracy tier)."""
    shape = tuple(int(s) for s in shape)
    for n in shape:
        _check_dd_extent(n, shape)

    def yz(pair):
        return _dd_yz_planes(pair, forward=forward)

    def x_line(pair):
        return ddfft.fft_axis_dd(*pair, 0, forward=forward)

    return trace_stages([("t0_dd_fft_yz", jax.jit(yz)),
                         ("t3_dd_fft_x", jax.jit(x_line))])


def build_dd_slab_stages(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    axis_name: str = "slab",
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    wire_dtype: str | None = None,
) -> tuple[list, SlabSpec]:
    """Forward dd slab transform as separately-jitted t0/t2/t3 stages.

    The dd twin of ``slab.build_slab_stages``: each stage maps a
    (hi, lo) pair, and t2 moves both components through the same global
    transpose. Fusing hides the ICI cost (SURVEY.md §7), so the dd tier
    keeps a staged mode for measurement exactly like the c64 tier.
    """
    shape = tuple(int(s) for s in shape)
    for n in shape:
        _check_dd_extent(n, shape)
    p = mesh.shape[axis_name]
    spec = SlabSpec(shape, p, axis_name)
    n0, n1, _ = shape
    n0p = spec.n0p
    xs, ys = spec.in_pspec, spec.out_pspec
    x_slab = NamedSharding(mesh, xs)
    y_slab = NamedSharding(mesh, ys)
    platform = mesh.devices.flat[0].platform

    def smap(f, ins, outs):
        return _shard_map(f, mesh=mesh, in_specs=((ins, ins),),
                          out_specs=(outs, outs))

    def t0(pair):
        hi, lo = pair
        hi = _pad_axis(hi, 0, n0p)
        lo = _pad_axis(lo, 0, n0p)
        hi = lax.with_sharding_constraint(hi, x_slab)
        lo = lax.with_sharding_constraint(lo, x_slab)
        return smap(_dd_yz_planes, xs, xs)((hi, lo))

    def local_exchange(pair):
        return exchange_chunked(
            pair, axis_name, split_axis=1, concat_axis=0, axis_size=p,
            algorithm=algorithm, wire_dtype=wire_dtype, overlap_chunks=overlap_chunks,
            uneven=True, platform=platform,
            exchange_name="t2_all_to_all")

    def local_x(pair):
        hi, lo = pair
        hi = _crop_axis(hi, 0, n0)
        lo = _crop_axis(lo, 0, n0)
        return ddfft.fft_axis_dd(hi, lo, 0, forward=True)

    def t3(pair):
        hi, lo = smap(local_x, ys, ys)(pair)
        return _crop_axis(hi, 1, n1), _crop_axis(lo, 1, n1)

    pair_x = (x_slab, x_slab)
    pair_y = (y_slab, y_slab)
    stages = [
        ("t0_dd_fft_yz", jax.jit(t0, out_shardings=pair_x)),
        ("t2_all_to_all", jax.jit(smap(local_exchange, xs, ys),
                                  in_shardings=(pair_x,),
                                  out_shardings=pair_y)),
        # No out_shardings pin on t3: the final crop (axis 1 back to n1)
        # need not divide the mesh for uneven worlds.
        ("t3_dd_fft_x", jax.jit(t3, in_shardings=(pair_y,))),
    ]
    return trace_stages(stages), spec


def build_dd_pencil_stages(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    row_axis: str = "row",
    col_axis: str = "col",
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
):
    """Forward dd pencil transform as the five timed t0/t2a/t1/t2b/t3
    stages: the c64 pencil stage pipeline (``staged.build_pencil_stages``
    — tree-generic) driven by a pair-aware dd executor. Completes the dd
    staged matrix (single, slab, pencil)."""
    from .staged import build_pencil_stages

    shape = tuple(int(s) for s in shape)
    for n in shape:
        _check_dd_extent(n, shape)

    def dd_ex(pair, axes, forward):
        hi, lo = pair
        for ax in axes:
            hi, lo = ddfft.fft_axis_dd(hi, lo, ax, forward=forward)
        return hi, lo

    return build_pencil_stages(mesh, shape, row_axis=row_axis,
                               col_axis=col_axis, executor=dd_ex,
                               algorithm=algorithm, wire_dtype=wire_dtype,
                               overlap_chunks=overlap_chunks, batch=batch)
