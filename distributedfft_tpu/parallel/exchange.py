"""Global-transpose exchange algorithms over a mesh axis.

The reference exposes a menu of distributed-transpose transports: heFFTe's
``reshape_algorithm`` enum {alltoall, alltoallv, p2p, p2p_plined}
(``heffte/heffteBenchmark/include/heffte_plan_logic.h:47-56``;
implementations ``src/heffte_reshape3d.cpp:268,375,497-625``) and the
first-party engine's hand-rolled peer DMA + MPI_Isend/Irecv tables
(``3dmpifft_opt/include/fft_mpi_3d_api.cpp:610-699``).

The TPU-native menu has three entries, selected per plan:

- ``"alltoall"`` — one ``jax.lax.all_to_all`` on the mesh axis. XLA lowers
  this to the platform all-to-all riding ICI; the analog of
  ``MPI_Alltoall`` with equal (ceil-padded) counts
  (``reshape3d_alltoall``, ``src/heffte_reshape3d.cpp:268`` pads to equal
  counts the same way).
- ``"alltoallv"`` — one ``jax.lax.ragged_all_to_all`` shipping each peer's
  TRUE slice of the split axis (no split-axis padding on the wire) — the
  analog of ``MPI_Alltoallv`` with the exact per-peer count tables
  (``reshape3d_alltoallv``, ``src/heffte_reshape3d.cpp:375``;
  count/offset semantics = ``dfft_exchange_table``,
  ``native/dfft_native.cpp``). Concat-axis padding (each sender's equal
  ceil-chunk block, zero rows on the tail device) is inherent to the SPMD
  equal-shard layout and still travels.
- ``"ppermute"`` — an explicit (P-1)-step ring of ``jax.lax.ppermute``
  neighbor shifts, each step moving one peer's chunk. The analog of the
  pipelined point-to-point path (``reshape3d_pointtopoint``,
  ``src/heffte_reshape3d.cpp:497-625``): per-step transfers are
  nearest-neighbor permutes that map 1:1 onto ICI ring links, and XLA can
  overlap each step's transfer with the next step's slice/update work.

``alltoall``/``ppermute`` require equal chunk sizes — the ceil-pad/crop
scheme of :mod:`.slab` / :mod:`.pencil` (via :func:`exchange_uneven`)
guarantees that; ``alltoallv`` takes the unpadded split axis directly.

On top of the transport menu, :func:`exchange_overlapped` provides the
*pipelined* execution mode: the local block is split into K chunks along
the bystander (non-split, non-concat) axis, and chunk ``i``'s exchange is
issued before chunk ``i-1``'s downstream FFT — the TPU-native analog of
the reference's ``MPI_Waitany``-ordered overlap loop
(``3dmpifft_opt/include/fft_mpi_3d_api.cpp:610-699``, heFFTe's pipelined
p2p ``src/heffte_reshape3d.cpp:497-625``), with XLA's async collectives
(start/done pairs) playing the Isend/Irecv role. K-chunked hierarchical
exchanges go one level deeper (:func:`_hierarchical_pipelined`): chunk
``i``'s intra-slice ICI leg is issued while chunk ``i-1``'s inter-slice
DCN leg and downstream FFT run — a two-deep pipeline, bit-identical to
the monolithic two-leg exchange.

Orthogonal to both, the **wire-codec registry** (:data:`WIRE_CODECS`)
compresses any transport's payload on the wire: each codec declares its
per-complex-element ``pair_bytes``, its encode/decode callables
(multi-part wire forms — payload plus a per-tile scale sidecar — ride
the same collective stage), and is measured by
:func:`wire_roundtrip_error` the same seeded/cached way. Registered:
``bf16`` (component pairs, half the c64 wire bytes) and ``int8``
(per-tile block-scaled planes + f32 power-of-two-step sidecar, ~quarter
the c64 wire bytes).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..geometry import pad_to
from ..utils.trace import add_trace

#: Flat transports: the whole mesh axis is one collective's domain.
FLAT_ALGORITHMS = ("alltoall", "alltoallv", "ppermute")
#: Full menu, including the two-leg ICI/DCN transport (hybrid meshes
#: only — see :func:`hierarchical_all_to_all`).
ALGORITHMS = FLAT_ALGORITHMS + ("hierarchical",)

#: Which :func:`..plan_logic.exchange_payloads` byte entry each transport
#: actually ships on the wire — shared by the per-execute byte counters
#: (api) and the tuner's candidate-pruning model, so wire accounting can
#: never disagree between the two. The hierarchical transport's payload
#: entries are already per-leg (dense within each leg's axis), so it
#: reads the dense key of each leg entry.
WIRE_BYTE_KEYS = {
    "alltoall": "alltoall_bytes",
    "ppermute": "alltoall_bytes",   # the padded ring ships the pads too
    "alltoallv": "alltoallv_bytes",
    "hierarchical": "alltoall_bytes",
}

#: Registered on-wire codec names, ``None`` (exact) first — the public
#: wire-mode menu every validation error prints. Rebuilt by
#: :func:`register_wire_codec`; ``_WIRE_PAIR_BYTES`` mirrors each
#: codec's per-complex-element wire bytes for the byte accounting.
WIRE_DTYPES = (None,)
_WIRE_PAIR_BYTES: dict = {}


def wire_codec(name: str) -> "WireCodec":
    """The registered :class:`WireCodec` for ``name``; raises with the
    full codec menu for anything unregistered (the plan-time failure
    mode of an unknown ``wire_dtype`` string)."""
    try:
        return WIRE_CODECS[name]
    except KeyError:
        raise ValueError(
            f"unknown wire_dtype {name!r}; use one of {WIRE_DTYPES}"
        ) from None


def wire_itemsize(itemsize: int, wire_dtype: str | None) -> int:
    """Per-element bytes actually on the wire for a payload of
    ``itemsize``-byte complex elements under ``wire_dtype`` compression
    (``None`` = the payload travels as-is). Codecs shipping a per-tile
    scale sidecar (``int8``) declare their ``pair_bytes`` with the
    sidecar included — the sidecar is O(tiles) f32 values against an
    O(volume) payload, so the declared figure is the accounting truth
    the model, the counters, and the docs table all share."""
    if wire_dtype is None:
        return int(itemsize)
    try:
        return _WIRE_PAIR_BYTES[wire_dtype]
    except KeyError:
        raise ValueError(
            f"unknown wire_dtype {wire_dtype!r}; use one of {WIRE_DTYPES}"
        ) from None


def transport_steps(algorithm: str, parts: int) -> int:
    """Sequential collective launches one exchange pays on ``parts``
    devices: the fused transports are one launch; the explicit ring is
    ``parts - 1`` neighbor shifts (each a dependent ppermute); the
    hierarchical transport is two dependent axis-local collectives
    (the ``parts`` here are one LEG's parts — each leg entry is priced
    separately, one launch per leg). The latency term of the tuner's
    analytical cost model."""
    if algorithm == "ppermute":
        return max(1, parts - 1)
    return 1


def exchange_model_seconds(
    wire_bytes_per_dev: float,
    parts: int,
    algorithm: str,
    *,
    wire_gbps: float,
    launch_seconds: float,
    overlap_chunks: int = 1,
    hide_seconds: float = 0.0,
    batch: int = 1,
) -> dict:
    """Analytical time model of ONE exchange under one transport — the
    single source of truth shared by the tuner's candidate-pruning cost
    (:func:`..tuner.model_cost`) and the explain layer's per-stage
    prediction, so the two can never disagree about what the model says.

    ``seconds`` is the raw exchange time (wire transfer at ``wire_gbps``
    plus ``transport_steps`` launch latencies); ``exposed_seconds`` is
    what remains on the critical path at ``overlap_chunks = K`` with
    ``hide_seconds`` of downstream compute available to hide under:
    ``t/K + max(0, t - hide) * (K-1)/K`` plus the K-1 extra launches each
    additional chunk costs (the crossover model behind
    ``auto_overlap_chunks``; docs/MFU_ANALYSIS.md "Exchange/compute
    overlap").

    ``batch`` scales the wire transfer for a batched chain: B coalesced
    transforms ride ONE collective as a bystander dim, so the payload
    grows B-fold while the ``transport_steps`` launch latencies are paid
    once — the whole point of batching the exchange. Callers passing
    bytes already scaled by B (``exchange_payloads`` of a batched
    LogicPlan) keep the default 1."""
    steps = transport_steps(algorithm, parts)
    t_ex = (max(1, int(batch)) * wire_bytes_per_dev / (wire_gbps * 1e9)
            + steps * launch_seconds)
    k = max(1, int(overlap_chunks))
    exposed = (t_ex / k
               + max(0.0, t_ex - hide_seconds) * (k - 1) / k
               + (k - 1) * steps * launch_seconds)
    return {"seconds": t_ex, "exposed_seconds": exposed, "steps": steps}


# ----------------------------------------------- wire codecs (registry)

def _check_complex(x) -> None:
    if not jnp.issubdtype(x.dtype, jnp.complexfloating):
        raise TypeError(
            f"wire compression applies to complex exchange payloads, "
            f"got {x.dtype}")


def _component_dtype(dtype):
    return (jnp.float64 if jnp.dtype(dtype) == jnp.complex128
            else jnp.float32)


def _bf16_encode(x: jnp.ndarray, *, tile_axis: int = 0,
                 tiles: int = 1) -> tuple:
    """bf16 wire form: (real, imag) stacked as a trailing bfloat16 pair
    — half the wire bytes of c64 at ~2^-9 relative rounding per
    component. Elementwise (``tile_axis``/``tiles`` unused): the
    trailing wire dim is a bystander of every transport."""
    _check_complex(x)
    return (jnp.stack([x.real, x.imag], axis=-1).astype(jnp.bfloat16),)


def _bf16_decode(parts, dtype, *, tile_axis: int = 0,
                 tiles: int = 1) -> jnp.ndarray:
    (y,) = parts
    rdt = _component_dtype(dtype)
    r = y[..., 0].astype(rdt)
    i = y[..., 1].astype(rdt)
    return lax.complex(r, i).astype(dtype)


def exact_pow2(k: jnp.ndarray) -> jnp.ndarray:
    """Exact float32 ``2**k`` for integer-valued ``k``, built from the
    exponent bits. XLA's ``exp2`` can land 1 ulp off an exact power of
    two (observed on XLA:CPU at ``exp2(-13.0)``), which would silently
    void the exact-decode/idempotence contract the pow2 steps exist
    for. Clamps to the normal range (denormal steps would lose the
    exact ``q * step`` product anyway)."""
    kk = jnp.clip(k, -126.0, 127.0).astype(jnp.int32)
    return lax.bitcast_convert_type((kk + 127) << 23, jnp.float32)


def _pow2_step(amax: jnp.ndarray) -> jnp.ndarray:
    """Power-of-two quantization step covering ``amax`` in 127 signed
    levels. Power-of-two steps make every decode product ``q * step``
    exact in float32 and the encode/decode pair exactly idempotent —
    the property the staged per-leg wire boundaries (decode at one
    stage's exit, re-encode at the next stage's entry) rely on for
    bit-parity with the fused single-cast chain."""
    safe = jnp.where(amax > 0.0, amax, jnp.float32(127.0))
    return jnp.where(
        amax > 0.0, exact_pow2(jnp.ceil(jnp.log2(safe / 127.0))),
        jnp.float32(1.0)).astype(jnp.float32)


def _int8_encode(x: jnp.ndarray, *, tile_axis: int = 0,
                 tiles: int = 1) -> tuple:
    """int8 wire form: per-block symmetric quantization of the (real,
    imag) planes along the exchange tile axis — one power-of-two step
    per (peer tile, component plane), the steps riding as a tiny f32
    sidecar part through the same collective stage. ~quarter the c64
    wire bytes (the sidecar is O(tiles) values against an O(volume)
    payload).

    Returns ``(q, scales)``: ``q`` int8 of shape ``x.shape + (2,)``
    (trailing component-plane axis, a transport bystander) and
    ``scales`` f32 with extent ``tiles`` on ``tile_axis``, 1 on every
    other payload axis, and the trailing plane pair — exactly the shape
    that makes the sidecar route through any tiled transport with the
    same (split, concat) semantics as the payload, one scale slot per
    peer tile."""
    _check_complex(x)
    planes = jnp.stack([x.real, x.imag], axis=-1).astype(jnp.float32)
    t = tile_axis
    p = max(1, int(tiles))
    S = planes.shape[t]
    c = -(-S // p)
    padded = _pad_axis(planes, t, p * c)
    shp = padded.shape
    view = padded.reshape(shp[:t] + (p, c) + shp[t + 1:])
    red = tuple(a for a in range(view.ndim)
                if a != t and a != view.ndim - 1)
    amax = jnp.max(jnp.abs(view), axis=red, keepdims=True)
    bshape = [1] * planes.ndim
    bshape[t] = p
    bshape[-1] = 2
    scales = _pow2_step(amax).reshape(bshape)
    per_row = lax.slice_in_dim(jnp.repeat(scales, c, axis=t), 0, S, axis=t)
    q = jnp.clip(jnp.round(planes / per_row), -127.0, 127.0).astype(
        jnp.int8)
    return (q, scales)


def _int8_decode(parts, dtype, *, tile_axis: int = 0,
                 tiles: int = 1) -> jnp.ndarray:
    """Inverse of :func:`_int8_encode`, with ``tile_axis`` naming the
    axis the peer tiles sit on NOW — the split axis before an exchange,
    the concat axis after (the collective moves tile blocks and sidecar
    slots identically, so alignment is positional)."""
    q, scales = parts
    t = tile_axis
    p = max(1, int(tiles))
    S = q.shape[t]
    c = -(-S // p)
    per_row = lax.slice_in_dim(jnp.repeat(scales, c, axis=t), 0, S, axis=t)
    vals = q.astype(jnp.float32) * per_row  # exact: |q| <= 127, pow2 step
    rdt = _component_dtype(dtype)
    r = vals[..., 0].astype(rdt)
    i = vals[..., 1].astype(rdt)
    return lax.complex(r, i).astype(dtype)


def _pow2_step16(amax: jnp.ndarray) -> jnp.ndarray:
    """Power-of-two step covering ``amax`` in 32767 signed levels — the
    16-bit analog of :func:`_pow2_step`, with the same exactly-idempotent
    decode property (``q * step`` exact in float32)."""
    safe = jnp.where(amax > 0.0, amax, jnp.float32(32767.0))
    return jnp.where(
        amax > 0.0, exact_pow2(jnp.ceil(jnp.log2(safe / 32767.0))),
        jnp.float32(1.0)).astype(jnp.float32)


def _split_encode(x: jnp.ndarray, *, tile_axis: int = 0,
                  tiles: int = 1) -> tuple:
    """Split-exponent (shared-exponent block-float) wire form: one
    power-of-two exponent per (peer tile, component plane) rides a tiny
    f32 sidecar while every element ships a full int16 mantissa. Same
    wire bytes as ``bf16`` (4 per complex pair) but the 15-bit mantissa
    against a block-shared exponent lands ~2^-15 relative error where
    bf16's 8-bit mantissa gives ~2^-9 — a distinct accuracy point at
    the same byte cost. Block/sidecar geometry is identical to
    :func:`_int8_encode` (one scale slot per peer tile, transported
    with the payload's (split, concat) semantics)."""
    _check_complex(x)
    planes = jnp.stack([x.real, x.imag], axis=-1).astype(jnp.float32)
    t = tile_axis
    p = max(1, int(tiles))
    S = planes.shape[t]
    c = -(-S // p)
    padded = _pad_axis(planes, t, p * c)
    shp = padded.shape
    view = padded.reshape(shp[:t] + (p, c) + shp[t + 1:])
    red = tuple(a for a in range(view.ndim)
                if a != t and a != view.ndim - 1)
    amax = jnp.max(jnp.abs(view), axis=red, keepdims=True)
    bshape = [1] * planes.ndim
    bshape[t] = p
    bshape[-1] = 2
    scales = _pow2_step16(amax).reshape(bshape)
    per_row = lax.slice_in_dim(jnp.repeat(scales, c, axis=t), 0, S, axis=t)
    q = jnp.clip(jnp.round(planes / per_row), -32767.0, 32767.0).astype(
        jnp.int16)
    return (q, scales)


def _split_decode(parts, dtype, *, tile_axis: int = 0,
                  tiles: int = 1) -> jnp.ndarray:
    """Inverse of :func:`_split_encode` (see :func:`_int8_decode` for
    the tile-axis alignment contract)."""
    q, scales = parts
    t = tile_axis
    p = max(1, int(tiles))
    S = q.shape[t]
    c = -(-S // p)
    per_row = lax.slice_in_dim(jnp.repeat(scales, c, axis=t), 0, S, axis=t)
    vals = q.astype(jnp.float32) * per_row  # exact: pow2 step
    rdt = _component_dtype(dtype)
    r = vals[..., 0].astype(rdt)
    i = vals[..., 1].astype(rdt)
    return lax.complex(r, i).astype(dtype)


@dataclass(frozen=True)
class WireCodec:
    """One pluggable on-wire compression codec of the t2 exchange.

    ``pair_bytes`` is the wire bytes per complex element (sidecar
    included for codecs that ship one — see :func:`wire_itemsize`);
    ``encode(x, tile_axis=, tiles=)`` returns the tuple of wire parts
    that ride the collective (payload first, any sidecar after), every
    part shaped so the SAME (split, concat, axis_size) tiled-transport
    semantics route it; ``decode(parts, dtype, tile_axis=, tiles=)``
    restores the complex payload, with ``tile_axis`` naming where the
    peer tiles sit at decode time. ``sidecar`` flags a multi-part wire
    (the legacy single-array :func:`wire_encode` API rejects those)."""

    name: str
    pair_bytes: int
    encode: Any
    decode: Any
    sidecar: bool = False


#: The codec registry — one entry per ``wire_dtype`` string. Extend via
#: :func:`register_wire_codec`; every registered codec must carry a
#: ``pair_bytes`` figure, a measured-error path (it gets one for free
#: through :func:`wire_roundtrip_error`), and a docs/TUNING.md table row
#: (the registry-completeness test holds all three).
WIRE_CODECS: dict[str, WireCodec] = {}


def register_wire_codec(codec: WireCodec) -> WireCodec:
    """Register a codec and rebuild the public menu/byte tables."""
    global WIRE_DTYPES
    WIRE_CODECS[codec.name] = codec
    _WIRE_PAIR_BYTES[codec.name] = int(codec.pair_bytes)
    WIRE_DTYPES = (None,) + tuple(WIRE_CODECS)
    return codec


register_wire_codec(WireCodec(
    name="bf16", pair_bytes=4, encode=_bf16_encode, decode=_bf16_decode))
register_wire_codec(WireCodec(
    name="int8", pair_bytes=2, encode=_int8_encode, decode=_int8_decode,
    sidecar=True))
register_wire_codec(WireCodec(
    name="split", pair_bytes=4, encode=_split_encode, decode=_split_decode,
    sidecar=True))


def wire_encode(x: jnp.ndarray, wire_dtype: str) -> jnp.ndarray:
    """Legacy single-array encode of a sidecar-free codec (``bf16``):
    the codec's one wire part. Codecs shipping a sidecar (``int8``)
    need the tile geometry and the multi-part form — use
    ``wire_codec(name).encode`` directly."""
    codec = wire_codec(wire_dtype)
    if codec.sidecar:
        raise ValueError(
            f"wire codec {wire_dtype!r} ships a multi-part payload "
            f"(scale sidecar); use wire_codec({wire_dtype!r}).encode")
    return codec.encode(x)[0]


def wire_decode(y: jnp.ndarray, dtype,
                wire_dtype: str = "bf16") -> jnp.ndarray:
    """Inverse of :func:`wire_encode` (single-part codecs only)."""
    codec = wire_codec(wire_dtype)
    if codec.sidecar:
        raise ValueError(
            f"wire codec {wire_dtype!r} ships a multi-part payload "
            f"(scale sidecar); use wire_codec({wire_dtype!r}).decode")
    return codec.decode((y,), dtype)


def wire_roundtrip_error(dtype, wire_dtype: str | None = "bf16",
                         n: int = 4096, *, sample=None) -> float:
    """Measured relative round-trip error of one wire cast
    (``max |decode(encode(x)) - x| / max |x|`` over a seeded
    standard-normal complex block, tiled the way an 8-way exchange
    would tile it) — the number the tuner's error-budget filter and
    ``explain``'s ``wire.compression_err`` field report. Every
    registered codec is measured the same seeded/cached way, so
    per-candidate pruning never re-measures. 0.0 for the exact wire.

    ``sample`` measures on caller-supplied data instead of the seeded
    Gaussian (cached by content digest — the convolve-kernel digest
    discipline). The seeded figure is OPTIMISTIC for non-Gaussian
    dynamic ranges: the block-scaled codecs (int8/split) share one
    pow2 exponent per tile, so a heavy-tailed sample degrades far
    beyond the seeded estimate (docs/TUNING.md codec table; the
    numerics plane's shadow audit exists to observe exactly this)."""
    if wire_dtype is None:
        return 0.0
    codec = wire_codec(wire_dtype)
    if sample is not None:
        x = np.asarray(sample, dtype=np.dtype(dtype)).ravel()
        digest = hashlib.sha256(x.tobytes()).hexdigest()[:16]
        key = (str(np.dtype(dtype)), wire_dtype, x.size, digest)
    else:
        x = None
        key = (str(np.dtype(dtype)), wire_dtype, int(n))
    hit = _WIRE_ERR_CACHE.get(key)
    if hit is not None:
        return hit
    if x is None:
        rng = np.random.default_rng(0)
        x = (rng.standard_normal(n)
             + 1j * rng.standard_normal(n)).astype(np.dtype(dtype))
    tiles = 8
    parts = codec.encode(jnp.asarray(x), tile_axis=0, tiles=tiles)
    y = np.asarray(codec.decode(parts, dtype, tile_axis=0, tiles=tiles))
    err = float(np.max(np.abs(y - x)) / np.max(np.abs(x)))
    _WIRE_ERR_CACHE[key] = err
    return err


_WIRE_ERR_CACHE: dict = {}


def _axis_label(axis_name) -> str:
    """Stage-span label of a mesh axis spec: the name itself, or
    ``a+b`` for a combined (hierarchical) axis tuple."""
    if isinstance(axis_name, (tuple, list)):
        return "+".join(str(a) for a in axis_name)
    return str(axis_name)


def _pad_axis(x: jnp.ndarray, axis: int, to: int) -> jnp.ndarray:
    """Zero-pad ``axis`` up to extent ``to`` (no-op when already there).
    Single definition shared by every chain builder and exchange path — the
    dense and ragged paths depend on bit-identical padding."""
    if x.shape[axis] == to:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, to - x.shape[axis])
    return jnp.pad(x, pads)


def _crop_axis(x: jnp.ndarray, axis: int, to: int) -> jnp.ndarray:
    if x.shape[axis] == to:
        return x
    return lax.slice_in_dim(x, 0, to, axis=axis)


def exchange(
    x: jnp.ndarray,
    axis_name,
    *,
    split_axis: int,
    concat_axis: int,
    axis_size: int,
    algorithm: str = "alltoall",
    axis_sizes: tuple[int, int] | None = None,
    wire_dtype: str | None = None,
) -> jnp.ndarray:
    """Tiled all-to-all on ``axis_name`` inside ``shard_map``.

    Splits the local block into ``axis_size`` chunks along ``split_axis`` and
    concatenates the chunks received from every peer along ``concat_axis``
    (the semantics of ``lax.all_to_all(..., tiled=True)``).

    ``axis_name`` is one mesh axis name, or — for the flat transports on a
    hybrid mesh and for ``"hierarchical"`` — a (dcn, ici) tuple of names
    whose combined extent is ``axis_size`` (``axis_sizes`` gives the
    per-axis factors the hierarchical legs need). ``wire_dtype`` casts the
    payload to its on-wire form immediately before the collective and back
    after (:func:`wire_encode`); ``None`` ships the payload as-is —
    byte-identical to the pre-compression HLO.
    """
    if wire_dtype is not None:
        codec = wire_codec(wire_dtype)
        parts = codec.encode(x, tile_axis=split_axis, tiles=axis_size)
        outs = tuple(
            exchange(w, axis_name, split_axis=split_axis,
                     concat_axis=concat_axis, axis_size=axis_size,
                     algorithm=algorithm, axis_sizes=axis_sizes)
            for w in parts)
        return codec.decode(outs, x.dtype, tile_axis=concat_axis,
                            tiles=axis_size)
    if algorithm == "alltoall":
        return lax.all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
        )
    if algorithm == "alltoallv":
        return ragged_all_to_all_exchange(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
            p=axis_size,
        )
    if algorithm == "ppermute":
        return ring_all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis, p=axis_size
        )
    if algorithm == "hierarchical":
        return hierarchical_all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
            axis_sizes=axis_sizes,
        )
    raise ValueError(f"unknown exchange algorithm {algorithm!r}; use {ALGORITHMS}")


def exchange_uneven(
    x: jnp.ndarray,
    axis_name,
    *,
    split_axis: int,
    concat_axis: int,
    axis_size: int,
    algorithm: str = "alltoall",
    platform: str | None = None,
    axis_sizes: tuple[int, int] | None = None,
    wire_dtype: str | None = None,
) -> jnp.ndarray:
    """Exchange whose split-axis extent need not divide ``axis_size``.

    The dense algorithms ceil-pad the split axis first (the reference's
    padded-equal-counts strategy, ``src/heffte_reshape3d.cpp:268``);
    ``alltoallv`` ships the true slices unpadded. Either way the result's
    split axis holds the local ceil-chunk (padded at the tail) and the
    concat axis holds ``axis_size`` ceil-chunks per sender — callers crop
    the concat axis to its true extent exactly as before. ``platform`` is
    the mesh devices' platform (used by ``alltoallv`` to pick the real
    ragged collective vs its CPU mirror). ``wire_dtype`` wraps the whole
    exchange (both hierarchical legs ride one encoded payload) in the
    on-wire cast pair; ``axis_sizes`` as in :func:`exchange`.
    """
    if algorithm == "alltoallv":
        if wire_dtype is not None:
            # The ragged transport takes the unpadded split axis: encode
            # on it directly (the codec's ceil-tile blocks match the
            # ragged ownership tables) and ship every wire part — the
            # int8 sidecar's split extent is axis_size, always even.
            codec = wire_codec(wire_dtype)
            parts = codec.encode(x, tile_axis=split_axis, tiles=axis_size)
            outs = tuple(
                ragged_all_to_all_exchange(
                    w, axis_name, split_axis=split_axis,
                    concat_axis=concat_axis, p=axis_size,
                    platform=platform)
                for w in parts)
            return codec.decode(outs, x.dtype, tile_axis=concat_axis,
                                tiles=axis_size)
        return ragged_all_to_all_exchange(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
            p=axis_size, platform=platform,
        )
    x = _pad_axis(x, split_axis, pad_to(x.shape[split_axis], axis_size))
    return exchange(x, axis_name, split_axis=split_axis,
                    concat_axis=concat_axis, axis_size=axis_size,
                    algorithm=algorithm, axis_sizes=axis_sizes,
                    wire_dtype=wire_dtype)


# ----------------------------------------------- hierarchical (ICI/DCN)

def _hier_names_sizes(axis_name, axis_sizes) -> tuple[str, str, int, int]:
    """Validate and unpack the (dcn, ici) axis pair of a hierarchical
    exchange."""
    if not (isinstance(axis_name, (tuple, list)) and len(axis_name) == 2):
        raise ValueError(
            "hierarchical exchange needs a (dcn, ici) mesh-axis name "
            f"pair, got {axis_name!r}")
    if not (isinstance(axis_sizes, (tuple, list)) and len(axis_sizes) == 2):
        raise ValueError(
            "hierarchical exchange needs axis_sizes=(dcn_parts, "
            f"ici_parts), got {axis_sizes!r}")
    dcn_name, ici_name = axis_name
    d, i = int(axis_sizes[0]), int(axis_sizes[1])
    return dcn_name, ici_name, d, i


def _regroup_split(x: jnp.ndarray, split_axis: int, a: int, b: int,
                   c: int) -> jnp.ndarray:
    """Local reindex between the two legs: view ``split_axis`` as
    ``[a, b, c]`` chunk factors and swap the leading two — the
    destination-index transpose that turns flat chunk order into the
    order each leg's tiled all-to-all expects."""
    shp = x.shape
    pre, post = shp[:split_axis], shp[split_axis + 1:]
    x = x.reshape(pre + (a, b, c) + post)
    perm = list(range(x.ndim))
    i0 = len(pre)
    perm[i0], perm[i0 + 1] = perm[i0 + 1], perm[i0]
    return x.transpose(perm).reshape(pre + (a * b * c,) + post)


def hierarchical_all_to_all(
    x: jnp.ndarray,
    axis_name,
    *,
    split_axis: int,
    concat_axis: int,
    axis_sizes: tuple[int, int],
) -> jnp.ndarray:
    """Two-leg topology-aware all-to-all over a hybrid (dcn x ici) axis
    pair: an intra-slice tiled all-to-all on the ICI axis, a local
    reindex, and an inter-slice tiled all-to-all on the DCN axis — each
    leg riding the link it was built for, instead of one flat collective
    the compiler routes across both fabrics at once (the 2.5D
    decomposition of "Collective-Optimized FFTs", arXiv 2306.16589; the
    reference's analogous split is peer-DMA within a node vs MPI across,
    ``fft_mpi_3d_api.cpp:627-672``).

    Bit-identical to the flat tiled all-to-all over the combined axis:
    with device index ``i = d*I + e`` (the row-major linearization of a
    ``P((dcn, ici))`` sharding), the ICI leg delivers every chunk to its
    destination's ici coordinate within each slice, the DCN leg to its
    destination slice, and the final local reindex lays the P sender
    chunks onto ``concat_axis`` in sender-major order — exactly the
    ``tiled=True`` contract. Requires ``split_axis`` extent divisible by
    ``D * I`` (the ceil-pad discipline of :func:`exchange_uneven`).

    The two legs carry ``t2a_exchange_<ici>`` / ``t2b_exchange_<dcn>``
    trace spans (both normalize to the ``t2`` stage key), so the explain
    layer attributes each leg separately.
    """
    dcn_name, ici_name, d, i = _hier_names_sizes(axis_name, axis_sizes)
    p = d * i
    S = x.shape[split_axis]
    if S % p:
        raise ValueError(
            f"split axis extent {S} not divisible by {p} (= {d} dcn x "
            f"{i} ici); hierarchical exchange takes the ceil-padded axis")
    c = S // p
    # Leg A (ICI): destination-e-major chunk order, intra-slice a2a.
    with add_trace(f"t2a_exchange_{_axis_label(ici_name)}"):
        v = _regroup_split(x, split_axis, d, i, c)
        v = lax.all_to_all(v, ici_name, split_axis=split_axis,
                           concat_axis=split_axis, tiled=True)
    # Leg B (DCN): destination-d-major order, inter-slice a2a.
    with add_trace(f"t2b_exchange_{_axis_label(dcn_name)}"):
        v = _regroup_split(v, split_axis, i, d, c)
        v = lax.all_to_all(v, dcn_name, split_axis=split_axis,
                           concat_axis=split_axis, tiled=True)
    # Final local reindex: the split axis now holds the P sender-major
    # chunks [(d_src, e_src), c]; lay them onto the concat axis exactly
    # where the flat tiled all-to-all would.
    shp = v.shape
    pre, post = shp[:split_axis], shp[split_axis + 1:]
    v = v.reshape(pre + (p, c) + post)
    v = jnp.moveaxis(v, split_axis, concat_axis)
    shp2 = v.shape
    out = list(shp2)
    out[concat_axis:concat_axis + 2] = [shp2[concat_axis]
                                        * shp2[concat_axis + 1]]
    return v.reshape(out)


def hierarchical_legs(
    axis_name,
    *,
    split_axis: int,
    concat_axis: int,
    axis_sizes: tuple[int, int],
):
    """The two legs of :func:`hierarchical_all_to_all` as separate
    callables ``(leg_ici, leg_dcn)`` — the staged-pipeline view, so the
    per-stage timing harness (and ``dfft.explain``) can bracket each leg
    as its own ``t2a``/``t2b`` stage. ``leg_dcn`` includes the final
    sender-major reindex onto ``concat_axis``; composing
    ``leg_dcn(leg_ici(x))`` is bit-identical to the fused transport."""
    dcn_name, ici_name, d, i = _hier_names_sizes(axis_name, axis_sizes)
    p = d * i

    def leg_ici(x):
        c = x.shape[split_axis] // p
        v = _regroup_split(x, split_axis, d, i, c)
        return lax.all_to_all(v, ici_name, split_axis=split_axis,
                              concat_axis=split_axis, tiled=True)

    def leg_dcn(v):
        c = v.shape[split_axis] // p
        v = _regroup_split(v, split_axis, i, d, c)
        v = lax.all_to_all(v, dcn_name, split_axis=split_axis,
                           concat_axis=split_axis, tiled=True)
        shp = v.shape
        pre, post = shp[:split_axis], shp[split_axis + 1:]
        v = v.reshape(pre + (p, c) + post)
        v = jnp.moveaxis(v, split_axis, concat_axis)
        shp2 = v.shape
        out = list(shp2)
        out[concat_axis:concat_axis + 2] = [shp2[concat_axis]
                                            * shp2[concat_axis + 1]]
        return v.reshape(out)

    return leg_ici, leg_dcn


def ragged_all_to_all_exchange(
    x: jnp.ndarray, axis_name: str, *, split_axis: int, concat_axis: int,
    p: int, platform: str | None = None,
) -> jnp.ndarray:
    """All-to-all transpose shipping each peer's TRUE split-axis slice.

    The MPI_Alltoallv analog (``reshape3d_alltoallv``,
    ``src/heffte_reshape3d.cpp:375``): where the dense path pads the split
    axis to ``p * ceil(S/p)`` and ships the padding, this sends peer ``j``
    exactly its ``sizes[j]`` true elements via ``lax.ragged_all_to_all``.
    The per-peer counts/offsets follow the ceil-split ownership convention —
    the same tables ``dfft_exchange_table`` computes (elements =
    ``rows * sizes[j] * inner``).

    Takes the UNPADDED split axis (extent S = the true global extent of the
    post-exchange sharded axis); returns the same shape the padded path
    would: split axis -> local ceil chunk ``c``, concat axis ->
    ``p * local_chunk`` (each sender's equal-size block, tail padding
    included — that padding is the SPMD equal-shard layout itself and is
    cropped by the caller, never transformed).
    """
    import jax

    from ..utils.compat import force_real_lowering

    S = x.shape[split_axis]
    c = -(-S // p)
    if platform is None:
        platform = jax.default_backend()
    if platform == "cpu" and not force_real_lowering():
        # XLA:CPU has no ragged-all-to-all lowering; the ceil-padded dense
        # exchange produces the bit-identical result (the padding positions
        # the ragged path never writes stay zero either way), so the CPU
        # test backend mirrors through it — the same discipline as the
        # Pallas kernel's interpreter-mode mirror (and the same
        # force_real_lowering override for chipless lowering tests).
        x = _pad_axis(x, split_axis, p * c)
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)
    i = lax.axis_index(axis_name)
    # Static per-peer ownership of the split axis (ceil splits, short/empty
    # tail) — the dfft_exchange_table geometry.
    bounds = np.minimum(np.arange(p + 1) * c, S)
    starts, sizes = bounds[:-1], np.diff(bounds)

    xt = jnp.moveaxis(x, split_axis, 0)
    rest = xt.shape[1:]
    out = jnp.zeros((p * c,) + rest, x.dtype)
    my_size = jnp.minimum((i + 1) * c, S) - jnp.minimum(i * c, S)
    y = lax.ragged_all_to_all(
        xt, out,
        jnp.asarray(starts, jnp.int32),
        jnp.asarray(sizes, jnp.int32),
        # Sender i's slice lands at leading offset i*c on every receiver.
        jnp.full((p,), i * c, jnp.int32),
        jnp.full((p,), my_size, jnp.int32),
        axis_name=axis_name,
    )
    # y: [p, c, *rest] with the sender dim to be merged into the concat
    # axis (sender-major) and the local split chunk moved back into place.
    y = y.reshape((p, c) + rest)
    cpos = 1 + (concat_axis if concat_axis < split_axis else concat_axis - 1)
    perm = [1]
    for k in range(len(rest)):
        ax = 2 + k
        if k == cpos - 1:
            perm.extend([0, ax])
        else:
            perm.append(ax)
    y = y.transpose(perm)
    j = perm.index(0)
    shp = list(y.shape)
    shp[j:j + 2] = [p * shp[j + 1]]
    y = y.reshape(shp)
    return jnp.moveaxis(y, 0, split_axis)


def ring_all_to_all(
    x: jnp.ndarray, axis_name: str, *, split_axis: int, concat_axis: int, p: int
) -> jnp.ndarray:
    """All-to-all as a (P-1)-step ``ppermute`` ring.

    Step ``s`` shifts by ``s`` around the ring: device ``i`` sends the chunk
    destined for ``(i - s) % p`` and receives its own chunk from
    ``(i + s) % p``. Each step is a uniform neighbor permutation (distance-s
    rotation), so on a physical ICI ring/torus every step uses disjoint
    links; the Python loop unrolls at trace time (P is static), letting XLA
    pipeline transfer ``s`` with the slice/update of step ``s+1`` — the role
    of ``MPI_Waitany``-driven overlap in the reference's pipelined p2p path
    (``src/heffte_reshape3d.cpp:611``).
    """
    ns = x.shape[split_axis]
    if ns % p:
        raise ValueError(f"split axis extent {ns} not divisible by {p}")
    c = ns // p
    nc = x.shape[concat_axis]
    i = lax.axis_index(axis_name)

    def chunk_for(dst):
        return lax.dynamic_slice_in_dim(x, dst * c, c, axis=split_axis)

    out_shape = list(x.shape)
    out_shape[split_axis] = c
    out_shape[concat_axis] = nc * p
    buf = jnp.zeros(tuple(out_shape), x.dtype)

    def place(buf, chunk, src):
        return lax.dynamic_update_slice_in_dim(buf, chunk, src * nc, axis=concat_axis)

    buf = place(buf, chunk_for(i), i)  # own chunk stays put
    for s in range(1, p):
        send = chunk_for((i - s) % p)
        recv = lax.ppermute(
            send, axis_name, perm=[(j, (j - s) % p) for j in range(p)]
        )
        buf = place(buf, recv, (i + s) % p)
    return buf


# --------------------------------------------------- pipelined t2/t3 overlap

def _hierarchical_pipelined(
    x,
    axis_name,
    *,
    split_axis: int,
    concat_axis: int,
    axis_size: int,
    axis_sizes: tuple[int, int],
    wire_dtype: str | None,
    bounds: list[tuple[int, int]],
    chunk_axis: int,
    compute=None,
    compute_name: str = "t3_fft",
    compute_takes_bounds: bool = False,
):
    """Leg-level pipelined hierarchical exchange over K > 1 chunks: a
    two-deep software pipeline in which chunk ``i``'s intra-slice ICI
    all-to-all is issued while chunk ``i-1``'s inter-slice DCN
    all-to-all and downstream ``compute`` run — so the cheap fast-fabric
    leg hides under the slow-fabric leg plus the t3 FFT of the previous
    chunk, instead of the two legs of every chunk serializing in flat
    chunk order.

    Per chunk the math is exactly ``pad -> encode -> leg_ici -> leg_dcn
    -> decode`` — the same ops :func:`hierarchical_all_to_all` fuses
    (its legs compose bit-identically), so the pipelined schedule is
    bit-identical to the monolithic hierarchical exchange at every K;
    only the issue order changes. Each leg carries a per-chunk span
    (``t2a_exchange_<ici>[k]`` / ``t2b_exchange_<dcn>[k]``, both
    normalizing to the ``t2`` stage key) so the staged view shows the
    interleave. ``compute=None`` is the staged tier: exchange-only,
    chunks concatenated back."""
    tree = jax.tree_util
    dcn_name, ici_name, _, _ = _hier_names_sizes(axis_name, axis_sizes)
    leg_ici, leg_dcn = hierarchical_legs(
        axis_name, split_axis=split_axis, concat_axis=concat_axis,
        axis_sizes=axis_sizes)
    codec = wire_codec(wire_dtype) if wire_dtype is not None else None
    a_name = f"t2a_exchange_{_axis_label(ici_name)}"
    b_name = f"t2b_exchange_{_axis_label(dcn_name)}"
    leaves, treedef = tree.tree_flatten(x)
    dtypes = [u.dtype for u in leaves]

    def take(lo, hi):
        return [lax.slice_in_dim(u, lo, hi, axis=chunk_axis)
                for u in leaves]

    def leg_a(k, chunk_leaves):
        with add_trace(f"{a_name}[{k}]"):
            out = []
            for u in chunk_leaves:
                u = _pad_axis(u, split_axis,
                              pad_to(u.shape[split_axis], axis_size))
                parts = (codec.encode(u, tile_axis=split_axis,
                                      tiles=axis_size)
                         if codec else (u,))
                out.append(tuple(leg_ici(w) for w in parts))
            return out

    def leg_b(k, enc_leaves):
        with add_trace(f"{b_name}[{k}]"):
            out = []
            for parts, dt in zip(enc_leaves, dtypes):
                done = tuple(leg_dcn(w) for w in parts)
                out.append(codec.decode(done, dt, tile_axis=concat_axis,
                                        tiles=axis_size)
                           if codec else done[0])
            return tree.tree_unflatten(treedef, out)

    def run_chunk(k, y):
        if compute is None:
            return y
        with add_trace(f"{compute_name}[{k}]"):
            return (compute(y, *bounds[k]) if compute_takes_bounds
                    else compute(y))

    parts_out = []
    inflight = leg_a(0, take(*bounds[0]))
    for k in range(1, len(bounds)):
        nxt = leg_a(k, take(*bounds[k]))  # chunk k's ICI leg issues
        parts_out.append(run_chunk(k - 1, leg_b(k - 1, inflight)))
        inflight = nxt                    # ... before chunk k-1's DCN+t3
    last = len(bounds) - 1
    parts_out.append(run_chunk(last, leg_b(last, inflight)))
    return tree.tree_map(
        lambda *ps: jnp.concatenate(ps, axis=chunk_axis), *parts_out)


def overlap_chunk_bounds(extent: int, k: int) -> list[tuple[int, int]]:
    """Static (start, stop) bounds of the overlap chunks along the
    bystander axis: balanced splits (``numpy.array_split`` semantics —
    the first ``extent % k`` chunks one element longer), so a K that does
    not divide the extent still yields exactly K non-empty chunks.
    K is clamped to the extent (at most one chunk per element) and to a
    floor of 1."""
    extent = int(extent)
    k = max(1, min(int(k), max(extent, 1)))
    base, rem = divmod(extent, k)
    bounds = []
    start = 0
    for i in range(k):
        stop = start + base + (1 if i < rem else 0)
        bounds.append((start, stop))
        start = stop
    return bounds


def exchange_overlapped(
    x,
    axis_name,
    *,
    split_axis: int,
    concat_axis: int,
    axis_size: int,
    compute,
    overlap_chunks: int = 1,
    chunk_axis: int | None = None,
    algorithm: str = "alltoall",
    platform: str | None = None,
    axis_sizes: tuple[int, int] | None = None,
    wire_dtype: str | None = None,
    exchange_name: str = "t2_exchange",
    compute_name: str = "t3_fft",
    compute_takes_bounds: bool = False,
):
    """Pipelined global transpose + downstream compute (t2 ↔ t3 overlap).

    Splits the local block into ``overlap_chunks`` chunks along
    ``chunk_axis`` (default: the bystander axis ``3 - split - concat``,
    which neither the exchange nor ``compute`` may transform), exchanges
    each chunk independently, and applies ``compute`` (crop + downstream
    1D FFT) per exchanged chunk, concatenating the results back along the
    chunk axis. The schedule is software-pipelined: chunk ``i``'s exchange
    is issued *before* chunk ``i-1``'s compute, so XLA's async collectives
    (collective start/done) can run chunk ``i``'s ICI transfer under chunk
    ``i-1``'s MXU/VPU work — the ``MPI_Waitany`` overlap loop of the
    reference's pipelined p2p transport (``fft_mpi_3d_api.cpp:610-699``),
    expressed as K independent collectives the latency-hiding scheduler is
    free to hoist.

    ``x`` may be a single array or any pytree of same-shape arrays (the dd
    tier's (hi, lo) pair); ``compute`` maps the exchanged pytree chunk.
    Chunking is along a batch axis only, so every per-chunk exchange and
    FFT sees exactly the lines the monolithic path sees: the result is
    bit-identical to ``overlap_chunks=1``.

    ``overlap_chunks <= 1`` (or a 1-extent chunk axis) degenerates to the
    monolithic exchange + compute with today's HLO and the original
    un-suffixed trace spans; K > 1 emits ``{exchange_name}[k]`` /
    ``{compute_name}[k]`` spans so the PR 1 timeline shows the interleave.
    The hierarchical transport at K > 1 pipelines one level deeper
    (:func:`_hierarchical_pipelined`): chunk ``i``'s ICI leg is issued
    while chunk ``i-1``'s DCN leg and compute run, with per-leg
    ``t2a[k]``/``t2b[k]`` spans — bit-identical to the fused two-leg
    exchange per chunk.

    ``compute_takes_bounds=True`` calls ``compute(chunk, lo, hi)`` with
    the chunk's static (start, stop) bounds along ``chunk_axis`` — the
    midpoint hook of the fused spectral-operator chains, whose
    wavenumber-indexed pointwise multiplier must be generated for
    exactly the chunk's global slice (the bystander axis keeps global
    positions through the exchange, so the bounds ARE the slice).
    """
    tree = jax.tree_util
    leaves = tree.tree_leaves(x)
    if chunk_axis is None:
        chunk_axis = 3 - split_axis - concat_axis
    ex_kw = dict(split_axis=split_axis, concat_axis=concat_axis,
                 axis_size=axis_size, algorithm=algorithm, platform=platform,
                 axis_sizes=axis_sizes, wire_dtype=wire_dtype)
    extent = leaves[0].shape[chunk_axis] if leaves else 1
    bounds = overlap_chunk_bounds(extent, overlap_chunks)
    if len(bounds) <= 1:
        with add_trace(exchange_name):
            y = tree.tree_map(
                lambda u: exchange_uneven(u, axis_name, **ex_kw), x)
        with add_trace(compute_name):
            return (compute(y, 0, extent) if compute_takes_bounds
                    else compute(y))
    if algorithm == "hierarchical":
        # Leg-level two-deep pipeline: chunk i's ICI leg issues while
        # chunk i-1's DCN leg and downstream compute run — bit-identical
        # to the per-chunk fused hierarchical exchange, with per-leg
        # per-chunk spans replacing the flat chunk order.
        return _hierarchical_pipelined(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
            axis_size=axis_size, axis_sizes=axis_sizes,
            wire_dtype=wire_dtype, bounds=bounds, chunk_axis=chunk_axis,
            compute=compute, compute_name=compute_name,
            compute_takes_bounds=compute_takes_bounds)

    def take(lo, hi):
        return tree.tree_map(
            lambda u: lax.slice_in_dim(u, lo, hi, axis=chunk_axis), x)

    def exch(i, chunk):
        with add_trace(f"{exchange_name}[{i}]"):
            return tree.tree_map(
                lambda u: exchange_uneven(u, axis_name, **ex_kw), chunk)

    def run_chunk(i, chunk):
        if compute_takes_bounds:
            return compute(chunk, *bounds[i])
        return compute(chunk)

    parts = []
    inflight = exch(0, take(*bounds[0]))
    for i in range(1, len(bounds)):
        nxt = exch(i, take(*bounds[i]))  # issued before chunk i-1's compute
        with add_trace(f"{compute_name}[{i - 1}]"):
            parts.append(run_chunk(i - 1, inflight))
        inflight = nxt
    with add_trace(f"{compute_name}[{len(bounds) - 1}]"):
        parts.append(run_chunk(len(bounds) - 1, inflight))
    return tree.tree_map(
        lambda *ps: jnp.concatenate(ps, axis=chunk_axis), *parts)


def exchange_chunked(
    x,
    axis_name,
    *,
    split_axis: int,
    concat_axis: int,
    axis_size: int,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    chunk_axis: int | None = None,
    exchange_name: str = "t2_exchange",
    uneven: bool = False,
    platform: str | None = None,
    axis_sizes: tuple[int, int] | None = None,
    wire_dtype: str | None = None,
):
    """The staged-pipeline tier of the overlap mode: K independent
    per-chunk exchanges inside ONE stage jit. Stage boundaries are
    dispatch barriers, so true t2/t3 overlap belongs to the fused
    builders (:func:`exchange_overlapped`); the staged pipelines keep the
    same K-collective transport shape so their per-stage timing and the
    lowering pins describe the overlapped chains. Tree-generic (the dd
    (hi, lo) pair rides through). Most stage boundaries carry
    ceil-padded arrays and chunk the plain :func:`exchange`;
    ``uneven=True`` routes through :func:`exchange_uneven` for stages
    whose split axis is not pre-padded (the dd slab stage pipeline).
    ``overlap_chunks <= 1`` is exactly today's single exchange."""
    tree = jax.tree_util
    if chunk_axis is None:
        chunk_axis = 3 - split_axis - concat_axis
    leaves = tree.tree_leaves(x)
    extent = leaves[0].shape[chunk_axis] if leaves else 1
    bounds = overlap_chunk_bounds(extent, overlap_chunks)
    kw = dict(split_axis=split_axis, concat_axis=concat_axis,
              axis_size=axis_size, algorithm=algorithm,
              axis_sizes=axis_sizes, wire_dtype=wire_dtype)
    if uneven:
        one = lambda u: exchange_uneven(u, axis_name, platform=platform,
                                        **kw)
    else:
        one = lambda u: exchange(u, axis_name, **kw)
    if len(bounds) <= 1:
        return tree.tree_map(one, x)
    if algorithm == "hierarchical":
        # The staged tier of the leg-level pipeline: K per-leg chunked
        # collectives inside ONE stage jit, issued in the same two-deep
        # order as the fused chain (chunk i's ICI leg before chunk
        # i-1's DCN leg) with the same t2a[k]/t2b[k] spans — replacing
        # the old flat-order per-chunk fallback.
        return _hierarchical_pipelined(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
            axis_size=axis_size, axis_sizes=axis_sizes,
            wire_dtype=wire_dtype, bounds=bounds, chunk_axis=chunk_axis)
    parts = []
    for i, (lo, hi) in enumerate(bounds):
        chunk = tree.tree_map(
            lambda u: lax.slice_in_dim(u, lo, hi, axis=chunk_axis), x)
        with add_trace(f"{exchange_name}[{i}]"):
            parts.append(tree.tree_map(one, chunk))
    return tree.tree_map(
        lambda *ps: jnp.concatenate(ps, axis=chunk_axis), *parts)
