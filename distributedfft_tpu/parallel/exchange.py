"""Global-transpose exchange algorithms over a mesh axis.

The reference exposes a menu of distributed-transpose transports: heFFTe's
``reshape_algorithm`` enum {alltoall, alltoallv, p2p, p2p_plined}
(``heffte/heffteBenchmark/include/heffte_plan_logic.h:47-56``;
implementations ``src/heffte_reshape3d.cpp:268,375,497-625``) and the
first-party engine's hand-rolled peer DMA + MPI_Isend/Irecv tables
(``3dmpifft_opt/include/fft_mpi_3d_api.cpp:610-699``).

The TPU-native menu has three entries, selected per plan:

- ``"alltoall"`` — one ``jax.lax.all_to_all`` on the mesh axis. XLA lowers
  this to the platform all-to-all riding ICI; the analog of
  ``MPI_Alltoall`` with equal (ceil-padded) counts
  (``reshape3d_alltoall``, ``src/heffte_reshape3d.cpp:268`` pads to equal
  counts the same way).
- ``"alltoallv"`` — one ``jax.lax.ragged_all_to_all`` shipping each peer's
  TRUE slice of the split axis (no split-axis padding on the wire) — the
  analog of ``MPI_Alltoallv`` with the exact per-peer count tables
  (``reshape3d_alltoallv``, ``src/heffte_reshape3d.cpp:375``;
  count/offset semantics = ``dfft_exchange_table``,
  ``native/dfft_native.cpp``). Concat-axis padding (each sender's equal
  ceil-chunk block, zero rows on the tail device) is inherent to the SPMD
  equal-shard layout and still travels.
- ``"ppermute"`` — an explicit (P-1)-step ring of ``jax.lax.ppermute``
  neighbor shifts, each step moving one peer's chunk. The analog of the
  pipelined point-to-point path (``reshape3d_pointtopoint``,
  ``src/heffte_reshape3d.cpp:497-625``): per-step transfers are
  nearest-neighbor permutes that map 1:1 onto ICI ring links, and XLA can
  overlap each step's transfer with the next step's slice/update work.

``alltoall``/``ppermute`` require equal chunk sizes — the ceil-pad/crop
scheme of :mod:`.slab` / :mod:`.pencil` (via :func:`exchange_uneven`)
guarantees that; ``alltoallv`` takes the unpadded split axis directly.

On top of the transport menu, :func:`exchange_overlapped` provides the
*pipelined* execution mode: the local block is split into K chunks along
the bystander (non-split, non-concat) axis, and chunk ``i``'s exchange is
issued before chunk ``i-1``'s downstream FFT — the TPU-native analog of
the reference's ``MPI_Waitany``-ordered overlap loop
(``3dmpifft_opt/include/fft_mpi_3d_api.cpp:610-699``, heFFTe's pipelined
p2p ``src/heffte_reshape3d.cpp:497-625``), with XLA's async collectives
(start/done pairs) playing the Isend/Irecv role.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..geometry import pad_to
from ..utils.trace import add_trace

#: Flat transports: the whole mesh axis is one collective's domain.
FLAT_ALGORITHMS = ("alltoall", "alltoallv", "ppermute")
#: Full menu, including the two-leg ICI/DCN transport (hybrid meshes
#: only — see :func:`hierarchical_all_to_all`).
ALGORITHMS = FLAT_ALGORITHMS + ("hierarchical",)

#: Which :func:`..plan_logic.exchange_payloads` byte entry each transport
#: actually ships on the wire — shared by the per-execute byte counters
#: (api) and the tuner's candidate-pruning model, so wire accounting can
#: never disagree between the two. The hierarchical transport's payload
#: entries are already per-leg (dense within each leg's axis), so it
#: reads the dense key of each leg entry.
WIRE_BYTE_KEYS = {
    "alltoall": "alltoall_bytes",
    "ppermute": "alltoall_bytes",   # the padded ring ships the pads too
    "alltoallv": "alltoallv_bytes",
    "hierarchical": "alltoall_bytes",
}

#: Bytes one complex element occupies on the wire under each compression
#: mode: bf16 ships a (real, imag) bfloat16 pair — 4 bytes regardless of
#: the payload's complex width (half of c64, quarter of c128).
WIRE_DTYPES = (None, "bf16")
_WIRE_PAIR_BYTES = {"bf16": 4}


def wire_itemsize(itemsize: int, wire_dtype: str | None) -> int:
    """Per-element bytes actually on the wire for a payload of
    ``itemsize``-byte complex elements under ``wire_dtype`` compression
    (``None`` = the payload travels as-is)."""
    if wire_dtype is None:
        return int(itemsize)
    try:
        return _WIRE_PAIR_BYTES[wire_dtype]
    except KeyError:
        raise ValueError(
            f"unknown wire_dtype {wire_dtype!r}; use one of {WIRE_DTYPES}"
        ) from None


def transport_steps(algorithm: str, parts: int) -> int:
    """Sequential collective launches one exchange pays on ``parts``
    devices: the fused transports are one launch; the explicit ring is
    ``parts - 1`` neighbor shifts (each a dependent ppermute); the
    hierarchical transport is two dependent axis-local collectives
    (the ``parts`` here are one LEG's parts — each leg entry is priced
    separately, one launch per leg). The latency term of the tuner's
    analytical cost model."""
    if algorithm == "ppermute":
        return max(1, parts - 1)
    return 1


def exchange_model_seconds(
    wire_bytes_per_dev: float,
    parts: int,
    algorithm: str,
    *,
    wire_gbps: float,
    launch_seconds: float,
    overlap_chunks: int = 1,
    hide_seconds: float = 0.0,
    batch: int = 1,
) -> dict:
    """Analytical time model of ONE exchange under one transport — the
    single source of truth shared by the tuner's candidate-pruning cost
    (:func:`..tuner.model_cost`) and the explain layer's per-stage
    prediction, so the two can never disagree about what the model says.

    ``seconds`` is the raw exchange time (wire transfer at ``wire_gbps``
    plus ``transport_steps`` launch latencies); ``exposed_seconds`` is
    what remains on the critical path at ``overlap_chunks = K`` with
    ``hide_seconds`` of downstream compute available to hide under:
    ``t/K + max(0, t - hide) * (K-1)/K`` plus the K-1 extra launches each
    additional chunk costs (the crossover model behind
    ``auto_overlap_chunks``; docs/MFU_ANALYSIS.md "Exchange/compute
    overlap").

    ``batch`` scales the wire transfer for a batched chain: B coalesced
    transforms ride ONE collective as a bystander dim, so the payload
    grows B-fold while the ``transport_steps`` launch latencies are paid
    once — the whole point of batching the exchange. Callers passing
    bytes already scaled by B (``exchange_payloads`` of a batched
    LogicPlan) keep the default 1."""
    steps = transport_steps(algorithm, parts)
    t_ex = (max(1, int(batch)) * wire_bytes_per_dev / (wire_gbps * 1e9)
            + steps * launch_seconds)
    k = max(1, int(overlap_chunks))
    exposed = (t_ex / k
               + max(0.0, t_ex - hide_seconds) * (k - 1) / k
               + (k - 1) * steps * launch_seconds)
    return {"seconds": t_ex, "exposed_seconds": exposed, "steps": steps}


# ------------------------------------------------------ wire compression

def wire_encode(x: jnp.ndarray, wire_dtype: str) -> jnp.ndarray:
    """Cast a complex payload to its on-wire representation immediately
    before the collective: ``"bf16"`` stacks (real, imag) as a trailing
    bfloat16 pair — half the wire bytes of c64 at ~2^-9 relative
    rounding per component. The trailing wire dim is a bystander of
    every transport (split/concat/chunk axes keep their indices)."""
    if wire_dtype != "bf16":
        raise ValueError(
            f"unknown wire_dtype {wire_dtype!r}; use one of {WIRE_DTYPES}")
    if not jnp.issubdtype(x.dtype, jnp.complexfloating):
        raise TypeError(
            f"wire compression applies to complex exchange payloads, "
            f"got {x.dtype}")
    return jnp.stack([x.real, x.imag], axis=-1).astype(jnp.bfloat16)


def wire_decode(y: jnp.ndarray, dtype) -> jnp.ndarray:
    """Inverse of :func:`wire_encode`: trailing (real, imag) wire pair
    back to the complex payload dtype, immediately after the
    collective."""
    rdt = jnp.float64 if jnp.dtype(dtype) == jnp.complex128 else jnp.float32
    r = y[..., 0].astype(rdt)
    i = y[..., 1].astype(rdt)
    return lax.complex(r, i).astype(dtype)


def wire_roundtrip_error(dtype, wire_dtype: str | None = "bf16",
                         n: int = 4096) -> float:
    """Measured relative round-trip error of one wire cast
    (``max |decode(encode(x)) - x| / max |x|`` over a seeded
    standard-normal complex block) — the number the tuner's error-budget
    filter and ``explain``'s ``wire.compression_err`` field report.
    Deterministic (fixed seed) and cached per (dtype, wire_dtype), so
    per-candidate pruning never re-measures. 0.0 for the exact wire."""
    if wire_dtype is None:
        return 0.0
    key = (str(np.dtype(dtype)), wire_dtype, int(n))
    hit = _WIRE_ERR_CACHE.get(key)
    if hit is not None:
        return hit
    rng = np.random.default_rng(0)
    x = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.dtype(dtype))
    y = np.asarray(wire_decode(wire_encode(jnp.asarray(x), wire_dtype),
                               dtype))
    err = float(np.max(np.abs(y - x)) / np.max(np.abs(x)))
    _WIRE_ERR_CACHE[key] = err
    return err


_WIRE_ERR_CACHE: dict = {}


def _axis_label(axis_name) -> str:
    """Stage-span label of a mesh axis spec: the name itself, or
    ``a+b`` for a combined (hierarchical) axis tuple."""
    if isinstance(axis_name, (tuple, list)):
        return "+".join(str(a) for a in axis_name)
    return str(axis_name)


def _pad_axis(x: jnp.ndarray, axis: int, to: int) -> jnp.ndarray:
    """Zero-pad ``axis`` up to extent ``to`` (no-op when already there).
    Single definition shared by every chain builder and exchange path — the
    dense and ragged paths depend on bit-identical padding."""
    if x.shape[axis] == to:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, to - x.shape[axis])
    return jnp.pad(x, pads)


def _crop_axis(x: jnp.ndarray, axis: int, to: int) -> jnp.ndarray:
    if x.shape[axis] == to:
        return x
    return lax.slice_in_dim(x, 0, to, axis=axis)


def exchange(
    x: jnp.ndarray,
    axis_name,
    *,
    split_axis: int,
    concat_axis: int,
    axis_size: int,
    algorithm: str = "alltoall",
    axis_sizes: tuple[int, int] | None = None,
    wire_dtype: str | None = None,
) -> jnp.ndarray:
    """Tiled all-to-all on ``axis_name`` inside ``shard_map``.

    Splits the local block into ``axis_size`` chunks along ``split_axis`` and
    concatenates the chunks received from every peer along ``concat_axis``
    (the semantics of ``lax.all_to_all(..., tiled=True)``).

    ``axis_name`` is one mesh axis name, or — for the flat transports on a
    hybrid mesh and for ``"hierarchical"`` — a (dcn, ici) tuple of names
    whose combined extent is ``axis_size`` (``axis_sizes`` gives the
    per-axis factors the hierarchical legs need). ``wire_dtype`` casts the
    payload to its on-wire form immediately before the collective and back
    after (:func:`wire_encode`); ``None`` ships the payload as-is —
    byte-identical to the pre-compression HLO.
    """
    if wire_dtype is not None:
        w = wire_encode(x, wire_dtype)
        y = exchange(w, axis_name, split_axis=split_axis,
                     concat_axis=concat_axis, axis_size=axis_size,
                     algorithm=algorithm, axis_sizes=axis_sizes)
        return wire_decode(y, x.dtype)
    if algorithm == "alltoall":
        return lax.all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
        )
    if algorithm == "alltoallv":
        return ragged_all_to_all_exchange(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
            p=axis_size,
        )
    if algorithm == "ppermute":
        return ring_all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis, p=axis_size
        )
    if algorithm == "hierarchical":
        return hierarchical_all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
            axis_sizes=axis_sizes,
        )
    raise ValueError(f"unknown exchange algorithm {algorithm!r}; use {ALGORITHMS}")


def exchange_uneven(
    x: jnp.ndarray,
    axis_name,
    *,
    split_axis: int,
    concat_axis: int,
    axis_size: int,
    algorithm: str = "alltoall",
    platform: str | None = None,
    axis_sizes: tuple[int, int] | None = None,
    wire_dtype: str | None = None,
) -> jnp.ndarray:
    """Exchange whose split-axis extent need not divide ``axis_size``.

    The dense algorithms ceil-pad the split axis first (the reference's
    padded-equal-counts strategy, ``src/heffte_reshape3d.cpp:268``);
    ``alltoallv`` ships the true slices unpadded. Either way the result's
    split axis holds the local ceil-chunk (padded at the tail) and the
    concat axis holds ``axis_size`` ceil-chunks per sender — callers crop
    the concat axis to its true extent exactly as before. ``platform`` is
    the mesh devices' platform (used by ``alltoallv`` to pick the real
    ragged collective vs its CPU mirror). ``wire_dtype`` wraps the whole
    exchange (both hierarchical legs ride one encoded payload) in the
    on-wire cast pair; ``axis_sizes`` as in :func:`exchange`.
    """
    if wire_dtype is not None:
        w = wire_encode(x, wire_dtype)
        y = exchange_uneven(w, axis_name, split_axis=split_axis,
                            concat_axis=concat_axis, axis_size=axis_size,
                            algorithm=algorithm, platform=platform,
                            axis_sizes=axis_sizes)
        return wire_decode(y, x.dtype)
    if algorithm == "alltoallv":
        return ragged_all_to_all_exchange(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
            p=axis_size, platform=platform,
        )
    x = _pad_axis(x, split_axis, pad_to(x.shape[split_axis], axis_size))
    return exchange(x, axis_name, split_axis=split_axis,
                    concat_axis=concat_axis, axis_size=axis_size,
                    algorithm=algorithm, axis_sizes=axis_sizes)


# ----------------------------------------------- hierarchical (ICI/DCN)

def _hier_names_sizes(axis_name, axis_sizes) -> tuple[str, str, int, int]:
    """Validate and unpack the (dcn, ici) axis pair of a hierarchical
    exchange."""
    if not (isinstance(axis_name, (tuple, list)) and len(axis_name) == 2):
        raise ValueError(
            "hierarchical exchange needs a (dcn, ici) mesh-axis name "
            f"pair, got {axis_name!r}")
    if not (isinstance(axis_sizes, (tuple, list)) and len(axis_sizes) == 2):
        raise ValueError(
            "hierarchical exchange needs axis_sizes=(dcn_parts, "
            f"ici_parts), got {axis_sizes!r}")
    dcn_name, ici_name = axis_name
    d, i = int(axis_sizes[0]), int(axis_sizes[1])
    return dcn_name, ici_name, d, i


def _regroup_split(x: jnp.ndarray, split_axis: int, a: int, b: int,
                   c: int) -> jnp.ndarray:
    """Local reindex between the two legs: view ``split_axis`` as
    ``[a, b, c]`` chunk factors and swap the leading two — the
    destination-index transpose that turns flat chunk order into the
    order each leg's tiled all-to-all expects."""
    shp = x.shape
    pre, post = shp[:split_axis], shp[split_axis + 1:]
    x = x.reshape(pre + (a, b, c) + post)
    perm = list(range(x.ndim))
    i0 = len(pre)
    perm[i0], perm[i0 + 1] = perm[i0 + 1], perm[i0]
    return x.transpose(perm).reshape(pre + (a * b * c,) + post)


def hierarchical_all_to_all(
    x: jnp.ndarray,
    axis_name,
    *,
    split_axis: int,
    concat_axis: int,
    axis_sizes: tuple[int, int],
) -> jnp.ndarray:
    """Two-leg topology-aware all-to-all over a hybrid (dcn x ici) axis
    pair: an intra-slice tiled all-to-all on the ICI axis, a local
    reindex, and an inter-slice tiled all-to-all on the DCN axis — each
    leg riding the link it was built for, instead of one flat collective
    the compiler routes across both fabrics at once (the 2.5D
    decomposition of "Collective-Optimized FFTs", arXiv 2306.16589; the
    reference's analogous split is peer-DMA within a node vs MPI across,
    ``fft_mpi_3d_api.cpp:627-672``).

    Bit-identical to the flat tiled all-to-all over the combined axis:
    with device index ``i = d*I + e`` (the row-major linearization of a
    ``P((dcn, ici))`` sharding), the ICI leg delivers every chunk to its
    destination's ici coordinate within each slice, the DCN leg to its
    destination slice, and the final local reindex lays the P sender
    chunks onto ``concat_axis`` in sender-major order — exactly the
    ``tiled=True`` contract. Requires ``split_axis`` extent divisible by
    ``D * I`` (the ceil-pad discipline of :func:`exchange_uneven`).

    The two legs carry ``t2a_exchange_<ici>`` / ``t2b_exchange_<dcn>``
    trace spans (both normalize to the ``t2`` stage key), so the explain
    layer attributes each leg separately.
    """
    dcn_name, ici_name, d, i = _hier_names_sizes(axis_name, axis_sizes)
    p = d * i
    S = x.shape[split_axis]
    if S % p:
        raise ValueError(
            f"split axis extent {S} not divisible by {p} (= {d} dcn x "
            f"{i} ici); hierarchical exchange takes the ceil-padded axis")
    c = S // p
    # Leg A (ICI): destination-e-major chunk order, intra-slice a2a.
    with add_trace(f"t2a_exchange_{_axis_label(ici_name)}"):
        v = _regroup_split(x, split_axis, d, i, c)
        v = lax.all_to_all(v, ici_name, split_axis=split_axis,
                           concat_axis=split_axis, tiled=True)
    # Leg B (DCN): destination-d-major order, inter-slice a2a.
    with add_trace(f"t2b_exchange_{_axis_label(dcn_name)}"):
        v = _regroup_split(v, split_axis, i, d, c)
        v = lax.all_to_all(v, dcn_name, split_axis=split_axis,
                           concat_axis=split_axis, tiled=True)
    # Final local reindex: the split axis now holds the P sender-major
    # chunks [(d_src, e_src), c]; lay them onto the concat axis exactly
    # where the flat tiled all-to-all would.
    shp = v.shape
    pre, post = shp[:split_axis], shp[split_axis + 1:]
    v = v.reshape(pre + (p, c) + post)
    v = jnp.moveaxis(v, split_axis, concat_axis)
    shp2 = v.shape
    out = list(shp2)
    out[concat_axis:concat_axis + 2] = [shp2[concat_axis]
                                        * shp2[concat_axis + 1]]
    return v.reshape(out)


def hierarchical_legs(
    axis_name,
    *,
    split_axis: int,
    concat_axis: int,
    axis_sizes: tuple[int, int],
):
    """The two legs of :func:`hierarchical_all_to_all` as separate
    callables ``(leg_ici, leg_dcn)`` — the staged-pipeline view, so the
    per-stage timing harness (and ``dfft.explain``) can bracket each leg
    as its own ``t2a``/``t2b`` stage. ``leg_dcn`` includes the final
    sender-major reindex onto ``concat_axis``; composing
    ``leg_dcn(leg_ici(x))`` is bit-identical to the fused transport."""
    dcn_name, ici_name, d, i = _hier_names_sizes(axis_name, axis_sizes)
    p = d * i

    def leg_ici(x):
        c = x.shape[split_axis] // p
        v = _regroup_split(x, split_axis, d, i, c)
        return lax.all_to_all(v, ici_name, split_axis=split_axis,
                              concat_axis=split_axis, tiled=True)

    def leg_dcn(v):
        c = v.shape[split_axis] // p
        v = _regroup_split(v, split_axis, i, d, c)
        v = lax.all_to_all(v, dcn_name, split_axis=split_axis,
                           concat_axis=split_axis, tiled=True)
        shp = v.shape
        pre, post = shp[:split_axis], shp[split_axis + 1:]
        v = v.reshape(pre + (p, c) + post)
        v = jnp.moveaxis(v, split_axis, concat_axis)
        shp2 = v.shape
        out = list(shp2)
        out[concat_axis:concat_axis + 2] = [shp2[concat_axis]
                                            * shp2[concat_axis + 1]]
        return v.reshape(out)

    return leg_ici, leg_dcn


def ragged_all_to_all_exchange(
    x: jnp.ndarray, axis_name: str, *, split_axis: int, concat_axis: int,
    p: int, platform: str | None = None,
) -> jnp.ndarray:
    """All-to-all transpose shipping each peer's TRUE split-axis slice.

    The MPI_Alltoallv analog (``reshape3d_alltoallv``,
    ``src/heffte_reshape3d.cpp:375``): where the dense path pads the split
    axis to ``p * ceil(S/p)`` and ships the padding, this sends peer ``j``
    exactly its ``sizes[j]`` true elements via ``lax.ragged_all_to_all``.
    The per-peer counts/offsets follow the ceil-split ownership convention —
    the same tables ``dfft_exchange_table`` computes (elements =
    ``rows * sizes[j] * inner``).

    Takes the UNPADDED split axis (extent S = the true global extent of the
    post-exchange sharded axis); returns the same shape the padded path
    would: split axis -> local ceil chunk ``c``, concat axis ->
    ``p * local_chunk`` (each sender's equal-size block, tail padding
    included — that padding is the SPMD equal-shard layout itself and is
    cropped by the caller, never transformed).
    """
    import jax

    from ..utils.compat import force_real_lowering

    S = x.shape[split_axis]
    c = -(-S // p)
    if platform is None:
        platform = jax.default_backend()
    if platform == "cpu" and not force_real_lowering():
        # XLA:CPU has no ragged-all-to-all lowering; the ceil-padded dense
        # exchange produces the bit-identical result (the padding positions
        # the ragged path never writes stay zero either way), so the CPU
        # test backend mirrors through it — the same discipline as the
        # Pallas kernel's interpreter-mode mirror (and the same
        # force_real_lowering override for chipless lowering tests).
        x = _pad_axis(x, split_axis, p * c)
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)
    i = lax.axis_index(axis_name)
    # Static per-peer ownership of the split axis (ceil splits, short/empty
    # tail) — the dfft_exchange_table geometry.
    bounds = np.minimum(np.arange(p + 1) * c, S)
    starts, sizes = bounds[:-1], np.diff(bounds)

    xt = jnp.moveaxis(x, split_axis, 0)
    rest = xt.shape[1:]
    out = jnp.zeros((p * c,) + rest, x.dtype)
    my_size = jnp.minimum((i + 1) * c, S) - jnp.minimum(i * c, S)
    y = lax.ragged_all_to_all(
        xt, out,
        jnp.asarray(starts, jnp.int32),
        jnp.asarray(sizes, jnp.int32),
        # Sender i's slice lands at leading offset i*c on every receiver.
        jnp.full((p,), i * c, jnp.int32),
        jnp.full((p,), my_size, jnp.int32),
        axis_name=axis_name,
    )
    # y: [p, c, *rest] with the sender dim to be merged into the concat
    # axis (sender-major) and the local split chunk moved back into place.
    y = y.reshape((p, c) + rest)
    cpos = 1 + (concat_axis if concat_axis < split_axis else concat_axis - 1)
    perm = [1]
    for k in range(len(rest)):
        ax = 2 + k
        if k == cpos - 1:
            perm.extend([0, ax])
        else:
            perm.append(ax)
    y = y.transpose(perm)
    j = perm.index(0)
    shp = list(y.shape)
    shp[j:j + 2] = [p * shp[j + 1]]
    y = y.reshape(shp)
    return jnp.moveaxis(y, 0, split_axis)


def ring_all_to_all(
    x: jnp.ndarray, axis_name: str, *, split_axis: int, concat_axis: int, p: int
) -> jnp.ndarray:
    """All-to-all as a (P-1)-step ``ppermute`` ring.

    Step ``s`` shifts by ``s`` around the ring: device ``i`` sends the chunk
    destined for ``(i - s) % p`` and receives its own chunk from
    ``(i + s) % p``. Each step is a uniform neighbor permutation (distance-s
    rotation), so on a physical ICI ring/torus every step uses disjoint
    links; the Python loop unrolls at trace time (P is static), letting XLA
    pipeline transfer ``s`` with the slice/update of step ``s+1`` — the role
    of ``MPI_Waitany``-driven overlap in the reference's pipelined p2p path
    (``src/heffte_reshape3d.cpp:611``).
    """
    ns = x.shape[split_axis]
    if ns % p:
        raise ValueError(f"split axis extent {ns} not divisible by {p}")
    c = ns // p
    nc = x.shape[concat_axis]
    i = lax.axis_index(axis_name)

    def chunk_for(dst):
        return lax.dynamic_slice_in_dim(x, dst * c, c, axis=split_axis)

    out_shape = list(x.shape)
    out_shape[split_axis] = c
    out_shape[concat_axis] = nc * p
    buf = jnp.zeros(tuple(out_shape), x.dtype)

    def place(buf, chunk, src):
        return lax.dynamic_update_slice_in_dim(buf, chunk, src * nc, axis=concat_axis)

    buf = place(buf, chunk_for(i), i)  # own chunk stays put
    for s in range(1, p):
        send = chunk_for((i - s) % p)
        recv = lax.ppermute(
            send, axis_name, perm=[(j, (j - s) % p) for j in range(p)]
        )
        buf = place(buf, recv, (i + s) % p)
    return buf


# --------------------------------------------------- pipelined t2/t3 overlap

def overlap_chunk_bounds(extent: int, k: int) -> list[tuple[int, int]]:
    """Static (start, stop) bounds of the overlap chunks along the
    bystander axis: balanced splits (``numpy.array_split`` semantics —
    the first ``extent % k`` chunks one element longer), so a K that does
    not divide the extent still yields exactly K non-empty chunks.
    K is clamped to the extent (at most one chunk per element) and to a
    floor of 1."""
    extent = int(extent)
    k = max(1, min(int(k), max(extent, 1)))
    base, rem = divmod(extent, k)
    bounds = []
    start = 0
    for i in range(k):
        stop = start + base + (1 if i < rem else 0)
        bounds.append((start, stop))
        start = stop
    return bounds


def exchange_overlapped(
    x,
    axis_name,
    *,
    split_axis: int,
    concat_axis: int,
    axis_size: int,
    compute,
    overlap_chunks: int = 1,
    chunk_axis: int | None = None,
    algorithm: str = "alltoall",
    platform: str | None = None,
    axis_sizes: tuple[int, int] | None = None,
    wire_dtype: str | None = None,
    exchange_name: str = "t2_exchange",
    compute_name: str = "t3_fft",
    compute_takes_bounds: bool = False,
):
    """Pipelined global transpose + downstream compute (t2 ↔ t3 overlap).

    Splits the local block into ``overlap_chunks`` chunks along
    ``chunk_axis`` (default: the bystander axis ``3 - split - concat``,
    which neither the exchange nor ``compute`` may transform), exchanges
    each chunk independently, and applies ``compute`` (crop + downstream
    1D FFT) per exchanged chunk, concatenating the results back along the
    chunk axis. The schedule is software-pipelined: chunk ``i``'s exchange
    is issued *before* chunk ``i-1``'s compute, so XLA's async collectives
    (collective start/done) can run chunk ``i``'s ICI transfer under chunk
    ``i-1``'s MXU/VPU work — the ``MPI_Waitany`` overlap loop of the
    reference's pipelined p2p transport (``fft_mpi_3d_api.cpp:610-699``),
    expressed as K independent collectives the latency-hiding scheduler is
    free to hoist.

    ``x`` may be a single array or any pytree of same-shape arrays (the dd
    tier's (hi, lo) pair); ``compute`` maps the exchanged pytree chunk.
    Chunking is along a batch axis only, so every per-chunk exchange and
    FFT sees exactly the lines the monolithic path sees: the result is
    bit-identical to ``overlap_chunks=1``.

    ``overlap_chunks <= 1`` (or a 1-extent chunk axis) degenerates to the
    monolithic exchange + compute with today's HLO and the original
    un-suffixed trace spans; K > 1 emits ``{exchange_name}[k]`` /
    ``{compute_name}[k]`` spans so the PR 1 timeline shows the interleave.

    ``compute_takes_bounds=True`` calls ``compute(chunk, lo, hi)`` with
    the chunk's static (start, stop) bounds along ``chunk_axis`` — the
    midpoint hook of the fused spectral-operator chains, whose
    wavenumber-indexed pointwise multiplier must be generated for
    exactly the chunk's global slice (the bystander axis keeps global
    positions through the exchange, so the bounds ARE the slice).
    """
    tree = jax.tree_util
    leaves = tree.tree_leaves(x)
    if chunk_axis is None:
        chunk_axis = 3 - split_axis - concat_axis
    ex_kw = dict(split_axis=split_axis, concat_axis=concat_axis,
                 axis_size=axis_size, algorithm=algorithm, platform=platform,
                 axis_sizes=axis_sizes, wire_dtype=wire_dtype)
    extent = leaves[0].shape[chunk_axis] if leaves else 1
    bounds = overlap_chunk_bounds(extent, overlap_chunks)
    if len(bounds) <= 1:
        with add_trace(exchange_name):
            y = tree.tree_map(
                lambda u: exchange_uneven(u, axis_name, **ex_kw), x)
        with add_trace(compute_name):
            return (compute(y, 0, extent) if compute_takes_bounds
                    else compute(y))

    def take(lo, hi):
        return tree.tree_map(
            lambda u: lax.slice_in_dim(u, lo, hi, axis=chunk_axis), x)

    def exch(i, chunk):
        with add_trace(f"{exchange_name}[{i}]"):
            return tree.tree_map(
                lambda u: exchange_uneven(u, axis_name, **ex_kw), chunk)

    def run_chunk(i, chunk):
        if compute_takes_bounds:
            return compute(chunk, *bounds[i])
        return compute(chunk)

    parts = []
    inflight = exch(0, take(*bounds[0]))
    for i in range(1, len(bounds)):
        nxt = exch(i, take(*bounds[i]))  # issued before chunk i-1's compute
        with add_trace(f"{compute_name}[{i - 1}]"):
            parts.append(run_chunk(i - 1, inflight))
        inflight = nxt
    with add_trace(f"{compute_name}[{len(bounds) - 1}]"):
        parts.append(run_chunk(len(bounds) - 1, inflight))
    return tree.tree_map(
        lambda *ps: jnp.concatenate(ps, axis=chunk_axis), *parts)


def exchange_chunked(
    x,
    axis_name,
    *,
    split_axis: int,
    concat_axis: int,
    axis_size: int,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    chunk_axis: int | None = None,
    exchange_name: str = "t2_exchange",
    uneven: bool = False,
    platform: str | None = None,
    axis_sizes: tuple[int, int] | None = None,
    wire_dtype: str | None = None,
):
    """The staged-pipeline tier of the overlap mode: K independent
    per-chunk exchanges inside ONE stage jit. Stage boundaries are
    dispatch barriers, so true t2/t3 overlap belongs to the fused
    builders (:func:`exchange_overlapped`); the staged pipelines keep the
    same K-collective transport shape so their per-stage timing and the
    lowering pins describe the overlapped chains. Tree-generic (the dd
    (hi, lo) pair rides through). Most stage boundaries carry
    ceil-padded arrays and chunk the plain :func:`exchange`;
    ``uneven=True`` routes through :func:`exchange_uneven` for stages
    whose split axis is not pre-padded (the dd slab stage pipeline).
    ``overlap_chunks <= 1`` is exactly today's single exchange."""
    tree = jax.tree_util
    if chunk_axis is None:
        chunk_axis = 3 - split_axis - concat_axis
    leaves = tree.tree_leaves(x)
    extent = leaves[0].shape[chunk_axis] if leaves else 1
    bounds = overlap_chunk_bounds(extent, overlap_chunks)
    kw = dict(split_axis=split_axis, concat_axis=concat_axis,
              axis_size=axis_size, algorithm=algorithm,
              axis_sizes=axis_sizes, wire_dtype=wire_dtype)
    if uneven:
        one = lambda u: exchange_uneven(u, axis_name, platform=platform,
                                        **kw)
    else:
        one = lambda u: exchange(u, axis_name, **kw)
    if len(bounds) <= 1:
        return tree.tree_map(one, x)
    parts = []
    for i, (lo, hi) in enumerate(bounds):
        chunk = tree.tree_map(
            lambda u: lax.slice_in_dim(u, lo, hi, axis=chunk_axis), x)
        with add_trace(f"{exchange_name}[{i}]"):
            parts.append(tree.tree_map(one, chunk))
    return tree.tree_map(
        lambda *ps: jnp.concatenate(ps, axis=chunk_axis), *parts)
