"""Global-transpose exchange algorithms over a mesh axis.

The reference exposes a menu of distributed-transpose transports: heFFTe's
``reshape_algorithm`` enum {alltoall, alltoallv, p2p, p2p_plined}
(``heffte/heffteBenchmark/include/heffte_plan_logic.h:47-56``;
implementations ``src/heffte_reshape3d.cpp:268,375,497-625``) and the
first-party engine's hand-rolled peer DMA + MPI_Isend/Irecv tables
(``3dmpifft_opt/include/fft_mpi_3d_api.cpp:610-699``).

The TPU-native menu has three entries, selected per plan:

- ``"alltoall"`` — one ``jax.lax.all_to_all`` on the mesh axis. XLA lowers
  this to the platform all-to-all riding ICI; the analog of
  ``MPI_Alltoall`` with equal (ceil-padded) counts
  (``reshape3d_alltoall``, ``src/heffte_reshape3d.cpp:268`` pads to equal
  counts the same way).
- ``"alltoallv"`` — one ``jax.lax.ragged_all_to_all`` shipping each peer's
  TRUE slice of the split axis (no split-axis padding on the wire) — the
  analog of ``MPI_Alltoallv`` with the exact per-peer count tables
  (``reshape3d_alltoallv``, ``src/heffte_reshape3d.cpp:375``;
  count/offset semantics = ``dfft_exchange_table``,
  ``native/dfft_native.cpp``). Concat-axis padding (each sender's equal
  ceil-chunk block, zero rows on the tail device) is inherent to the SPMD
  equal-shard layout and still travels.
- ``"ppermute"`` — an explicit (P-1)-step ring of ``jax.lax.ppermute``
  neighbor shifts, each step moving one peer's chunk. The analog of the
  pipelined point-to-point path (``reshape3d_pointtopoint``,
  ``src/heffte_reshape3d.cpp:497-625``): per-step transfers are
  nearest-neighbor permutes that map 1:1 onto ICI ring links, and XLA can
  overlap each step's transfer with the next step's slice/update work.

``alltoall``/``ppermute`` require equal chunk sizes — the ceil-pad/crop
scheme of :mod:`.slab` / :mod:`.pencil` (via :func:`exchange_uneven`)
guarantees that; ``alltoallv`` takes the unpadded split axis directly.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..geometry import pad_to

ALGORITHMS = ("alltoall", "alltoallv", "ppermute")


def _pad_axis(x: jnp.ndarray, axis: int, to: int) -> jnp.ndarray:
    """Zero-pad ``axis`` up to extent ``to`` (no-op when already there).
    Single definition shared by every chain builder and exchange path — the
    dense and ragged paths depend on bit-identical padding."""
    if x.shape[axis] == to:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, to - x.shape[axis])
    return jnp.pad(x, pads)


def _crop_axis(x: jnp.ndarray, axis: int, to: int) -> jnp.ndarray:
    if x.shape[axis] == to:
        return x
    return lax.slice_in_dim(x, 0, to, axis=axis)


def exchange(
    x: jnp.ndarray,
    axis_name: str,
    *,
    split_axis: int,
    concat_axis: int,
    axis_size: int,
    algorithm: str = "alltoall",
) -> jnp.ndarray:
    """Tiled all-to-all on ``axis_name`` inside ``shard_map``.

    Splits the local block into ``axis_size`` chunks along ``split_axis`` and
    concatenates the chunks received from every peer along ``concat_axis``
    (the semantics of ``lax.all_to_all(..., tiled=True)``).
    """
    if algorithm == "alltoall":
        return lax.all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
        )
    if algorithm == "alltoallv":
        return ragged_all_to_all_exchange(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
            p=axis_size,
        )
    if algorithm == "ppermute":
        return ring_all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis, p=axis_size
        )
    raise ValueError(f"unknown exchange algorithm {algorithm!r}; use {ALGORITHMS}")


def exchange_uneven(
    x: jnp.ndarray,
    axis_name: str,
    *,
    split_axis: int,
    concat_axis: int,
    axis_size: int,
    algorithm: str = "alltoall",
    platform: str | None = None,
) -> jnp.ndarray:
    """Exchange whose split-axis extent need not divide ``axis_size``.

    The dense algorithms ceil-pad the split axis first (the reference's
    padded-equal-counts strategy, ``src/heffte_reshape3d.cpp:268``);
    ``alltoallv`` ships the true slices unpadded. Either way the result's
    split axis holds the local ceil-chunk (padded at the tail) and the
    concat axis holds ``axis_size`` ceil-chunks per sender — callers crop
    the concat axis to its true extent exactly as before. ``platform`` is
    the mesh devices' platform (used by ``alltoallv`` to pick the real
    ragged collective vs its CPU mirror).
    """
    if algorithm == "alltoallv":
        return ragged_all_to_all_exchange(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
            p=axis_size, platform=platform,
        )
    x = _pad_axis(x, split_axis, pad_to(x.shape[split_axis], axis_size))
    return exchange(x, axis_name, split_axis=split_axis,
                    concat_axis=concat_axis, axis_size=axis_size,
                    algorithm=algorithm)


def ragged_all_to_all_exchange(
    x: jnp.ndarray, axis_name: str, *, split_axis: int, concat_axis: int,
    p: int, platform: str | None = None,
) -> jnp.ndarray:
    """All-to-all transpose shipping each peer's TRUE split-axis slice.

    The MPI_Alltoallv analog (``reshape3d_alltoallv``,
    ``src/heffte_reshape3d.cpp:375``): where the dense path pads the split
    axis to ``p * ceil(S/p)`` and ships the padding, this sends peer ``j``
    exactly its ``sizes[j]`` true elements via ``lax.ragged_all_to_all``.
    The per-peer counts/offsets follow the ceil-split ownership convention —
    the same tables ``dfft_exchange_table`` computes (elements =
    ``rows * sizes[j] * inner``).

    Takes the UNPADDED split axis (extent S = the true global extent of the
    post-exchange sharded axis); returns the same shape the padded path
    would: split axis -> local ceil chunk ``c``, concat axis ->
    ``p * local_chunk`` (each sender's equal-size block, tail padding
    included — that padding is the SPMD equal-shard layout itself and is
    cropped by the caller, never transformed).
    """
    import jax

    from ..utils.compat import force_real_lowering

    S = x.shape[split_axis]
    c = -(-S // p)
    if platform is None:
        platform = jax.default_backend()
    if platform == "cpu" and not force_real_lowering():
        # XLA:CPU has no ragged-all-to-all lowering; the ceil-padded dense
        # exchange produces the bit-identical result (the padding positions
        # the ragged path never writes stay zero either way), so the CPU
        # test backend mirrors through it — the same discipline as the
        # Pallas kernel's interpreter-mode mirror (and the same
        # force_real_lowering override for chipless lowering tests).
        x = _pad_axis(x, split_axis, p * c)
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)
    i = lax.axis_index(axis_name)
    # Static per-peer ownership of the split axis (ceil splits, short/empty
    # tail) — the dfft_exchange_table geometry.
    bounds = np.minimum(np.arange(p + 1) * c, S)
    starts, sizes = bounds[:-1], np.diff(bounds)

    xt = jnp.moveaxis(x, split_axis, 0)
    rest = xt.shape[1:]
    out = jnp.zeros((p * c,) + rest, x.dtype)
    my_size = jnp.minimum((i + 1) * c, S) - jnp.minimum(i * c, S)
    y = lax.ragged_all_to_all(
        xt, out,
        jnp.asarray(starts, jnp.int32),
        jnp.asarray(sizes, jnp.int32),
        # Sender i's slice lands at leading offset i*c on every receiver.
        jnp.full((p,), i * c, jnp.int32),
        jnp.full((p,), my_size, jnp.int32),
        axis_name=axis_name,
    )
    # y: [p, c, *rest] with the sender dim to be merged into the concat
    # axis (sender-major) and the local split chunk moved back into place.
    y = y.reshape((p, c) + rest)
    cpos = 1 + (concat_axis if concat_axis < split_axis else concat_axis - 1)
    perm = [1]
    for k in range(len(rest)):
        ax = 2 + k
        if k == cpos - 1:
            perm.extend([0, ax])
        else:
            perm.append(ax)
    y = y.transpose(perm)
    j = perm.index(0)
    shp = list(y.shape)
    shp[j:j + 2] = [p * shp[j + 1]]
    y = y.reshape(shp)
    return jnp.moveaxis(y, 0, split_axis)


def ring_all_to_all(
    x: jnp.ndarray, axis_name: str, *, split_axis: int, concat_axis: int, p: int
) -> jnp.ndarray:
    """All-to-all as a (P-1)-step ``ppermute`` ring.

    Step ``s`` shifts by ``s`` around the ring: device ``i`` sends the chunk
    destined for ``(i - s) % p`` and receives its own chunk from
    ``(i + s) % p``. Each step is a uniform neighbor permutation (distance-s
    rotation), so on a physical ICI ring/torus every step uses disjoint
    links; the Python loop unrolls at trace time (P is static), letting XLA
    pipeline transfer ``s`` with the slice/update of step ``s+1`` — the role
    of ``MPI_Waitany``-driven overlap in the reference's pipelined p2p path
    (``src/heffte_reshape3d.cpp:611``).
    """
    ns = x.shape[split_axis]
    if ns % p:
        raise ValueError(f"split axis extent {ns} not divisible by {p}")
    c = ns // p
    nc = x.shape[concat_axis]
    i = lax.axis_index(axis_name)

    def chunk_for(dst):
        return lax.dynamic_slice_in_dim(x, dst * c, c, axis=split_axis)

    out_shape = list(x.shape)
    out_shape[split_axis] = c
    out_shape[concat_axis] = nc * p
    buf = jnp.zeros(tuple(out_shape), x.dtype)

    def place(buf, chunk, src):
        return lax.dynamic_update_slice_in_dim(buf, chunk, src * nc, axis=concat_axis)

    buf = place(buf, chunk_for(i), i)  # own chunk stays put
    for s in range(1, p):
        send = chunk_for((i - s) % p)
        recv = lax.ppermute(
            send, axis_name, perm=[(j, (j - s) % p) for j in range(p)]
        )
        buf = place(buf, recv, (i + s) % p)
    return buf
