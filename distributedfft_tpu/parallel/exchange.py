"""Global-transpose exchange algorithms over a mesh axis.

The reference exposes a menu of distributed-transpose transports: heFFTe's
``reshape_algorithm`` enum {alltoall, alltoallv, p2p, p2p_plined}
(``heffte/heffteBenchmark/include/heffte_plan_logic.h:47-56``;
implementations ``src/heffte_reshape3d.cpp:268,375,497-625``) and the
first-party engine's hand-rolled peer DMA + MPI_Isend/Irecv tables
(``3dmpifft_opt/include/fft_mpi_3d_api.cpp:610-699``).

The TPU-native menu has two entries, selected per plan:

- ``"alltoall"`` — one ``jax.lax.all_to_all`` on the mesh axis. XLA lowers
  this to the platform all-to-all riding ICI; the analog of
  ``MPI_Alltoall`` with equal (ceil-padded) counts.
- ``"ppermute"`` — an explicit (P-1)-step ring of ``jax.lax.ppermute``
  neighbor shifts, each step moving one peer's chunk. The analog of the
  pipelined point-to-point path (``reshape3d_pointtopoint``,
  ``src/heffte_reshape3d.cpp:497-625``): per-step transfers are
  nearest-neighbor permutes that map 1:1 onto ICI ring links, and XLA can
  overlap each step's transfer with the next step's slice/update work.

Both require equal chunk sizes — the ceil-pad/crop scheme of
:mod:`.slab` / :mod:`.pencil` guarantees that.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

ALGORITHMS = ("alltoall", "ppermute")


def exchange(
    x: jnp.ndarray,
    axis_name: str,
    *,
    split_axis: int,
    concat_axis: int,
    axis_size: int,
    algorithm: str = "alltoall",
) -> jnp.ndarray:
    """Tiled all-to-all on ``axis_name`` inside ``shard_map``.

    Splits the local block into ``axis_size`` chunks along ``split_axis`` and
    concatenates the chunks received from every peer along ``concat_axis``
    (the semantics of ``lax.all_to_all(..., tiled=True)``).
    """
    if algorithm == "alltoall":
        return lax.all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
        )
    if algorithm == "ppermute":
        return ring_all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis, p=axis_size
        )
    raise ValueError(f"unknown exchange algorithm {algorithm!r}; use {ALGORITHMS}")


def ring_all_to_all(
    x: jnp.ndarray, axis_name: str, *, split_axis: int, concat_axis: int, p: int
) -> jnp.ndarray:
    """All-to-all as a (P-1)-step ``ppermute`` ring.

    Step ``s`` shifts by ``s`` around the ring: device ``i`` sends the chunk
    destined for ``(i - s) % p`` and receives its own chunk from
    ``(i + s) % p``. Each step is a uniform neighbor permutation (distance-s
    rotation), so on a physical ICI ring/torus every step uses disjoint
    links; the Python loop unrolls at trace time (P is static), letting XLA
    pipeline transfer ``s`` with the slice/update of step ``s+1`` — the role
    of ``MPI_Waitany``-driven overlap in the reference's pipelined p2p path
    (``src/heffte_reshape3d.cpp:611``).
    """
    ns = x.shape[split_axis]
    if ns % p:
        raise ValueError(f"split axis extent {ns} not divisible by {p}")
    c = ns // p
    nc = x.shape[concat_axis]
    i = lax.axis_index(axis_name)

    def chunk_for(dst):
        return lax.dynamic_slice_in_dim(x, dst * c, c, axis=split_axis)

    out_shape = list(x.shape)
    out_shape[split_axis] = c
    out_shape[concat_axis] = nc * p
    buf = jnp.zeros(tuple(out_shape), x.dtype)

    def place(buf, chunk, src):
        return lax.dynamic_update_slice_in_dim(buf, chunk, src * nc, axis=concat_axis)

    buf = place(buf, chunk_for(i), i)  # own chunk stays put
    for s in range(1, p):
        send = chunk_for((i - s) % p)
        recv = lax.ppermute(
            send, axis_name, perm=[(j, (j - s) % p) for j in range(p)]
        )
        buf = place(buf, recv, (i + s) % p)
    return buf
