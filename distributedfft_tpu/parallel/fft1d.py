"""Distributed 1D FFT of one long sequence over a device mesh.

The reference scales long 1D sequences *within* one device via templateFFT's
four-step axis split (``FFTScheduler``, ``templateFFT.cpp:3975-4100``, sizes
up to 5^11 = 48,828,125, ``runTest1D_opt.sh:14-20``) — its cross-device story
exists only for 3D grids. This module is the missing cross-device analog,
TPU-native: the same four-step identity, but with the two DFT stages running
on different mesh shards and the inter-stage reorder riding ICI as
all-to-alls — sequence parallelism for a single transform far larger than
one chip's HBM.

Math (j = j1*B + j2, k = k1 + A*k2, n = A*B):

    X[k1 + A*k2] = sum_j2 w_B^{j2 k2} * w_n^{j2 k1}
                   * (sum_j1 w_A^{j1 k1} x[j1*B + j2])

Pipeline over a 1D mesh of P devices (input [A, B] row-major view of x,
sharded by rows):

    s0  all_to_all:  rows -> columns            ([A, B/P] per device)
    s1  executor FFT over axis 0 (length A)
    s2  twiddle w_n^{k1 * j2}                   (exact integer mulmod phase)
    s3  all_to_all:  columns -> rows            ([A/P, B] per device)
    s4  executor FFT over axis 1 (length B)

The result is the spectrum in **transposed order**: element [k1, k2] of the
output's [A, B] view is X[k1 + A*k2] — the FFTW-MPI ``TRANSPOSED_OUT``
convention. ``order="natural"`` appends one more global transpose (a third
all-to-all) to return X in index order.

Twiddle exactness: w_n^{k1*j2} phases are reduced with integer
multiply-mod (binary doubling, intermediates < 2n), never by forming the
float product k1*j2 — exact for n < 2^30 in int32 (larger n switches to
int64, which requires x64 mode). The per-device factor w_n^{k1*(dev*Bl)}
is computed on device; the device-independent factor w_n^{k1*c}, c < B/P,
is a host-precomputed LUT (plan-time table discipline as everywhere else).
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from ..ops.executors import get_executor
from ..utils.compat import pvary, typeof_vma
from .exchange import exchange


def _find_split(n: int, p: int) -> tuple[int, int] | None:
    best = None
    for a in range(int(math.isqrt(n)), 0, -1):
        if n % a:
            continue
        b = n // a
        for big, small in ((a, b), (b, a)):
            if big % p == 0 and small % p == 0:
                if best is None or abs(big - small) < abs(best[0] - best[1]):
                    best = (big, small)
        if best is not None and best[0] == a:
            break
    return best


def choose_split_1d(n: int, p: int) -> tuple[int, int]:
    """Balanced divisor pair (A, B) of n with both divisible by ``p`` (both
    exchange axes must split evenly across the mesh). Raises when no such
    pair exists — pad the sequence to a friendlier length."""
    best = _find_split(n, p)
    if best is None:
        raise ValueError(
            f"length {n} has no factor pair with both factors divisible by "
            f"{p}; pad the sequence (e.g. to {_suggest_length(n, p)})"
        )
    return best


def _suggest_length(n: int, p: int) -> int:
    m = n
    while _find_split(m, p) is None:
        m += 1
    return m


def _mulmod(a, b: int, n: int, idt):
    """(a * b) % n elementwise with intermediates < 2n (binary doubling over
    the static multiplier ``b``); exact where a float product would not be."""
    a = (a % n).astype(idt)
    acc = jnp.zeros_like(a)
    cur = a
    for s in range(max(1, b.bit_length())):
        if (b >> s) & 1:
            acc = (acc + cur) % n
        cur = (cur * 2) % n
    return acc


def _mulmod_traced(a, b, n: int, idt):
    """Same, but for a traced multiplier ``b`` (static bit budget)."""
    a = (a % n).astype(idt)
    b = b.astype(idt)
    acc = jnp.zeros_like(a)
    cur = a
    for s in range(max(1, (n - 1).bit_length())):
        bit = (b >> s) & 1
        acc = jnp.where(bit == 1, (acc + cur) % n, acc)
        cur = (cur * 2) % n
    return acc


@functools.lru_cache(maxsize=None)
def _local_twiddle_np(n: int, a: int, bl: int, forward: bool) -> np.ndarray:
    """Device-independent twiddle factor w_n^{k1*c} for local columns
    c < bl, exact host f64 (complex128; cast to working dtype on use)."""
    sign = -2j if forward else 2j
    kc = np.outer(np.arange(a, dtype=np.int64), np.arange(bl, dtype=np.int64))
    return np.exp(sign * np.pi * (kc % n) / n)


@dataclass
class Dist1DSpec:
    """Static geometry of a distributed 1D plan."""

    n: int
    a: int  # rows    (first-stage DFT length)
    b: int  # columns (second-stage DFT length)
    parts: int
    axis_name: str
    order: str  # "transposed" | "natural"


def build_dist_fft1d(
    mesh: Mesh,
    n: int,
    *,
    axis_name: str = "slab",
    forward: bool = True,
    executor: str | Callable = "xla",
    order: str = "transposed",
    algorithm: str = "alltoall",
    donate: bool = False,
) -> tuple[Callable, Dist1DSpec]:
    """Build the jitted distributed 1D C2C transform of length ``n``.

    Forward maps a length-``n`` vector (sharded in contiguous blocks) to its
    spectrum in transposed order ([A, B]-view element [k1, k2] = X[k1+A*k2])
    or natural order. Backward inverts exactly that layout back to the
    natural-order sequence (1/n scaling, numpy convention).
    """
    if order not in ("transposed", "natural"):
        raise ValueError("order must be 'transposed' or 'natural'")
    p = mesh.shape[axis_name]
    a, b = choose_split_1d(n, p)
    bl = b // p
    ex = get_executor(executor) if isinstance(executor, str) else executor
    spec = Dist1DSpec(n, a, b, p, axis_name, order)
    idt = jnp.int32 if n < (1 << 30) else jnp.int64

    w_local_np = _local_twiddle_np(n, a, bl, forward)

    def twiddle(g):  # g: [a, bl] complex, full k1 range, local j2 columns
        dev = lax.axis_index(axis_name)
        # per-device phase w_n^{k1 * dev*bl}: exact integer phase reduction
        ps = _mulmod(jnp.full((1,), dev, idt), bl, n, idt)[0]
        rows = _mulmod_traced(jnp.arange(a, dtype=idt), ps, n, idt)
        rdt = g.real.dtype
        sign = -2.0 if forward else 2.0
        ang = (sign * np.pi / n) * rows.astype(rdt)
        rot = lax.complex(jnp.cos(ang), jnp.sin(ang))
        w = jnp.asarray(w_local_np, dtype=g.dtype)
        vma = typeof_vma(g)
        if vma:
            w = pvary(w, tuple(vma))
        return g * rot[:, None] * w

    if forward:

        def local_fn(x2):  # [a/p, b] per device
            g = exchange(x2, axis_name, split_axis=1, concat_axis=0,
                         axis_size=p, algorithm=algorithm)   # s0: [a, bl]
            g = ex(g, (0,), True)                            # s1: DFT_A
            g = twiddle(g)                                   # s2
            h = exchange(g, axis_name, split_axis=0, concat_axis=1,
                         axis_size=p, algorithm=algorithm)   # s3: [a/p, b]
            return ex(h, (1,), True)                         # s4: DFT_B

    else:

        def local_fn(r2):  # transposed-order spectrum [a/p, b] per device
            h = ex(r2, (1,), False)                          # inverse DFT_B
            g = exchange(h, axis_name, split_axis=1, concat_axis=0,
                         axis_size=p, algorithm=algorithm)   # [a, bl]
            g = twiddle(g)                                   # conj twiddle
            g = ex(g, (0,), False)                           # inverse DFT_A
            return exchange(g, axis_name, split_axis=0, concat_axis=1,
                            axis_size=p, algorithm=algorithm)  # [a/p, b]

    rows_spec = P(axis_name, None)
    mapped = _shard_map(local_fn, mesh=mesh,
                        in_specs=(rows_spec,), out_specs=rows_spec)
    vec_sh = NamedSharding(mesh, P(axis_name))
    rows_sh = NamedSharding(mesh, rows_spec)
    jit_kw: dict[str, Any] = {"donate_argnums": 0} if donate else {}
    jit_kw |= {"in_shardings": vec_sh, "out_shardings": vec_sh}

    if forward:

        @functools.partial(jax.jit, **jit_kw)
        def fn(x):
            x2 = lax.with_sharding_constraint(x.reshape(a, b), rows_sh)
            r = mapped(x2)
            if order == "natural":
                # one more global transpose: [a, b] rows-sharded ->
                # [b, a] rows-sharded; flat index becomes k2*a + k1 = k.
                r = lax.with_sharding_constraint(
                    r.T, NamedSharding(mesh, P(axis_name, None))
                )
            return r.reshape(n)

    else:

        @functools.partial(jax.jit, **jit_kw)
        def fn(r):
            if order == "natural":
                r2 = lax.with_sharding_constraint(
                    r.reshape(b, a).T, rows_sh
                )
            else:
                r2 = r.reshape(a, b)
            r2 = lax.with_sharding_constraint(r2, rows_sh)
            x2 = mapped(r2)
            return x2.reshape(n)

    return fn, spec


@dataclass
class DistPlan1D:
    """Callable distributed 1D plan (cf. the local :class:`~..local.LocalPlan`
    surface; this is its cross-device sibling)."""

    spec: Dist1DSpec
    direction: int
    dtype: Any
    executor: str
    fn: Callable

    def __call__(self, x):
        x = jnp.asarray(x, dtype=self.dtype)
        if x.shape != (self.spec.n,):
            raise ValueError(f"plan input shape is ({self.spec.n},), got {x.shape}")
        return self.fn(x)

    def flops(self) -> float:
        return 5.0 * self.spec.n * math.log2(self.spec.n)


def plan_dft_c2c_1d_dist(
    n: int,
    mesh: Mesh | None,
    *,
    direction: int = -1,
    executor: str = "xla",
    order: str = "transposed",
    algorithm: str = "alltoall",
    dtype: Any = None,
    donate: bool = False,
) -> DistPlan1D:
    """Plan a distributed 1D C2C FFT of one length-``n`` sequence.

    With ``mesh=None`` (or one device) the plan is a plain local transform;
    ``order`` then has no effect (output is always natural)."""
    if dtype is None:
        dtype = jnp.complex128 if jax.config.jax_enable_x64 else jnp.complex64
    forward = direction == -1
    if mesh is None or math.prod(mesh.devices.shape) == 1:
        ex = get_executor(executor)
        fn = jax.jit(lambda x: ex(x, (0,), forward),
                     donate_argnums=(0,) if donate else ())
        spec = Dist1DSpec(n, n, 1, 1, "", "natural")
        return DistPlan1D(spec, direction, jnp.dtype(dtype), executor, fn)
    axis_name = mesh.axis_names[0]
    fn, spec = build_dist_fft1d(
        mesh, n, axis_name=axis_name, forward=forward, executor=executor,
        order=order, algorithm=algorithm, donate=donate,
    )
    return DistPlan1D(spec, direction, jnp.dtype(dtype), executor, fn)
