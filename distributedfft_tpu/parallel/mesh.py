"""Device-mesh construction — the TPU-native communicator layer.

Replaces the reference's communicator setup: ``fft_mpi_init``'s device
renegotiation + peer-access enabling (``3dmpifft_opt/include/fft_mpi_3d_api.cpp:3-39,
232-272``) and the MPI/UCX transport (``speedTest.sh``). On TPU the transport
is a :class:`jax.sharding.Mesh` over ICI (intra-slice) / DCN (multi-host);
XLA inserts the collectives, and ``jax.distributed.initialize`` replaces
``MPI_Init`` for the multi-host tier (SURVEY.md §7 step 8).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Default axis names: "slab" for the 1D decomposition, ("row", "col") for 2D
# pencil grids.
SLAB_AXIS = "slab"
PENCIL_AXES = ("row", "col")


def mesh_devices(n: int | None = None) -> list:
    devs = jax.devices()
    if n is None:
        return devs
    if n > len(devs):
        raise ValueError(f"requested {n} devices, only {len(devs)} available")
    return devs[:n]


def make_mesh(shape: int | Sequence[int], axis_names: Sequence[str] | None = None) -> Mesh:
    """Build a mesh of the leading devices with the given logical shape.

    ``make_mesh(4)`` -> 1D slab mesh; ``make_mesh((2, 4))`` -> 2D pencil mesh.
    Unlike the reference, which silently *shrinks* the device count until the
    grid divides (``getProperDeviceNum``, ``fft_mpi_3d_api.cpp:244-259``), the
    TPU design keeps all devices and pads the data instead
    (:func:`distributedfft_tpu.geometry.ceil_shards`).
    """
    if isinstance(shape, int):
        shape = (shape,)
    shape = tuple(int(s) for s in shape)
    if axis_names is None:
        axis_names = (SLAB_AXIS,) if len(shape) == 1 else PENCIL_AXES[: len(shape)]
    if len(axis_names) != len(shape):
        raise ValueError("axis_names must match mesh shape rank")
    n = int(np.prod(shape))
    devs = np.asarray(mesh_devices(n)).reshape(shape)
    return Mesh(devs, tuple(axis_names))


def init_distributed(**kwargs) -> None:
    """Multi-host initialization (the ``MPI_Init_thread`` analog,
    ``fftSpeed3d_c2c.cpp:18``).

    Must be called before any JAX computation, exactly like ``MPI_Init``;
    with no arguments, coordinator discovery uses the cluster environment
    (TPU pod metadata / SLURM / OMPI vars). Safe to call twice.
    """
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:  # already initialized -> idempotent no-op
        if "already" not in str(e).lower():
            raise
