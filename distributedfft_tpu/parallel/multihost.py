"""Multi-host (DCN) tier: process bootstrap, hybrid meshes, host/global data.

The reference's multi-node story is MPI over UCX/InfiniBand: one rank per
node, ``MPI_Init_thread`` + hostfile (``3dmpifft_opt/fftSpeed3d_c2c.cpp:18``,
``speedTest.sh``, ``nodelist``), GPU-aware Isend/Irecv between nodes and
peer-DMA inside a node (``fft_mpi_3d_api.cpp:610-699``). The TPU-native
equivalent keeps the same two-tier shape with XLA collectives:

- process bootstrap  = ``jax.distributed.initialize``  (replaces MPI_Init;
  coordinator address plays the role of the hostfile),
- intra-node XGMI    = ICI mesh axes (devices within a slice),
- inter-node UCX/IB  = DCN mesh axes (across processes/slices),

and one jitted mesh program spans both tiers — XLA routes each collective
over ICI or DCN according to which mesh axis it runs on, replacing the
reference's hand-split hipMemcpyPeerAsync / MPI_Isend code paths.

Everything here is single-process-safe: with one process the DCN axis has
extent 1 and every helper degenerates to the local behavior, so the same
driver script runs on a laptop, one TPU host, or a multi-host pod (the
"multi-node without a cluster" property of the reference's test strategy,
SURVEY.md §4.2).
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_initialized = False


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kw,
) -> bool:
    """Initialize the cross-host runtime (``jax.distributed.initialize``).

    Arguments default to the standard environment (JAX_COORDINATOR_ADDRESS
    etc. / cloud auto-detection). Returns True when a multi-process runtime
    was initialized, False when running single-process (no coordinator
    configured) — in which case everything degrades gracefully to one
    process. Safe to call twice.
    """
    global _initialized
    if _initialized or jax.process_count() > 1:
        _initialized = True
        return True
    configured = (
        coordinator_address is not None
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS")
    )
    if not configured:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kw,
    )
    _initialized = True
    return True


def make_hybrid_mesh(
    axis_names: tuple[str, str] = ("dcn", "slab"),
    *,
    devices: Sequence | None = None,
) -> Mesh:
    """2D (processes x per-process-devices) mesh: axis 0 spans DCN (one row
    per process), axis 1 spans the ICI-connected devices of each process.

    For the FFT engines this is the pencil mesh with the *column* axis on
    ICI — lay the heavy exchange on ``axis_names[1]`` so it rides ICI and
    only the coarse exchange crosses DCN (the ICI/DCN layering rule; the
    reference's analogous split is peer-DMA within a node vs MPI across,
    ``fft_mpi_3d_api.cpp:627-672``).
    """
    devs = list(devices) if devices is not None else jax.devices()
    nproc = max(1, jax.process_count())
    per = len(devs) // nproc
    if per * nproc != len(devs):
        raise ValueError(
            f"{len(devs)} devices do not divide over {nproc} processes"
        )
    # jax.devices() orders by process; rows = processes -> row-major grid.
    grid = np.array(devs).reshape(nproc, per)
    return Mesh(grid, axis_names)


def is_hybrid_mesh(mesh) -> bool:
    """True when ``mesh`` is a 2D hybrid (dcn x ici) mesh — the shape
    :func:`make_hybrid_mesh` builds and the one the hierarchical two-leg
    transport (and the tuner's hierarchical candidates) target. The
    convention: axis 0 is named ``"dcn"`` and spans processes/slices,
    axis 1 is the ICI-connected intra-slice axis."""
    return (isinstance(mesh, Mesh) and len(mesh.axis_names) == 2
            and mesh.axis_names[0] == "dcn")


def fft_mesh_for(ndev_total: int | None = None) -> Mesh:
    """The default distributed-FFT mesh for this runtime: hybrid 2D when
    multi-process, flat 1D slab mesh when single-process."""
    from .mesh import make_mesh

    if jax.process_count() > 1:
        return make_hybrid_mesh()
    return make_mesh(ndev_total or len(jax.devices()))


def host_local_to_global(mesh: Mesh, spec: P, local: np.ndarray):
    """Assemble a global (sharded) array from each process's host-local
    block — the data-ingest direction of the reference's per-rank init
    (``fftSpeed3d_c2c.cpp:59-72`` fills each rank's slab then plans over the
    world). Single-process this is just device_put with a sharding."""
    if jax.process_count() == 1:
        return jax.device_put(local, NamedSharding(mesh, spec))
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(local, mesh, spec)


def global_to_host_local(x) -> np.ndarray:
    """Fetch the full value of a (possibly sharded) global array to every
    host (cross-process allgather when needed) — the validation direction."""
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def sync_global_devices(tag: str = "dfft") -> None:
    """Cross-process barrier (the MPI_Barrier analog used between timing
    sections, ``test_common.h`` banner sync)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)
