"""Pencil-decomposed distributed 3D FFT over a 2D device mesh.

The reference's baseline (vendored heFFTe) plans pencil pipelines
brick -> z-pencil -> y-pencil -> x-pencil with up to four reshapes
(``plan_pencil_reshapes``, ``heffte/heffteBenchmark/src/heffte_plan_logic.cpp:162-245``).
The TPU-native equivalent fixes the canonical three-stage pencil pipeline on
a 2D mesh (rows x cols):

    input  z-pencils: sharded (axis0 -> row, axis1 -> col), full Z
    t0  1D FFT along Z
    t2a ``all_to_all`` over *col*: reshard Z<->Y  -> y-pencils
    t1' 1D FFT along Y
    t2b ``all_to_all`` over *row*: reshard Y<->X  -> x-pencils
    t3  1D FFT along X
    output x-pencils: sharded (axis1 -> row, axis2 -> col), full X

Both collectives ride one mesh axis each, so on a physical 2D ICI torus every
exchange stays on its ring — the property heFFTe's min-surface processor grid
chases (``heffte_geometry.h:589``). Uneven extents use the same
ceil-pad/crop scheme as :mod:`.slab` (pads only ever touch an axis while it
is *not* being transformed at its true length).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from ..geometry import pad_to
from ..ops.executors import get_executor
from .slab import _crop_axis, _pad_axis


@dataclass(frozen=True)
class PencilSpec:
    """Static geometry of a pencil plan on a (rows x cols) mesh."""

    shape: tuple[int, int, int]
    rows: int
    cols: int
    row_axis: str = "row"
    col_axis: str = "col"

    @property
    def n0p(self) -> int:  # axis0 split over rows on input
        return pad_to(self.shape[0], self.rows)

    @property
    def n1p_col(self) -> int:  # axis1 split over cols on input
        return pad_to(self.shape[1], self.cols)

    @property
    def n1p_row(self) -> int:  # axis1 split over rows on output
        return pad_to(self.shape[1], self.rows)

    @property
    def n2p(self) -> int:  # axis2 split over cols after the first exchange
        return pad_to(self.shape[2], self.cols)

    @property
    def in_spec(self) -> P:
        return P(self.row_axis, self.col_axis, None)

    @property
    def out_spec(self) -> P:
        return P(None, self.row_axis, self.col_axis)


def build_pencil_fft3d(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    row_axis: str = "row",
    col_axis: str = "col",
    executor: str | Callable = "xla",
    forward: bool = True,
    donate: bool = False,
) -> tuple[Callable, PencilSpec]:
    """Build the jitted end-to-end pencil transform.

    Forward maps z-pencils (global array sharded ``P(row, col, None)``) to
    x-pencils (``P(None, row, col)``); backward is the exact mirror.
    """
    rows, cols = mesh.shape[row_axis], mesh.shape[col_axis]
    spec = PencilSpec(tuple(int(s) for s in shape), rows, cols, row_axis, col_axis)
    ex = get_executor(executor) if isinstance(executor, str) else executor
    n0, n1, n2 = spec.shape
    n0p, n1pc, n1pr, n2p = spec.n0p, spec.n1p_col, spec.n1p_row, spec.n2p

    if forward:

        def local_fn(x):  # [n0p/rows, n1pc/cols, N2]
            y = ex(x, (2,), True)                       # t0: Z lines
            y = _pad_axis(y, 2, n2p)
            # z-pencils -> y-pencils: exchange along cols
            y = lax.all_to_all(y, col_axis, split_axis=2, concat_axis=1, tiled=True)
            y = _crop_axis(y, 1, n1)                    # true Y extent
            y = ex(y, (1,), True)                       # Y lines
            y = _pad_axis(y, 1, n1pr)
            # y-pencils -> x-pencils: exchange along rows
            y = lax.all_to_all(y, row_axis, split_axis=1, concat_axis=0, tiled=True)
            y = _crop_axis(y, 0, n0)                    # true X extent
            return ex(y, (0,), True)                    # t3: X lines

        in_spec, out_spec = spec.in_spec, spec.out_spec
        pre = lambda x: _pad_axis(_pad_axis(x, 0, n0p), 1, n1pc)
        post = lambda y: _crop_axis(_crop_axis(y, 1, n1), 2, n2)
    else:

        def local_fn(y):  # [N0, n1pr/rows, n2p/cols]
            x = ex(y, (0,), False)                      # inverse X lines
            x = _pad_axis(x, 0, n0p)
            x = lax.all_to_all(x, row_axis, split_axis=0, concat_axis=1, tiled=True)
            x = _crop_axis(x, 1, n1)
            x = ex(x, (1,), False)                      # inverse Y lines
            x = _pad_axis(x, 1, n1pc)
            x = lax.all_to_all(x, col_axis, split_axis=1, concat_axis=2, tiled=True)
            x = _crop_axis(x, 2, n2)
            return ex(x, (2,), False)                   # inverse Z lines

        in_spec, out_spec = spec.out_spec, spec.in_spec
        pre = lambda y: _pad_axis(_pad_axis(y, 1, n1pr), 2, n2p)
        post = lambda x: _crop_axis(_crop_axis(x, 0, n0), 1, n1)

    mapped = _shard_map(local_fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec)

    in_sh = NamedSharding(mesh, in_spec)
    out_sh = NamedSharding(mesh, out_spec)
    even = n0p == n0 and n1pc == n1 and n1pr == n1 and n2p == n2
    jit_kw: dict = {"donate_argnums": 0} if donate else {}
    if even:
        jit_kw |= {"in_shardings": in_sh, "out_shardings": out_sh}

    @functools.partial(jax.jit, **jit_kw)
    def fn(x):
        x = lax.with_sharding_constraint(pre(x), in_sh)
        return post(mapped(x))

    return fn, spec
