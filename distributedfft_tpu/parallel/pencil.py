"""Pencil-decomposed distributed 3D FFT over a 2D device mesh.

The reference's baseline (vendored heFFTe) plans pencil pipelines
brick -> z-pencil -> y-pencil -> x-pencil with up to four reshapes
(``plan_pencil_reshapes``, ``heffte/heffteBenchmark/src/heffte_plan_logic.cpp:162-245``).
The TPU-native equivalent fixes the canonical three-stage pencil pipeline on
a 2D mesh (rows x cols):

    input  z-pencils: sharded (axis0 -> row, axis1 -> col), full Z
    t0  1D FFT along Z
    t2a ``all_to_all`` over *col*: reshard Z<->Y  -> y-pencils
    t1' 1D FFT along Y
    t2b ``all_to_all`` over *row*: reshard Y<->X  -> x-pencils
    t3  1D FFT along X
    output x-pencils: sharded (axis1 -> row, axis2 -> col), full X

Both collectives ride one mesh axis each, so on a physical 2D ICI torus every
exchange stays on its ring — the property heFFTe's min-surface processor grid
chases (``heffte_geometry.h:589``). Uneven extents use the same
ceil-pad/crop scheme as :mod:`.slab` (pads only ever touch an axis while it
is *not* being transformed at its true length).

**Stage-graph IR**: every builder here emits a declarative stage graph
(:mod:`..stagegraph`) — t0 | t2a | t1 | t2b | t3 with each exchange's
downstream FFT as its fused per-chunk compute — compiled by ONE
executor, byte-identical to the pre-migration hand-threaded chains
(pinned in ``tests/test_a2m_stagegraph.py``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..geometry import pad_to
from ..ops.executors import get_executor
from ..stagegraph import (
    StageGraph, apply_midpoint, compile_fused, exchange_node, local_node,
)
from .slab import (
    _L, _crop_axis, _pad_axis, apply_multiplier, batch_pspec, check_batch,
)

__all__ = [
    "PencilSpec", "chain_geometry", "build_pencil_general",
    "build_pencil_spectral_op", "build_pencil_fft3d", "build_pencil_rfft3d",
]


@dataclass(frozen=True)
class PencilSpec:
    """Static geometry of a pencil plan on a (rows x cols) mesh.

    ``perm = (a, b, c)`` is the input layout: axis ``a`` sharded over mesh
    rows, axis ``b`` over mesh cols, axis ``c`` local (full pencils along
    ``c``). ``order`` picks which mesh axis exchanges first; the two orders
    reach two different output pencil orientations, which is the pencil
    planner's reshape-minimization lever (``heffte_plan_logic.cpp:162-245``):

    - ``"col_first"``: fft c | exch col (c<->b) | fft b | exch row (b<->a)
      | fft a -> output axis ``b`` on rows, ``c`` on cols, ``a`` local.
    - ``"row_first"``: fft c | exch row (c<->a) | fft a | exch col (a<->b)
      | fft b -> output axis ``c`` on rows, ``a`` on cols, ``b`` local.

    The canonical forward plan is perm (0, 1, 2) col_first (z-pencils in,
    x-pencils out); canonical backward is perm (1, 2, 0) row_first.
    """

    shape: tuple[int, int, int]
    rows: int
    cols: int
    row_axis: str = "row"
    col_axis: str = "col"
    perm: tuple[int, int, int] = (0, 1, 2)
    order: str = "col_first"

    @property
    def n0p(self) -> int:  # axis0 split over rows on canonical input
        return pad_to(self.shape[0], self.rows)

    @property
    def n1p_col(self) -> int:  # axis1 split over cols on canonical input
        return pad_to(self.shape[1], self.cols)

    @property
    def n1p_row(self) -> int:  # axis1 split over rows on canonical output
        return pad_to(self.shape[1], self.rows)

    @property
    def n2p(self) -> int:  # axis2 split over cols after the first exchange
        return pad_to(self.shape[2], self.cols)

    @property
    def out_placement(self) -> tuple[int, int]:
        """(row_dim, col_dim) of the output layout."""
        a, b, c = self.perm
        return (b, c) if self.order == "col_first" else (c, a)

    def _pspec(self, row_dim: int, col_dim: int) -> P:
        return P(*[
            self.row_axis if d == row_dim
            else self.col_axis if d == col_dim
            else None
            for d in range(3)
        ])

    @property
    def in_spec(self) -> P:
        return self._pspec(self.perm[0], self.perm[1])

    @property
    def out_spec(self) -> P:
        return self._pspec(*self.out_placement)


def chain_geometry(perm, order, rows, cols, row_axis, col_axis, n):
    """The pencil chain's static geometry, shared by the c64 and dd
    builders (one source of truth for the exchange-order taxonomy):
    returns ``(seq, last_fft, in_pads, out_crops)`` where ``seq`` lists
    ``(mesh_axis, parts, split_axis, concat_axis)`` per exchange."""
    a, b, c = perm
    if order == "col_first":
        seq = [(col_axis, cols, c, b), (row_axis, rows, b, a)]
        last_fft = a
    else:
        seq = [(row_axis, rows, c, a), (col_axis, cols, a, b)]
        last_fft = b
    in_pads = ((a, pad_to(n[a], rows)), (b, pad_to(n[b], cols)))
    # Each exchange's split axis keeps its pad on the global output.
    out_crops = tuple((split, n[split]) for _, _, split, _ in seq)
    return seq, last_fft, in_pads, out_crops


def build_pencil_general(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    perm: tuple[int, int, int],
    order: str,
    row_axis: str = "row",
    col_axis: str = "col",
    executor: str | Callable = "xla",
    forward: bool = True,
    donate: bool = False,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
    midpoint: Callable | None = None,
) -> tuple[Callable, PencilSpec]:
    """Build the jitted end-to-end pencil transform for ANY input layout
    permutation and exchange order (see :class:`PencilSpec` for the chain
    taxonomy). Uneven extents use the ceil-pad/crop scheme of :mod:`.slab`
    (pads only ever touch an axis while it is *not* being transformed at its
    true length).

    ``overlap_chunks > 1`` pipelines each exchange under the FFT stage
    that follows it, chunked along that exchange's bystander axis; both
    t2a and t2b overlap. ``batch=B`` prepends a leading batch axis
    (``[B, N0, N1, N2]`` of B independent transforms): batched FFT stages
    and ONE shared collective per (chunk, exchange) with the batch riding
    as a bystander dim — exactly the :func:`..slab.build_slab_general`
    convention.

    ``midpoint`` is the spectral-operator fusion hook (the
    stop-at-transposed / start-from-transposed mode): the chain stops in
    the transposed x-pencil layout, applies the wavenumber-diagonal
    multiplier there, and continues with the inverse legs back to the
    input layout (:func:`build_pencil_spectral_op`; canonical forward
    orientation only).
    """
    if midpoint is not None:
        if (not forward or tuple(perm) != (0, 1, 2)
                or order != "col_first"):
            raise ValueError(
                "the midpoint (spectral-operator) hook runs the canonical "
                "forward chain: forward=True, perm=(0, 1, 2), col_first")
        return build_pencil_spectral_op(
            mesh, shape, midpoint, row_axis=row_axis, col_axis=col_axis,
            executor=executor, donate=donate, algorithm=algorithm,
            overlap_chunks=overlap_chunks, batch=batch,
            wire_dtype=wire_dtype)
    if sorted(perm) != [0, 1, 2]:
        raise ValueError(f"perm must be a permutation of (0, 1, 2), got {perm}")
    if order not in ("col_first", "row_first"):
        raise ValueError(f"order must be col_first|row_first, got {order!r}")
    check_batch(batch)
    rows, cols = mesh.shape[row_axis], mesh.shape[col_axis]
    spec = PencilSpec(tuple(int(s) for s in shape), rows, cols,
                      row_axis, col_axis, tuple(perm), order)
    n = spec.shape
    seq, last_fft, in_pads, out_crops = chain_geometry(
        perm, order, rows, cols, row_axis, col_axis, n)
    bo = 0 if batch is None else 1  # leading-batch axis offset

    # Stage nodes: the reference taxonomy with the two pencil exchanges
    # split out as t2a/t2b; the FFT following each exchange runs along
    # that exchange's concat axis (the axis that just became local), so
    # each exchange pipelines under its own downstream fft stage.
    fft_names = (f"t0_fft_{_L[seq[0][2]]}", f"t1_fft_{_L[seq[1][2]]}")
    exch_names = (f"t2a_exchange_{seq[0][0]}", f"t2b_exchange_{seq[1][0]}")
    t3_name = f"t3_fft_{_L[last_fft]}"

    nodes = [local_node("t0", fft_names[0],
                        ("fft", (seq[0][2] + bo,), forward))]
    for i, (mesh_ax, parts, split, concat) in enumerate(seq):
        nodes.append(exchange_node(
            "t2a" if i == 0 else "t2b", exch_names[i], mesh_axis=mesh_ax,
            parts=parts, split=split + bo, concat=concat + bo,
            chunk_axis=3 - split - concat + bo))
        nodes.append(local_node(
            "t1" if i == 0 else "t3",
            fft_names[1] if i == 0 else t3_name,
            ("crop", concat + bo, n[concat]),
            ("fft", (concat + bo,), forward), fuse=True))

    graph = StageGraph(
        mesh=mesh, nodes=tuple(nodes),
        in_pspec=batch_pspec(spec.in_spec, batch),
        out_pspec=batch_pspec(spec.out_spec, batch),
        pre=tuple(("pad", ax + bo, to) for ax, to in in_pads),
        post=tuple(("crop", ax + bo, to) for ax, to in out_crops),
        # Even iff every pad in the chain is a no-op: the two input-side
        # pads and each exchange's split-axis pad.
        even=all(to == n[ax] for ax, to in in_pads) and all(
            pad_to(n[split], parts) == n[split]
            for _, parts, split, _ in seq),
        donate=donate, algorithm=algorithm, wire_dtype=wire_dtype,
        overlap_chunks=overlap_chunks, executor=executor,
        meta=dict(shape=spec.shape, batch=batch, forward=forward,
                  decomposition="pencil", kind="c2c"),
    )
    return compile_fused(graph), spec


def build_pencil_spectral_op(
    mesh: Mesh,
    shape: tuple[int, int, int],
    multiplier: Callable,
    *,
    row_axis: str = "row",
    col_axis: str = "col",
    executor: str | Callable = "xla",
    donate: bool = False,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[Callable, PencilSpec]:
    """Fused pencil FFT -> pointwise -> iFFT pipeline in ONE jitted
    program — the 2D-mesh tier of the spectral-operator chain
    (:func:`..slab.build_slab_spectral_op` documents the multiplier
    contract and the transposed-layout fusion).

    The forward half runs the canonical z-pencil -> x-pencil chain and
    STOPS in the transposed x-pencil layout (k0 full local, k1 on rows,
    k2 on cols); the multiplier is generated per shard (and per overlap
    chunk) right there, and the inverse half retraces the chain back to
    z-pencils. Four exchanges total (t2a/t2b out, t2b/t2a back) vs the
    six a natural-layout unfused forward+multiply+inverse composition
    pays — and the caller-side layout round trip disappears entirely.
    I/O is the canonical z-pencil layout on both sides.
    """
    check_batch(batch)
    rows, cols = mesh.shape[row_axis], mesh.shape[col_axis]
    spec = PencilSpec(tuple(int(s) for s in shape), rows, cols,
                      row_axis, col_axis, (0, 1, 2), "col_first")
    ex = get_executor(executor) if isinstance(executor, str) else executor
    n0, n1, n2 = spec.shape
    n0p, n1pc, n1pr, n2p = spec.n0p, spec.n1p_col, spec.n1p_row, spec.n2p
    bo = 0 if batch is None else 1
    c1 = n1pr // rows  # midpoint local k1 extent (row shard)
    c2 = n2p // cols   # midpoint local k2 extent (col shard)

    def mid_factory():
        # Transposed x-pencil midpoint: final forward FFT, the
        # wavenumber-diagonal multiply, first inverse FFT — all local
        # (bounds are this chunk's slice of the col shard).
        k1_lo = lax.axis_index(row_axis) * c1
        k2_lo = lax.axis_index(col_axis) * c2

        def mid_chunk(u, lo, hi):
            u = _crop_axis(u, bo, n0)
            u = ex(u, (bo,), True)                       # t3 of fwd half
            u = apply_midpoint(u, multiplier, (
                jnp.arange(n0, dtype=jnp.int32)[:, None, None],
                (k1_lo + jnp.arange(c1, dtype=jnp.int32))[None, :, None],
                (k2_lo + jnp.arange(lo, hi,
                                    dtype=jnp.int32))[None, None, :]))
            return ex(u, (bo,), False)                   # inverse X lines

        return mid_chunk

    nodes = (
        local_node("t0", "t0_fft_z", ("fft", (2 + bo,), True)),
        exchange_node("t2a", f"t2a_exchange_{col_axis}", mesh_axis=col_axis,
                      parts=cols, split=2 + bo, concat=1 + bo,
                      chunk_axis=bo),
        local_node("t1", "t1_fft_y",
                   ("crop", 1 + bo, n1), ("fft", (1 + bo,), True),
                   fuse=True),
        exchange_node("t2b", f"t2b_exchange_{row_axis}", mesh_axis=row_axis,
                      parts=rows, split=1 + bo, concat=bo,
                      chunk_axis=2 + bo),
        local_node("t_mid", "t_mid", fuse=True, takes_bounds=True,
                   factory=mid_factory),
        exchange_node("t2b", f"t2b_exchange_{row_axis}", mesh_axis=row_axis,
                      parts=rows, split=bo, concat=1 + bo,
                      chunk_axis=2 + bo),
        local_node("t3", "t3_ifft_y",
                   ("crop", 1 + bo, n1), ("fft", (1 + bo,), False),
                   fuse=True),
        exchange_node("t2a", f"t2a_exchange_{col_axis}", mesh_axis=col_axis,
                      parts=cols, split=1 + bo, concat=2 + bo,
                      chunk_axis=bo),
        local_node("t3", "t3_ifft_z",
                   ("crop", 2 + bo, n2), ("fft", (2 + bo,), False),
                   fuse=True),
    )
    io_spec = batch_pspec(spec.in_spec, batch)
    graph = StageGraph(
        mesh=mesh, nodes=nodes, in_pspec=io_spec, out_pspec=io_spec,
        pre=(("pad", bo, n0p), ("pad", 1 + bo, n1pc)),
        post=(("crop", bo, n0), ("crop", 1 + bo, n1)),
        even=n0p == n0 and n1pc == n1, donate=donate,
        algorithm=algorithm, wire_dtype=wire_dtype,
        overlap_chunks=overlap_chunks, executor=executor,
        meta=dict(shape=spec.shape, batch=batch, forward=True,
                  decomposition="pencil", kind="op"),
    )
    return compile_fused(graph), spec


def build_pencil_fft3d(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    row_axis: str = "row",
    col_axis: str = "col",
    executor: str | Callable = "xla",
    forward: bool = True,
    donate: bool = False,
    algorithm: str = "alltoall",
    perm: tuple[int, int, int] | None = None,
    order: str | None = None,
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[Callable, PencilSpec]:
    """Canonical-orientation wrapper over :func:`build_pencil_general`:
    forward maps z-pencils (``P(row, col, None)``) to x-pencils
    (``P(None, row, col)``); backward is the exact mirror — unless the
    planner supplies a different permutation/order.
    """
    if perm is None:
        perm = (0, 1, 2) if forward else (1, 2, 0)
    if order is None:
        order = "col_first" if forward else "row_first"
    return build_pencil_general(
        mesh, shape, perm=perm, order=order, row_axis=row_axis,
        col_axis=col_axis, executor=executor, forward=forward, donate=donate,
        algorithm=algorithm, overlap_chunks=overlap_chunks, batch=batch,
        wire_dtype=wire_dtype,
    )


def build_pencil_rfft3d(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    row_axis: str = "row",
    col_axis: str = "col",
    executor: str = "xla",
    forward: bool = True,
    donate: bool = False,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[Callable, PencilSpec]:
    """Pencil-decomposed r2c (forward) / c2r (backward) 3D transform.

    The real axis is Z (axis 2), full-extent in the input z-pencils, so the
    r2c shrink to ``n2//2+1`` happens before the first exchange — mirroring
    heFFTe's rule that the r2c reduction runs on the first pencil stage
    (``src/heffte_fft3d.cpp:202-304``). Forward maps real z-pencils
    ``[N0, N1, N2]`` to complex x-pencils ``[N0, N1, N2//2+1]``.
    ``batch=B`` prepends a leading batch axis with one shared exchange per
    batch, the :func:`build_pencil_general` convention.
    """
    if not isinstance(executor, str):
        raise TypeError("r2c builders take a registered executor name")
    check_batch(batch)
    rows, cols = mesh.shape[row_axis], mesh.shape[col_axis]
    # Direction-true spec: the canonical r2c chain is perm (0,1,2) col_first
    # forward (z->x pencils) and perm (1,2,0) row_first backward — the same
    # taxonomy as the generalized c2c builder, so plan-level shardings can be
    # read straight off the spec.
    spec = PencilSpec(
        tuple(int(s) for s in shape), rows, cols, row_axis, col_axis,
        perm=(0, 1, 2) if forward else (1, 2, 0),
        order="col_first" if forward else "row_first",
    )
    n0, n1, n2 = spec.shape
    n0p, n1pc, n1pr = spec.n0p, spec.n1p_col, spec.n1p_row
    n2h = n2 // 2 + 1
    n2hp = pad_to(n2h, cols)
    bo = 0 if batch is None else 1  # leading-batch axis offset

    if forward:
        nodes = (
            local_node("t0", "t0_r2c_z", ("r2c", 2 + bo)),
            exchange_node("t2a", f"t2a_exchange_{col_axis}",
                          mesh_axis=col_axis, parts=cols, split=2 + bo,
                          concat=1 + bo, chunk_axis=bo),
            local_node("t1", "t1_fft_y",
                       ("crop", 1 + bo, n1), ("fft", (1 + bo,), True),
                       fuse=True),
            exchange_node("t2b", f"t2b_exchange_{row_axis}",
                          mesh_axis=row_axis, parts=rows, split=1 + bo,
                          concat=bo, chunk_axis=2 + bo),
            local_node("t3", "t3_fft_x",
                       ("crop", bo, n0), ("fft", (bo,), True), fuse=True),
        )
        pre = (("pad", bo, n0p), ("pad", 1 + bo, n1pc))
        post = (("crop", 1 + bo, n1), ("crop", 2 + bo, n2h))
    else:
        nodes = (
            local_node("t3", "t3_ifft_x", ("fft", (bo,), False)),
            exchange_node("t2b", f"t2b_exchange_{row_axis}",
                          mesh_axis=row_axis, parts=rows, split=bo,
                          concat=1 + bo, chunk_axis=2 + bo),
            local_node("t1", "t1_ifft_y",
                       ("crop", 1 + bo, n1), ("fft", (1 + bo,), False),
                       fuse=True),
            # Per-chunk work after the last exchange is the crop only:
            # chunking the c2r itself trips XLA:CPU's fft-thunk layout
            # RET_CHECK (irfft on a sliced, non-dim0-major operand), so
            # the real Z transform runs monolithically after the merge —
            # the same structure as the slab c2r chain.
            exchange_node("t2a", f"t2a_exchange_{col_axis}",
                          mesh_axis=col_axis, parts=cols, split=1 + bo,
                          concat=2 + bo, chunk_axis=bo),
            local_node("t1", "t1_crop", ("crop", 2 + bo, n2h), fuse=True),
            local_node("t0", "t0_c2r_z", ("c2r", n2, 2 + bo)),
        )
        # Direction-true spec: perm (1,2,0) row_first makes spec.in_spec
        # the complex x-pencils and spec.out_spec the real z-pencils.
        pre = (("pad", 1 + bo, n1pr), ("pad", 2 + bo, n2hp))
        post = (("crop", bo, n0), ("crop", 1 + bo, n1))

    graph = StageGraph(
        mesh=mesh, nodes=nodes,
        in_pspec=batch_pspec(spec.in_spec, batch),
        out_pspec=batch_pspec(spec.out_spec, batch),
        pre=pre, post=post,
        # The complex extent n2h = n2//2+1 rarely divides the col axis
        # even when n2 does, so sharding pinning additionally requires
        # n2hp == n2h.
        even=(n0p == n0 and n1pc == n1 and n1pr == n1 and n2hp == n2h),
        donate=donate, algorithm=algorithm, wire_dtype=wire_dtype,
        overlap_chunks=overlap_chunks, executor=executor,
        meta=dict(shape=spec.shape, batch=batch, forward=forward,
                  decomposition="pencil", kind="r2c"),
    )
    return compile_fused(graph), spec
