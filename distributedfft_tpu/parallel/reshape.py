"""Standalone distributed reshapes: brick layout A -> brick layout B.

heFFTe's reshape engine (``heffte_reshape3d.h:60-498``) moves data between
arbitrary box decompositions with four MPI algorithms and explicit
pack/unpack kernels (``heffte_pack3d.h``). On TPU the same operation is a
*resharding*: the global array stays logically fixed and only its
:class:`~jax.sharding.NamedSharding` changes; XLA emits the collective
(all-to-all / collective-permute / all-gather as needed) and fuses the
pack/unpack into it — the role of ``direct_packer``/``transpose_packer``
(``heffte_pack3d.h:83,116``) is played by layout assignment.

Decompositions expressible this way are the regular grids a ``PartitionSpec``
can name (slabs, pencils, bricks from mesh-axis products) — the arbitrary
per-rank boxes of heFFTe's C API collapse to these on a mesh, since TPU
collectives require uniform shards (pad/crop handles ragged extents at the
plan layer, see :mod:`.slab`).
"""

from __future__ import annotations

from typing import Callable

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_reshape3d(
    mesh: Mesh, in_spec: P, out_spec: P, *, donate: bool = False
) -> Callable:
    """Build a jitted reshard: array sharded ``in_spec`` -> ``out_spec``.

    The analog of ``make_reshape3d`` (``heffte_reshape3d.h:498``), with the
    algorithm menu replaced by XLA's collective selection. Works for any
    global shape (one compiled executable per shape, cached by jit).
    """
    in_sh = NamedSharding(mesh, in_spec)
    out_sh = NamedSharding(mesh, out_spec)

    def _fn(x):
        x = lax.with_sharding_constraint(x, in_sh)
        return lax.with_sharding_constraint(x, out_sh)

    return jax.jit(_fn, donate_argnums=0) if donate else jax.jit(_fn)


def reshape3d(x, mesh: Mesh, out_spec: P):
    """One-shot reshard of ``x`` to ``out_spec`` on ``mesh``."""
    return jax.device_put(x, NamedSharding(mesh, out_spec))
