"""Slab-decomposed distributed 3D FFT over a 1D device mesh.

TPU-native re-design of the reference's core engine
(``3dmpifft_opt/include/fft_mpi_3d_api.cpp``): the forward pipeline is the
same four-stage taxonomy the reference prints as t0..t3
(``fft_mpi_3d_api.cpp:181-214``, ``README.md:44-58``):

    t0  batched 2D FFT over the local YZ planes   (``fftZY``, :466)
    t1  local transpose / layout prep             (``localTransposeUneven``, :575)
    t2  global transpose across devices           (``slabAlltoall``, :610)
    t3  batched 1D FFT over X                     (``fftX``, :524)

but each stage is expressed the XLA way: t0/t3 are executor calls that XLA
fuses and tiles, t1 degenerates to a pad (XLA chooses physical layouts, so
the hand-written transpose kernels of ``kernel_func.cpp:45-158`` and the
vendored cuTranspose engine have no TPU analog), and t2 is a single
``jax.lax.all_to_all`` on the mesh axis riding ICI — replacing
``hipMemcpyPeerAsync`` + ``MPI_Isend/Irecv`` peer tables (:627-672).

Uneven shapes: ``all_to_all`` needs equal shards, so instead of the
reference's asymmetric per-peer count tables (``fft_mpi_3d_api.cpp:93-133``)
both split axes are ceil-padded; zero-padding is inserted only where it
cannot perturb a transform (before an axis is FFT'd at its true length) and
cropped on output. With divisible shapes every pad/crop is a no-op.

Data layout convention: the forward input is X-slabs (global array sharded
along axis 0) and the forward output is Y-slabs (sharded along axis 1) in
*natural index order* — the reference's physically-transposed output layout
is a GPU-memory-coalescing concern that XLA's layout assignment subsumes.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from ..geometry import pad_to
from ..ops.executors import get_c2r, get_executor, get_r2c
from ..utils.trace import add_trace, trace_stages
# _pad_axis/_crop_axis live in exchange.py (single definition shared with
# the ragged path) and are re-exported here for the other chain builders.
from .exchange import (
    _axis_label, _crop_axis, _pad_axis, exchange_chunked,
    exchange_overlapped, hierarchical_legs, wire_codec,
)

_L = "xyz"  # axis index -> stage-name letter (t0_fft_yz taxonomy)


def _axis_parts(mesh: Mesh, axis_name) -> tuple[int, tuple | None]:
    """(combined parts, per-axis sizes) of a slab chain's mesh-axis spec:
    a plain 1D axis name, or the (dcn, ici) tuple of the hierarchical
    transport's hybrid mesh (row-major linearization = the combined slab
    axis). ``axis_sizes`` is None for a plain axis — the flat transports
    take the single named axis exactly as before."""
    if isinstance(axis_name, (tuple, list)):
        sizes = tuple(int(mesh.shape[a]) for a in axis_name)
        return math.prod(sizes), sizes
    return int(mesh.shape[axis_name]), None


def check_batch(batch: int | None) -> int | None:
    """Validate a chain-builder ``batch`` argument: ``None`` is the
    unbatched 3D chain (today's HLO exactly); an int >= 1 prepends a
    leading batch axis of that extent carrying B independent transforms
    through ONE shared exchange per t2 stage (the batch rides every
    collective as a bystander dim — B transforms pay one collective
    latency)."""
    if batch is None:
        return None
    if not isinstance(batch, int) or isinstance(batch, bool) or batch < 1:
        raise ValueError(f"batch must be an int >= 1 or None, got {batch!r}")
    return batch


def batch_pspec(pspec: P, batch: int | None) -> P:
    """The 3D chain PartitionSpec with a leading replicated batch entry
    prepended when the chain is batched; the spec itself (same object)
    otherwise — shared by every chain builder and the plan layer so
    batched shardings can never drift between them."""
    return pspec if batch is None else P(*((None,) + tuple(pspec)))


@dataclass(frozen=True)
class SlabSpec:
    """Static geometry of a slab plan: true and padded extents.

    ``in_axis``/``out_axis`` are the sharded array axes of this plan's input
    and output — the generalized axis assignment that lets the planner start
    a chain directly on a caller's slab layout (reshape minimization,
    ``heffte_plan_logic.cpp:265-408``). The canonical forward plan is
    (0, 1): X-slabs in, Y-slabs out.
    """

    shape: tuple[int, int, int]
    parts: int
    axis_name: str
    in_axis: int = 0
    out_axis: int = 1

    @property
    def n0p(self) -> int:
        return pad_to(self.shape[0], self.parts)

    @property
    def n1p(self) -> int:
        return pad_to(self.shape[1], self.parts)

    @property
    def in_padded_extent(self) -> int:
        return pad_to(self.shape[self.in_axis], self.parts)

    @property
    def out_padded_extent(self) -> int:
        return pad_to(self.shape[self.out_axis], self.parts)

    @property
    def in_pspec(self) -> P:
        return P(*[self.axis_name if d == self.in_axis else None
                   for d in range(3)])

    @property
    def out_pspec(self) -> P:
        return P(*[self.axis_name if d == self.out_axis else None
                   for d in range(3)])

    @property
    def in_padded(self) -> tuple[int, int, int]:
        s = list(self.shape)
        s[self.in_axis] = self.in_padded_extent
        return tuple(s)

    @property
    def out_padded(self) -> tuple[int, int, int]:
        s = list(self.shape)
        s[self.out_axis] = self.out_padded_extent
        return tuple(s)


def build_slab_general(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    in_axis: int,
    out_axis: int,
    axis_name: str | tuple = "slab",
    executor: str | Callable = "xla",
    forward: bool = True,
    donate: bool = False,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
    midpoint: Callable | None = None,
) -> tuple[Callable, SlabSpec]:
    """Build the jitted end-to-end slab transform for ANY ordered axis pair.

    Input is the global ``[N0, N1, N2]`` array sharded along ``in_axis``;
    the two other axes are transformed locally, one exchange reshards
    ``in_axis <-> out_axis``, and ``in_axis`` is transformed last — so the
    chain works started from any slab layout (reshape minimization,
    ``heffte_plan_logic.cpp:265-408``). The canonical forward plan is
    ``(in_axis, out_axis) = (0, 1)`` (the reference engine's only mode,
    ``fft_mpi_3d_api.cpp:181-214``), backward is (1, 0).

    ``overlap_chunks > 1`` pipelines t2 under t3 along the bystander axis
    (:func:`.exchange.exchange_overlapped`); 1 is today's monolithic chain.

    ``batch=B`` prepends a leading batch axis: the input is ``[B, N0, N1,
    N2]`` carrying B independent transforms, t0/t3 run as batched FFTs,
    and the t2 global transpose is ONE shared collective per (chunk,
    exchange) with the batch riding as a bystander dim — B transforms pay
    one collective latency. ``None`` is the unbatched 3D chain, today's
    HLO exactly.

    ``midpoint`` is the spectral-operator fusion hook (the
    stop-at-transposed / start-from-transposed mode): a wavenumber-
    indexed pointwise multiplier generator applied at the chain's
    transposed full-spectrum midpoint, after which the chain continues
    with the INVERSE legs back to the input layout — the whole fused
    FFT -> pointwise -> iFFT round trip as one program
    (:func:`build_slab_spectral_op`; canonical forward orientation
    only).
    """
    if midpoint is not None:
        if not forward or (in_axis, out_axis) != (0, 1):
            raise ValueError(
                "the midpoint (spectral-operator) hook runs the canonical "
                "forward chain: forward=True, (in_axis, out_axis)=(0, 1)")
        return build_slab_spectral_op(
            mesh, shape, midpoint, axis_name=axis_name, executor=executor,
            donate=donate, algorithm=algorithm,
            overlap_chunks=overlap_chunks, batch=batch,
            wire_dtype=wire_dtype)
    if in_axis == out_axis or not (0 <= in_axis < 3 and 0 <= out_axis < 3):
        raise ValueError(f"need distinct 3D axes, got {in_axis}, {out_axis}")
    check_batch(batch)
    p, axis_sizes = _axis_parts(mesh, axis_name)
    spec = SlabSpec(tuple(int(s) for s in shape), p, axis_name,
                    in_axis, out_axis)
    ex = get_executor(executor) if isinstance(executor, str) else executor
    n_in, n_out = spec.shape[in_axis], spec.shape[out_axis]
    n_inp, n_outp = spec.in_padded_extent, spec.out_padded_extent
    local_axes = tuple(a for a in range(3) if a != in_axis)
    platform = mesh.devices.flat[0].platform
    # Leading-batch offset: spatial axis a of the 3D chain is array axis
    # a + bo. Stage names, SlabSpec, and all geometry stay spatial.
    bo = 0 if batch is None else 1
    ax_in, ax_out = in_axis + bo, out_axis + bo
    chunk_axis = 3 - in_axis - out_axis + bo  # spatial bystander

    # Stage spans of the reference taxonomy (fft_mpi_3d_api.cpp:184-201):
    # recorded dispatch-side when the jit first traces, and passed through
    # to the device timeline as profiler annotations.
    t0_name = f"t0_fft_{''.join(_L[a] for a in local_axes)}"
    t2_name = f"t2_exchange_{_axis_label(axis_name)}"
    t3_name = f"t3_fft_{_L[in_axis]}"

    def t3_chunk(y):
        y = _crop_axis(y, ax_in, n_in)                   # drop in-axis padding
        return ex(y, (ax_in,), forward)                  # t3: final lines

    def local_fn(x):  # in_axis extent n_inp/p per device, others full
        with add_trace(t0_name):
            y = ex(x, tuple(a + bo for a in local_axes), forward)  # t0
        with add_trace("t1_pack"):
            # exchange prep: dense algorithms ceil-pad the split axis
            # (alltoallv ships the true slices; the pad below is then a
            # no-op inside exchange_uneven, which skips it)
            if algorithm != "alltoallv":
                y = _pad_axis(y, ax_out, n_outp)
        # t2 + t3: monolithic exchange-then-fft at overlap_chunks=1, the
        # chunked pipelined interleave above it.
        return exchange_overlapped(
            y, axis_name, split_axis=ax_out, concat_axis=ax_in,
            axis_size=p, algorithm=algorithm, platform=platform,
            axis_sizes=axis_sizes, wire_dtype=wire_dtype,
            compute=t3_chunk, overlap_chunks=overlap_chunks,
            chunk_axis=chunk_axis,
            exchange_name=t2_name, compute_name=t3_name)

    in_spec = batch_pspec(spec.in_pspec, batch)
    out_spec = batch_pspec(spec.out_pspec, batch)
    mapped = _shard_map(local_fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec)

    in_sh = NamedSharding(mesh, in_spec)
    out_sh = NamedSharding(mesh, out_spec)
    # jit-level shardings require divisible extents; when the plan pads, the
    # constraint moves inside (after the pad / before the crop) instead.
    even = n_inp == n_in and n_outp == n_out
    jit_kw: dict = {"donate_argnums": 0} if donate else {}
    if even:
        jit_kw |= {"in_shardings": in_sh, "out_shardings": out_sh}

    @functools.partial(jax.jit, **jit_kw)
    def fn(x):
        x = _pad_axis(x, ax_in, n_inp)
        x = lax.with_sharding_constraint(x, in_sh)
        y = mapped(x)
        return _crop_axis(y, ax_out, n_out)

    return fn, spec


def combined_axis_index(mesh: Mesh, axis_name):
    """Device index along a slab chain's mesh-axis spec, inside
    ``shard_map``: ``lax.axis_index`` of a plain axis, or the row-major
    linearization of a hierarchical plan's (dcn, ici) tuple — the same
    device order as ``P((dcn, ici))``'s combined sharding, so per-shard
    wavenumber offsets agree with what XLA placed on each device."""
    if isinstance(axis_name, (tuple, list)):
        idx = lax.axis_index(axis_name[0])
        for a in axis_name[1:]:
            idx = idx * mesh.shape[a] + lax.axis_index(a)
        return idx
    return lax.axis_index(axis_name)


def apply_multiplier(u: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """Pointwise spectral multiply without dtype surprises: a real
    multiplier casts to the payload's component dtype (f64 constants
    must not promote a c64 chain to c128), a complex one to the payload
    dtype. ``m`` is rank-3 (spatial) and broadcasts over any leading
    batch axis."""
    if jnp.issubdtype(m.dtype, jnp.complexfloating):
        return u * m.astype(u.dtype)
    rdt = jnp.float64 if u.dtype == jnp.dtype(jnp.complex128) else jnp.float32
    return u * m.astype(rdt)


def build_slab_spectral_op(
    mesh: Mesh,
    shape: tuple[int, int, int],
    multiplier: Callable,
    *,
    axis_name: str | tuple = "slab",
    executor: str | Callable = "xla",
    donate: bool = False,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[Callable, SlabSpec]:
    """Fused slab FFT -> pointwise -> iFFT pipeline in ONE jitted program.

    The spectral-operator chain (AccFFT's operator tier, arXiv
    1506.07933): the forward half runs ``stop_at_transposed`` — t0
    (local YZ FFTs), t1 pack, t2 exchange, then the final X FFT *in the
    transposed (Y-slab) layout* — the pointwise multiplier is applied
    right there (the ``t_mid`` stage), and the inverse half runs
    ``start_from_transposed``: inverse X FFT, the return exchange, and
    the inverse YZ FFTs back to the input's X-slab layout. Because the
    multiplier is diagonal (pointwise) in the transposed layout, the
    natural-order restore transpose a back-to-back forward+inverse pair
    would pay on each side of the multiply cancels — the fused chain
    compiles exactly TWO all-to-alls where the unfused natural-layout
    pair compiles four (the classic pruned-spectral-solver trick;
    pinned in ``tests/test_a2h_operators.py``).

    ``multiplier(i0, i1, i2)`` receives broadcastable int32 GLOBAL index
    grids of the three spatial axes (already offset for this shard and
    overlap chunk — the transposed midpoint layout) and returns the
    pointwise factor (real or complex, broadcastable to the grids'
    shape). Index rows landing in ceil-pad territory are cropped before
    any inverse transform, so their values only need to be finite.

    Composes with every chain axis: ``overlap_chunks`` pipelines BOTH
    exchanges (the multiplier is generated per chunk via the midpoint
    bounds hook), ``batch=B`` rides the collectives as a bystander dim
    (the multiplier broadcasts over it), ``wire_dtype`` compresses each
    exchange's wire with the multiplier applying on the DECODED payload,
    and ``algorithm="hierarchical"`` runs each exchange as the two-leg
    ICI/DCN transport over a hybrid-mesh ``axis_name`` tuple.

    I/O is the canonical X-slab layout on both sides (in == out
    sharding); forward transform unnormalized, inverse scaled 1/N —
    i.e. a unit multiplier is the identity.
    """
    check_batch(batch)
    p, axis_sizes = _axis_parts(mesh, axis_name)
    spec = SlabSpec(tuple(int(s) for s in shape), p, axis_name, 0, 1)
    ex = get_executor(executor) if isinstance(executor, str) else executor
    n0, n1, n2 = spec.shape
    n0p, n1p = spec.n0p, spec.n1p
    platform = mesh.devices.flat[0].platform
    bo = 0 if batch is None else 1
    c1 = n1p // p  # transposed-midpoint local extent of the k1 axis
    t2_name = f"t2_exchange_{_axis_label(axis_name)}"

    def local_fn(x):  # X-slab shard [(B,) n0p/p, N1, N2]
        with add_trace("t0_fft_yz"):
            y = ex(x, (1 + bo, 2 + bo), True)            # t0: YZ planes
        with add_trace("t1_pack"):
            if algorithm != "alltoallv":
                y = _pad_axis(y, 1 + bo, n1p)
        k1_lo = combined_axis_index(mesh, axis_name) * c1

        def mid_chunk(u, lo, hi):
            # The transposed-space midpoint: final forward FFT, the
            # wavenumber-diagonal multiply, and the first inverse FFT —
            # all local in the Y-slab layout (k0 full, k1 this shard's
            # slice, k2 this overlap chunk's slice).
            u = _crop_axis(u, bo, n0)
            u = ex(u, (bo,), True)                       # t3 of fwd half
            with add_trace("t_mid_pointwise"):
                m = multiplier(
                    jnp.arange(n0, dtype=jnp.int32)[:, None, None],
                    (k1_lo + jnp.arange(c1, dtype=jnp.int32))[None, :, None],
                    jnp.arange(lo, hi, dtype=jnp.int32)[None, None, :])
                u = apply_multiplier(u, m)
            return ex(u, (bo,), False)                   # inverse X lines

        y = exchange_overlapped(
            y, axis_name, split_axis=1 + bo, concat_axis=bo,
            axis_size=p, algorithm=algorithm, platform=platform,
            axis_sizes=axis_sizes, wire_dtype=wire_dtype,
            compute=mid_chunk, compute_takes_bounds=True,
            overlap_chunks=overlap_chunks, chunk_axis=2 + bo,
            exchange_name=t2_name, compute_name="t_mid")
        with add_trace("t1_pack"):
            if algorithm != "alltoallv":
                y = _pad_axis(y, bo, n0p)

        def inv_chunk(v):
            v = _crop_axis(v, 1 + bo, n1)
            return ex(v, (1 + bo,), False)               # inverse Y lines

        # The inverse Z pass transforms the bystander (chunk) axis, so it
        # runs monolithically after the chunked exchange/ifft-Y merge —
        # the same discipline as the c2r chains' final real transform.
        y = exchange_overlapped(
            y, axis_name, split_axis=bo, concat_axis=1 + bo,
            axis_size=p, algorithm=algorithm, platform=platform,
            axis_sizes=axis_sizes, wire_dtype=wire_dtype,
            compute=inv_chunk,
            overlap_chunks=overlap_chunks, chunk_axis=2 + bo,
            exchange_name=t2_name, compute_name="t3_ifft_y")
        with add_trace("t3_ifft_z"):
            return ex(y, (2 + bo,), False)               # inverse Z lines

    io_spec = batch_pspec(spec.in_pspec, batch)
    mapped = _shard_map(local_fn, mesh=mesh, in_specs=(io_spec,),
                        out_specs=io_spec)
    io_sh = NamedSharding(mesh, io_spec)
    # Only axis 0 is sharded at the jit boundary (in == out layout), so
    # the sharding pin needs only the in-axis to divide.
    even = n0p == n0
    jit_kw: dict = {"donate_argnums": 0} if donate else {}
    if even:
        jit_kw |= {"in_shardings": io_sh, "out_shardings": io_sh}

    @functools.partial(jax.jit, **jit_kw)
    def fn(x):
        x = _pad_axis(x, bo, n0p)
        x = lax.with_sharding_constraint(x, io_sh)
        y = mapped(x)
        return _crop_axis(y, bo, n0)

    return fn, spec


def build_slab_fft3d(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    axis_name: str | tuple = "slab",
    executor: str | Callable = "xla",
    forward: bool = True,
    donate: bool = False,
    algorithm: str = "alltoall",
    in_axis: int | None = None,
    out_axis: int | None = None,
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[Callable, SlabSpec]:
    """Canonical-orientation wrapper over :func:`build_slab_general`:
    X-slabs -> Y-slabs forward, Y-slabs -> X-slabs backward (the reference
    pipeline, ``fft_mpi_3d_api.cpp:181-214``), unless the planner supplies a
    different axis pair.
    """
    d_in, d_out = (0, 1) if forward else (1, 0)
    return build_slab_general(
        mesh, shape,
        in_axis=d_in if in_axis is None else in_axis,
        out_axis=d_out if out_axis is None else out_axis,
        axis_name=axis_name, executor=executor, forward=forward,
        donate=donate, algorithm=algorithm, overlap_chunks=overlap_chunks,
        batch=batch, wire_dtype=wire_dtype,
    )


def build_slab_rfft3d(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    axis_name: str = "slab",
    executor: str = "xla",
    forward: bool = True,
    donate: bool = False,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[Callable, SlabSpec]:
    """Slab-decomposed real-to-complex (forward) / complex-to-real (backward)
    3D transform — the distributed analog of heFFTe's ``fft3d_r2c``
    (``heffte_fft3d_r2c.h``, ``src/heffte_fft3d.cpp:202-304``).

    The real axis is axis 2 (Z), which is always device-local in the slab
    decomposition, so the r2c shrink to ``n2//2+1`` (``box3d::r2c``,
    ``heffte_geometry.h:94``) happens before any exchange. Forward maps real
    X-slabs ``[N0, N1, N2]`` to complex Y-slabs ``[N0, N1, N2//2+1]``;
    backward is the exact inverse (output real, numpy 1/N scaling).
    ``batch=B`` prepends a leading batch axis with one shared exchange per
    batch, exactly like :func:`build_slab_general`.
    """
    if not isinstance(executor, str):
        raise TypeError("r2c builders take a registered executor name")
    check_batch(batch)
    p = mesh.shape[axis_name]
    # Direction-true spec (like build_slab_general): forward maps X-slabs to
    # Y-slabs, backward the mirror — so plan-level shardings read straight
    # off the spec.
    spec = SlabSpec(
        tuple(int(s) for s in shape), p, axis_name,
        in_axis=0 if forward else 1, out_axis=1 if forward else 0,
    )
    ex = get_executor(executor)
    r2c, c2r = get_r2c(executor), get_c2r(executor)
    n0, n1, n2 = spec.shape
    n0p, n1p = spec.n0p, spec.n1p
    bo = 0 if batch is None else 1  # leading-batch axis offset
    in_spec = batch_pspec(spec.in_pspec, batch)
    out_spec = batch_pspec(spec.out_pspec, batch)

    if forward:

        def t3_chunk(y):
            y = _crop_axis(y, bo, n0)
            return ex(y, (bo,), True)                    # t3: X lines

        def local_fn(x):  # real [n0p/p, N1, N2] per device
            with add_trace("t0_r2c_zy"):
                y = r2c(x, 2 + bo)                       # t0a: real Z lines
                y = ex(y, (1 + bo,), True)               # t0b: Y lines
            with add_trace("t1_pack"):
                if algorithm != "alltoallv":
                    y = _pad_axis(y, 1 + bo, n1p)
            return exchange_overlapped(
                y, axis_name, split_axis=1 + bo, concat_axis=bo,
                axis_size=p, algorithm=algorithm, compute=t3_chunk,
                wire_dtype=wire_dtype,
                overlap_chunks=overlap_chunks, chunk_axis=2 + bo,
                exchange_name=f"t2_exchange_{axis_name}",
                compute_name="t3_fft_x")

        pre = lambda x: _pad_axis(x, bo, n0p)
        post = lambda y: _crop_axis(y, 1 + bo, n1)
    else:

        def t0_chunk(x):
            x = _crop_axis(x, 1 + bo, n1)
            return ex(x, (1 + bo,), False)               # inverse Y lines

        def local_fn(y):  # complex [N0, n1p/p, n2h] per device
            with add_trace("t3_ifft_x"):
                x = ex(y, (bo,), False)                  # inverse X lines
            with add_trace("t1_pack"):
                if algorithm != "alltoallv":
                    x = _pad_axis(x, bo, n0p)
            # The c2r (real Z lines) transforms the bystander axis, so it
            # runs monolithically after the chunked exchange/ifft-Y merge.
            x = exchange_overlapped(
                x, axis_name, split_axis=bo, concat_axis=1 + bo,
                axis_size=p, algorithm=algorithm, compute=t0_chunk,
                wire_dtype=wire_dtype,
                overlap_chunks=overlap_chunks, chunk_axis=2 + bo,
                exchange_name=f"t2_exchange_{axis_name}",
                compute_name="t0_ifft_y")
            with add_trace("t0_c2r_z"):
                return c2r(x, n2, 2 + bo)                # real Z lines

        pre = lambda y: _pad_axis(y, 1 + bo, n1p)
        post = lambda x: _crop_axis(x, bo, n0)

    mapped = _shard_map(local_fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec)
    in_sh = NamedSharding(mesh, in_spec)
    jit_kw: dict = {"donate_argnums": 0} if donate else {}
    if spec.n0p == n0 and spec.n1p == n1:
        jit_kw |= {"in_shardings": in_sh,
                   "out_shardings": NamedSharding(mesh, out_spec)}

    @functools.partial(jax.jit, **jit_kw)
    def fn(x):
        x = lax.with_sharding_constraint(pre(x), in_sh)
        return post(mapped(x))

    return fn, spec


def build_slab_stages(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    axis_name: str | tuple = "slab",
    executor: str | Callable = "xla",
    forward: bool = True,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[list[tuple[str, Callable]], SlabSpec]:
    """The same transform split into separately-jitted t0..t3 stages for the
    per-stage timing breakdown the reference prints on every execute
    (``fft_mpi_3d_api.cpp:184-201``). Fusing everything under one jit hides
    the ICI cost (SURVEY.md §7 "hard parts"), so benchmarking keeps this
    staged mode alongside the fused one. ``overlap_chunks > 1`` keeps the
    overlapped chains' K-collective transport shape inside the t2 stage
    (:func:`.exchange.exchange_chunked`). ``batch=B`` runs the stages over
    ``[B, ...]`` arrays with one shared exchange per chunk.

    ``algorithm="hierarchical"`` (hybrid mesh; ``axis_name`` a (dcn, ici)
    tuple) splits the t2 stage into its two axis-local legs — separately
    jitted ``t2a``/``t2b`` stages, so the per-stage harness times each
    fabric's leg on its own. overlap_chunks > 1 keeps ONE t2 stage (a
    per-chunk leg boundary would multiply stage dispatches), but inside
    it the K chunks run the leg-level pipeline — chunk i's ICI leg
    issued before chunk i-1's DCN leg, with per-chunk ``t2a[k]`` /
    ``t2b[k]`` spans (:func:`.exchange.exchange_chunked`).
    ``wire_dtype`` compresses each exchange stage's wire exactly like the
    fused chain (the t2 stage boundary still carries the decoded complex
    array, so stage I/O shapes are unchanged).
    """
    check_batch(batch)
    p, axis_sizes = _axis_parts(mesh, axis_name)
    spec = SlabSpec(tuple(int(s) for s in shape), p, axis_name)
    ex = get_executor(executor) if isinstance(executor, str) else executor
    n0, n1, n2 = spec.shape
    n0p, n1p = spec.n0p, spec.n1p
    bo = 0 if batch is None else 1  # leading-batch axis offset

    xs = batch_pspec(P(axis_name, None, None), batch)
    ys = batch_pspec(P(None, axis_name, None), batch)
    x_slab = NamedSharding(mesh, xs)
    y_slab = NamedSharding(mesh, ys)

    def smap(f, ins, outs):
        return _shard_map(f, mesh=mesh, in_specs=(ins,), out_specs=outs)

    def t2_stages(split_axis, concat_axis, ins, outs, in_sh, out_sh):
        """The t2 tier: one chunked exchange stage, or the hierarchical
        transport's two per-leg stages (K=1 only — see docstring)."""
        if algorithm == "hierarchical" and overlap_chunks <= 1:
            leg_ici, leg_dcn = hierarchical_legs(
                axis_name, split_axis=split_axis, concat_axis=concat_axis,
                axis_sizes=axis_sizes)
            dcn_name, ici_name = axis_name

            def wrap(leg, tile_axis_out):
                if wire_dtype is None:
                    return leg
                # Per-leg wire casts: every registered codec round-trips
                # idempotently (bf16 by value, int8 by its power-of-two
                # steps), so leg-boundary decode/re-encode is
                # bit-identical to the fused chain's single cast pair
                # around both legs. The legs permute peer tiles and
                # sidecar slots identically, so decode aligns on the
                # axis the tiles sit on at the leg's exit
                # (``tile_axis_out``).
                codec = wire_codec(wire_dtype)

                def run(u):
                    parts = codec.encode(u, tile_axis=split_axis,
                                         tiles=p)
                    done = tuple(leg(w) for w in parts)
                    return codec.decode(done, u.dtype,
                                        tile_axis=tile_axis_out,
                                        tiles=p)

                return run

            return [
                (f"t2a_exchange_{_axis_label(ici_name)}", jax.jit(
                    smap(wrap(leg_ici, split_axis), ins, ins),
                    in_shardings=in_sh, out_shardings=in_sh)),
                (f"t2b_exchange_{_axis_label(dcn_name)}", jax.jit(
                    smap(wrap(leg_dcn, concat_axis), ins, outs),
                    in_shardings=in_sh, out_shardings=out_sh)),
            ]
        return [
            ("t2_all_to_all", jax.jit(
                smap(lambda v: exchange_chunked(
                    v, axis_name, split_axis=split_axis,
                    concat_axis=concat_axis, axis_size=p,
                    algorithm=algorithm, axis_sizes=axis_sizes,
                    wire_dtype=wire_dtype,
                    overlap_chunks=overlap_chunks, chunk_axis=2 + bo),
                    ins, outs),
                in_shardings=in_sh, out_shardings=out_sh)),
        ]

    if forward:
        stages = [
            ("t0_fft_yz", jax.jit(
                lambda x: _pad_axis(smap(
                    lambda v: ex(v, (1 + bo, 2 + bo), True), xs, xs)(
                    _pad_axis(x, bo, n0p)), 1 + bo, n1p),
                in_shardings=x_slab, out_shardings=x_slab)),
            *t2_stages(1 + bo, bo, xs, ys, x_slab, y_slab),
            ("t3_fft_x", jax.jit(
                lambda v: _crop_axis(smap(
                    lambda u: ex(_crop_axis(u, bo, n0), (bo,), True),
                    ys, ys)(v), 1 + bo, n1),
                in_shardings=y_slab, out_shardings=y_slab)),
        ]
    else:
        stages = [
            ("t3_ifft_x", jax.jit(
                lambda v: _pad_axis(smap(
                    lambda u: ex(u, (bo,), False), ys, ys)(
                    _pad_axis(v, 1 + bo, n1p)), bo, n0p),
                in_shardings=y_slab, out_shardings=y_slab)),
            *t2_stages(bo, 1 + bo, ys, xs, y_slab, x_slab),
            ("t0_ifft_yz", jax.jit(
                lambda v: _crop_axis(smap(
                    lambda u: ex(_crop_axis(u, 1 + bo, n1), (1 + bo, 2 + bo),
                                 False), xs, xs)(v), bo, n0),
                in_shardings=x_slab, out_shardings=x_slab)),
        ]
    return trace_stages(stages), spec
