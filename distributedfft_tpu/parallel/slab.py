"""Slab-decomposed distributed 3D FFT over a 1D device mesh.

TPU-native re-design of the reference's core engine
(``3dmpifft_opt/include/fft_mpi_3d_api.cpp``): the forward pipeline is the
same four-stage taxonomy the reference prints as t0..t3
(``fft_mpi_3d_api.cpp:181-214``, ``README.md:44-58``):

    t0  batched 2D FFT over the local YZ planes   (``fftZY``, :466)
    t1  local transpose / layout prep             (``localTransposeUneven``, :575)
    t2  global transpose across devices           (``slabAlltoall``, :610)
    t3  batched 1D FFT over X                     (``fftX``, :524)

but each stage is expressed the XLA way: t0/t3 are executor calls that XLA
fuses and tiles, t1 degenerates to a pad (XLA chooses physical layouts, so
the hand-written transpose kernels of ``kernel_func.cpp:45-158`` and the
vendored cuTranspose engine have no TPU analog), and t2 is a single
``jax.lax.all_to_all`` on the mesh axis riding ICI — replacing
``hipMemcpyPeerAsync`` + ``MPI_Isend/Irecv`` peer tables (:627-672).

Uneven shapes: ``all_to_all`` needs equal shards, so instead of the
reference's asymmetric per-peer count tables (``fft_mpi_3d_api.cpp:93-133``)
both split axes are ceil-padded; zero-padding is inserted only where it
cannot perturb a transform (before an axis is FFT'd at its true length) and
cropped on output. With divisible shapes every pad/crop is a no-op.

Data layout convention: the forward input is X-slabs (global array sharded
along axis 0) and the forward output is Y-slabs (sharded along axis 1) in
*natural index order* — the reference's physically-transposed output layout
is a GPU-memory-coalescing concern that XLA's layout assignment subsumes.

**Stage-graph IR**: since the chain-IR migration, every builder in this
module *emits a declarative stage graph* (:mod:`..stagegraph`) instead of
hand-threading stages; ONE compiler executes it (trace spans, donation,
overlap-K interleaving, sharding pins). The emitted graphs compile
byte-identical HLO to the pre-migration chains — pinned in
``tests/test_a2m_stagegraph.py``.
"""

from __future__ import annotations

import math
from typing import Callable

import jax.numpy as jnp
from dataclasses import dataclass
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..geometry import pad_to
from ..ops.executors import get_executor
from ..stagegraph import (
    StagedGraph, StagedStage, StageGraph, apply_midpoint, apply_multiplier,
    compile_fused, compile_staged, exchange_node, local_node,
)
# _pad_axis/_crop_axis live in exchange.py (single definition shared with
# the ragged path) and are re-exported here for the other chain builders.
from .exchange import _axis_label, _crop_axis, _pad_axis  # noqa: F401

__all__ = [
    "SlabSpec", "build_slab_general", "build_slab_spectral_op",
    "build_slab_fft3d", "build_slab_rfft3d", "build_slab_stages",
    "apply_multiplier", "batch_pspec", "check_batch",
    "combined_axis_index",
]

_L = "xyz"  # axis index -> stage-name letter (t0_fft_yz taxonomy)


def _axis_parts(mesh: Mesh, axis_name) -> tuple[int, tuple | None]:
    """(combined parts, per-axis sizes) of a slab chain's mesh-axis spec:
    a plain 1D axis name, or the (dcn, ici) tuple of the hierarchical
    transport's hybrid mesh (row-major linearization = the combined slab
    axis). ``axis_sizes`` is None for a plain axis — the flat transports
    take the single named axis exactly as before."""
    if isinstance(axis_name, (tuple, list)):
        sizes = tuple(int(mesh.shape[a]) for a in axis_name)
        return math.prod(sizes), sizes
    return int(mesh.shape[axis_name]), None


def check_batch(batch: int | None) -> int | None:
    """Validate a chain-builder ``batch`` argument: ``None`` is the
    unbatched 3D chain (today's HLO exactly); an int >= 1 prepends a
    leading batch axis of that extent carrying B independent transforms
    through ONE shared exchange per t2 stage (the batch rides every
    collective as a bystander dim — B transforms pay one collective
    latency)."""
    if batch is None:
        return None
    if not isinstance(batch, int) or isinstance(batch, bool) or batch < 1:
        raise ValueError(f"batch must be an int >= 1 or None, got {batch!r}")
    return batch


def batch_pspec(pspec: P, batch: int | None) -> P:
    """The 3D chain PartitionSpec with a leading replicated batch entry
    prepended when the chain is batched; the spec itself (same object)
    otherwise — shared by every chain builder and the plan layer so
    batched shardings can never drift between them."""
    return pspec if batch is None else P(*((None,) + tuple(pspec)))


@dataclass(frozen=True)
class SlabSpec:
    """Static geometry of a slab plan: true and padded extents.

    ``in_axis``/``out_axis`` are the sharded array axes of this plan's input
    and output — the generalized axis assignment that lets the planner start
    a chain directly on a caller's slab layout (reshape minimization,
    ``heffte_plan_logic.cpp:265-408``). The canonical forward plan is
    (0, 1): X-slabs in, Y-slabs out.
    """

    shape: tuple[int, int, int]
    parts: int
    axis_name: str
    in_axis: int = 0
    out_axis: int = 1

    @property
    def n0p(self) -> int:
        return pad_to(self.shape[0], self.parts)

    @property
    def n1p(self) -> int:
        return pad_to(self.shape[1], self.parts)

    @property
    def in_padded_extent(self) -> int:
        return pad_to(self.shape[self.in_axis], self.parts)

    @property
    def out_padded_extent(self) -> int:
        return pad_to(self.shape[self.out_axis], self.parts)

    @property
    def in_pspec(self) -> P:
        return P(*[self.axis_name if d == self.in_axis else None
                   for d in range(3)])

    @property
    def out_pspec(self) -> P:
        return P(*[self.axis_name if d == self.out_axis else None
                   for d in range(3)])

    @property
    def in_padded(self) -> tuple[int, int, int]:
        s = list(self.shape)
        s[self.in_axis] = self.in_padded_extent
        return tuple(s)

    @property
    def out_padded(self) -> tuple[int, int, int]:
        s = list(self.shape)
        s[self.out_axis] = self.out_padded_extent
        return tuple(s)


def build_slab_general(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    in_axis: int,
    out_axis: int,
    axis_name: str | tuple = "slab",
    executor: str | Callable = "xla",
    forward: bool = True,
    donate: bool = False,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
    midpoint: Callable | None = None,
) -> tuple[Callable, SlabSpec]:
    """Build the jitted end-to-end slab transform for ANY ordered axis pair.

    Input is the global ``[N0, N1, N2]`` array sharded along ``in_axis``;
    the two other axes are transformed locally, one exchange reshards
    ``in_axis <-> out_axis``, and ``in_axis`` is transformed last — so the
    chain works started from any slab layout (reshape minimization,
    ``heffte_plan_logic.cpp:265-408``). The canonical forward plan is
    ``(in_axis, out_axis) = (0, 1)`` (the reference engine's only mode,
    ``fft_mpi_3d_api.cpp:181-214``), backward is (1, 0).

    ``overlap_chunks > 1`` pipelines t2 under t3 along the bystander axis;
    1 is today's monolithic chain. ``batch=B`` prepends a leading batch
    axis: the input is ``[B, N0, N1, N2]`` carrying B independent
    transforms, t0/t3 run as batched FFTs, and the t2 global transpose is
    ONE shared collective per (chunk, exchange) with the batch riding as a
    bystander dim. ``None`` is the unbatched 3D chain, today's HLO exactly.

    ``midpoint`` is the spectral-operator fusion hook (the
    stop-at-transposed / start-from-transposed mode): a wavenumber-
    indexed pointwise multiplier generator applied at the chain's
    transposed full-spectrum midpoint, after which the chain continues
    with the INVERSE legs back to the input layout
    (:func:`build_slab_spectral_op`; canonical forward orientation only).

    The chain is emitted as a stage graph — t0 | t1 pack | t2 exchange
    with the t3 lines as its fused per-chunk compute — and compiled by
    :func:`..stagegraph.compile_fused`.
    """
    if midpoint is not None:
        if not forward or (in_axis, out_axis) != (0, 1):
            raise ValueError(
                "the midpoint (spectral-operator) hook runs the canonical "
                "forward chain: forward=True, (in_axis, out_axis)=(0, 1)")
        return build_slab_spectral_op(
            mesh, shape, midpoint, axis_name=axis_name, executor=executor,
            donate=donate, algorithm=algorithm,
            overlap_chunks=overlap_chunks, batch=batch,
            wire_dtype=wire_dtype)
    if in_axis == out_axis or not (0 <= in_axis < 3 and 0 <= out_axis < 3):
        raise ValueError(f"need distinct 3D axes, got {in_axis}, {out_axis}")
    check_batch(batch)
    p, axis_sizes = _axis_parts(mesh, axis_name)
    spec = SlabSpec(tuple(int(s) for s in shape), p, axis_name,
                    in_axis, out_axis)
    n_in, n_out = spec.shape[in_axis], spec.shape[out_axis]
    n_inp, n_outp = spec.in_padded_extent, spec.out_padded_extent
    local_axes = tuple(a for a in range(3) if a != in_axis)
    platform = mesh.devices.flat[0].platform
    # Leading-batch offset: spatial axis a of the 3D chain is array axis
    # a + bo. Stage names, SlabSpec, and all geometry stay spatial.
    bo = 0 if batch is None else 1
    ax_in, ax_out = in_axis + bo, out_axis + bo
    chunk_axis = 3 - in_axis - out_axis + bo  # spatial bystander

    # Stage nodes of the reference taxonomy (fft_mpi_3d_api.cpp:184-201).
    nodes = (
        local_node("t0", f"t0_fft_{''.join(_L[a] for a in local_axes)}",
                   ("fft", tuple(a + bo for a in local_axes), forward)),
        local_node("t1", "t1_pack", ("pack", ax_out, n_outp)),
        exchange_node("t2", f"t2_exchange_{_axis_label(axis_name)}",
                      mesh_axis=axis_name, parts=p, split=ax_out,
                      concat=ax_in, chunk_axis=chunk_axis,
                      axis_sizes=axis_sizes),
        local_node("t3", f"t3_fft_{_L[in_axis]}",
                   ("crop", ax_in, n_in), ("fft", (ax_in,), forward),
                   fuse=True),
    )
    graph = StageGraph(
        mesh=mesh, nodes=nodes,
        in_pspec=batch_pspec(spec.in_pspec, batch),
        out_pspec=batch_pspec(spec.out_pspec, batch),
        pre=(("pad", ax_in, n_inp),), post=(("crop", ax_out, n_out),),
        even=n_inp == n_in and n_outp == n_out, donate=donate,
        algorithm=algorithm, platform=platform, wire_dtype=wire_dtype,
        overlap_chunks=overlap_chunks, executor=executor,
        meta=dict(shape=spec.shape, batch=batch, forward=forward,
                  decomposition="slab", kind="c2c"),
    )
    return compile_fused(graph), spec


def combined_axis_index(mesh: Mesh, axis_name):
    """Device index along a slab chain's mesh-axis spec, inside
    ``shard_map``: ``lax.axis_index`` of a plain axis, or the row-major
    linearization of a hierarchical plan's (dcn, ici) tuple — the same
    device order as ``P((dcn, ici))``'s combined sharding, so per-shard
    wavenumber offsets agree with what XLA placed on each device."""
    if isinstance(axis_name, (tuple, list)):
        idx = lax.axis_index(axis_name[0])
        for a in axis_name[1:]:
            idx = idx * mesh.shape[a] + lax.axis_index(a)
        return idx
    return lax.axis_index(axis_name)


def build_slab_spectral_op(
    mesh: Mesh,
    shape: tuple[int, int, int],
    multiplier: Callable,
    *,
    axis_name: str | tuple = "slab",
    executor: str | Callable = "xla",
    donate: bool = False,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[Callable, SlabSpec]:
    """Fused slab FFT -> pointwise -> iFFT pipeline in ONE jitted program.

    The spectral-operator chain (AccFFT's operator tier, arXiv
    1506.07933): the forward half runs ``stop_at_transposed`` — t0
    (local YZ FFTs), t1 pack, t2 exchange, then the final X FFT *in the
    transposed (Y-slab) layout* — the pointwise multiplier is applied
    right there (the ``t_mid`` stage), and the inverse half runs
    ``start_from_transposed``: inverse X FFT, the return exchange, and
    the inverse YZ FFTs back to the input's X-slab layout. Because the
    multiplier is diagonal (pointwise) in the transposed layout, the
    natural-order restore transpose a back-to-back forward+inverse pair
    would pay on each side of the multiply cancels — the fused chain
    compiles exactly TWO all-to-alls where the unfused natural-layout
    pair compiles four (the classic pruned-spectral-solver trick;
    pinned in ``tests/test_a2h_operators.py``).

    ``multiplier(i0, i1, i2)`` receives broadcastable int32 GLOBAL index
    grids of the three spatial axes (already offset for this shard and
    overlap chunk — the transposed midpoint layout) and returns the
    pointwise factor (real or complex, broadcastable to the grids'
    shape). Index rows landing in ceil-pad territory are cropped before
    any inverse transform, so their values only need to be finite.

    Composes with every chain axis: ``overlap_chunks`` pipelines BOTH
    exchanges (the multiplier is generated per chunk via the midpoint
    bounds hook), ``batch=B`` rides the collectives as a bystander dim
    (the multiplier broadcasts over it), ``wire_dtype`` compresses each
    exchange's wire with the multiplier applying on the DECODED payload,
    and ``algorithm="hierarchical"`` runs each exchange as the two-leg
    ICI/DCN transport over a hybrid-mesh ``axis_name`` tuple.

    I/O is the canonical X-slab layout on both sides (in == out
    sharding); forward transform unnormalized, inverse scaled 1/N —
    i.e. a unit multiplier is the identity.

    The midpoint rides the graph as a *factory* node: the per-shard
    wavenumber offset (``combined_axis_index``) is emitted at trace
    time right before the outbound exchange issues — the position the
    hand-threaded chain emitted it, part of the HLO byte-parity pin.
    """
    check_batch(batch)
    p, axis_sizes = _axis_parts(mesh, axis_name)
    spec = SlabSpec(tuple(int(s) for s in shape), p, axis_name, 0, 1)
    ex = get_executor(executor) if isinstance(executor, str) else executor
    n0, n1, n2 = spec.shape
    n0p, n1p = spec.n0p, spec.n1p
    platform = mesh.devices.flat[0].platform
    bo = 0 if batch is None else 1
    c1 = n1p // p  # transposed-midpoint local extent of the k1 axis
    t2_name = f"t2_exchange_{_axis_label(axis_name)}"

    def mid_factory():
        # The transposed-space midpoint: final forward FFT, the
        # wavenumber-diagonal multiply, and the first inverse FFT — all
        # local in the Y-slab layout (k0 full, k1 this shard's slice,
        # k2 the overlap chunk's slice).
        k1_lo = combined_axis_index(mesh, axis_name) * c1

        def mid_chunk(u, lo, hi):
            u = _crop_axis(u, bo, n0)
            u = ex(u, (bo,), True)                       # t3 of fwd half
            u = apply_midpoint(u, multiplier, (
                jnp.arange(n0, dtype=jnp.int32)[:, None, None],
                (k1_lo + jnp.arange(c1, dtype=jnp.int32))[None, :, None],
                jnp.arange(lo, hi, dtype=jnp.int32)[None, None, :]))
            return ex(u, (bo,), False)                   # inverse X lines

        return mid_chunk

    nodes = (
        local_node("t0", "t0_fft_yz", ("fft", (1 + bo, 2 + bo), True)),
        local_node("t1", "t1_pack", ("pack", 1 + bo, n1p)),
        exchange_node("t2", t2_name, mesh_axis=axis_name, parts=p,
                      split=1 + bo, concat=bo, chunk_axis=2 + bo,
                      axis_sizes=axis_sizes),
        local_node("t_mid", "t_mid", fuse=True, takes_bounds=True,
                   factory=mid_factory),
        local_node("t1", "t1_pack", ("pack", bo, n0p)),
        exchange_node("t2", t2_name, mesh_axis=axis_name, parts=p,
                      split=bo, concat=1 + bo, chunk_axis=2 + bo,
                      axis_sizes=axis_sizes),
        local_node("t3", "t3_ifft_y",
                   ("crop", 1 + bo, n1), ("fft", (1 + bo,), False),
                   fuse=True),
        # The inverse Z pass transforms the bystander (chunk) axis, so
        # it runs monolithically after the chunked exchange/ifft-Y merge
        # — the same discipline as the c2r chains' final real transform.
        local_node("t3", "t3_ifft_z", ("fft", (2 + bo,), False)),
    )
    io_spec = batch_pspec(spec.in_pspec, batch)
    graph = StageGraph(
        mesh=mesh, nodes=nodes, in_pspec=io_spec, out_pspec=io_spec,
        pre=(("pad", bo, n0p),), post=(("crop", bo, n0),),
        # Only axis 0 is sharded at the jit boundary (in == out layout),
        # so the sharding pin needs only the in-axis to divide.
        even=n0p == n0, donate=donate, algorithm=algorithm,
        platform=platform, wire_dtype=wire_dtype,
        overlap_chunks=overlap_chunks, executor=executor,
        meta=dict(shape=spec.shape, batch=batch, forward=True,
                  decomposition="slab", kind="op"),
    )
    return compile_fused(graph), spec


def build_slab_fft3d(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    axis_name: str | tuple = "slab",
    executor: str | Callable = "xla",
    forward: bool = True,
    donate: bool = False,
    algorithm: str = "alltoall",
    in_axis: int | None = None,
    out_axis: int | None = None,
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[Callable, SlabSpec]:
    """Canonical-orientation wrapper over :func:`build_slab_general`:
    X-slabs -> Y-slabs forward, Y-slabs -> X-slabs backward (the reference
    pipeline, ``fft_mpi_3d_api.cpp:181-214``), unless the planner supplies a
    different axis pair.
    """
    d_in, d_out = (0, 1) if forward else (1, 0)
    return build_slab_general(
        mesh, shape,
        in_axis=d_in if in_axis is None else in_axis,
        out_axis=d_out if out_axis is None else out_axis,
        axis_name=axis_name, executor=executor, forward=forward,
        donate=donate, algorithm=algorithm, overlap_chunks=overlap_chunks,
        batch=batch, wire_dtype=wire_dtype,
    )


def build_slab_rfft3d(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    axis_name: str = "slab",
    executor: str = "xla",
    forward: bool = True,
    donate: bool = False,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[Callable, SlabSpec]:
    """Slab-decomposed real-to-complex (forward) / complex-to-real (backward)
    3D transform — the distributed analog of heFFTe's ``fft3d_r2c``
    (``heffte_fft3d_r2c.h``, ``src/heffte_fft3d.cpp:202-304``).

    The real axis is axis 2 (Z), which is always device-local in the slab
    decomposition, so the r2c shrink to ``n2//2+1`` (``box3d::r2c``,
    ``heffte_geometry.h:94``) happens before any exchange. Forward maps real
    X-slabs ``[N0, N1, N2]`` to complex Y-slabs ``[N0, N1, N2//2+1]``;
    backward is the exact inverse (output real, numpy 1/N scaling).
    ``batch=B`` prepends a leading batch axis with one shared exchange per
    batch, exactly like :func:`build_slab_general`.
    """
    if not isinstance(executor, str):
        raise TypeError("r2c builders take a registered executor name")
    check_batch(batch)
    p = mesh.shape[axis_name]
    # Direction-true spec (like build_slab_general): forward maps X-slabs to
    # Y-slabs, backward the mirror — so plan-level shardings read straight
    # off the spec.
    spec = SlabSpec(
        tuple(int(s) for s in shape), p, axis_name,
        in_axis=0 if forward else 1, out_axis=1 if forward else 0,
    )
    n0, n1, n2 = spec.shape
    n0p, n1p = spec.n0p, spec.n1p
    bo = 0 if batch is None else 1  # leading-batch axis offset
    t2_name = f"t2_exchange_{axis_name}"

    if forward:
        nodes = (
            local_node("t0", "t0_r2c_zy",
                       ("r2c", 2 + bo),          # t0a: real Z lines
                       ("fft", (1 + bo,), True)),  # t0b: Y lines
            local_node("t1", "t1_pack", ("pack", 1 + bo, n1p)),
            exchange_node("t2", t2_name, mesh_axis=axis_name, parts=p,
                          split=1 + bo, concat=bo, chunk_axis=2 + bo),
            local_node("t3", "t3_fft_x",
                       ("crop", bo, n0), ("fft", (bo,), True), fuse=True),
        )
        pre = (("pad", bo, n0p),)
        post = (("crop", 1 + bo, n1),)
    else:
        nodes = (
            local_node("t3", "t3_ifft_x", ("fft", (bo,), False)),
            local_node("t1", "t1_pack", ("pack", bo, n0p)),
            exchange_node("t2", t2_name, mesh_axis=axis_name, parts=p,
                          split=bo, concat=1 + bo, chunk_axis=2 + bo),
            local_node("t0", "t0_ifft_y",
                       ("crop", 1 + bo, n1), ("fft", (1 + bo,), False),
                       fuse=True),
            # The c2r (real Z lines) transforms the bystander axis, so
            # it runs monolithically after the chunked merge.
            local_node("t0", "t0_c2r_z", ("c2r", n2, 2 + bo)),
        )
        pre = (("pad", 1 + bo, n1p),)
        post = (("crop", bo, n0),)

    graph = StageGraph(
        mesh=mesh, nodes=nodes,
        in_pspec=batch_pspec(spec.in_pspec, batch),
        out_pspec=batch_pspec(spec.out_pspec, batch),
        pre=pre, post=post,
        even=n0p == n0 and n1p == n1, donate=donate,
        algorithm=algorithm, wire_dtype=wire_dtype,
        overlap_chunks=overlap_chunks, executor=executor,
        meta=dict(shape=spec.shape, batch=batch, forward=forward,
                  decomposition="slab", kind="r2c"),
    )
    return compile_fused(graph), spec


def build_slab_stages(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    axis_name: str | tuple = "slab",
    executor: str | Callable = "xla",
    forward: bool = True,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[list[tuple[str, Callable]], SlabSpec]:
    """The same transform split into separately-jitted t0..t3 stages for the
    per-stage timing breakdown the reference prints on every execute
    (``fft_mpi_3d_api.cpp:184-201``). Fusing everything under one jit hides
    the ICI cost (SURVEY.md §7 "hard parts"), so benchmarking keeps this
    staged mode alongside the fused one. ``overlap_chunks > 1`` keeps the
    overlapped chains' K-collective transport shape inside the t2 stage.
    ``batch=B`` runs the stages over ``[B, ...]`` arrays with one shared
    exchange per chunk.

    ``algorithm="hierarchical"`` (hybrid mesh; ``axis_name`` a (dcn, ici)
    tuple) splits the t2 stage into its two axis-local legs — separately
    jitted ``t2a``/``t2b`` stages, so the per-stage harness times each
    fabric's leg on its own. overlap_chunks > 1 keeps ONE t2 stage (a
    per-chunk leg boundary would multiply stage dispatches), but inside
    it the K chunks run the leg-level pipeline — chunk i's ICI leg
    issued before chunk i-1's DCN leg, with per-chunk ``t2a[k]`` /
    ``t2b[k]`` spans. ``wire_dtype`` compresses each exchange stage's
    wire exactly like the fused chain (the t2 stage boundary still
    carries the decoded complex array, so stage I/O shapes are
    unchanged).

    Emitted as a :class:`..stagegraph.StagedGraph` and compiled by
    :func:`..stagegraph.compile_staged`.
    """
    check_batch(batch)
    p, axis_sizes = _axis_parts(mesh, axis_name)
    spec = SlabSpec(tuple(int(s) for s in shape), p, axis_name)
    n0, n1, _ = spec.shape
    n0p, n1p = spec.n0p, spec.n1p
    bo = 0 if batch is None else 1  # leading-batch axis offset

    xs = batch_pspec(P(axis_name, None, None), batch)
    ys = batch_pspec(P(None, axis_name, None), batch)

    def t2_stages(split, concat, ins, outs):
        """The t2 tier: one chunked exchange stage, or the hierarchical
        transport's two per-leg stages (K=1 only — see docstring)."""
        if algorithm == "hierarchical" and overlap_chunks <= 1:
            dcn_name, ici_name = axis_name
            leg = dict(mesh_axis=axis_name, split=split, concat=concat,
                       axis_sizes=axis_sizes, parts=p)
            jn = "run" if wire_dtype is not None else None
            return [
                StagedStage(
                    kind="t2a",
                    name=f"t2a_exchange_{_axis_label(ici_name)}",
                    jit_name=jn or "leg_ici", smap_in=ins, smap_out=ins,
                    leg=dict(leg, which="ici", tile_axis_out=split),
                    pin_in=ins, pin_out=ins),
                StagedStage(
                    kind="t2b",
                    name=f"t2b_exchange_{_axis_label(dcn_name)}",
                    jit_name=jn or "leg_dcn", smap_in=ins, smap_out=outs,
                    leg=dict(leg, which="dcn", tile_axis_out=concat),
                    pin_in=ins, pin_out=outs),
            ]
        return [
            StagedStage(
                kind="t2", name="t2_all_to_all", smap_in=ins,
                smap_out=outs,
                exchange=dict(mesh_axis=axis_name, parts=p, split=split,
                              concat=concat, chunk_axis=2 + bo,
                              axis_sizes=axis_sizes),
                pin_in=ins, pin_out=outs),
        ]

    if forward:
        stages = [
            StagedStage(
                kind="t0", name="t0_fft_yz", smap_in=xs, smap_out=xs,
                local=(("fft", (1 + bo, 2 + bo), True),),
                pre=(("pad", bo, n0p),), post=(("pad", 1 + bo, n1p),),
                pin_in=xs, pin_out=xs),
            *t2_stages(1 + bo, bo, xs, ys),
            StagedStage(
                kind="t3", name="t3_fft_x", smap_in=ys, smap_out=ys,
                local=(("crop", bo, n0), ("fft", (bo,), True)),
                post=(("crop", 1 + bo, n1),), pin_in=ys, pin_out=ys),
        ]
    else:
        stages = [
            StagedStage(
                kind="t3", name="t3_ifft_x", smap_in=ys, smap_out=ys,
                local=(("fft", (bo,), False),),
                pre=(("pad", 1 + bo, n1p),), post=(("pad", bo, n0p),),
                pin_in=ys, pin_out=ys),
            *t2_stages(bo, 1 + bo, ys, xs),
            StagedStage(
                kind="t0", name="t0_ifft_yz", smap_in=xs, smap_out=xs,
                local=(("crop", 1 + bo, n1),
                       ("fft", (1 + bo, 2 + bo), False)),
                post=(("crop", bo, n0),), pin_in=xs, pin_out=xs),
        ]
    graph = StagedGraph(
        mesh=mesh, stages=tuple(stages), algorithm=algorithm,
        wire_dtype=wire_dtype, overlap_chunks=overlap_chunks,
        executor=executor,
        meta=dict(shape=spec.shape, batch=batch, forward=forward,
                  decomposition="slab", kind="c2c"),
    )
    return compile_staged(graph), spec
