"""Separately-jitted stage pipelines for per-stage timing — all plan kinds.

The reference prints a per-stage wall-time breakdown on every distributed
execute (t0 fftZY / t1 transpose / t2 all-to-all / t3 fftX,
``fft_mpi_3d_api.cpp:184-201``, ``README.md:44-58``) for every benchmarkable
config. Fusing the whole transform under one jit hides the ICI cost
(SURVEY.md §7), so benchmarking keeps a staged mode: each stage is its own
jit, synchronized and timed by :func:`..utils.timing.time_staged`.

:mod:`.slab` provides ``build_slab_stages`` for the slab c2c plan; this
module adds the pencil c2c pipeline (two exchanges -> t2a/t2b lines) and the
r2c/c2r pipelines for both decompositions. Stage boundaries carry
ceil-padded global arrays; shardings are established with
``with_sharding_constraint`` inside each stage (not pinned on the jits), so
uneven extents — e.g. the r2c half-spectrum n2//2+1, which almost never
divides the mesh — work in staged mode too.

**Stage-graph IR**: every staged builder here emits a
:class:`..stagegraph.StagedGraph` — per-stage nodes carrying their
boundary layouts, pads/crops, and exchange transport — compiled by
:func:`..stagegraph.compile_staged` into the ``[(name, jit), ...]``
pipeline, byte-identical to the pre-migration hand-threaded stages
(pinned in ``tests/test_a2m_stagegraph.py``). The pipelines stay
tree-generic over the stage value (the dd tier's (hi, lo) pair rides
:func:`build_pencil_stages` unchanged through
``ddslab.build_dd_pencil_stages``).
"""

from __future__ import annotations

from typing import Callable

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..geometry import pad_to
from ..ops.executors import get_executor, thunk_guard_substitute
from ..stagegraph import StagedGraph, StagedStage, compile_staged
from ..utils.trace import trace_stages
from .pencil import PencilSpec
from .slab import SlabSpec, _crop_axis, _pad_axis, batch_pspec, check_batch

__all__ = [
    "build_pencil_stages",
    "build_slab_rfft_stages",
    "build_pencil_rfft_stages",
    "build_single_stages",
    "build_slab_op_stages",
]


def build_single_stages(
    shape: tuple[int, int, int],
    *,
    executor: str | Callable = "xla",
    forward: bool = True,
    batch: int | None = None,
) -> list:
    """Single-device staged pipeline: t0 (YZ planes) and t3 (X lines) as
    separate jits — the per-stage breakdown the reference prints even on
    one rank (``fft_mpi_3d_api.cpp:184-201``; t1/t2 are identically zero
    without a transpose/exchange). With the pallas executor, t0 is the
    fused 2D plane kernel and t3 the strided axis-0 kernel. ``batch=B``
    runs the stages over ``[B, ...]`` arrays. (No mesh, no exchange —
    the one staged pipeline below the stage-graph IR's mesh tier.)"""
    check_batch(batch)
    bo = 0 if batch is None else 1
    ex = get_executor(executor) if isinstance(executor, str) else executor
    return trace_stages([
        ("t0_fft_yz", jax.jit(lambda x: ex(x, (1 + bo, 2 + bo), forward))),
        ("t3_fft_x", jax.jit(lambda y: ex(y, (bo,), forward))),
    ])

_AXIS_LETTER = "xyz"


def _pspec(mapping: dict[int, str]) -> P:
    return P(*[mapping.get(d) for d in range(3)])


def build_pencil_stages(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    row_axis: str = "row",
    col_axis: str = "col",
    executor: str | Callable = "xla",
    forward: bool = True,
    algorithm: str = "alltoall",
    perm: tuple[int, int, int] | None = None,
    order: str | None = None,
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[list[tuple[str, Callable]], PencilSpec]:
    """Pencil c2c transform as five timed stages:
    t0 (first fft) | t2a (first exchange) | t1 (mid fft) | t2b (second
    exchange) | t3 (last fft) — the reference's taxonomy with the two
    pencil exchanges split out as t2a/t2b. ``overlap_chunks > 1`` keeps
    the overlapped chains' K-collective transport shape inside each
    exchange stage. ``batch=B`` runs the stages over ``[B, ...]`` arrays
    with one shared exchange per chunk.

    Generic over the stage value: ``executor`` may be a callable taking
    any pytree of same-shape arrays (the dd tier passes a (hi, lo) pair
    through ``ddslab.build_dd_pencil_stages``); pads/crops/exchanges map
    over leaves and specs broadcast as pytree prefixes."""
    if perm is None:
        perm = (0, 1, 2) if forward else (1, 2, 0)
    if order is None:
        order = "col_first" if forward else "row_first"
    check_batch(batch)
    bo = 0 if batch is None else 1  # leading-batch axis offset
    rows, cols = mesh.shape[row_axis], mesh.shape[col_axis]
    spec = PencilSpec(tuple(int(s) for s in shape), rows, cols,
                      row_axis, col_axis, tuple(perm), order)
    n = spec.shape
    a, b, c = perm
    if order == "col_first":
        seq = [(col_axis, cols, c, b), (row_axis, rows, b, a)]
        mid_fft, last_fft = b, a
    else:
        seq = [(row_axis, rows, c, a), (col_axis, cols, a, b)]
        mid_fft, last_fft = a, b
    # fft-thunk guard (DFFT_THUNK_GUARD): the staged view of an uneven
    # inverse pencil chain is in the known XLA:CPU poisoned class exactly
    # like the fused chain — substitute before any stage traces (the
    # planner applies the same shared predicate).
    executor = thunk_guard_substitute(
        executor, decomposition="pencil", forward=forward,
        uneven=bool(n[a] % rows or n[b] % cols
                    or n[seq[0][2]] % seq[0][1]
                    or n[seq[1][2]] % seq[1][1]))

    in_lay = {a: row_axis, b: col_axis}
    mid_lay = ({a: row_axis, c: col_axis} if order == "col_first"
               else {c: row_axis, b: col_axis})
    op = spec.out_placement
    out_lay = {op[0]: row_axis, op[1]: col_axis}

    bspec = lambda lay: batch_pspec(_pspec(lay), batch)
    ins, mid, outs = bspec(in_lay), bspec(mid_lay), bspec(out_lay)
    pads = {a: pad_to(n[a], rows), b: pad_to(n[b], cols)}
    # each exchange's split axis is padded to its part count before it runs
    pads[seq[0][2]] = pad_to(n[seq[0][2]], seq[0][1])
    mid_pad = pad_to(n[seq[1][2]], seq[1][1])

    L = _AXIS_LETTER
    concat0, concat1 = seq[0][3], seq[1][3]
    stages = (
        StagedStage(
            kind="t0", name=f"t0_fft_{L[c]}", jit_name="t0",
            smap_in=ins, smap_out=ins,
            local=(("fft", (c + bo,), forward),),
            pre=(("pad", a + bo, pads[a]), ("pad", b + bo, pads[b])),
            post=(("pad", seq[0][2] + bo, pads[seq[0][2]]),),
            wsc_in=ins, wsc_out=ins),
        StagedStage(
            kind="t2a", name=f"t2a_exchange_{seq[0][0]}", jit_name="t2a",
            smap_in=ins, smap_out=mid,
            exchange=dict(mesh_axis=seq[0][0], parts=seq[0][1],
                          split=seq[0][2] + bo, concat=seq[0][3] + bo,
                          chunk_axis=3 - seq[0][2] - seq[0][3] + bo,
                          exchange_name=f"t2a_exchange_{seq[0][0]}"),
            wsc_in=ins, wsc_out=mid),
        StagedStage(
            kind="t1", name=f"t1_fft_{L[mid_fft]}", jit_name="t1",
            smap_in=mid, smap_out=mid,
            local=(("crop", concat0 + bo, n[concat0]),
                   ("fft", (mid_fft + bo,), forward),
                   ("pad", seq[1][2] + bo, mid_pad)),
            wsc_in=mid, wsc_out=mid),
        StagedStage(
            kind="t2b", name=f"t2b_exchange_{seq[1][0]}", jit_name="t2b",
            smap_in=mid, smap_out=outs,
            exchange=dict(mesh_axis=seq[1][0], parts=seq[1][1],
                          split=seq[1][2] + bo, concat=seq[1][3] + bo,
                          chunk_axis=3 - seq[1][2] - seq[1][3] + bo,
                          exchange_name=f"t2b_exchange_{seq[1][0]}"),
            wsc_in=mid, wsc_out=outs),
        StagedStage(
            kind="t3", name=f"t3_fft_{L[last_fft]}", jit_name="t3",
            smap_in=outs, smap_out=outs,
            local=(("crop", concat1 + bo, n[concat1]),
                   ("fft", (last_fft + bo,), forward)),
            post=tuple(("crop", ax + bo, n[ax]) for ax in op),
            wsc_in=outs),
    )
    graph = StagedGraph(
        mesh=mesh, stages=stages, algorithm=algorithm,
        wire_dtype=wire_dtype, overlap_chunks=overlap_chunks,
        executor=executor,
        meta=dict(shape=spec.shape, batch=batch, forward=forward,
                  decomposition="pencil", kind="c2c"),
    )
    return compile_staged(graph), spec


def build_slab_op_stages(
    mesh: Mesh,
    shape: tuple[int, int, int],
    multiplier,
    *,
    axis_name: str = "slab",
    executor: str | Callable = "xla",
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[list[tuple[str, Callable]], SlabSpec]:
    """The fused slab spectral-operator chain
    (:func:`..slab.build_slab_spectral_op`) as five separately-jitted,
    timed stages — the ``stop_at_transposed``/``start_from_transposed``
    mode at the staged tier, so the explain layer can measure the
    ``t_mid`` pointwise stage next to t0/t2/t3:

    t0 (forward YZ FFTs) | t2 (outbound exchange) | **t_mid** (final
    forward X FFT + wavenumber-diagonal multiply + first inverse X FFT,
    all in the transposed Y-slab layout) | t2 (return exchange) | t3
    (inverse YZ FFTs back to X-slabs).

    ``multiplier(i0, i1, i2)`` follows the fused builder's contract
    (int32 global index grids, per-shard offsets applied here).
    ``overlap_chunks > 1`` keeps the K-collective transport shape
    inside each exchange stage; flat transports and a plain 1D mesh
    axis only (the hierarchical two-leg chain measures fused)."""
    import jax.numpy as jnp

    from .slab import apply_multiplier

    check_batch(batch)
    bo = 0 if batch is None else 1
    p = mesh.shape[axis_name]
    spec = SlabSpec(tuple(int(s) for s in shape), p, axis_name, 0, 1)
    ex = get_executor(executor) if isinstance(executor, str) else executor
    n0, n1, n2 = spec.shape
    n0p, n1p = spec.n0p, spec.n1p
    c1 = n1p // p  # transposed-midpoint local extent of the k1 axis
    xs = batch_pspec(P(axis_name, None, None), batch)
    ys = batch_pspec(P(None, axis_name, None), batch)

    def mid_local(u):
        u = _crop_axis(u, bo, n0)
        u = ex(u, (bo,), True)                   # final forward X
        k1_lo = lax.axis_index(axis_name) * c1
        m = multiplier(
            jnp.arange(n0, dtype=jnp.int32)[:, None, None],
            (k1_lo + jnp.arange(c1, dtype=jnp.int32))[None, :, None],
            jnp.arange(n2, dtype=jnp.int32)[None, None, :])
        u = apply_multiplier(u, m)
        return _pad_axis(ex(u, (bo,), False), bo, n0p)  # inverse X

    exch = dict(mesh_axis=axis_name, parts=p, chunk_axis=2 + bo)
    stages = (
        # Both exchange stages normalize to the t2 key (stage_key), so
        # the explain join sums them per pass; the distinct names keep
        # the driver-tier breakdown showing each leg on its own row.
        StagedStage(
            kind="t0", name="t0_fft_yz", jit_name="t0",
            smap_in=xs, smap_out=xs,
            local=(("fft", (1 + bo, 2 + bo), True), ("pad", 1 + bo, n1p)),
            pre=(("pad", bo, n0p),), wsc_in=xs, wsc_out=xs),
        StagedStage(
            kind="t2", name="t2_exchange_out", jit_name="t2_out",
            smap_in=xs, smap_out=ys,
            exchange=dict(exch, split=1 + bo, concat=bo),
            wsc_in=xs, wsc_out=ys),
        StagedStage(
            kind="t_mid", name="t_mid", jit_name="t_mid",
            smap_in=ys, smap_out=ys,
            local=(("call", mid_local),), wsc_in=ys, wsc_out=ys),
        StagedStage(
            kind="t2", name="t2_exchange_back", jit_name="t2_back",
            smap_in=ys, smap_out=xs,
            exchange=dict(exch, split=bo, concat=1 + bo),
            wsc_in=ys, wsc_out=xs),
        StagedStage(
            kind="t3", name="t3_ifft_yz", jit_name="t3",
            smap_in=xs, smap_out=xs,
            local=(("crop", 1 + bo, n1), ("fft", (1 + bo, 2 + bo), False)),
            post=(("crop", bo, n0),), wsc_in=xs),
    )
    graph = StagedGraph(
        mesh=mesh, stages=stages, algorithm=algorithm,
        wire_dtype=wire_dtype, overlap_chunks=overlap_chunks,
        executor=executor,
        meta=dict(shape=spec.shape, batch=batch, forward=True,
                  decomposition="slab", kind="op"),
    )
    return compile_staged(graph), spec


def build_slab_rfft_stages(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    axis_name: str = "slab",
    executor: str = "xla",
    forward: bool = True,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[list[tuple[str, Callable]], SlabSpec]:
    """Slab r2c (forward) / c2r (backward) as three timed stages — the
    per-stage breakdown for every benchmarkable r2c config
    (``fft_mpi_3d_api.cpp:184-201`` prints it for every run)."""
    check_batch(batch)
    bo = 0 if batch is None else 1
    p = mesh.shape[axis_name]
    spec = SlabSpec(tuple(int(s) for s in shape), p, axis_name,
                    in_axis=0 if forward else 1, out_axis=1 if forward else 0)
    n0, n1, n2 = spec.shape
    n0p, n1p = spec.n0p, spec.n1p
    xs = batch_pspec(P(axis_name, None, None), batch)
    ys = batch_pspec(P(None, axis_name, None), batch)
    exch = dict(mesh_axis=axis_name, parts=p, chunk_axis=2 + bo)

    if forward:
        stages = (
            StagedStage(
                kind="t0", name="t0_r2c_zy", jit_name="t0",
                smap_in=xs, smap_out=xs,
                local=(("r2c", 2 + bo), ("fft", (1 + bo,), True),
                       ("pad", 1 + bo, n1p)),
                pre=(("pad", bo, n0p),), wsc_in=xs, wsc_out=xs),
            StagedStage(
                kind="t2", name="t2_exchange", jit_name="t2",
                smap_in=xs, smap_out=ys,
                exchange=dict(exch, split=1 + bo, concat=bo),
                wsc_in=xs, wsc_out=ys),
            StagedStage(
                kind="t3", name="t3_fft_x", jit_name="t3",
                smap_in=ys, smap_out=ys,
                local=(("crop", bo, n0), ("fft", (bo,), True)),
                post=(("crop", 1 + bo, n1),), wsc_in=ys),
        )
    else:
        stages = (
            StagedStage(
                kind="t3", name="t3_ifft_x", jit_name="t3i",
                smap_in=ys, smap_out=ys,
                local=(("fft", (bo,), False), ("pad", bo, n0p)),
                pre=(("pad", 1 + bo, n1p),), wsc_in=ys, wsc_out=ys),
            StagedStage(
                kind="t2", name="t2_exchange", jit_name="t2",
                smap_in=ys, smap_out=xs,
                exchange=dict(exch, split=bo, concat=1 + bo),
                wsc_in=ys, wsc_out=xs),
            StagedStage(
                kind="t0", name="t0_ifft_y_c2r", jit_name="t0i",
                smap_in=xs, smap_out=xs,
                local=(("crop", 1 + bo, n1), ("fft", (1 + bo,), False),
                       ("c2r", n2, 2 + bo)),
                post=(("crop", bo, n0),), wsc_in=xs),
        )
    graph = StagedGraph(
        mesh=mesh, stages=stages, algorithm=algorithm,
        wire_dtype=wire_dtype, overlap_chunks=overlap_chunks,
        executor=executor,
        meta=dict(shape=spec.shape, batch=batch, forward=forward,
                  decomposition="slab", kind="r2c"),
    )
    return compile_staged(graph), spec


def build_pencil_rfft_stages(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    row_axis: str = "row",
    col_axis: str = "col",
    executor: str = "xla",
    forward: bool = True,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[list[tuple[str, Callable]], PencilSpec]:
    """Pencil r2c/c2r as five timed stages with t2a/t2b exchange lines.
    Canonical chains only (the real axis must be device-local axis 2 on the
    real side), matching :func:`.pencil.build_pencil_rfft3d`."""
    check_batch(batch)
    bo = 0 if batch is None else 1
    rows, cols = mesh.shape[row_axis], mesh.shape[col_axis]
    spec = PencilSpec(
        tuple(int(s) for s in shape), rows, cols, row_axis, col_axis,
        perm=(0, 1, 2) if forward else (1, 2, 0),
        order="col_first" if forward else "row_first",
    )
    # fft-thunk guard: the staged uneven c2r pencil pipeline is in the
    # known XLA:CPU poisoned class (see build_pencil_stages).
    executor = thunk_guard_substitute(
        executor, decomposition="pencil", forward=forward,
        uneven=bool(spec.shape[0] % rows or spec.shape[1] % cols
                    or spec.shape[1] % rows
                    or (spec.shape[2] // 2 + 1) % cols))
    n0, n1, n2 = spec.shape
    n0p, n1pc, n1pr = spec.n0p, spec.n1p_col, spec.n1p_row
    n2h = n2 // 2 + 1
    n2hp = pad_to(n2h, cols)
    zs, ysp, xs = (batch_pspec(P(row_axis, col_axis, None), batch),
                   batch_pspec(P(row_axis, None, col_axis), batch),
                   batch_pspec(P(None, row_axis, col_axis), batch))
    exch_a = dict(mesh_axis=col_axis, parts=cols, chunk_axis=bo)
    exch_b = dict(mesh_axis=row_axis, parts=rows, chunk_axis=2 + bo)

    if forward:
        stages = (
            StagedStage(
                kind="t0", name="t0_r2c_z", jit_name="t0",
                smap_in=zs, smap_out=zs,
                local=(("r2c", 2 + bo), ("pad", 2 + bo, n2hp)),
                pre=(("pad", bo, n0p), ("pad", 1 + bo, n1pc)),
                wsc_in=zs, wsc_out=zs),
            StagedStage(
                kind="t2a", name="t2a_exchange_col", jit_name="t2a",
                smap_in=zs, smap_out=ysp,
                exchange=dict(exch_a, split=2 + bo, concat=1 + bo),
                wsc_in=zs, wsc_out=ysp),
            StagedStage(
                kind="t1", name="t1_fft_y", jit_name="t1",
                smap_in=ysp, smap_out=ysp,
                local=(("crop", 1 + bo, n1), ("fft", (1 + bo,), True),
                       ("pad", 1 + bo, n1pr)),
                wsc_in=ysp, wsc_out=ysp),
            StagedStage(
                kind="t2b", name="t2b_exchange_row", jit_name="t2b",
                smap_in=ysp, smap_out=xs,
                exchange=dict(exch_b, split=1 + bo, concat=bo),
                wsc_in=ysp, wsc_out=xs),
            StagedStage(
                kind="t3", name="t3_fft_x", jit_name="t3",
                smap_in=xs, smap_out=xs,
                local=(("crop", bo, n0), ("fft", (bo,), True)),
                post=(("crop", 1 + bo, n1), ("crop", 2 + bo, n2h)),
                wsc_in=xs),
        )
    else:
        stages = (
            StagedStage(
                kind="t3", name="t3_ifft_x", jit_name="t3i",
                smap_in=xs, smap_out=xs,
                local=(("fft", (bo,), False), ("pad", bo, n0p)),
                pre=(("pad", 1 + bo, n1pr), ("pad", 2 + bo, n2hp)),
                wsc_in=xs, wsc_out=xs),
            StagedStage(
                kind="t2b", name="t2b_exchange_row", jit_name="t2b",
                smap_in=xs, smap_out=ysp,
                exchange=dict(exch_b, split=bo, concat=1 + bo),
                wsc_in=xs, wsc_out=ysp),
            StagedStage(
                kind="t1", name="t1_ifft_y", jit_name="t1i",
                smap_in=ysp, smap_out=ysp,
                local=(("crop", 1 + bo, n1), ("fft", (1 + bo,), False),
                       ("pad", 1 + bo, n1pc)),
                wsc_in=ysp, wsc_out=ysp),
            StagedStage(
                kind="t2a", name="t2a_exchange_col", jit_name="t2a",
                smap_in=ysp, smap_out=zs,
                exchange=dict(exch_a, split=1 + bo, concat=2 + bo),
                wsc_in=ysp, wsc_out=zs),
            StagedStage(
                kind="t0", name="t0_c2r_z", jit_name="t0i",
                smap_in=zs, smap_out=zs,
                local=(("crop", 2 + bo, n2h), ("c2r", n2, 2 + bo)),
                post=(("crop", bo, n0), ("crop", 1 + bo, n1)),
                wsc_in=zs),
        )
    graph = StagedGraph(
        mesh=mesh, stages=stages, algorithm=algorithm,
        wire_dtype=wire_dtype, overlap_chunks=overlap_chunks,
        executor=executor,
        meta=dict(shape=spec.shape, batch=batch, forward=forward,
                  decomposition="pencil", kind="r2c"),
    )
    return compile_staged(graph), spec
